"""Pallas substring matching — the TPU twin of the CUDA ``mark`` kernel.

The reference marks every occurrence of ``<a href="`` in an HTML buffer with
a 0/1 segmask via a 9-char stencil compare on the GPU
(``cuda/InvertedIndex.cu:79-107``), then compacts the mask with Thrust
(``:321-362``) and scans each hit forward to the closing quote
(``compute_url_length``, ``:109-135``).

TPU re-design: the byte buffer is laid out ``[rows, 128]`` (one byte per
lane, widened to int32 in VMEM — the VPU has no sub-word lanes).  For each
pattern offset j the shifted view ``x[i+j]`` is assembled from two
``pltpu.roll``s (same-row lane roll + next-row carry), and the stencil
compare ANDs across offsets.  One kernel pass over the buffer produces the
match mask; compaction and length-scan stay in XLA (`jnp.nonzero` /
windowed gather), where fusion already does the right thing.

``mark_xla`` is the compiler-twin used for CPU tests and as a fallback —
bit-identical output by construction.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

LANES = 128
BLOCK_ROWS = 256  # 32 KB of bytes per grid step

# shipped compaction default — ONE constant so the env fallback, the
# builder parameter defaults, and the proof script cannot drift apart
# (r5 review).  'blocked' since r5: ~3x 'scatter' on the CPU backend,
# avoids the full-length major-axis cumsum and the m-element scatter.
DEFAULT_COMPACT = "blocked"


def _i32(x: int):
    """Index-map constants must stay i32: under jax_enable_x64 a bare python
    int traces as i64, which Mosaic refuses to return from an index map."""
    return np.int32(x)


def _pad_to(buf: jnp.ndarray, mult: int) -> jnp.ndarray:
    n = buf.shape[0]
    pad = (-n) % mult
    if pad:
        buf = jnp.concatenate([buf, jnp.zeros(pad, buf.dtype)])
    return buf


def mark_xla(buf, pattern: bytes):
    """Reference implementation: mask[i]=1 iff pattern starts at byte i.
    Nine shifted compares; XLA fuses them into one elementwise pass."""
    n = buf.shape[0]
    acc = jnp.ones(n, dtype=bool)
    for j, p in enumerate(pattern):
        shifted = jnp.concatenate(
            [buf[j:], jnp.zeros(j, buf.dtype)]) if j else buf
        acc = acc & (shifted == np.uint8(p))
    return acc


def _mark_kernel(pattern: bytes, buf_ref, nxt_ref, mask_ref):
    x = buf_ref[:].astype(jnp.int32)                  # [BR, 128]
    nxt = nxt_ref[0:1].astype(jnp.int32)              # next block's first row
    # next-row view of x (row r+1; last row fed by the next block's head)
    from jax.experimental.pallas import tpu as pltpu
    # pltpu.roll requires non-negative shifts: roll by (size - j) ≡ roll by -j
    # (shifts as np.int32 — x64 mode would make a weak i64 that mosaic rejects)
    xr = pltpu.roll(x, np.int32(x.shape[0] - 1), axis=0)
    xr = jnp.where(jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
                   == x.shape[0] - 1, nxt, xr)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    acc = jnp.ones(x.shape, dtype=jnp.bool_)
    for j, p in enumerate(pattern):
        if j == 0:
            shifted = x
        else:
            a = pltpu.roll(x, np.int32(LANES - j), axis=1)   # x[r, c+j mod 128]
            b = pltpu.roll(xr, np.int32(LANES - j), axis=1)  # x[r+1, c+j mod 128]
            shifted = jnp.where(lane < LANES - j, a, b)
        acc = acc & (shifted == p)
    mask_ref[:] = acc.astype(jnp.int8)


def mark_pallas(buf, pattern: bytes, interpret: bool = False):
    """Pallas mark kernel over a uint8 buffer [n] → int8 mask [n]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from . import note_kernel_launch

    note_kernel_launch(buf)   # eager launches count as dispatches
    n = buf.shape[0]
    blk = BLOCK_ROWS * LANES
    buf_p = _pad_to(buf, blk)
    rows = buf_p.shape[0] // LANES
    grid = rows // BLOCK_ROWS
    # one extra zero block so the "next block head" index map stays in range
    buf_2d = jnp.concatenate(
        [buf_p.reshape(rows, LANES),
         jnp.zeros((BLOCK_ROWS, LANES), buf_p.dtype)])
    out = pl.pallas_call(
        functools.partial(_mark_kernel, pattern),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, _i32(0)),
                         memory_space=pltpu.VMEM),
            # 8-row block (TPU min sublane tile); kernel uses its first row
            pl.BlockSpec((8, LANES),
                         lambda i: ((i + _i32(1)) * _i32(BLOCK_ROWS // 8),
                                    _i32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, _i32(0)),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(buf_2d, buf_2d)
    return out.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# word-packed mark kernel — 4 bytes/lane
# ---------------------------------------------------------------------------
#
# The byte-per-lane kernel above widens every byte to an i32 lane: 9 pattern
# offsets × (2 rolls + select + compare + and) ≈ 45 VPU ops *per byte*, and
# it writes a byte-sized mask — most of the kernel's time is arithmetic on
# 75%-empty lanes.  The word-packed variant bitcasts the buffer to u32
# words (4 bytes/lane) and checks the pattern at each of the 4 byte
# alignments with masked word compares: ``(w & m) == v`` over the
# ceil((L+3)/4) words the pattern can touch.  Output is ONE int8 per word
# encoding which alignment matched (0 = none, a+1 = byte 4*i+a) — valid
# whenever the pattern cannot match at two alignments of the same word,
# i.e. its minimal period is ≥ 4 (checked; ``<a href="`` has period 9).
# Net: ~4× fewer VPU ops and a 4× smaller mask for downstream compaction.

WORD_BLOCK_ROWS = 512   # 256 KB of buffer per grid step (u32 lanes)


def _min_period(pattern: bytes) -> int:
    for d in range(1, len(pattern)):
        if pattern[d:] == pattern[:-d]:
            return d
    return len(pattern)


def _alignment_tables(pattern: bytes):
    """Per-alignment masked-compare constants: for byte alignment a in 0..3,
    (masks[a], vals[a]) are u32 words with 0xFF at the byte positions the
    pattern occupies in the little-endian word window starting at the
    match word."""
    L = len(pattern)
    nw = (L + 3 + 3) // 4  # pattern shifted by ≤3 bytes spans ≤ this many words
    masks = np.zeros((4, nw), np.uint32)
    vals = np.zeros((4, nw), np.uint32)
    for a in range(4):
        mb = bytearray(4 * nw)
        vb = bytearray(4 * nw)
        for i, p in enumerate(pattern):
            mb[a + i] = 0xFF
            vb[a + i] = p
        masks[a] = np.frombuffer(bytes(mb), "<u4")
        vals[a] = np.frombuffer(bytes(vb), "<u4")
    return masks, vals


def _u32_as_i32(v: int) -> np.int32:
    return np.int32(v - (1 << 32) if v >= (1 << 31) else v)


def _mark_words_kernel(masks, vals, w_ref, nxt_ref, out_ref):
    from jax.experimental.pallas import tpu as pltpu
    x = w_ref[:]                                   # [BR, 128] i32 words
    nxt = nxt_ref[0:1]                             # next block's first row
    br = x.shape[0]
    xr = pltpu.roll(x, np.int32(br - 1), axis=0)   # next-row view
    xr = jnp.where(jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
                   == br - 1, nxt, xr)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    nw = masks.shape[1]
    views = [x]
    for j in range(1, nw):                         # word at linear index i+j
        a = pltpu.roll(x, np.int32(LANES - j), axis=1)
        b = pltpu.roll(xr, np.int32(LANES - j), axis=1)
        views.append(jnp.where(lane < LANES - j, a, b))
    out = jnp.zeros(x.shape, jnp.int32)
    for a in range(3, -1, -1):                     # lowest alignment wins
        hit = None
        for j in range(nw):
            if not masks[a, j]:
                continue
            m = _u32_as_i32(int(masks[a, j]))
            v = _u32_as_i32(int(vals[a, j] & masks[a, j]))
            eq = (views[j] & m) == v
            hit = eq if hit is None else (hit & eq)
        out = jnp.where(hit, np.int32(a + 1), out)
    out_ref[:] = out.astype(jnp.int8)


def mark_words_xla(words, pattern: bytes):
    """Compiler-twin of the word-packed kernel over a u32/i32 word buffer
    [m] — same masked-compare math in plain jnp (the 'xla' engine path and
    the CPU oracle; XLA fuses the compares into one elementwise pass)."""
    if _min_period(pattern) < 4:
        raise ValueError("pattern period < 4 needs the byte kernel")
    masks, vals = _alignment_tables(pattern)
    m = words.shape[0]
    wu = words.astype(jnp.uint32)
    nw = masks.shape[1]
    views = [wu]
    for j in range(1, nw):
        views.append(jnp.concatenate([wu[j:], jnp.zeros(j, jnp.uint32)]))
    out = jnp.zeros(m, jnp.int8)
    for a in range(3, -1, -1):
        hit = None
        for j in range(nw):
            if not masks[a, j]:
                continue
            eq = (views[j] & np.uint32(masks[a, j])) \
                == np.uint32(vals[a, j] & masks[a, j])
            hit = eq if hit is None else (hit & eq)
        out = jnp.where(hit, np.int8(a + 1), out)
    return out


def bytes_view_u32(data: np.ndarray) -> np.ndarray:
    """HOST helper: u8 [n] → little-endian u32 words [ceil(n/4)] (zero-pad
    tail).  The device buffer travels and lives as u32 — a [m,4] u8 view
    on TPU would tile to (8,128) per 4-wide row and blow up 32× in HBM."""
    n = data.shape[0]
    pad = (-n) % 4
    if pad:
        data = np.concatenate([data, np.zeros(pad, np.uint8)])
    return np.ascontiguousarray(data).view(np.dtype("<u4"))


# Fixed page size for the paged mark (words; 4 MW = 16 MB of corpus per
# Pallas dispatch).  The round-4 TPU window proved the kernel green at the
# 8 MB proof shape (grid ~33) but the 256 MB single-dispatch bench shape
# (grid ~1024) raised with the traceback lost to the tunnel drop; paging
# keeps every on-chip dispatch at the proven shape class — one Mosaic
# compile regardless of corpus size — and bounds what any per-dispatch
# scale limit can see.  Exact by construction: mask word i depends only on
# words i..i+nw-1 (nw = ceil((len(pattern)+3+3)/4)), so pages overlap by
# nw-1 words.  Override with MR_MARK_PAGE_WORDS (tests use tiny pages to
# cross page seams; the debug ladder can bisect with it).
MARK_PAGE_WORDS = 1 << 22


def mark_words_pallas(words, pattern: bytes, interpret: bool = False,
                      page_words: int | None = None):
    """Word-packed Pallas mark over a u32/i32 word buffer [m] → int8 word
    mask [m]: 0 = no match, a+1 = pattern starts at byte 4*i+a.  Buffers
    larger than ``page_words`` are marked page-by-page (same compiled
    kernel per page; see MARK_PAGE_WORDS)."""
    if _min_period(pattern) < 4:
        raise ValueError(
            f"pattern period {_min_period(pattern)} < 4: two alignments of "
            f"one word could match; use the byte kernel (mark_pallas)")
    masks, vals = _alignment_tables(pattern)
    m = words.shape[0]
    if words.dtype != jnp.int32:
        words = jax.lax.bitcast_convert_type(words, jnp.int32)
    if page_words is None:
        # mrlint: disable=cache-key-missing-knob,purity-host-call —
        # documented eager-fallback: cached/jitted callers pass
        # page_words explicitly (threaded through _env_knobs keys)
        page_words = int(os.environ.get("MR_MARK_PAGE_WORDS",
                                        MARK_PAGE_WORDS))
    if m > page_words:
        ov = masks.shape[1] - 1
        npages = -(-m // page_words)
        pad = npages * page_words + ov - m
        padded = jnp.concatenate([words, jnp.zeros(pad, jnp.int32)])
        outs = [
            _mark_words_call(padded[p * page_words:
                                    p * page_words + page_words + ov],
                             masks, vals, interpret)[:page_words]
            for p in range(npages)]
        return jnp.concatenate(outs)[:m]
    return _mark_words_call(words, masks, vals, interpret)


def _mark_words_call(words, masks, vals, interpret: bool):
    """One Pallas dispatch over an i32 word buffer [m] (the pre-r4 whole-
    buffer path; pages funnel through here at a fixed shape)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from . import note_kernel_launch

    note_kernel_launch(words)   # eager launches count as dispatches
    m = words.shape[0]
    blk = WORD_BLOCK_ROWS * LANES
    # one concatenate: round up to a block multiple AND append the zero
    # sentinel block the next-block-head index map reads past the end
    pad = (-m) % blk + blk
    words = jnp.concatenate([words, jnp.zeros(pad, jnp.int32)])
    rows = words.shape[0] // LANES               # incl. the sentinel block
    grid = rows // WORD_BLOCK_ROWS - 1
    out_rows = grid * WORD_BLOCK_ROWS            # mask excludes the sentinel
    words_2d = words.reshape(rows, LANES)
    out = pl.pallas_call(
        functools.partial(_mark_words_kernel, masks, vals),
        out_shape=jax.ShapeDtypeStruct((out_rows, LANES), jnp.int8),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((WORD_BLOCK_ROWS, LANES), lambda i: (i, _i32(0)),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, LANES),
                         lambda i: ((i + _i32(1)) * _i32(WORD_BLOCK_ROWS // 8),
                                    _i32(0)),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((WORD_BLOCK_ROWS, LANES),
                               lambda i: (i, _i32(0)),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(words_2d, words_2d)
    return out.reshape(-1)[:m]


def compact_word_matches(wmask, nbytes: int, max_hits: int,
                         mode: str | None = None):
    """Word mask → sorted byte start offsets [max_hits] (fill = nbytes,
    i.e. positively out of range) + match count.

    Stream compaction as cumsum + scatter — the Thrust copy_if stage
    (cuda/InvertedIndex.cu:321-362) in XLA terms.  NOT jnp.nonzero: its
    TPU lowering runs ~20× slower than this two-op form at 16M words
    (measured on v5e; nonzero sorts where a prefix-sum + scatter-with-drop
    suffices, since scatter positions here are unique by construction).

    mode (or MR_COMPACT when mode is None) selects among three
    bit-identical variants for on-chip A/B: 'scatter' (this path),
    'searchsorted' (each OUTPUT slot binary-searches the hit-count
    prefix sum — max_hits·log m gathered lanes instead of an m-element
    scatter), and 'blocked' (_compact_blocked: two-level scan, no
    full-length major-axis cumsum at all).  NOTE: the env fallback reads
    at TRACE time — callers inside cached/jitted builders must pass
    mode explicitly (apps/invertedindex.py threads it through
    _env_knobs into every builder cache key)."""
    if mode is None:
        # mrlint: disable=cache-key-missing-knob,purity-host-call —
        # the trace-time read documented above: cached/jitted callers
        # must pass mode explicitly (and do, via _env_knobs keys)
        mode = os.environ.get("MR_COMPACT", DEFAULT_COMPACT)
    if mode not in ("scatter", "searchsorted", "blocked"):
        # a typo'd A/B label must error, not silently measure scatter
        raise ValueError(f"MR_COMPACT/mode {mode!r}: expected "
                         f"'scatter', 'searchsorted' or 'blocked'")
    if mode == "searchsorted":
        return _compact_searchsorted(wmask, nbytes, max_hits)
    if mode == "blocked":
        return _compact_blocked(wmask, nbytes, max_hits)
    m = wmask.shape[0]
    hit = wmask > 0
    pos = jnp.cumsum(hit.astype(jnp.int32)) - 1
    tgt = jnp.where(hit & (pos < max_hits), pos, max_hits)
    idx = jax.lax.broadcasted_iota(jnp.int32, (m,), 0)
    start_of_word = 4 * idx + wmask.astype(jnp.int32) - 1
    starts = jnp.full(max_hits, nbytes, jnp.int32).at[tgt].set(
        start_of_word, mode="drop")
    return starts, jnp.sum(hit.astype(jnp.int32))


_BLOCK_C = 512   # lanes per row in the blocked compaction's 2-level scan


def _compact_blocked(wmask, nbytes: int, max_hits: int):
    """Hierarchical compaction: NO scan or scatter ever runs over the full
    m words along the major axis.  The mask reshapes to [R, 512]; the
    per-row prefix sum is a minor-axis cumsum (lane-parallel on the VPU),
    the row totals scan is R = m/512 elements, and each output slot then
    finds its hit with a two-level binary search (log R gathered lanes to
    pick the row, log 512 within it).  The right trade when XLA's
    full-length major-axis cumsum lowering dominates the map stage —
    bit-identical to the scatter path (oracle test runs all three)."""
    m = wmask.shape[0]
    C = _BLOCK_C
    pad = (-m) % C
    hit = (wmask > 0).astype(jnp.int32)
    if pad:
        hit = jnp.concatenate([hit, jnp.zeros(pad, jnp.int32)])
    R = hit.shape[0] // C
    intra = jnp.cumsum(hit.reshape(R, C), axis=1)        # [R, C] minor axis
    row_tot = intra[:, C - 1]
    row_off = jnp.cumsum(row_tot)                        # [R] inclusive
    total = row_off[R - 1]
    j = jnp.arange(1, max_hits + 1, dtype=jnp.int32)
    row = jnp.searchsorted(row_off, j, side="left").astype(jnp.int32)
    rsafe = jnp.minimum(row, R - 1)
    prev = jnp.where(row > 0,
                     jnp.take(row_off, jnp.maximum(rsafe - 1, 0)),
                     jnp.int32(0))
    r = j - prev                                         # rank within row
    flat = intra.reshape(-1)
    lo = jnp.zeros(max_hits, jnp.int32)
    hi = jnp.full(max_hits, C, jnp.int32)
    # lower_bound over a size-C range converges in bit_length(C) guarded
    # steps (the last resolves the final length-1 interval; converged
    # lanes are no-ops under the lo<hi guard)
    for _ in range(C.bit_length()):
        upd = lo < hi
        mid = (lo + hi) // 2
        v = jnp.take(flat, jnp.minimum(rsafe * C + mid, R * C - 1))
        ge = v >= r
        hi = jnp.where(upd & ge, mid, hi)
        lo = jnp.where(upd & ~ge, mid + 1, lo)
    word = rsafe * C + lo
    wsafe = jnp.minimum(word, m - 1)
    starts = 4 * word + jnp.take(wmask, wsafe).astype(jnp.int32) - 1
    starts = jnp.where(j <= total, starts, jnp.int32(nbytes))
    return starts, total


def _compact_searchsorted(wmask, nbytes: int, max_hits: int):
    """Gather-side compaction: slot j finds the (j+1)-th hit via binary
    search over the hit-count prefix sum.  Replaces the 64M-element
    scatter with max_hits·ceil(log2 m) random 4-byte reads — the right
    trade when XLA's TPU scatter lowering dominates the map stage."""
    m = wmask.shape[0]
    hit = wmask > 0
    c = jnp.cumsum(hit.astype(jnp.int32))
    total = c[m - 1]
    j = jnp.arange(1, max_hits + 1, dtype=jnp.int32)
    idx = jnp.searchsorted(c, j, side="left").astype(jnp.int32)
    safe = jnp.minimum(idx, m - 1)
    starts = 4 * idx + jnp.take(wmask, safe).astype(jnp.int32) - 1
    starts = jnp.where(j <= total, starts, jnp.int32(nbytes))
    return starts, total


# ---------------------------------------------------------------------------
# unaligned word windows — the u32-resident replacement for byte gathers
# ---------------------------------------------------------------------------

def unaligned_words(words, starts, nwords: int):
    """Gather unaligned little-endian u32 windows from a u32 buffer [m]:
    row i holds ``nwords`` words whose bytes start at BYTE offset
    ``starts[i]``.  Rebuilt from two aligned loads + shifts — the TPU never
    sees a byte-typed array (a [m,4] u8 view would tile 32× larger in HBM).
    Out-of-range bytes read as zero."""
    m = words.shape[0]
    wu = words.astype(jnp.uint32) if words.dtype != jnp.uint32 else words
    k = (starts // 4).astype(jnp.int32)
    r = (starts % 4).astype(jnp.uint32)
    idx = k[:, None] + jnp.arange(nwords + 1, dtype=jnp.int32)[None, :]
    g = jnp.take(wu, jnp.clip(idx, 0, m - 1), axis=0)
    g = jnp.where((idx >= 0) & (idx < m), g, np.uint32(0))
    sh = (np.uint32(8) * r)[:, None]
    lo = g[:, :-1] >> sh
    hi_sh = (np.uint32(32) - sh) % np.uint32(32)   # avoid shift-by-32 UB
    hi = jnp.where(sh > 0, g[:, 1:] << hi_sh, np.uint32(0))
    return lo | hi


def first_byte_pos(wu, byte: int):
    """Per row of a u32 window array [n, W]: byte offset of the first
    occurrence of ``byte``, or -1 (the compute_url_length scan,
    cuda/InvertedIndex.cu:109-135, on word lanes)."""
    n, W = wu.shape
    big = np.int32(4 * W)
    best = jnp.full(n, big, jnp.int32)
    for j in range(4):
        hit = ((wu >> np.uint32(8 * j)) & np.uint32(0xFF)) == np.uint32(byte)
        p = jnp.argmax(hit, axis=1).astype(jnp.int32)
        cand = jnp.where(jnp.any(hit, axis=1), 4 * p + j, big)
        best = jnp.minimum(best, cand)
    return jnp.where(best < big, best, np.int32(-1))


def mask_words_to_length(wu, lengths):
    """Zero every byte at offset >= lengths[i] in row i of a u32 window
    array — produces the zero-padded words the masked hash requires."""
    W = wu.shape[1]
    nb = jnp.clip(lengths[:, None]
                  - np.int32(4) * jnp.arange(W, dtype=jnp.int32)[None, :],
                  0, 4)
    lut = jnp.asarray(
        np.array([0, 0xFF, 0xFFFF, 0xFFFFFF, 0xFFFFFFFF], np.uint32))
    return wu & jnp.take(lut, nb)


def compact_matches(mask, max_hits: int):
    """Mask → sorted start offsets [max_hits] (fill = len(mask)) + count.
    The Thrust sequence/count/copy_if stage (cuda/InvertedIndex.cu:321-362)
    collapses to one jnp.nonzero."""
    n = mask.shape[0]
    idx = jnp.nonzero(mask.astype(bool), size=max_hits, fill_value=n)[0]
    return idx, jnp.sum(mask.astype(jnp.int32))


def url_lengths(buf, starts, terminator: int, max_len: int):
    """For each start offset, distance to the terminator byte (the
    compute_url_length kernel, cuda/InvertedIndex.cu:109-135).

    Returns lengths [k] (-1 if no terminator within max_len — the reference
    would run off the buffer; we flag and let the caller drop) and the
    gathered windows [k, max_len].  A length of 0 is a real empty URL
    (``href=""``), distinct from the no-terminator case."""
    n = buf.shape[0]
    pos = starts[:, None] + jnp.arange(max_len)[None, :]
    windows = jnp.take(buf, jnp.minimum(pos, n - 1), axis=0)
    windows = jnp.where(pos < n, windows, 0)
    hit = windows == np.uint8(terminator)
    any_hit = jnp.any(hit, axis=1)
    length = jnp.where(any_hit, jnp.argmax(hit, axis=1), -1)
    return length.astype(jnp.int32), windows


