"""Sorting ops — replaces the reference's qsort + 2-way merge cascade.

The reference sorts per-process with an index-array qsort over one page and a
Spool-based merge cascade across pages (``src/mapreduce.cpp:2359-2633``).  On
TPU a whole shard sorts in one ``jax.lax.sort`` call (XLA's bitonic sort runs
on the VPU), so the merge machinery disappears for in-core/device data.
Out-of-core datasets take the streaming path instead: per-frame sorted runs
+ k-way merge in ~one page budget (``core/external.py`` — the Spool
cascade's capability, rebuilt).

Sort "flags" ±1..6 select the pre-built comparators in the reference
(int/uint64/float/double/str/strn, ``src/mapreduce.cpp:2692-2802``).  Columns
already know their dtype, so a flag here only encodes direction: flag > 0
ascending, flag < 0 descending.  A user compare callback is honoured on the
host path (parity with appcompare, slow by design).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.column import BytesColumn, Column, DenseColumn


def argsort_column(col: Column, descending: bool = False,
                   cmp: Optional[Callable] = None) -> np.ndarray:
    """Stable argsort of a column; lexicographic over trailing width dim."""
    n = len(col)
    if cmp is not None:
        rows = col.tolist()
        order = sorted(range(n), key=functools.cmp_to_key(
            lambda i, j: cmp(rows[i], rows[j])))
        return np.asarray(order, dtype=np.int64)
    if isinstance(col, BytesColumn):
        rows = col.tolist()
        order = sorted(range(n), key=lambda i: rows[i], reverse=descending)
        return np.asarray(order, dtype=np.int64)
    from ..core.column import ObjectColumn
    if isinstance(col, ObjectColumn):
        # arbitrary objects order by their pickles (the bytes the
        # reference's C++ comparators would see)
        rows = col.pickles()
        order = sorted(range(n), key=lambda i: rows[i], reverse=descending)
        return np.asarray(order, dtype=np.int64)
    data = col.data
    if isinstance(data, jax.Array):
        if data.ndim == 1:
            idx = jnp.argsort(data, stable=True)
        else:
            # lexicographic: last key = leading column → sort by trailing first
            keys = tuple(data[:, j] for j in range(data.shape[1] - 1, -1, -1))
            idx = jnp.lexsort(keys)
        if descending:
            idx = idx[::-1]
        return idx
    if data.ndim == 1:
        idx = np.argsort(data, kind="stable")
    else:
        idx = np.lexsort(tuple(data[:, j] for j in range(data.shape[1] - 1, -1, -1)))
    if descending:
        idx = idx[::-1]
    return idx


def argsort_slots(sortval, occupied):
    """Jittable slot ordering for the Pallas group tables
    (``ops/pallas/group.py``): occupied slots first, ascending by the
    reconstructed key value — the only sort the kernel-backed group
    path ever runs, over O(groups) table slots instead of O(rows)
    received rows (the whole point of replacing the lexsort hot path).
    lexsort's LAST key is primary: the emptiness flag, then the key."""
    return jnp.lexsort((sortval, ~occupied))


def sorted_dense(data, descending: bool = False):
    """Direct value sort of a dense [n] or [n,w] array (device-friendly)."""
    if data.ndim == 1:
        out = jnp.sort(data) if isinstance(data, jax.Array) else np.sort(data, kind="stable")
        return out[::-1] if descending else out
    idx = argsort_column(DenseColumn(data), descending)
    return data[idx]
