"""Registered kernel reduces — the vectorised callback tier.

These are the TPU equivalents of the reference's reusable reduce callbacks
(``oink/reduce_count.cpp:14-20``, ``oink/reduce_cull.cpp:13-20``): batch
functions usable directly as ``mr.reduce(fn, batch=True)`` that dispatch on
the frame kind (local KMVFrame vs mesh ShardedKMV) and stay columnar/on
device throughout."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.frame import KMVFrame
from .segment import kmv_segment_ids, segment_reduce


def _is_sharded(frame) -> bool:
    return not isinstance(frame, KMVFrame)


def count(frame, kv, ptr=None):
    """(key, [v...]) → (key, nvalues) — oink reduce_count."""
    if _is_sharded(frame):
        from ..parallel.group import reduce_sharded
        kv.add_frame(reduce_sharded(frame, "count"))
    else:
        kv.add_batch(frame.key, np.asarray(frame.nvalues))


def cull(frame, kv, ptr=None):
    """(key, [v...]) → (key, first value) — dedupe, oink reduce_cull."""
    if _is_sharded(frame):
        from ..parallel.group import first_sharded
        kv.add_frame(first_sharded(frame))
    else:
        firsts = frame.offsets[:-1]
        kv.add_batch(frame.key, frame.values.take(firsts))


def _segment_op(op):
    def fn(frame, kv, ptr=None):
        if _is_sharded(frame):
            from ..parallel.group import reduce_sharded
            kv.add_frame(reduce_sharded(frame, op))
        else:
            seg = jnp.asarray(kmv_segment_ids(frame))
            vals = jnp.asarray(np.asarray(frame.values.data))
            out = segment_reduce(vals, seg, len(frame), op)
            kv.add_batch(frame.key, out)
    fn.__name__ = f"reduce_{op}"
    fn.__doc__ = f"(key, [v...]) → (key, {op}(values)), columnar."
    return fn


sum_values = _segment_op("sum")
max_values = _segment_op("max")
min_values = _segment_op("min")
