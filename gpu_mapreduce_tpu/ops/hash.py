"""lookup3 hashing (Bob Jenkins, public domain algorithm) — JAX/TPU port.

The reference uses ``hashlittle()`` (reference ``src/hash.cpp:104``) for two
jobs: key→process partitioning in ``MapReduce::aggregate``
(``src/mapreduce.cpp:469-472``) and key→bucket in ``KeyMultiValue::convert``
(``src/keymultivalue.cpp:1430``).  We re-implement the same algorithm twice:

* :func:`hashlittle` — exact scalar port over arbitrary ``bytes`` (host path,
  string keys).  Bit-identical to the C version for any input.
* :func:`hash_words32` — vectorised JAX version over fixed-width keys viewed
  as little-endian ``uint32`` words.  For inputs whose length is a multiple of
  4 bytes this is bit-identical to ``hashlittle`` on the equivalent byte
  string (the C code's aligned ``k[0..2]`` path), so device-side partitioning
  of u64 graph keys agrees exactly with host-side hashing of the same bytes.

Unlike the reference we also need a 64-bit variant (:func:`hash_bytes64`) for
string interning: variable-length byte keys are mapped to u64 ids so they can
live in TPU registers; the id→bytes dictionary stays on the host
(SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import numpy as np

try:  # the module must import host-side even if jax is unavailable
    import jax.numpy as jnp
except Exception:  # pragma: no cover
    jnp = None

_M32 = 0xFFFFFFFF


def _rot(x: int, k: int) -> int:
    return ((x << k) | (x >> (32 - k))) & _M32


def _mix(a: int, b: int, c: int):
    # lookup3 mix() — reference src/hash.cpp:50-57 region
    a = (a - c) & _M32; a ^= _rot(c, 4); c = (c + b) & _M32
    b = (b - a) & _M32; b ^= _rot(a, 6); a = (a + c) & _M32
    c = (c - b) & _M32; c ^= _rot(b, 8); b = (b + a) & _M32
    a = (a - c) & _M32; a ^= _rot(c, 16); c = (c + b) & _M32
    b = (b - a) & _M32; b ^= _rot(a, 19); a = (a + c) & _M32
    c = (c - b) & _M32; c ^= _rot(b, 4); b = (b + a) & _M32
    return a, b, c


def _final(a: int, b: int, c: int):
    # lookup3 final() — reference src/hash.cpp:69-77 region
    c ^= b; c = (c - _rot(b, 14)) & _M32
    a ^= c; a = (a - _rot(c, 11)) & _M32
    b ^= a; b = (b - _rot(a, 25)) & _M32
    c ^= b; c = (c - _rot(b, 16)) & _M32
    a ^= c; a = (a - _rot(c, 4)) & _M32
    b ^= a; b = (b - _rot(a, 14)) & _M32
    c ^= b; c = (c - _rot(b, 24)) & _M32
    return a, b, c


def hashlittle(data: bytes, initval: int = 0) -> int:
    """Exact port of hashlittle(key, length, initval) → uint32.

    Follows the byte-at-a-time (unaligned) formulation, which produces the
    same result as the aligned word reads in the C code on little-endian
    machines (reference src/hash.cpp:104-228).
    """
    length = len(data)
    a = b = c = (0xDEADBEEF + length + initval) & _M32
    i = 0
    while length > 12:
        a = (a + int.from_bytes(data[i:i + 4], "little")) & _M32
        b = (b + int.from_bytes(data[i + 4:i + 8], "little")) & _M32
        c = (c + int.from_bytes(data[i + 8:i + 12], "little")) & _M32
        a, b, c = _mix(a, b, c)
        i += 12
        length -= 12
    tail = data[i:]
    if length == 0:
        return c
    pad = tail + b"\x00" * (12 - len(tail))
    a = (a + int.from_bytes(pad[0:4], "little")) & _M32
    b = (b + int.from_bytes(pad[4:8], "little")) & _M32
    c = (c + int.from_bytes(pad[8:12], "little")) & _M32
    a, b, c = _final(a, b, c)
    return c


def hash_bytes64(data: bytes) -> int:
    """64-bit intern id for a byte string: two seeded hashlittle passes.

    Equivalent in spirit to lookup3's hashlittle2 (primary+secondary hash).
    Used for string→u64 interning on the device path; collision probability
    for n distinct strings is ~n^2/2^64.
    """
    hi = hashlittle(data, 0)
    lo = hashlittle(data, 0xDEADBEEF)
    return (hi << 32) | lo


def hash_bytes64_batch(strings, seed_hi: int = 0,
                       seed_lo: int = 0xDEADBEEF) -> np.ndarray:
    """Vector hash_bytes64 over a sequence of byte strings — routed
    through the native C++ runtime when built (the reference's host
    hashing is C++, src/hash.cpp; our interning loops were the last
    per-item Python hot paths).  Non-default seeds give an independent
    id family (the intern collision check)."""
    from .. import native
    if native.available() and len(strings):
        lens = np.fromiter((len(s) for s in strings), np.int64,
                           count=len(strings))
        offs = np.zeros(len(strings) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        buf = b"".join(strings)
        if (seed_hi, seed_lo) == (0, 0xDEADBEEF):
            return native.intern64_batch(buf, offs)
        return native.intern_ranges(buf, offs[:-1], lens, seed_hi, seed_lo)
    return np.array([(np.uint64(hashlittle(s, seed_hi)) << np.uint64(32))
                     | np.uint64(hashlittle(s, seed_lo))
                     for s in strings], np.uint64)


# ---------------------------------------------------------------------------
# Vectorised JAX version for fixed-width keys
# ---------------------------------------------------------------------------

def _jrot(x, k):
    return (x << np.uint32(k)) | (x >> np.uint32(32 - k))


def _jmix(a, b, c):
    a = a - c; a = a ^ _jrot(c, 4); c = c + b
    b = b - a; b = b ^ _jrot(a, 6); a = a + c
    c = c - b; c = c ^ _jrot(b, 8); b = b + a
    a = a - c; a = a ^ _jrot(c, 16); c = c + b
    b = b - a; b = b ^ _jrot(a, 19); a = a + c
    c = c - b; c = c ^ _jrot(b, 4); b = b + a
    return a, b, c


def _jfinal(a, b, c):
    c = c ^ b; c = c - _jrot(b, 14)
    a = a ^ c; a = a - _jrot(c, 11)
    b = b ^ a; b = b - _jrot(a, 25)
    c = c ^ b; c = c - _jrot(b, 16)
    a = a ^ c; a = a - _jrot(c, 4)
    b = b ^ a; b = b - _jrot(a, 14)
    c = c ^ b; c = c - _jrot(b, 24)
    return a, b, c


def hash_words32(words, initval: int = 0):
    """Vectorised hashlittle over uint32-word keys.

    ``words``: array of shape [..., W] (uint32), each row one key of 4*W
    bytes.  Returns uint32 hashes of shape [...].  Bit-identical to
    :func:`hashlittle` on the corresponding little-endian byte strings.

    W is static, so the word loop unrolls at trace time — XLA sees a fixed
    chain of vector int ops, which fuses into surrounding kernels.
    """
    xp = jnp if (jnp is not None and not isinstance(words, np.ndarray)) else np
    words = words.astype(np.uint32)
    w = words.shape[-1]
    length = np.uint32(4 * w)
    init = np.uint32((0xDEADBEEF + int(length) + initval) & _M32)
    a = xp.full(words.shape[:-1], init, dtype=np.uint32)
    b = a
    c = a
    i = 0
    while w > 3:
        a = a + words[..., i]
        b = b + words[..., i + 1]
        c = c + words[..., i + 2]
        a, b, c = _jmix(a, b, c)
        i += 3
        w -= 3
    if w == 0:
        return c
    if w >= 1:
        a = a + words[..., i]
    if w >= 2:
        b = b + words[..., i + 1]
    if w >= 3:
        c = c + words[..., i + 2]
    a, b, c = _jfinal(a, b, c)
    return c


def hashlittle_masked(words, lengths, initval: int = 0):
    """Vectorised hashlittle over VARIABLE-length byte strings.

    ``words``: uint32 array [..., T] — each row a key's bytes as
    little-endian u32 words, **zeroed beyond its length** (lookup3's tail
    handling pads with zero bytes, so pre-zeroed words reproduce it
    exactly).  ``lengths``: int32 byte lengths [...].  Returns uint32 [...]
    bit-identical to :func:`hashlittle` on each row's exact bytes.

    The reference hashes raw variable-length key bytes on the host
    (src/hash.cpp:104-228); this is the device twin that lets string-keyed
    workloads (URLs, words) intern to u64 ids *on chip* instead of in a
    host loop.  The 12-byte-block loop is unrolled over the static word
    width T: each row applies mix() while >12 bytes remain, then one
    final() at its own tail block, selected by masks — no data-dependent
    control flow, so it fuses into surrounding kernels.
    """
    xp = jnp if (jnp is not None and not isinstance(words, np.ndarray)) else np
    words = words.astype(np.uint32)
    T = words.shape[-1]
    pad = (-T) % 3
    if pad:
        zshape = words.shape[:-1] + (pad,)
        words = xp.concatenate([words, xp.zeros(zshape, np.uint32)], axis=-1)
        T += pad
    lengths = lengths.astype(np.uint32)
    init = (np.uint32((0xDEADBEEF + initval) & _M32) + lengths)
    a = b = c = init
    out = init  # length==0 rows: hashlittle returns c == init
    lengths_i = lengths.astype(np.int32)

    def step(t, a, b, c, out, w0, w1, w2):
        rem = lengths_i - np.int32(12) * t
        is_full = rem > 12          # another 12-byte block follows → mix
        is_tail = (rem > 0) & (rem <= 12)   # this block is the tail → final
        a0, b0, c0 = a + w0, b + w1, c + w2
        am, bm, cm = _jmix(a0, b0, c0)
        _, _, cf = _jfinal(a0, b0, c0)
        return (xp.where(is_full, am, a), xp.where(is_full, bm, b),
                xp.where(is_full, cm, c), xp.where(is_tail, cf, out))

    nblocks = T // 3
    if xp is np or nblocks <= 8:
        # short keys / numpy: unrolled (XLA fuses a short chain fine)
        for t in range(nblocks):
            a, b, c, out = step(np.int32(t), a, b, c, out,
                                words[..., 3 * t], words[..., 3 * t + 1],
                                words[..., 3 * t + 2])
        return out

    # long keys under jit: a fori_loop keeps the compiled program O(1) in
    # key width — the fully unrolled 80+-step mix/final chain stalls XLA's
    # CPU backend for minutes and bloats the TPU program for no speedup
    # (the loop body is pure VPU work; 80 trips of ~40 vector ops is
    # nothing next to the gathers around it)
    import jax as _jax

    def body(t, carry):
        a, b, c, out = carry
        w = _jax.lax.dynamic_slice_in_dim(words, 3 * t, 3, axis=-1)
        return step(t.astype(np.int32), a, b, c, out,
                    w[..., 0], w[..., 1], w[..., 2])

    a, b, c, out = _jax.lax.fori_loop(0, nblocks, body, (a, b, c, out))
    return out


def hash_bytes64_masked(words, lengths, seed_hi: int = 0,
                        seed_lo: int = 0xDEADBEEF):
    """Device twin of :func:`hash_bytes64`: u64 intern id from two seeded
    masked-hashlittle passes.  With the default seeds, bit-identical to the
    host/native intern on the same byte strings — device- and host-produced
    ids interoperate.  Alternate seeds give an INDEPENDENT id family (used
    to detect 64-bit intern collisions without the byte strings)."""
    hi = hashlittle_masked(words, lengths, seed_hi).astype(np.uint64)
    lo = hashlittle_masked(words, lengths, seed_lo).astype(np.uint64)
    return (hi << np.uint64(32)) | lo


def bytes_to_words32(buf: np.ndarray, max_len: int) -> np.ndarray:
    """Host helper: [n, max_len] u8 rows (zero-padded) → [n, max_len/4] u32
    little-endian words for the masked hash functions."""
    assert max_len % 4 == 0
    return np.ascontiguousarray(buf[..., :max_len]).view(
        np.dtype("<u4")).reshape(buf.shape[0], max_len // 4)


def hash_u64(keys, initval: int = 0):
    """Hash an array of uint64 keys → uint32, matching hashlittle on their
    8-byte little-endian encodings (the aggregate() partition hash applied to
    the reference's VERTEX=uint64 graph keys, oink/typedefs.h:22)."""
    xp = jnp if (jnp is not None and not isinstance(keys, np.ndarray)) else np
    keys = keys.astype(np.uint64)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    words = xp.stack([lo, hi], axis=-1)
    return hash_words32(words, initval)
