"""Group-by (convert) and segment reductions — replaces the reference's
in-memory hash of Unique records.

The reference's ``KeyMultiValue::convert`` builds an open-chained hash table
of Unique records in a 2-page arena, recursively splitting partitions that
overflow (``src/keymultivalue.cpp:645-1433``).  On TPU the idiomatic
equivalent is *sort + run-length detection*: sort pairs by key, find group
boundaries, and reduce with segment ops (SURVEY.md §7).  No hash table, no
partition recursion — XLA's sort is the workhorse and skewed keys cost
nothing extra.

Two layers:

* :func:`group_dense` / :func:`group_bytes` — full convert for one frame.
* jittable segment helpers (:func:`segment_ids_from_offsets`,
  :func:`segment_reduce`) used by registered kernel reduces
  (count/sum/max/...) so entire map→collate→reduce pipelines stay on device.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.column import BytesColumn, Column, DenseColumn
from ..core.frame import KMVFrame, KVFrame
from .sort import argsort_column


def _boundaries_dense(sorted_keys) -> np.ndarray:
    """Boolean host mask: row starts a new group (row 0 always True)."""
    k = np.asarray(sorted_keys)
    if k.ndim == 1:
        new = k[1:] != k[:-1]
    else:
        new = np.any(k[1:] != k[:-1], axis=1)
    return np.concatenate([[True], new]) if len(k) else np.zeros(0, bool)


def group_dense(kv: KVFrame) -> KMVFrame:
    """Convert a dense KVFrame → KMVFrame by sort + boundary detection."""
    if len(kv) == 0:
        return KMVFrame(kv.key, np.zeros(0, np.int64), np.zeros(1, np.int64), kv.value)
    order = argsort_column(kv.key)
    skey = kv.key.take(order)
    svals = kv.value.take(order)
    starts = np.flatnonzero(_boundaries_dense(skey.data))
    offsets = np.concatenate([starts, [len(kv)]]).astype(np.int64)
    nvalues = np.diff(offsets)
    ukeys = skey.take(starts)
    return KMVFrame(ukeys, nvalues, offsets, svals)


def group_bytes(kv: KVFrame) -> KMVFrame:
    """Convert with byte-string keys (host path): dict grouping preserving
    first-seen key order (the reference's hash-insertion order is likewise
    arbitrary but deterministic)."""
    groups = {}
    keys = kv.key.tolist()
    for i, k in enumerate(keys):
        groups.setdefault(k, []).append(i)
    ukeys = list(groups.keys())
    idx = np.asarray([i for ids in groups.values() for i in ids], dtype=np.int64)
    nvalues = np.asarray([len(v) for v in groups.values()], dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(nvalues)]).astype(np.int64)
    svals = kv.value.take(idx)
    key_col: Column = BytesColumn(ukeys) if isinstance(kv.key, BytesColumn) \
        else DenseColumn(np.asarray(ukeys))
    return KMVFrame(key_col, nvalues, offsets, svals)


def group_objects(kv: KVFrame) -> KMVFrame:
    """Convert with arbitrary-object keys: group by PICKLE equality (the
    reference's Python wrapper groups by pickled bytes — the C++ core
    only ever sees the pickle, python/mrmpi.py:17-45)."""
    from ..core.column import ObjectColumn
    groups: dict = {}
    firsts: dict = {}
    for i, p in enumerate(kv.key.pickles()):
        groups.setdefault(p, []).append(i)
        firsts.setdefault(p, i)
    idx = np.asarray([i for ids in groups.values() for i in ids],
                     dtype=np.int64)
    nvalues = np.asarray([len(v) for v in groups.values()], dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(nvalues)]).astype(np.int64)
    ukeys = ObjectColumn([kv.key.data[firsts[p]] for p in groups])
    return KMVFrame(ukeys, nvalues, offsets, kv.value.take(idx))


def group_frame(kv: KVFrame) -> KMVFrame:
    from ..core.column import ObjectColumn
    if isinstance(kv.key, ObjectColumn):
        return group_objects(kv)
    if kv.is_dense():
        return group_dense(kv)
    return group_bytes(kv)


# ---------------------------------------------------------------------------
# Jittable segment helpers (device pipelines)
# ---------------------------------------------------------------------------

def segment_ids_from_boundary(is_start):
    """[n] bool 'starts new group' mask → [n] int32 segment ids (jittable)."""
    return jnp.cumsum(is_start.astype(jnp.int32)) - 1


def boundary_mask(sorted_keys):
    """Jittable group-start mask for sorted dense keys [n] or [n,w]."""
    k = sorted_keys
    if k.ndim == 1:
        new = k[1:] != k[:-1]
    else:
        new = jnp.any(k[1:] != k[:-1], axis=1)
    first = jnp.ones((1,), dtype=bool)
    return jnp.concatenate([first, new]) if k.shape[0] else jnp.zeros(0, bool)


_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
    "prod": jax.ops.segment_prod,
}


def segment_reduce(values, segment_ids, num_segments: int, op: str = "sum"):
    """Jittable segment reduction; op in {sum,max,min,prod,count}."""
    if op == "count":
        ones = jnp.ones(values.shape[0], dtype=jnp.int64)
        return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    fn = _REDUCERS[op]
    return fn(values, segment_ids, num_segments=num_segments)


def kmv_segment_ids(kmv: KMVFrame):
    """[n] segment ids for a KMVFrame's flat value column."""
    return np.repeat(np.arange(len(kmv), dtype=np.int64), kmv.nvalues)


# ---------------------------------------------------------------------------
# table epilogue for the Pallas group kernels (ops/pallas/group.py)
# ---------------------------------------------------------------------------

def table_to_groups(table, T: int, gcap: int, reduce_op: str,
                    key_dtype, value_dtype):
    """Accumulation-table slots → the grouped output layout (jittable).

    ``table`` is ``(tkh, tkl, occ, cnt[, shi, slo])`` from
    ``ops/pallas/group.segment_table`` (slots [0, T) live, slot T
    invalid-row trash, slot T+1 the probe-overflow counter).  Orders
    the slots — occupied first, ascending reconstructed key — and
    emits ``(ukey, uval, g, overflow)`` sized [gcap], byte-identical
    to the sort path's grouped layout: ascending unique keys with the
    eager zero fill, counts as int64, sums at the value dtype's width
    (the limb accumulate wraps mod 2^64, truncation wraps mod
    2^width — exactly what the sorted ``segment_sum`` does)."""
    from .pallas.group import join_limbs
    from .sort import argsort_slots
    tkh, tkl, occ, cnt = table[:4]
    occb = occ[:T] == 1
    key = join_limbs(tkh[:T], tkl[:T], key_dtype)
    order = argsort_slots(key, occb)[:gcap]
    ok = jnp.take(occb, order)
    ukey = jnp.where(ok, jnp.take(key, order),
                     jnp.zeros((), jnp.dtype(key_dtype)))
    if reduce_op == "count":
        uval = jnp.where(ok, jnp.take(cnt[:T], order), 0) \
            .astype(jnp.int64)
    else:
        sval = join_limbs(table[4][:T], table[5][:T], value_dtype)
        uval = jnp.where(ok, jnp.take(sval, order),
                         jnp.zeros((), jnp.dtype(value_dtype)))
    g = jnp.sum(occb.astype(jnp.int32))
    return ukey, uval, g, cnt[T + 1]
