"""Device-side staging for the fused graph engines (VERDICT r2 #2).

The fused cc/sssp/luby/tri engines need compact vertex ranks 0..n-1
(their labels/state live in dense replicated vectors).  Round 2 staged
this on the controller — ``scan_kv`` pulled the whole edge list to host
numpy and ``np.unique`` ranked it — a funnel the mesh cannot outgrow
(the reference gives every rank its own slice and never funnels,
``cuda/InvertedIndex.cu:284-312``).

Here the ranking runs on device over the mesh-resident edge KV:

* :func:`unique_verts` — ONE jitted global sort-unique over the sharded
  [rows, 2] u64 edge keys produces the sorted vertex table (replicated,
  sentinel-padded, trimmed to ``round_cap(n)``) and the count.  Only the
  scalar ``n`` syncs to the host.
* :func:`rank_edges` — a second jitted searchsorted maps each edge
  endpoint to its rank; outputs stay row-sharded in the SAME layout as
  the input frame, ready for the fused models' shard_map loops.

The O(E) edge columns never touch the host; commands pull only the [n]
vertex-id table afterwards for their printed output.  Vertex id
``2^64-1`` is reserved as the padding sentinel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import mesh_axis_size, row_spec
from .sharded import ShardedKV, round_cap

SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


def mesh_kv_frame(mr) -> Optional[ShardedKV]:
    """The mr's KV as ONE ShardedKV frame if it is mesh-resident (several
    sharded frames concatenate on device), else None."""
    kv = getattr(mr, "kv", None)
    if kv is None or not kv._frames:
        return None
    fr = kv.one_frame()
    return fr if isinstance(fr, ShardedKV) else None


def staged_frame(mr) -> Optional[ShardedKV]:
    """Mesh-resident frame of mr's KV, aggregating (shard + hash
    exchange) first if the data is still host-resident.  Returns None
    only when the dataset is empty/absent.  NOTE: byte/object VALUES
    shard as interned u64 ids (``value_decode`` set) — callers that
    consume ``fr.value`` numerically must check ``value_decode``."""
    fr = mesh_kv_frame(mr)
    if fr is None:
        mr.aggregate()
        fr = mesh_kv_frame(mr)
    return fr


class StagedGraph:
    """Result of :func:`stage_graph`: ranked sharded edge arrays plus the
    host-side [n] vertex-id table (pulled once, for output)."""

    __slots__ = ("verts", "n", "src", "dst", "valid", "weights")

    def __init__(self, verts, n, src, dst, valid, weights):
        self.verts, self.n = verts, n
        self.src, self.dst, self.valid = src, dst, valid
        self.weights = weights


def stage_graph(mr, comm, drop_self: bool = False,
                need_weights: bool = False) -> Optional[StagedGraph]:
    """The fused graph commands' shared staging: mesh-shard the edge KV,
    rank vertices/edges on device.  Returns None when mesh staging does
    not apply (no mesh comm, empty dataset, or — with ``need_weights`` —
    interned byte values, whose ids are not numbers); the caller then
    takes its host path.  An n==0 result carries empty arrays so callers
    can emit their empty output without re-pulling the edge list."""
    from jax.sharding import Mesh
    if not isinstance(comm, Mesh):
        return None
    fr = staged_frame(mr)
    if fr is None or not len(fr):
        return None
    if need_weights and fr.value_decode is not None:
        return None
    verts_d, n = unique_verts(fr, drop_self=drop_self)
    if n == 0:
        return StagedGraph(np.zeros(0, np.uint64), 0, None, None, None,
                           None)
    src_d, dst_d, valid_d = rank_edges(fr, verts_d, drop_self=drop_self)
    return StagedGraph(np.asarray(verts_d)[:n], n, src_d, dst_d, valid_d,
                       fr.value if need_weights else None)


def _valid_rows(nrows: int, nprocs: int, counts):
    cap = nrows // nprocs
    idx = jnp.arange(nrows)
    return (idx % cap) < counts[idx // cap]


@functools.lru_cache(maxsize=None)
def _unique_fn(mesh, nrows: int, drop_self: bool):
    rep = NamedSharding(mesh, PartitionSpec())
    shard = NamedSharding(mesh, row_spec(mesh))
    nprocs = mesh_axis_size(mesh)

    # the sorted 2E table stays ROW-SHARDED here; only the [round_cap(n)]
    # trim (second dispatch below) replicates — forcing rep on the full
    # array would put O(E) on every device
    @functools.partial(jax.jit, out_shardings=(shard, rep, rep))
    def run(key, counts):
        valid = _valid_rows(nrows, nprocs, counts)
        if drop_self:
            valid = valid & (key[:, 0] != key[:, 1])
        # vertex id 2^64-1 IS the padding sentinel — count real
        # occurrences so the host wrapper can refuse instead of
        # silently dropping the vertex
        nbad = jnp.sum((valid[:, None] & (key == SENTINEL))
                       .astype(jnp.int32))
        flat = jnp.where(valid[:, None], key, SENTINEL).reshape(-1)
        s = jnp.sort(flat)
        first = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
        isu = first & (s != SENTINEL)
        n = jnp.sum(isu.astype(jnp.int64))
        # compact uniques to the front with prefix-sum + scatter-drop
        # (positions unique by construction) — ~20× cheaper than a
        # second sort; the sentinel fill keeps the table globally
        # sorted for searchsorted
        m = s.shape[0]
        # int64 positions: at pod scale the flattened endpoints can
        # exceed 2^31 rows and an i32 cumsum would wrap (silent drop)
        pos = jnp.cumsum(isu.astype(jnp.int64)) - 1
        tgt = jnp.where(isu, pos, m)
        verts = jnp.full(m, SENTINEL).at[tgt].set(s, mode="drop")
        return verts, n, nbad

    return run


@functools.lru_cache(maxsize=None)
def _trim_fn(mesh, nout: int):
    rep = NamedSharding(mesh, PartitionSpec())

    @functools.partial(jax.jit, out_shardings=rep)
    def run(x):
        return x[:nout]

    return run


def unique_verts(fr: ShardedKV, drop_self: bool = False
                 ) -> Tuple[jax.Array, int]:
    """Sorted unique endpoint ids of a mesh-resident [rows,2] edge frame:
    (replicated sentinel-padded table of length round_cap(n), n).  With
    ``drop_self`` endpoints of self-loop-only vertices are excluded (the
    luby convention)."""
    verts, n, nbad = _unique_fn(fr.mesh, fr.key.shape[0], drop_self)(
        fr.key, jnp.asarray(fr.counts))
    if int(nbad):
        raise ValueError(
            f"vertex id {SENTINEL} is reserved as the device staging "
            f"sentinel ({int(nbad)} occurrences in the edge list)")
    n = int(n)
    return _trim_fn(fr.mesh, round_cap(n))(verts), n


@functools.lru_cache(maxsize=None)
def _rank_fn(mesh, nrows: int, nvp: int, drop_self: bool):
    shard = NamedSharding(mesh, row_spec(mesh))
    nprocs = mesh_axis_size(mesh)

    @functools.partial(jax.jit, out_shardings=(shard, shard, shard))
    def run(key, counts, verts):
        valid = _valid_rows(nrows, nprocs, counts)
        if drop_self:
            valid = valid & (key[:, 0] != key[:, 1])
        src = jnp.searchsorted(verts, key[:, 0]).astype(jnp.int32)
        dst = jnp.searchsorted(verts, key[:, 1]).astype(jnp.int32)
        return src, dst, valid

    return run


def rank_edges(fr: ShardedKV, verts: jax.Array, drop_self: bool = False
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Edge endpoints as vertex ranks: (src, dst, valid), each [rows]
    row-sharded like the frame — feed directly to the fused models'
    sharded loops (invalid/padding rows carry valid=False)."""
    return _rank_fn(fr.mesh, fr.key.shape[0], verts.shape[0], drop_self)(
        fr.key, jnp.asarray(fr.counts), verts)
