"""Exact wire codec for the shuffle exchange (ROADMAP item 2).

The two-phase exchange (``shuffle.py``) ships raw u64 keys + full-width
values padded to a global per-bucket cap — the pad tax
``mrtpu_exchange_bytes_total{pad}`` measures on every run.  EQuARX
(PAPERS.md) compresses collectives inside XLA at near-zero cost; here
the compression can stay **byte-exact** because the metadata it needs is
already on the host (the count matrix) or one tiny scatter away (per-
bucket min/max stats, computed by phase 1 in the same program):

* **delta-packed keys** — phase 1 records each per-destination bucket's
  key minimum; phase 2 sends ``key - base[dest]`` cast to the narrowest
  unsigned dtype that holds the largest bucket range shard-wide (the
  static jit parameter), and the receiver adds the sender's base back.
  Integer subtract/add round-trips exactly, so the decode is
  bit-identical to the raw path.  (A per-run *dictionary* would need
  dynamic shapes; base+delta is the static-shape exact equivalent, and
  hash-spread intern ids — the worst case for run deltas — still narrow
  whenever the live id range does.)
* **narrow values** — same mechanism on the value column (base = bucket
  min, signed columns handled via their 64-bit bit patterns).
* **tiered bucket caps** — instead of ``nrounds`` uniform rounds of the
  power-of-two cap ``B`` (overshoot up to 2× of the max bucket), the
  round schedule becomes a descending ladder of power-of-two caps whose
  sum hugs the max bucket to ≲6% (4 significant bits), so one skewed
  bucket no longer inflates every bucket's padding to the next power of
  two.  The ladder is only adopted when it strictly beats the uniform
  schedule's slot count without exploding the round count.

Everything is decided HOST-side from the pulled count/stats matrices —
no extra device sync — and encoded/decoded INSIDE the phase-2
``shard_map`` program, so the host and every downstream consumer
(phase-2 sort/group, plan/ fused programs, reshard range exchanges) see
byte-identical uncompressed rows.  ``MRTPU_WIRE=0`` restores the raw
path; the planner itself falls back (``("raw", ...)`` plan) when no
column narrows and the tier ladder cannot beat the uniform schedule —
the "ratio < 1 auto-bypass" of doc/perf.md.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from .sharded import narrowest_uint, round_cap

# wire pack candidates, narrowest first: (dtype name, itemsize)
_PACKS = (("uint8", 1), ("uint16", 2), ("uint32", 4))

_META_COLS = 3             # count, kbase, vbase exchanged per bucket
_MAX_TIERS = 16            # same bound as shuffle._MAX_ROUNDS


def wire_enabled() -> bool:
    """``MRTPU_WIRE`` (default on; ``0`` = raw exchange).  Read at call
    time like the exec/ knobs so tests and the bench A/B flip it per
    run without re-importing."""
    from ..utils.env import env_flag
    return env_flag("MRTPU_WIRE", True)


def col_eligible(arr) -> bool:
    """A column the codec can delta-pack: 1-D integer rows wider than a
    byte.  Multi-word keys, floats and 1-byte riders ship raw (the
    tiered caps still apply to them)."""
    return (arr.ndim == 1 and arr.dtype.kind in "iu"
            and arr.dtype.itemsize >= 2)


def columns_eligible(key, value) -> Tuple[bool, bool]:
    return (col_eligible(key), col_eligible(value))


# ---------------------------------------------------------------------------
# phase-1 side: per-destination bucket stats (inside the same program)
# ---------------------------------------------------------------------------

def _widen(col):
    """The column in its 64-bit kind (min/max compare in the SIGNED
    domain for signed columns)."""
    return col.astype(jnp.int64 if col.dtype.kind == "i" else jnp.uint64)


def _bits64(x):
    """64-bit value → its uint64 bit pattern (host decodes signedness
    back via ``.view``)."""
    if x.dtype == jnp.uint64:
        return x
    return lax.bitcast_convert_type(x, jnp.uint64)


def bucket_stats(nprocs: int, key, value, dest, k_elig: bool,
                 v_elig: bool):
    """Per-destination (kmin, kmax, vmin, vmax) of this shard's valid
    rows, [P, 4] uint64 bit patterns.  ``dest`` carries ``nprocs`` for
    padding rows, so the scatters drop them; empty buckets keep their
    sentinels and the host masks them via the count matrix."""
    def minmax(col):
        w = _widen(col)
        info = jnp.iinfo(w.dtype)
        mn = jnp.full((nprocs,), info.max, w.dtype).at[dest].min(
            w, mode="drop")
        mx = jnp.full((nprocs,), info.min, w.dtype).at[dest].max(
            w, mode="drop")
        return _bits64(mn), _bits64(mx)

    zero = jnp.zeros((nprocs,), jnp.uint64)
    kmn, kmx = minmax(key) if k_elig else (zero, zero)
    vmn, vmx = minmax(value) if v_elig else (zero, zero)
    return jnp.stack([kmn, kmx, vmn, vmx], axis=1)


# ---------------------------------------------------------------------------
# host-side planning (from the pulled count + stats matrices)
# ---------------------------------------------------------------------------

def plan_tiers(counts_mat: np.ndarray, B: int, nrounds: int
               ) -> Tuple[int, ...]:
    """The round-cap schedule: a descending power-of-two ladder whose
    sum covers the max bucket with ≲6% overshoot (the max rounded up to
    4 significant bits — bounded compile diversity), each step bounded
    so the send buffer stays ≤ ~P·Bmax/4.  Falls back to the uniform
    ``(B,) * nrounds`` schedule whenever the ladder would not strictly
    reduce slots or would balloon the round count (tiny exchanges)."""
    uniform = (B,) * nrounds
    bmax = int(counts_mat.max()) if counts_mat.size else 0
    if bmax < 64:
        # tiny exchanges are latency-bound: extra collective rounds to
        # shave a few pad slots is a losing trade
        return uniform
    unit = 1 << max(0, bmax.bit_length() - 4)
    # one quantization unit of headroom (~1/8 of the max): a ladder
    # hugging the max exactly would invalidate the speculative-cap
    # cache on every few-percent distribution shift between repeats
    q = -(-bmax // unit) * unit + unit
    bbuf = round_cap(max(-(-q // 4), 8))   # ≤ ~5 rounds, buffer ≤ P·q/2
    tiers = []
    remaining = q
    while remaining > 0 and len(tiers) < _MAX_TIERS - 1:
        step = min(bbuf, round_cap(remaining))
        tiers.append(step)
        remaining -= step
    if remaining > 0:
        tiers.append(round_cap(remaining))
    tiers = tuple(tiers)
    if sum(tiers) >= B * nrounds or len(tiers) > max(nrounds + 2, 6):
        return uniform
    return tiers


def _bucket_ranges(counts_mat: np.ndarray, stats_mat: np.ndarray,
                   lo_col: int, hi_col: int, signed: bool
                   ) -> Optional[int]:
    """Largest (max - min) over nonempty buckets, as a python int (no
    overflow), or None when every bucket is empty."""
    mask = counts_mat > 0
    if not mask.any():
        return None
    view = stats_mat.view(np.int64) if signed else stats_mat
    lo = view[:, :, lo_col][mask]
    hi = view[:, :, hi_col][mask]
    return max(int(h) - int(l) for l, h in zip(lo.tolist(), hi.tolist()))


def _pack_for(rng: Optional[int], itemsize: int) -> Optional[str]:
    """Narrowest unsigned dtype (strictly narrower than the column)
    whose capacity holds ``rng``; None = ship raw."""
    if rng is None:
        return None
    name, width = narrowest_uint(rng)
    return name if width < itemsize else None


def plan_packs(key, value, counts_mat: np.ndarray,
               stats_mat: Optional[np.ndarray],
               elig: Tuple[bool, bool]):
    """(kpack, vpack, kvrange): wire dtypes per column (None = raw) and
    the observed max bucket ranges (speculation-validity evidence)."""
    kpack = vpack = None
    krange = vrange = None
    if stats_mat is not None:
        if elig[0]:
            krange = _bucket_ranges(counts_mat, stats_mat, 0, 1,
                                    key.dtype.kind == "i")
            kpack = _pack_for(krange, key.dtype.itemsize)
        if elig[1]:
            vrange = _bucket_ranges(counts_mat, stats_mat, 2, 3,
                                    value.dtype.kind == "i")
            vpack = _pack_for(vrange, value.dtype.itemsize)
    return kpack, vpack, (krange, vrange)


def make_plan(key, value, counts_mat: np.ndarray,
              stats_mat: Optional[np.ndarray], elig, B: int,
              nrounds: int, cap_out: int):
    """The exchange plan, a hashable tagged tuple (it keys the phase-2
    jit caches, the speculative-cap cache and the fused-plan caps):

    * ``("wire", tiers, cap_out, kpack, vpack)`` — codec engaged;
    * ``("raw", B, nrounds, cap_out)`` — auto-bypass: the codec's TOTAL
      per-pair bytes (tier slots at packed width + the [P, 3] u64
      metadata block) would not undercut the raw program's (uniform
      slots at full width + its int32 counts block), so the original
      program is the cheaper wire format.  Covers both "nothing
      narrows" and the tiny-exchange case where the metadata overhead
      eats the packing savings.

    Returns ``(plan, kvrange)``."""
    tiers = plan_tiers(counts_mat, B, nrounds)
    kpack, vpack, kvrange = plan_packs(key, value, counts_mat,
                                       stats_mat, elig)
    rb_full = _col_rowbytes(key, None) + _col_rowbytes(value, None)
    rb_packed = _col_rowbytes(key, kpack) + _col_rowbytes(value, vpack)
    wire_per_pair = sum(tiers) * rb_packed + _META_COLS * 8
    raw_per_pair = B * nrounds * rb_full + 4      # int32 counts block
    if wire_per_pair >= raw_per_pair:
        return ("raw", B, nrounds, cap_out), kvrange
    return ("wire", tiers, cap_out, kpack, vpack), kvrange


def _pack_capacity(pack: Optional[str]) -> Optional[int]:
    if pack is None:
        return None
    return (1 << (8 * np.dtype(pack).itemsize)) - 1


def _pack_covers(spec_pack: Optional[str], rng: Optional[int]) -> bool:
    """A cached plan's pack still round-trips the fresh data: raw always
    does; a narrow pack needs the fresh range to fit."""
    if spec_pack is None:
        return True
    if rng is None:        # no valid rows — any width is exact
        return True
    return rng <= _pack_capacity(spec_pack)


def plan_slots(plan) -> int:
    """Per-bucket slots the plan exchanges (the pad accounting input)."""
    if plan[0] == "wire":
        return int(sum(plan[1]))
    return int(plan[1] * plan[2])


def plan_rounds(plan) -> Tuple[int, int]:
    """(bucket_cap, nrounds) for telemetry: the largest tier stands in
    for the uniform B under a wire plan."""
    if plan[0] == "wire":
        return int(max(plan[1])), len(plan[1])
    return int(plan[1]), int(plan[2])


def plan_cap_out(plan) -> int:
    return int(plan[2] if plan[0] == "wire" else plan[3])


def plan_holds(plan, Bmax: int, nmax_out: int, kvrange) -> bool:
    """A cached/speculative plan still delivers every row exactly: the
    slot budget covers the max bucket, the output cap covers the max
    shard, and (wire plans) the cached pack widths still hold the fresh
    bucket ranges."""
    if plan_slots(plan) < Bmax or plan_cap_out(plan) < nmax_out:
        return False
    if plan[0] == "wire":
        return (_pack_covers(plan[3], kvrange[0])
                and _pack_covers(plan[4], kvrange[1]))
    return True


def plan_oversized(plan, Bmax: int, nmax_out: int) -> bool:
    """Grossly over-provisioned for the fresh distribution (the
    speculative cache's right-sizing rule, shared with the fused tier)."""
    return (plan_slots(plan) > 4 * max(Bmax, 8)
            or plan_cap_out(plan) > 4 * round_cap(nmax_out))


def plan_from_pull(key, value, counts_mat: np.ndarray,
                   stats_mat: Optional[np.ndarray], wire_on: bool, elig):
    """ONE copy of the host planning step shared by the eager exchange
    and the plan/ fuser (their plan choice and telemetry must never
    diverge): pulled count/stats matrices → ``(plan, kvrange,
    bmax_raw, nmax_out, new_counts)``.  ``bmax_raw`` is the coverage
    bound cached plans validate against (the pow2-rounded Bmax would
    wrongly invalidate tier ladders that hug the real max)."""
    from .shuffle import _plan_caps
    B, nrounds, cap_out, _bmax, new_counts = _plan_caps(counts_mat)
    bmax_raw = int(counts_mat.max())
    nmax_out = max(int(new_counts.max()), 8)
    if wire_on:
        plan, kvrange = make_plan(key, value, counts_mat, stats_mat,
                                  elig, B, nrounds, cap_out)
    else:
        plan, kvrange = ("raw", B, nrounds, cap_out), (None, None)
    return plan, kvrange, bmax_raw, nmax_out, new_counts


def wire_ratio(moved: int, pad: int, wire_bytes: int) -> float:
    """The logical/actual compression ratio (one formula for the eager
    and fused telemetry feeds; 0.0 = the codec did not run)."""
    return round((moved + pad) / wire_bytes, 4) if wire_bytes else 0.0


# ---------------------------------------------------------------------------
# the in-program codec (phase-2 shard body)
# ---------------------------------------------------------------------------

def _base_in(base_bits, dtype):
    """[P] uint64 bit patterns → per-bucket bases in the column dtype
    (exact: the base is a value OF that column)."""
    if np.dtype(dtype).kind == "i":
        return lax.bitcast_convert_type(base_bits, jnp.int64).astype(dtype)
    return base_bits.astype(dtype)


def _encode_col(col, base_bits, dest, pack: str):
    """``col - base[dest]`` cast to the wire dtype.  Valid rows fit the
    pack width by construction (the planner checked the ranges); rows
    past the valid count carry garbage and are dropped by the send
    scatter."""
    base = _base_in(base_bits, col.dtype)
    return (col - jnp.take(base, dest)).astype(jnp.dtype(pack))


def _decode_col(packed, base_bits, src, valid, dtype):
    """``base[src] + delta``, masked to zero off the valid prefix so the
    decoded block is byte-identical to the raw path's zero-padded
    output."""
    base = _base_in(base_bits, dtype)
    full = jnp.take(base, src) + packed.astype(dtype)
    return jnp.where(valid, full, jnp.zeros((), dtype))


def phase2_wire_shard_body(nprocs: int, transport: int, mesh, tiers,
                           cap_out: int, kpack: Optional[str],
                           vpack: Optional[str], k, v, cl, stats):
    """The wire twin of ``shuffle.phase2_shard_body``: same multi-round
    bounded exchange and same packed output layout (row positions are
    identical, so output is byte-identical), but rows cross the
    interconnect delta-packed at the planned widths and the round caps
    follow the tier ladder.  One extra tiny collective replaces the
    counts exchange: ``(count, kbase, vbase)`` per bucket ride together
    as a [P, 3] uint64 block."""
    from .shuffle import _build_send_window, _exchange_blocks

    meta_local = jnp.stack([cl.astype(jnp.uint64), stats[:, 0],
                            stats[:, 2]], axis=1)          # [P, 3]
    meta_from = _exchange_blocks(meta_local[:, None, :], transport,
                                 mesh)[:, 0, :]
    counts_from = meta_from[:, 0].astype(jnp.int32)

    # encode: dest of each dest-sorted row from the local counts
    cap = k.shape[0]
    cum = jnp.cumsum(cl)
    denc = jnp.minimum(
        jnp.searchsorted(cum, jnp.arange(cap), side="right"),
        nprocs - 1).astype(jnp.int32)
    ke = _encode_col(k, stats[:, 0], denc, kpack) if kpack else k
    ve = _encode_col(v, stats[:, 2], denc, vpack) if vpack else v

    cumf = jnp.cumsum(counts_from)
    base = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), cumf[:-1].astype(jnp.int32)])
    out_k = jnp.zeros((cap_out,) + ke.shape[1:], ke.dtype)
    out_v = jnp.zeros((cap_out,) + ve.shape[1:], ve.dtype)
    start = 0
    for B in tiers:
        recv_k = _exchange_blocks(
            _build_send_window(nprocs, B, start, ke, cl), transport, mesh)
        recv_v = _exchange_blocks(
            _build_send_window(nprocs, B, start, ve, cl), transport, mesh)
        q_global = start + jnp.arange(B, dtype=jnp.int32)[None, :]
        pos = jnp.where(q_global < counts_from[:, None],
                        base[:, None] + q_global, cap_out)
        out_k = out_k.at[pos.reshape(-1)].set(
            recv_k.reshape((-1,) + ke.shape[1:]), mode="drop")
        out_v = out_v.at[pos.reshape(-1)].set(
            recv_v.reshape((-1,) + ve.shape[1:]), mode="drop")
        start += B
    nrecv = jnp.sum(counts_from)

    if kpack or vpack:
        idx = jnp.arange(cap_out)
        src = jnp.minimum(jnp.searchsorted(cumf, idx, side="right"),
                          nprocs - 1).astype(jnp.int32)
        valid = idx < nrecv
        if kpack:
            out_k = _decode_col(out_k, meta_from[:, 1], src, valid,
                                k.dtype)
        if vpack:
            out_v = _decode_col(out_v, meta_from[:, 2], src, valid,
                                v.dtype)
    return out_k, out_v, nrecv


# ---------------------------------------------------------------------------
# byte accounting
# ---------------------------------------------------------------------------

def _col_rowbytes(arr, pack: Optional[str]) -> int:
    if pack is not None:
        return np.dtype(pack).itemsize
    return arr.dtype.itemsize * (arr.shape[-1] if arr.ndim > 1 else 1)


def wire_volume(skv, counts_mat: np.ndarray, plan) -> int:
    """Actual bytes a ``("wire", ...)`` plan puts on the interconnect:
    every exchanged slot (useful + pad, diagonal excluded on both sides
    like ``exchange_volume``) at the packed row width, plus the [P, 3]
    uint64 per-bucket metadata block the codec ships instead of the raw
    path's [P, 1] int32 counts."""
    nprocs = counts_mat.shape[0]
    _tag, tiers, _cap_out, kpack, vpack = plan
    rowbytes = (_col_rowbytes(skv.key, kpack)
                + _col_rowbytes(skv.value, vpack))
    slots = nprocs * (nprocs - 1) * int(sum(tiers))
    meta = nprocs * (nprocs - 1) * _META_COLS * 8
    return slots * rowbytes + meta
