"""Live topology resharding — ``mr.reshard(new_mesh)`` as a collective.

The mesh was fixed at MapReduce construction (ROADMAP item 4): losing
or gaining a device meant rebuilding the world and re-ingesting.  This
module redistributes a resident :class:`~.sharded.ShardedKV` /
:class:`~.sharded.ShardedKMV` from an N-way to an M-way mesh as a
collective program, following the portable collective-communication
redistribution recipe (arXiv:2112.01075): the redistribution SCHEDULE
(which global row ranges land on which target shard) is computed
host-side from the per-shard counts — metadata the controller already
holds — while the data itself moves only through the existing two-phase
``lax.all_to_all`` exchange (``shuffle.py``), never through a host
round-trip.

Mechanics, per direction:

* **narrowing (M ≤ N)** — one exchange ON THE OLD MESH with the
  ``("range", offsets, ends)`` destination spec: row r of shard i has
  global index ``offsets[i]+r`` and routes to the target shard whose
  cumulative range covers it (all dests < M ≤ N, so the old mesh's
  collective can deliver them).  The output blocks for shards < M are
  then *re-homed* onto the new mesh — per-device buffer adoption via
  ``make_array_from_single_device_arrays``, zero-copy when old and new
  meshes share a device prefix.
* **widening (M > N)** — re-home first (old blocks become the first N
  shards of an M-wide array, the rest zero-padded), then run the same
  range exchange ON THE NEW MESH, where all M destinations exist.

Because the range destination is monotone in the global row index,
phase 1's stable dest-sort is the identity permutation and the packed
exchange output preserves exact global row order — an N→M→N round trip
is byte-identical (``tests/test_elastic.py``), and the whole thing runs
under the ft/ ``shuffle.exchange`` retry policy like every exchange.

Range exchanges ride the SAME ``exchange()`` core as dest-fn shuffles,
so they inherit the wire codec (``parallel/wire.py``, MRTPU_WIRE —
delta-packed keys, narrow values, tiered caps; the KMV value pass's
1-byte rider ships raw by construction) and feed the same telemetry:
``record_exchange`` sent/pad/wire bytes, ``mr.counters`` cssize/cspad,
and the active RequestAccount — pinned by
``tests/test_wire.py::test_range_reshard_feeds_exchange_metrics``.

KMV datasets reshard at GROUP granularity: groups stay atomic (a
group's value run never splits across shards).  The group-boundary
schedule needs the per-group value counts — an O(groups) metadata pull,
not a data round-trip — and the value rows then follow their groups
through a second range exchange.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .mesh import mesh_axis_size, row_sharding
from .sharded import ShardedKMV, ShardedKV, round_cap
from .shuffle import exchange


def even_counts(n: int, m: int) -> np.ndarray:
    """The canonical M-way contiguous split (same formula as
    ``sharded.shard_frame`` — the two must never disagree, or a
    reshard and a fresh shard of the same rows would differ)."""
    per = -(-n // m) if n else 0
    starts = np.minimum(np.arange(m) * per, n)
    ends = np.minimum(starts + per, n)
    return (ends - starts).astype(np.int32)


def _offsets(counts) -> Tuple[int, ...]:
    """Exclusive prefix sum: shard i's global row offset."""
    return tuple(int(x) for x in
                 np.concatenate([[0], np.cumsum(counts)])[:-1])


def _blocks(arr, nprocs: int) -> list:
    """Per-shard single-device blocks of a row-sharded array, shard
    order.  Single-controller scope: every shard must be addressable
    (the multi-host variant would swap this for a per-process slice)."""
    cap = arr.shape[0] // nprocs
    out = [None] * nprocs
    for sh in arr.addressable_shards:
        out[(sh.index[0].start or 0) // cap] = sh.data
    if any(b is None for b in out):
        raise ValueError("reshard: not every shard is addressable "
                         "from this controller")
    return out


def _assemble(blocks: list, new_mesh: Mesh):
    """Adopt per-shard blocks as one row-sharded array on ``new_mesh``
    — zero-copy for blocks already resident on the target device, a
    device-to-device put otherwise (never through the host)."""
    M = mesh_axis_size(new_mesh)
    assert len(blocks) == M
    cap = blocks[0].shape[0]
    sharding = row_sharding(new_mesh)
    shape = (M * cap,) + tuple(blocks[0].shape[1:])
    dmap = sharding.addressable_devices_indices_map(shape)
    arrs = []
    for dev, idx in dmap.items():
        blk = blocks[(idx[0].start or 0) // cap]
        if dev not in blk.devices():
            blk = jax.device_put(blk, dev)
        arrs.append(blk)
    return jax.make_array_from_single_device_arrays(shape, sharding, arrs)


def _zeros_like_block(block, dev):
    return jax.device_put(jnp.zeros(block.shape, block.dtype), dev)


def _widen(skv: ShardedKV, new_mesh: Mesh) -> ShardedKV:
    """Re-home an N-shard dataset as the first N shards of an M-wide
    mesh (M > N), zero-padding the rest — the pre-pass that lets the
    range exchange run where all M destinations exist."""
    N = skv.nprocs
    M = mesh_axis_size(new_mesh)
    devs = list(np.asarray(new_mesh.devices).reshape(-1))

    def grow(arr):
        blocks = _blocks(arr, N)
        pad = [_zeros_like_block(blocks[0], devs[j])
               for j in range(N, M)]
        return _assemble(blocks + pad, new_mesh)

    counts = np.concatenate([skv.counts,
                             np.zeros(M - N, np.int32)]).astype(np.int32)
    out = ShardedKV(new_mesh, grow(skv.key), grow(skv.value), counts,
                    key_decode=skv.key_decode,
                    value_decode=skv.value_decode)
    # the widened arrays ALIAS the original frame's device buffers —
    # donation would delete them out from under a failed exchange's
    # retry, so mark shared (exec.can_donate vetoes)
    out._shared = True
    return out


def _narrow(skv: ShardedKV, new_mesh: Mesh) -> ShardedKV:
    """Adopt the first M shard blocks of a routed exchange output as an
    M-wide dataset (the counts beyond M are zero by construction)."""
    M = mesh_axis_size(new_mesh)
    N = skv.nprocs
    assert all(int(c) == 0 for c in skv.counts[M:]), \
        "narrow: rows routed past the target width"
    return ShardedKV(new_mesh,
                     _assemble(_blocks(skv.key, N)[:M], new_mesh),
                     _assemble(_blocks(skv.value, N)[:M], new_mesh),
                     skv.counts[:M].copy(),
                     key_decode=skv.key_decode,
                     value_decode=skv.value_decode)


def _exchange_range(skv: ShardedKV, new_mesh: Mesh,
                    ends: Tuple[int, ...], transport: int,
                    counters) -> ShardedKV:
    """The shared routing core: contiguous-global-order rows of ``skv``
    → target shards per the host-computed ``ends`` schedule, result on
    ``new_mesh``."""
    N = skv.nprocs
    M = mesh_axis_size(new_mesh)
    if M > N:
        skv = _widen(skv, new_mesh)
        out = exchange(skv, ("range", _offsets(skv.counts), ends),
                       transport=transport, counters=counters)
        return out
    out = exchange(skv, ("range", _offsets(skv.counts), ends),
                   transport=transport, counters=counters)
    return _narrow(out, new_mesh)


def reshard_kv(skv: ShardedKV, new_mesh: Mesh, transport: int = 1,
               counters=None) -> ShardedKV:
    """Redistribute a ShardedKV onto ``new_mesh`` (any width), global
    row order preserved exactly.  The id→bytes decode tables ride along
    unchanged: ``ShardTables.decode_batch`` routes by id hash over its
    OWN table count, independent of row placement."""
    tcounts = even_counts(len(skv), mesh_axis_size(new_mesh))
    ends = tuple(int(x) for x in np.cumsum(tcounts))
    out = _exchange_range(skv, new_mesh, ends, transport, counters)
    return out


def reshard_kmv(skmv: ShardedKMV, new_mesh: Mesh, transport: int = 1,
                counters=None) -> ShardedKMV:
    """Redistribute a ShardedKMV onto ``new_mesh`` at group
    granularity.  Two range exchanges (groups, then their value runs)
    share one host-computed schedule; the new shard-local value offsets
    are recomputed from the same metadata."""
    N = skmv.nprocs
    M = mesh_axis_size(new_mesh)
    G = len(skmv)
    gcap = skmv.gcap
    # metadata pull: per-group value counts in global (shard-major)
    # group order — the schedule input, not the data
    nv_host = np.asarray(skmv.nvalues)
    nv_global = (np.concatenate(
        [nv_host[i * gcap:i * gcap + int(skmv.gcounts[i])]
         for i in range(N)]).astype(np.int64)
        if G else np.zeros(0, np.int64))

    tg = even_counts(G, M)                       # groups per target shard
    gends = tuple(int(x) for x in np.cumsum(tg))
    vcum = np.concatenate([[0], np.cumsum(nv_global)]).astype(np.int64)
    vends = tuple(int(vcum[e]) for e in gends)   # group-aligned value cuts
    tv = np.diff(np.concatenate([[0], vends])).astype(np.int32)

    # exchange 1: the group-level rows (ukey + nvalues ride together)
    gkv = ShardedKV(skmv.mesh, skmv.ukey, skmv.nvalues,
                    skmv.gcounts.astype(np.int32),
                    key_decode=skmv.key_decode)
    gkv._shared = True      # buffers belong to the live KMV frame
    gout = _exchange_range(gkv, new_mesh, gends, transport, counters)

    # exchange 2: the value rows, routed by the SAME group-aligned cuts
    # (a 1-byte rider fills the KV-shaped exchange's second column)
    rider = jnp.zeros((skmv.values.shape[0],), jnp.int8)
    rider = jax.device_put(rider, row_sharding(skmv.mesh))
    vkv = ShardedKV(skmv.mesh, skmv.values, rider,
                    skmv.vcounts.astype(np.int32),
                    key_decode=skmv.value_decode)
    vkv._shared = True
    vout = _exchange_range(vkv, new_mesh, vends, transport, counters)

    # new shard-local value offsets from the same host schedule
    gcap_new = gout.cap
    voff = np.zeros(M * gcap_new, np.int32)
    gstart = 0
    for j in range(M):
        nvj = nv_global[gstart:gstart + int(tg[j])]
        voff[j * gcap_new:j * gcap_new + int(tg[j])] = np.concatenate(
            [[0], np.cumsum(nvj)])[:-1]
        gstart += int(tg[j])
    from .mesh import device_put_chunked
    voff_dev = device_put_chunked(voff, row_sharding(new_mesh))

    return ShardedKMV(new_mesh, gout.key, gout.value, voff_dev,
                      vout.key, tg, tv,
                      key_decode=skmv.key_decode,
                      value_decode=skmv.value_decode)
