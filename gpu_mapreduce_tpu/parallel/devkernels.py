"""Device twins of the OINK graph kernels — shard-resident iteration.

Round 1 ran every graph-command callback by pulling ShardedKV/ShardedKMV to
host numpy each round (``oink/kernels.py`` ``host_kv``/``host_kmv``) — the
mesh shuffled on device but computed on the controller, which caps scaling
at the controller's memory and PCIe (VERDICT r1 #4).  This module gives the
iterative commands (cc_find, luby_find, sssp, tri_find, degree …) a
*device tier*: each batch kernel has a per-shard jittable body running
under ``shard_map``, so a whole iteration is shuffle → segment ops →
emit, all in HBM; the only host traffic is the per-op row counts — the
same scalars the reference Allreduces after every op
(``src/mapreduce.cpp:557-558``).

Kernel bodies follow one convention: they receive the shard's padded
blocks and return ``(key_rows, value_rows, valid_mask)`` of one static
shape; the wrapper packs valid rows to the front (stable, so emission
order within a shard is deterministic), counts them, and wraps a new
:class:`ShardedKV`.  Row counts per shard are data-dependent — the pack +
count IS the TPU version of the reference's "emit into the open KV page".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .group import _local_segment_ids
from .mesh import row_sharding, row_spec
from .sharded import ShardedKMV, ShardedKV, SyncStats

U64MAX = np.uint64(0xFFFFFFFFFFFFFFFF)


def is_sharded_kv(fr) -> bool:
    return isinstance(fr, ShardedKV)


def is_sharded_kmv(fr) -> bool:
    return isinstance(fr, ShardedKMV)


def _pack(ok, ov, valid):
    """Stable front-packing via prefix-sum + scatter-with-drop — the same
    idiom compact_word_matches documents (~20× cheaper than the sort-based
    form on TPU; positions are unique by construction)."""
    n = valid.shape[0]
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    tgt = jnp.where(valid, pos, n)
    okey = jnp.zeros_like(ok).at[tgt].set(ok, mode="drop")
    oval = jnp.zeros_like(ov).at[tgt].set(ov, mode="drop")
    return okey, oval, jnp.sum(valid.astype(jnp.int32))[None]


@functools.lru_cache(maxsize=None)
def _skv_map_jit(mesh, fn, static, nextra):
    spec = row_spec(mesh)

    @jax.jit
    def run(key, value, count, *extra):
        def body(k, v, c, *ex):
            return _pack(*fn(k, v, c[0], *ex, *static))
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec) + (P(),) * nextra,
            out_specs=(spec, spec, spec))(key, value, count, *extra)

    return run


def _check_decodes(fr, preserve_decodes: bool, what: str):
    """Interned byte/object ids look like plain numbers inside a kernel
    body; silently doing arithmetic on them is the bug reduce_sharded
    already guards against (ADVICE r3: the kernel-map path did not).
    ``preserve_decodes=True`` is the caller's assertion that the kernel
    treats ids as opaque and keeps them in the same id space, so the
    tables stay valid on the output frame."""
    if preserve_decodes:
        return fr.key_decode, fr.value_decode
    if fr.key_decode is not None or fr.value_decode is not None:
        which = [n for n, t in (("key", fr.key_decode),
                                ("value", fr.value_decode)) if t is not None]
        raise ValueError(
            f"{what}: {'/'.join(which)} entries are interned byte/object "
            f"ids — a numeric kernel over them is meaningless; decode to "
            f"host first, or pass preserve_decodes=True if the kernel "
            f"treats them as opaque ids")
    return None, None


def skv_map(skv: ShardedKV, fn, static=(), extra=(),
            preserve_decodes: bool = False) -> ShardedKV:
    """Run a per-shard KV kernel body ``fn(key, value, count, *extra,
    *static) → (okey, ovalue, valid)`` and pack the result into a new
    ShardedKV.  ``static`` values are jit constants (shapes, caps);
    ``extra`` values are TRACED replicated operands (seeds, thresholds) —
    varying them re-uses the compiled kernel.  Frames carrying decode
    tables are rejected unless ``preserve_decodes`` (see
    :func:`_check_decodes`)."""
    kd, vd = _check_decodes(skv, preserve_decodes, "skv_map")
    counts = jax.device_put(skv.counts.astype(np.int32),
                            row_sharding(skv.mesh))
    k, v, c = _skv_map_jit(skv.mesh, fn, tuple(static), len(extra))(
        skv.key, skv.value, counts, *extra)
    SyncStats.bump()
    return ShardedKV(skv.mesh, k, v, np.asarray(c).astype(np.int32),
                     key_decode=kd, value_decode=vd)


@functools.lru_cache(maxsize=None)
def _skmv_map_jit(mesh, fn, static, nextra):
    spec = row_spec(mesh)

    @jax.jit
    def run(ukey, nval, voff, values, gcount, vcount, *extra):
        def body(uk, nv, vo, vals, gc, vc, *ex):
            return _pack(*fn(uk, nv, vo, vals, gc[0], vc[0], *ex, *static))
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec,) * 6 + (P(),) * nextra,
            out_specs=(spec, spec, spec))(ukey, nval, voff, values,
                                          gcount, vcount, *extra)

    return run


def skmv_map(kmv: ShardedKMV, fn, static=(), extra=(),
             preserve_decodes: bool = False) -> ShardedKV:
    """Run a per-shard KMV kernel body ``fn(ukey, nvalues, voffsets,
    values, gcount, vcount, *extra, *static) → (okey, ovalue, valid)`` (a
    vectorised appreduce) and pack into a new ShardedKV.  ``extra`` and
    the decode-table guard as in :func:`skv_map`."""
    kd, vd = _check_decodes(kmv, preserve_decodes, "skmv_map")
    put = lambda x: jax.device_put(x.astype(np.int32), row_sharding(kmv.mesh))
    k, v, c = _skmv_map_jit(kmv.mesh, fn, tuple(static), len(extra))(
        kmv.ukey, kmv.nvalues, kmv.voffsets, kmv.values,
        put(kmv.gcounts), put(kmv.vcounts), *extra)
    SyncStats.bump()
    return ShardedKV(kmv.mesh, k, v, np.asarray(c).astype(np.int32),
                     key_decode=kd, value_decode=vd)


# ---------------------------------------------------------------------------
# shard-resident concat (MapReduce.add of two mesh datasets)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _concat_jit(mesh):
    spec = row_spec(mesh)

    @jax.jit
    def run(k1, v1, c1, k2, v2, c2):
        def body(ka, va, ca, kb, vb, cb):
            na, nb = ka.shape[0], kb.shape[0]
            valid = jnp.concatenate([jnp.arange(na) < ca[0],
                                     jnp.arange(nb) < cb[0]])
            return _pack(jnp.concatenate([ka, kb]),
                         jnp.concatenate([va, vb]), valid)
        return jax.shard_map(body, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=(spec, spec, spec))(k1, v1, c1,
                                                           k2, v2, c2)

    return run


def _merge_decode(ta, tb, what: str):
    """Union two id→key/value intern tables (None means plain ids; mixing
    plain with interned would merge two incompatible spaces).  Tables of
    DIFFERENT kinds (bytes vs object) must be domain-aligned first —
    concat_sharded re-interns the bytes-kind side through the pickle
    domain before calling here (:func:`_align_domains`)."""
    if (ta is None) != (tb is None):
        raise ValueError(
            f"cannot add an interned byte/object-{what}ed mesh dataset "
            f"to a plain one: the merge would span two {what} spaces")
    if not tb:
        return ta
    from ..core.column import InternTable, ShardTables
    if isinstance(ta, ShardTables):
        return ta.merge(tb)
    if isinstance(tb, ShardTables):
        return tb.merge(ta)
    kind = ("object" if "object" in (getattr(ta, "kind", "bytes"),
                                     getattr(tb, "kind", "bytes"))
            else "bytes")
    return InternTable({**ta, **tb}, kind=kind)


@functools.lru_cache(maxsize=None)
def _remap_ids_jit(mesh, m: int):
    """old-id → new-id elementwise remap against a replicated sorted
    lookup of length m (pow2-padded); ids absent from the lookup pass
    through unchanged (padding rows beyond counts)."""
    from jax.sharding import NamedSharding

    @functools.partial(jax.jit,
                       out_shardings=NamedSharding(mesh, row_spec(mesh)))
    def run(col, old_sorted, new_by_old):
        pos = jnp.clip(jnp.searchsorted(old_sorted, col), 0, m - 1)
        hit = old_sorted[pos] == col
        return jnp.where(hit, new_by_old[pos], col)

    return run


def _reintern_pickle_domain(col, table, mesh):
    """Re-intern a bytes-kind decode table + its device id column through
    the PICKLE id domain (the object tier's): every stored bytes row
    re-hashes over its pickle — exactly what _intern_side's
    BytesColumn→ObjectColumn promotion does at ingest
    (parallel/ingest.py) — and the id column remaps old→new in one
    jitted lookup.  Returns (new column, new object-kind table)."""
    import pickle
    from jax.sharding import NamedSharding
    from ..core.column import InternTable, ShardTables, _intern_core
    from .sharded import round_cap
    old_ids = np.fromiter(table.keys(), np.uint64, len(table))
    if not len(old_ids):
        empty = (ShardTables(table.P, kind="object")
                 if isinstance(table, ShardTables)
                 else InternTable(kind="object"))
        return col, empty
    rows = (table.decode_batch(old_ids) if hasattr(table, "decode_batch")
            else [table[int(h)] for h in old_ids])
    probes = [pickle.dumps(r, protocol=4) for r in rows]
    new_ids, uniq, first = _intern_core(probes)
    if isinstance(table, ShardTables):
        newt = ShardTables(table.P, kind="object")
        newt.absorb(uniq, [rows[int(i)] for i in first],
                    probes=[probes[int(i)] for i in first])
    else:
        newt = InternTable(((int(new_ids[int(i)]), rows[int(i)])
                            for i in first), kind="object")
    order = np.argsort(old_ids)
    # pow2-padded replicated lookup (sentinel never matches a real id)
    # so recompiles stay bounded, like sort_interned_sharded's surrogate
    m = len(old_ids)
    mcap = round_cap(m)
    old_sorted = np.full(mcap, U64MAX, np.uint64)
    new_by_old = np.full(mcap, U64MAX, np.uint64)
    old_sorted[:m] = old_ids[order]
    new_by_old[:m] = new_ids[order]
    rep = NamedSharding(mesh, P())
    out = _remap_ids_jit(mesh, mcap)(col,
                                     jax.device_put(old_sorted, rep),
                                     jax.device_put(new_by_old, rep))
    return out, newt


def _align_domains(a: ShardedKV, b: ShardedKV, which: str):
    """Cross-domain id alignment before a concat (ADVICE r5): a
    bytes-kind table's ids hash RAW BYTES while an object-kind table's
    hash PICKLES, so the same logical key concatenated from a bytes-keyed
    and an object-keyed dataset would carry two distinct u64 ids and
    never group.  When the kinds differ, the bytes-kind side re-interns
    through the pickle domain so both datasets share one id space."""
    ta = a.key_decode if which == "key" else a.value_decode
    tb = b.key_decode if which == "key" else b.value_decode
    ca = a.key if which == "key" else a.value
    cb = b.key if which == "key" else b.value
    if ta is None or tb is None or \
            getattr(ta, "kind", "bytes") == getattr(tb, "kind", "bytes"):
        return ca, cb, ta, tb
    if getattr(ta, "kind", "bytes") == "bytes":
        ca, ta = _reintern_pickle_domain(ca, ta, a.mesh)
    else:
        cb, tb = _reintern_pickle_domain(cb, tb, b.mesh)
    return ca, cb, ta, tb


def concat_sharded(a: ShardedKV, b: ShardedKV) -> ShardedKV:
    """Per-shard concatenation of two mesh KV datasets (the device path of
    ``MapReduce::add``, src/mapreduce.cpp:348-374).  Differing intern
    domains (bytes-kind vs object-kind tables) align through the pickle
    domain first, so equal logical keys from the two datasets group after
    the concat (:func:`_align_domains`, ADVICE r5)."""
    assert a.mesh is b.mesh or a.mesh == b.mesh
    ak, bk, kta, ktb = _align_domains(a, b, "key")
    av, bv, vta, vtb = _align_domains(a, b, "value")
    put = lambda s: jax.device_put(s.counts.astype(np.int32),
                                   row_sharding(a.mesh))
    k, v, c = _concat_jit(a.mesh)(ak, av, put(a), bk, bv, put(b))
    SyncStats.bump()
    return ShardedKV(a.mesh, k, v, np.asarray(c).astype(np.int32),
                     key_decode=_merge_decode(kta, ktb, "key"),
                     value_decode=_merge_decode(vta, vtb, "value"))


def clone_sharded(skv: ShardedKV) -> ShardedKMV:
    """KV→KMV with every row its own single-value group, per shard
    (the device path of ``MapReduce::clone``, src/mapreduce.cpp:631-652)."""
    P, cap = skv.nprocs, skv.cap
    nv = (np.arange(cap)[None, :] < skv.counts[:, None]).astype(np.int32)
    vo = np.tile(np.arange(cap, dtype=np.int32), (P, 1))
    sharding = row_sharding(skv.mesh)
    from .mesh import device_put_chunked
    return ShardedKMV(skv.mesh, skv.key,
                      device_put_chunked(nv.reshape(-1), sharding),
                      device_put_chunked(vo.reshape(-1), sharding),
                      skv.value, skv.counts.copy(), skv.counts.copy(),
                      key_decode=skv.key_decode,
                      value_decode=skv.value_decode)


# ---------------------------------------------------------------------------
# segment helpers shared by the KMV kernel bodies
# ---------------------------------------------------------------------------

def kmv_row_state(nv, vo, vals, gc, vc):
    """Common prologue: (segment ids [vcap], row-valid [vcap],
    group-valid [gcap])."""
    vcap = vals.shape[0]
    seg = _local_segment_ids(vo, nv, vcap)
    rows_valid = (jnp.arange(vcap) < vc) & (seg >= 0)
    groups_valid = jnp.arange(nv.shape[0]) < gc
    return seg, rows_valid, groups_valid


def seg_min_u64(x, seg, valid, gcap):
    v = jnp.where(valid, x, U64MAX)
    return jax.ops.segment_min(v, jnp.where(valid, seg, gcap),
                               num_segments=gcap + 1)[:gcap]


def seg_max_u64(x, seg, valid, gcap):
    v = jnp.where(valid, x, jnp.uint64(0))
    return jax.ops.segment_max(v, jnp.where(valid, seg, gcap),
                               num_segments=gcap + 1)[:gcap]


def seg_min_with(x, seg, valid, gcap, identity):
    """Segment min with an explicit identity (f64 paths use +inf)."""
    v = jnp.where(valid, x, identity)
    return jax.ops.segment_min(v, jnp.where(valid, seg, gcap),
                               num_segments=gcap + 1)[:gcap]


def seg_lex_min2(a, b, seg, valid, gcap, ident_a, ident_b):
    """Per-segment lexicographic min of (a, b) rows: returns (amin, bmin)
    where amin = min a and bmin = min b among rows attaining amin —
    the shared 'best (dist, pred) per vertex' idiom (sssp)."""
    amin = seg_min_with(a, seg, valid, gcap, ident_a)
    att = valid & (a == jnp.take(amin, jnp.maximum(seg, 0)))
    bmin = seg_min_with(b, seg, att, gcap, ident_b)
    return amin, bmin


# ---------------------------------------------------------------------------
# generic edge/vertex kernel bodies (device twins of oink/kernels.py maps)
# ---------------------------------------------------------------------------

def _null_like(k):
    return jnp.zeros(k.shape[0], jnp.uint8)


def edge_to_vertices_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    okey = jnp.concatenate([k[:, 0], k[:, 1]])
    vv = jnp.concatenate([valid, valid])
    return okey, _null_like(okey), vv


def edge_to_vertex_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    return k[:, 0], _null_like(k), valid


def edge_to_vertex_pair_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    return k[:, 0], k[:, 1], valid


def edge_both_directions_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    okey = jnp.concatenate([k[:, 0], k[:, 1]])
    oval = jnp.concatenate([k[:, 1], k[:, 0]])
    return okey, oval, jnp.concatenate([valid, valid])


def edge_upper_dev(k, v, c):
    valid = (jnp.arange(k.shape[0]) < c) & (k[:, 0] != k[:, 1])
    lo = jnp.minimum(k[:, 0], k[:, 1])
    hi = jnp.maximum(k[:, 0], k[:, 1])
    return jnp.stack([lo, hi], 1), _null_like(k), valid


def invert_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    return v, k, valid


def add_weight_dev(k, v, c):
    valid = jnp.arange(k.shape[0]) < c
    return k, jnp.ones(k.shape[0], jnp.float64), valid
