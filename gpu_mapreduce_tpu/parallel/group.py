"""Sharded convert / sort / segment-reduce — the local half of collate.

The reference's convert is purely local per rank (SURVEY.md §3.3: "No MPI at
all — the parallelism came from aggregate").  Same here: each shard sorts its
own block and finds group boundaries under ``shard_map``; no collectives.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from jax.sharding import NamedSharding

from ..core.runtime import bump_dispatch
from .mesh import mesh_axis_size, row_sharding, row_spec
from .sharded import (ShardedKMV, ShardedKV, SyncStats, _decode_col,
                      round_cap)


def _sort_key_tuple(key, valid):
    """lexsort key tuple putting invalid rows last, then by key ascending.
    numpy/jnp lexsort: LAST key is primary."""
    cols = [key] if key.ndim == 1 else [key[:, j] for j in range(key.shape[1] - 1, -1, -1)]
    return tuple(cols) + (~valid,)


def _local_sort(key, value, count):
    cap = key.shape[0]
    valid = jnp.arange(cap) < count
    order = jnp.lexsort(_sort_key_tuple(key, valid))
    return (jnp.take(key, order, axis=0), jnp.take(value, order, axis=0), valid)


def _boundary(skey, valid):
    if skey.ndim == 1:
        diff = skey[1:] != skey[:-1]
    else:
        diff = jnp.any(skey[1:] != skey[:-1], axis=1)
    first = jnp.ones(1, bool)
    return valid & jnp.concatenate([first, diff])


@functools.lru_cache(maxsize=None)
def _convert_phase1_jit(mesh):
    spec = row_spec(mesh)

    @jax.jit
    def phase1(key, value, count):
        def body(k, v, c):
            sk, sv, valid = _local_sort(k, v, c)
            mask = _boundary(sk, valid)
            return sk, sv, mask, jnp.sum(mask).astype(jnp.int32)[None]
        return jax.shard_map(body, mesh=mesh,
                             in_specs=(spec, spec, spec),
                             out_specs=(spec, spec, spec, spec))(key, value, count)

    return phase1


def grouped_layout(sk, mask, nrows, gcap: int):
    """Shard-local group layout of SORTED rows → (ukey, sizes, voff,
    seg, g).  THE one copy of the convert phase-2 math — shared by the
    eager `_convert_phase2_jit` and the plan/ fuser's fused programs, so
    fused output can never drift from eager."""
    cap = sk.shape[0]
    seg = jnp.cumsum(mask.astype(jnp.int32)) - 1
    in_group = seg >= 0  # rows before the first boundary are invalid
    tgt = jnp.where(mask, seg, gcap)
    # unique keys: first row of each group
    ushape = (gcap,) + sk.shape[1:]
    ukey = jnp.zeros(ushape, sk.dtype).at[tgt].set(sk, mode="drop")
    # group start offsets (shard-local row index)
    voff = jnp.full(gcap, cap, jnp.int32).at[tgt].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")
    # per-group sizes: count rows whose running seg == g
    sizes = jax.ops.segment_sum(
        jnp.where(in_group, 1, 0).astype(jnp.int32),
        jnp.where(in_group, seg, gcap), num_segments=gcap + 1)[:gcap]
    # clamp ON DEVICE: padding rows sorted past the valid count
    # inherit the last group's seg id — the last group must end
    # at nrows, groups past the shard's group count zero out (was a
    # host loop + second round-trip, VERDICT r2 #8)
    g = jnp.sum(mask.astype(jnp.int32))
    gi = jnp.arange(gcap)
    last = jnp.maximum(g - 1, 0)
    sizes = jnp.where(gi < g, sizes, 0)
    sizes = jnp.where((gi == last) & (g > 0),
                      nrows.astype(jnp.int32) - voff[last], sizes)
    return ukey, sizes.astype(jnp.int32), voff, seg, g


def segment_reduce_rows(x, seg, valid, gcap: int, op: str):
    """One output row per segment (sum/max/min with the kernel tier's
    fill values) — shared by `_reduce_build` and the fuser."""
    ids = jnp.where(valid, seg, gcap)
    vmask = _bmask(valid, x)
    if op == "sum":
        return jax.ops.segment_sum(jnp.where(vmask, x, 0), ids,
                                   num_segments=gcap + 1)[:gcap]
    if op == "max":
        return jax.ops.segment_max(jnp.where(vmask, x, _tiny(x.dtype)),
                                   ids, num_segments=gcap + 1)[:gcap]
    if op == "min":
        return jax.ops.segment_min(jnp.where(vmask, x, _huge(x.dtype)),
                                   ids, num_segments=gcap + 1)[:gcap]
    raise ValueError(op)


@functools.lru_cache(maxsize=None)
def _convert_phase2_jit(mesh, gcap: int):
    spec = row_spec(mesh)

    @jax.jit
    def phase2(skey, mask, count):
        def body(sk, m, c):
            ukey, sizes, voff, _seg, _g = grouped_layout(sk, m, c[0],
                                                         gcap)
            return ukey, sizes, voff
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=(spec, spec, spec))(skey, mask,
                                                           count)

    return phase2


def convert_sharded(skv: ShardedKV, counters=None) -> ShardedKMV:
    """Per-shard sort + boundary detection → grouped frame.  The jitted
    phases are cached per (mesh, gcap) — iterative commands convert every
    round and must not re-trace (see shuffle._phase1_jit).  Exactly ONE
    controller round-trip (the ucounts pull that sizes gcap and becomes
    the host gcounts metadata) — per-op parity with the reference's one
    MPI_Allreduce."""
    mesh = skv.mesh
    counts_dev = jax.device_put(skv.counts.astype(np.int32), row_sharding(mesh))
    bump_dispatch()
    skey, svalue, mask, ucounts = _convert_phase1_jit(mesh)(
        skv.key, skv.value, counts_dev)
    SyncStats.bump()
    gcounts = np.asarray(ucounts).astype(np.int32)
    gcap = round_cap(int(gcounts.max())) if gcounts.max() else 8

    bump_dispatch()
    ukey, nvalues, voffsets = _convert_phase2_jit(mesh, gcap)(
        skey, mask, counts_dev)
    return ShardedKMV(skv.mesh, ukey, nvalues, voffsets, svalue,
                      gcounts, skv.counts.copy(), key_decode=skv.key_decode,
                      value_decode=skv.value_decode)


def fused_group_body(k, v, nrecv, gcap: int, out_kind: str, reduce_op,
                     pallas_cfg=None):
    """THE fused convert(+reduce) shard-local body — composed by the
    plan/ fuser's exchange/local/megafused programs over packed valid
    rows.  Two interchangeable engines, byte-identical by construction:

    * sort path (default): sort by key, boundary-detect, then either
      the grouped layout (``out_kind='kmv'``) or a segment reduce to
      one pair per group (``out_kind='kv'``) — the SAME shard-local
      bodies the eager tier jits (`_local_sort`/`_boundary`/
      `grouped_layout`/`segment_reduce_rows`).
    * table path (``pallas_cfg`` set, kv + count/sum only — the fuser
      gates support via ``ops/pallas/group.group_supported``): the
      paged Pallas bucketed-scatter kernel accumulates per-key
      count/sum with NO row sort, then orders only the table slots.

    Returns ``(..., meta)`` where meta = [groups, nrecv, overflow]:
    ``overflow`` is the table path's probe-exhaustion count (always 0
    on the sort path) the megafused executor validates host-side."""
    if pallas_cfg is not None and out_kind == "kv" \
            and reduce_op in ("count", "sum"):
        from ..ops.pallas.group import segment_group_reduce
        ukey, uval, g, overflow = segment_group_reduce(
            k, v, nrecv, gcap, reduce_op, pallas_cfg)
        meta = jnp.stack([g, nrecv.astype(jnp.int32), overflow])
        return ukey, uval, meta
    sk, sv, valid = _local_sort(k, v, nrecv)
    mask = _boundary(sk, valid)
    ukey, sizes, voff, seg, g = grouped_layout(sk, mask, nrecv, gcap)
    meta = jnp.stack([g, nrecv.astype(jnp.int32),
                      jnp.zeros((), jnp.int32)])
    if out_kind == "kmv":
        return ukey, sizes, voff, sv, meta
    if reduce_op == "count":
        return ukey, sizes.astype(jnp.int64), meta
    if reduce_op == "first":
        uval = jnp.zeros((gcap,) + sv.shape[1:], sv.dtype).at[
            jnp.where(mask, seg, gcap)].set(sv, mode="drop")
        return ukey, uval, meta
    return ukey, segment_reduce_rows(sv, seg, valid, gcap, reduce_op), \
        meta


# ---------------------------------------------------------------------------
# segment reductions over a ShardedKMV (the registered-kernel reduce tier)
# ---------------------------------------------------------------------------

def _local_segment_ids(voff, nval, vcap: int):
    """Per-shard value-row → group-id mapping (jittable, shard-local)."""
    starts = jnp.zeros(vcap + 1, jnp.int32).at[voff].add(
        jnp.where(nval > 0, 1, 0).astype(jnp.int32), mode="drop")
    return jnp.cumsum(starts[:vcap]) - 1


def _reduce_jit(mesh, gcap: int, op: str, values_transform):
    """Cache only transform-free reduces (see shuffle._phase1_jit)."""
    if values_transform is not None:
        return _reduce_build(mesh, gcap, op, values_transform)
    return _reduce_cached(mesh, gcap, op, None)


@functools.lru_cache(maxsize=None)
def _reduce_cached(mesh, gcap, op, values_transform):
    return _reduce_build(mesh, gcap, op, values_transform)


def _reduce_build(mesh, gcap: int, op: str, values_transform):
    spec = row_spec(mesh)

    @jax.jit
    def run(ukey, nval, voff, values, vcount):
        def body(uk, nv, vo, vals, vc):
            if op == "count":
                return uk, nv.astype(jnp.int64)
            vcap = vals.shape[0]
            seg = _local_segment_ids(vo, nv, vcap)
            valid = jnp.arange(vcap) < vc
            x = vals if values_transform is None else values_transform(vals)
            return uk, segment_reduce_rows(x, seg, valid, gcap, op)
        return jax.shard_map(body, mesh=mesh,
                             in_specs=(spec, spec, spec, spec, spec),
                             out_specs=(spec, spec))(ukey, nval, voff, values,
                                                     vcount)

    return run


def reduce_sharded(kmv: ShardedKMV, op: str = "sum",
                   values_transform: Callable = None) -> ShardedKV:
    """Vectorised reduce: one output pair per group, computed with XLA
    segment ops per shard (count/sum/max/min).  Cached per (mesh, gcap,
    op, transform identity)."""
    if kmv.value_decode is not None and op != "count":
        raise ValueError(
            f"reduce_sharded({op!r}): values are interned byte/object "
            f"ids — arithmetic on them is meaningless; decode to host "
            f"first (only 'count' is value-agnostic)")
    run = _reduce_jit(kmv.mesh, kmv.gcap, op, values_transform)
    vcounts_dev = jax.device_put(kmv.vcounts.astype(np.int32),
                                 row_sharding(kmv.mesh))
    bump_dispatch()
    ukey, out = run(kmv.ukey, kmv.nvalues, kmv.voffsets, kmv.values, vcounts_dev)
    return ShardedKV(kmv.mesh, ukey, out, kmv.gcounts.copy(),
                     key_decode=kmv.key_decode)


def _bmask(valid, x):
    return valid if x.ndim == 1 else valid[:, None]


def _tiny(dtype):
    v = (jnp.finfo(dtype).min if jnp.issubdtype(dtype, jnp.floating)
         else jnp.iinfo(dtype).min)
    return jnp.array(v, dtype=dtype)  # typed scalar: u64 max overflows weak int


def _huge(dtype):
    v = (jnp.finfo(dtype).max if jnp.issubdtype(dtype, jnp.floating)
         else jnp.iinfo(dtype).max)
    return jnp.array(v, dtype=dtype)


@functools.lru_cache(maxsize=None)
def _first_jit(mesh):
    spec = row_spec(mesh)

    @jax.jit
    def run(ukey, voff, values):
        def body(uk, vo, vals):
            idx = jnp.minimum(vo, vals.shape[0] - 1)
            return uk, jnp.take(vals, idx, axis=0)
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=(spec, spec))(ukey, voff, values)

    return run


def first_sharded(kmv: ShardedKMV) -> ShardedKV:
    """One output pair per group with the group's FIRST value (dedupe/cull)."""
    bump_dispatch()
    uk, v = _first_jit(kmv.mesh)(kmv.ukey, kmv.voffsets, kmv.values)
    return ShardedKV(kmv.mesh, uk, v, kmv.gcounts.copy(),
                     key_decode=kmv.key_decode,
                     value_decode=kmv.value_decode)


@functools.lru_cache(maxsize=None)
def _sortmv_jit(mesh, descending: bool):
    spec = row_spec(mesh)

    @jax.jit
    def run(voff, nval, values, vcount):
        def body(vo, nv, vals, vc):
            vcap = vals.shape[0]
            seg = _local_segment_ids(vo, nv, vcap)
            valid = jnp.arange(vcap) < vc
            v = vals if vals.ndim == 1 else vals[:, 0]
            keyv = _desc_key(v) if descending else v
            order = jnp.lexsort((keyv, seg, ~valid))
            return jnp.take(vals, order, axis=0)
        return jax.shard_map(body, mesh=mesh, in_specs=(spec,) * 4,
                             out_specs=spec)(voff, nval, values, vcount)

    return run


def sort_multivalues_sharded(kmv: ShardedKMV,
                             descending: bool = False) -> ShardedKMV:
    """Sort values within each group, per shard (reference
    src/mapreduce.cpp:2210-2352).  Stable lexsort by (validity, group,
    value) keeps every group in its original [voffset, voffset+nvalue)
    region, so offsets/sizes are unchanged."""
    vcounts_dev = jax.device_put(kmv.vcounts.astype(np.int32),
                                 row_sharding(kmv.mesh))
    bump_dispatch()
    values = _sortmv_jit(kmv.mesh, descending)(
        kmv.voffsets, kmv.nvalues, kmv.values, vcounts_dev)
    return ShardedKMV(kmv.mesh, kmv.ukey, kmv.nvalues, kmv.voffsets, values,
                      kmv.gcounts.copy(), kmv.vcounts.copy(),
                      key_decode=kmv.key_decode,
                      value_decode=kmv.value_decode)


def _desc_key(v):
    if jnp.issubdtype(v.dtype, jnp.unsignedinteger):
        return ~v  # bitwise complement reverses unsigned order
    return -v


# ---------------------------------------------------------------------------
# per-shard sort (reference sort_keys/sort_values are rank-local)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _sort_jit(mesh, by: str, descending: bool):
    spec = row_spec(mesh)

    @jax.jit
    def run(key, value, count):
        def body(k, v, c):
            col = k if by == "key" else v
            cap = col.shape[0]
            valid = jnp.arange(cap) < c
            order = jnp.lexsort(_sort_key_tuple(col, valid))
            if descending:
                r = jnp.arange(cap)
                pos = jnp.where(r < c, c - 1 - r, r)
                inv = jnp.zeros(cap, order.dtype).at[pos].set(r)
                order = jnp.take(order, inv)
            return jnp.take(k, order, axis=0), jnp.take(v, order, axis=0)
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=(spec, spec))(key, value, count)

    return run


def sort_sharded(skv: ShardedKV, by: str = "key",
                 descending: bool = False) -> ShardedKV:
    counts_dev = jax.device_put(skv.counts.astype(np.int32),
                                row_sharding(skv.mesh))
    bump_dispatch()
    k, v = _sort_jit(skv.mesh, by, descending)(skv.key, skv.value, counts_dev)
    return ShardedKV(skv.mesh, k, v, skv.counts.copy(),
                     key_decode=skv.key_decode,
                     value_decode=skv.value_decode)


# ---------------------------------------------------------------------------
# device sort of INTERNED byte/object columns by rank surrogate
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _sort_interned_jit(mesh, nrows: int, by: str, descending: bool):
    shard = NamedSharding(mesh, row_spec(mesh))
    nprocs = mesh_axis_size(mesh)
    cap = nrows // nprocs

    @functools.partial(jax.jit, out_shardings=(shard, shard))
    def run(key, value, counts, ids_by_id, rank_of):
        col = key if by == "key" else value
        idx = jnp.arange(nrows)
        valid = (idx % cap) < counts[idx // cap]
        pos = jnp.clip(jnp.searchsorted(ids_by_id, col), 0,
                       ids_by_id.shape[0] - 1)
        rank = jnp.take(rank_of, pos)
        order = jnp.lexsort((rank, ~valid))   # valid first, GLOBAL order
        if descending:
            total = jnp.sum(counts)
            r = jnp.arange(nrows)
            ppos = jnp.where(r < total, total - 1 - r, r)
            inv = jnp.zeros(nrows, order.dtype).at[ppos].set(r,
                                                             mode="drop")
            order = jnp.take(order, inv)
        return jnp.take(key, order, axis=0), jnp.take(value, order, axis=0)

    return run


def sort_interned_sharded(skv: ShardedKV, by: str = "key",
                          descending: bool = False) -> ShardedKV:
    """GLOBAL sort of an INTERNED byte/object column without pulling the
    dataset to host (VERDICT r2 #7): the id→rank permutation builds once
    from the (small, controller-side) decode table — ranked by the
    decoded bytes / pickles, the host tiers' comparison order — and one
    jitted lexsort orders the whole mesh dataset by the rank surrogate
    (GSPMD inserts the collectives).  Matches the host path's global
    lexicographic output; valid rows pack to the front shards."""
    table = skv.key_decode if by == "key" else skv.value_decode
    cached = getattr(table, "_rank_cache", None)
    if cached is not None and cached[0] == len(table):
        _, ids_by_id, rank_of = cached
    else:
        from ..ops.sort import argsort_column
        ids = np.fromiter(table.keys(), np.uint64, len(table))
        by_bytes = argsort_column(_decode_col(table, ids))
        rank = np.empty(len(ids), np.int64)
        rank[by_bytes] = np.arange(len(ids))
        by_id = np.argsort(ids)
        # pad the replicated lookup to a pow2 so recompiles stay bounded
        m = len(ids)
        mcap = round_cap(m)
        ids_by_id = np.full(mcap, np.uint64(0xFFFFFFFFFFFFFFFF),
                            np.uint64)
        rank_of = np.full(mcap, m, np.int64)
        ids_by_id[:m] = ids[by_id]
        rank_of[:m] = rank[by_id]
        # memoised on the table itself (rebuilt only if it grows —
        # iterative sorts over an unchanged dictionary pay once)
        table._rank_cache = (len(table), ids_by_id, rank_of)
    rep = NamedSharding(skv.mesh, P())
    nrows = skv.key.shape[0]
    bump_dispatch()
    k, v = _sort_interned_jit(skv.mesh, nrows, by, descending)(
        skv.key, skv.value, jnp.asarray(skv.counts.astype(np.int32)),
        jax.device_put(ids_by_id, rep), jax.device_put(rank_of, rep))
    # valid rows are globally packed to the front: first shards full
    total = int(skv.counts.sum())
    cap = nrows // mesh_axis_size(skv.mesh)
    new_counts = np.clip(total - np.arange(len(skv.counts)) * cap,
                         0, cap).astype(np.int32)
    return ShardedKV(skv.mesh, k, v, new_counts,
                     key_decode=skv.key_decode,
                     value_decode=skv.value_decode)
