"""Per-shard mesh ingestion for the generic file map path (VERDICT r4 #4).

The reference's map stage is flat under weak scaling because every MPI
rank reads its own files on its own node (``src/mapreduce.cpp:1102-1225``;
chapter Fig. 4).  Round 4 built that shape for InvertedIndex only
(``apps/invertedindex._map_corpus_mesh``); this module generalises it to
``map_files`` / ``map_file_char`` / ``map_file_str`` — wordfreq and every
file-driven OINK command — on a mesh backend:

* the file list splits into P CONTIGUOUS byte-balanced slices (the
  reference's consecutive per-proc file ranges);
* every task's callback runs into a private sink (a thread pool overlaps
  the file reads — CPython releases the GIL for I/O and numpy parsing);
* each shard's sinks assemble into ONE host frame whose rows go to that
  shard's device — a ``ShardedKV`` is born at map time, rows already
  living on the shard that read them;
* byte/object keys and values intern into DEST-SHARDED decode tables
  (``core.column.ShardTables``): each (id, bytes) entry lives in the
  table of the shard the aggregate will route the id to, so the exchange
  moves u64 ids and shard d's output later decodes from table d alone —
  no controller-global dict (the reference shuffles raw bytes fully
  distributed, ``src/mapreduce.cpp:453-473``).

Anything unshardable (mixed dtypes across shards, frames added via
``add_frame``, out-of-core datasets) falls back to replaying the recorded
sinks into the host KV — bit-identical to the pre-r5 behavior, and the
callbacks never run twice.
"""

from __future__ import annotations

import os
from typing import Callable, List, Sequence

import jax
import numpy as np

from ..core.column import (BytesColumn, DenseColumn, ObjectColumn,
                           ShardTables)
from ..core.frame import KVFrame

# per-message cap for generic ingest H2D, in BYTES (the r4 lesson: the
# axon tunnel fails on large single messages — apps/invertedindex caps
# its corpus transfers the same way)
H2D_CHUNK_BYTES = 32 << 20


class Unshardable(Exception):
    """Raised when per-shard frames cannot form one mesh dataset; the
    caller replays the sinks into the host KV instead."""


def balance_by_bytes(names: Sequence[str], P: int):
    """Split files into P contiguous chunks of ~equal bytes (the
    reference's consecutive per-proc file ranges).  Returns
    ``[(first_index, files, sizes)] * P`` — the ONE balancing policy;
    apps/invertedindex._balance_files delegates here (r5 review: the
    two ingest paths must not diverge)."""
    sizes = np.array([os.path.getsize(f) for f in names], np.int64)
    total = max(int(sizes.sum()), 1)
    mid = np.cumsum(sizes) - sizes // 2
    assign = np.minimum((mid * P) // total, P - 1)  # non-decreasing
    out = []
    i = 0
    for p in range(P):
        j = i
        while j < len(names) and assign[j] == p:
            j += 1
        out.append((i, list(names[i:j]), sizes[i:j]))
        i = j
    return out


def run_sinks(payloads, call: Callable, threaded: bool = True,
              base: int = 0, pool=None, onfault: str = "fail",
              shard=None):
    """Run ``call(base+i, payload, sink)`` for every payload into
    private _TaskSink buffers; returns the sinks in task order.
    Threaded by default (the per-rank parallel read the reference gets
    from MPI); assembly order is by task index either way, so the
    result is deterministic regardless of scheduling.

    Every task runs through the ft/ ingest policy (``ft.retry
    .ingest_task``): fault points, bounded retries into a fresh private
    buffer per attempt (a retried task can never duplicate pairs or
    reorder — sinks are positional), OSError→MRError wrapping naming
    file/shard/task, and quarantine-skip under ``onfault="skip"``.

    ``pool``: a shared ThreadPoolExecutor (``MapReduce._ingest_pool`` —
    one pool per MapReduce instead of a fresh executor per call); when
    None a private pool capped at ``min(nworkers, len(payloads))`` is
    built and torn down here (standalone callers)."""
    import contextlib
    from concurrent.futures import ThreadPoolExecutor
    from ..core.mapreduce import _TaskSink
    from ..ft.retry import ingest_task
    from ..obs import get_tracer
    sinks = [_TaskSink() for _ in payloads]
    with get_tracer().span("ingest.read", cat="ingest",
                           ntasks=len(payloads), threaded=threaded):
        if not threaded or len(payloads) <= 1:
            for i, p in enumerate(payloads):
                ingest_task(call, base + i, p, sinks[i],
                            onfault=onfault, shard=shard)
            return sinks
        # one submit/drain loop for both executors: a shared pool stays
        # open (nullcontext), a private one tears down here
        if pool is not None:
            ctx = contextlib.nullcontext(pool)
        else:
            nworkers = max(1, min((os.cpu_count() or 4), 16,
                                  len(payloads)))
            ctx = ThreadPoolExecutor(nworkers)
        # pool workers run the SUBMITTING request's trace context
        # (obs/context.py): their ft fault-point spans and any counter
        # traffic charge the request, and the pool is shared across
        # sessions so each task must carry its own binding
        from ..obs.context import bind as _ctx_bind
        task = _ctx_bind(ingest_task)
        with ctx as ex:
            futs = [ex.submit(task, call, base + i, p, sinks[i],
                              onfault=onfault, shard=shard)
                    for i, p in enumerate(payloads)]
            for f in futs:
                f.result()   # propagate callback exceptions
    return sinks


def _sink_frame(sinks) -> KVFrame:
    """One host KVFrame from a shard's sinks (task order).  add/add_batch
    traffic only — add_frame/add_kv payloads (pre-built or sharded
    frames) don't belong to a file-ingest callback and fall back."""
    from ..core.dataset import _coerce_rows, _merge_frames, as_column
    frames = []
    for s in sinks:
        buf_k: list = []
        buf_v: list = []
        for name, *args in s._calls:
            if name == "add":
                buf_k.append(args[0])
                buf_v.append(args[1])
                continue
            if buf_k:
                frames.append(KVFrame(_coerce_rows(buf_k),
                                      _coerce_rows(buf_v)))
                buf_k, buf_v = [], []
            if name != "add_batch":
                raise Unshardable(name)
            fr = KVFrame(as_column(args[0]), as_column(args[1]))
            if len(fr):
                frames.append(fr)
        if buf_k:
            frames.append(KVFrame(_coerce_rows(buf_k), _coerce_rows(buf_v)))
    if not frames:
        from ..core.frame import empty_kv
        return empty_kv()
    try:
        return _merge_frames(frames)
    except TypeError as e:       # mixed byte/numeric rows across tasks
        raise Unshardable(str(e))


def _intern_side(cols, P: int):
    """Intern one side's byte/object columns into shared dest-sharded
    tables.  All-or-nothing: one shard emitting bytes while another
    emits numbers is two incompatible key spaces (Unshardable → host
    fallback).  Returns (new columns, tables-or-None)."""
    stringy = [isinstance(c, (BytesColumn, ObjectColumn))
               for c in cols if len(c)]
    if not any(stringy):
        return cols, None
    if not all(stringy):
        raise Unshardable("mixed byte and numeric rows across shards")
    kind = ("object" if any(isinstance(c, ObjectColumn) for c in cols)
            else "bytes")
    tables = ShardTables(P, kind=kind)
    out = []
    for c in cols:
        if kind == "object" and isinstance(c, BytesColumn):
            # one shard emitted objects: EVERY shard's rows must hash in
            # the pickle domain, or the same logical bytes key would get
            # two ids (host concat() promotes the same way — r5 review)
            c = ObjectColumn(c.data)
        if isinstance(c, (BytesColumn, ObjectColumn)):
            out.append(c.intern_sharded(tables))
        elif len(c):
            raise Unshardable("mixed byte and numeric rows across shards")
        else:
            out.append(DenseColumn(np.zeros(0, np.uint64)))
    return out, tables


def _common_spec(arrs: List[np.ndarray]):
    """(dtype, row-shape) every shard must share; empty shards defer."""
    spec = None
    for a in arrs:
        if a.shape[0] == 0:
            continue
        s = (a.dtype, a.shape[1:])
        if spec is None:
            spec = s
        elif spec != s:
            raise Unshardable(f"shard dtype/shape mismatch: {spec} vs {s}")
    return spec or (np.dtype(np.uint8), ())


def _put_blocks(blocks: List[np.ndarray], cap: int, mesh):
    """Device-put per-shard row blocks [cap,...] each onto ITS device in
    bounded messages (mesh.h2d_chunk_bytes — honors MR_H2D_CHUNK_WORDS
    like every other chunked-transfer site); assemble the row-sharded
    global [P*cap,...]."""
    from ..obs import get_tracer
    from .mesh import h2d_chunk_bytes, row_sharding
    P = len(blocks)
    sharding = row_sharding(mesh)
    shape = (P * cap,) + blocks[0].shape[1:]
    dmap = sharding.addressable_devices_indices_map(shape)
    budget = h2d_chunk_bytes(H2D_CHUNK_BYTES)
    with get_tracer().span("ingest.h2d", cat="ingest", shards=P,
                           bytes=int(sum(b.nbytes for b in blocks))):
        shards = []
        for dev, idx in dmap.items():
            p = (idx[0].start or 0) // cap
            host = np.ascontiguousarray(blocks[p])
            rowbytes = max(1, int(host.nbytes // max(1, cap)))
            chunk = max(1, budget // rowbytes)
            if cap > chunk:
                import jax.numpy as jnp
                parts = [jax.device_put(host[o:o + chunk], dev)
                         for o in range(0, cap, chunk)]
                shards.append(jnp.concatenate(parts))
            else:
                shards.append(jax.device_put(host, dev))
        return jax.make_array_from_single_device_arrays(shape, sharding,
                                                        shards)


def build_sharded(frames: List[KVFrame], mesh):
    """Per-shard host frames → one ShardedKV, interning byte/object
    columns into dest-sharded tables.  Rows normally stay on the shard
    whose file slice produced them — EXCEPT a severely lopsided ingest
    (max shard > 2× the even share, e.g. one file on an 8-shard mesh),
    which re-splits rows evenly: the padded cap tracks the fullest
    shard, so keeping the skew would move ~P× the real rows through
    every downstream collective.  Raises Unshardable when the frames
    cannot agree."""
    from .sharded import ShardedKV, round_cap, _pad_rows
    P = len(frames)
    kcols, ktables = _intern_side([f.key for f in frames], P)
    vcols, vtables = _intern_side([f.value for f in frames], P)
    karrs = [np.asarray(c.to_host().data) for c in kcols]
    varrs = [np.asarray(c.to_host().data) for c in vcols]
    kdt, kshape = _common_spec(karrs)
    vdt, vshape = _common_spec(varrs)
    counts = np.array([a.shape[0] for a in karrs], np.int32)
    total = int(counts.sum())
    if P > 1 and total and int(counts.max()) > 2 * (-(-total // P)):
        # lopsided ingest (fewer files than shards — e.g. one edge file
        # on an 8-shard mesh): the padded cap tracks the FULLEST shard,
        # so every downstream collective would move ~P x the real rows.
        # Re-split evenly — free on a single controller (the bytes are
        # already in host RAM), and order-preserving.  A multi-host
        # runtime would keep locality instead; with one file only one
        # host has the data anyway (r5 P=8 soak regression).
        kall = np.concatenate([a.astype(kdt, copy=False)
                               .reshape((-1,) + kshape) for a in karrs])
        vall = np.concatenate([a.astype(vdt, copy=False)
                               .reshape((-1,) + vshape) for a in varrs])
        per = -(-total // P)
        starts = np.minimum(np.arange(P) * per, total)
        ends = np.minimum(starts + per, total)
        karrs = [kall[s:e] for s, e in zip(starts, ends)]
        varrs = [vall[s:e] for s, e in zip(starts, ends)]
        counts = (ends - starts).astype(np.int32)
    cap = round_cap(int(counts.max()) if counts.max() else 0)
    kb = [_pad_rows(a.astype(kdt, copy=False).reshape((-1,) + kshape), cap)
          for a in karrs]
    vb = [_pad_rows(a.astype(vdt, copy=False).reshape((-1,) + vshape), cap)
          for a in varrs]
    key = _put_blocks(kb, cap, mesh)
    value = _put_blocks(vb, cap, mesh)
    return ShardedKV(mesh, key, value, counts,
                     key_decode=ktables, value_decode=vtables)



def _balanced_shards(names: Sequence[str], P: int,
                     onfault: str) -> List[List[str]]:
    """balance_by_bytes under the ft/ discovery policy — ONE copy for
    both mesh map paths: a file that vanished between findfiles and
    the byte balance gets the SAME disposition a task-time failure
    would (MRError naming it, or quarantine-drop + rebalance under
    onfault="skip"), so which stage notices a bad input never decides
    whether the run survives it."""
    from ..ft.retry import quarantine_or_raise
    names = list(names)
    while True:
        try:
            return [files for _, files, _ in balance_by_bytes(names, P)]
        except OSError as e:
            bad = getattr(e, "filename", None)
            if bad in names:
                quarantine_or_raise(e, bad, onfault)
                names.remove(bad)
            else:
                quarantine_or_raise(e, bad, "fail")


def _shard_sink_stream(shards_payloads, call: Callable, threaded: bool,
                       pool, onfault: str = "fail"):
    """Generator of per-shard sink lists: ``run_sinks`` over each
    shard's payloads in turn, with GLOBAL task numbering (cumulative
    base).  This is the producer half the prefetch pipeline runs in its
    background thread — read + tokenize shard N+1 while the consumer
    assembles/interns shard N's frame.  A retry inside ``run_sinks``
    happens WITHIN a task slot, so the producer can never reorder
    frames (the chaos golden contract)."""
    itask = 0
    for sidx, payloads in enumerate(shards_payloads):
        sinks = run_sinks(payloads, call, threaded=threaded, base=itask,
                          pool=pool, onfault=onfault, shard=sidx)
        itask += len(payloads)
        yield sinks


def _pooled_file_sink_stream(shards, call: Callable, pool,
                             onfault: str = "fail"):
    """mapstyle-2 map_files producer: EVERY file's task submits to the
    shared pool up front (the full cross-file parallelism the pre-exec
    single run_sinks had — a P-shard mesh with ~1 file per shard must
    not serialize its reads), then per-shard sink groups yield in task
    order as their futures complete, so the consumer assembles shard N
    while shards > N are still reading."""
    from ..core.mapreduce import _TaskSink
    from ..ft.retry import ingest_task
    from ..obs import get_tracer
    names = [f for files in shards for f in files]
    shard_of = [s for s, files in enumerate(shards) for _ in files]
    sinks = [_TaskSink() for _ in names]
    from ..obs.context import bind as _ctx_bind
    task = _ctx_bind(ingest_task)   # shared pool: each task carries the
    #                                 submitting request's trace context
    with get_tracer().span("ingest.read", cat="ingest",
                           ntasks=len(names), threaded=True):
        futs = [pool.submit(task, call, i, name, sinks[i],
                            onfault=onfault, shard=shard_of[i])
                for i, name in enumerate(names)]
        i = 0
        for files in shards:
            for f in futs[i:i + len(files)]:
                f.result()   # propagate callback exceptions, task order
            yield sinks[i:i + len(files)]
            i += len(files)


def mesh_map_files(mr, kv, names: Sequence[str], call: Callable) -> dict:
    """The mesh map_files path: per-shard ingest + dest-sharded intern.
    Returns the ingest stats record ({"mode": "mesh"|"host", ...});
    either way every callback has run exactly once and its pairs are in
    ``kv``.

    Shards pipeline through the exec/ prefetch: the reader/tokenizer
    produce shard N+1's sinks while shard N's frame assembles (task ids
    and replay order stay global file order — output is bit-identical
    to the unprefetched path)."""
    from ..exec import prefetch_iter
    from .mesh import mesh_axis_size
    P = mesh_axis_size(mr.backend.mesh)
    onfault = mr.settings.onfault
    shards = _balanced_shards(names, P, onfault)
    stats = {"mode": "mesh", "shards": P,
             "files_per_shard": [len(s) for s in shards]}
    threaded = mr.settings.mapstyle == 2
    if threaded:
        # all files in flight on the shared pool at once (cross-file
        # parallelism), groups stream out in shard order
        stream = _pooled_file_sink_stream(shards, call,
                                          mr._ingest_pool(),
                                          onfault=onfault)
    else:
        stream = _shard_sink_stream(shards, call, False, None,
                                    onfault=onfault)
    frames: List[KVFrame] = []
    done_sinks: List[list] = []   # per-shard sinks kept for fallback
    failed = None
    for sinks in prefetch_iter(stream, path="ingest.files"):
        if failed is not None:
            for s in sinks:
                s.replay(kv)
            continue
        try:
            frames.append(_sink_frame(sinks))
            done_sinks.append(sinks)
        except Unshardable as e:
            failed = str(e)[:200]
            for ss in done_sinks:
                for s in ss:
                    s.replay(kv)
            for s in sinks:
                s.replay(kv)
            frames, done_sinks = [], []
    if failed is None:
        try:
            skv = build_sharded(frames, mr.backend.mesh)
        except Unshardable as e:
            failed = str(e)[:200]
            for ss in done_sinks:
                for s in ss:
                    s.replay(kv)
    if failed is not None:
        stats["mode"] = "host"
        stats["fallback"] = failed
        return stats
    kv.add_frame(skv)
    stats["rows_per_shard"] = skv.counts.tolist()
    return stats


def mesh_map_chunks(mr, kv, names: Sequence[str], per_file: int, sep: bytes,
                    delta: int, call: Callable) -> dict:
    """Mesh path for map_file_char/str: files balance across shards, each
    file splits into its ~per_file chunks (utils.io.file_chunks — same
    chunking as the host path, so callbacks see identical payloads and
    task ids stay global file-then-chunk order).

    Shards process ONE AT A TIME: a shard's raw chunk payloads are
    generated, consumed into its frame, and released before the next
    shard reads — peak raw-bytes residency is ~one shard's slice per
    in-flight pipeline stage, not the whole corpus (the host path's
    lazy-window property, kept; the exec/ prefetch pipeline holds at
    most MRTPU_PREFETCH extra shards' tokenized sinks)."""
    from ..exec import prefetch_iter
    from ..ft.retry import ingest_read
    from ..utils.io import file_chunks
    from .mesh import mesh_axis_size
    P = mesh_axis_size(mr.backend.mesh)
    onfault = mr.settings.onfault
    shards = _balanced_shards(names, P, onfault)
    stats = {"mode": "mesh", "shards": P,
             "files_per_shard": [len(s) for s in shards],
             "chunks_per_shard": []}
    threaded = mr.settings.mapstyle == 2
    pool = mr._ingest_pool() if threaded else None
    counts = {"ntasks": 0}

    def shard_payloads():
        # producer side: the raw chunk bytes of one shard materialize,
        # tokenize through the callbacks, and release before the next
        # shard reads (run_sinks happens in _shard_sink_stream).  Each
        # file reads under the ft/ ingest.read policy: retry budget,
        # MRError naming the file, quarantine-skip under onfault=skip
        for sidx, chunk_files in enumerate(shards):
            payloads = []
            for fname in chunk_files:
                chunks = ingest_read(
                    lambda f=fname: list(file_chunks(f, per_file, sep,
                                                     delta)),
                    file=fname, onfault=onfault, shard=sidx)
                if chunks is not None:
                    payloads.extend(chunks)
            stats["chunks_per_shard"].append(len(payloads))
            counts["ntasks"] += len(payloads)
            yield payloads

    frames: List[KVFrame] = []
    done_sinks: List[list] = []   # per-shard sinks kept for fallback
    failed = None
    for sinks in prefetch_iter(
            _shard_sink_stream(shard_payloads(), call, threaded, pool,
                               onfault=onfault),
            path="ingest.chunks"):
        if failed is not None:
            for s in sinks:
                s.replay(kv)
            continue
        try:
            frames.append(_sink_frame(sinks))
            done_sinks.append(sinks)
        except Unshardable as e:
            failed = str(e)[:200]
            for ss in done_sinks:
                for s in ss:
                    s.replay(kv)
            for s in sinks:
                s.replay(kv)
            frames, done_sinks = [], []
    stats["ntasks"] = counts["ntasks"]
    if failed is None:
        try:
            skv = build_sharded(frames, mr.backend.mesh)
        except Unshardable as e:
            failed = str(e)[:200]
            for ss in done_sinks:
                for s in ss:
                    s.replay(kv)
    if failed is not None:
        stats["mode"] = "host"
        stats["fallback"] = failed
        return stats
    kv.add_frame(skv)
    stats["rows_per_shard"] = skv.counts.tolist()
    return stats
