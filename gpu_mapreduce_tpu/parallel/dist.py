"""Multi-process data plane — the mesh finally leaves one process.

MR-MPI is multi-node by construction (every op ends in MPI collectives
across OS processes); until now this reproduction ran its collectives on
a fake mesh inside one process.  This module is the process-spanning
runtime: ``jax.distributed.initialize`` coordinator bootstrap over N
local CPU processes (gloo cross-process collectives + forced
host-platform device counts emulate multi-host — the same
multi-controller code path a TPU pod uses), over which the existing
shuffle exchange, wire codec and range-exchange programs run unchanged
as collective programs.

And then it survives its peers.  The moment the mesh spans processes, a
SIGKILLed or hung rank turns every ``all_to_all`` into an unbounded
stall on every survivor — a failure class no retry budget can see,
because nothing *fails*.  Three mechanisms convert that stall into a
bounded, recoverable error:

* **heartbeats** — every rank's :class:`Heartbeat` thread renews an
  fsync'd lease file under ``<rundir>/hb/`` (the serve/fleet.py lease
  idiom: tmp + fsync + rename + dir fsync, expiry + skew margin).  A
  rank whose lease passes expiry + ``MRTPU_DIST_SKEW`` is presumed
  dead.
* **collective watchdog** — :meth:`DistRuntime.guard` wraps every host
  sync point (phase-1 count pull, exchange, reshard, checkpoint
  barrier): the blocking call runs on a worker thread while the guard
  polls peer leases, the rank's own fence, and a hard deadline
  (``MRTPU_DIST_SYNC_TIMEOUT`` — the only way to catch a peer that is
  *hung but still heartbeating*).  A dead peer surfaces as
  :class:`PeerLostError` on every survivor within
  ``lease + skew + poll`` seconds, never an infinite stall.
* **fencing** — survivors (or the launcher) create
  ``<rundir>/hb/rank<k>.fence.json`` with ``O_CREAT|O_EXCL`` before the
  shrunk generation resumes.  A fenced rank that was merely hung and
  wakes up later discovers the fence at its next heartbeat or sync
  point (:class:`RankFencedError`) and exits without touching output —
  the same epoch-fence discipline serve/fleet.py applies to journal
  claims, so a zombie double-writing a survivor's output is
  structurally impossible, not just unlikely.

Shrink-and-resume is launcher-driven (``scripts/mrlaunch.py``): the
coordinator of a failed generation cannot be re-used (survivors' gloo
contexts hold dead TCP peers), so survivors exit with
:data:`EXIT_PEER_LOST`, and the launcher fences the dead rank, picks
:func:`shrink_width` (largest power of two ≤ survivors — the same
power-of-two mesh rule the rest of the tree compiles for), and
relaunches a fresh generation that ``ft.resume``-style restores from
the last durable checkpoint manifest.  Chaos is deterministic via
ft/inject's process-level kinds (``peer_kill``/``peer_hang`` +
``rank=`` selector) at the ``dist.*`` sites this module probes.

Single-process behavior is untouched: with no ``MRTPU_DIST_WORLD`` the
module never initializes anything, :func:`active` is None, and
:func:`host_pull`/:func:`guard_call` are direct passthroughs.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional

from ..core.runtime import MRError
from ..utils.env import env_knob, env_str

# launcher/worker exit protocol: a survivor that detected a dead peer
# exits PEER_LOST (the launcher shrinks); a fenced zombie exits FENCED
# (the launcher ignores it — its rank was already failed over)
EXIT_PEER_LOST = 75
EXIT_FENCED = 76

_HB_DIR = "hb-g"       # per-GENERATION heartbeat/fence dir: a fence
#                        for gen g's rank 2 must never fence the next
#                        generation's (re-used) rank number
_HB_SUF = ".hb.json"
_FENCE_SUF = ".fence.json"
_EXIT_SUF = ".exit.json"


class PeerLostError(MRError):
    """A collective sync point detected dead/hung peer rank(s): the
    bounded-time replacement for an infinite ``all_to_all`` stall."""

    def __init__(self, site: str, dead: List[int], reason: str = ""):
        self.site = site
        self.dead = list(dead)
        super().__init__(
            f"peer rank(s) {self.dead or '?'} lost at sync point "
            f"{site!r}{': ' + reason if reason else ''}")


class RankFencedError(MRError):
    """THIS rank was fenced (a shrunk generation took over its work):
    it must stop without writing output — the anti-zombie guard."""

    def __init__(self, rank: int, site: str = ""):
        self.rank = rank
        super().__init__(
            f"rank {rank} is fenced (superseded by a shrunk generation)"
            + (f" at {site!r}" if site else ""))


def shrink_width(survivors: int) -> int:
    """Mesh width for the next generation: the largest power of two
    ≤ ``survivors`` (power-of-two meshes are what every capacity /
    round_cap policy in the tree compiles for; running 3-wide would
    trade one dead rank for a fleet of fresh compiles)."""
    if survivors < 1:
        return 0
    w = 1
    while w * 2 <= survivors:
        w *= 2
    return w


# ---------------------------------------------------------------------------
# heartbeat + fence files (the fleet lease idiom on the data plane)
# ---------------------------------------------------------------------------

def hb_dir(rundir: str, gen: int = 0) -> str:
    return os.path.join(rundir, f"{_HB_DIR}{gen}")


def hb_path(rundir: str, rank: int, gen: int = 0) -> str:
    return os.path.join(hb_dir(rundir, gen), f"rank{rank}{_HB_SUF}")


def fence_path(rundir: str, rank: int, gen: int = 0) -> str:
    return os.path.join(hb_dir(rundir, gen), f"rank{rank}{_FENCE_SUF}")


def exit_path(rundir: str, rank: int, gen: int = 0) -> str:
    return os.path.join(hb_dir(rundir, gen), f"rank{rank}{_EXIT_SUF}")


def write_beat(rundir: str, rank: int, lease_s: float, gen: int = 0,
               state: str = "ready", seq: int = 0) -> None:
    """One durable heartbeat: the lease every peer's death verdict (and
    the launcher's recovery clock) reads."""
    from ..utils.fsio import atomic_write_json
    os.makedirs(hb_dir(rundir, gen), exist_ok=True)
    now = time.time()
    atomic_write_json(hb_path(rundir, rank, gen), {
        "rank": rank, "pid": os.getpid(), "gen": gen, "state": state,
        "seq": seq, "ts": now, "ttl": lease_s, "expires": now + lease_s})


def read_beat(rundir: str, rank: int, gen: int = 0) -> Optional[dict]:
    from ..utils.fsio import read_json
    return read_json(hb_path(rundir, rank, gen))


def write_exit_report(rundir: str, rank: int, gen: int, code: str,
                      dead: Optional[List[int]] = None,
                      site: str = "") -> None:
    """A survivor's last word before exiting: which peers it observed
    dead at which sync point — the launcher unions these reports with
    child exit codes to name the dead rank(s) of a generation."""
    from ..utils.fsio import atomic_write_json
    try:
        atomic_write_json(exit_path(rundir, rank, gen), {
            "rank": rank, "gen": gen, "code": code,
            "dead": list(dead or []), "site": site, "ts": time.time()})
    except OSError:
        pass                 # best-effort: the exit code still speaks


def beat_expired(beat: Optional[dict], skew_s: float,
                 now: Optional[float] = None) -> bool:
    """Dead once past ``expires + skew`` — clock disagreement under the
    margin can never fail over a live rank; an unreadable/missing beat
    protects nobody and counts as expired."""
    if beat is None:
        return True
    now = time.time() if now is None else now
    try:
        return now > float(beat["expires"]) + skew_s
    except (KeyError, TypeError, ValueError):
        return True


def fence_rank(rundir: str, rank: int, by: str, gen: int = 0) -> bool:
    """Fence ``rank``: O_CREAT|O_EXCL + dir fsync, exactly like a fleet
    journal claim — the filesystem arbitrates concurrent fencers, and
    the fence's existence (not its content) is the verdict a zombie
    reads.  Returns whether WE created it (False: already fenced —
    equally final, not an error)."""
    import json as _json

    from ..utils.fsio import fsync_dir
    os.makedirs(hb_dir(rundir, gen), exist_ok=True)
    path = fence_path(rundir, rank, gen)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    try:
        os.write(fd, _json.dumps(
            {"rank": rank, "by": by, "gen": gen,
             "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                  time.gmtime())}).encode())
        os.fsync(fd)
    finally:
        os.close(fd)
    fsync_dir(hb_dir(rundir, gen))
    return True


def is_fenced(rundir: str, rank: int, gen: int = 0) -> bool:
    return os.path.exists(fence_path(rundir, rank, gen))


class Heartbeat:
    """One rank's lease writer thread.  Beats every ``heartbeat_s``;
    each beat also checks the rank's own fence and latches
    ``self.fenced`` so sync points see a takeover within one beat even
    between collectives."""

    def __init__(self, rundir: str, rank: int, *, heartbeat_s: float,
                 lease_s: float, gen: int = 0):
        self.rundir = rundir
        self.rank = rank
        self.heartbeat_s = heartbeat_s
        self.lease_s = lease_s
        self.gen = gen
        self.seq = 0
        self.fenced = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        os.makedirs(hb_dir(self.rundir, self.gen), exist_ok=True)
        self.beat_once()              # beat 0 lands BEFORE any collective
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"mrtpu-dist-hb-r{self.rank}")
        self._thread.start()

    def beat_once(self) -> None:
        self.seq += 1
        write_beat(self.rundir, self.rank, self.lease_s, gen=self.gen,
                   seq=self.seq)
        if is_fenced(self.rundir, self.rank, self.gen):
            self.fenced = True
        try:
            from ..obs.metrics import get_registry
            get_registry().counter(
                "mrtpu_dist_heartbeats_total",
                "data-plane heartbeats written by this rank").inc()
        except Exception:
            pass

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self.beat_once()
            except OSError:
                # a failed beat must not kill the data plane thread —
                # peers will judge us by the last durable lease; if the
                # disk stays broken we expire honestly
                pass

    def stop(self, leave: bool = True) -> None:
        """Stop beating; ``leave`` removes the lease (a clean exit is
        not a death — peers should not see an expiry to claim)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.heartbeat_s + 1.0)
        if leave:
            try:
                os.remove(hb_path(self.rundir, self.rank, self.gen))
            except OSError:
                pass


# ---------------------------------------------------------------------------
# the runtime
# ---------------------------------------------------------------------------

class DistRuntime:
    """This process's membership in the multi-process data plane."""

    def __init__(self, rank: int, world: int, rundir: str, *,
                 heartbeat_s: Optional[float] = None,
                 lease_s: Optional[float] = None,
                 skew_s: Optional[float] = None,
                 sync_timeout_s: Optional[float] = None,
                 gen: int = 0):
        self.rank = rank
        self.world = world
        self.rundir = rundir
        self.gen = gen
        self.heartbeat_s = heartbeat_s if heartbeat_s is not None else \
            env_knob("MRTPU_DIST_HEARTBEAT", float, 0.25)
        self.lease_s = lease_s if lease_s is not None else \
            env_knob("MRTPU_DIST_LEASE", float, 1.5)
        self.skew_s = skew_s if skew_s is not None else \
            env_knob("MRTPU_DIST_SKEW", float, 0.25)
        self.sync_timeout_s = sync_timeout_s if sync_timeout_s is not None \
            else env_knob("MRTPU_DIST_SYNC_TIMEOUT", float, 60.0)
        self.heartbeat = Heartbeat(rundir, rank,
                                   heartbeat_s=self.heartbeat_s,
                                   lease_s=self.lease_s, gen=gen)
        self.peer_lost: Optional[PeerLostError] = None
        # fleet-observability attachments (armed by _arm_observability
        # from init_from_env; None when gated off): the per-sync-site
        # straggler observer and the per-rank metrics dump channel
        self.sync_obs = None
        self.metrics_dumper = None

    # -- observation -------------------------------------------------------
    def peer_ranks(self) -> List[int]:
        return [r for r in range(self.world) if r != self.rank]

    def dead_peers(self, now: Optional[float] = None) -> List[int]:
        now = time.time() if now is None else now
        return [r for r in self.peer_ranks()
                if beat_expired(read_beat(self.rundir, r, self.gen),
                                self.skew_s, now)]

    def fenced(self) -> bool:
        return self.heartbeat.fenced or \
            is_fenced(self.rundir, self.rank, self.gen)

    # -- the watchdog ------------------------------------------------------
    def guard(self, site: str, fn: Callable, *args, **kwargs):
        """Run ``fn`` (a host sync point: count pull, exchange dispatch
        + block, reshard, checkpoint barrier) under the collective
        watchdog.  Returns ``fn``'s result, or raises:

        * :class:`RankFencedError` — WE were fenced (zombie guard);
        * :class:`PeerLostError` — a peer's lease expired, the sync
          deadline passed (hung-but-heartbeating peer), or ``fn``
          failed while a peer was dying (the transport saw the death
          first — confirmed against leases within one expiry window).

        The blocking call runs on a daemon worker thread so a peer that
        is already dead cannot pin this thread forever: on a trip the
        worker is abandoned mid-collective (the process is about to
        exit with :data:`EXIT_PEER_LOST`; nothing reuses the wedged
        gloo context)."""
        from ..ft.inject import fault_point
        fault_point(f"dist.{site}")
        if self.fenced():
            self._note_fenced(site)
            raise RankFencedError(self.rank, site)

        # arrival stamp BEFORE dispatching into the collective (and
        # AFTER fault_point — an injected delay lands in the stamp):
        # durable by the time any peer's sync completes, which is what
        # lets every rank compute the arrival spread locally with zero
        # extra collectives.  Observing a sync must never fail it.
        obs, arec = self.sync_obs, None
        if obs is not None:
            try:
                arec = obs.arrive(site)
            except Exception:
                arec = None

        done = threading.Event()
        box: list = [None, None]     # [result, exception]

        def _work():
            try:
                box[0] = fn(*args, **kwargs)
            except BaseException as e:        # noqa: BLE001 — re-raised
                box[1] = e
            finally:
                done.set()

        t = threading.Thread(target=_work, daemon=True,
                             name=f"mrtpu-dist-sync-{site}")
        t0 = time.monotonic()
        t.start()
        poll = max(0.05, self.heartbeat_s / 2.0)
        while not done.wait(poll):
            if self.fenced():
                self._note_fenced(site)
                raise RankFencedError(self.rank, site)
            dead = self.dead_peers()
            if dead:
                self._trip(site, dead, "lease expired")
            if time.monotonic() - t0 > self.sync_timeout_s:
                self._trip(site, self.dead_peers(),
                           f"sync deadline {self.sync_timeout_s:g}s "
                           f"passed (hung peer?)")
        if box[1] is not None:
            # the transport may observe a dying peer before its lease
            # expires (connection reset beats the expiry clock): give
            # the leases one expiry window to confirm, then convert —
            # otherwise the original error propagates untouched.  A
            # peerless (shrunk-to-1) runtime skips the window: there is
            # no lease that could ever confirm anything
            if self.peer_ranks():
                deadline = time.time() + self.lease_s + self.skew_s
                while time.time() < deadline:
                    dead = self.dead_peers()
                    if dead:
                        self._trip(site, dead,
                                   f"transport error {box[1]!r}")
                    time.sleep(poll)
            raise box[1]
        if arec is not None:
            try:
                obs.complete(site, arec)
            except Exception:
                pass
        return box[0]

    def _trip(self, site: str, dead: List[int], reason: str):
        err = PeerLostError(site, dead, reason)
        self.peer_lost = err
        try:
            from ..obs import get_tracer
            from ..obs.metrics import get_registry
            reg = get_registry()
            reg.counter(
                "mrtpu_dist_watchdog_trips_total",
                "collective watchdog trips (a sync point detected a "
                "dead/hung peer instead of stalling)", ("site",)
            ).inc(site=site)
            reg.counter(
                "mrtpu_dist_peer_lost_total",
                "peer ranks lost (as observed by this rank)"
            ).inc(max(1, len(dead)))
            with get_tracer().span("dist.peer_lost", cat="dist",
                                   site=site, rank=self.rank,
                                   dead=list(dead)):
                pass
        except Exception:
            pass
        raise err

    def _note_fenced(self, site: str):
        try:
            from ..obs.metrics import get_registry
            get_registry().counter(
                "mrtpu_dist_fenced_total",
                "sync points this rank declined because it was fenced "
                "(zombie double-execution guard)", ("site",)
            ).inc(site=site)
        except Exception:
            pass

    def stop(self, leave: bool = True) -> None:
        if self.metrics_dumper is not None:
            try:
                self.metrics_dumper.stop("exit")
            except Exception:
                pass
        if self.sync_obs is not None:
            try:
                self.sync_obs.close()
            except Exception:
                pass
        self.heartbeat.stop(leave=leave)


def lease_table(rt: DistRuntime) -> dict:
    """Point-in-time snapshot of the generation's lease/fence state —
    what a ``PeerLostError`` flight dump embeds so "who died, and when"
    is answerable from the artifact alone (obs/flight.py attaches it as
    ``doc["dist"]``)."""
    now = time.time()
    peers = {}
    for r in range(rt.world):
        beat = read_beat(rt.rundir, r, rt.gen)
        row = {"fenced": is_fenced(rt.rundir, r, rt.gen),
               "expired": beat_expired(beat, rt.skew_s, now)}
        if beat is None:
            row["missing"] = True
        else:
            try:
                row["age_s"] = round(now - float(beat["ts"]), 3)
                row["expires_in_s"] = round(float(beat["expires"]) - now,
                                            3)
                row["seq"] = int(beat.get("seq", 0))
                row["state"] = str(beat.get("state", ""))
                row["pid"] = beat.get("pid")
            except (KeyError, TypeError, ValueError):
                row["unreadable"] = True
        peers[str(r)] = row
    return {"rank": rt.rank, "world": rt.world, "gen": rt.gen,
            "rundir": rt.rundir, "fenced": rt.fenced(),
            "lease_s": rt.lease_s, "skew_s": rt.skew_s,
            "dead": [r for r, row in peers.items() if row["expired"]],
            "peers": peers}


def note_sync_rows(counts_mat) -> None:
    """Feed the straggler classifier the shuffle count matrix's
    per-destination row totals (column sums of the [P, P] src×dest
    matrix every rank already pulls at the phase-1 count sync) — the
    data-skew half of the cause verdict.  Crash-proof no-op outside the
    data plane."""
    rt = _ACTIVE
    if rt is None or rt.sync_obs is None:
        return
    try:
        rows = [int(x) for x in counts_mat.sum(axis=0)]
        # multiple local devices: P = world * ndev shards — fold shard
        # totals onto their owning rank (launcher slices contiguously)
        P = len(rows)
        if P != rt.world and rt.world > 0 and P % rt.world == 0:
            per = P // rt.world
            rows = [sum(rows[r * per:(r + 1) * per])
                    for r in range(rt.world)]
        rt.sync_obs.note_rows(rows)
    except Exception:
        pass


_ACTIVE: Optional[DistRuntime] = None
_LOCK = threading.Lock()


def active() -> Optional[DistRuntime]:
    return _ACTIVE


def activate(rt: Optional[DistRuntime]) -> Optional[DistRuntime]:
    global _ACTIVE
    with _LOCK:
        prev, _ACTIVE = _ACTIVE, rt
    return prev


def init_from_env() -> Optional[DistRuntime]:
    """Join the multi-process data plane if ``MRTPU_DIST_WORLD`` > 1:
    force the host-platform device count, select gloo cross-process CPU
    collectives, ``jax.distributed.initialize`` against the launcher's
    coordinator, start heartbeating, and install the runtime (rank-
    tagging every span via the tracer's process attrs).  MUST run
    before any other jax use in the process — the launcher guarantees
    this by making it the worker's first call.  Returns None (and
    touches nothing) in single-process runs."""
    world = env_knob("MRTPU_DIST_WORLD", int, 0)
    rundir = env_str("MRTPU_DIST_RUNDIR", "")
    if world < 1 or (world == 1 and not rundir):
        return None
    rank = env_knob("MRTPU_DIST_RANK", int, 0)
    coord = env_str("MRTPU_DIST_COORD", "")
    gen = env_knob("MRTPU_DIST_GEN", int, 0)
    if world > 1 and (not coord or not rundir):
        raise MRError("MRTPU_DIST_WORLD is set but MRTPU_DIST_COORD / "
                      "MRTPU_DIST_RUNDIR are not — use scripts/"
                      "mrlaunch.py (doc/distributed.md)")
    ndev = env_knob("MRTPU_DIST_LOCAL_DEVICES", int, 1)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={ndev}"
        ).strip()
    import jax
    if world > 1:
        # a shrunk-to-1 generation needs NO coordinator or gloo: its
        # mesh is local, and jax.distributed would just add the
        # coordination service's own failure modes back in
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, ValueError):
            # jax ≥0.5 renamed/retired the flag (gloo became the
            # default for multiprocess CPU); a TPU backend never
            # needed it
            pass
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=world, process_id=rank)
    # arm MRTPU_FAULTS here: chaos workers drive the collective tier
    # directly and never construct a MapReduce (the usual arming site)
    from ..ft.inject import configure_from_env
    configure_from_env()
    rt = DistRuntime(rank, world, rundir, gen=gen)
    rt.heartbeat.start()
    activate(rt)
    try:
        from ..obs import get_tracer
        from ..obs.metrics import get_registry
        get_tracer().set_proc_attrs(rank=rank)
        reg = get_registry()
        reg.gauge("mrtpu_dist_world",
                  "process count of the active data plane").set(world)
        reg.gauge("mrtpu_dist_rank",
                  "this process's rank in the data plane").set(rank)
        reg.gauge("mrtpu_dist_gen",
                  "shrink generation of the active data plane (0 = "
                  "first launch)").set(gen)
    except Exception:
        pass
    _arm_observability(rt)
    return rt


def _arm_observability(rt: DistRuntime) -> None:
    """Per-rank fleet-observability wiring (doc/observability.md
    "Fleet & mesh"): install the launcher's trace id so every span /
    journal record / flight dump this rank emits carries the LAUNCH's
    single id, open this rank's trace shard under the shared run dir,
    and arm the sync-site straggler observer + the metrics dump
    channel.  Every piece is knob-gated and individually crash-proof —
    observability must never take down the data plane it watches."""
    from ..utils.env import env_flag
    tid = env_str("MRTPU_DIST_TRACE_ID", "")
    if tid:
        try:
            from ..obs.context import set_process_trace_id
            set_process_trace_id(tid)
        except Exception:
            pass
    if env_flag("MRTPU_DIST_TRACE", True):
        try:
            from ..obs import get_tracer
            get_tracer().enable(jsonl=os.path.join(
                rt.rundir, f"trace-r{rt.rank}.jsonl"))
        except Exception:
            pass
    if env_str("MRTPU_FLIGHT", "") == "":
        # no explicit flight config: arm the recorder at the shared run
        # dir, so every rank's ring (with the lease table) is dumpable
        # on PeerLost — the post-mortem satellite.  MRTPU_FLIGHT=0
        # still disables; an explicit dir was already armed at import.
        try:
            from ..obs import flight as _flight
            _flight.enable(dir=rt.rundir)
        except Exception:
            pass
    if env_flag("MRTPU_DIST_SYNC_OBS", True):
        try:
            from ..obs.fleetobs import SyncObserver
            rt.sync_obs = SyncObserver(rt.rundir, rt.rank, rt.world,
                                       gen=rt.gen)
        except Exception:
            rt.sync_obs = None
    if env_flag("MRTPU_DIST_METRICS", True):
        try:
            from ..obs.fleetobs import RankMetricsDumper
            rt.metrics_dumper = RankMetricsDumper(rt.rundir, rt.rank,
                                                  gen=rt.gen)
            rt.metrics_dumper.start()
        except Exception:
            rt.metrics_dumper = None


def guard_call(site: str, fn: Callable, *args, **kwargs):
    """Watchdog-wrapped ``fn`` when the data plane is active, direct
    call otherwise — the zero-overhead spelling library sync points use
    (parallel/shuffle count pull, reshard, checkpoint barriers)."""
    rt = _ACTIVE
    if rt is None:
        return fn(*args, **kwargs)
    return rt.guard(site, fn, *args, **kwargs)


def surviving_width() -> Optional[int]:
    """The mesh-width cap after a shrink: the active runtime's world,
    or the launcher/operator-set ``MRTPU_DIST_WIDTH_CAP`` (how a serve
    daemon that is NOT itself a data-plane rank learns the fleet
    degraded).  None = uncapped."""
    rt = _ACTIVE
    if rt is not None:
        return rt.world
    cap = env_knob("MRTPU_DIST_WIDTH_CAP", int, 0)
    return cap if cap > 0 else None


# ---------------------------------------------------------------------------
# multi-controller host pulls
# ---------------------------------------------------------------------------

def host_pull(arr, mesh=None):
    """``np.asarray`` that works across process-spanning meshes.

    A sharded global array spans non-addressable devices in
    multi-controller runs, so a direct ``np.asarray`` raises.  When the
    data plane is active and the array isn't fully addressable, run a
    compiled identity resharded to fully-replicated (an all_gather —
    every controller then holds every shard) and pull that.  Single-
    process: a plain ``np.asarray``, zero extra dispatch."""
    import numpy as np
    if _ACTIVE is None:
        return np.asarray(arr)
    try:
        fully = bool(getattr(arr, "is_fully_addressable", True)
                     or getattr(arr, "is_fully_replicated", False))
    except Exception:
        fully = True
    if fully:
        return np.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec
    sharding = getattr(arr, "sharding", None)
    m = mesh if mesh is not None else getattr(sharding, "mesh", None)
    if m is None:
        return np.asarray(arr)       # let jax raise its own error
    return np.asarray(_replicate_jit(NamedSharding(m, PartitionSpec()))
                      (arr))


# one jitted replicate-identity per output sharding: a fresh lambda per
# pull would retrace+recompile the all-gather on EVERY count sync —
# the data plane's one mandatory barrier per op
_REP_JITS: dict = {}


def _replicate_jit(rep):
    fn = _REP_JITS.get(rep)
    if fn is None:
        import jax
        with _LOCK:
            fn = _REP_JITS.get(rep)
            if fn is None:
                if len(_REP_JITS) >= 32:       # churny meshes: bounded
                    _REP_JITS.clear()
                fn = _REP_JITS[rep] = jax.jit(lambda x: x,
                                              out_shardings=rep)
    return fn


def shard_local_rows(mesh, local_rows, counts):
    """Build a [P*cap, ...] row-sharded global array where THIS process
    contributes ``local_rows`` for its addressable shard(s) — the
    multi-controller twin of ``sharded.shard_frame_with_counts`` (which
    needs the whole host array and cannot run on one controller).

    ``counts[P]`` must be the globally-agreed per-shard valid counts
    (every rank computes the same vector from the same metadata — the
    launcher's deterministic slicing makes that free).  ``local_rows``
    is a list of one host block per addressable shard, in shard order;
    blocks are padded to the common power-of-two cap here."""
    import jax
    import numpy as np

    from .mesh import row_sharding
    from .sharded import _pad_rows, round_cap
    counts = np.asarray(counts)
    cap = round_cap(int(counts.max()) if counts.size else 0)
    sharding = row_sharding(mesh)
    P = int(counts.shape[0])
    first = np.asarray(local_rows[0])
    shape = (P * cap,) + first.shape[1:]
    dmap = sharding.addressable_devices_indices_map(shape)
    devs = sorted(dmap.items(),
                  key=lambda di: (di[1][0].start or 0))
    if len(devs) != len(local_rows):
        raise MRError(f"shard_local_rows: {len(local_rows)} local "
                      f"blocks for {len(devs)} addressable shards")
    shards = [jax.device_put(_pad_rows(np.asarray(block), cap), dev)
              for (dev, _idx), block in zip(devs, local_rows)]
    return jax.make_array_from_single_device_arrays(
        shape, sharding, shards), cap
