"""The distributed shuffle — aggregate() over ICI collectives.

Re-designs the reference's ``MapReduce::aggregate`` + ``Irregular`` stack
(``src/mapreduce.cpp:385-563``, ``src/irregular.cpp``; call stack SURVEY.md
§3.2) as a two-phase padded all-to-all:

phase 1 (jitted, per shard): hash each valid key to a destination shard
  (user hash or the lookup3 port — same default as
  ``hashlittle(key,bytes,nprocs)%nprocs``, src/mapreduce.cpp:469-472),
  stable-sort rows by destination, count rows per destination.

host: read the [P,P] count matrix, pick the padded bucket size B and the
  output capacity (rounded to powers of two to bound recompiles).  This
  replaces the reference's INTMAX/fraction flow-control negotiation
  (``irregular.cpp:95-242``) — static shapes instead of retry loops.

phase 2 (jitted, per shard): scatter sorted rows into a [P,B] send buffer,
  exchange via ``lax.all_to_all`` (``all2all=1``) or a ppermute ring
  (``all2all=0`` — the reference's custom Irecv/Send transport,
  ``irregular.cpp:311-363``), then compact received rows to the front.

Skew note: padding to the max bucket wastes ICI bandwidth on skewed keys
(RMAT high-degree vertices); the count matrix is already on the host, so a
multi-round fixed-budget variant can slot in here later (SURVEY.md §7).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.frame import KVFrame
from ..core.runtime import bump_dispatch
from ..ops.hash import hash_words32
from ..plan.cache import LRUCache
from .mesh import (flat_axis_index, mesh_axes, mesh_axis_size,
                   row_sharding, row_spec)
from .sharded import ShardedKV, SyncStats, round_cap, shard_frame

# ---------------------------------------------------------------------------
# hashing of device keys
# ---------------------------------------------------------------------------

def keys_to_words32(keys):
    """Bitcast any fixed-width key array [n(,w)] to uint32 words [n, W] so
    the device hash sees the same little-endian bytes the host hash would
    (reference hashes raw key bytes)."""
    if keys.ndim == 1:
        keys = keys[:, None]
    nbytes = keys.dtype.itemsize
    if nbytes >= 4:
        words = lax.bitcast_convert_type(keys, jnp.uint32)  # [n,w,nb/4]
        return words.reshape(keys.shape[0], -1)
    # sub-4-byte dtypes: widen to u32 (hash equals hashlittle on padded bytes)
    return keys.astype(jnp.uint32).reshape(keys.shape[0], -1)


def default_hash(keys):
    """lookup3 over the key's bytes → uint32 (device twin of
    hashlittle(key,keybytes,nprocs), src/mapreduce.cpp:472)."""
    return hash_words32(keys_to_words32(keys))


# ---------------------------------------------------------------------------
# generic two-phase exchange
# ---------------------------------------------------------------------------

_MAX_ROUNDS = 16     # unrolled in the jitted phase2; bounds trace size

def _phase1_core(nprocs: int, dest_of: Callable, key, value, count):
    """Per-shard: dest per row, stable sort rows by dest, per-dest
    counts.  Padding rows get dest=nprocs (dropped later).  Returns the
    per-row dest too so the wire variant's bucket stats share one dest
    computation."""
    cap = key.shape[0]
    valid = jnp.arange(cap) < count
    dest = jnp.where(valid, dest_of(key).astype(jnp.int32), nprocs)
    order = jnp.argsort(dest, stable=True)
    skey = jnp.take(key, order, axis=0)
    svalue = jnp.take(value, order, axis=0)
    counts_local = jnp.bincount(dest, length=nprocs + 1)[:nprocs].astype(jnp.int32)
    return skey, svalue, counts_local, dest


def _phase1(nprocs: int, dest_of: Callable, key, value, count):
    return _phase1_core(nprocs, dest_of, key, value, count)[:3]


def phase1_shard_body(nprocs: int, dest_of: Callable, wire_elig, k, v, c):
    """Per-shard phase-1 body — the composable twin of
    :func:`phase2_shard_body`: dest-sorted rows + per-dest counts, plus
    (``wire_elig`` set) the wire codec's per-bucket min/max stats
    computed in the SAME pass (``parallel/wire.bucket_stats``).
    Returns ``(skey, svalue, counts_local, stats_or_None)``.  Shared by
    the standalone phase-1 program builder and the plan/ fuser's
    megafused single-dispatch programs, so their row layout can never
    drift."""
    sk, sv, cl, d = _phase1_core(nprocs, dest_of, k, v, c)
    if wire_elig is None:
        return sk, sv, cl, None
    from .wire import bucket_stats
    k_elig, v_elig = wire_elig
    return sk, sv, cl, bucket_stats(nprocs, k, v, d, k_elig, v_elig)


def _build_send_window(nprocs: int, B: int, start: int, rows,
                       counts_local):
    """Scatter dest-sorted rows into a [P, B, ...] send buffer, taking
    only bucket positions [start, start+B) — the window slice of the
    flow-controlled exchange (uniform rounds use start = r*B; the wire
    codec's tiered caps use the running tier offset)."""
    cap = rows.shape[0]
    cum = jnp.cumsum(counts_local)
    r = jnp.arange(cap)
    d = jnp.searchsorted(cum, r, side="right").astype(jnp.int32)  # dest of row r
    off = jnp.concatenate([jnp.zeros(1, jnp.int32), cum[:-1].astype(jnp.int32)])
    q0 = r - jnp.take(off, jnp.minimum(d, nprocs - 1))  # slot within bucket
    # rows outside this round's window must go POSITIVELY out of bounds:
    # a negative q wraps NumPy-style (idx+B) before mode="drop" checks, so
    # earlier rounds' rows would scatter into [0,B) and corrupt this round
    in_window = (q0 >= start) & (q0 < start + B)
    q = jnp.where(in_window, q0 - start, B)
    shape = (nprocs, B) + rows.shape[1:]
    send = jnp.zeros(shape, rows.dtype)
    # rows with d==nprocs (padding) or q==B (other round) → dropped
    return send.at[d, q].set(rows, mode="drop")


def _build_send(nprocs: int, B: int, rows, counts_local, round_idx: int = 0):
    """Uniform-round window: bucket positions [rB, rB+B)."""
    return _build_send_window(nprocs, B, round_idx * B, rows, counts_local)


def _ring_exchange(send, mesh):
    """Systolic shift-by-one ring: recv[j] = what shard j holds for me.

    The reference's second transport is a hand-rolled Irecv/Send ring
    (``irregular.cpp:311-363``).  Round 1 unrolled one ppermute per shift
    distance k — O(P) collectives of O(P·B) state each, an O(P²) trace
    that stops compiling at pod scale.  This version keeps the *same*
    single shift-by-one permutation every step inside ``lax.fori_loop``
    (ppermute's permutation must be trace-static, so a varying shift can't
    live in the loop): after s shifts my buffer is shard (me-s)'s original
    send array, and its row [me] is that shard's block for me."""
    axes = mesh_axes(mesh)
    nprocs = send.shape[0]
    me = flat_axis_index(mesh)
    perm = [(i, (i + 1) % nprocs) for i in range(nprocs)]
    recv = jnp.zeros_like(send)
    recv = recv.at[me].set(send[me])  # self-copy overlap (irregular.cpp:311)

    def body(s, carry):
        buf, recv = carry
        # flat 1-axis mesh only: _exchange_blocks/_exchange_counts route
        # every 2-axis mesh through _a2a_hier before reaching the ring
        buf = lax.ppermute(buf, axes[0], perm)
        recv = recv.at[(me - s) % nprocs].set(buf[me])
        return buf, recv

    _, recv = lax.fori_loop(1, nprocs, body, (send, recv))
    return recv


def _a2a_hier(send, mesh):
    """Hierarchical all-to-all for a (slice, chip) mesh: rows for
    (s', c') first move to the LOCAL chip c' over ICI (axis "c"), then
    one DCN all-to-all between same-chip-index peers (axis "s") delivers
    them — each cross-slice row crosses DCN exactly once, pre-aggregated
    per (c', s') pair.  Output matches the flat all_to_all: recv[p] =
    block from flat proc p."""
    axes = mesh_axes(mesh)
    S = int(mesh.shape[axes[0]])
    C = int(mesh.shape[axes[1]])
    x = send.reshape((S, C) + send.shape[1:])   # [dest_slice, dest_chip,...]
    x = lax.all_to_all(x, axes[1], 1, 1)        # ICI: [dest_slice, src_c,...]
    x = lax.all_to_all(x, axes[0], 0, 0)        # DCN: [src_s, src_c, ...]
    return x.reshape(send.shape)


def _exchange_counts(counts_local, transport: int, mesh):
    """Exchange per-dest counts: counts_from[j] = rows shard j sends me.
    Multi-slice meshes always take the hierarchical route (a flat ring
    would cross DCN on most hops — the pattern the hierarchy avoids)."""
    if transport == 1 or len(mesh_axes(mesh)) == 2:
        return _exchange_blocks(counts_local[:, None], transport, mesh)[:, 0]
    return _ring_exchange(counts_local[:, None], mesh)[:, 0]


def _exchange_blocks(send, transport: int, mesh):
    """[P,B,...] send blocks → [P,B,...] recv blocks."""
    axes = mesh_axes(mesh)
    if len(axes) == 2:
        return _a2a_hier(send, mesh)            # ICI-then-DCN (module doc)
    if transport == 1:
        return lax.all_to_all(send, axes[0], 0, 0)
    return _ring_exchange(send, mesh)


def _compact(recv, counts_from, cap_out: int):
    """[P,B,...] recv blocks → [cap_out,...] rows packed to the front."""
    nprocs, B = recv.shape[0], recv.shape[1]
    flat = recv.reshape((nprocs * B,) + recv.shape[2:])
    valid = (jnp.arange(B)[None, :] < counts_from[:, None]).reshape(-1)
    order = jnp.argsort(~valid, stable=True)  # valid rows first, order kept
    packed = jnp.take(flat, order[:cap_out], axis=0)
    return packed, jnp.sum(counts_from)


def _dest_fn(dest, nprocs: int, mesh) -> Callable:
    """Destination spec → per-row dest function.  Specs are hashable so
    the jitted phase1 caches across calls (the iterative graph commands
    re-shuffle every round; re-jitting per round was the dominant cost):

    * ("hash", fn_or_None) — fn(keys)%nprocs, default lookup3;
    * ("fixed_mod", n) — every row of shard i to shard i%n: the
      reference gather's EXACT sender→receiver mapping ("lo procs recv
      from set of hi procs with same (ID % numprocs)",
      src/mapreduce.cpp:919-928);
    * ("range", offsets, ends) — topology resharding (reshard.py):
      row r of shard i has GLOBAL index offsets[i]+r; it routes to the
      target shard whose cumulative row range covers that index
      (``searchsorted(ends, g, "right")``).  The redistribution
      schedule (offsets/ends, both hashable tuples) is computed
      host-side from the counts — the data itself moves only through
      the collective, the 2112.01075 recipe."""
    kind = dest[0]
    if kind == "hash":
        fn = dest[1]
        if fn is None:
            return lambda keys: default_hash(keys) % nprocs
        return lambda keys: fn(keys) % nprocs
    if kind == "fixed_mod":
        n = dest[1]

        def fixed(keys):
            me = flat_axis_index(mesh)
            d = (me % n).astype(jnp.int32)
            return jnp.full(keys.shape[0], d, jnp.int32)
        return fixed
    if kind == "range":
        offsets, ends = dest[1], dest[2]

        def ranged(keys):
            me = flat_axis_index(mesh)
            offs = jnp.asarray(offsets, jnp.int64)
            g = offs[me] + jnp.arange(keys.shape[0], dtype=jnp.int64)
            # dest is monotone in the row index, so phase1's stable
            # dest-sort is the identity and the packed output preserves
            # exact global row order — reshard's byte-identity contract
            return jnp.searchsorted(jnp.asarray(ends, jnp.int64), g,
                                    side="right").astype(jnp.int32)
        return ranged
    raise ValueError(dest)


# bounded executable caches (ISSUE 2 satellite): the pre-plan caches
# were functools.lru_cache(None) — long soak runs across many meshes /
# dest functions / cap tuples pinned every executable forever.  Same
# LRU policy (and telemetry) as the plan cache; stats land in
# MapReduce.stats()["plan"] via plan.cache.cache_stats().
from ..utils.env import env_knob  # noqa: E402

PHASE1_CACHE = LRUCache(env_knob("MRTPU_JIT_CACHE", int, 64),
                        name="shuffle.phase1")
PHASE2_CACHE = LRUCache(env_knob("MRTPU_JIT_CACHE", int, 64),
                        name="shuffle.phase2")


def _phase1_jit(mesh, dest, donate: bool = False, wire=None):
    """Cache the jitted phase1 only for stable dest specs — a per-call
    user hash lambda would defeat reuse (and one-shot entries would
    churn the LRU), so those build uncached (old behavior).

    ``donate=True`` (exec/: MRTPU_DONATE) donates the key/value inputs —
    the dest-sorted outputs are same-shape/dtype, so XLA aliases the
    input buffers instead of materialising a second copy; the caller's
    arrays are DELETED at dispatch and must be dead (the exchange's
    input dataset is — it is replaced by the exchange output).

    ``wire=(k_elig, v_elig)`` (parallel/wire.py, MRTPU_WIRE): the same
    program ALSO emits per-destination bucket min/max stats — a fourth
    [P, 4] uint64 output the wire codec's host planner reads alongside
    the count matrix.  Part of the cache key: the wire and raw programs
    have different output signatures."""
    if dest[0] == "hash" and dest[1] is not None:
        return _phase1_build(mesh, dest, donate, wire)
    return PHASE1_CACHE.get_or_build(
        (mesh, dest, donate, wire),
        lambda: _phase1_build(mesh, dest, donate, wire))


def _phase1_build(mesh, dest, donate: bool = False, wire=None):
    nprocs = mesh_axis_size(mesh)
    dest_of = _dest_fn(dest, nprocs, mesh)
    spec = row_spec(mesh)

    if wire is None:
        def body(k, v, c):
            return phase1_shard_body(nprocs, dest_of, None, k, v, c)[:3]
        nouts = 3
    else:
        def body(k, v, c):
            return phase1_shard_body(nprocs, dest_of, wire, k, v, c)
        nouts = 4

    def phase1(key, value, count):
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(spec,) * nouts)(key, value, count)

    # phase 1 is shape-preserving (dest-sorted rows), so donation always
    # aliases — the biggest win, on every aggregate/gather
    from ..exec import donated_jit
    return donated_jit(phase1, (0, 1) if donate else ())


def phase2_shard_body(nprocs: int, transport: int, mesh, B: int,
                      nrounds: int, cap_out: int, k, v, cl):
    """Per-shard phase-2 body — the fusible stage builder the plan/
    fuser composes with convert/reduce inside ONE shard_map program.
    Returns ``(out_k, out_v, nrecv)``: received rows packed to the
    front of a [cap_out, ...] block plus this shard's valid-row count.

    Multi-round bounded exchange: each round moves ≤ B rows per
    (src, dest) bucket, so the padded send buffer is [P, B] regardless
    of skew — the TPU equivalent of the reference's fraction<1.0
    flow-control retry loop (src/mapreduce.cpp:498-513,
    irregular.cpp:95-242), but with statically known round count.
    Received rows scatter directly to their final packed position
    (base[src] + round*B + slot), so no per-round compaction pass."""
    counts_from = _exchange_counts(cl, transport, mesh)
    cum = jnp.cumsum(counts_from)
    base = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), cum[:-1].astype(jnp.int32)])
    out_k = jnp.zeros((cap_out,) + k.shape[1:], k.dtype)
    out_v = jnp.zeros((cap_out,) + v.shape[1:], v.dtype)
    slot = jnp.arange(B, dtype=jnp.int32)
    for r in range(nrounds):
        recv_k = _exchange_blocks(
            _build_send(nprocs, B, k, cl, r), transport, mesh)
        recv_v = _exchange_blocks(
            _build_send(nprocs, B, v, cl, r), transport, mesh)
        # position of recv[j, q]: base[j] + r*B + q; invalid slots
        # (past counts_from[j]) push out of range and drop
        q_global = r * B + slot[None, :]
        pos = jnp.where(q_global < counts_from[:, None],
                        base[:, None] + q_global, cap_out)
        out_k = out_k.at[pos.reshape(-1)].set(
            recv_k.reshape((-1,) + k.shape[1:]), mode="drop")
        out_v = out_v.at[pos.reshape(-1)].set(
            recv_v.reshape((-1,) + v.shape[1:]), mode="drop")
    return out_k, out_v, jnp.sum(counts_from)


def _phase2_jit(mesh, transport: int, B: int, nrounds: int, cap_out: int,
                donate: bool = False):
    """``donate=True`` donates the dest-sorted skey/svalue (dead after
    the exchange scatters them into the output blocks).  NEVER used for
    the SPECULATIVE phase 2: a failed speculation re-runs phase 2 with
    the same inputs, which donation would have deleted.  Callers only
    pass donate=True when cap_out == cap (the caller checks) — the one
    case the donation is byte-aliasable, so it never degrades to a
    warned no-op."""
    return PHASE2_CACHE.get_or_build(
        (mesh, transport, B, nrounds, cap_out, donate),
        lambda: _phase2_build(mesh, transport, B, nrounds, cap_out,
                              donate))


def _phase2_build(mesh, transport: int, B: int, nrounds: int, cap_out: int,
                  donate: bool = False):
    nprocs = mesh_axis_size(mesh)
    spec = row_spec(mesh)

    def phase2(skey, svalue, counts_local):
        def body(k, v, cl):
            out_k, out_v, _ = phase2_shard_body(
                nprocs, transport, mesh, B, nrounds, cap_out, k, v, cl)
            return out_k, out_v
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(spec, spec))(skey, svalue, counts_local)

    from ..exec import donated_jit
    return donated_jit(phase2, (0, 1) if donate else ())


def _phase2_wire_jit(mesh, transport: int, tiers, cap_out: int, kpack,
                     vpack, donate: bool = False):
    """The wire-codec phase 2 (parallel/wire.py): same packed output as
    :func:`_phase2_jit` byte for byte, but rows cross the interconnect
    delta-packed at the planned widths with tiered round caps.  The
    plan's every static knob keys the executable cache — the "wire in
    the jit key" contract of doc/perf.md."""
    return PHASE2_CACHE.get_or_build(
        (mesh, transport, "wire", tiers, cap_out, kpack, vpack, donate),
        lambda: _phase2_wire_build(mesh, transport, tiers, cap_out,
                                   kpack, vpack, donate))


def _phase2_wire_build(mesh, transport: int, tiers, cap_out: int, kpack,
                       vpack, donate: bool = False):
    from .wire import phase2_wire_shard_body
    nprocs = mesh_axis_size(mesh)
    spec = row_spec(mesh)

    def phase2(skey, svalue, counts_local, stats_local):
        def body(k, v, cl, st):
            out_k, out_v, _ = phase2_wire_shard_body(
                nprocs, transport, mesh, tiers, cap_out, kpack, vpack,
                k, v, cl, st)
            return out_k, out_v
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec,) * 4,
            out_specs=(spec, spec))(skey, svalue, counts_local,
                                    stats_local)

    from ..exec import donated_jit
    return donated_jit(phase2, (0, 1) if donate else ())


# speculative capacity cache (round 4, VERDICT r3 weak #5): composed
# iterative commands pay the exchange's ONE host sync — the count-matrix
# pull that sizes the bucket/round/output shapes — once per op, a full
# tunnel round-trip on remote TPU setups.  Keyed by (mesh, transport,
# operand shapes/dtypes), the caps that worked last time are assumed
# again: phase 2 is ENQUEUED immediately with the cached shapes and the
# count matrix is pulled while it runs.  The pull then verifies the
# speculation — on overflow (a bucket past B*nrounds or an output shard
# past cap_out would have dropped rows) the correctly-sized phase 2
# re-runs; on gross oversizing (>4x) the cache right-sizes for next
# time but the speculative result is kept.  Same sync count either way
# (SyncStats.pulls is still 1/op) — the sync just moves OFF the
# critical path whenever consecutive ops keep a similar distribution,
# which is exactly the composed-loop case.
# Guarded by _SPEC_LOCK: ``-partition`` worlds exchange from interpreter
# THREADS (oink/universe.py), and an unlocked read-modify-write could
# publish a half-observed cap tuple (VERDICT r4 weak #7).  Entries are
# immutable tuples, so lock only the dict accesses, not the exchange.
_SPEC_CACHE: dict = {}
import threading as _threading
_SPEC_LOCK = _threading.Lock()


def _plan_caps(counts_mat: np.ndarray):
    """Bucket/round/output sizing from the pulled count matrix (the
    flow-control policy: pad buckets to ~the mean nonzero bucket, round
    up to _MAX_ROUNDS rounds — see exchange())."""
    Bmax = round_cap(int(counts_mat.max())) if counts_mat.max() else 8
    new_counts = counts_mat.sum(axis=0).astype(np.int32)
    cap_out = round_cap(int(new_counts.max())) if new_counts.max() else 8
    nz = counts_mat[counts_mat > 0]
    B = round_cap(int(np.ceil(nz.mean()))) if len(nz) else 8
    nrounds = -(-Bmax // B)
    if nrounds > _MAX_ROUNDS:
        nrounds = _MAX_ROUNDS
        B = round_cap(-(-Bmax // nrounds))
        nrounds = -(-Bmax // B)
    return B, nrounds, cap_out, Bmax, new_counts


class _ExchangeStatsMeta(type):
    """Class-level assignment to the legacy names would silently
    REPLACE their read-through descriptors and freeze the value (the
    pre-r5 reset idiom `ExchangeStats.last_nrounds = 0` did exactly
    this) — intercept it with a clear error (r5 review)."""

    def __setattr__(cls, name, value):
        if name in ("last_nrounds", "last_bucket"):
            raise AttributeError(
                f"{name} is a read-only view of ExchangeStats.last — "
                f"assign ExchangeStats.last = (nrounds, bucket) instead")
        super().__setattr__(name, value)


@dataclass
class ExchangeCallStats:
    """Flow-control telemetry of ONE exchange() call (ISSUE 2
    satellite): the class-level ExchangeStats records only the LAST
    exchange process-wide, so two concurrent MapReduce objects
    (mapstyle-2 threads, -partition worlds, fused plans running
    interleaved segments) silently clobber each other.  This per-call
    object is attached to the returned ShardedKV (``.exchange_stats``)
    and surfaced as ``MapReduce.last_exchange`` after aggregate(); the
    same numbers land on the obs ``shuffle.exchange`` span."""

    nrounds: int
    bucket: int
    cap_out: int
    rows: int                 # total rows routed (count-matrix sum)
    speculative: bool         # phase 2 ran on cached caps
    sent_bytes: int = 0
    pad_bytes: int = 0
    # wire codec (parallel/wire.py, MRTPU_WIRE): actual interconnect
    # bytes after delta/narrow packing + tiered caps, and the logical/
    # wire compression ratio ((sent+pad)/wire).  0 = codec bypassed or
    # MRTPU_WIRE=0 (the raw path's bytes ARE sent+pad).
    wire_bytes: int = 0
    wire_ratio: float = 0.0


def exchange_volume(skv: ShardedKV, counts_mat, slots: int,
                    nprocs: int) -> tuple:
    """(moved, pad, rowbytes) of one exchange at LOGICAL (unpacked) row
    width — shared by the eager exchange and the plan/ fuser so their
    telemetry can never diverge.  ``slots`` is the per-bucket slot
    budget the flow-control plan exchanges (B*nrounds for the uniform
    schedule, the tier-ladder sum under the wire codec).  Padding
    diagnosis (VERDICT r2 #5): the slack beyond the real rows is pure
    padding volume.  Diagonal (self→self) slots never cross the
    interconnect — excluded on BOTH sides so pad is directly comparable
    to cssize."""
    rowbytes = (skv.key.dtype.itemsize
                * (skv.key.shape[-1] if skv.key.ndim > 1 else 1)
                + skv.value.dtype.itemsize
                * (skv.value.shape[-1] if skv.value.ndim > 1 else 1))
    useful = int(counts_mat.sum() - np.trace(counts_mat))
    moved = useful * rowbytes
    sent_slots = nprocs * (nprocs - 1) * slots
    pad = max(0, sent_slots - useful) * rowbytes
    return moved, pad, rowbytes


class ExchangeStats(metaclass=_ExchangeStatsMeta):
    """DEPRECATED process-global telemetry of the LAST exchange's flow
    control — kept as a read-only shim for existing callers; new code
    reads the per-call :class:`ExchangeCallStats` on the exchange
    result (or ``mr.last_exchange``), which concurrent MapReduce
    objects cannot clobber.  ``last`` is ONE (nrounds, bucket) tuple so
    a reader under -partition threading never sees a torn pair; the
    legacy attribute names read through it."""
    last = (0, 0)

    class _Attr:
        def __init__(self, i):
            self.i = i

        def __get__(self, obj, owner):
            return owner.last[self.i]

    last_nrounds = _Attr(0)
    last_bucket = _Attr(1)


def free_if_donated(kv, skv) -> bool:
    """After a FAILED exchange: if donation already consumed ``skv``'s
    buffers and ``skv`` is an installed frame of ``kv``, free the
    dataset — the next op then raises the clean "Cannot … without
    completed KeyValue" MRError instead of a cryptic deleted-array
    RuntimeError deep in XLA.  (Without donation a failed exchange
    leaves the input intact and retryable, as before exec/.)  Returns
    whether it freed."""
    try:
        if (skv is not None and any(f is skv for f in kv._frames)
                and skv.key.is_deleted()):
            kv.free()
            kv.complete_done = False   # _require_kv now raises MRError
            return True
    except Exception:
        pass
    return False


def exchange(skv: ShardedKV, dest, transport: int = 1,
             counters=None) -> ShardedKV:
    """Full ragged exchange: route every valid row to its dest shard.
    ``dest`` is a hashable spec (see :func:`_dest_fn`).  The intern table
    of byte-keyed datasets rides along (ids move, bytes stay put).

    Emits a ``shuffle.exchange`` child span (obs/) under the calling MR
    op carrying the flow-control telemetry (bucket/rounds/caps, useful
    vs padding bytes, whether the speculative caps held).

    Runs under the ft/ ``shuffle.exchange`` retry policy: a transient
    failure retries the WHOLE two-phase exchange — but only while the
    input buffers still exist (a failure after the donated phase-1
    dispatch consumed them is vetoed as non-retryable and propagates to
    ``free_if_donated`` as before).  The injection fault point sits
    before any dispatch, so injected faults are always retry-safe."""
    from ..ft.inject import fault_point
    from ..ft.retry import retry_call
    from ..obs import NULL_SPAN, get_tracer
    # the shuffle sync is a cancellation barrier (obs/context): a
    # cancelled request stops BEFORE the exchange dispatches — the
    # input frames are untouched, same recovery contract as a fault
    # injected here.  Outside _once so a cancel never burns the ft/
    # retry budget (CancelledError is MRError = fatal anyway).
    from ..obs.context import barrier_check
    barrier_check()

    def _once():
        fault_point("shuffle.exchange")
        tr = get_tracer()
        if not tr.enabled:
            return _exchange_impl(skv, dest, transport, counters,
                                  NULL_SPAN)
        with tr.span("shuffle.exchange", cat="shuffle",
                     nprocs=mesh_axis_size(skv.mesh),
                     transport=transport) as sp:
            return _exchange_impl(skv, dest, transport, counters, sp)

    def _retryable(e):
        try:
            return not skv.key.is_deleted()
        except Exception:
            return False

    return retry_call("shuffle.exchange", _once,
                      detail=f"P={mesh_axis_size(skv.mesh)}",
                      retryable=_retryable)


def _dispatch_phase2(plan, mesh, transport, donate2, skey, svalue,
                     counts_local, stats_local):
    """Run one exchange plan (the tagged tuple of parallel/wire.py):
    raw plans take the original counts-only program, wire plans the
    codec program (which additionally consumes the phase-1 stats)."""
    if plan[0] == "wire":
        _tag, tiers, cap_out, kpack, vpack = plan
        return _phase2_wire_jit(mesh, transport, tiers, cap_out, kpack,
                                vpack, donate=donate2)(
            skey, svalue, counts_local, stats_local)
    _tag, B, nrounds, cap_out = plan
    return _phase2_jit(mesh, transport, B, nrounds, cap_out,
                       donate=donate2)(skey, svalue, counts_local)


def _exchange_impl(skv: ShardedKV, dest, transport: int,
                   counters, sp) -> ShardedKV:
    from . import wire as _wire
    mesh = skv.mesh
    nprocs = mesh_axis_size(mesh)

    # exec/: donate dead buffers so XLA aliases instead of copying.
    # phase 1's inputs (the pre-exchange dataset, replaced by the
    # exchange output) and the definitive phase 2's inputs (the
    # dest-sorted intermediates) are both dead after their use.  The
    # eligibility rule (knob + not-shared + not-self-aliased) is
    # exec.can_donate — ONE copy, shared with the fuser
    from ..exec import can_donate
    donate = can_donate(skv)
    wire_on = _wire.wire_enabled()
    elig = _wire.columns_eligible(skv.key, skv.value) if wire_on else None

    counts_dev = jax.device_put(skv.counts.astype(np.int32),
                                row_sharding(mesh))
    bump_dispatch()
    stats_local = None
    if wire_on:
        skey, svalue, counts_local, stats_local = _phase1_jit(
            mesh, dest, donate, wire=elig)(skv.key, skv.value, counts_dev)
    else:
        skey, svalue, counts_local = _phase1_jit(mesh, dest, donate)(
            skv.key, skv.value, counts_dev)
    # speculative phase 2: enqueue with last time's plan BEFORE the
    # count-matrix pull, so the pull overlaps device work (async
    # dispatch) instead of gating it
    # dest is part of the key: a gather's fixed-dest exchange and an
    # aggregate's hash exchange over the same shapes have wildly
    # different bucket profiles — sharing one slot would cross-
    # contaminate caps and waste speculative dispatches (r4 review).
    # wire_on too: raw and wire plans are different executables
    spec_key = (mesh, transport, dest, skv.key.shape, skv.key.dtype.str,
                skv.value.shape, skv.value.dtype.str, wire_on)
    with _SPEC_LOCK:
        spec = _SPEC_CACHE.get(spec_key)
    out_spec = None
    if spec is not None:
        bump_dispatch()
        out_spec = _dispatch_phase2(spec, mesh, transport, False,
                                    skey, svalue, counts_local,
                                    stats_local)
    SyncStats.bump()   # the op's ONE round-trip: the count matrix
    from ..obs import get_tracer
    with get_tracer().span("shuffle.count_sync", cat="shuffle"):
        # the host pull that sizes the exchange — with a speculative
        # phase 2 in flight this overlaps device work.  The wire stats
        # ride the same sync point (a second small transfer, not a
        # second barrier).  Multi-process runs (parallel/dist.py) route
        # through host_pull (the count matrix spans non-addressable
        # devices there) under the collective watchdog — a dead peer
        # turns this, the op's one mandatory barrier, into a bounded
        # PeerLostError instead of an unbounded stall
        from . import dist as _dist

        def _pull():
            cm = _dist.host_pull(counts_local).reshape(nprocs, nprocs)
            sm = (_dist.host_pull(stats_local).reshape(nprocs, nprocs, 4)
                  if stats_local is not None else None)
            return cm, sm

        counts_mat, stats_mat = _dist.guard_call("count_sync", _pull)
        # straggler attribution (obs/fleetobs): hand the per-dest row
        # totals to the sync observer so the NEXT syncs' cause verdict
        # (data_skew vs host_slow) has the count-matrix evidence
        _dist.note_sync_rows(counts_mat)
    # round budget: pad buckets to ~the mean nonzero bucket, not the max —
    # under key skew (RMAT hubs) the max bucket is far above the mean and
    # single-round padding would inflate the exchanged volume by that
    # ratio.  Up to _MAX_ROUNDS rounds of [P, B] each (uniform data stays
    # one round since mean == max).  The wire planner then tightens the
    # schedule (tier ladder) and picks the pack widths — ONE planning
    # step shared with the fused tier (wire.plan_from_pull)
    plan, kvrange, bmax_raw, nmax_out, new_counts = _wire.plan_from_pull(
        skv.key, skv.value, counts_mat, stats_mat, wire_on, elig)
    if out_spec is not None and _wire.plan_holds(spec, bmax_raw,
                                                 nmax_out, kvrange):
        # speculation holds: no row would have overflowed a bucket
        # window or an output shard, and a cached pack width still
        # round-trips the fresh ranges — keep the already-running result
        out_k, out_v = out_spec
        sp.set(speculative=True)
        # a grossly over-sized speculation right-sizes the cache for
        # next time, and a plan-TAG mismatch migrates the entry (a raw
        # plan cached from a wide first run must not pin compressible
        # repeats to full-width bytes forever); padding/stats below
        # reflect the plan that RAN
        with _SPEC_LOCK:
            _SPEC_CACHE[spec_key] = plan if (
                spec[0] != plan[0]
                or _wire.plan_oversized(spec, bmax_raw, nmax_out)) \
                else spec
        ran = spec
    else:
        sp.set(speculative=False)
        bump_dispatch()
        # definitive phase 2: skey/svalue are dead after — donate them
        # when the donation can actually alias (cap_out == cap; other
        # sizes would be a warned no-op).  The speculative call above
        # never donates: a failed speculation re-runs phase 2 on the
        # same inputs
        donate2 = (donate
                   and _wire.plan_cap_out(plan)
                   == skey.shape[0] // max(nprocs, 1))
        out_k, out_v = _dispatch_phase2(plan, mesh, transport, donate2,
                                        skey, svalue, counts_local,
                                        stats_local)
        with _SPEC_LOCK:
            _SPEC_CACHE[spec_key] = plan
        ran = plan

    B_eff, nrounds_eff = _wire.plan_rounds(ran)
    cap_out_eff = _wire.plan_cap_out(ran)
    # one tuple assignment: a concurrent world's exchange can interleave
    # here, but a reader then sees ONE exchange's (nrounds, bucket) pair,
    # never a torn mix (VERDICT r4 weak #7) — deprecated shim; the
    # per-call truth is the ExchangeCallStats built below
    ExchangeStats.last = (nrounds_eff, B_eff)
    stats = ExchangeCallStats(nrounds=nrounds_eff, bucket=B_eff,
                              cap_out=cap_out_eff,
                              rows=int(counts_mat.sum()),
                              speculative=out_spec is not None
                              and (out_k is out_spec[0]))
    sp.set(bucket=B_eff, nrounds=nrounds_eff, cap_out=cap_out_eff,
           rows=stats.rows)
    # byte accounting ALWAYS lands on the per-call stats (and so the
    # live metrics + request profile), whether or not a Counters object
    # rides along — a direct reshard/gather caller without counters
    # must not read as "no exchange traffic" on /metrics
    moved, pad, rowbytes = exchange_volume(skv, counts_mat,
                                           _wire.plan_slots(ran), nprocs)
    stats.sent_bytes, stats.pad_bytes = moved, pad
    if ran[0] == "wire":
        stats.wire_bytes = _wire.wire_volume(skv, counts_mat, ran)
        stats.wire_ratio = _wire.wire_ratio(moved, pad, stats.wire_bytes)
    sp.set(sent_bytes=moved, pad_bytes=pad, rowbytes=rowbytes,
           wire_bytes=stats.wire_bytes, wire_ratio=stats.wire_ratio)
    if counters is not None:
        counters.add(cssize=moved, crsize=moved, cspad=pad)
    out = ShardedKV(mesh, out_k, out_v, new_counts,
                    key_decode=skv.key_decode,
                    value_decode=skv.value_decode)
    out.exchange_stats = stats   # per-call telemetry rides the result
    # live metrics (obs/metrics.py): the same per-call numbers feed the
    # exchange byte/round counters — a direct feed, not via the span, so
    # the counters are exact even for spans the ring has already evicted
    from ..obs.metrics import record_exchange
    record_exchange(stats)
    return out


# ---------------------------------------------------------------------------
# aggregate()
# ---------------------------------------------------------------------------

def aggregate_kv(backend, mr, hash_fn: Optional[Callable]):
    """MapReduce.aggregate on the mesh backend: shard-if-needed, then
    hash-exchange.  Host byte-string data cannot shard (intern first —
    SURVEY.md §7); it stays controller-resident with a warning."""
    from ..core.runtime import Timer
    kv = mr.kv
    if hash_fn is not None and getattr(hash_fn, "host_hash", False):
        # user hash evaluated per key on the host (the C-ABI apphash and
        # python callbacks over raw key bytes, src/mapreduce.cpp:469-471):
        # partition host-side, then place the blocks on the mesh
        _aggregate_host_hash(backend, mr, hash_fn)
        return
    frame = kv.one_frame()
    ktable = vtable = None
    if isinstance(frame, KVFrame):
        frame, ktable, vtable = _intern_frame(
            frame, mesh_axis_size(backend.mesh))
    if mesh_axis_size(backend.mesh) == 1:
        # reference early-out for nprocs==1 (src/mapreduce.cpp:403-406):
        # no exchange — but a dense host frame still moves onto the device
        # so convert/reduce run the sharded (device) tier, and an already-
        # computed multi-frame concat is kept (one_frame above was not free)
        if isinstance(frame, KVFrame):
            if frame.is_dense():
                skv = shard_frame(frame, backend.mesh)
                skv.key_decode = ktable
                skv.value_decode = vtable
                _replace_kv_frames(kv, skv)
        else:
            _replace_kv_frames(kv, frame)
        return
    if isinstance(frame, KVFrame):
        skv = shard_frame(frame, backend.mesh)
        skv.key_decode = ktable
        skv.value_decode = vtable
    else:
        skv = frame  # already sharded
    t = Timer()
    try:
        out = exchange(skv, ("hash", hash_fn),
                       transport=mr.settings.all2all,
                       counters=mr.counters)
    except BaseException:
        free_if_donated(kv, skv)
        raise
    mr.counters.add(commtime=t.elapsed())
    # per-call stats (not the deprecated class attrs): concurrent MRs
    # each keep their own last_exchange
    mr.last_exchange = getattr(out, "exchange_stats", None)
    _replace_kv_frames(kv, out)


def _key_bytes_rows(col) -> list:
    """Raw per-row key bytes — what the reference's user hash receives."""
    from ..core.column import BytesColumn, ObjectColumn
    if isinstance(col, ObjectColumn):
        return col.pickles()
    if isinstance(col, BytesColumn):
        return [bytes(b) for b in col.data]
    data = np.ascontiguousarray(np.asarray(col.to_host().data))
    return [data[i].tobytes() for i in range(data.shape[0])]


def _aggregate_host_hash(backend, mr, hash_fn):
    kv = mr.kv
    P = mesh_axis_size(backend.mesh)
    frame = kv.one_frame()
    if not isinstance(frame, KVFrame):
        frame = frame.to_host()
    if len(frame) == 0:
        return
    dest = (np.asarray(hash_fn(_key_bytes_rows(frame.key)))
            .astype(np.int64) % P).astype(np.int32)
    frame, ktable, vtable = _intern_frame(frame, P)
    order = np.argsort(dest, kind="stable")
    counts = np.bincount(dest, minlength=P).astype(np.int32)
    from .sharded import shard_frame_with_counts
    skv = shard_frame_with_counts(frame.take(order), backend.mesh, counts)
    skv.key_decode = ktable
    skv.value_decode = vtable
    _replace_kv_frames(kv, skv)


def _intern_frame(frame: KVFrame, P: int = 1):
    """Byte-string or arbitrary-object KEYS and VALUES intern to u64 ids
    for the device shuffle; the id→bytes tables ride on the ShardedKV
    (SURVEY.md §7 'hard parts'; VERDICT r1 #5 for keys, r2 #4 for
    values — the reference shuffles raw bytes on both sides,
    src/mapreduce.cpp:453-473).  With P>1 the tables are DEST-SHARDED
    (ShardTables, VERDICT r4 #5): entry (id, bytes) lives in the table
    of the shard the hash exchange will route the id to, so no
    controller-global dict builds and shard d's post-aggregate output
    decodes from its own table alone."""
    from ..core.column import BytesColumn, ObjectColumn, ShardTables

    def _one(col):
        if not isinstance(col, (BytesColumn, ObjectColumn)):
            return col, None
        if P > 1:
            kind = "object" if isinstance(col, ObjectColumn) else "bytes"
            tables = ShardTables(P, kind=kind)
            return col.intern_sharded(tables), tables
        return col.intern()

    key, ktable = _one(frame.key)
    value, vtable = _one(frame.value)
    if ktable is None and vtable is None:
        return frame, None, None
    return KVFrame(key, value), ktable, vtable


def _replace_kv_frames(kv, sharded_frame):
    kv.free()
    kv._frames = [sharded_frame]
    kv.counters.mem(sharded_frame.nbytes())
    kv.nkv = len(sharded_frame)
    kv.complete_done = True
