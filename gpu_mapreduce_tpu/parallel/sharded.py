"""Sharded dataset frames: per-shard padded row blocks + valid counts.

The reference's distributed state is "each rank owns a list of pages"
(``src/keyvalue.h:83-92``).  Here each *shard* of the mesh owns a padded
block of rows inside one global ``jax.Array``:

* data arrays have global shape ``[P*cap, ...]``, sharded over mesh axis
  ``"p"`` on dim 0, so shard i's local block is rows ``[i*cap, (i+1)*cap)``;
* a host-side ``counts[P]`` records how many leading rows of each block are
  valid (the rest is padding — the price of XLA's static shapes, standing in
  for the reference's variable page fill).

Caps are rounded up to powers of two (min 8) so repeated shuffles re-use
compiled programs instead of recompiling per exact size.

``ShardedKV`` quacks enough like a ``KVFrame`` (len/nbytes/pairs/to_host)
to sit inside a ``KeyValue`` dataset as a frame; same for ``ShardedKMV``
vs ``KMVFrame``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..core.column import BytesColumn, DenseColumn
from ..core.frame import KMVFrame, KVFrame
from .mesh import mesh_axis_size, row_sharding


import threading

# one lock for all telemetry class counters in the parallel tier:
# ``-partition`` worlds run their interpreters in threads
# (oink/universe.py), so the read-modify-write bumps below would
# otherwise lose counts across concurrently-exchanging worlds
# (VERDICT r4 weak #7)
_STATS_LOCK = threading.Lock()


class SyncStats:
    """Counts controller round-trips (small device→host metadata pulls)
    in the sharded tier.  The contract (VERDICT r2 #8): each sharded op
    costs exactly ONE such sync — parity with the reference, where every
    op ends in one MPI_Allreduce (src/mapreduce.cpp:557-558); the fused
    engines skip even that inside their while_loops.  Thread-safe via
    :func:`bump` (``pulls += 1`` is not atomic under -partition worlds)."""

    pulls = 0

    @classmethod
    def bump(cls, n: int = 1):
        with _STATS_LOCK:
            cls.pulls += n

    @classmethod
    def snapshot(cls):
        return cls.pulls

    @classmethod
    def delta(cls, snap):
        return cls.pulls - snap


class ToHostStats:
    """Counts device→host frame materialisations — the instrument that
    proves device-resident iteration stays device-resident (VERDICT r1 #3:
    'no to_host inside the iteration loop, assert via a counter').
    Thread-safe via :func:`bump`."""

    kv = 0
    kmv = 0

    @classmethod
    def bump(cls, which: str):
        with _STATS_LOCK:
            setattr(cls, which, getattr(cls, which) + 1)

    @classmethod
    def snapshot(cls):
        with _STATS_LOCK:          # one consistent (kv, kmv) pair —
            return (cls.kv, cls.kmv)  # never a torn mix (r5 review)

    @classmethod
    def delta(cls, snap):
        with _STATS_LOCK:
            return (cls.kv - snap[0], cls.kmv - snap[1])


def _decode_col(table: dict, ids: np.ndarray):
    """id→key decode: the InternTable's kind (not a first-row guess)
    selects bytes vs object column — an object table may legitimately
    hold bytes rows.  decode_batch (InternTable/ShardTables) computes
    dest routing once for the whole array instead of per row."""
    from ..core.column import ObjectColumn
    if hasattr(table, "decode_batch"):
        rows = table.decode_batch(ids)
    else:
        rows = [table[int(h)] for h in ids]
    if getattr(table, "kind", "bytes") == "object":
        return ObjectColumn(rows)
    return BytesColumn(rows)


def round_cap(n: int) -> int:
    """Round a per-shard capacity up to a power of two (min 8) to bound
    the number of distinct compiled shapes."""
    cap = 8
    while cap < n:
        cap <<= 1
    return cap


def narrowest_uint(maxval: int):
    """(dtype name, itemsize) of the narrowest unsigned dtype holding
    ``maxval`` — the wire codec's width rule (parallel/wire.py), kept
    next to :func:`round_cap` so every capacity/width policy of the
    sharded tier lives in one place."""
    for name, width in (("uint8", 1), ("uint16", 2), ("uint32", 4)):
        if maxval <= (1 << (8 * width)) - 1:
            return name, width
    return "uint64", 8


def _pad_rows(arr: np.ndarray, cap: int) -> np.ndarray:
    pad = cap - arr.shape[0]
    if pad <= 0:
        return arr[:cap]
    width = ((0, pad),) + tuple((0, 0) for _ in arr.shape[1:])
    return np.pad(arr, width)


@dataclass
class ShardedKV:
    """Sharded KV frame: key/value row blocks + per-shard counts.

    ``key_decode`` (optional): id→bytes table when the keys are interned
    byte strings (the device shuffle moves u64 ids; the bytes live on the
    controller — SURVEY.md §7 "hard parts").  ``to_host`` resurrects the
    byte keys so host callbacks/printing see the original strings."""

    mesh: Mesh
    key: jax.Array        # [P*cap] or [P*cap, w]
    value: jax.Array      # [P*cap] or [P*cap, w]
    counts: np.ndarray    # host [P] int32
    key_decode: dict = None
    value_decode: dict = None   # id→bytes/object when VALUES are interned
    #                             (VERDICT r2 #4: byte values shard too)

    @property
    def nprocs(self) -> int:
        return mesh_axis_size(self.mesh)

    @property
    def cap(self) -> int:
        return self.key.shape[0] // self.nprocs

    def __len__(self) -> int:
        return int(self.counts.sum())

    @property
    def nkv(self) -> int:
        return len(self)

    def nbytes(self) -> int:
        return self.key.nbytes + self.value.nbytes

    def is_dense(self) -> bool:
        return True

    def to_host(self) -> KVFrame:
        """Compact to an exact host KVFrame (drops padding)."""
        ToHostStats.bump("kv")
        P, cap = self.nprocs, self.cap
        k = np.asarray(self.key)
        v = np.asarray(self.value)
        keep = np.concatenate([np.arange(i * cap, i * cap + int(self.counts[i]))
                               for i in range(P)]) if len(self) else \
            np.zeros(0, np.int64)
        key_col = (_decode_col(self.key_decode, k[keep])
                   if self.key_decode is not None else DenseColumn(k[keep]))
        val_col = (_decode_col(self.value_decode, v[keep])
                   if self.value_decode is not None
                   else DenseColumn(v[keep]))
        return KVFrame(key_col, val_col)

    def shard_to_host(self, p: int) -> KVFrame:
        """Host KVFrame of ONE shard's valid rows — device_get of just
        that shard's block (the HBM-budget demotion streams blocks one
        at a time; ``to_host`` would materialise the whole dataset)."""
        ToHostStats.bump("kv")
        cap = self.cap
        n = int(self.counts[p])
        k = v = None
        for sh in self.key.addressable_shards:
            if (sh.index[0].start or 0) == p * cap:
                k = np.asarray(sh.data)[:n]
                break
        for sh in self.value.addressable_shards:
            if (sh.index[0].start or 0) == p * cap:
                v = np.asarray(sh.data)[:n]
                break
        key_col = (_decode_col(self.key_decode, k)
                   if self.key_decode is not None else DenseColumn(k))
        val_col = (_decode_col(self.value_decode, v)
                   if self.value_decode is not None else DenseColumn(v))
        return KVFrame(key_col, val_col)

    def pairs(self) -> Iterator[Tuple[object, object]]:
        yield from self.to_host().pairs()

    def __repr__(self):
        return (f"ShardedKV(P={self.nprocs}, cap={self.cap}, "
                f"counts={self.counts.tolist()})")


@dataclass
class ShardedKMV:
    """Sharded KMV frame: per-shard grouped blocks.

    Per shard i: groups ``ukey[i*gcap : i*gcap+gcounts[i]]`` with value runs
    inside ``values[i*vcap : i*vcap+vcounts[i]]`` located by local
    ``voffsets`` (offsets are shard-local, i.e. relative to ``i*vcap``)."""

    mesh: Mesh
    ukey: jax.Array       # [P*gcap(, w)]
    nvalues: jax.Array    # [P*gcap] int32
    voffsets: jax.Array   # [P*gcap] int32 (shard-local)
    values: jax.Array     # [P*vcap(, w)]
    gcounts: np.ndarray   # host [P]
    vcounts: np.ndarray   # host [P]
    key_decode: dict = None   # see ShardedKV.key_decode
    value_decode: dict = None  # see ShardedKV.value_decode

    @property
    def nprocs(self) -> int:
        return mesh_axis_size(self.mesh)

    @property
    def gcap(self) -> int:
        return self.ukey.shape[0] // self.nprocs

    @property
    def vcap(self) -> int:
        return self.values.shape[0] // self.nprocs

    def __len__(self) -> int:
        return int(self.gcounts.sum())

    @property
    def nkmv(self) -> int:
        return len(self)

    @property
    def nvalues_total(self) -> int:
        return int(self.vcounts.sum())

    def nbytes(self) -> int:
        return (self.ukey.nbytes + self.nvalues.nbytes +
                self.voffsets.nbytes + self.values.nbytes)

    def is_dense(self) -> bool:
        return True

    def to_host(self) -> KMVFrame:
        """Compact to an exact host KMVFrame (vectorised ragged gather —
        the round-1 per-group python loop was a controller hot spot,
        VERDICT r1 weak #4)."""
        ToHostStats.bump("kmv")
        P, gcap, vcap = self.nprocs, self.gcap, self.vcap
        uk = np.asarray(self.ukey)
        nv = np.asarray(self.nvalues)
        vo = np.asarray(self.voffsets)
        vals = np.asarray(self.values)
        gkeep = (np.concatenate(
            [np.arange(i * gcap, i * gcap + int(self.gcounts[i]))
             for i in range(P)]) if len(self) else np.zeros(0, np.int64))
        key = uk[gkeep]
        key_col = (_decode_col(self.key_decode, key)
                   if self.key_decode is not None else None)
        nvalues = nv[gkeep].astype(np.int64)
        # global row index of each group's value run, then one ragged gather
        shard_of = gkeep // gcap
        starts = shard_of * vcap + vo[gkeep].astype(np.int64)
        offsets = np.concatenate([[0], np.cumsum(nvalues)]).astype(np.int64)
        total = int(offsets[-1])
        idx = (np.repeat(starts - offsets[:-1], nvalues)
               + np.arange(total, dtype=np.int64))
        values = vals[idx]
        val_col = (_decode_col(self.value_decode, values)
                   if self.value_decode is not None
                   else DenseColumn(values))
        return KMVFrame(key_col if key_col is not None else DenseColumn(key),
                        nvalues, offsets, val_col)

    def shard_to_host(self, p: int) -> KMVFrame:
        """Host KMVFrame of ONE shard's groups — device_get of just that
        shard's blocks (per-shard output files stream shards one at a
        time; ``to_host`` would materialise the whole dataset on the
        controller — VERDICT r3 #7)."""
        ToHostStats.bump("kmv")
        gcap, vcap = self.gcap, self.vcap
        g = int(self.gcounts[p])
        nval = int(self.vcounts[p])

        def block(arr, start, n):
            for sh in arr.addressable_shards:
                if (sh.index[0].start or 0) == start:
                    return np.asarray(sh.data)[:n]
            raise ValueError(f"shard {p} not addressable on this host")

        uk = block(self.ukey, p * gcap, g)
        nv = block(self.nvalues, p * gcap, g).astype(np.int64)
        vo = block(self.voffsets, p * gcap, g).astype(np.int64)
        vals = block(self.values, p * vcap, nval)
        offsets = np.concatenate([[0], np.cumsum(nv)]).astype(np.int64)
        total = int(offsets[-1])
        idx = (np.repeat(vo - offsets[:-1], nv)
               + np.arange(total, dtype=np.int64))
        values = vals[idx]
        key_col = (_decode_col(self.key_decode, uk)
                   if self.key_decode is not None else DenseColumn(uk))
        val_col = (_decode_col(self.value_decode, values)
                   if self.value_decode is not None
                   else DenseColumn(values))
        return KMVFrame(key_col, nv, offsets, val_col)

    def groups(self):
        yield from self.to_host().groups()

    def group_values(self, i: int):
        return self.to_host().group_values(i)

    def __repr__(self):
        return (f"ShardedKMV(P={self.nprocs}, gcap={self.gcap}, "
                f"g={len(self)}, n={self.nvalues_total})")


def shard_frame(frame: KVFrame, mesh: Mesh) -> ShardedKV:
    """Initial block distribution of a host/device KVFrame over the mesh
    (contiguous split — the analogue of 'each rank mapped its own tasks')."""
    P = mesh_axis_size(mesh)
    n = len(frame)
    per = -(-n // P) if n else 0
    starts = np.minimum(np.arange(P) * per, n)
    ends = np.minimum(starts + per, n)
    return shard_frame_with_counts(frame, mesh,
                                   (ends - starts).astype(np.int32))


def shard_frame_with_counts(frame: KVFrame, mesh: Mesh,
                            counts: np.ndarray) -> ShardedKV:
    """Place a host frame on the mesh with an EXPLICIT partition: shard i
    gets the next counts[i] consecutive rows (callers order rows first —
    the host-hash aggregate path)."""
    P = mesh_axis_size(mesh)
    k = np.asarray(frame.key.data)
    v = np.asarray(frame.value.data)
    cap = round_cap(int(counts.max()) if len(frame) else 0)
    offs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    kb, vb = [], []
    for i in range(P):
        kb.append(_pad_rows(k[offs[i]:offs[i + 1]], cap))
        vb.append(_pad_rows(v[offs[i]:offs[i + 1]], cap))
    sharding = row_sharding(mesh)
    # bounded per-device messages: at soak scale a shard block is
    # >100 MB, past what a tunneled single transfer survives (r5)
    from .mesh import device_put_chunked
    key = device_put_chunked(np.concatenate(kb), sharding)
    value = device_put_chunked(np.concatenate(vb), sharding)
    return ShardedKV(mesh, key, value, counts.astype(np.int32))
