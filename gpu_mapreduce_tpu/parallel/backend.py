"""Distributed mesh backend — sharded datasets + ICI collectives.

Replaces the reference's MPI layer (``src/irregular.*`` + direct MPI calls in
``mapreduce.cpp``): a ``jax.sharding.Mesh`` over axis ``"p"`` plays the role
of MPI_COMM_WORLD, and the shuffle/gather/broadcast ops run as XLA
collectives (SURVEY.md §5 "Distributed communication backend").

Implemented in ``shuffle.py``/``collectives.py``; this module holds the
backend object the MapReduce class dispatches to.
"""

from __future__ import annotations

from ..core.runtime import MRError


class MeshBackend:
    """Sharded execution over a jax.sharding.Mesh (axis name "p")."""

    def __init__(self, mesh):
        try:
            from .shuffle import mesh_axis_size
        except ImportError as e:  # pragma: no cover
            raise MRError(f"mesh backend unavailable: {e}") from e
        self.mesh = mesh
        self.nprocs = mesh_axis_size(mesh)
        self.me = 0

    def aggregate(self, mr, hash_fn):
        from .shuffle import aggregate_kv
        aggregate_kv(self, mr, hash_fn)

    def gather(self, mr, nprocs: int):
        from .collectives import gather_kv
        gather_kv(self, mr, nprocs)

    def broadcast(self, mr, root: int):
        from .collectives import broadcast_kv
        broadcast_kv(self, mr, root)

    def allreduce_sum(self, x):
        return x  # dataset counts are already global (controller-side)
