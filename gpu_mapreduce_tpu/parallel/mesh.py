"""Mesh construction — the framework's MPI_COMM_WORLD.

Flat form: one axis ``"p"`` of "procs" (chips); the reference's rank/size
(``MPI_Comm_rank``/``MPI_Comm_size``) become ``lax.axis_index("p")`` and
the axis size.

Multi-slice form (``make_mesh2``): the proc axis factors into
``("s", "c")`` — slice × chip — so datasets still shard by flat proc id
(row i*C+c lives on slice i, chip c) but the shuffle can route
hierarchically: ICI all-to-all within a slice first (grouping rows by
destination chip), then ONE DCN all-to-all between same-chip-index peers
across slices (shuffle._exchange_blocks).  That is the TPU analogue of
the reference's single-level MPI world (SURVEY.md §5 'multi-slice'
note; their NCCL/MPI stacks do the same hierarchical aggregation
internally)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS = "p"


def make_mesh(ndev: Optional[int] = None, devices: Optional[Sequence] = None
              ) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if ndev is not None:
        devices = devices[:ndev]
    return Mesh(np.asarray(devices), (AXIS,))


def make_mesh2(nslice: int, nchip: Optional[int] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Multi-slice mesh: devices [nslice, nchip] over axes ("s", "c")."""
    if devices is None:
        devices = jax.devices()
    if nchip is None:
        nchip = len(devices) // nslice
    devices = np.asarray(devices[:nslice * nchip]).reshape(nslice, nchip)
    return Mesh(devices, ("s", "c"))


def mesh_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def mesh_axis_size(mesh: Mesh) -> int:
    """Total proc count (product over all mesh axes)."""
    n = 1
    for a in mesh.axis_names:
        n *= int(mesh.shape[a])
    return n


def row_spec(mesh: Mesh) -> PartitionSpec:
    """PartitionSpec sharding dim 0 over ALL mesh axes (flat proc id =
    row-major (slice, chip) index)."""
    axes = mesh_axes(mesh)
    return PartitionSpec(axes[0] if len(axes) == 1 else axes)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows split over procs (axis 0 of every dataset array)."""
    return NamedSharding(mesh, row_spec(mesh))


def flat_axis_index(mesh: Mesh):
    """Inside shard_map: this shard's flat proc id (row-major over axes)."""
    axes = mesh_axes(mesh)
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * int(mesh.shape[a]) + lax.axis_index(a)
    return idx


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_map_kernels(body, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` for bodies that embed a ``pallas_call`` (the
    plan/ megafused group programs): jax has no replication rule for
    the pallas primitive, so the rep/vma check must be disabled — the
    fused bodies are plain per-shard SPMD with explicit specs, which
    is exactly the case the check waives.  Tries the pre-0.5 spelling
    first (``check_rep``), then the renamed one (``check_vma``)."""
    try:
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
    except TypeError:
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)


def h2d_chunk_bytes(default: int = 32 << 20) -> int:
    """The per-message H2D budget, with the MR_H2D_CHUNK_WORDS override
    (u32 words, ×4 bytes) — ONE parse shared by every chunked-transfer
    site so the knob cannot be honored in some paths and not others."""
    import os
    env = os.environ.get("MR_H2D_CHUNK_WORDS")
    if env is None:
        return default
    if int(env) <= 0:
        raise ValueError(f"MR_H2D_CHUNK_WORDS={env}: must be > 0")
    return int(env) * 4


def device_put_chunked(host, sharding: Optional[NamedSharding] = None,
                       chunk_bytes: int = 32 << 20):
    """``jax.device_put`` in bounded per-device messages.

    Tunneled TPU setups fail (or silently hang) on large single
    transfer messages — the r4 bench lesson, applied here to EVERY
    bulk H2D: each device's block travels as ≤``chunk_bytes`` pieces
    concatenated on its own device.  Honors the same
    ``MR_H2D_CHUNK_WORDS`` override as the ingest paths (u32 words,
    ×4 bytes).  With ``sharding=None`` the array lands on the default
    device."""
    chunk_bytes = h2d_chunk_bytes(chunk_bytes)
    host = np.asarray(host)
    if host.ndim == 0 or host.nbytes <= chunk_bytes:
        return jax.device_put(host, sharding) if sharding is not None \
            else jax.device_put(host)
    import jax.numpy as jnp

    def put_block(block, dev):
        # dev=None → uncommitted puts on the configured default device
        # (committing to devices()[0] would flip placement semantics on
        # a size threshold the caller never sees — r5 review)
        put = (jax.device_put if dev is None
               else lambda x: jax.device_put(x, dev))
        rowbytes = max(1, int(block.nbytes // max(1, block.shape[0])))
        step = max(1, chunk_bytes // rowbytes)
        if block.shape[0] <= step:
            return put(block)
        parts = [put(block[o:o + step])
                 for o in range(0, block.shape[0], step)]
        return jnp.concatenate(parts)

    if sharding is None:
        return put_block(host, None)
    dmap = sharding.addressable_devices_indices_map(host.shape)
    shards = [put_block(np.ascontiguousarray(host[idx]), dev)
              for dev, idx in dmap.items()]
    return jax.make_array_from_single_device_arrays(
        host.shape, sharding, shards)


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   local_device_ids: Optional[Sequence[int]] = None) -> int:
    """Join JAX's multi-controller runtime so ``make_mesh()`` spans every
    host's chips — the reference's ``MPI_Init`` for multi-node runs (its
    NCCL/MPI backend scales the same way; SURVEY.md §5 "distributed
    communication backend").  Call once per process BEFORE any other jax
    use; args default to the cluster auto-detection
    (``jax.distributed.initialize``'s env/cloud discovery).  Returns
    this process's index.

    What is and isn't multi-host ready: the SPMD compute paths — the
    exchange collectives, the fused graph engines, per-shard output —
    address only LOCAL shards (``addressable_shards`` /
    ``addressable_devices_indices_map`` everywhere), so each process
    computes and writes its own hosts' slices, with DCN routes via
    ``make_mesh2``.  Dest-sharded decode tables (``ShardTables``) mean
    a process only needs the tables of shards it writes.  Host-side
    INGESTION is per-shard in *placement* but not yet in *reads*: the
    generic ``map_files`` runs every callback in the calling process —
    a multi-controller deployment should hand each process its own
    file slice.  ``to_host`` of the whole dataset and host per-pair
    callbacks stay single-controller conveniences."""
    kw = {}
    if coordinator is not None:
        kw["coordinator_address"] = coordinator
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    if local_device_ids is not None:
        kw["local_device_ids"] = list(local_device_ids)
    jax.distributed.initialize(**kw)
    return jax.process_index()
