"""Mesh construction — the framework's MPI_COMM_WORLD.

One flat axis ``"p"`` of "procs" (chips).  The reference's rank/size
(``MPI_Comm_rank``/``MPI_Comm_size``) become ``lax.axis_index("p")`` and the
axis size; multi-slice TPU systems can later map ``p`` to (slice, chip) so
collectives ride ICI within a slice and DCN across (SURVEY.md §5)."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS = "p"


def make_mesh(ndev: Optional[int] = None, devices: Optional[Sequence] = None
              ) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if ndev is not None:
        devices = devices[:ndev]
    return Mesh(np.asarray(devices), (AXIS,))


def mesh_axis_size(mesh: Mesh) -> int:
    return int(mesh.shape[AXIS])


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows split over procs (axis 0 of every dataset array)."""
    return NamedSharding(mesh, PartitionSpec(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
