"""Mesh construction — the framework's MPI_COMM_WORLD.

Flat form: one axis ``"p"`` of "procs" (chips); the reference's rank/size
(``MPI_Comm_rank``/``MPI_Comm_size``) become ``lax.axis_index("p")`` and
the axis size.

Multi-slice form (``make_mesh2``): the proc axis factors into
``("s", "c")`` — slice × chip — so datasets still shard by flat proc id
(row i*C+c lives on slice i, chip c) but the shuffle can route
hierarchically: ICI all-to-all within a slice first (grouping rows by
destination chip), then ONE DCN all-to-all between same-chip-index peers
across slices (shuffle._exchange_blocks).  That is the TPU analogue of
the reference's single-level MPI world (SURVEY.md §5 'multi-slice'
note; their NCCL/MPI stacks do the same hierarchical aggregation
internally)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS = "p"


def make_mesh(ndev: Optional[int] = None, devices: Optional[Sequence] = None
              ) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if ndev is not None:
        devices = devices[:ndev]
    return Mesh(np.asarray(devices), (AXIS,))


def make_mesh2(nslice: int, nchip: Optional[int] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Multi-slice mesh: devices [nslice, nchip] over axes ("s", "c")."""
    if devices is None:
        devices = jax.devices()
    if nchip is None:
        nchip = len(devices) // nslice
    devices = np.asarray(devices[:nslice * nchip]).reshape(nslice, nchip)
    return Mesh(devices, ("s", "c"))


def mesh_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def mesh_axis_size(mesh: Mesh) -> int:
    """Total proc count (product over all mesh axes)."""
    n = 1
    for a in mesh.axis_names:
        n *= int(mesh.shape[a])
    return n


def row_spec(mesh: Mesh) -> PartitionSpec:
    """PartitionSpec sharding dim 0 over ALL mesh axes (flat proc id =
    row-major (slice, chip) index)."""
    axes = mesh_axes(mesh)
    return PartitionSpec(axes[0] if len(axes) == 1 else axes)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows split over procs (axis 0 of every dataset array)."""
    return NamedSharding(mesh, row_spec(mesh))


def flat_axis_index(mesh: Mesh):
    """Inside shard_map: this shard's flat proc id (row-major over axes)."""
    axes = mesh_axes(mesh)
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * int(mesh.shape[a]) + lax.axis_index(a)
    return idx


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
