"""gather / broadcast over the mesh.

* gather(n): funnel every shard's rows onto the first n shards — the
  reference's rank-matched Send/Recv funnel (``src/mapreduce.cpp:893-1036``)
  becomes one exchange with a constant destination per shard.
* broadcast(root): every shard ends up with a copy of root's rows — the
  reference's per-page MPI_Bcast (``src/mapreduce.cpp:569-623``) becomes an
  ``all_gather`` + select.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.frame import KVFrame
from .mesh import mesh_axes, row_sharding, row_spec
from .sharded import ShardedKV, shard_frame
from .shuffle import exchange, free_if_donated, _replace_kv_frames


def _ensure_sharded(backend, mr):
    frame = mr.kv.one_frame()
    if isinstance(frame, KVFrame):
        if not frame.is_dense():
            return None
        return shard_frame(frame, backend.mesh)
    return frame


def gather_kv(backend, mr, nprocs: int):
    skv = _ensure_sharded(backend, mr)
    if skv is None:
        return  # host-resident data is already "gathered"
    n = min(nprocs, backend.nprocs)
    # shard i → i % n: the reference's exact funnel layout ("lo procs
    # recv from hi procs with same ID % numprocs",
    # src/mapreduce.cpp:919-928)
    try:
        out = exchange(skv, ("fixed_mod", n),
                       transport=mr.settings.all2all, counters=mr.counters)
    except BaseException:
        # donation may have consumed an installed frame: leave a clean
        # empty dataset, not deleted buffers (shuffle.free_if_donated)
        free_if_donated(mr.kv, skv)
        raise
    # per-call stats like aggregate's: gather/scrunch exchanges were
    # invisible to mr.last_exchange (the bench --wire A/B reads it)
    mr.last_exchange = getattr(out, "exchange_stats", None)
    _replace_kv_frames(mr.kv, out)


@functools.lru_cache(maxsize=None)
def _broadcast_jit(mesh, root: int):
    spec = row_spec(mesh)
    axes = mesh_axes(mesh)
    ax = axes[0] if len(axes) == 1 else axes

    @jax.jit
    def run(key, value):
        def body(k, v):
            allk = lax.all_gather(k, ax)     # [P, cap, ...]
            allv = lax.all_gather(v, ax)
            return allk[root], allv[root]
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec),
                             out_specs=(spec, spec))(key, value)

    return run


def broadcast_kv(backend, mr, root: int):
    skv = _ensure_sharded(backend, mr)
    if skv is None:
        return
    mesh = skv.mesh
    k, v = _broadcast_jit(mesh, root)(skv.key, skv.value)
    counts = np.full(backend.nprocs, skv.counts[root], np.int32)
    rowbytes = (skv.key.dtype.itemsize *
                (skv.key.shape[-1] if skv.key.ndim > 1 else 1) +
                skv.value.dtype.itemsize *
                (skv.value.shape[-1] if skv.value.ndim > 1 else 1))
    moved = int(skv.counts[root]) * (backend.nprocs - 1) * rowbytes
    mr.counters.add(cssize=moved, crsize=moved)
    _replace_kv_frames(mr.kv, ShardedKV(mesh, k, v, counts,
                                        key_decode=skv.key_decode,
                                        value_decode=skv.value_decode))
