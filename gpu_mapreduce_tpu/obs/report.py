"""Per-op aggregation + table formatting over span events.

The one summarizer every consumer shares: ``mr.stats()["ops"]``,
``scripts/trace_view.py``, bench's detail record and soak's end-of-run
table all call :func:`aggregate_ops` / :func:`per_op_table`.
"""

from __future__ import annotations

from typing import Dict, List

_BYTE_KEYS = ("shuffle_sent_bytes", "shuffle_pad_bytes",
              "spill_write_bytes", "spill_read_bytes")


def aggregate_ops(events: List[dict]) -> Dict[str, dict]:
    """events → {span name: {count, total_s, max_s, <byte sums>}} —
    sorted by total time descending."""
    agg: Dict[str, dict] = {}
    for ev in events:
        name = ev.get("name", "?")
        dur_s = float(ev.get("dur", 0.0)) / 1e6
        row = agg.get(name)
        if row is None:
            row = agg[name] = {"count": 0, "total_s": 0.0, "max_s": 0.0}
        row["count"] += 1
        row["total_s"] += dur_s
        if dur_s > row["max_s"]:
            row["max_s"] = dur_s
        args = ev.get("args") or {}
        for k in _BYTE_KEYS:
            v = args.get(k)
            if v:
                row[k] = row.get(k, 0) + int(v)
    for row in agg.values():
        row["total_s"] = round(row["total_s"], 6)
        row["max_s"] = round(row["max_s"], 6)
    return dict(sorted(agg.items(),
                       key=lambda kv: -kv[1]["total_s"]))


def _mb(n) -> str:
    return f"{n / (1 << 20):.3g}" if n else "-"


def per_op_table(events: List[dict]) -> str:
    """A printable per-op time/bytes table."""
    agg = aggregate_ops(events)
    if not agg:
        return "(no trace events)"
    rows = [("op", "count", "total_s", "max_s",
             "sent_Mb", "pad_Mb", "spill_w_Mb", "spill_r_Mb")]
    for name, r in agg.items():
        rows.append((name, str(r["count"]), f"{r['total_s']:.4f}",
                     f"{r['max_s']:.4f}",
                     _mb(r.get("shuffle_sent_bytes", 0)),
                     _mb(r.get("shuffle_pad_bytes", 0)),
                     _mb(r.get("spill_write_bytes", 0)),
                     _mb(r.get("spill_read_bytes", 0))))
    widths = [max(len(row[i]) for row in rows) for i in range(len(rows[0]))]
    lines = []
    for i, row in enumerate(rows):
        lines.append("  ".join(c.ljust(w) if j == 0 else c.rjust(w)
                               for j, (c, w) in enumerate(zip(row, widths))))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
