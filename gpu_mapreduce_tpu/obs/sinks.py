"""Span-event sinks + Chrome trace-event export.

Events arrive already in Chrome trace-event form (tracer.Span.event):
complete events (``ph: "X"``) with ``ts``/``dur`` in microseconds.  The
JSONL file is therefore self-describing — one event per line — and
:func:`chrome_trace` only wraps the list so Perfetto / chrome://tracing
load it directly.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Callable, List, Optional

from ..utils.env import env_knob


def _jsonable(x):
    """json.dumps default= hook: numpy scalars/arrays, bytes, anything
    else degrades to str — a trace line must never raise."""
    try:
        import numpy as np
        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.ndarray):
            return x.tolist()
    except Exception:
        pass
    if isinstance(x, bytes):
        return x.decode("utf-8", errors="replace")
    return str(x)


def dumps(ev: dict) -> str:
    return json.dumps(ev, default=_jsonable)


class RingSink:
    """Bounded in-memory buffer.  Locked: a snapshot (list()) taken
    while another thread appends would raise 'deque mutated during
    iteration' — concurrent ``-partition`` worlds emit while a reader
    calls ``mr.stats()``/``dump_trace``."""

    def __init__(self, maxlen: int = 65536):
        self.events: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def emit(self, ev: dict) -> None:
        with self._lock:
            self.events.append(ev)

    def snapshot(self) -> list:
        with self._lock:
            return list(self.events)

    def clear(self) -> None:
        with self._lock:
            self.events.clear()


class JsonlSink:
    """One JSON event per line, flushed per event so a killed run still
    leaves a readable trace.

    Bounded by size-based rotation so a multi-hour soak cannot fill the
    disk: past ``max_bytes`` (``MRTPU_TRACE_MAX_MB``; 0/unset =
    unbounded) the file rotates to ``path.1`` .. ``path.<keep>``
    (``MRTPU_TRACE_KEEP``, default 3, oldest dropped) and a fresh
    ``path`` opens.  Each rotation bumps the
    ``mrtpu_trace_rotated_total`` metrics counter."""

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 keep: Optional[int] = None):
        self.path = path
        if max_bytes is None:
            # env_knob: a typo'd knob warns and falls back — it must
            # not crash the run the trace was meant to observe
            mb = env_knob("MRTPU_TRACE_MAX_MB", float, 0.0)
            max_bytes = int(mb * (1 << 20)) if mb > 0 else 0
        self.max_bytes = max_bytes
        if keep is None:
            keep = env_knob("MRTPU_TRACE_KEEP", int, 3)
        self.keep = max(1, int(keep))
        self.rotations = 0
        self._f = open(path, "w")
        self._lock = threading.Lock()

    def emit(self, ev: dict) -> None:
        line = dumps(ev)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()
            if self.max_bytes and self._f.tell() >= self.max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        """Shift path.(i) → path.(i+1), current → path.1, reopen fresh
        (caller holds the lock).  A rotation failure (permissions, a
        vanished directory) keeps writing to the current file — a trace
        must degrade, not raise into the traced op — and DISABLES
        further rotation: retrying on every emit would pay a close/open
        per span and inflate the rotation counter while rotating
        nothing."""
        try:
            self._f.close()
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            self.max_bytes = 0            # broken: back to unbounded
            self._reopen()
            return
        self._reopen()                    # fresh file (rename moved it)
        self.rotations += 1
        from .metrics import note_trace_rotated
        note_trace_rotated()

    def _reopen(self) -> None:
        """Reopen the live file after a rotation attempt.  If even that
        fails (directory vanished, ENOSPC at create), the sink goes
        inert on /dev/null rather than raising out of emit() — a
        raising sink gets dropped by the tracer and the rest of a
        multi-hour run would leave no trace at all."""
        try:
            self._f = open(self.path, "a")
        except OSError:
            self.max_bytes = 0
            self._f = open(os.devnull, "w")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class CallbackSink:
    """Adapter: any ``fn(event_dict)`` as a sink."""

    def __init__(self, fn: Callable[[dict], None]):
        self.fn = fn

    def emit(self, ev: dict) -> None:
        self.fn(ev)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

def chrome_trace(events: List[dict]) -> dict:
    """Wrap span events as a Chrome trace-event JSON object (the
    Perfetto-loadable envelope).  Events already carry ph/ts/dur/pid/tid;
    non-serializable args are scrubbed here."""
    return {"traceEvents": json.loads(json.dumps(list(events),
                                                 default=_jsonable)),
            "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, events: List[dict]) -> int:
    """Write the Chrome trace JSON; returns the event count."""
    doc = chrome_trace(events)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])


def read_jsonl(path: str) -> List[dict]:
    """Load a JSONL trace file (skipping any truncated final line from a
    killed run)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
