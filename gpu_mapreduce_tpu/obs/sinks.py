"""Span-event sinks + Chrome trace-event export.

Events arrive already in Chrome trace-event form (tracer.Span.event):
complete events (``ph: "X"``) with ``ts``/``dur`` in microseconds.  The
JSONL file is therefore self-describing — one event per line — and
:func:`chrome_trace` only wraps the list so Perfetto / chrome://tracing
load it directly.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Callable, List


def _jsonable(x):
    """json.dumps default= hook: numpy scalars/arrays, bytes, anything
    else degrades to str — a trace line must never raise."""
    try:
        import numpy as np
        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.ndarray):
            return x.tolist()
    except Exception:
        pass
    if isinstance(x, bytes):
        return x.decode("utf-8", errors="replace")
    return str(x)


def dumps(ev: dict) -> str:
    return json.dumps(ev, default=_jsonable)


class RingSink:
    """Bounded in-memory buffer.  Locked: a snapshot (list()) taken
    while another thread appends would raise 'deque mutated during
    iteration' — concurrent ``-partition`` worlds emit while a reader
    calls ``mr.stats()``/``dump_trace``."""

    def __init__(self, maxlen: int = 65536):
        self.events: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def emit(self, ev: dict) -> None:
        with self._lock:
            self.events.append(ev)

    def snapshot(self) -> list:
        with self._lock:
            return list(self.events)

    def clear(self) -> None:
        with self._lock:
            self.events.clear()


class JsonlSink:
    """One JSON event per line, flushed per event so a killed run still
    leaves a readable trace."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "w")
        self._lock = threading.Lock()

    def emit(self, ev: dict) -> None:
        line = dumps(ev)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class CallbackSink:
    """Adapter: any ``fn(event_dict)`` as a sink."""

    def __init__(self, fn: Callable[[dict], None]):
        self.fn = fn

    def emit(self, ev: dict) -> None:
        self.fn(ev)


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

def chrome_trace(events: List[dict]) -> dict:
    """Wrap span events as a Chrome trace-event JSON object (the
    Perfetto-loadable envelope).  Events already carry ph/ts/dur/pid/tid;
    non-serializable args are scrubbed here."""
    return {"traceEvents": json.loads(json.dumps(list(events),
                                                 default=_jsonable)),
            "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, events: List[dict]) -> int:
    """Write the Chrome trace JSON; returns the event count."""
    doc = chrome_trace(events)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])


def read_jsonl(path: str) -> List[dict]:
    """Load a JSONL trace file (skipping any truncated final line from a
    killed run)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
