"""Observability: structured tracing + metrics for every layer.

The reference exposes runtime behavior only as printf-style reports
(``kv_stats``/``cummulative_stats``, ``src/mapreduce.cpp:2937-3066``).
This package is the machine-readable twin: a thread-safe tracer with
nested spans that every layer reports into (MR ops in
``core/mapreduce.py``, collectives in ``parallel/shuffle.py``, H2D
staging in ``parallel/ingest.py``, script commands in
``oink/script.py``), pluggable sinks (in-memory ring, JSONL file,
callbacks), a Chrome trace-event (Perfetto-loadable) exporter, and a
per-op summarizer.

Enable via ``MRTPU_TRACE=/path/trace.jsonl``, ``MapReduce(trace=...)``,
or ``get_tracer().enable()``.  When disabled, ``tracer.span()`` returns
a shared no-op singleton — zero allocation, zero per-op cost.

See ``doc/observability.md`` for the span model and Perfetto how-to.
"""

from .tracer import (NULL_SPAN, Span, Tracer, configure_from_env,
                     get_tracer)
from .sinks import (CallbackSink, JsonlSink, RingSink, chrome_trace,
                    read_jsonl, write_chrome_trace)
from .report import aggregate_ops, per_op_table

__all__ = [
    "Tracer", "Span", "NULL_SPAN", "get_tracer", "configure_from_env",
    "RingSink", "JsonlSink", "CallbackSink",
    "chrome_trace", "write_chrome_trace", "read_jsonl",
    "aggregate_ops", "per_op_table",
]
