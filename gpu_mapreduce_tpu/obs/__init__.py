"""Observability: structured tracing + live metrics for every layer.

The reference exposes runtime behavior only as printf-style reports
(``kv_stats``/``cummulative_stats``, ``src/mapreduce.cpp:2937-3066``).
This package is the machine-readable twin, in two halves:

* **tracing** (PR 1, post-hoc): a thread-safe tracer with nested spans
  that every layer reports into (MR ops in ``core/mapreduce.py``,
  collectives in ``parallel/shuffle.py``, H2D staging in
  ``parallel/ingest.py``, script commands in ``oink/script.py``),
  pluggable sinks (in-memory ring, size-rotated JSONL file, callbacks),
  a Chrome trace-event (Perfetto-loadable) exporter, and a per-op
  summarizer.
* **metrics** (PR 3, live): a thread-safe registry of labeled
  counters/gauges/histograms fed automatically from the tracer
  (``metrics.py``), exposed via ``mr.stats()["metrics"]``, a Prometheus
  endpoint (``httpd.py``, ``MRTPU_METRICS_PORT``) and periodic JSONL
  snapshots — plus a flight recorder (``flight.py``) that dumps a
  forensic artifact on unhandled exceptions or SIGUSR1.

Enable tracing via ``MRTPU_TRACE=/path/trace.jsonl``,
``MapReduce(trace=...)``, or ``get_tracer().enable()``.  When disabled,
``tracer.span()`` returns a shared no-op singleton — zero allocation,
zero per-op cost.

See ``doc/observability.md`` for the span model, the metric catalog and
the Perfetto how-to.
"""

from .tracer import (NULL_SPAN, Span, Tracer, configure_from_env,
                     get_tracer)
from .sinks import (CallbackSink, JsonlSink, RingSink, chrome_trace,
                    read_jsonl, write_chrome_trace)
from .report import aggregate_ops, per_op_table
from .metrics import MetricsRegistry, enable_metrics, get_registry
from .context import (RequestAccount, current_trace_id, new_trace_id,
                      request_scope)

__all__ = [
    "Tracer", "Span", "NULL_SPAN", "get_tracer", "configure_from_env",
    "RingSink", "JsonlSink", "CallbackSink",
    "chrome_trace", "write_chrome_trace", "read_jsonl",
    "aggregate_ops", "per_op_table",
    "MetricsRegistry", "get_registry", "enable_metrics",
    "RequestAccount", "request_scope", "current_trace_id",
    "new_trace_id",
]

# apply MRTPU_METRICS_PORT / MRTPU_METRICS_SNAP / MRTPU_FLIGHT once the
# package is first imported (every entry point that builds a MapReduce
# gets here); never raises
from .metrics import configure_from_env as _metrics_env
_metrics_env()
