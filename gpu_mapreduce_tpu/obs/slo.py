"""Tenant SLO engine: declarative objectives, multi-window burn rates.

The serve/ daemon already exports per-tenant session counters and a
latency histogram (``mrtpu_serve_sessions_total{tenant,status}``,
``mrtpu_serve_session_seconds{tenant,status}``) — but an operator
watching raw counters has to do the error-budget arithmetic by hand.
This module closes the loop:

* **objectives** are declared in ``MRTPU_SLO`` (or programmatically via
  :func:`configure`)::

      MRTPU_SLO="tenant=*;p99_ms=5000;err_pct=1"
      MRTPU_SLO="tenant=acme;p99_ms=2000;err_pct=0.5;windows=300,3600|tenant=*;err_pct=5"

  ``tenant=*`` matches every tenant without a more specific objective.
  ``p99_ms`` means "99% of sessions complete under this"; its error
  budget is the remaining 1%.  ``err_pct`` is the failed-session
  budget.  ``windows`` (seconds, comma-separated; default 300,3600)
  are the burn-rate evaluation windows.

* **burn rate** = (budget consumed in a window) / (budget available
  for that window): 1.0 means exactly on budget, 10 means the budget
  burns 10× too fast.  Evaluated per tenant per window from DELTAS of
  the metrics-registry counters — the engine keeps a ring of periodic
  registry snapshots, so it composes with any feeder of those metrics,
  not just the in-process daemon.  Latency burn uses the histogram's
  bucket resolution (a threshold between boundaries rounds UP to the
  next bucket edge — conservative: never under-reports slowness).

* **exposure**: ``mrtpu_slo_burn_ratio{tenant,window}`` gauges
  (refreshed at scrape time via the obs/metrics collector), the serve/
  daemon's ``GET /v1/slo``, and :meth:`SLOEngine.snapshot`.

* **burn alerts**: when a tenant burns >``MRTPU_SLO_BURN`` (default 1)
  in EVERY window of its objective — the classic multi-window AND that
  filters blips — the engine records an alert, bumps
  ``mrtpu_slo_alerts_total{tenant}`` and ARMS the flight recorder
  (obs/flight.py), so the forensic ring is already collecting when the
  operator comes looking.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

DEFAULT_WINDOWS = (300.0, 3600.0)

_SESSIONS_METRIC = "mrtpu_serve_sessions_total"
_LATENCY_METRIC = "mrtpu_serve_session_seconds"


class SLOObjective:
    """One declarative objective: a tenant selector plus latency and/or
    error-rate targets over a set of burn windows."""

    __slots__ = ("tenant", "p99_ms", "err_pct", "windows")

    def __init__(self, tenant: str = "*", p99_ms: Optional[float] = None,
                 err_pct: Optional[float] = None,
                 windows: Tuple[float, ...] = DEFAULT_WINDOWS):
        if p99_ms is None and err_pct is None:
            raise ValueError("SLO objective needs p99_ms and/or err_pct")
        if p99_ms is not None and p99_ms <= 0:
            raise ValueError(f"p99_ms must be > 0, got {p99_ms}")
        if err_pct is not None and not 0 < err_pct <= 100:
            raise ValueError(f"err_pct must be in (0, 100], got {err_pct}")
        if not windows:
            raise ValueError("SLO objective needs at least one window")
        self.tenant = tenant
        self.p99_ms = p99_ms
        self.err_pct = err_pct
        self.windows = tuple(sorted(float(w) for w in windows))

    def describe(self) -> dict:
        return {"tenant": self.tenant, "p99_ms": self.p99_ms,
                "err_pct": self.err_pct, "windows": list(self.windows)}


def parse_slo(text: str) -> List[SLOObjective]:
    """``"tenant=*;p99_ms=5000;err_pct=1|tenant=acme;..."`` →
    objectives.  Unknown fields raise (→ one stderr warning via
    :func:`get_engine`) — a typo'd knob silently watching nothing would
    be the worst failure mode for an alerting layer."""
    out = []
    for spec in text.split("|"):
        spec = spec.strip()
        if not spec:
            continue
        fields: Dict[str, str] = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad SLO field {part!r} (need k=v)")
            k, v = part.split("=", 1)
            fields[k.strip()] = v.strip()
        unknown = set(fields) - {"tenant", "p99_ms", "err_pct", "windows"}
        if unknown:
            raise ValueError(f"unknown SLO fields {sorted(unknown)} "
                             f"(known: tenant, p99_ms, err_pct, windows)")
        windows = DEFAULT_WINDOWS
        if "windows" in fields:
            windows = tuple(float(w) for w in
                            fields["windows"].split(",") if w.strip())
        out.append(SLOObjective(
            tenant=fields.get("tenant", "*"),
            p99_ms=float(fields["p99_ms"]) if "p99_ms" in fields
            else None,
            err_pct=float(fields["err_pct"]) if "err_pct" in fields
            else None,
            windows=windows))
    return out


def _bucket_slow_count(sample: dict, threshold_s: float) -> int:
    """Sessions in one histogram child slower than ``threshold_s``:
    total count minus the cumulative count of the smallest bucket edge
    ≥ the threshold (bucket resolution; conservative)."""
    best_le, best_cum = None, None
    for le, cum in sample.get("buckets", {}).items():
        edge = float("inf") if le == "+Inf" else float(le)
        if edge >= threshold_s and (best_le is None or edge < best_le):
            best_le, best_cum = edge, cum
    if best_cum is None:
        return 0
    return max(0, int(sample.get("count", 0)) - int(best_cum))


class SLOEngine:
    """Snapshot ring + burn-rate evaluator + alert edge detector."""

    def __init__(self, objectives: List[SLOObjective]):
        self.objectives = list(objectives)
        self._lock = threading.Lock()
        self._snaps: List[tuple] = []       # (ts, {tenant: counts})
        self._last_tick = 0.0
        self._burn: Dict[str, Dict[str, float]] = {}
        self._firing: Dict[str, dict] = {}  # tenant → active alert
        self.alerts: List[dict] = []        # history (bounded)
        self._t0 = time.time()

    # -- objective lookup --------------------------------------------------
    def objective_for(self, tenant: str) -> Optional[SLOObjective]:
        """Most specific objective: exact tenant match beats ``*``."""
        fallback = None
        for obj in self.objectives:
            if obj.tenant == tenant:
                return obj
            if obj.tenant == "*":
                fallback = fallback or obj
        return fallback

    # -- registry reading --------------------------------------------------
    def _read(self, reg) -> Dict[str, dict]:
        """Per-tenant cumulative counts from the registry's serve
        metrics, WITHOUT running collectors (this runs inside one):
        total/failed sessions plus slow counts for every latency
        threshold an objective declares."""
        thresholds = sorted({o.p99_ms / 1000.0 for o in self.objectives
                             if o.p99_ms is not None})
        out: Dict[str, dict] = {}

        def row(tenant: str) -> dict:
            r = out.get(tenant)
            if r is None:
                r = out[tenant] = {"total": 0, "failed": 0,
                                   "slow": {t: 0 for t in thresholds}}
            return r

        sess = reg._metrics.get(_SESSIONS_METRIC)
        if sess is not None:
            for s in sess.samples():
                lab = s["labels"]
                r = row(lab.get("tenant", "default"))
                n = int(s["value"])
                r["total"] += n
                if lab.get("status") == "failed":
                    r["failed"] += n
        lat = reg._metrics.get(_LATENCY_METRIC)
        if lat is not None and thresholds:
            for s in lat.samples():
                r = row(s["labels"].get("tenant", "default"))
                for t in thresholds:
                    r["slow"][t] += _bucket_slow_count(s, t)
        return out

    # -- evaluation --------------------------------------------------------
    def tick(self, now: Optional[float] = None, reg=None,
             force: bool = False) -> Dict[str, Dict[str, float]]:
        """Snapshot the registry and re-evaluate every objective.
        Rate-limited (a tenth of the shortest window, ≥0.5 s) so scrape
        storms don't grow the ring; ``force`` and an explicit ``now``
        bypass it (tests drive synthetic clocks)."""
        if not self.objectives:
            return {}
        if reg is None:
            from .metrics import get_registry
            reg = get_registry()
        t = time.time() if now is None else now
        min_w = min(w for o in self.objectives for w in o.windows)
        with self._lock:
            if not force and now is None and \
                    t - self._last_tick < max(0.5, min_w / 10.0):
                return dict(self._burn)
            self._last_tick = t
        snap = self._read(reg)
        max_w = max(w for o in self.objectives for w in o.windows)
        with self._lock:
            self._snaps.append((t, snap))
            # keep 1.5× the longest window of history, min 8 entries
            cutoff = t - max_w * 1.5
            while len(self._snaps) > 8 and self._snaps[0][0] < cutoff:
                self._snaps.pop(0)
            snaps = list(self._snaps)
        burn = self._evaluate(t, snaps)
        self._export(reg, burn)
        self._alerting(t, burn)
        with self._lock:
            self._burn = burn
        return burn

    def _baseline(self, snaps, t: float, window: float) -> dict:
        """The newest snapshot at or before ``t - window``.  A young
        engine (no snapshot that old) uses zero — all observed traffic
        counts against the window, which over-reports burn briefly
        rather than under-reporting it."""
        base: dict = {}
        for ts, snap in snaps:
            if ts <= t - window:
                base = snap
            else:
                break
        return base

    def _evaluate(self, t: float, snaps) -> Dict[str, Dict[str, float]]:
        cur = snaps[-1][1] if snaps else {}
        burn: Dict[str, Dict[str, float]] = {}
        for tenant, row in cur.items():
            obj = self.objective_for(tenant)
            if obj is None:
                continue
            per = burn.setdefault(tenant, {})
            for w in obj.windows:
                base = self._baseline(snaps, t, w).get(tenant, {})
                d_total = row["total"] - base.get("total", 0)
                if d_total <= 0:
                    per[f"{int(w)}s"] = 0.0
                    continue
                b = 0.0
                if obj.err_pct is not None:
                    d_failed = row["failed"] - base.get("failed", 0)
                    b = max(b, (d_failed / d_total)
                            / (obj.err_pct / 100.0))
                if obj.p99_ms is not None:
                    thr = obj.p99_ms / 1000.0
                    d_slow = row["slow"].get(thr, 0) \
                        - base.get("slow", {}).get(thr, 0)
                    b = max(b, (d_slow / d_total) / 0.01)
                per[f"{int(w)}s"] = round(b, 4)
        return burn

    def _export(self, reg, burn) -> None:
        try:
            g = reg.gauge("mrtpu_slo_burn_ratio",
                          "SLO error-budget burn rate per tenant and "
                          "evaluation window (1 = exactly on budget)",
                          ("tenant", "window"))
            for tenant, per in burn.items():
                for window, b in per.items():
                    g.set(b, tenant=tenant, window=window)
        except Exception:
            pass

    def _alerting(self, t: float, burn) -> None:
        """Multi-window AND edge detection; a rising edge arms the
        flight recorder so evidence collection starts BEFORE anyone
        investigates."""
        from ..utils.env import env_knob
        thresh = env_knob("MRTPU_SLO_BURN", float, 1.0)
        for tenant, per in burn.items():
            obj = self.objective_for(tenant)
            if obj is None or not per:
                continue
            firing = all(per.get(f"{int(w)}s", 0.0) > thresh
                         for w in obj.windows)
            with self._lock:
                was = tenant in self._firing
                if firing and not was:
                    alert = {"tenant": tenant,
                             "utc": time.strftime(
                                 "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t)),
                             "burn": dict(per),
                             "objective": obj.describe()}
                    self._firing[tenant] = alert
                    self.alerts.append(alert)
                    del self.alerts[:-64]
                elif not firing and was:
                    del self._firing[tenant]
                    continue
                elif not firing or was:
                    continue
            # rising edge only (outside the lock: flight/metrics take
            # their own locks and must never nest under ours)
            try:
                from . import flight as _flight
                _flight.enable()
            except Exception:
                pass
            try:
                from .metrics import get_registry
                get_registry().counter(
                    "mrtpu_slo_alerts_total",
                    "SLO burn alerts raised (multi-window AND edge)",
                    ("tenant",)).inc(tenant=tenant)
            except Exception:
                pass
            print(f"SLO burn alert: tenant {tenant!r} over budget in "
                  f"every window ({per}) — flight recorder armed",
                  file=sys.stderr)

    def burning(self, tenant: str,
                thresh: Optional[float] = None) -> bool:
        """The multi-window AND, as a query: is ``tenant`` currently
        burning past ``thresh`` (default ``MRTPU_SLO_BURN``) in EVERY
        window of its objective?  Same predicate as the alert edge
        detector — the serve/ admission shedder keys off it, so a
        tenant is shed exactly when it would (or did) alert
        (doc/serve.md#slo-burn-shedding)."""
        obj = self.objective_for(tenant)
        if obj is None:
            return False
        if thresh is None:
            from ..utils.env import env_knob
            thresh = env_knob("MRTPU_SLO_BURN", float, 1.0)
        with self._lock:
            per = dict(self._burn.get(tenant) or {})
        if not per:
            return False
        return all(per.get(f"{int(w)}s", 0.0) > thresh
                   for w in obj.windows)

    def min_window(self) -> float:
        """Shortest declared window — the honest Retry-After scale for
        burn-driven shedding (the burn decays over this window)."""
        return min((w for o in self.objectives for w in o.windows),
                   default=DEFAULT_WINDOWS[0])

    # -- read-out ----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"objectives": [o.describe() for o in self.objectives],
                    "burn": {t: dict(p) for t, p in self._burn.items()},
                    "firing": sorted(self._firing),
                    "alerts": list(self.alerts)}


# ---------------------------------------------------------------------------
# process-global engine (env-armed, like every other obs knob)
# ---------------------------------------------------------------------------

_ENGINE: Optional[SLOEngine] = None
_ENV_APPLIED: Optional[str] = None
_LOCK = threading.Lock()


def configure(objectives: List[SLOObjective]) -> SLOEngine:
    """Programmatic twin of ``MRTPU_SLO`` (replaces the active engine;
    soak.py's serve workload uses this for its short windows)."""
    import os
    global _ENGINE, _ENV_APPLIED
    with _LOCK:
        _ENGINE = SLOEngine(objectives)
        # record the CURRENT env value as applied: explicit config wins
        # until MRTPU_SLO actually changes — otherwise the very next
        # get_engine() (any metrics scrape) would see an "unapplied"
        # env string and silently evict the configured engine
        from ..utils.env import env_str
        _ENV_APPLIED = env_str("MRTPU_SLO", "")
        return _ENGINE


def get_engine() -> Optional[SLOEngine]:
    """The active engine: env-armed from ``MRTPU_SLO`` (re-read when
    the value changes; malformed values warn and disarm), or whatever
    :func:`configure` installed.  None when no objectives exist."""
    global _ENGINE, _ENV_APPLIED
    from ..utils.env import env_str
    raw = env_str("MRTPU_SLO", "")
    with _LOCK:
        if raw != (_ENV_APPLIED or ""):
            _ENV_APPLIED = raw
            if raw:
                try:
                    _ENGINE = SLOEngine(parse_slo(raw))
                except (ValueError, TypeError) as e:
                    print(f"MRTPU_SLO ignored: {e!r}", file=sys.stderr)
                    _ENGINE = None
            else:
                _ENGINE = None
        return _ENGINE


def reset() -> None:
    """Test isolation: drop the engine and the env cache."""
    global _ENGINE, _ENV_APPLIED
    with _LOCK:
        _ENGINE = None
        _ENV_APPLIED = None
