"""The loopback HTTP plane: metrics export + pluggable request routes.

``curl localhost:$MRTPU_METRICS_PORT/metrics`` during a run returns the
Prometheus exposition text (op latency histograms, exchange byte
counters, plan-cache hit ratio, HBM hi-water, ...) — the "watch a
running soak" exposure the printf reports and post-hoc traces lack.

Built-in routes:

* ``/metrics`` — Prometheus text format (version 0.0.4);
* ``/metrics.json`` — the structured registry snapshot;
* ``/flight`` — the flight recorder's current snapshot (without
  writing an artifact); 404 when the recorder is not armed;
* ``/healthz`` — liveness AND readiness: any response at all means the
  process is alive; the body is ``{"status": "ok"}`` with HTTP 200 when
  the process is ready for work, or ``{"status": "draining"}`` (or
  ``"paused"``/``"fenced"``) with HTTP 503 when it is alive but must
  not receive new work — a draining serve/ replica stays pingable
  while external LBs and the fleet router stop sending to it
  (:func:`set_health`).

Subsystems mount further routes with :func:`register_routes` — the
serve/ daemon's ``/v1/...`` job API rides the same listener (GET and
POST), so one port serves both the request plane and its telemetry
(doc/serve.md).

Start with ``MRTPU_METRICS_PORT=9090`` in the environment,
``MapReduce(metrics_port=9090)``, or :func:`ensure_server`.  Port 0
binds an ephemeral port (tests); :func:`ensure_server` returns the port
ACTUALLY bound, which is also on ``MetricsServer.port``.  Binds
127.0.0.1 only — this is an operator loopback, not a public listener.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# pluggable routes: (prefix, handler) pairs tried in registration order
# after the built-in paths.  A handler receives
# ``(method, path, body_bytes, headers)`` and returns
# ``(status_code, body, content_type, extra_headers_dict_or_None)`` —
# ``body`` may be bytes, str, or any json-serializable object.
# ---------------------------------------------------------------------------

RouteHandler = Callable[[str, str, bytes, dict],
                        Tuple[int, object, str, Optional[dict]]]

_ROUTES: List[Tuple[str, RouteHandler]] = []
_ROUTES_LOCK = threading.Lock()

# /healthz readiness provider: () -> status string ("ok" = ready; any
# other value — "draining", "paused", "fenced" — answers 503 so LBs
# stop routing while the process stays alive and pingable).  One global
# provider for the process-default listener; a private MetricsServer
# can carry its own (the fleet router's listener must not report the
# co-resident daemon's drain state).
_HEALTH: Optional[Callable[[], str]] = None


def set_health(fn: Optional[Callable[[], str]]) -> None:
    """Install (or clear, with None) the process-default /healthz
    readiness provider."""
    global _HEALTH
    _HEALTH = fn


def register_routes(prefix: str, handler: RouteHandler) -> None:
    """Mount ``handler`` for every request path starting with
    ``prefix`` (idempotent per prefix: re-registering replaces — a
    restarted serve/ daemon must not stack dead handlers)."""
    with _ROUTES_LOCK:
        for i, (p, _) in enumerate(_ROUTES):
            if p == prefix:
                _ROUTES[i] = (prefix, handler)
                return
        _ROUTES.append((prefix, handler))


def unregister_routes(prefix: str) -> None:
    with _ROUTES_LOCK:
        _ROUTES[:] = [(p, h) for p, h in _ROUTES if p != prefix]


def _find_route(path: str) -> Optional[RouteHandler]:
    with _ROUTES_LOCK:
        for prefix, handler in _ROUTES:
            if path.startswith(prefix):
                return handler
    return None


class _Handler(BaseHTTPRequestHandler):
    def _send(self, code: int, body: bytes, ctype: str,
              extra: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _dispatch(self, method: str) -> None:
        # in-flight tracking: stop() drains these before closing the
        # socket, so a handler mid-write never races server_close
        srv = self.server
        with srv._inflight_lock:
            srv._inflight += 1
        try:
            path = self.path.split("?", 1)[0]
            if method == "GET" and self._builtin_get(path):
                return
            handler = srv.find_route(path)
            if handler is None:
                self._send(404, b"not found\n", "text/plain")
                return
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n) if n else b""
            code, out, ctype, extra = handler(method, path, body,
                                              dict(self.headers))
            if callable(getattr(out, "__next__", None)):
                # a handler returned an ITERATOR body: stream it (the
                # serve/ daemon's /v1/jobs/<id>/events long-lived feed)
                self._send_stream(code, out,
                                  ctype or "application/x-ndjson", extra)
                return
            if isinstance(out, bytes):
                payload = out
            elif isinstance(out, str):
                payload = out.encode()
            else:
                payload = json.dumps(out, default=str).encode()
                ctype = ctype or "application/json"
            self._send(code, payload, ctype or "application/json", extra)
        except Exception as e:  # a handler bug must not kill the thread
            try:
                self._send(500, f"{e!r}\n".encode(), "text/plain")
            except Exception:
                pass
        finally:
            with srv._inflight_lock:
                srv._inflight -= 1

    def _send_stream(self, code: int, it, ctype: str,
                     extra: Optional[dict] = None) -> None:
        """Stream an iterator body chunk by chunk, flushed per chunk.
        No Content-Length: under the handler's HTTP/1.0 semantics the
        connection close delimits the body, so a stdlib-urllib client
        reading line by line sees each chunk as it is produced — the
        no-polling contract of ``/v1/jobs/<id>/events``.  The iterator
        is always closed (its ``finally`` is how the producer
        unsubscribes), including when the client disconnects mid-
        stream."""
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Cache-Control", "no-store")
        for k, v in (extra or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        try:
            for chunk in it:
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                self.wfile.write(chunk)
                self.wfile.flush()
        except Exception:
            # the status line and part of the body are already on the
            # wire: nothing coherent can follow.  Swallow (producer bug
            # or client disconnect alike) so the outer handler doesn't
            # write an HTTP 500 status line INTO the stream body —
            # ending the connection mid-stream IS the error signal
            pass
        finally:
            close = getattr(it, "close", None)
            if close:
                try:
                    close()
                except Exception:
                    pass

    def _builtin_get(self, path: str) -> bool:
        """The metrics-plane routes; returns whether ``path`` was one."""
        from . import metrics as _metrics
        if path == "/metrics":
            self._send(200, _metrics.prometheus_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/metrics.json":
            self._send(200,
                       json.dumps(_metrics.snapshot(),
                                  default=str).encode(),
                       "application/json")
        elif path == "/flight":
            from . import flight as _flight
            rec = _flight.get()
            if rec is None:
                self._send(404, b"flight recorder not armed\n",
                           "text/plain")
            else:
                from .sinks import _jsonable
                self._send(200,
                           json.dumps(rec.snapshot("http"),
                                      default=_jsonable).encode(),
                           "application/json")
        elif path == "/healthz":
            # liveness (we answered) + readiness (the code): "ok" →
            # 200, anything else → 503 {"status": ...} so a draining/
            # paused/fenced replica is alive but not routable
            provider = getattr(self.server, "_health", None) or _HEALTH
            status = "ok"
            if provider is not None:
                try:
                    status = str(provider() or "ok")
                except Exception:
                    status = "ok"    # a broken provider must not flap
            self._send(200 if status == "ok" else 503,
                       json.dumps({"status": status}).encode() + b"\n",
                       "application/json")
        else:
            return False
        return True

    def do_GET(self):  # noqa: N802 (stdlib API name)
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class _Httpd(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, *a, routes=None, health=None, **kw):
        super().__init__(*a, **kw)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # server-local routes/health beat the process globals: a fleet
        # router and an embedded daemon in one process each keep their
        # own /v1/ (and their own readiness) on their own port
        self._local_routes: List[Tuple[str, RouteHandler]] = \
            list(routes or [])
        self._health = health

    def find_route(self, path: str) -> Optional[RouteHandler]:
        for prefix, handler in self._local_routes:
            if path.startswith(prefix):
                return handler
        if self._local_routes:
            return None     # a private listener serves ONLY its routes
        return _find_route(path)


class MetricsServer:
    """One ThreadingHTTPServer on a daemon thread.  With ``routes``
    the listener is PRIVATE: it serves only those prefixes (plus the
    builtin metrics paths) and ignores the process-global route table —
    how a fleet of in-process replicas (or the router beside a daemon)
    each get their own port without clobbering each other's ``/v1/``."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 routes: Optional[List[Tuple[str, RouteHandler]]] = None,
                 health: Optional[Callable[[], str]] = None):
        self.host = host
        self.port = port
        self._routes = routes
        self._health = health
        self._httpd: Optional[_Httpd] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind + serve; returns the actual port (resolves port 0)."""
        if self._httpd is not None:
            return self.port
        self._httpd = _Httpd((self.host, self.port), _Handler,
                             routes=self._routes, health=self._health)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="mrtpu-metrics-httpd")
        self._thread.start()
        return self.port

    def stop(self, drain_timeout: float = 5.0) -> None:
        """Stop accepting, DRAIN in-flight handlers, then close the
        socket.  daemon handler threads are not joined by
        ``server_close`` (socketserver only tracks non-daemon threads),
        so closing immediately could yank the socket from under a
        handler mid-write — the flaky-scrape-on-shutdown failure this
        ordering removes."""
        httpd = self._httpd
        if httpd is None:
            return
        self._httpd = None
        self._thread = None
        httpd.shutdown()        # stops the accept loop (blocks until idle)
        deadline = time.monotonic() + drain_timeout
        while time.monotonic() < deadline:
            with httpd._inflight_lock:
                if httpd._inflight == 0:
                    break
            time.sleep(0.01)
        httpd.server_close()

    @property
    def running(self) -> bool:
        return self._httpd is not None


_SERVER: Optional[MetricsServer] = None
_LOCK = threading.Lock()


def ensure_server(port: int) -> int:
    """Start the process HTTP server (idempotent: a second call returns
    the running server's port — the first bound port wins, with a
    stderr note when it differs from the requested port, so an operator
    curling the port they asked for and getting connection refused has
    a trail to the one actually serving).  Returns the port ACTUALLY
    bound — with ``port=0`` that is the ephemeral port the kernel
    picked, which is what every caller needs to hand to a client."""
    global _SERVER
    import sys
    from . import metrics as _metrics
    _metrics.enable_metrics()
    with _LOCK:
        if _SERVER is None or not _SERVER.running:
            _SERVER = MetricsServer(port=port)
            _SERVER.start()
        elif port not in (0, _SERVER.port):
            print(f"metrics server already on port {_SERVER.port}; "
                  f"ignoring requested port {port}", file=sys.stderr)
        return _SERVER.port


def get_server() -> Optional[MetricsServer]:
    return _SERVER


def stop_server() -> None:
    """Stop the process-global server (drains in-flight handlers)."""
    global _SERVER
    with _LOCK:
        srv, _SERVER = _SERVER, None
    if srv is not None:
        srv.stop()
