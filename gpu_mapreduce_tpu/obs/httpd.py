"""Live metrics export: a stdlib HTTP server on a daemon thread.

``curl localhost:$MRTPU_METRICS_PORT/metrics`` during a run returns the
Prometheus exposition text (op latency histograms, exchange byte
counters, plan-cache hit ratio, HBM hi-water, ...) — the "watch a
running soak" exposure the printf reports and post-hoc traces lack.

Routes:

* ``/metrics`` — Prometheus text format (version 0.0.4);
* ``/metrics.json`` — the structured registry snapshot;
* ``/flight`` — the flight recorder's current snapshot (without
  writing an artifact); 404 when the recorder is not armed;
* ``/healthz`` — liveness ("ok").

Start with ``MRTPU_METRICS_PORT=9090`` in the environment,
``MapReduce(metrics_port=9090)``, or :func:`ensure_server`.  Port 0
binds an ephemeral port (tests); the bound port is on
``MetricsServer.port``.  Binds 127.0.0.1 only — this is an operator
loopback, not a public listener.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _Handler(BaseHTTPRequestHandler):
    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API name)
        from . import metrics as _metrics
        try:
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                self._send(200, _metrics.prometheus_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/metrics.json":
                self._send(200,
                           json.dumps(_metrics.snapshot(),
                                      default=str).encode(),
                           "application/json")
            elif path == "/flight":
                from . import flight as _flight
                rec = _flight.get()
                if rec is None:
                    self._send(404, b"flight recorder not armed\n",
                               "text/plain")
                else:
                    from .sinks import _jsonable
                    self._send(200,
                               json.dumps(rec.snapshot("http"),
                                          default=_jsonable).encode(),
                               "application/json")
            elif path == "/healthz":
                self._send(200, b"ok\n", "text/plain")
            else:
                self._send(404, b"not found\n", "text/plain")
        except Exception as e:  # a scrape bug must not kill the thread
            try:
                self._send(500, f"{e!r}\n".encode(), "text/plain")
            except Exception:
                pass

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MetricsServer:
    """One ThreadingHTTPServer on a daemon thread."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind + serve; returns the actual port (resolves port 0)."""
        if self._httpd is not None:
            return self.port
        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="mrtpu-metrics-httpd")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    @property
    def running(self) -> bool:
        return self._httpd is not None


_SERVER: Optional[MetricsServer] = None
_LOCK = threading.Lock()


def ensure_server(port: int) -> MetricsServer:
    """Start the process metrics server (idempotent: a second call
    returns the running server — the first bound port wins, with a
    stderr note when it differs from the requested port, so an
    operator curling the port they asked for and getting connection
    refused has a trail to the one actually serving)."""
    global _SERVER
    import sys
    from . import metrics as _metrics
    _metrics.enable_metrics()
    with _LOCK:
        if _SERVER is None or not _SERVER.running:
            _SERVER = MetricsServer(port=port)
            _SERVER.start()
        elif port not in (0, _SERVER.port):
            print(f"metrics server already on port {_SERVER.port}; "
                  f"ignoring requested port {port}", file=sys.stderr)
    return _SERVER


def get_server() -> Optional[MetricsServer]:
    return _SERVER
