"""Request-scoped trace context + exact per-request cost attribution.

Everything before this module attributed work to the PROCESS: spans
carried wall time but no owner, the cumulative ``runtime.Counters`` are
shared across MapReduce objects, and the serve/ daemon's per-request
meta deltas were documented as "exact only when idle".  This module
gives every request (a serve session, a top-level OINK script, or the
process's own programmatic run) a **trace context**:

* a ``trace_id`` every span opened under the context carries (stamped
  into the span event, the JSONL trace, the flight-recorder ring, ft/
  journal records and quarantine records — one id connects a request to
  every artifact it produced);
* a :class:`RequestAccount` — the exact-attribution generalization of
  ``serve/budget.py``'s ``PageAccount``: counter deltas
  (dispatches, exchange sent/pad bytes, spill bytes, HBM residency),
  retry outcomes, plan-cache hits/misses and per-span stage timings are
  charged to the ACTIVE context instead of read back as deltas over
  process-global state, so two concurrent sessions can never bleed into
  each other's numbers.

Propagation is ``contextvars``-based.  A context variable is per-thread
by default, so the worker threads the execution layer spawns
(exec/ prefetch producer, exec/ spill writer, the shared ingest pool)
re-install the submitting request's context explicitly via
:func:`capture` / :func:`use` / :func:`bind` — the tests pin that a
producer-thread span carries the consumer request's trace_id.

With no explicit scope installed, :func:`active_account` falls back to
a lazily-created **process context** (one trace_id for the whole run) —
that is what "a top-level programmatic run gets a trace_id" means, and
it is what ``scripts/trace_view.py --trace`` filters on for
non-serve runs.  ``MRTPU_PROFILE=0`` disables the fallback (and the
implicit per-script scopes), returning the pre-context behavior: one
ContextVar read per counter bump, nothing else — the disarmed cost the
bench's ``detail.profile_overhead_pct`` row keeps honest.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Dict, Optional

# the active request account for THIS thread/context.  Deliberately a
# ContextVar and not a threading.local: a context can be captured and
# re-installed in worker threads, and nested scopes restore via tokens.
_CTXVAR: contextvars.ContextVar[Optional["RequestAccount"]] = \
    contextvars.ContextVar("mrtpu-request", default=None)

_PROCESS: Optional["RequestAccount"] = None
_PROC_LOCK = threading.Lock()

# distinct stage names kept per account; the tail aggregates into one
# "(other)" row so a pathological span-name cardinality (a bug, or a
# hostile script) cannot grow a session's account without bound
_STAGE_CAP = 64


def profiling_enabled() -> bool:
    """The implicit-context knob (``MRTPU_PROFILE``, default on).
    Explicit scopes — :func:`request_scope`, the serve/ daemon's
    per-session install — always work regardless."""
    from ..utils.env import env_flag
    return env_flag("MRTPU_PROFILE", True)


def new_trace_id() -> str:
    """16 hex chars of OS entropy — unique across daemon restarts
    without any coordination (a counter would collide after replay)."""
    return os.urandom(8).hex()


class RequestAccount:
    """Exact cost attribution for one request.

    Fed from the single funnels the work already goes through —
    ``Counters.add``/``Counters.mem`` (core/runtime.py), the retry
    engine's outcome counter (ft/retry.py), the LRU compile caches
    (plan/cache.py), the exchange per-call stats (obs/metrics.py) and
    finished spans (obs/tracer.py) — so there is no second measurement
    path to drift from the process-global truth: the account receives
    the same deltas, scoped to whichever context was active."""

    __slots__ = ("trace_id", "tenant", "label", "t0", "_lock",
                 "dispatches", "comm_s",
                 "exchange_count", "exchange_sent", "exchange_pad",
                 "exchange_rows", "exchange_rounds", "exchange_wire",
                 "exchange_wire_logical",
                 "spill_write", "spill_read",
                 "mem_in_use", "mem_hi_water",
                 "retries", "plan", "fusion", "stages", "sync_sites",
                 "cancel_reason", "deadline", "last_barrier", "barriers",
                 "cancel_closed")

    def __init__(self, trace_id: Optional[str] = None,
                 tenant: str = "", label: str = ""):
        self.trace_id = trace_id or new_trace_id()
        self.tenant = tenant
        self.label = label
        self.t0 = time.perf_counter()
        self._lock = threading.Lock()
        # cooperative cancellation (doc/serve.md#deadlines-and-cancel):
        # a reason string arms the flag; barrier_check() trips it at the
        # next op barrier.  Plain attribute writes — str/float
        # assignment is atomic under the GIL and the checker tolerates
        # one-barrier staleness, so no lock is needed on this path.
        self.cancel_reason: Optional[str] = None
        self.deadline: Optional[float] = None    # time.monotonic()
        self.last_barrier = time.monotonic()     # stall-watchdog clock
        self.barriers = 0                        # barrier-progress count
        self.cancel_closed = False               # disarm is PERMANENT
        self.dispatches = 0
        self.comm_s = 0.0
        self.exchange_count = 0
        self.exchange_sent = 0
        self.exchange_pad = 0
        self.exchange_rows = 0
        self.exchange_rounds = 0
        self.exchange_wire = 0
        self.exchange_wire_logical = 0
        self.spill_write = 0
        self.spill_read = 0
        self.mem_in_use = 0
        self.mem_hi_water = 0
        self.retries: Dict[str, int] = {}
        self.plan: Dict[str, Dict[str, int]] = {}
        self.fusion: Dict[str, int] = {
            "groups": 0, "fused_groups": 0, "mega_groups": 0,
            "pallas_groups": 0, "dispatches": 0,
            "dispatches_saved": 0}
        self.stages: Dict[str, dict] = {}
        # per-sync-site straggler evidence (parallel/dist guarded
        # collectives, fed via obs/fleetobs.SyncObserver): worst spread,
        # the rank most often last, attributed cause counts
        self.sync_sites: Dict[str, dict] = {}

    # -- feeds (each must never raise into the work it observes) ----------
    def note_counters(self, deltas: dict) -> None:
        """One ``Counters.add`` call's deltas (the byte/dispatch funnel:
        exchange volume, spill traffic, collective seconds, compiled-
        program launches)."""
        with self._lock:
            self.dispatches += deltas.get("ndispatch", 0)
            self.exchange_sent += deltas.get("cssize", 0)
            self.exchange_pad += deltas.get("cspad", 0)
            self.spill_write += deltas.get("wsize", 0)
            self.spill_read += deltas.get("rsize", 0)
            self.comm_s += deltas.get("commtime", 0.0)

    def charge_mem(self, delta: int) -> None:
        """One ``Counters.mem`` charge: per-request HBM residency and
        hi-water (the PageAccount mechanism, scoped to a request)."""
        with self._lock:
            self.mem_in_use = max(0, self.mem_in_use + int(delta))
            if self.mem_in_use > self.mem_hi_water:
                self.mem_hi_water = self.mem_in_use

    def note_exchange(self, stats) -> None:
        """Per-call shuffle telemetry (rows/rounds/calls + the wire
        codec's actual interconnect bytes; the logical byte volume
        arrives via :meth:`note_counters` — one source each, never
        double-counted)."""
        with self._lock:
            self.exchange_count += 1
            self.exchange_rows += int(getattr(stats, "rows", 0))
            self.exchange_rounds += int(getattr(stats, "nrounds", 0))
            wire = int(getattr(stats, "wire_bytes", 0))
            self.exchange_wire += wire
            if wire:
                # the ratio's numerator counts ONLY codec-compressed
                # exchanges — raw-bypass logical bytes in the request
                # must not inflate the reported compression
                self.exchange_wire_logical += (
                    int(getattr(stats, "sent_bytes", 0))
                    + int(getattr(stats, "pad_bytes", 0)))

    def note_retry(self, site: str, outcome: str) -> None:
        with self._lock:
            key = f"{site}:{outcome}"
            self.retries[key] = self.retries.get(key, 0) + 1

    def note_plan(self, cache: str, hit: bool) -> None:
        with self._lock:
            c = self.plan.get(cache)
            if c is None:
                c = self.plan[cache] = {"hits": 0, "misses": 0}
            c["hits" if hit else "misses"] += 1

    def note_fusion(self, fused: bool, mega: bool, dispatches: int,
                    saved: int, pallas: bool) -> None:
        """One executed plan group charged to this request: fusion
        effectiveness (plan/cache.note_fusion's per-request twin —
        which classifies the kind/mode strings ONCE and hands the
        derived booleans here)."""
        with self._lock:
            self.fusion["groups"] += 1
            if fused:
                self.fusion["fused_groups"] += 1
                if mega:
                    self.fusion["mega_groups"] += 1
                if pallas:
                    self.fusion["pallas_groups"] += 1
            self.fusion["dispatches"] += int(dispatches)
            self.fusion["dispatches_saved"] += int(saved)

    def note_span(self, name: str, cat: str, dur_s: float,
                  attrs: dict) -> None:
        """One finished span under this context → a stage row.  Rows
        aggregate per span name (bounded), like report.aggregate_ops;
        nested spans each get their own row, so rows overlap in wall
        time — the table reads like a profile, not a partition."""
        with self._lock:
            row = self.stages.get(name)
            if row is None:
                if len(self.stages) >= _STAGE_CAP:
                    name = "(other)"
                    row = self.stages.get(name)
                if row is None:
                    row = self.stages[name] = {
                        "cat": cat, "count": 0, "total_s": 0.0,
                        "max_s": 0.0, "dispatches": 0}
            row["count"] += 1
            row["total_s"] += dur_s
            if dur_s > row["max_s"]:
                row["max_s"] = dur_s
            row["dispatches"] += int(attrs.get("dispatches", 0) or 0)
            for k in ("shuffle_sent_bytes", "shuffle_pad_bytes",
                      "spill_write_bytes", "spill_read_bytes"):
                v = attrs.get(k)
                if v:
                    row[k] = row.get(k, 0) + int(v)

    def note_sync_point(self, site: str, spread_s: float, slowest: int,
                        cause: str, ranks_seen: int) -> None:
        """One guarded collective sync's arrival evidence charged to
        this request (the ``straggler`` profile section)."""
        with self._lock:
            row = self.sync_sites.get(site)
            if row is None:
                row = self.sync_sites[site] = {
                    "count": 0, "spread_s_sum": 0.0, "max_spread_s": 0.0,
                    "slowest_rank": -1, "causes": {}}
            row["count"] += 1
            row["spread_s_sum"] += spread_s
            if spread_s >= row["max_spread_s"]:
                row["max_spread_s"] = spread_s
                row["slowest_rank"] = int(slowest)
                row["worst_cause"] = cause
            row["causes"][cause] = row["causes"].get(cause, 0) + 1
            row["ranks_seen"] = int(ranks_seen)

    # -- cooperative cancellation ------------------------------------------
    def cancel(self, reason: str = "client") -> None:
        """Arm the cancellation flag: the request raises
        :class:`~...core.runtime.CancelledError` at its next op barrier.
        Idempotent; the FIRST reason wins (a deadline firing after a
        client cancel must not rewrite the story).  A no-op once the
        owner disarmed — the release path must stay uncancellable even
        against a DELETE racing the request's last lines."""
        with self._lock:
            if self.cancel_reason is None and not self.cancel_closed:
                self.cancel_reason = reason

    def set_deadline(self, seconds_from_now: float) -> None:
        with self._lock:      # pairs with disarm_cancel's clear
            self.deadline = time.monotonic() + max(0.0, seconds_from_now)

    def check_cancel(self) -> None:
        """Raise if cancelled or past deadline (the barrier-site hook —
        attribute reads only on the un-armed fast path; the deadline
        trip takes the same lock as cancel/disarm so a concurrent
        disarm can never be overwritten)."""
        reason = self.cancel_reason
        if reason is None:
            dl = self.deadline
            if dl is None or time.monotonic() <= dl:
                return
            with self._lock:
                if self.cancel_reason is None and \
                        not self.cancel_closed:
                    self.cancel_reason = "deadline"
                reason = self.cancel_reason
            if reason is None:
                return      # disarmed concurrently: nothing to stop
        from ..core.runtime import CancelledError
        raise CancelledError(reason)

    def disarm_cancel(self) -> None:
        """Drop the armed flag + deadline, PERMANENTLY: the owner is
        past the point of stopping (releasing resources, writing the
        terminal record) — a cancel arriving after this is the
        cancel-vs-complete race and loses.  The lock makes close-vs-
        cancel atomic: without it a cancel() preempted between its
        check and its store could re-arm the flag AFTER the disarm and
        cancel the release path anyway (serve/session.py)."""
        with self._lock:
            self.cancel_closed = True
            self.cancel_reason = None
            self.deadline = None

    # -- read-out ----------------------------------------------------------
    def profile(self) -> dict:
        """The per-request cost profile: what ``meta.profile``,
        ``GET /v1/jobs/<id>/profile`` and ``trace_view --trace`` show."""
        with self._lock:
            stages = {}
            for name, row in self.stages.items():
                r = dict(row)
                r["total_s"] = round(r["total_s"], 6)
                r["max_s"] = round(r["max_s"], 6)
                stages[name] = r
            straggler = {}
            for site, row in self.sync_sites.items():
                straggler[site] = {
                    "count": row["count"],
                    "avg_spread_s": round(
                        row["spread_s_sum"] / max(1, row["count"]), 6),
                    "max_spread_s": round(row["max_spread_s"], 6),
                    "slowest_rank": row["slowest_rank"],
                    "worst_cause": row.get("worst_cause", ""),
                    "causes": dict(row["causes"]),
                    "ranks_seen": row.get("ranks_seen", 0)}
            return {
                "trace_id": self.trace_id,
                "tenant": self.tenant,
                "label": self.label,
                "wall_s": round(time.perf_counter() - self.t0, 4),
                "dispatches": self.dispatches,
                "comm_s": round(self.comm_s, 6),
                "exchange": {"count": self.exchange_count,
                             "sent_bytes": self.exchange_sent,
                             "pad_bytes": self.exchange_pad,
                             "rows": self.exchange_rows,
                             "rounds": self.exchange_rounds,
                             "wire_bytes": self.exchange_wire,
                             # logical/wire ratio over the request's
                             # codec-compressed exchanges ONLY (raw-
                             # bypass traffic excluded; 0 = none ran)
                             "compression_ratio": round(
                                 self.exchange_wire_logical
                                 / self.exchange_wire, 4)
                             if self.exchange_wire else 0.0},
                "spill": {"write_bytes": self.spill_write,
                          "read_bytes": self.spill_read},
                "hbm": {"hi_water_bytes": self.mem_hi_water},
                "retries": dict(sorted(self.retries.items())),
                "plan_cache": {c: dict(v)
                               for c, v in sorted(self.plan.items())},
                # fusion v2 effectiveness: how many of this request's
                # plan groups fused / megafused / took the Pallas group
                # kernels, and the dispatches that saved vs eager
                "fusion": dict(self.fusion),
                # which collective sync sites this request waited at,
                # who was last, and whether the data or the host was
                # at fault (doc/distributed.md "a rank is slow, not
                # dead")
                "straggler": dict(sorted(straggler.items())),
                "stages": dict(sorted(
                    stages.items(),
                    key=lambda kv: -kv[1]["total_s"])),
            }


# ---------------------------------------------------------------------------
# scope management
# ---------------------------------------------------------------------------

def _process_account() -> Optional[RequestAccount]:
    """The lazy process-default context (the "top-level programmatic
    run").  None when profiling is disabled."""
    global _PROCESS
    if _PROCESS is not None:
        # an explicitly-installed account (set_process_trace_id — the
        # dist trace stitch) outranks the MRTPU_PROFILE gate
        return _PROCESS
    if not profiling_enabled():
        return None
    with _PROC_LOCK:
        if _PROCESS is None:
            _PROCESS = RequestAccount(label="process")
    return _PROCESS


def active_account() -> Optional[RequestAccount]:
    """The account charged by the feeds: the innermost explicit scope,
    else the process default (else None under MRTPU_PROFILE=0)."""
    acct = _CTXVAR.get()
    if acct is not None:
        return acct
    return _process_account()


def current_trace_id() -> Optional[str]:
    acct = active_account()
    return acct.trace_id if acct is not None else None


@contextlib.contextmanager
def request_scope(trace_id: Optional[str] = None, tenant: str = "",
                  label: str = "", account: Optional[RequestAccount]
                  = None):
    """``with request_scope() as acct:`` — install a fresh (or given)
    account as THIS context's attribution target.  Always works, even
    under MRTPU_PROFILE=0 (the knob only gates the implicit scopes)."""
    acct = account if account is not None else RequestAccount(
        trace_id=trace_id, tenant=tenant, label=label)
    token = _CTXVAR.set(acct)
    try:
        yield acct
    finally:
        _CTXVAR.reset(token)


@contextlib.contextmanager
def ensure_scope(label: str = "", tenant: str = ""):
    """A scope for top-level drivers (OinkScript): reuse the already-
    installed context when one exists (a serve session wrapping the
    script must stay ONE request), otherwise open a fresh one — unless
    profiling is disabled, in which case this is a no-op."""
    if _CTXVAR.get() is not None or not profiling_enabled():
        yield _CTXVAR.get()
        return
    with request_scope(label=label, tenant=tenant) as acct:
        yield acct


def capture() -> Optional[RequestAccount]:
    """The effective context to hand to a worker thread (explicit scope
    or the process default) — pair with :func:`use` on the other side."""
    return active_account()


@contextlib.contextmanager
def use(acct: Optional[RequestAccount]):
    """Install a captured context in the current thread (no-op on
    None).  The worker-thread half of cross-thread propagation."""
    if acct is None:
        yield None
        return
    token = _CTXVAR.set(acct)
    try:
        yield acct
    finally:
        _CTXVAR.reset(token)


def bind(fn):
    """Wrap ``fn`` so it runs under the CURRENT context wherever it is
    later called (thread-pool submission sites: the shared ingest pool,
    mapstyle-2 task queues).  Identity when no context is active."""
    acct = active_account()
    if acct is None:
        return fn

    def wrapper(*a, **kw):
        token = _CTXVAR.set(acct)
        try:
            return fn(*a, **kw)
        finally:
            _CTXVAR.reset(token)
    return wrapper


# ---------------------------------------------------------------------------
# the runtime feed (installed into core/runtime at import — runtime
# cannot import obs/ at module level without a cycle)
# ---------------------------------------------------------------------------

def _counters_feed(kind: str, payload) -> None:
    """``Counters.add``/``mem`` hook.  Must never raise into the
    counter bump it observes."""
    try:
        acct = _CTXVAR.get()
        if acct is None:
            acct = _process_account()
            if acct is None:
                return
        if kind == "add":
            acct.note_counters(payload)
        else:
            acct.charge_mem(payload)
    except Exception:
        pass


def note_exchange(stats) -> None:
    """Feed point for parallel/shuffle + plan/fuser per-call exchange
    stats (via obs/metrics.record_exchange)."""
    acct = active_account()
    if acct is not None:
        acct.note_exchange(stats)


def note_retry(site: str, outcome: str) -> None:
    """Feed point for ft/retry's outcome counter."""
    acct = active_account()
    if acct is not None:
        acct.note_retry(site, outcome)


def note_plan(cache: str, hit: bool) -> None:
    """Feed point for plan/cache.LRUCache hit/miss telemetry."""
    acct = active_account()
    if acct is not None:
        acct.note_plan(cache, hit)


def note_fusion(fused: bool, mega: bool, dispatches: int, saved: int,
                pallas: bool) -> None:
    """Feed point for plan/cache.note_fusion — per-request fusion
    effectiveness (``profile()["fusion"]``, the serve per-request
    profile's "did this job's pipelines megafuse" section)."""
    acct = active_account()
    if acct is not None:
        acct.note_fusion(fused, mega, dispatches, saved, pallas)


def note_span(name: str, cat: str, dur_s: float, attrs: dict) -> None:
    """Feed point for finished spans (obs/tracer.Span.__exit__)."""
    acct = active_account()
    if acct is not None:
        acct.note_span(name, cat, dur_s, attrs)


def note_sync(site: str, spread_s: float, slowest: int, cause: str,
              ranks_seen: int) -> None:
    """Feed point for collective sync straggler evidence
    (obs/fleetobs.SyncObserver → the profile's ``straggler`` section)."""
    acct = active_account()
    if acct is not None:
        acct.note_sync_point(site, spread_s, slowest, cause, ranks_seen)


def set_process_trace_id(trace_id: str) -> None:
    """Pin the process-default context to a GIVEN trace id — the
    cross-process stitch: mrlaunch mints one id, ships it via
    ``MRTPU_DIST_TRACE_ID``, and every rank installs it here so all
    ranks' spans/journals/flight dumps carry the launch's single id.
    Creates the process account if needed (even under MRTPU_PROFILE=0 —
    an explicit launch-provided id outranks the implicit-context knob)."""
    global _PROCESS
    with _PROC_LOCK:
        if _PROCESS is None:
            _PROCESS = RequestAccount(trace_id=trace_id, label="dist")
        else:
            _PROCESS.trace_id = trace_id


def barrier_check() -> None:
    """The op-barrier hook (core/mapreduce op start + plan barrier,
    parallel/shuffle count sync, oink command/checkpoint round): note
    barrier progress for the stall watchdog, then raise
    :class:`~..core.runtime.CancelledError` when the active request was
    cancelled or ran past its deadline.  Cooperative by design — a
    running program is never interrupted mid-dispatch; it stops at the
    next barrier with its datasets in a consistent, resumable state
    (doc/serve.md#deadlines-and-cancel).  No-op (a ContextVar read)
    when no request context is active."""
    acct = _CTXVAR.get()
    if acct is None:
        return
    acct.last_barrier = time.monotonic()
    acct.barriers += 1
    if acct.cancel_reason is not None or acct.deadline is not None:
        acct.check_cancel()


def reset() -> None:
    """Test isolation: drop the process-default context (explicit
    scopes are stack-managed and need no reset)."""
    global _PROCESS
    with _PROC_LOCK:
        _PROCESS = None


from ..core import runtime as _runtime  # noqa: E402

_runtime._REQUEST_FEED = _counters_feed
