"""Thread-safe metrics registry: counters, gauges, histograms with labels.

PR 1's spans are post-hoc — a JSONL file you read after the run.  This
module is the LIVE half: aggregated time series a running job exposes
while it executes (the DrJAX "visibility into sharded execution"
argument, arXiv:2403.07128).  One registry, three exposures:

* ``mr.stats()["metrics"]`` — the structured snapshot;
* the Prometheus text endpoint (``obs/httpd.py``,
  ``MRTPU_METRICS_PORT`` / ``MapReduce(metrics_port=...)``);
* periodic JSONL snapshots (``MRTPU_METRICS_SNAP=path``, interval
  ``MRTPU_METRICS_SNAP_SECS``) for multi-hour soak/TPU-capture windows.

Feeding is automatic once :func:`enable_metrics` runs (any of the
exposures above enables it):

* a **span→metric bridge** subscribes to the process tracer: every
  finished span observes ``mrtpu_op_latency_seconds{op,cat}`` and
  top-level spans bump the spill byte counters;
* ``parallel/shuffle.exchange`` reports per-call flow-control telemetry
  (:func:`record_exchange`: useful/pad bytes, rounds, rows);
* **collectors** run at snapshot/scrape time and refresh gauges from
  the cumulative ``runtime.Counters`` (HBM hi-water, ndispatch, comm
  seconds) and the ``plan/cache.py`` compile caches (hit ratio per
  cache);
* the trace sink's rotation bumps ``mrtpu_trace_rotated_total``
  (``sinks.JsonlSink``).

The registry itself is usable standalone (tests hammer it from
mapstyle-2 style worker threads); ``enable_metrics`` only wires the
automatic feeds.  Like the tracer, everything here must be crash-proof:
a metrics bug must never fail the op that reported it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_INF = float("inf")

# op latencies span ~µs host ops to multi-minute compiles
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, _INF)


def _fmt_value(v) -> str:
    """Prometheus sample value: integers render bare, floats via repr."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()
                              and abs(v) < 1e15):
        return str(int(v))
    if v == _INF:
        return "+Inf"
    return repr(float(v))


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


class _Metric:
    """One metric family: a name, fixed label names, and one child per
    label-value combination.  A single lock guards the children dict AND
    child mutation, so concurrent inc/observe from worker threads land
    exactly (the registry hammer test's contract)."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str]):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple, object] = OrderedDict()
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> Tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def _labels_dict(self, key: Tuple) -> dict:
        return dict(zip(self.labelnames, key))


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount=1, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0) + amount

    def value(self, **labels):
        with self._lock:
            return self._children.get(self._key(labels), 0)

    def samples(self) -> List[dict]:
        with self._lock:
            return [{"labels": self._labels_dict(k), "value": v}
                    for k, v in self._children.items()]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = value

    def inc(self, amount=1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0) + amount

    def value(self, **labels):
        with self._lock:
            return self._children.get(self._key(labels), 0)

    samples = Counter.samples


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames, buckets=None):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(buckets or DEFAULT_BUCKETS))
        if b[-1] != _INF:
            b = b + (_INF,)
        self.buckets = b

    def observe(self, value, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = \
                    {"counts": [0] * len(self.buckets), "sum": 0.0,
                     "count": 0}
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    child["counts"][i] += 1
                    break
            child["sum"] += value
            child["count"] += 1

    def samples(self) -> List[dict]:
        with self._lock:
            out = []
            for k, ch in self._children.items():
                cum, buckets = 0, OrderedDict()
                for ub, c in zip(self.buckets, ch["counts"]):
                    cum += c
                    buckets["+Inf" if ub == _INF else _fmt_value(ub)] = cum
                out.append({"labels": self._labels_dict(k),
                            "count": ch["count"],
                            "sum": ch["sum"], "buckets": buckets})
            return out


class MetricsRegistry:
    """Metric factory + snapshot/export.  ``counter``/``gauge``/
    ``histogram`` are get-or-create (idempotent per name), so feed sites
    can look their metric up on every call without holding references.
    ``collect()`` first runs the registered collectors — pull-style
    refreshers that copy cumulative sources (Counters, plan caches)
    into gauges at read time."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = OrderedDict()
        self._collectors: List[Callable] = []
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames, **kw)
                return m
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} re-declared as {cls.kind}"
                f"{tuple(labelnames)} (was {m.kind}{m.labelnames})")
        return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> Histogram:
        h = self._get_or_create(Histogram, name, help, labelnames,
                                buckets=buckets)
        if buckets is not None:
            b = tuple(sorted(buckets))
            if b[-1] != _INF:
                b = b + (_INF,)
            if h.buckets != b:
                # same loud contract as kind/labelnames conflicts —
                # observations silently landing in buckets the caller
                # never declared would be unfindable
                raise ValueError(
                    f"metric {name!r} re-declared with buckets {b} "
                    f"(was {h.buckets})")
        return h

    def register_collector(self, fn: Callable) -> None:
        """``fn(registry)`` runs before every collect()/prometheus_text()
        — refresh gauges from a cumulative source.  Registered at most
        once per function identity (enable_metrics re-runs are no-ops)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:
                pass  # a broken collector must not break the scrape

    def collect(self) -> Dict[str, dict]:
        """{name: {type, help, labelnames, samples}} snapshot."""
        self._run_collectors()
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"type": m.kind, "help": m.help,
                         "labelnames": list(m.labelnames),
                         "samples": m.samples()}
                for m in metrics}

    def prometheus_text(self) -> str:
        """The Prometheus exposition format (text/plain version 0.0.4)."""
        self._run_collectors()
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for s in m.samples():
                lab = s["labels"]

                def render(extra=None):
                    items = list(lab.items()) + (extra or [])
                    if not items:
                        return ""
                    return "{" + ",".join(
                        f'{k}="{_escape_label(v)}"' for k, v in items) + "}"

                if m.kind == "histogram":
                    for ub, cum in s["buckets"].items():
                        lines.append(f"{m.name}_bucket"
                                     f"{render([('le', ub)])} {cum}")
                    lines.append(
                        f"{m.name}_sum{render()} {_fmt_value(s['sum'])}")
                    lines.append(f"{m.name}_count{render()} {s['count']}")
                else:
                    lines.append(
                        f"{m.name}{render()} {_fmt_value(s['value'])}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every metric and collector (test isolation)."""
        with self._lock:
            self._metrics = OrderedDict()
            self._collectors = []


# ---------------------------------------------------------------------------
# process-global registry + the automatic feeds
# ---------------------------------------------------------------------------

_REGISTRY: Optional[MetricsRegistry] = None
_REG_LOCK = threading.Lock()
_ENABLED = False


def get_registry() -> MetricsRegistry:
    global _REGISTRY
    if _REGISTRY is None:
        with _REG_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


def enabled() -> bool:
    return _ENABLED


# last cumulative wsize/rsize the bridge has accounted: top-level span
# ARGS deltas are per-span snapshots of the shared global Counters, so
# two overlapping top-level spans (mapstyle-2 threads, two MapReduce
# objects) would both include the same bump — delta-tracking the
# cumulative source here counts every spilled byte exactly once
_SPILL_LOCK = threading.Lock()
_SPILL_SEEN = {"wsize": 0, "rsize": 0}


def _bridge_emit(ev: dict) -> None:
    """Tracer sink: every finished span becomes metric updates.  Must
    never raise (the tracer drops a raising sink)."""
    try:
        reg = get_registry()
        reg.histogram(
            "mrtpu_op_latency_seconds",
            "wall time of traced spans by op name and category",
            ("op", "cat")).observe(
                float(ev.get("dur", 0.0)) / 1e6,
                op=ev.get("name", "?"), cat=ev.get("cat", "?"))
        if not ev.get("parent"):
            from ..core.runtime import global_counters
            snap = global_counters().snapshot()
            with _SPILL_LOCK:
                dw = snap["wsize"] - _SPILL_SEEN["wsize"]
                dr = snap["rsize"] - _SPILL_SEEN["rsize"]
                _SPILL_SEEN["wsize"] = snap["wsize"]
                _SPILL_SEEN["rsize"] = snap["rsize"]
            spill = reg.counter(
                "mrtpu_spill_bytes_total",
                "bytes spilled to / re-read from fpath files", ("dir",))
            if dw > 0:
                spill.inc(dw, dir="write")
            if dr > 0:
                spill.inc(dr, dir="read")
    except Exception:
        pass


def _collect_counters(reg: MetricsRegistry) -> None:
    """Refresh gauges from the cumulative cross-instance Counters."""
    from ..core.runtime import global_counters
    snap = global_counters().snapshot()
    cum = reg.gauge("mrtpu_cum",
                    "cumulative runtime.Counters fields (bytes/seconds "
                    "/launches; the cummulative_stats snapshot)",
                    ("field",))
    for k, v in snap.items():
        cum.set(v, field=k)
    reg.gauge("mrtpu_hbm_hiwater_bytes",
              "hi-water of bytes resident in HBM frames (msizemax)"
              ).set(snap["msizemax"])
    reg.gauge("mrtpu_dispatch_total",
              "compiled-program launches (Counters.ndispatch)"
              ).set(snap["ndispatch"])


def _collect_plan(reg: MetricsRegistry) -> None:
    """Refresh plan/jit compile-cache telemetry (plan/cache.py)."""
    from ..plan.cache import cache_stats
    st = cache_stats()
    g = reg.gauge("mrtpu_plan_cache",
                  "compile-cache telemetry per cache and stat",
                  ("cache", "stat"))
    ratio = reg.gauge("mrtpu_plan_cache_hit_ratio",
                      "hits / (hits + misses) per compile cache",
                      ("cache",))
    for cname, s in st.items():
        for k, v in s.items():
            g.set(v, cache=cname, stat=k)
        tot = s.get("hits", 0) + s.get("misses", 0)
        ratio.set(round(s.get("hits", 0) / tot, 6) if tot else 0.0,
                  cache=cname)


_FT_LOCK = threading.Lock()
# last ft/ counter values already synced into the registry: the ft
# counters are process-cumulative and may predate enable_metrics, so
# the collector delta-syncs at scrape time (exact regardless of when
# the registry armed; the lock keeps concurrent scrapes from double-
# counting a delta)
_FT_SEEN: Dict[str, dict] = {"retries": {}, "faults": {},
                             "quarantined": {}}


def _collect_ft(reg: MetricsRegistry) -> None:
    """Refresh the fault-tolerance counters from ft/'s cumulative
    sources: mrtpu_retries_total{site,outcome},
    mrtpu_faults_injected_total{site}, mrtpu_quarantined_total{site}."""
    from ..ft import counters_snapshot
    snap = counters_snapshot()
    specs = (("retries", "mrtpu_retries_total",
              "ft/ retry engine outcomes per site "
              "(retry/recovered/exhausted/fatal)", ("site", "outcome")),
             ("faults", "mrtpu_faults_injected_total",
              "faults injected by the ft/ chaos schedule", ("site",)),
             ("quarantined", "mrtpu_quarantined_total",
              "poisoned map inputs skipped under onfault=skip",
              ("site",)))
    with _FT_LOCK:
        for field, name, help, labels in specs:
            c = reg.counter(name, help, labels)
            seen = _FT_SEEN[field]
            for key, n in snap[field].items():
                d = n - seen.get(key, 0)
                if d < 0:
                    # the source went backwards — only ft.reset() does
                    # that, so everything now counted is NEW since the
                    # reset: inc the full n (staying monotonic) rather
                    # than silently dropping post-reset events until
                    # counts exceed their pre-reset values
                    d = n
                if d > 0:
                    lab = dict(zip(labels, key if isinstance(key, tuple)
                                   else (key,)))
                    c.inc(d, **lab)
                seen[key] = n


def _collect_exec(reg: MetricsRegistry) -> None:
    """Refresh the async-overlap gauges (exec/) at scrape time, so a
    registry armed after an ingest still reads the cumulative ratios."""
    from ..exec import exec_stats
    g = reg.gauge("mrtpu_overlap_ratio",
                  "fraction of background work hidden behind foreground "
                  "work, per overlap path (1 = fully overlapped)",
                  ("path",))
    for path, rec in exec_stats()["overlap"].items():
        g.set(rec["overlap_ratio"], path=path)


def _collect_slo(reg: MetricsRegistry) -> None:
    """Tick the tenant SLO engine (obs/slo.py) at scrape time: windowed
    burn-rate evaluation over the serve session counters this registry
    already holds, refreshing ``mrtpu_slo_burn_ratio{tenant,window}``.
    A no-op when no objectives are configured (MRTPU_SLO unset)."""
    from . import slo as _slo
    eng = _slo.get_engine()
    if eng is not None:
        eng.tick(reg=reg)


def enable_metrics(flight: Optional[bool] = None) -> MetricsRegistry:
    """Wire the automatic feeds (idempotent): subscribe the span bridge
    to the process tracer (this enables tracing), register the Counters
    and plan-cache collectors plus the exec/ overlap collector, and —
    unless ``flight=False`` or ``MRTPU_FLIGHT=0`` — arm the flight
    recorder so a failing run leaves a forensic artifact
    (obs/flight.py)."""
    global _ENABLED
    reg = get_registry()
    reg.register_collector(_collect_counters)
    reg.register_collector(_collect_plan)
    reg.register_collector(_collect_exec)
    reg.register_collector(_collect_ft)
    reg.register_collector(_collect_slo)
    from .tracer import get_tracer
    get_tracer().subscribe_once(_bridge_emit)
    _ENABLED = True
    if flight is None:
        from ..utils.env import env_str
        # MRTPU_FLIGHT is a path-or-flag: any value but "0" arms it
        flight = env_str("MRTPU_FLIGHT", "") != "0"
    if flight:
        try:
            from . import flight as _flight
            _flight.enable()
        except Exception:
            pass
    return reg


def snapshot() -> Dict[str, dict]:
    return get_registry().collect()


def prometheus_text() -> str:
    return get_registry().prometheus_text()


def reset() -> None:
    """Test isolation: drop metrics/collectors and the enabled flag.
    (The bridge sink, if subscribed, is cleared by ``tracer.reset()``.)"""
    global _ENABLED
    _ENABLED = False
    get_registry().reset()
    with _FT_LOCK:
        for d in _FT_SEEN.values():
            d.clear()


# -- feed points ------------------------------------------------------------

def record_exchange(stats) -> None:
    """Per-call shuffle telemetry (parallel/shuffle.exchange): useful vs
    padding bytes, flow-control rounds, routed rows."""
    # the request account's exchange feed runs BEFORE the registry
    # gate: per-request attribution (obs/context.py) must stay exact
    # whether or not live metrics are armed
    try:
        from .context import note_exchange
        note_exchange(stats)
    except Exception:
        pass
    if not _ENABLED:
        return
    try:
        reg = get_registry()
        reg.counter("mrtpu_exchanges_total",
                    "shuffle exchange() calls").inc()
        b = reg.counter("mrtpu_exchange_bytes_total",
                        "bytes moved by exchanges: useful (sent) vs "
                        "static-shape padding slack (pad) at logical "
                        "row width, and actual interconnect bytes "
                        "after the MRTPU_WIRE codec (wire)", ("kind",))
        b.inc(int(stats.sent_bytes), kind="sent")
        b.inc(int(stats.pad_bytes), kind="pad")
        b.inc(int(getattr(stats, "wire_bytes", 0)), kind="wire")
        reg.counter("mrtpu_exchange_rounds_total",
                    "flow-control rounds across exchanges"
                    ).inc(int(stats.nrounds))
        reg.counter("mrtpu_exchange_rows_total",
                    "rows routed across exchanges").inc(int(stats.rows))
    except Exception:
        pass


def note_trace_rotated() -> None:
    """The trace sink rotated a JSONL file (sinks.JsonlSink under
    MRTPU_TRACE_MAX_MB).  Counts even before enable_metrics — rotation
    evidence must not depend on the bridge being armed."""
    try:
        get_registry().counter(
            "mrtpu_trace_rotated_total",
            "JSONL trace-file rotations (MRTPU_TRACE_MAX_MB)").inc()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# periodic JSONL snapshots
# ---------------------------------------------------------------------------

class Snapshotter(threading.Thread):
    """Daemon thread appending one ``{"utc", "metrics"}`` JSON line to
    ``path`` every ``every_s`` seconds — the long-window exposure: a
    multi-hour soak leaves a time series even when nothing ever scrapes
    the HTTP endpoint."""

    def __init__(self, path: str, every_s: float = 60.0):
        super().__init__(daemon=True, name="mrtpu-metrics-snap")
        self.path = path
        self.every_s = max(1.0, float(every_s))
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.every_s):
            self.write_once()

    def write_once(self) -> None:
        try:
            line = json.dumps(
                {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                 "metrics": snapshot()}, default=str)
            with open(self.path, "a") as f:
                f.write(line + "\n")
        except Exception:
            pass  # a full disk must not kill the run

    def stop(self) -> None:
        self._stop.set()


_SNAPSHOTTER: Optional[Snapshotter] = None
_SNAP_LOCK = threading.Lock()   # NOT _REG_LOCK: enable_metrics() below
#                                 reaches get_registry(), which takes it


def start_snapshotter(path: str, every_s: float = 60.0) -> Snapshotter:
    """Start (or return the already-running) periodic snapshot writer."""
    global _SNAPSHOTTER
    enable_metrics()
    with _SNAP_LOCK:
        if _SNAPSHOTTER is None or not _SNAPSHOTTER.is_alive():
            _SNAPSHOTTER = Snapshotter(path, every_s)
            _SNAPSHOTTER.start()
    return _SNAPSHOTTER


def configure_from_env() -> None:
    """Apply MRTPU_METRICS_PORT / MRTPU_METRICS_SNAP[_SECS] /
    MRTPU_FLIGHT if set (called once at obs import).  Never raises,
    and each knob is independent — a bad port value must not silently
    disarm the snapshotter or the flight recorder set via their own
    valid env vars."""
    import sys

    def _warn(knob: str, e: Exception) -> None:
        # one stderr line, not silence: a typo'd port on a multi-hour
        # capture window must not quietly run with no live export
        print(f"{knob} ignored: {e!r}", file=sys.stderr)

    from ..utils.env import env_knob, env_str
    try:
        port = env_knob("MRTPU_METRICS_PORT", int, None)
        if port is not None:
            enable_metrics()
            from .httpd import ensure_server
            ensure_server(port)
    except Exception as e:
        _warn("MRTPU_METRICS_PORT", e)
    try:
        snap = env_str("MRTPU_METRICS_SNAP", None)
        if snap:
            start_snapshotter(
                snap, env_knob("MRTPU_METRICS_SNAP_SECS", float, 60.0))
    except Exception as e:
        _warn("MRTPU_METRICS_SNAP", e)
    try:
        fl = env_str("MRTPU_FLIGHT", None)
        if fl and fl != "0":
            from . import flight as _flight
            _flight.enable()
    except Exception as e:
        _warn("MRTPU_FLIGHT", e)
