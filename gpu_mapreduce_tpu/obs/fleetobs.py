"""Fleet-wide observability: sync-point straggler attribution, the
per-rank metrics dump channel, and the ``{replica,rank}`` federation
renderer.

Everything obs/ built through PR 8 is process-local; PRs 13-15 made the
system a replicated serve fleet driving a multi-process data plane.
This module is the convergence layer (doc/observability.md "Fleet &
mesh"):

* :class:`SyncObserver` — per-rank **arrival records** at every
  watchdog-guarded sync site (count_sync/exchange/reshard/ckpt_barrier).
  Each rank appends ``{"site","seq","rank","ts","rows"}`` to its own
  ``<rundir>/hb-g<gen>/rank<k>.sync.jsonl`` BEFORE entering the
  collective; because the collective cannot complete until every rank
  entered, every peer's stamp for that (site, seq) is durable by the
  time any rank's call returns — so each rank computes the sync's
  **arrival spread** and **slowest rank** locally, with zero extra
  collectives perturbing the thing being measured.  The cause class is
  **data_skew** when the slowest rank's routed row count (fed from the
  shuffle's count matrix via :meth:`note_rows`) exceeds
  ``MRTPU_DIST_SKEW_RATIO`` x the mean, else **host_slow**.  Exposed as
  ``mrtpu_dist_sync_spread_seconds{site}`` + the request profile's
  ``straggler`` section; a spread past ``MRTPU_DIST_SPREAD_FLIGHT``
  dumps the flight recorder (once per site).
* :class:`RankMetricsDumper` — the per-rank metrics dump channel:
  snapshots the registry into ``<rundir>/metrics-r<rank>.json``
  (atomic) every ``MRTPU_DIST_METRICS_SECS`` and at exit/PeerLost, so a
  rank that dies mid-run still left a recent, labeled registry image
  the federation route can serve (marked stale, never absent).
* :func:`federate_text` / :func:`read_rank_dumps` — the router's
  ``/metrics/fleet`` building blocks: merge replica scrapes and rank
  dumps into one Prometheus exposition where every sample carries
  ``{replica,rank}`` labels (one of the two empty — a series is either
  a replica's or a rank's), plus honest liveness/staleness series
  (``mrtpu_fleet_member_up/stale/age_seconds``) for every member,
  including the dead ones.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..utils.env import env_knob

# mirror of parallel/dist.py's per-generation heartbeat dir layout
# (obs/ must not import parallel/ at module level); the sync shards
# live beside the lease files they are judged with
_HB_DIR = "hb-g"
_SYNC_SUF = ".sync.jsonl"
_METRICS_PAT = "metrics-r{rank}.json"


def sync_path(rundir: str, rank: int, gen: int = 0) -> str:
    return os.path.join(rundir, f"{_HB_DIR}{gen}",
                        f"rank{rank}{_SYNC_SUF}")


def rank_metrics_path(rundir: str, rank: int) -> str:
    return os.path.join(rundir, _METRICS_PAT.format(rank=rank))


def classify_straggler(slowest: int, rows_by_rank
                       ) -> str:
    """``data_skew`` when the slowest rank's routed rows exceed
    ``MRTPU_DIST_SKEW_RATIO`` x the mean per-rank rows (the count
    matrix says the imbalance was the DATA's fault), else
    ``host_slow`` (same rows, late anyway: CPU steal, page cache,
    a sick host — the half the autoscaler cannot fix by resharding)."""
    if not rows_by_rank or slowest >= len(rows_by_rank):
        return "host_slow"
    mean = sum(rows_by_rank) / len(rows_by_rank)
    if mean <= 0:
        return "host_slow"
    ratio = env_knob("MRTPU_DIST_SKEW_RATIO", float, 2.0)
    return "data_skew" if rows_by_rank[slowest] >= ratio * mean \
        else "host_slow"


class SyncObserver:
    """One rank's sync-site instrumentation (armed from
    ``parallel/dist.DistRuntime`` when ``MRTPU_DIST_SYNC_OBS`` is on).
    Every method is crash-proof at the call site (dist.guard wraps in
    try/except): observing a sync must never fail it."""

    def __init__(self, rundir: str, rank: int, world: int, gen: int = 0):
        self.rundir = rundir
        self.rank = rank
        self.world = world
        self.gen = gen
        self.path = sync_path(rundir, rank, gen)
        self.spread_flight_s = env_knob("MRTPU_DIST_SPREAD_FLIGHT",
                                        float, 0.0)
        self._lock = threading.Lock()
        self._f = None
        self._seq: Dict[str, int] = {}
        self._rows: Optional[List[int]] = None
        # incremental peer tails: byte offset + (rank, site, seq) → ts
        self._offsets: Dict[int, int] = {}
        self._peer_index: Dict[tuple, float] = {}
        self._flight_dumped: set = set()

    # -- feed --------------------------------------------------------------
    def note_rows(self, rows_by_rank) -> None:
        """Last known per-rank routed row counts (the shuffle count
        matrix's destination sums) — the data-skew evidence."""
        with self._lock:
            self._rows = [int(x) for x in rows_by_rank]

    # -- the two guard hooks ----------------------------------------------
    def arrive(self, site: str) -> dict:
        """Stamp this rank's arrival at ``site`` (durable BEFORE the
        collective blocks) and return the record ``complete`` needs."""
        with self._lock:
            seq = self._seq.get(site, 0)
            self._seq[site] = seq + 1
            rec = {"site": site, "seq": seq, "rank": self.rank,
                   "ts": time.time()}
            if self._rows is not None and self.rank < len(self._rows):
                rec["rows"] = self._rows[self.rank]
            self._append(rec)
        return rec

    def complete(self, site: str, rec: dict) -> Optional[dict]:
        """The sync returned on this rank: read every peer's arrival
        stamp for (site, seq) — all durable, since the collective could
        not have completed otherwise — and report spread / slowest /
        cause.  Returns the spread record (None when no peer stamp was
        found, e.g. a site that is not a true all-ranks collective)."""
        now = time.time()
        seq = int(rec["seq"])
        with self._lock:
            arrivals = {self.rank: float(rec["ts"])}
            for r in range(self.world):
                if r == self.rank:
                    continue
                ts = self._lookup(r, site, seq)
                if ts is not None:
                    arrivals[r] = ts
            rows = list(self._rows) if self._rows else []
        if len(arrivals) < 2:
            return None
        first = min(arrivals.values())
        slowest = max(arrivals, key=lambda r: arrivals[r])
        spread = arrivals[slowest] - first
        cause = classify_straggler(slowest, rows)
        out = {"kind": "spread", "site": site, "seq": seq,
               "spread_s": round(spread, 6), "slowest": slowest,
               "cause": cause, "ranks_seen": len(arrivals),
               "wall_s": round(now - float(rec["ts"]), 6),
               "arrivals": {str(r): round(ts - first, 6)
                            for r, ts in sorted(arrivals.items())}}
        with self._lock:
            self._append(out)
        self._report(site, spread, slowest, cause, len(arrivals))
        return out

    # -- reporting ---------------------------------------------------------
    def _report(self, site: str, spread: float, slowest: int,
                cause: str, seen: int) -> None:
        try:
            from .metrics import get_registry
            reg = get_registry()
            reg.histogram(
                "mrtpu_dist_sync_spread_seconds",
                "per-sync arrival spread across ranks (last arrival "
                "minus first) at each guarded collective site",
                ("site",)).observe(spread, site=site)
            reg.counter(
                "mrtpu_dist_sync_total",
                "guarded collective syncs observed with full per-rank "
                "arrival evidence", ("site",)).inc(site=site)
            reg.gauge(
                "mrtpu_dist_sync_slowest_rank",
                "last rank to arrive at the most recent sync of each "
                "site", ("site",)).set(slowest, site=site)
            if spread >= env_knob("MRTPU_DIST_SPREAD_WARN",
                                  float, 0.25):
                reg.counter(
                    "mrtpu_dist_sync_straggler_total",
                    "syncs whose arrival spread crossed "
                    "MRTPU_DIST_SPREAD_WARN, by attributed cause "
                    "(data_skew vs host_slow)", ("site", "cause")
                ).inc(site=site, cause=cause)
        except Exception:
            pass
        try:
            from .context import note_sync
            note_sync(site, spread, slowest, cause, seen)
        except Exception:
            pass
        if self.spread_flight_s > 0 and spread >= self.spread_flight_s \
                and site not in self._flight_dumped:
            self._flight_dumped.add(site)
            try:
                from . import flight as _flight
                rec = _flight.get()
                if rec is not None:
                    rec.dump(f"sync_spread:{site}")
            except Exception:
                pass

    # -- internals ---------------------------------------------------------
    def _append(self, rec: dict) -> None:
        if self._f is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            # mrlint: disable=lock-unguarded-mutation — every caller
            # (arrive/complete) already holds self._lock; the Lock is
            # non-reentrant so this helper cannot take it again
            self._f = open(self.path, "ab")
        self._f.write(json.dumps(rec).encode() + b"\n")
        self._f.flush()          # same-host visibility; no fsync — the
        #                          record matters for attribution, not
        #                          durability across power loss

    def _lookup(self, r: int, site: str, seq: int) -> Optional[float]:
        key = (r, site, seq)
        ts = self._peer_index.get(key)
        if ts is None:
            self._ingest_peer(r)
            ts = self._peer_index.get(key)
        return ts

    def _ingest_peer(self, r: int) -> None:
        """Tail-read peer ``r``'s sync shard from the last offset; only
        complete lines are consumed (a peer may be mid-append)."""
        path = sync_path(self.rundir, r, self.gen)
        try:
            with open(path, "rb") as f:
                f.seek(self._offsets.get(r, 0))
                data = f.read()
        except OSError:
            return
        end = data.rfind(b"\n") + 1
        if not end:
            return
        self._offsets[r] = self._offsets.get(r, 0) + end
        for line in data[:end].splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "spread":
                continue
            try:
                self._peer_index[(int(rec["rank"]), str(rec["site"]),
                                  int(rec["seq"]))] = float(rec["ts"])
            except (KeyError, TypeError, ValueError):
                continue

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


def read_sync_records(rundir: str) -> List[dict]:
    """Every rank's sync records across all generations of a run dir —
    the offline merge trace_view's sync-alignment table renders."""
    out: List[dict] = []
    try:
        gens = sorted(d for d in os.listdir(rundir)
                      if d.startswith(_HB_DIR))
    except OSError:
        return out
    for g in gens:
        gdir = os.path.join(rundir, g)
        try:
            shards = sorted(f for f in os.listdir(gdir)
                            if f.endswith(_SYNC_SUF))
        except OSError:
            continue
        for shard in shards:
            try:
                with open(os.path.join(gdir, shard), "rb") as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        rec["gen"] = g
                        out.append(rec)
            except OSError:
                continue
    return out


# ---------------------------------------------------------------------------
# the per-rank metrics dump channel
# ---------------------------------------------------------------------------

class RankMetricsDumper(threading.Thread):
    """Daemon thread atomically rewriting
    ``<rundir>/metrics-r<rank>.json`` with the full registry snapshot
    every ``every_s`` — plus :meth:`dump_once` at exit/PeerLost.  The
    file (not a socket) is the channel on purpose: a SIGKILLed rank's
    last cadence dump survives it, which is what lets the federation
    route mark the rank stale instead of losing it."""

    def __init__(self, rundir: str, rank: int, gen: int = 0,
                 every_s: Optional[float] = None):
        super().__init__(daemon=True,
                         name=f"mrtpu-dist-metrics-r{rank}")
        self.rundir = rundir
        self.rank = rank
        self.gen = gen
        self.every_s = every_s if every_s is not None else \
            env_knob("MRTPU_DIST_METRICS_SECS", float, 5.0)
        self.every_s = max(0.25, float(self.every_s))
        self._stop = threading.Event()

    def run(self) -> None:
        self.dump_once("start")   # a dump exists before the first sync
        while not self._stop.wait(self.every_s):
            self.dump_once("cadence")

    def dump_once(self, reason: str = "cadence") -> Optional[str]:
        """One atomic dump; never raises (a full disk must not fail the
        rank it observes).  Returns the path (None on failure)."""
        try:
            from ..utils.fsio import atomic_write_json
            from .context import current_trace_id
            from .metrics import snapshot
            path = rank_metrics_path(self.rundir, self.rank)
            atomic_write_json(path, {
                "rank": self.rank, "gen": self.gen, "pid": os.getpid(),
                "ts": time.time(),
                "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
                "every_s": self.every_s, "reason": reason,
                "trace_id": current_trace_id(),
                "metrics": snapshot()})
            return path
        except Exception:
            return None

    def stop(self, reason: str = "exit") -> None:
        """Final dump; idempotent, FIRST reason wins — the exit path
        stops with its specific story ("done", "peer_lost:<site>") and
        the generic runtime-teardown "exit" must not rewrite it."""
        if self._stop.is_set():
            return
        self._stop.set()
        self.dump_once(reason)


def read_rank_dumps(rundir: str) -> Dict[int, dict]:
    """{rank: dump doc} over ``<rundir>/metrics-r*.json``."""
    out: Dict[int, dict] = {}
    try:
        names = sorted(os.listdir(rundir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("metrics-r")
                and name.endswith(".json")):
            continue
        try:
            rank = int(name[len("metrics-r"):-len(".json")])
        except ValueError:
            continue
        try:
            with open(os.path.join(rundir, name)) as f:
                out[rank] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


def rank_dump_stale(doc: dict, now: Optional[float] = None) -> float:
    """Age of a rank dump in seconds; compare against
    ``3 x every_s + 1`` for the staleness verdict (one missed cadence
    is scheduling noise; three is a dead or wedged rank)."""
    now = time.time() if now is None else now
    try:
        return max(0.0, now - float(doc["ts"]))
    except (KeyError, TypeError, ValueError):
        return float("inf")


# ---------------------------------------------------------------------------
# federation rendering (the router's /metrics/fleet)
# ---------------------------------------------------------------------------

def member_row(replica: str = "", rank: str = "", *, up: bool,
               stale: bool, age_s: float,
               metrics: Optional[dict] = None,
               state: str = "") -> dict:
    """One federation member (exactly one of ``replica``/``rank`` set)."""
    return {"replica": str(replica), "rank": str(rank), "up": bool(up),
            "stale": bool(stale), "age_s": round(float(age_s), 3),
            "state": state, "metrics": metrics}


# liveness/staleness series every member gets, dead ones included
_MEMBER_GAUGES = (
    ("mrtpu_fleet_member_up",
     "federation member currently serving/reporting "
     "(0 = dead or unreachable)",
     lambda m: 1 if m["up"] else 0),
    ("mrtpu_fleet_member_stale",
     "member's metrics are a last-known image, not a live scrape",
     lambda m: 1 if m["stale"] else 0),
    ("mrtpu_fleet_member_age_seconds",
     "seconds since the member's lease/dump was last renewed",
     lambda m: m["age_s"]),
)


def federate_text(members: List[dict]) -> str:
    """Merge member registry snapshots into ONE Prometheus exposition:
    every sample gains ``{replica,rank}`` labels (its member's), and
    liveness/staleness series cover every member — the dead ones
    emphatically included (stale, not absent)."""
    lines: List[str] = []
    for gname, ghelp, gval in _MEMBER_GAUGES:
        lines.append(f"# HELP {gname} {ghelp}")
        lines.append(f"# TYPE {gname} gauge")
        for m in members:
            lines.append(f"{gname}{_mlab(m)} {gval(m)}")
    # merged member series, grouped per metric so HELP/TYPE render once
    order: List[str] = []
    families: Dict[str, dict] = {}
    for m in members:
        snap = m.get("metrics") or {}
        for name, fam in snap.items():
            if name not in families:
                families[name] = {"type": fam.get("type", "untyped"),
                                  "help": fam.get("help", ""),
                                  "rows": []}
                order.append(name)
            families[name]["rows"].append((m, fam.get("samples") or []))
    for name in order:
        fam = families[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for m, samples in fam["rows"]:
            extra = [("replica", m["replica"]), ("rank", m["rank"])]
            for s in samples:
                labels = list((s.get("labels") or {}).items()) + extra
                if fam["type"] == "histogram":
                    for ub, cum in (s.get("buckets") or {}).items():
                        lines.append(
                            f"{name}_bucket"
                            f"{_plab(labels + [('le', ub)])} {cum}")
                    lines.append(f"{name}_sum{_plab(labels)} "
                                 f"{_fmt(s.get('sum', 0))}")
                    lines.append(f"{name}_count{_plab(labels)} "
                                 f"{s.get('count', 0)}")
                else:
                    lines.append(f"{name}{_plab(labels)} "
                                 f"{_fmt(s.get('value', 0))}")
    return "\n".join(lines) + "\n"


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _plab(items) -> str:
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in items) + "}"


def _mlab(m: dict) -> str:
    return _plab([("replica", m["replica"]), ("rank", m["rank"])])


def _fmt(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if f == float("inf"):
        return "+Inf"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)
