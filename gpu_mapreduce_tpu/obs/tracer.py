"""Thread-safe tracer with nested spans.

A span records wall time, deltas of the cumulative ``runtime.Counters``
(bytes shuffled/padded/spilled, HBM hi-water), execution tier and
arbitrary op metadata.  Nesting is per thread (a thread-local stack), so
``collate`` naturally parents ``aggregate``/``convert``, which parent
the shuffle's ``exchange`` span, and the ``-partition`` universe's
concurrent interpreter threads each get their own stack.

Events are emitted to sinks already in Chrome trace-event form
(``ph: "X"`` complete events, ``ts``/``dur`` in microseconds), so the
JSONL file a run writes needs only wrapping in ``{"traceEvents": [...]}``
to load in Perfetto (``sinks.chrome_trace``).

Counter deltas are PROCESS-GLOBAL (the counters are shared across
MapReduce objects, like the reference's static stats): when concurrent
``-partition`` worlds overlap, a span may attribute another world's
bytes to itself.  Wall time and nesting stay correct per thread.

Zero-cost when disabled: ``span()`` returns the shared :data:`NULL_SPAN`
singleton — one attribute check, no allocation.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..utils.env import env_flag, env_knob, env_str
from .context import current_trace_id as _ctx_trace_id
from .context import note_span as _ctx_note_span

# Counters fields snapshotted at span entry; the exit delta lands in the
# span's args under the mapped name (only when nonzero, to keep traces
# small).  msizemax is a hi-water, not a flow — reported as the absolute
# hi-water at span exit when it moved during the span.
_DELTA_FIELDS = (
    ("cssize", "shuffle_sent_bytes"),
    ("cspad", "shuffle_pad_bytes"),
    ("wsize", "spill_write_bytes"),
    ("rsize", "spill_read_bytes"),
    ("commtime", "comm_secs"),
    ("ndispatch", "dispatches"),
)


class _NullSpan:
    """Shared no-op stand-in when tracing is disabled (or for the
    ``annotate`` of a thread with no open span)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        pass


NULL_SPAN = _NullSpan()


class Span:
    """One timed region.  Use as a context manager::

        with tracer.span("collate", shards=P) as sp:
            ...
            sp.set(nkv=n)
    """

    __slots__ = ("tracer", "name", "cat", "attrs", "span_id", "parent_id",
                 "t0", "t1", "_snap", "_mem0", "_jax_ctx", "trace_id")

    def __init__(self, tracer: "Tracer", name: str, cat: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.span_id = 0
        self.parent_id = 0
        self.t0 = self.t1 = 0.0
        self._snap = None
        self._mem0 = 0
        self._jax_ctx = None
        self.trace_id = None

    def set(self, **attrs):
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        tr = self.tracer
        self.span_id = tr._next_id()
        stack = tr._stack()
        self.parent_id = stack[-1].span_id if stack else 0
        stack.append(self)
        # request-scoped trace context (obs/context.py): the id rides
        # the event so one request's spans are filterable out of any
        # sink — including spans emitted from worker threads that
        # re-installed the submitting request's context
        self.trace_id = _ctx_trace_id()
        c = tr.counters
        self._snap = tuple(getattr(c, f) for f, _ in _DELTA_FIELDS)
        self._mem0 = c.msizemax
        if tr.jax_annotations:
            try:
                import jax
                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None  # no profiler backend: spans still work
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.t1 = time.perf_counter()
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        tr = self.tracer
        stack = tr._stack()
        # pop self even if an inner span leaked (exception unwinding)
        while stack and stack.pop() is not self:
            pass
        c = tr.counters
        for (field, label), before in zip(_DELTA_FIELDS, self._snap):
            d = getattr(c, field) - before
            if d:
                self.attrs[label] = d
        if c.msizemax != self._mem0:
            self.attrs["hbm_hiwater_bytes"] = c.msizemax
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        # per-request stage profile (obs/context.py): the finished
        # span's wall + counter deltas land on the active account too —
        # same numbers, scoped to the request instead of the process
        _ctx_note_span(self.name, self.cat, self.t1 - self.t0, self.attrs)
        tr._emit(self)
        return False

    def event(self) -> dict:
        """This finished span as a Chrome trace-event dict."""
        tr = self.tracer
        ev = {
            "name": self.name, "cat": self.cat, "ph": "X",
            "ts": round((self.t0 - tr.epoch) * 1e6, 1),
            "dur": round((self.t1 - self.t0) * 1e6, 1),
            "pid": tr.pid, "tid": threading.get_ident() & 0x7FFFFFFF,
            "id": self.span_id, "parent": self.parent_id,
            "wall": round(tr.wall_epoch + self.t0, 6),
            "args": self.attrs,
        }
        if self.trace_id is not None:
            ev["trace"] = self.trace_id
        return ev


class Tracer:
    """Span factory + sink fan-out.  One per process normally
    (:func:`get_tracer`); tests may build private instances."""

    def __init__(self, counters=None):
        if counters is None:
            from ..core.runtime import global_counters
            counters = global_counters()
        self.enabled = False
        self.counters = counters
        # process-wide attrs merged into EVERY span (parallel/dist.py
        # stamps rank= here so one multi-rank trace merge stays
        # attributable without threading rank through call signatures)
        self.proc_attrs: dict = {}
        self.jax_annotations = env_flag("MRTPU_TRACE_JAX", True)
        self.epoch = time.perf_counter()
        # wall-clock origin of the perf_counter timeline: lets a
        # cross-process merge (trace_view over per-rank shards) rebase
        # each process's private ts epoch onto one shared clock
        self.wall_epoch = time.time() - time.perf_counter()
        self.pid = os.getpid()
        self._sinks: List[object] = []
        self._ring: Optional["RingSink"] = None
        self._jsonl: Dict[str, object] = {}   # path → JsonlSink (dedupe)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._id = 0

    # -- span construction --------------------------------------------------
    def span(self, name: str, cat: str = "op", **attrs):
        """A new child span of this thread's current span — or the no-op
        singleton when disabled (the zero-cost fast path)."""
        if not self.enabled:
            return NULL_SPAN
        if self.proc_attrs:
            attrs = {**self.proc_attrs, **attrs}
        return Span(self, name, cat, attrs)

    def annotate(self, **attrs) -> None:
        """Attach attrs to this thread's innermost open span (no-op when
        disabled or no span is open) — how deep layers report tier/shape
        facts without threading span objects through call signatures."""
        if not self.enabled:
            return
        stack = self._stack()
        if stack:
            stack[-1].attrs.update(attrs)

    def current(self):
        stack = self._stack() if self.enabled else None
        return stack[-1] if stack else None

    def set_proc_attrs(self, **attrs) -> None:
        """Merge process-wide span attrs (e.g. ``rank=3``) — stamped on
        every span this tracer creates from now on."""
        self.proc_attrs.update(attrs)

    # -- configuration ------------------------------------------------------
    def enable(self, jsonl: Optional[str] = None, ring: Optional[int] = None):
        """Turn tracing on.  ``jsonl``: also stream events to this path
        (idempotent per path).  ``ring``: in-memory buffer capacity (a
        ring is always attached; default from MRTPU_TRACE_RING or 65536).
        Returns self for chaining."""
        from .sinks import JsonlSink, RingSink
        with self._lock:
            if self._ring is None:
                cap = ring or env_knob("MRTPU_TRACE_RING", int, 65536)
                self._ring = RingSink(cap)
                self._sinks.append(self._ring)
            if jsonl and jsonl not in self._jsonl:
                sink = JsonlSink(jsonl)
                self._jsonl[jsonl] = sink
                self._sinks.append(sink)
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    def subscribe(self, fn) -> None:
        """Register ``fn(event_dict)`` as a sink and enable tracing —
        the external-consumer hook.  Goes through enable() so the ring
        (and hence events()/stats()/dump_trace) works too."""
        from .sinks import CallbackSink
        self.enable()
        with self._lock:
            self._sinks.append(CallbackSink(fn))

    def subscribe_once(self, fn) -> None:
        """subscribe() unless ``fn`` already is — check and append under
        ONE lock hold, so concurrent enables (two threads constructing
        MapReduce(metrics_port=...)) cannot double-subscribe the metrics
        bridge / flight ring and double-count every span; long-lived
        consumers also re-arm safely after a reset().  Membership is by
        ``==``, not ``is``: a bound method (the flight recorder's
        ``rec.emit``) is a fresh object per access but compares equal."""
        from .sinks import CallbackSink
        self.enable()
        with self._lock:
            if not any(isinstance(s, CallbackSink) and s.fn == fn
                       for s in self._sinks):
                self._sinks.append(CallbackSink(fn))

    def unsubscribe(self, fn) -> None:
        """Detach a callback sink subscribed via subscribe[_once] (by
        ``==``, matching subscribe_once's membership rule).  A consumer
        with a bounded lifetime — the serve/ daemon's per-session event
        feed — must detach on shutdown or every emission keeps paying
        for a dead listener."""
        from .sinks import CallbackSink
        with self._lock:
            self._sinks = [s for s in self._sinks
                           if not (isinstance(s, CallbackSink)
                                   and s.fn == fn)]

    def reset(self) -> None:
        """Drop sinks/events and disable (test isolation)."""
        self.enabled = False
        with self._lock:
            for s in self._sinks:
                close = getattr(s, "close", None)
                if close:
                    try:
                        close()
                    except Exception:
                        pass
            self._sinks = []
            self._ring = None
            self._jsonl = {}

    # -- event access -------------------------------------------------------
    def events(self) -> list:
        """Snapshot of the in-memory ring (empty when never enabled)."""
        return self._ring.snapshot() if self._ring is not None else []

    def clear(self) -> None:
        """Drop buffered ring events (sinks stay attached) — e.g. to
        separate a warmup run from the timed run."""
        if self._ring is not None:
            # mrlint: disable=lock-unguarded-mutation — RingSink.clear
            # takes the sink's OWN lock; self._lock only guards the
            # _ring/_jsonl attachment maps, not ring contents
            self._ring.clear()

    def stats(self) -> dict:
        """Per-op aggregate over the ring (see report.aggregate_ops)."""
        from .report import aggregate_ops
        return aggregate_ops(self.events())

    # -- internals ----------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _emit(self, span: Span) -> None:
        ev = span.event()
        with self._lock:
            sinks = list(self._sinks)
        for s in sinks:
            try:
                s.emit(ev)
            except Exception:
                # a broken sink (full disk, closed file) must never fail
                # the traced op; drop it fully — including its jsonl
                # dedup entry, so a later enable(jsonl=path) can attach
                # a fresh sink instead of silently no-opping
                with self._lock:
                    if s in self._sinks:
                        self._sinks.remove(s)
                    for path, sink in list(self._jsonl.items()):
                        if sink is s:
                            del self._jsonl[path]
                close = getattr(s, "close", None)
                if close:
                    try:
                        close()
                    except Exception:
                        pass


def configure_from_env(tracer: Tracer) -> Tracer:
    """Apply MRTPU_TRACE (JSONL path, or '1' for ring-only) if set."""
    path = env_str("MRTPU_TRACE", None)
    if path:
        tracer.enable(jsonl=None if path == "1" else path)
    return tracer


_GLOBAL: Optional[Tracer] = None
_GLOBAL_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer (created on first use; MRTPU_TRACE in
    the environment auto-enables it)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = configure_from_env(Tracer())
    return _GLOBAL
