"""Flight recorder: a bounded ring of recent spans + counter snapshots
that dumps a forensic JSON artifact when a run dies.

VERDICT r5's failure mode: 543 consecutive TPU probe FAILs left nothing
but an unstructured text log — a dead capture window with no evidence of
what the process was doing when it died.  The recorder subscribes to the
process tracer (so its ring always holds the last N finished spans) and
dumps on:

* any unhandled exception (``sys.excepthook`` chain) — the "unhandled
  MRError" / interpreter-exit-with-failure case;
* ``SIGUSR1`` — poke a live-but-suspect run from outside
  (``kill -USR1 <pid>``) without stopping it;
* an explicit :meth:`FlightRecorder.dump` call.

The artifact (``mr_flight.<pid>.<seq>.json`` under the configured
directory) carries the reason, the last spans (matching the tail of any
JSONL trace sink — both fed by the same emissions), the cumulative
``Counters`` snapshot, plan-cache stats, and the metrics snapshot when
the registry is armed.

Enable via ``MRTPU_FLIGHT=<dir>`` (or ``1`` for the working directory),
or implicitly through :func:`obs.metrics.enable_metrics`;
``MRTPU_FLIGHT=0`` keeps it off.  ``MRTPU_FLIGHT_RING`` bounds the span
ring (default 2048).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional


class FlightRecorder:
    """The ring + dumper.  ``emit`` is a tracer sink; every method is
    crash-proof — a recorder bug must never mask the original failure."""

    def __init__(self, dir: str = ".", capacity: Optional[int] = None):
        self.dir = dir
        from ..utils.env import env_knob
        cap = capacity or env_knob("MRTPU_FLIGHT_RING", int, 2048)
        self.events: deque = deque(maxlen=cap)
        self._lock = threading.Lock()
        self._seq = 0
        self.last_dump: Optional[str] = None

    # -- tracer sink --------------------------------------------------------
    def emit(self, ev: dict) -> None:
        with self._lock:
            self.events.append(ev)

    # -- artifact -----------------------------------------------------------
    def snapshot(self, reason: str = "snapshot") -> dict:
        from ..core.runtime import global_counters
        from .context import current_trace_id
        with self._lock:
            spans = list(self.events)
        doc = {"reason": reason,
               "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
               "pid": os.getpid(),
               "argv": list(sys.argv),
               # the request the dumping thread was serving (None when
               # no context is active); every ringed span additionally
               # carries its OWN "trace" id, so a multi-tenant dump
               # still attributes each span to its request
               "trace_id": current_trace_id(),
               "counters": global_counters().snapshot(),
               "spans": spans}
        try:
            from ..plan.cache import cache_stats
            doc["plan"] = cache_stats()
        except Exception:
            pass
        try:
            from . import metrics as _metrics
            if _metrics.enabled():
                doc["metrics"] = _metrics.snapshot()
        except Exception:
            pass
        try:
            # multi-process data plane: the peer lease table (heartbeat
            # ages, fence state) — a PeerLostError dump must answer
            # "who died, and when" from the artifact alone.  Late
            # import; parallel/dist pulls in no obs/ at module level.
            from ..parallel import dist as _dist
            rt = _dist.active()
            if rt is not None:
                doc["dist"] = _dist.lease_table(rt)
        except Exception:
            pass
        return doc

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Write the artifact; returns its path (None when even the
        write fails — never raises)."""
        try:
            from .sinks import _jsonable
            with self._lock:
                self._seq += 1
                seq = self._seq
            if self.dir not in ("", "."):
                os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(
                self.dir, f"mr_flight.{os.getpid()}.{seq}.json")
            doc = self.snapshot(reason)
            with open(path, "w") as f:
                json.dump(doc, f, default=_jsonable)
            self.last_dump = path
            print(f"flight recorder: {reason} -> {path}", file=sys.stderr)
            return path
        except Exception:
            return None


_RECORDER: Optional[FlightRecorder] = None
_LOCK = threading.Lock()
_HOOKED = False


def get() -> Optional[FlightRecorder]:
    return _RECORDER


def enable(dir: Optional[str] = None,
           capacity: Optional[int] = None) -> FlightRecorder:
    """Arm the recorder (idempotent): subscribe its ring to the tracer
    (enables tracing), chain ``sys.excepthook``, install the SIGUSR1
    handler (main thread only — silently skipped elsewhere)."""
    global _RECORDER, _HOOKED
    with _LOCK:
        if _RECORDER is None:
            if dir is None:
                from ..utils.env import env_str
                env = env_str("MRTPU_FLIGHT", "")
                dir = env if env not in ("", "0", "1") else "."
            _RECORDER = FlightRecorder(dir=dir, capacity=capacity)
        elif dir is not None:
            _RECORDER.dir = dir
        rec = _RECORDER
    from .tracer import get_tracer
    get_tracer().subscribe_once(rec.emit)
    with _LOCK:
        if not _HOOKED:
            _HOOKED = True
            _install_hooks()
    return rec


def _install_hooks() -> None:
    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        rec = _RECORDER
        if rec is not None and not issubclass(
                exc_type, (SystemExit, KeyboardInterrupt)):
            rec.dump(f"unhandled:{exc_type.__name__}")
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook
    try:
        prev_sig = signal.getsignal(signal.SIGUSR1)

        def on_usr1(signum, frame):
            rec = _RECORDER
            if rec is not None:
                # dump on a SEPARATE thread: the handler runs on the
                # main thread at a bytecode boundary, possibly INSIDE a
                # ring/metrics lock section — dumping inline would
                # re-acquire those non-reentrant locks and deadlock the
                # run this signal was meant to merely poke.  The dump
                # thread just blocks until the handler returns and the
                # interrupted code releases its locks.
                threading.Thread(target=rec.dump, args=("SIGUSR1",),
                                 daemon=True,
                                 name="mrtpu-flight-dump").start()
            if callable(prev_sig):
                prev_sig(signum, frame)

        signal.signal(signal.SIGUSR1, on_usr1)
    except (ValueError, AttributeError, OSError):
        # not the main thread, or a platform without SIGUSR1 — the
        # excepthook path still works
        pass


def reset() -> None:
    """Drop the recorder (test isolation).  The installed hooks stay
    (they no-op with no recorder) — re-installing per test would build
    an unbounded excepthook chain."""
    global _RECORDER
    with _LOCK:
        _RECORDER = None
