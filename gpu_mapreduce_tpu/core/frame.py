"""KV and KMV frames — the in-memory unit of data.

A *frame* is the TPU-native replacement for one reference "page"
(``src/keyvalue.h:83-92``): an immutable batch of key/value pairs (KVFrame) or
grouped key/multivalue pairs (KMVFrame).  A dataset (``KeyValue`` /
``KeyMultiValue`` in ``dataset.py``) is a list of frames, exactly as a
reference KV is a list of pages — frames past the memory budget spill to host
DRAM (and optionally disk) instead of staying in HBM.

KMV layout: the reference packs ``[nvalue][keybytes][mvbytes][valuesizes[]]
[key][values]`` per group (``src/keymultivalue.h:23-196``).  Columnar
equivalent: unique keys ``[g]``, per-group counts ``[g]``, exclusive offsets
``[g+1]``, and a flat value column ``[n]`` whose rows are grouped
contiguously.  A group larger than one frame's budget is the reference's
"extended"/multi-block KMV (``src/keymultivalue.cpp:1219-1350``); here any
group is already contiguous so blocks are just sub-slices — see
``KMVFrame.blocks_of``.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from .column import BytesColumn, Column, DenseColumn, as_column


class KVFrame:
    """Immutable batch of (key, value) pairs."""

    __slots__ = ("key", "value")

    def __init__(self, key: Column, value: Column):
        key = as_column(key)
        value = as_column(value)
        assert len(key) == len(value), (len(key), len(value))
        self.key = key
        self.value = value

    def __len__(self) -> int:
        return len(self.key)

    @property
    def nkv(self) -> int:
        return len(self.key)

    def nbytes(self) -> int:
        return self.key.nbytes() + self.value.nbytes()

    def to_host(self) -> "KVFrame":
        return KVFrame(self.key.to_host(), self.value.to_host())

    def take(self, idx) -> "KVFrame":
        return KVFrame(self.key.take(idx), self.value.take(idx))

    def slice(self, start: int, stop: int) -> "KVFrame":
        return KVFrame(self.key.slice(start, stop), self.value.slice(start, stop))

    def pairs(self) -> Iterator[Tuple[object, object]]:
        """Host iteration as python scalars — the per-pair callback view
        (what the reference hands to appmap/appreduce callbacks)."""
        yield from zip(self.key.tolist(), self.value.tolist())

    def is_dense(self) -> bool:
        return isinstance(self.key, DenseColumn) and isinstance(self.value, DenseColumn)

    def __repr__(self):
        return f"KVFrame(n={len(self)}, key={self.key!r}, value={self.value!r})"


class KMVFrame:
    """Immutable batch of (key, multivalue) groups.

    ``offsets`` has length g+1; group i's values are
    ``values[offsets[i]:offsets[i+1]]``.
    """

    __slots__ = ("key", "nvalues", "offsets", "values")

    def __init__(self, key: Column, nvalues, offsets, values: Column):
        self.key = as_column(key)
        self.nvalues = np.asarray(nvalues, dtype=np.int64)
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self.values = as_column(values)
        assert len(self.offsets) == len(self.key) + 1

    def __len__(self) -> int:
        return len(self.key)

    @property
    def nkmv(self) -> int:
        return len(self.key)

    @property
    def nvalues_total(self) -> int:
        return len(self.values)

    def nbytes(self) -> int:
        return self.key.nbytes() + self.values.nbytes() + self.nvalues.nbytes

    def to_host(self) -> "KMVFrame":
        return KMVFrame(self.key.to_host(), self.nvalues, self.offsets,
                        self.values.to_host())

    def group_values(self, i: int) -> Column:
        return self.values.slice(int(self.offsets[i]), int(self.offsets[i + 1]))

    def groups(self) -> Iterator[Tuple[object, list]]:
        """Host iteration: (key, [values]) per group — the appreduce view
        (reference src/mapreduce.cpp:1804-1849)."""
        keys = self.key.tolist()
        vals = self.values.tolist()
        for i, k in enumerate(keys):
            yield k, vals[int(self.offsets[i]):int(self.offsets[i + 1])]

    def blocks_of(self, i: int, block_rows: int) -> Iterator[Column]:
        """Iterate one group's values in blocks of ≤ block_rows rows — the
        multi-block KMV API (reference multivalue_blocks()/multivalue_block(),
        src/mapreduce.cpp:1874-1925, doc/Technical.txt:316-320)."""
        start, stop = int(self.offsets[i]), int(self.offsets[i + 1])
        for s in range(start, stop, block_rows):
            yield self.values.slice(s, min(s + block_rows, stop))

    def is_dense(self) -> bool:
        return isinstance(self.key, DenseColumn) and isinstance(self.values, DenseColumn)

    def __repr__(self):
        return (f"KMVFrame(g={len(self)}, n={self.nvalues_total}, "
                f"key={self.key!r}, values={self.values!r})")


class BlockedMultivalue:
    """The reference's "extended" multi-page KMV handle: a reduce callback
    receives this instead of a value list when a group exceeds
    ``block_rows`` (the reference signals with ``nvalues==0`` and the
    callback pulls pages via ``multivalue_blocks()``/``multivalue_block()``,
    src/mapreduce.cpp:1874-1925).  Iterating yields one value-list block
    at a time, so a group of any size streams through bounded memory."""

    __slots__ = ("_frame", "_i", "block_rows")

    def __init__(self, frame: "KMVFrame", i: int, block_rows: int):
        self._frame = frame
        self._i = i
        self.block_rows = block_rows

    @property
    def nvalues_total(self) -> int:
        return int(self._frame.nvalues[self._i])

    def __len__(self) -> int:
        return self.nvalues_total

    def __iter__(self):
        for col in self._frame.blocks_of(self._i, self.block_rows):
            yield col.tolist()


def iter_blocks(multivalue) -> Iterator[list]:
    """Normalise a reduce callback's multivalue: yields value-list blocks
    whether it got a plain list or a :class:`BlockedMultivalue` — the
    CHECK_FOR_BLOCKS/BEGIN_BLOCK_LOOP/END_BLOCK_LOOP idiom of
    ``oink/blockmacros.h`` as one generator."""
    if isinstance(multivalue, BlockedMultivalue):
        yield from multivalue
    else:
        yield multivalue


def empty_kv() -> KVFrame:
    return KVFrame(DenseColumn(np.zeros(0, np.uint64)),
                   DenseColumn(np.zeros(0, np.uint64)))
