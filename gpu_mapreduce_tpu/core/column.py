"""Column types backing KV/KMV datasets.

The reference packs every key/value into byte-aligned pages
(``src/keyvalue.cpp:343-392``: ``[keybytes][valuebytes][key pad][value pad]``).
A TPU wants fixed-width lanes, so we go columnar instead (SURVEY.md §7):

* :class:`DenseColumn` — fixed-width numeric data, shape ``[n]`` or
  ``[n, w]``; lives as a ``numpy`` or ``jax`` array and moves between the two
  lazily.  This is the fast path: every oink graph workload uses fixed-width
  struct keys/values (``oink/typedefs.h:22-40`` VERTEX=uint64, EDGE={vi,vj},
  WEIGHT=double).
* :class:`BytesColumn` — arbitrary per-row byte strings (object ndarray),
  host-only; the analogue of the reference's variable-length byte path.  It
  can be *interned* to a u64 DenseColumn plus a host-side id→bytes dictionary
  so shuffles/group-bys run on device (SURVEY.md §7 "hard parts").

Both support the minimal op set the runtime needs: ``take`` (gather by row
index), ``concat``, ``slice``, and conversion to/from host.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.hash import hash_bytes64_batch

ArrayLike = Union[np.ndarray, jax.Array]


def _is_device(arr) -> bool:
    return isinstance(arr, jax.Array)


class Column:
    """Abstract base: a sequence of n fixed-arity rows."""

    def __len__(self) -> int:
        raise NotImplementedError

    def take(self, idx) -> "Column":
        raise NotImplementedError

    def slice(self, start: int, stop: int) -> "Column":
        raise NotImplementedError

    def to_host(self) -> "Column":
        raise NotImplementedError

    def nbytes(self) -> int:
        raise NotImplementedError

    def tolist(self) -> list:
        """Rows as python scalars/tuples/bytes (for host callbacks/printing)."""
        raise NotImplementedError


class DenseColumn(Column):
    __slots__ = ("data",)

    def __init__(self, data: ArrayLike):
        if not (_is_device(data) or isinstance(data, np.ndarray)):
            data = np.asarray(data)
        if data.ndim == 0:
            data = data.reshape(1)
        assert data.ndim in (1, 2), f"column rank must be 1 or 2, got {data.ndim}"
        self.data = data

    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def width(self) -> int:
        return 1 if self.data.ndim == 1 else int(self.data.shape[1])

    @property
    def dtype(self):
        return self.data.dtype

    def device(self) -> "DenseColumn":
        return self if _is_device(self.data) else DenseColumn(jnp.asarray(self.data))

    def to_host(self) -> "DenseColumn":
        return DenseColumn(np.asarray(self.data)) if _is_device(self.data) else self

    def take(self, idx) -> "DenseColumn":
        xp = jnp if _is_device(self.data) or _is_device(idx) else np
        return DenseColumn(xp.asarray(self.data)[xp.asarray(idx)])

    def slice(self, start: int, stop: int) -> "DenseColumn":
        return DenseColumn(self.data[start:stop])

    def nbytes(self) -> int:
        return int(self.data.size) * self.data.dtype.itemsize

    def tolist(self) -> list:
        host = np.asarray(self.data)
        if host.ndim == 1:
            return host.tolist()
        return [tuple(row) for row in host.tolist()]

    def __repr__(self):
        where = "dev" if _is_device(self.data) else "host"
        return f"DenseColumn<{self.data.dtype}{list(self.data.shape)}@{where}>"


class BytesColumn(Column):
    """Host column of arbitrary byte strings (reference's byte-packed path)."""

    __slots__ = ("data",)

    def __init__(self, data: Sequence[bytes]):
        if isinstance(data, np.ndarray) and data.dtype == object:
            self.data = data
        else:
            arr = np.empty(len(data), dtype=object)
            for i, x in enumerate(data):
                arr[i] = x if isinstance(x, bytes) else bytes(x)
            self.data = arr

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def to_host(self) -> "BytesColumn":
        return self

    def take(self, idx) -> "BytesColumn":
        return BytesColumn(self.data[np.asarray(idx)])

    def slice(self, start: int, stop: int) -> "BytesColumn":
        return BytesColumn(self.data[start:stop])

    def nbytes(self) -> int:
        return int(sum(len(x) for x in self.data))

    def tolist(self) -> list:
        return self.data.tolist()

    def intern(self) -> tuple:
        """Map byte strings to u64 ids for device-side shuffling/grouping.

        Returns ``(DenseColumn[uint64], {id: bytes})``.  All-vectorised:
        native batch hash of every row, numeric unique for the table,
        and — only when duplicate ids exist — an independent second hash
        family detects collisions (one id, two alts), the same standard
        the device tier uses (apps/invertedindex).  The former per-row
        Python dict loop was the aggregate hot spot on heavy-repetition
        columns (wordfreq tokens)."""
        strings = [bytes(s) for s in self.data]
        ids, table = _intern_ids(strings, strings, "bytes")
        return DenseColumn(ids), table

    def __repr__(self):
        return f"BytesColumn<n={len(self)}>"


def _intern_ids(strings, rows, kind: str):
    """Shared vectorised intern core: hash ``strings`` (the per-row
    bytes), build the id→``rows[i]`` table from the first occurrence of
    each unique id, and — when duplicate ids exist — verify them with
    an independent second hash family (same id + different alt = a real
    collision; both families agreeing on distinct inputs is ~2^-128,
    the device tier's standard, apps/invertedindex).  The byte buffer
    packs ONCE for both families.  Returns (ids uint64[n], InternTable);
    the former per-row Python dict loop was the aggregate hot spot."""
    from .. import native
    if not len(strings):
        return np.zeros(0, np.uint64), InternTable(kind=kind)
    if native.available():
        lens = np.fromiter((len(s) for s in strings), np.int64,
                           count=len(strings))
        offs = np.zeros(len(strings) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        buf = b"".join(strings)
        ids = native.intern64_batch(buf, offs)
        alt = lambda: native.intern_ranges(buf, offs[:-1], lens,
                                           0x9E3779B9, 0x85EBCA6B)
    else:
        ids = hash_bytes64_batch(strings)
        alt = lambda: hash_bytes64_batch(strings, 0x9E3779B9, 0x85EBCA6B)
    # ONE stable sort yields unique ids, first-occurrence rows AND the
    # adjacency layout the collision check needs (np.unique would be a
    # second full sort on this hot path)
    order = np.argsort(ids, kind="stable")
    si = ids[order]
    head = np.ones(len(si), bool)
    head[1:] = si[1:] != si[:-1]
    if not head.all():
        alts = alt()
        sa = alts[order]
        # no collision ⇒ every row of an id shares one alt; a collision
        # puts ≥2 alt values in some id run ⇒ some adjacent pair differs
        bad = ~head[1:] & (sa[1:] != sa[:-1])
        if bad.any():
            i = int(np.nonzero(bad)[0][0])
            raise ValueError(
                "64-bit intern collision between %r and %r"
                % (strings[order[i]], strings[order[i + 1]]))
    table = InternTable(((int(h), rows[int(i)]) for h, i in
                         zip(si[head], order[head])), kind=kind)
    return ids, table


class InternTable(dict):
    """id→key table from Column.intern(); ``kind`` records whether the
    decoded keys are raw bytes or arbitrary objects so the decode side
    rebuilds the right column type (no first-row guessing)."""

    def __init__(self, *a, kind: str = "bytes", **kw):
        super().__init__(*a, **kw)
        self.kind = kind


class ObjectColumn(Column):
    """Host column of ARBITRARY pickled python objects — the tier behind
    the reference's Python wrapper, which cPickles any key/value into the
    byte-packed KV (``python/mrmpi.py:17-45``, ``doc/Technical.txt:375-418``).

    Rows compare/group/sort by their pickled bytes (exactly the
    reference's semantics: the C++ core sees only the pickle), so keys
    need not be hashable or orderable themselves."""

    __slots__ = ("data", "_pickles")

    def __init__(self, data: Sequence):
        if isinstance(data, np.ndarray) and data.dtype == object:
            self.data = data
        else:
            arr = np.empty(len(data), dtype=object)
            for i, x in enumerate(data):
                arr[i] = x
            self.data = arr
        self._pickles: Optional[List[bytes]] = None

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def to_host(self) -> "ObjectColumn":
        return self

    def take(self, idx) -> "ObjectColumn":
        return ObjectColumn(self.data[np.asarray(idx)])

    def slice(self, start: int, stop: int) -> "ObjectColumn":
        return ObjectColumn(self.data[start:stop])

    def pickles(self) -> List[bytes]:
        """Per-row pickles, computed once — nbytes/sort/intern all consume
        these and a budget check per push must not re-pickle the world."""
        if self._pickles is None:
            import pickle
            self._pickles = [pickle.dumps(x, protocol=4) for x in self.data]
        return self._pickles

    def nbytes(self) -> int:
        return int(sum(len(p) for p in self.pickles()))

    def tolist(self) -> list:
        return self.data.tolist()

    def intern(self) -> tuple:
        """Objects → u64 ids via their pickles (see BytesColumn.intern);
        the id→object table stays controller-side."""
        ids, table = _intern_ids(self.pickles(), self.data.tolist(),
                                 "object")
        return DenseColumn(ids), table

    def __repr__(self):
        return f"ObjectColumn<n={len(self)}>"


def concat(cols: List[Column]) -> Column:
    cols = [c for c in cols if len(c) > 0] or cols[:1]
    if len(cols) == 1:
        return cols[0]
    if any(isinstance(c, ObjectColumn) for c in cols):
        # bytes are picklable objects: a mix of Bytes/Object frames (from
        # separate add-buffer flushes) promotes to the object tier
        if not all(isinstance(c, (ObjectColumn, BytesColumn))
                   for c in cols):
            raise TypeError("cannot concat object rows with numeric rows")
        return ObjectColumn(np.concatenate([c.data for c in cols]))
    first = cols[0]
    if isinstance(first, BytesColumn):
        if not all(isinstance(c, BytesColumn) for c in cols):
            raise TypeError("cannot concat byte rows with numeric rows")
        return BytesColumn(np.concatenate([c.data for c in cols]))
    assert all(isinstance(c, DenseColumn) for c in cols)
    if any(_is_device(c.data) for c in cols):
        return DenseColumn(jnp.concatenate([jnp.asarray(c.data) for c in cols], axis=0))
    return DenseColumn(np.concatenate([c.data for c in cols], axis=0))


def as_column(x) -> Column:
    """Coerce user-supplied data to a Column.

    bytes/str sequences → BytesColumn; numeric arrays/sequences → DenseColumn.
    """
    if isinstance(x, Column):
        return x
    if isinstance(x, (bytes, str)):
        return BytesColumn([x if isinstance(x, bytes) else x.encode()])
    if isinstance(x, np.ndarray) and x.dtype == object:
        return BytesColumn(x)
    if _is_device(x) or isinstance(x, np.ndarray):
        return DenseColumn(x)
    if isinstance(x, (list, tuple)) and len(x) > 0 and isinstance(x[0], (bytes, str)):
        return BytesColumn([s if isinstance(s, bytes) else s.encode() for s in x])
    return DenseColumn(np.asarray(x))


def empty_like(col: Column) -> Column:
    if isinstance(col, BytesColumn):
        return BytesColumn([])
    data = col.data
    shape = (0,) if data.ndim == 1 else (0, data.shape[1])
    if _is_device(data):
        return DenseColumn(jnp.zeros(shape, dtype=data.dtype))
    return DenseColumn(np.zeros(shape, dtype=data.dtype))
