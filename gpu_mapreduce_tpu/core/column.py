"""Column types backing KV/KMV datasets.

The reference packs every key/value into byte-aligned pages
(``src/keyvalue.cpp:343-392``: ``[keybytes][valuebytes][key pad][value pad]``).
A TPU wants fixed-width lanes, so we go columnar instead (SURVEY.md §7):

* :class:`DenseColumn` — fixed-width numeric data, shape ``[n]`` or
  ``[n, w]``; lives as a ``numpy`` or ``jax`` array and moves between the two
  lazily.  This is the fast path: every oink graph workload uses fixed-width
  struct keys/values (``oink/typedefs.h:22-40`` VERTEX=uint64, EDGE={vi,vj},
  WEIGHT=double).
* :class:`BytesColumn` — arbitrary per-row byte strings (object ndarray),
  host-only; the analogue of the reference's variable-length byte path.  It
  can be *interned* to a u64 DenseColumn plus a host-side id→bytes dictionary
  so shuffles/group-bys run on device (SURVEY.md §7 "hard parts").

Both support the minimal op set the runtime needs: ``take`` (gather by row
index), ``concat``, ``slice``, and conversion to/from host.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.hash import hash_bytes64_batch

ArrayLike = Union[np.ndarray, jax.Array]


def _is_device(arr) -> bool:
    return isinstance(arr, jax.Array)


class Column:
    """Abstract base: a sequence of n fixed-arity rows."""

    def __len__(self) -> int:
        raise NotImplementedError

    def take(self, idx) -> "Column":
        raise NotImplementedError

    def slice(self, start: int, stop: int) -> "Column":
        raise NotImplementedError

    def to_host(self) -> "Column":
        raise NotImplementedError

    def nbytes(self) -> int:
        raise NotImplementedError

    def tolist(self) -> list:
        """Rows as python scalars/tuples/bytes (for host callbacks/printing)."""
        raise NotImplementedError


class DenseColumn(Column):
    __slots__ = ("data",)

    def __init__(self, data: ArrayLike):
        if not (_is_device(data) or isinstance(data, np.ndarray)):
            data = np.asarray(data)
        if data.ndim == 0:
            data = data.reshape(1)
        assert data.ndim in (1, 2), f"column rank must be 1 or 2, got {data.ndim}"
        self.data = data

    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def width(self) -> int:
        return 1 if self.data.ndim == 1 else int(self.data.shape[1])

    @property
    def dtype(self):
        return self.data.dtype

    def device(self) -> "DenseColumn":
        return self if _is_device(self.data) else DenseColumn(jnp.asarray(self.data))

    def to_host(self) -> "DenseColumn":
        return DenseColumn(np.asarray(self.data)) if _is_device(self.data) else self

    def take(self, idx) -> "DenseColumn":
        xp = jnp if _is_device(self.data) or _is_device(idx) else np
        return DenseColumn(xp.asarray(self.data)[xp.asarray(idx)])

    def slice(self, start: int, stop: int) -> "DenseColumn":
        return DenseColumn(self.data[start:stop])

    def nbytes(self) -> int:
        return int(self.data.size) * self.data.dtype.itemsize

    def tolist(self) -> list:
        host = np.asarray(self.data)
        if host.ndim == 1:
            return host.tolist()
        return [tuple(row) for row in host.tolist()]

    def __repr__(self):
        where = "dev" if _is_device(self.data) else "host"
        return f"DenseColumn<{self.data.dtype}{list(self.data.shape)}@{where}>"


class BytesColumn(Column):
    """Host column of arbitrary byte strings (reference's byte-packed path)."""

    __slots__ = ("data",)

    def __init__(self, data: Sequence[bytes]):
        if isinstance(data, np.ndarray) and data.dtype == object:
            self.data = data
        else:
            arr = np.empty(len(data), dtype=object)
            for i, x in enumerate(data):
                arr[i] = x if isinstance(x, bytes) else bytes(x)
            self.data = arr

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def to_host(self) -> "BytesColumn":
        return self

    def take(self, idx) -> "BytesColumn":
        return BytesColumn(self.data[np.asarray(idx)])

    def slice(self, start: int, stop: int) -> "BytesColumn":
        return BytesColumn(self.data[start:stop])

    def nbytes(self) -> int:
        return int(sum(len(x) for x in self.data))

    def tolist(self) -> list:
        return self.data.tolist()

    def intern(self) -> tuple:
        """Map byte strings to u64 ids for device-side shuffling/grouping.

        Returns ``(DenseColumn[uint64], {id: bytes})``.  All-vectorised:
        native batch hash of every row, numeric unique for the table,
        and — only when duplicate ids exist — an independent second hash
        family detects collisions (one id, two alts), the same standard
        the device tier uses (apps/invertedindex).  The former per-row
        Python dict loop was the aggregate hot spot on heavy-repetition
        columns (wordfreq tokens)."""
        strings = [bytes(s) for s in self.data]
        ids, table = _intern_ids(strings, strings, "bytes")
        return DenseColumn(ids), table

    def intern_sharded(self, tables: "ShardTables") -> "DenseColumn":
        """Intern into dest-sharded decode tables — no controller-global
        dict ever builds (VERDICT r4 #5); cross-batch collisions surface
        in ShardTables.absorb."""
        strings = [bytes(s) for s in self.data]
        ids, uniq, first = _intern_core(strings)
        tables.absorb(uniq, [strings[int(i)] for i in first])
        return DenseColumn(ids)

    def __repr__(self):
        return f"BytesColumn<n={len(self)}>"


def _intern_ids(strings, rows, kind: str):
    """Shared vectorised intern core: hash ``strings`` (the per-row
    bytes), build the id→``rows[i]`` table from the first occurrence of
    each unique id, and — when duplicate ids exist — verify them with
    an independent second hash family (same id + different alt = a real
    collision; both families agreeing on distinct inputs is ~2^-128,
    the device tier's standard, apps/invertedindex).  The byte buffer
    packs ONCE for both families.  Returns (ids uint64[n], InternTable);
    the former per-row Python dict loop was the aggregate hot spot."""
    ids, uniq, first = _intern_core(strings)
    table = InternTable(((int(h), rows[int(i)]) for h, i in
                         zip(uniq, first)), kind=kind)
    return ids, table


def _intern_core(strings):
    """Hash + collision-check core shared by the global and the
    dest-sharded intern: returns (ids uint64[n], unique ids uint64[u],
    first-occurrence row index int64[u])."""
    from .. import native
    if not len(strings):
        z = np.zeros(0, np.uint64)
        return z, z, np.zeros(0, np.int64)
    if native.available():
        lens = np.fromiter((len(s) for s in strings), np.int64,
                           count=len(strings))
        offs = np.zeros(len(strings) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        buf = b"".join(strings)
        ids = native.intern64_batch(buf, offs)
        alt = lambda: native.intern_ranges(buf, offs[:-1], lens,
                                           0x9E3779B9, 0x85EBCA6B)
    else:
        ids = hash_bytes64_batch(strings)
        alt = lambda: hash_bytes64_batch(strings, 0x9E3779B9, 0x85EBCA6B)
    # ONE stable sort yields unique ids, first-occurrence rows AND the
    # adjacency layout the collision check needs (np.unique would be a
    # second full sort on this hot path)
    order = np.argsort(ids, kind="stable")
    si = ids[order]
    head = np.ones(len(si), bool)
    head[1:] = si[1:] != si[:-1]
    if not head.all():
        alts = alt()
        sa = alts[order]
        # no collision ⇒ every row of an id shares one alt; a collision
        # puts ≥2 alt values in some id run ⇒ some adjacent pair differs
        bad = ~head[1:] & (sa[1:] != sa[:-1])
        if bad.any():
            i = int(np.nonzero(bad)[0][0])
            raise ValueError(
                "64-bit intern collision between %r and %r"
                % (strings[order[i]], strings[order[i + 1]]))
    return ids, si[head], order[head]


def dest_of_ids(ids: np.ndarray, P: int) -> np.ndarray:
    """Aggregate destination shard of each u64 id — the HOST twin of the
    device shuffle's ``default_hash(keys) % P`` (lookup3 over the key's
    little-endian bytes, parallel/shuffle.py).  hash_words32 runs the
    same word-path lookup3 on numpy input, so the routing is bit-
    identical to what the exchange will do on device."""
    from ..ops.hash import hash_words32
    words = np.ascontiguousarray(ids.astype("<u8")).view("<u4")
    return (hash_words32(words.reshape(len(ids), 2)).astype(np.int64)
            % P).astype(np.int32)


class InternTable(dict):
    """id→key table from Column.intern(); ``kind`` records whether the
    decoded keys are raw bytes or arbitrary objects so the decode side
    rebuilds the right column type (no first-row guessing)."""

    def __init__(self, *a, kind: str = "bytes", **kw):
        super().__init__(*a, **kw)
        self.kind = kind

    def decode_batch(self, ids) -> list:
        return [self[int(h)] for h in ids]


class ShardTables:
    """Dest-sharded id→row decode tables (VERDICT r4 #5).

    The reference shuffles raw key bytes fully distributed
    (``src/mapreduce.cpp:453-473``); our exchange moves u64 ids and keeps
    the bytes host-side.  Instead of ONE controller-global dict, every
    (id, bytes) entry lives in the table of the shard the DEFAULT hash
    routes that id to (``dest_of_ids`` — the same lookup3 % P the device
    exchange applies).  Lookups always re-route by the same id hash, so
    decode is correct on every path.  The LOCALITY guarantee — shard d's
    rows decode from ``tables[d]`` alone after an exchange — holds for
    KEY tables under the default aggregate hash (the per-shard output
    case, and the entries a multi-host mesh would keep host-local).  A
    custom hash_fn or the value-side tables still get the size bound
    (~1/P of the id space per table) but place rows independently of
    their table, so cross-table decode_batch routing is the contract
    there, not per-table locality.

    Quacks like the InternTable dict for every existing consumer
    (``__getitem__``/``get``/``decode_batch``/``kind``)."""

    # _rank_cache: sort_interned_sharded memoises its id→rank permutation
    # on the table object (same contract as InternTable's dynamic attr)
    __slots__ = ("tables", "P", "kind", "_probes", "_rank_cache")

    def __init__(self, P: int, kind: str = "bytes"):
        self.P = P
        self.kind = kind
        self.tables = [InternTable(kind=kind) for _ in range(P)]
        # per-DEST id→pickle side tables for object rows — sharded like
        # the row tables, so no flat controller-global dict rebuilds
        # what the class exists to avoid (r5 review)
        self._probes: Optional[list] = None
        self._rank_cache = None

    def merge(self, other) -> "ShardTables":
        """Union with another decode table (ShardTables or plain dict) —
        the concat_sharded / MapReduce.add path.  Everything funnels
        through absorb so overlapping ids get the same cross-batch
        collision check as ingest (and object rows compare by pickle,
        never by __eq__ — r5 review).

        CONTRACT: both tables' ids must live in ONE hash domain.  A
        bytes-kind table hashes raw bytes, an object-kind table hashes
        pickles — merging across kinds would give the same logical key
        two distinct ids (they'd never group).  concat_sharded aligns
        domains first (devkernels._align_domains re-interns the
        bytes-kind side through the pickle domain, ADVICE r5); direct
        callers mixing kinds must do the same."""
        kind = ("object" if "object" in (self.kind,
                                         getattr(other, "kind", "bytes"))
                else "bytes")
        out = ShardTables(self.P, kind=kind)
        for src in (self, other):
            ids = np.fromiter(src.keys(), np.uint64, len(src))
            rows = (src.decode_batch(ids) if hasattr(src, "decode_batch")
                    else [src[int(h)] for h in ids])
            # reuse stored probes (the bytes that were HASHED) instead
            # of re-pickling live rows — cheaper, and immune to objects
            # mutated after ingest (r5 review)
            probes = (src.probes_for(ids)
                      if isinstance(src, ShardTables) else None)
            out.absorb(ids, rows, probes=probes)
        return out

    def probes_for(self, ids: np.ndarray):
        """Stored pickle probes for these ids, or None when this table
        never needed probes (bytes rows compare directly)."""
        if self._probes is None:
            return None
        dests = dest_of_ids(np.asarray(ids, np.uint64), self.P)
        return [self._probes[d][int(h)]
                for h, d in zip(ids.tolist(), dests.tolist())]

    def absorb(self, uniq_ids: np.ndarray, rows: list,
               probes: Optional[list] = None) -> None:
        """Route unique (id, row) pairs into the per-dest tables; a
        pre-existing id with DIFFERENT bytes is a real u64 intern
        collision (cross-batch — within-batch collisions are caught by
        the intern core's alt-family check).  ``probes``: comparison
        bytes when rows are arbitrary objects (object __eq__ is not a
        reliable identity; the pickle is — it IS what was hashed)."""
        if not len(uniq_ids):
            return
        if self.kind == "object" and probes is None:
            # object rows always compare by pickle — normalise here so
            # a probe-less batch (e.g. bytes rows promoted into an
            # object-kind table) can never compare a pickle to a row
            import pickle
            probes = [pickle.dumps(r, protocol=4) for r in rows]
        if probes is not None and self._probes is None:
            self._probes = [{} for _ in range(self.P)]
        dests = dest_of_ids(np.asarray(uniq_ids, np.uint64), self.P)
        for i, (h, d) in enumerate(zip(uniq_ids.tolist(), dests.tolist())):
            t = self.tables[d]
            if h not in t:
                t[h] = rows[i]
                if probes is not None:
                    self._probes[d][h] = probes[i]
                continue
            prev = self._probes[d][h] if probes is not None else t[h]
            cur = probes[i] if probes is not None else rows[i]
            if prev != cur:
                raise ValueError(
                    f"64-bit intern collision: {prev!r} vs {cur!r}")

    def shard(self, d: int) -> InternTable:
        return self.tables[d]

    def __getitem__(self, h):
        return self.tables[int(dest_of_ids(np.array([h], np.uint64),
                                           self.P)[0])][h]

    def get(self, h, default=None):
        try:
            return self[h]
        except KeyError:
            return default

    def __contains__(self, h) -> bool:
        # not via get(): an ObjectColumn row may legitimately BE None
        try:
            self[h]
            return True
        except KeyError:
            return False

    def __len__(self) -> int:
        return sum(len(t) for t in self.tables)

    def decode_batch(self, ids) -> list:
        """Vectorised decode: one dest computation for the whole id
        array, then per-shard dict lookups (the scalar __getitem__ would
        pay a hash dispatch per row)."""
        ids = np.asarray(ids, np.uint64)
        dests = dest_of_ids(ids, self.P)
        tabs = self.tables
        return [tabs[d][int(h)] for h, d in zip(ids.tolist(),
                                                dests.tolist())]

    def items(self):
        for t in self.tables:
            yield from t.items()

    def keys(self):
        for t in self.tables:
            yield from t.keys()

    def __repr__(self):
        sizes = [len(t) for t in self.tables]
        return f"ShardTables(P={self.P}, kind={self.kind}, sizes={sizes})"


class ObjectColumn(Column):
    """Host column of ARBITRARY pickled python objects — the tier behind
    the reference's Python wrapper, which cPickles any key/value into the
    byte-packed KV (``python/mrmpi.py:17-45``, ``doc/Technical.txt:375-418``).

    Rows compare/group/sort by their pickled bytes (exactly the
    reference's semantics: the C++ core sees only the pickle), so keys
    need not be hashable or orderable themselves."""

    __slots__ = ("data", "_pickles")

    def __init__(self, data: Sequence):
        if isinstance(data, np.ndarray) and data.dtype == object:
            self.data = data
        else:
            arr = np.empty(len(data), dtype=object)
            for i, x in enumerate(data):
                arr[i] = x
            self.data = arr
        self._pickles: Optional[List[bytes]] = None

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def to_host(self) -> "ObjectColumn":
        return self

    def take(self, idx) -> "ObjectColumn":
        return ObjectColumn(self.data[np.asarray(idx)])

    def slice(self, start: int, stop: int) -> "ObjectColumn":
        return ObjectColumn(self.data[start:stop])

    def pickles(self) -> List[bytes]:
        """Per-row pickles, computed once — nbytes/sort/intern all consume
        these and a budget check per push must not re-pickle the world."""
        if self._pickles is None:
            import pickle
            self._pickles = [pickle.dumps(x, protocol=4) for x in self.data]
        return self._pickles

    def nbytes(self) -> int:
        return int(sum(len(p) for p in self.pickles()))

    def tolist(self) -> list:
        return self.data.tolist()

    def intern(self) -> tuple:
        """Objects → u64 ids via their pickles (see BytesColumn.intern);
        the id→object table stays controller-side."""
        ids, table = _intern_ids(self.pickles(), self.data.tolist(),
                                 "object")
        return DenseColumn(ids), table

    def intern_sharded(self, tables: "ShardTables") -> "DenseColumn":
        """See BytesColumn.intern_sharded; rows are the live objects,
        compared across batches by their pickles."""
        rows = self.data.tolist()
        pk = self.pickles()
        ids, uniq, first = _intern_core(pk)
        tables.absorb(uniq, [rows[int(i)] for i in first],
                      probes=[pk[int(i)] for i in first])
        return DenseColumn(ids)

    def __repr__(self):
        return f"ObjectColumn<n={len(self)}>"


def concat(cols: List[Column]) -> Column:
    cols = [c for c in cols if len(c) > 0] or cols[:1]
    if len(cols) == 1:
        return cols[0]
    if any(isinstance(c, ObjectColumn) for c in cols):
        # bytes are picklable objects: a mix of Bytes/Object frames (from
        # separate add-buffer flushes) promotes to the object tier
        if not all(isinstance(c, (ObjectColumn, BytesColumn))
                   for c in cols):
            raise TypeError("cannot concat object rows with numeric rows")
        return ObjectColumn(np.concatenate([c.data for c in cols]))
    first = cols[0]
    if isinstance(first, BytesColumn):
        if not all(isinstance(c, BytesColumn) for c in cols):
            raise TypeError("cannot concat byte rows with numeric rows")
        return BytesColumn(np.concatenate([c.data for c in cols]))
    assert all(isinstance(c, DenseColumn) for c in cols)
    if any(_is_device(c.data) for c in cols):
        return DenseColumn(jnp.concatenate([jnp.asarray(c.data) for c in cols], axis=0))
    return DenseColumn(np.concatenate([c.data for c in cols], axis=0))


def as_column(x) -> Column:
    """Coerce user-supplied data to a Column.

    bytes/str sequences → BytesColumn; numeric arrays/sequences → DenseColumn.
    """
    if isinstance(x, Column):
        return x
    if isinstance(x, (bytes, str)):
        return BytesColumn([x if isinstance(x, bytes) else x.encode()])
    if isinstance(x, np.ndarray) and x.dtype == object:
        return BytesColumn(x)
    if _is_device(x) or isinstance(x, np.ndarray):
        return DenseColumn(x)
    if isinstance(x, (list, tuple)) and len(x) > 0 and isinstance(x[0], (bytes, str)):
        return BytesColumn([s if isinstance(s, bytes) else s.encode() for s in x])
    return DenseColumn(np.asarray(x))


def empty_like(col: Column) -> Column:
    if isinstance(col, BytesColumn):
        return BytesColumn([])
    data = col.data
    shape = (0,) if data.ndim == 1 else (0, data.shape[1])
    if _is_device(data):
        return DenseColumn(jnp.zeros(shape, dtype=data.dtype))
    return DenseColumn(np.zeros(shape, dtype=data.dtype))
