"""KeyValue / KeyMultiValue datasets: frame lists with an add/complete
protocol and host-DRAM/disk spill.

This is the TPU re-design of the reference's paged containers:

* ``KeyValue`` (``src/keyvalue.{h,cpp}``) — append-only byte-packed pairs in
  64 MB pages, spilling page-at-a-time to ``fpath/mrmpi.kv.*`` files
  (``src/mapreduce.cpp:3187-3205``).  Here: an append buffer of python rows
  and/or columnar batches that ``complete()`` consolidates into
  :class:`~..core.frame.KVFrame` frames.  Frames beyond the ``maxpage``
  HBM budget live as host numpy; with ``outofcore=1`` they move to ``.npz``
  spill files (same naming scheme), loaded back on demand — the
  ``request_page``/``write_page`` protocol (``src/keyvalue.cpp:277-308,
  688-756``) becomes :meth:`KeyValue.frames` iteration.
* ``KeyMultiValue`` (``src/keymultivalue.{h,cpp}``) — grouped frames.

``add()`` accepts scalars (host path, like kv->add per pair) and
``add_batch()`` accepts whole columns (the vectorised path every kernel op
uses).  ``complete()`` finalises and computes the global pair count, the
analogue of the Allreduce in ``KeyValue::complete`` (src/keyvalue.cpp:216-255).
"""

from __future__ import annotations

import os
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from .column import BytesColumn, Column, DenseColumn, as_column, concat
from .frame import KMVFrame, KVFrame
from .runtime import Counters, Error, Settings

_INSTANCE_COUNTER = [0]
_INSTANCE_LOCK = threading.Lock()


def _next_file_id() -> int:
    # atomic: concurrent -partition worlds (oink/universe.py threads)
    # must never share a spill-file id
    with _INSTANCE_LOCK:
        _INSTANCE_COUNTER[0] += 1
        return _INSTANCE_COUNTER[0]


class _Spilled:
    """A frame parked in an .npz spill file (reference write_page/read_page,
    src/keyvalue.cpp:688-756; naming src/mapreduce.cpp:3187-3205)."""

    __slots__ = ("path", "n", "bytes_")

    def __init__(self, path: str, n: int, bytes_: int):
        self.path = path
        self.n = n
        self.bytes_ = bytes_

    def load(self, counters: Counters) -> KVFrame:
        with np.load(self.path, allow_pickle=True) as z:
            key = _col_from_npz(z, "k")
            value = _col_from_npz(z, "v")
        counters.add(rsize=self.bytes_)
        return KVFrame(key, value)


def _col_to_npz(col: Column, prefix: str, out: dict):
    """Spill one column into npz payload entries.  numpy ≥ 2 refuses to
    save object arrays, so byte strings flatten to buffer+offsets and
    arbitrary objects to one pickle blob (the reference's pages are raw
    bytes on disk too)."""
    from .column import ObjectColumn
    if isinstance(col, ObjectColumn):
        import pickle
        blob = pickle.dumps(col.data.tolist(), protocol=4)
        out[prefix + "_pobj"] = np.frombuffer(blob, np.uint8)
    elif isinstance(col, BytesColumn):
        rows = [bytes(b) for b in col.data]
        out[prefix + "_obj"] = np.frombuffer(b"".join(rows), np.uint8)
        out[prefix + "_obj_off"] = np.concatenate(
            [[0], np.cumsum([len(b) for b in rows])]).astype(np.int64)
    else:
        out[prefix + "_arr"] = np.asarray(col.data)


def _write_spill(settings: Settings, counters: Counters, name: str,
                 fileid: int, seq: int, payload: dict, nbytes: int) -> str:
    """Shared spill writer: fpath dir + mrtpu.<name>.<id>.<seq>.npz naming
    + write-counter accounting (reference file naming
    src/mapreduce.cpp:3187-3205) — one implementation for KV and KMV."""
    os.makedirs(settings.fpath, exist_ok=True)
    path = os.path.join(settings.fpath,
                        f"mrtpu.{name}.{fileid}.{seq}.npz")
    np.savez(path, **payload)
    counters.add(wsize=nbytes)
    return path


def _spill_budget(settings: Settings) -> int:
    return settings.maxpage * settings.memsize * (1 << 20)


def _col_from_npz(z, prefix: str) -> Column:
    if prefix + "_pobj" in z:
        import pickle
        from .column import ObjectColumn
        return ObjectColumn(pickle.loads(z[prefix + "_pobj"].tobytes()))
    if prefix + "_obj" in z:
        buf = z[prefix + "_obj"].tobytes()
        off = z[prefix + "_obj_off"]
        return BytesColumn([buf[off[i]:off[i + 1]]
                            for i in range(len(off) - 1)])
    return DenseColumn(z[prefix + "_arr"])


class KeyValue:
    """Append-only KV dataset (one shard's worth on the serial backend; the
    mesh backend stores per-shard device arrays through the same interface)."""

    def __init__(self, settings: Settings, error: Error, counters: Counters,
                 name: str = "kv"):
        self.settings = settings
        self.error = error
        self.counters = counters
        self.name = name
        self.fileid = _next_file_id()
        self._buf_k: list = []           # scalar append buffer
        self._buf_v: list = []
        self._batches: List[KVFrame] = []  # columnar append buffer
        self._frames: List[object] = []    # KVFrame | _Spilled
        self.nkv = 0
        self.complete_done = False

    # -- add protocol ------------------------------------------------------

    def add(self, key, value):
        """Add one pair (reference kv->add(key,keybytes,value,valuebytes),
        src/keyvalue.cpp:343-392)."""
        self._buf_k.append(key)
        self._buf_v.append(value)
        if len(self._buf_k) >= 1 << 20:
            self._flush_scalars()

    def add_batch(self, keys, values):
        """Add a batch of pairs as columns/arrays (the vectorised fast path —
        replaces the reference's chunked bulk add, src/keyvalue.cpp:526-605)."""
        self._flush_scalars()  # preserve add order when interleaved with add()
        frame = KVFrame(as_column(keys), as_column(values))
        if len(frame):
            self._batches.append(frame)

    def add_kv(self, other: "KeyValue"):
        """Append another KV's pairs (reference MapReduce::add,
        src/mapreduce.cpp:348-374).  Frame OBJECTS are shared, not
        copied — mark them so the exchange's buffer donation (exec/,
        MRTPU_DONATE) never deletes device arrays another dataset still
        reads (an aggregate on one MR must not corrupt its copy())."""
        for fr in other.frames():
            if not isinstance(fr, KVFrame):   # ShardedKV: device arrays
                fr._shared = True             # now alias across datasets
            self._batches.append(fr)

    def add_frame(self, frame):
        """Append a pre-built frame — a KVFrame, or a parallel.ShardedKV
        coming out of a vectorised sharded reduce."""
        self._flush_scalars()
        self._batches.append(frame)

    def _flush_scalars(self):
        if not self._buf_k:
            return
        k = _coerce_rows(self._buf_k)
        v = _coerce_rows(self._buf_v)
        self._batches.append(KVFrame(k, v))
        self._buf_k, self._buf_v = [], []

    # -- completion --------------------------------------------------------

    def complete(self):
        """Finalise: consolidate buffers into budget-sized frames
        (reference KeyValue::complete, src/keyvalue.cpp:216-255)."""
        self._flush_scalars()
        plain = [b for b in self._batches if isinstance(b, KVFrame)]
        opaque = [b for b in self._batches if not isinstance(b, KVFrame)]
        self._batches = []
        if plain:
            merged = _merge_frames(plain)
            for fr in _split_to_budget(merged, self.settings):
                self._push_frame(fr)
        for f in opaque:  # sharded frames bypass the page splitter
            self._frames.append(f)
            self.counters.mem(f.nbytes())
        self.nkv = sum(self._frame_n(f) for f in self._frames)
        self.complete_done = True
        return self.nkv

    def append(self):
        """Re-open a completed KV for more adds (reference KeyValue::append,
        src/keyvalue.cpp:185-209)."""
        self.complete_done = False

    def _frame_n(self, f) -> int:
        return f.n if isinstance(f, _Spilled) else len(f)  # len covers ShardedKV too

    def _push_frame(self, fr: KVFrame):
        budget = _spill_budget(self.settings)
        if (self.settings.outofcore == 1 and budget
                and self._resident_bytes() + fr.nbytes() > budget):
            self._spill(fr)
        else:
            self._frames.append(fr)
            self.counters.mem(fr.nbytes())

    def _resident_bytes(self) -> int:
        return sum(f.nbytes() for f in self._frames if isinstance(f, KVFrame))

    def _spill(self, fr: KVFrame):
        payload: dict = {}
        _col_to_npz(fr.key.to_host(), "k", payload)
        _col_to_npz(fr.value.to_host(), "v", payload)
        nb = fr.nbytes()
        path = _write_spill(self.settings, self.counters, self.name,
                            self.fileid, len(self._frames), payload, nb)
        self._frames.append(_Spilled(path, len(fr), nb))

    # -- read protocol -----------------------------------------------------

    @property
    def nframes(self) -> int:
        return len(self._frames)

    def is_host_dataset(self) -> bool:
        """True when every frame is a host KVFrame or a spill file (the
        external sort/group machinery operates on these)."""
        return all(isinstance(f, (KVFrame, _Spilled)) for f in self._frames)

    def frames(self) -> Iterator[KVFrame]:
        """Stream frames (reference request_info/request_page cursor,
        src/keyvalue.cpp:277-308)."""
        for f in self._frames:
            yield f.load(self.counters) if isinstance(f, _Spilled) else f

    def one_frame(self):
        """Whole dataset as a single frame (in-core fast path).  Returns the
        ShardedKV directly when that's the sole frame; several sharded
        frames on one mesh concatenate per-shard ON DEVICE (the add() path
        of iterative mesh commands); a mixed plain+sharded dataset compacts
        to host."""
        frames = list(self.frames())
        if not frames:
            from .frame import empty_kv
            return empty_kv()
        if len(frames) == 1:
            return frames[0]
        from ..parallel.sharded import ShardedKV
        if all(isinstance(f, ShardedKV) for f in frames) \
                and len({f.mesh for f in frames}) == 1:
            import functools as _ft
            from ..parallel.devkernels import concat_sharded
            return _ft.reduce(concat_sharded, frames)
        frames = [f if isinstance(f, KVFrame) else f.to_host() for f in frames]
        return _merge_frames(frames)

    def nbytes(self) -> int:
        return sum(f.bytes_ if isinstance(f, _Spilled) else f.nbytes()
                   for f in self._frames)

    def free(self):
        for f in self._frames:
            if isinstance(f, _Spilled):
                try:
                    os.remove(f.path)
                except OSError:
                    pass
            else:
                self.counters.mem(-f.nbytes())
        self._frames = []
        self._batches = []
        self.nkv = 0


class _SpilledKMV:
    """A KMV frame parked in an .npz spill file (the grouped counterpart
    of _Spilled; the reference's extended-KMV pages also round-trip
    through fpath files, src/keymultivalue.cpp:1219-1350)."""

    __slots__ = ("path", "n", "nvalues_total", "bytes_")

    def __init__(self, path: str, n: int, nvalues_total: int, bytes_: int):
        self.path = path
        self.n = n
        self.nvalues_total = nvalues_total
        self.bytes_ = bytes_

    def load(self, counters: Counters) -> KMVFrame:
        with np.load(self.path, allow_pickle=True) as z:
            key = _col_from_npz(z, "k")
            values = _col_from_npz(z, "v")
            nvalues = z["nv"]
            offsets = z["off"]
        counters.add(rsize=self.bytes_)
        return KMVFrame(key, nvalues, offsets, values)


class KeyMultiValue:
    """Grouped dataset: list of KMVFrames (one per source frame batch),
    spilling to fpath .npz under ``outofcore=1`` like KeyValue."""

    def __init__(self, settings: Settings, error: Error, counters: Counters):
        self.settings = settings
        self.error = error
        self.counters = counters
        self.fileid = _next_file_id()
        self._frames: List[object] = []     # KMVFrame | _SpilledKMV | sharded
        self.nkmv = 0
        self.nvalues = 0

    def push(self, fr):
        budget = _spill_budget(self.settings)
        if (self.settings.outofcore == 1 and budget
                and isinstance(fr, KMVFrame)
                and self._resident_bytes() + fr.nbytes() > budget):
            # split on group boundaries first so each spilled piece fits
            # the budget — reduce()/scan then stream piece-at-a-time in
            # bounded memory instead of reloading one giant frame (the
            # point of the reference's paged KMV, doc/Technical.txt:200-214)
            for piece in _split_kmv_to_budget(fr, self.settings):
                self._spill(piece)
        else:
            self._frames.append(fr)
            self.counters.mem(fr.nbytes())

    def _resident_bytes(self) -> int:
        return sum(f.nbytes() for f in self._frames
                   if isinstance(f, KMVFrame))

    def _spill(self, fr: KMVFrame):
        payload: dict = {"nv": np.asarray(fr.nvalues),
                         "off": np.asarray(fr.offsets)}
        _col_to_npz(fr.key.to_host(), "k", payload)
        _col_to_npz(fr.values.to_host(), "v", payload)
        nb = fr.nbytes()
        path = _write_spill(self.settings, self.counters, "kmv",
                            self.fileid, len(self._frames), payload, nb)
        self._frames.append(_SpilledKMV(path, len(fr), fr.nvalues_total,
                                        nb))

    def complete(self):
        self.nkmv = sum(f.n if isinstance(f, _SpilledKMV) else len(f)
                        for f in self._frames)
        self.nvalues = sum(f.nvalues_total for f in self._frames)
        return self.nkmv

    @property
    def nframes(self) -> int:
        return len(self._frames)

    def frames(self) -> Iterator[KMVFrame]:
        for f in self._frames:
            yield f.load(self.counters) if isinstance(f, _SpilledKMV) else f

    def one_frame(self) -> KMVFrame:
        frames = list(self.frames())
        if len(frames) == 1:
            return frames[0]
        if not frames:
            return KMVFrame(DenseColumn(np.zeros(0, np.uint64)),
                            np.zeros(0, np.int64), np.zeros(1, np.int64),
                            DenseColumn(np.zeros(0, np.uint64)))
        frames = [f if isinstance(f, KMVFrame) else f.to_host()
                  for f in frames]
        key = concat([f.key for f in frames])
        values = concat([f.values for f in frames])
        nvalues = np.concatenate([f.nvalues for f in frames])
        offsets = np.concatenate([[0], np.cumsum(nvalues)]).astype(np.int64)
        return KMVFrame(key, nvalues, offsets, values)

    def nbytes(self) -> int:
        return sum(f.bytes_ if isinstance(f, _SpilledKMV) else f.nbytes()
                   for f in self._frames)

    def free(self):
        for f in self._frames:
            if isinstance(f, _SpilledKMV):
                try:
                    os.remove(f.path)
                except OSError:
                    pass
            else:
                self.counters.mem(-f.nbytes())
        self._frames = []
        self.nkmv = 0
        self.nvalues = 0


# ---------------------------------------------------------------------------

def rows_to_array(rows: list) -> np.ndarray:
    """np.asarray for scalar/tuple rows that REFUSES numpy's silent
    int→float64 fallback: a python-int list straddling 2^63 (u64 hash ids
    next to small counts) coerces to lossy float64 — here it becomes exact
    uint64 instead."""
    arr = np.asarray(rows)

    def _u64able(e):
        return isinstance(e, (int, np.integer)) and 0 <= int(e) < (1 << 64)

    if (arr.dtype == np.float64
            and all(_u64able(r) or
                    (isinstance(r, tuple) and all(_u64able(e) for e in r))
                    for r in rows)):
        arr = np.asarray(rows, dtype=np.uint64)
    return arr


def _coerce_rows(rows: list) -> Column:
    """Turn a python append buffer into a column: bytes→BytesColumn,
    numbers/uniform tuples→DenseColumn, anything else (dicts, mixed
    types, ragged tuples…)→ObjectColumn — the pickle tier matching the
    reference Python wrapper's arbitrary-object KVs
    (python/mrmpi.py:17-45)."""
    from .column import ObjectColumn
    first = rows[0]
    if isinstance(first, (bytes, str, bytearray)):
        if all(isinstance(r, (bytes, str, bytearray, memoryview))
               for r in rows):
            return BytesColumn([r if isinstance(r, bytes) else
                                (r.encode() if isinstance(r, str)
                                 else bytes(r)) for r in rows])
        # mixed with non-string rows (bytes(int) would silently build a
        # NUL run): arbitrary objects, pickle tier
        return ObjectColumn(rows)
    if first is None:
        return DenseColumn(np.zeros(len(rows), dtype=np.uint8))
    try:
        arr = rows_to_array(rows)
    except (ValueError, OverflowError):
        return ObjectColumn(rows)
    if arr.dtype == object or arr.dtype.kind in "USV":
        # numpy stringifies mixed tuples like ('a', 1) — those are
        # arbitrary objects, not data; keep the originals via pickle
        return ObjectColumn(rows)
    return DenseColumn(arr)


def _merge_frames(frames: Sequence[KVFrame]) -> KVFrame:
    if len(frames) == 1:
        return frames[0]
    return KVFrame(concat([f.key for f in frames]),
                   concat([f.value for f in frames]))


def _split_kmv_to_budget(fr: KMVFrame, settings: Settings) -> List[KMVFrame]:
    """Split a KMV frame into ≤ memsize pieces on group boundaries.  A
    single group larger than the budget stays one piece — that is the
    multi-block case BlockedMultivalue streams (reference "extended" KMV,
    src/keymultivalue.cpp:974-999)."""
    limit = settings.memsize * (1 << 20)
    if len(fr) == 0 or fr.nbytes() <= limit:
        return [fr]
    row_bytes = fr.nbytes() / max(1, fr.nvalues_total)
    rows_per = max(1, int(limit / row_bytes))
    offsets = np.asarray(fr.offsets)
    pieces: List[KMVFrame] = []
    g = 0
    while g < len(fr):
        start_row = int(offsets[g])
        # furthest group whose end stays within rows_per of start_row
        h = int(np.searchsorted(offsets, start_row + rows_per,
                                side="right")) - 1
        h = max(h, g + 1)          # always advance ≥ 1 group
        h = min(h, len(fr))
        sub_off = (offsets[g:h + 1] - start_row).astype(np.int64)
        pieces.append(KMVFrame(
            fr.key.slice(g, h), np.asarray(fr.nvalues[g:h]), sub_off,
            fr.values.slice(start_row, int(offsets[h]))))
        g = h
    return pieces


def _split_to_budget(fr: KVFrame, settings: Settings) -> List[KVFrame]:
    """Split a frame to the memsize budget (a reference page boundary)."""
    limit = settings.memsize * (1 << 20)
    n = len(fr)
    if n == 0 or fr.nbytes() <= limit:
        return [fr]
    rows_per = max(1, int(n * limit / fr.nbytes()))
    return [fr.slice(s, min(s + rows_per, n)) for s in range(0, n, rows_per)]
