"""Checkpoint / restore of MapReduce datasets.

The reference has NO checkpointing: its out-of-core page files are
deleted on destruction and persistence is limited to ``print``-to-file
output that OINK re-parses as text (SURVEY.md §5 "checkpoint/resume:
none").  This module is a deliberate capability improvement: a KV or
KMV dataset round-trips losslessly (typed columns, byte strings,
pickled objects, grouped frames) through a directory of ``.npz`` frame
files plus a JSON manifest — frames stream one at a time in both
directions, so saving or loading never materialises more than one
frame beyond the normal budget.

Script access: ``<MRname> save <dir>`` / ``<MRname> load <dir>``
(oink/mrscript.py) — the script-level analogue of the reference's
print-then-re-read idiom, without the text round-trip."""

from __future__ import annotations

import json
import os
import shutil
from typing import Optional

import numpy as np

from .dataset import _col_from_npz, _col_to_npz
from .frame import KMVFrame, KVFrame
from .runtime import MRError

_MANIFEST = "manifest.json"
_VERSION = 1


def save(mr, path: str) -> int:
    """Write mr's dataset (KV or KMV) to directory ``path``; returns the
    number of frames written.  Sharded frames are pulled to host (a
    checkpoint must be readable without the mesh that produced it).

    The save is atomic at directory granularity: frames + manifest are
    written to a temp sibling and swapped into place with rename, so an
    interrupted save can never leave a loadable manifest pointing at a
    mix of old and new frames (a prior in-place overwrite could).  That
    atomicity is also what makes the ft/ ``checkpoint.save`` retry
    policy sound: a retried save re-runs the whole swap and can never
    mix generations (callers wrap via ``ft.retry_call``)."""
    from ..ft.inject import fault_point
    fault_point("checkpoint.save", path=path)
    path = os.path.normpath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    kind = "kv" if mr.kv is not None else ("kmv" if mr.kmv is not None
                                           else "none")
    nframes = 0
    counts = []
    try:
        if kind != "none":
            ds = mr.kv if kind == "kv" else mr.kmv
            if kind == "kv" and (ds._buf_k or ds._batches):
                # an MR in the open() cross-add state has pairs only in
                # its append buffers — frames() would silently omit them
                raise MRError("cannot checkpoint an MR with uncompleted "
                              "adds; close()/complete it first")
            for fr in ds.frames():
                fr = fr.to_host()
                payload: dict = {}
                if isinstance(fr, KVFrame):
                    _col_to_npz(fr.key, "k", payload)
                    _col_to_npz(fr.value, "v", payload)
                elif isinstance(fr, KMVFrame):
                    _col_to_npz(fr.key, "k", payload)
                    _col_to_npz(fr.values, "v", payload)
                    payload["nvalues"] = np.asarray(fr.nvalues)
                    payload["offsets"] = np.asarray(fr.offsets)
                else:  # pragma: no cover - defensive
                    raise MRError(f"cannot checkpoint frame type "
                                  f"{type(fr).__name__}")
                np.savez(os.path.join(tmp, f"frame-{nframes:05d}.npz"),
                         **payload)
                counts.append(len(fr))
                nframes += 1
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump({"version": _VERSION, "kind": kind,
                       "nframes": nframes, "counts": counts}, f)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    # swap: worst case after a crash is a MISSING checkpoint (old dir
    # renamed aside), never a manifest over mixed-generation frames
    try:
        if os.path.exists(path):
            if not os.path.isdir(path):
                raise MRError(f"checkpoint target {path!r} exists and is "
                              f"not a directory")
            foreign = [f for f in os.listdir(path)
                       if f != _MANIFEST and not f.startswith("frame-")]
            if foreign:
                raise MRError(
                    f"checkpoint target {path!r} holds non-checkpoint "
                    f"files {foreign[:3]!r}; refusing to replace the "
                    f"directory")
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    old = f"{path}.old.{os.getpid()}"
    shutil.rmtree(old, ignore_errors=True)
    try:
        if os.path.exists(path):
            os.rename(path, old)
        try:
            os.rename(tmp, path)
        except BaseException as swap_err:
            if not os.path.exists(path) and os.path.exists(old):
                try:
                    os.rename(old, path)  # put the previous one back
                except OSError as restore_err:
                    # double fault: the new rename AND the restore both
                    # failed — `old` is now the only surviving copy, so
                    # it must outlive this call (ADVICE r3: the finally
                    # below used to delete it)
                    raise MRError(
                        f"checkpoint swap failed ({swap_err!r}) and the "
                        f"previous checkpoint could not be restored "
                        f"({restore_err!r}); it survives at {old!r}"
                    ) from swap_err
            raise
    finally:
        # only discard `old` once a checkpoint really sits at `path`
        # (the new one, or the restored previous one)
        if os.path.exists(path):
            shutil.rmtree(old, ignore_errors=True)
        shutil.rmtree(tmp, ignore_errors=True)
    return nframes


def load(mr, path: str) -> int:
    """Replace mr's dataset with the checkpoint at ``path``; returns the
    global pair/group count (like every mutating op)."""
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            man = json.load(f)
    except FileNotFoundError:
        raise MRError(f"no checkpoint manifest under {path!r}")
    if man.get("version") != _VERSION:
        raise MRError(f"unsupported checkpoint version {man.get('version')}")
    kind = man["kind"]
    if mr.kv is not None:
        mr.kv.free()
        mr.kv = None
    if mr.kmv is not None:
        mr.kmv.free()
        mr.kmv = None
    if kind == "none":
        return 0
    # frames restore ONE AT A TIME into the target's own budget:
    # _push_frame/push spill immediately when the receiving MR runs
    # outofcore, so a larger-than-RAM checkpoint restores without a
    # consolidating merge (complete() is bypassed for exactly that
    # reason on the KV path)
    if kind == "kv":
        ds = mr._new_kv()
    else:
        ds = mr._new_kmv()
    for i in range(man["nframes"]):
        with np.load(os.path.join(path, f"frame-{i:05d}.npz"),
                     allow_pickle=False) as z:
            if kind == "kv":
                ds._push_frame(KVFrame(_col_from_npz(z, "k"),
                                       _col_from_npz(z, "v")))
            else:
                ds.push(KMVFrame(_col_from_npz(z, "k"), z["nvalues"],
                                 z["offsets"], _col_from_npz(z, "v")))
    if kind == "kv":
        mr.kv = ds
        ds.nkv = sum(ds._frame_n(f) for f in ds._frames)
        ds.complete_done = True
        n = ds.nkv
    else:
        mr.kmv = ds
        n = ds.complete()
    return int(mr.backend.allreduce_sum(n))
