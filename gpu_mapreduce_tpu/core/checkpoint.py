"""Checkpoint / restore of MapReduce datasets.

The reference has NO checkpointing: its out-of-core page files are
deleted on destruction and persistence is limited to ``print``-to-file
output that OINK re-parses as text (SURVEY.md §5 "checkpoint/resume:
none").  This module is a deliberate capability improvement: a KV or
KMV dataset round-trips losslessly (typed columns, byte strings,
pickled objects, grouped frames) through a directory of ``.npz`` frame
files plus a JSON manifest — frames stream one at a time in both
directions, so saving or loading never materialises more than one
frame beyond the normal budget.

Script access: ``<MRname> save <dir>`` / ``<MRname> load <dir>``
(oink/mrscript.py) — the script-level analogue of the reference's
print-then-re-read idiom, without the text round-trip."""

from __future__ import annotations

import json
import os
import shutil
from typing import Optional

import numpy as np

from .dataset import _col_from_npz, _col_to_npz
from .frame import KMVFrame, KVFrame
from .runtime import MRError

_MANIFEST = "manifest.json"
_VERSION = 2       # v2: shard manifests + integrity stamps (v1 loads)


def _frame_shard_meta(fr) -> dict:
    """Topology record of one pre-``to_host`` frame: the writer's
    per-shard row counts (ShardedKV ``counts`` / ShardedKMV
    ``gcounts``), or None for host frames.  This is what makes a
    checkpoint *topology-portable*: a restore onto any mesh width knows
    the global row order (shard-major) without the writer's mesh."""
    counts = getattr(fr, "gcounts", None)
    if counts is None:
        counts = getattr(fr, "counts", None)
    if counts is None:
        return {"shards": None, "nprocs": 1}
    return {"shards": [int(c) for c in counts],
            "nprocs": int(getattr(fr, "nprocs", len(counts)))}


def _shard_digests(payload: dict, shards) -> list:
    """Per-shard digests of a KV frame's compacted row bytes: shard s
    owns host rows [cum[s], cum[s+1]) of the shard-major order — the
    integrity unit a cross-mesh restore can still be audited by."""
    from ..utils.integrity import array_digest
    k = payload.get("k_arr")
    v = payload.get("v_arr")
    if k is None or v is None or shards is None:
        return []
    out, start = [], 0
    for c in shards:
        out.append(array_digest(k[start:start + c], v[start:start + c]))
        start += c
    return out


def save(mr, path: str) -> int:
    """Write mr's dataset (KV or KMV) to directory ``path``; returns the
    number of frames written.  Sharded frames are pulled to host (a
    checkpoint must be readable without the mesh that produced it).

    The save is atomic at directory granularity: frames + manifest are
    written to a temp sibling and swapped into place with rename, so an
    interrupted save can never leave a loadable manifest pointing at a
    mix of old and new frames (a prior in-place overwrite could).  That
    atomicity is also what makes the ft/ ``checkpoint.save`` retry
    policy sound: a retried save re-runs the whole swap and can never
    mix generations (callers wrap via ``ft.retry_call``)."""
    from ..ft.inject import fault_point
    fault_point("checkpoint.save", path=path)
    path = os.path.normpath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    kind = "kv" if mr.kv is not None else ("kmv" if mr.kmv is not None
                                           else "none")
    nframes = 0
    counts = []
    frames_meta = []
    row_start = 0
    nprocs_max = 1
    try:
        if kind != "none":
            from ..utils.integrity import file_digest
            ds = mr.kv if kind == "kv" else mr.kmv
            if kind == "kv" and (ds._buf_k or ds._batches):
                # an MR in the open() cross-add state has pairs only in
                # its append buffers — frames() would silently omit them
                raise MRError("cannot checkpoint an MR with uncompleted "
                              "adds; close()/complete it first")
            for fr in ds.frames():
                smeta = _frame_shard_meta(fr)
                nprocs_max = max(nprocs_max, smeta["nprocs"])
                fr = fr.to_host()
                payload: dict = {}
                if isinstance(fr, KVFrame):
                    _col_to_npz(fr.key, "k", payload)
                    _col_to_npz(fr.value, "v", payload)
                elif isinstance(fr, KMVFrame):
                    _col_to_npz(fr.key, "k", payload)
                    _col_to_npz(fr.values, "v", payload)
                    payload["nvalues"] = np.asarray(fr.nvalues)
                    payload["offsets"] = np.asarray(fr.offsets)
                else:  # pragma: no cover - defensive
                    raise MRError(f"cannot checkpoint frame type "
                                  f"{type(fr).__name__}")
                fname = f"frame-{nframes:05d}.npz"
                np.savez(os.path.join(tmp, fname), **payload)
                counts.append(len(fr))
                # the shard manifest entry: file digest (np.savez seeks,
                # so stamp by read-back), GLOBAL row range, the writer's
                # per-shard partition and per-shard row digests — enough
                # to restore onto any mesh width and audit each piece
                frames_meta.append({
                    "file": fname, "n": len(fr),
                    "rows": [row_start, row_start + len(fr)],
                    "digest": file_digest(os.path.join(tmp, fname)),
                    "shards": smeta["shards"],
                    # per-shard row digests are KV-only: a KMV frame's
                    # value rows don't align 1:1 with its group counts
                    "shard_digests": (_shard_digests(payload,
                                                     smeta["shards"])
                                      if isinstance(fr, KVFrame) else []),
                })
                row_start += len(fr)
                nframes += 1
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump({"version": _VERSION, "kind": kind,
                       "nframes": nframes, "counts": counts,
                       "frames": frames_meta,
                       "mesh": {"nprocs": nprocs_max}}, f)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    # swap: worst case after a crash is a MISSING checkpoint (old dir
    # renamed aside), never a manifest over mixed-generation frames
    try:
        if os.path.exists(path):
            if not os.path.isdir(path):
                raise MRError(f"checkpoint target {path!r} exists and is "
                              f"not a directory")
            foreign = [f for f in os.listdir(path)
                       if f != _MANIFEST and not f.startswith("frame-")]
            if foreign:
                raise MRError(
                    f"checkpoint target {path!r} holds non-checkpoint "
                    f"files {foreign[:3]!r}; refusing to replace the "
                    f"directory")
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    old = f"{path}.old.{os.getpid()}"
    shutil.rmtree(old, ignore_errors=True)
    try:
        if os.path.exists(path):
            os.rename(path, old)
        try:
            os.rename(tmp, path)
        except BaseException as swap_err:
            if not os.path.exists(path) and os.path.exists(old):
                try:
                    os.rename(old, path)  # put the previous one back
                except OSError as restore_err:
                    # double fault: the new rename AND the restore both
                    # failed — `old` is now the only surviving copy, so
                    # it must outlive this call (ADVICE r3: the finally
                    # below used to delete it)
                    raise MRError(
                        f"checkpoint swap failed ({swap_err!r}) and the "
                        f"previous checkpoint could not be restored "
                        f"({restore_err!r}); it survives at {old!r}"
                    ) from swap_err
            raise
    finally:
        # only discard `old` once a checkpoint really sits at `path`
        # (the new one, or the restored previous one)
        if os.path.exists(path):
            shutil.rmtree(old, ignore_errors=True)
        shutil.rmtree(tmp, ignore_errors=True)
    # the directory swap is only durable once the PARENT's entry table
    # is — without this a crash after return can lose the rename of a
    # generation the journal's ckpt record already references
    from ..utils.fsio import fsync_dir
    fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")
    # cross-replica chunk dedup (utils/cas.py): re-home every frame
    # file through the content store, so N replicas checkpointing the
    # same resident dataset hold hardlinks to ONE copy of the bytes.
    # Pure optimisation: same bytes, same manifest digests, readers
    # unchanged; any failure (no store, cross-device) leaves the plain
    # file in place.
    try:
        from ..utils.cas import cas_store
        store = cas_store()
        if store is not None:
            for fname in os.listdir(path):
                if fname.startswith("frame-"):
                    store.dedup_file(os.path.join(path, fname))
    except Exception:
        pass
    return nframes


def read_manifest(path: str) -> dict:
    """The checkpoint's manifest dict (v1 or v2), or MRError."""
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            man = json.load(f)
    except FileNotFoundError:
        raise MRError(f"no checkpoint manifest under {path!r}")
    if man.get("version") not in (1, _VERSION):
        raise MRError(f"unsupported checkpoint version {man.get('version')}")
    return man


def validate(path: str) -> bool:
    """Cheap pre-restore probe: manifest readable, every frame file
    present, and (under MRTPU_VERIFY) every frame digest intact.  THE
    check ``ft.resume`` runs per checkpoint generation before deciding
    which one to restore from — a generation with a missing or
    bit-flipped frame is rejected BEFORE any replay commits to its
    sequence number, and the previous kept generation takes over."""
    from ..utils.integrity import (record_integrity_failure,
                                   verify_enabled, file_digest)
    try:
        man = read_manifest(path)
    except MRError:
        return False
    frames = man.get("frames") or [
        {"file": f"frame-{i:05d}.npz", "digest": None}
        for i in range(man.get("nframes", 0))]
    for fm in frames:
        fpath = os.path.join(path, fm["file"])
        if not os.path.exists(fpath):
            return False
        exp = fm.get("digest")
        if exp is not None and verify_enabled():
            if file_digest(fpath) != exp:
                record_integrity_failure("checkpoint")
                return False
    return True


def load(mr, path: str) -> int:
    """Replace mr's dataset with the checkpoint at ``path``; returns the
    global pair/group count (like every mutating op).  Under
    ``MRTPU_VERIFY`` (default on) every frame file is checksummed
    against its manifest stamp before any of its rows are pushed — a
    bit-flipped checkpoint raises IntegrityError instead of silently
    restoring garbage (callers with older generations fall back:
    ``ft.resume``)."""
    man = read_manifest(path)
    kind = man["kind"]
    frames_meta = man.get("frames") or []
    from ..utils.integrity import verify_file
    if mr.kv is not None:
        mr.kv.free()
        mr.kv = None
    if mr.kmv is not None:
        mr.kmv.free()
        mr.kmv = None
    if kind == "none":
        return 0
    # frames restore ONE AT A TIME into the target's own budget:
    # _push_frame/push spill immediately when the receiving MR runs
    # outofcore, so a larger-than-RAM checkpoint restores without a
    # consolidating merge (complete() is bypassed for exactly that
    # reason on the KV path)
    if kind == "kv":
        ds = mr._new_kv()
    else:
        ds = mr._new_kmv()
    from ..utils.integrity import (IntegrityError, array_digest,
                                   record_integrity_failure,
                                   verify_enabled)
    for i in range(man["nframes"]):
        fpath = os.path.join(path, f"frame-{i:05d}.npz")
        fm = frames_meta[i] if i < len(frames_meta) else {}
        if fm:
            # verify-before-consume: the stamp check precedes np.load,
            # so a corrupt frame can never partially restore
            verify_file(fpath, fm.get("digest"), "checkpoint")
        with np.load(fpath, allow_pickle=False) as z:
            # per-shard row digests: the finer-grained audit of the
            # same frame — which WRITER shard a mismatch came from
            # survives the cross-mesh restore (the file digest above
            # already gates; this localizes)
            if (verify_enabled() and kind == "kv" and fm.get("shards")
                    and fm.get("shard_digests") and "k_arr" in z
                    and "v_arr" in z):
                k, v, start = z["k_arr"], z["v_arr"], 0
                for s, (c, exp) in enumerate(zip(fm["shards"],
                                                 fm["shard_digests"])):
                    got = array_digest(k[start:start + c],
                                       v[start:start + c])
                    if got != exp:
                        record_integrity_failure("checkpoint")
                        raise IntegrityError(
                            "checkpoint",
                            f"{fpath} (writer shard {s})", exp, got)
                    start += c
            if kind == "kv":
                ds._push_frame(KVFrame(_col_from_npz(z, "k"),
                                       _col_from_npz(z, "v")))
            else:
                ds.push(KMVFrame(_col_from_npz(z, "k"), z["nvalues"],
                                 z["offsets"], _col_from_npz(z, "v")))
    if kind == "kv":
        mr.kv = ds
        ds.nkv = sum(ds._frame_n(f) for f in ds._frames)
        ds.complete_done = True
        n = ds.nkv
    else:
        mr.kmv = ds
        n = ds.complete()
    return int(mr.backend.allreduce_sum(n))
