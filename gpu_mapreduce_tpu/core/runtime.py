"""Runtime state: settings, error policy, cumulative statistics.

Mirrors the reference's user-visible settings fields
(``src/mapreduce.h:28-41``, semantics ``doc/settings.txt:12-24``) and the
static cross-instance counters (``src/mapreduce.h:46-57``,
``src/mapreduce.cpp:40-50``) reported by ``cummulative_stats``
(``src/mapreduce.cpp:3007-3066``).

TPU reinterpretations (documented, not silently dropped):

* ``memsize`` (MB) — still the page/frame budget: a dataset frame holds at
  most ``memsize`` MB and datasets exceeding ``maxpage`` frames in HBM spill
  to host DRAM (and to ``fpath`` on disk when ``outofcore=1``).
* ``keyalign``/``valuealign`` — byte alignment is meaningless for columnar
  arrays; accepted and ignored (validated like the reference,
  ``src/mapreduce.cpp:251-261``).
* ``all2all`` — selects the shuffle transport: 1 = single fused all_to_all
  collective, 0 = ppermute ring (the reference's MPI_Alltoallv vs.
  Irecv/Send ring, ``src/irregular.cpp:254-363``).
* ``mapstyle`` — 0 chunk / 1 stride task assignment both reduce to "run
  all tasks here" under one controller; 2 (the reference's master-slave
  MPI work queue, src/mapreduce.cpp:1136-1213) is a dynamic thread-pool
  work queue with deterministic task-order output (MapReduce._run_tasks).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Optional

from ..utils.env import env_knob, env_str


class MRError(RuntimeError):
    """Raised for fatal conditions (the reference's error->all/one,
    src/error.cpp:33-67 — both abort; in-process we raise instead)."""


class CancelledError(MRError):
    """A request was cancelled (client DELETE, deadline, or the stall
    watchdog) and the cancellation flag tripped at an op barrier
    (obs/context.barrier_check).  Deliberately an :class:`MRError`
    subclass: the ft/ retry engine classifies MRError as FATAL, so a
    cancellation is never retried — it propagates straight up to the
    request owner (the serve/ worker), which records the ``cancelled``
    terminal state."""

    def __init__(self, reason: str = "cancelled"):
        self.reason = reason
        super().__init__(f"request cancelled ({reason})")


class Error:
    def all(self, msg: str):  # collective fatal
        raise MRError(msg)

    def one(self, msg: str):  # single-rank fatal
        raise MRError(msg)

    def warning(self, msg: str):
        warnings.warn(msg, stacklevel=3)


@dataclass
class Settings:
    mapstyle: int = 0       # 0 chunk, 1 stride, 2 master-slave work queue
    all2all: int = 1        # shuffle transport (fused collective vs ring)
    verbosity: int = 0      # 0 silent, 1 totals, 2 + per-shard histograms
    timer: int = 0          # 0 off, 1 totals, 2 + per-shard histograms
    # MB per frame (reference default 64, mapreduce.cpp:209); the env
    # vars mirror the reference's compile-time default overrides
    # MRMPI_MEMSIZE / MRMPI_FPATH (mapreduce.cpp:206-229) — explicit
    # settings still win
    memsize: int = field(default_factory=lambda: env_knob(
        "MRTPU_MEMSIZE", int, 64))
    minpage: int = 0
    maxpage: int = 0        # max frames resident in HBM; 0 = unlimited
    freepage: int = 1
    outofcore: int = 0      # 1 = allow disk spill under fpath; -1 = never
    zeropage: int = 0
    keyalign: int = 8       # accepted, ignored (columnar)
    valuealign: int = 8
    fpath: str = field(default_factory=lambda: env_str(
        "MRTPU_FPATH", "."))  # spill-file dir (reference MRMPI_FPATH)
    # 1 = defer op chains into the plan/ recorder and run them fused
    # (no reference analog — the reference is eager by construction);
    # the MRTPU_FUSE env var flips the default like MRTPU_MEMSIZE does
    fuse: int = field(default_factory=lambda: env_knob(
        "MRTPU_FUSE", int, 0))
    # what a failed map input does after the ft/ retry budget is spent
    # (no reference analog — the reference aborts on any read error):
    # "fail" raises MRError, "retry" retries with a default budget even
    # when MRTPU_RETRY is unset, "skip" quarantines the poisoned input
    # and continues (records in mr.stats()["ft"] — doc/reliability.md)
    onfault: str = field(default_factory=lambda: env_str(
        "MRTPU_ONFAULT", "fail"))

    def validate(self, error: Error):
        if self.memsize <= 0:
            error.all("Invalid memsize setting")
        if self.mapstyle not in (0, 1, 2):
            error.all("Invalid mapstyle setting")
        if self.fuse not in (0, 1):
            error.all("Invalid fuse setting")
        if self.onfault not in ("fail", "retry", "skip"):
            error.all("Invalid onfault setting (fail, retry, or skip)")
        for a in (self.keyalign, self.valuealign):
            if a <= 0 or (a & (a - 1)):
                error.all("Alignment setting must be power of 2")


@dataclass
class Counters:
    """Cumulative cross-instance stats (reference mapreduce.h:46-57).

    Updates go through ``add()``/``mem()`` which take a lock — counters
    are shared across MapReduce objects (global_counters) and mutate
    from concurrent -partition world threads and mapstyle-2 workers."""
    msize: int = 0          # current bytes resident (HBM frames)
    msizemax: int = 0       # hi-water
    rsize: int = 0          # bytes read from spill files
    wsize: int = 0          # bytes written to spill files
    cssize: int = 0         # useful bytes sent in shuffles
    crsize: int = 0         # useful bytes received in shuffles
    cspad: int = 0          # PADDING bytes sent (static-shape exchange
    #                         slack: [P,B]-buckets minus real rows —
    #                         the weak-scaling "network volume" diagnosis)
    commtime: float = 0.0   # seconds in collectives
    ndispatch: int = 0      # compiled-program launches (jitted shuffle/
    #                         convert/reduce/sort programs, fused plans,
    #                         AND eager pallas_call kernel launches —
    #                         ops/pallas.note_kernel_launch) — what
    #                         plan/ fusion is meant to shrink; a kernel
    #                         traced inside a jit rides that program's
    #                         count, so megafused pipelines read 1

    def __post_init__(self):
        import threading
        self._lock = threading.Lock()

    def add(self, **deltas):
        """Atomically bump the named counters: add(rsize=n, wsize=m)."""
        with self._lock:
            for name, delta in deltas.items():
                setattr(self, name, getattr(self, name) + delta)
        if "wsize" in deltas or "rsize" in deltas:
            acct = getattr(_ACCOUNT_TLS, "acct", None)
            if acct is not None:
                acct.note_io(deltas.get("wsize", 0),
                             deltas.get("rsize", 0))
        feed = _REQUEST_FEED
        if feed is not None:
            feed("add", deltas)

    def mem(self, delta: int):
        with self._lock:
            self.msize += delta
            if self.msize > self.msizemax:
                self.msizemax = self.msize
        acct = getattr(_ACCOUNT_TLS, "acct", None)
        if acct is not None:
            acct.charge(delta)
        feed = _REQUEST_FEED
        if feed is not None:
            feed("mem", delta)

    def snapshot(self) -> dict:
        """Consistent copy of every counter field — the structured twin
        of the ``cummulative_stats`` print (MapReduce.stats)."""
        with self._lock:
            return {"msize": self.msize, "msizemax": self.msizemax,
                    "rsize": self.rsize, "wsize": self.wsize,
                    "cssize": self.cssize, "crsize": self.crsize,
                    "cspad": self.cspad, "commtime": self.commtime,
                    "ndispatch": self.ndispatch}


class PageAccount:
    """Per-tenant frame-residency accounting (serve/budget.py).

    The enforcement half of a tenant budget is the existing page
    machinery — a session's MRs are created with ``maxpage``/``memsize``
    /``outofcore`` derived from the tenant's allowance, so an
    over-budget dataset spills through ``core/dataset.py`` exactly like
    any memory-constrained run.  This class is the *attribution* half:
    bytes charged through :meth:`Counters.mem` while a tenant scope is
    installed land here, giving the serve/ daemon a live per-tenant
    ``pages in use`` reading (the ``mrtpu_tenant_pages{tenant}`` gauge)
    without a second accounting path in the datasets.

    Attribution is thread-scoped (:func:`page_account_scope`): bytes
    charged from helper threads a session spawns itself (ingest pool
    workers) bill the global counters but not the tenant — frame
    consolidation happens on the session thread, so residency totals
    stay accurate (doc/serve.md)."""

    __slots__ = ("tenant", "page_bytes", "limit_pages", "bytes_in_use",
                 "hi_water", "spilled_bytes", "reread_bytes", "_lock")

    def __init__(self, tenant: str, page_bytes: int,
                 limit_pages: int = 0):
        self.tenant = tenant
        self.page_bytes = max(1, int(page_bytes))
        self.limit_pages = int(limit_pages)      # 0 = unlimited
        self.bytes_in_use = 0
        self.hi_water = 0
        self.spilled_bytes = 0       # budget-enforcement evidence: what
        self.reread_bytes = 0        # THIS tenant paid in disk traffic
        self._lock = threading.Lock()

    def charge(self, delta: int) -> None:
        with self._lock:
            self.bytes_in_use = max(0, self.bytes_in_use + int(delta))
            if self.bytes_in_use > self.hi_water:
                self.hi_water = self.bytes_in_use

    def note_io(self, wsize: int, rsize: int) -> None:
        with self._lock:
            self.spilled_bytes += int(wsize)
            self.reread_bytes += int(rsize)

    def pages_in_use(self) -> float:
        with self._lock:
            return self.bytes_in_use / self.page_bytes

    def snapshot(self) -> dict:
        with self._lock:
            return {"tenant": self.tenant,
                    "bytes_in_use": self.bytes_in_use,
                    "hi_water": self.hi_water,
                    "spilled_bytes": self.spilled_bytes,
                    "reread_bytes": self.reread_bytes,
                    "page_bytes": self.page_bytes,
                    "pages_in_use": round(self.bytes_in_use
                                          / self.page_bytes, 4),
                    "limit_pages": self.limit_pages}


# the request-context attribution hook: obs/context.py installs its
# feed here at import (fn(kind, payload) — "add" with the deltas dict,
# "mem" with the byte delta).  Module-global instead of an import so
# core/ never depends on obs/ and the unarmed cost is one None check.
_REQUEST_FEED = None

_ACCOUNT_TLS = threading.local()


def set_page_account(acct: Optional["PageAccount"]
                     ) -> Optional["PageAccount"]:
    """Install ``acct`` as THIS thread's tenant attribution target;
    returns the previous one (callers restore it)."""
    prev = getattr(_ACCOUNT_TLS, "acct", None)
    _ACCOUNT_TLS.acct = acct
    return prev


def current_page_account() -> Optional["PageAccount"]:
    return getattr(_ACCOUNT_TLS, "acct", None)


@contextlib.contextmanager
def page_account_scope(acct: Optional["PageAccount"]):
    """``with page_account_scope(acct):`` — scoped install/restore."""
    prev = set_page_account(acct)
    try:
        yield acct
    finally:
        set_page_account(prev)


class Timer:
    __slots__ = ("t0",)

    def __init__(self):
        self.t0 = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self.t0


def histogram(values, nbins: int = 10):
    """(min, avg, max, bins) over per-shard values — the reference's
    histogram() (src/mapreduce.cpp:3267-3311): bins count how many shards
    fall in each equal-width slice of [min, max]."""
    import numpy as _np
    v = _np.asarray(values, dtype=_np.float64)
    if v.size == 0:
        return 0.0, 0.0, 0.0, [0] * nbins
    lo, hi = float(v.min()), float(v.max())
    if hi == lo:
        bins = [0] * nbins
        bins[0] = int(v.size)
        return lo, float(v.mean()), hi, bins
    idx = _np.minimum(((v - lo) / (hi - lo) * nbins).astype(int), nbins - 1)
    bins = _np.bincount(idx, minlength=nbins).astype(int).tolist()
    return lo, float(v.mean()), hi, bins


def write_histo(label: str, values, out=None):
    """Reference write_histo (src/mapreduce.cpp:3251-3263): one line of
    min/avg/max across shards plus the shard-count distribution."""
    import sys as _sys
    lo, ave, hi, bins = histogram(values)
    out = out or _sys.stdout
    out.write(f"  {label} (per shard): {ave:.4g} ave {hi:.4g} max "
              f"{lo:.4g} min\n")
    out.write("  histogram: " + " ".join(str(b) for b in bins) + "\n")


_GLOBAL_COUNTERS = Counters()


def global_counters() -> Counters:
    return _GLOBAL_COUNTERS


_DISPATCH_TLS = threading.local()


def bump_dispatch(n: int = 1) -> None:
    """Count one compiled-program launch (the jitted shuffle/convert/
    reduce/sort programs, fused plan programs AND eager pallas_call
    kernel launches — via ops/pallas.note_kernel_launch — all report
    here) — the denominator of the plan/ fusion win (bench
    detail.plan_ab).  Also bumps a per-thread counter so a caller can
    meter ITS OWN dispatches (thread_dispatches) without concurrent
    workers contaminating the delta."""
    _GLOBAL_COUNTERS.add(ndispatch=n)
    _DISPATCH_TLS.n = getattr(_DISPATCH_TLS, "n", 0) + n


def thread_dispatches() -> int:
    """Compiled-program launches made by THIS thread (cumulative).
    Delta two reads around a region for an exact per-region count even
    while other threads dispatch — the plan/ fusion telemetry's meter
    (dispatches run synchronously on the calling thread)."""
    return getattr(_DISPATCH_TLS, "n", 0)
