"""External (out-of-core) sort and group — the Spool merge cascade,
TPU-first.

The reference sorts one page with an index qsort and merges pages in a
2-way cascade through Spool files (``src/mapreduce.cpp:2359-2633``,
``spool.cpp``), and convert splits oversized hash partitions recursively
(``src/keymultivalue.cpp:736-775``) — every op runs in 1–7 fixed pages no
matter the data size (``doc/Interface_c++.txt:39-59``).

Round 1 spilled frames but reloaded everything for ``convert``/``sort_*``
(``KeyValue.one_frame``) — peak memory was unbounded, the one property
that matters (VERDICT r1 #4).  This module restores it with a design that
keeps the per-chunk work vectorised:

* **pass 1** — each frame (already ≤ the page budget by
  ``_split_to_budget``) loads, sorts *in memory* with one vector sort, and
  spills back as a sorted *run*;
* **pass 2** — a k-way streaming merge: each run holds one buffered block;
  every step takes all rows ≤ the smallest block-tail (they can no longer
  be beaten by unseen rows), merges them with one vector sort, and yields
  a chunk.  Working set ≈ budget; chunk sizes ≈ budget / 2.
* **grouping** — ``group_stream`` cuts the sorted chunk stream into
  KMVFrames on group boundaries, holding back each chunk's last key until
  the next chunk proves it complete (a group larger than a chunk stays one
  frame — the multi-block KMV case, ``BlockedMultivalue``).

Sort order is always ascending on a *surrogate* (see
:func:`sort_surrogate`); descending output reverses the chunk stream and
each chunk, which preserves bounded memory.  Working-set bytes are
reported through ``counters.mem`` so ``msizemax`` reflects the true peak.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional

import numpy as np

from .column import BytesColumn, Column, DenseColumn
from .frame import KMVFrame, KVFrame

_OBJ = np.dtype(object)


def sort_surrogate(col: Column) -> np.ndarray:
    """A 1-D array whose ascending order is the column's sort order:
    numeric 1-D columns as-is; multi-column rows as structured records
    (field-lexicographic comparison); byte strings as object rows;
    arbitrary objects by their pickles (argsort_column's order)."""
    from .column import ObjectColumn
    if isinstance(col, ObjectColumn):
        return np.asarray(col.pickles(), dtype=object)
    if isinstance(col, BytesColumn):
        return np.asarray(list(col.data), dtype=object)
    data = np.asarray(col.to_host().data)
    if data.ndim == 1:
        return data
    data = np.ascontiguousarray(data)
    rec = data.view([(f"f{i}", data.dtype) for i in range(data.shape[1])])
    return rec.reshape(-1)


class _Run:
    """One sorted spilled run with a block cursor.

    Dense columns spill as separate ``.npy`` files and re-open with
    ``mmap_mode='r'`` so each refill reads only its block (an ``.npz``
    member would decompress fully on every access — quadratic read
    amplification across refills).  Byte-string columns (object arrays)
    cannot mmap; they spill pickled and re-read whole per refill — the
    rare path, only for string-keyed out-of-core sorts.

    With the background spill writer (exec/spill.py) the files may still
    be in flight when the merge starts: ``pending`` is the durability
    barrier handle, and every read path goes through :meth:`wait_ready`
    first — the reader can never observe a half-written run."""

    def __init__(self, kpath: str, vpath: str, n: int, counters,
                 kkind: str, vkind: str):
        self.kpath = kpath
        self.vpath = vpath
        self.n = n
        self.pos = 0
        self.counters = counters
        self.kkind = kkind   # "dense" | "bytes" | "object" (column type
        self.vkind = vkind   # is recorded, never guessed from row values)
        self.buf: Optional[KVFrame] = None
        self.sur: Optional[np.ndarray] = None
        self.pending = None  # exec.spill.Pending when written in background
        # integrity (utils/integrity.py): the writer's crc stamps of the
        # exact file bytes, checked once before the first block is
        # consumed — a bit-flipped run can never be silently merged
        self.kdigest: Optional[str] = None
        self.vdigest: Optional[str] = None
        self._verified = False

    def wait_ready(self):
        """Durability barrier: block until this run is fully on disk
        (re-raising a background-writer failure).  Foreground wait time
        feeds the spill overlap ratio."""
        if self.pending is None:
            return
        pending, self.pending = self.pending, None
        try:
            waited = pending.wait()
        except BaseException:
            self.pending = pending   # stay un-ready: a retry re-raises
            raise
        from ..exec import note_overlap
        note_overlap("spill", wait_s=waited)

    def _load(self, path: str, start: int, stop: int, kind: str) -> Column:
        from ..ft.inject import fault_point
        fault_point("spill.read", path=path)
        if kind == "dense":
            arr = np.load(path, mmap_mode="r")
            return DenseColumn(np.array(arr[start:stop]))
        arr = np.load(path, allow_pickle=True)[start:stop]
        if kind == "object":
            from .column import ObjectColumn
            return ObjectColumn(arr)
        return BytesColumn(arr)

    def verify(self) -> None:
        """Checksum the run files against the writer's stamps (once,
        before the first block read; MRTPU_VERIFY=0 skips).  Runs under
        the caller's ``spill.read`` retry budget: a transient mismatch
        (torn page cache) recovers on re-read, a persistent one
        exhausts the budget into a loud MRError — "a bad spill run
        retries from its writer barrier record"."""
        if self._verified:
            return
        from ..utils.integrity import verify_file
        verify_file(self.kpath, self.kdigest, "spill")
        verify_file(self.vpath, self.vdigest, "spill")
        self._verified = True

    def refill(self, block_rows: int, by: str):
        if self.buf is not None or self.pos >= self.n:
            return
        self.wait_ready()
        if not self._verified:
            from ..ft.retry import retry_call
            retry_call("spill.read", self.verify, detail=self.kpath)
        stop = min(self.pos + block_rows, self.n)
        # ft/: a torn/transient block read retries under the spill.read
        # budget — loads are idempotent (the run file is immutable once
        # past the durability barrier above)
        from ..ft.retry import retry_call
        self.buf = KVFrame(
            retry_call("spill.read",
                       lambda: self._load(self.kpath, self.pos, stop,
                                          self.kkind),
                       detail=self.kpath),
            retry_call("spill.read",
                       lambda: self._load(self.vpath, self.pos, stop,
                                          self.vkind),
                       detail=self.vpath))
        self.sur = sort_surrogate(self.buf.key if by == "key"
                                  else self.buf.value)
        self.counters.add(rsize=self.buf.nbytes())
        self.pos = stop

    def exhausted(self) -> bool:
        return self.buf is None and self.pos >= self.n

    def take_upto(self, bound) -> Optional[KVFrame]:
        """Split off buffered rows with surrogate ≤ bound (they are sorted)."""
        if self.buf is None:
            return None
        cut = int(np.searchsorted(self.sur, bound, side="right"))
        if cut == 0:
            return None
        out = self.buf.slice(0, cut)
        if cut >= len(self.buf):
            self.buf, self.sur = None, None
        else:
            self.buf = self.buf.slice(cut, len(self.buf))
            self.sur = self.sur[cut:]
        return out

    def tail(self):
        return self.sur[-1]

    def drop(self):
        # a failed background write may leave only the tmp sibling; a
        # successful one only the final path — remove both forms
        for p in (self.kpath, self.vpath,
                  self.kpath + ".tmp", self.vpath + ".tmp"):
            try:
                os.remove(p)
            except OSError:
                pass


def _col_kind(col: Column) -> str:
    from .column import ObjectColumn
    if isinstance(col, ObjectColumn):
        return "object"
    if isinstance(col, BytesColumn):
        return "bytes"
    return "dense"


def _save_col(col: Column, path: str) -> str:
    from ..exec.spill import atomic_save
    if _col_kind(col) == "dense":
        return atomic_save(path, np.asarray(col.to_host().data))
    # element-wise build: np.asarray(list, dtype=object) would turn
    # uniform-length tuple rows into a 2-D array and corrupt keys
    arr = np.empty(len(col), dtype=object)
    for i, x in enumerate(col.data):
        arr[i] = x
    return atomic_save(path, arr, allow_pickle=True)


def _write_run(fr: KVFrame, settings, counters, seq: int,
               writer=None) -> _Run:
    """Spill one sorted frame as a run.  With ``writer`` (an
    exec.spill.SpillWriter) the write happens in the background and the
    returned run carries the durability-barrier handle; without, it is
    the pre-exec synchronous write."""
    from .dataset import _next_file_id
    os.makedirs(settings.fpath, exist_ok=True)
    base = os.path.join(settings.fpath,
                        f"mrtpu.sortrun.{_next_file_id()}.{seq}")
    kpath, vpath = base + ".k.npy", base + ".v.npy"
    nbytes = fr.nbytes()
    key, value = fr.key, fr.value

    def do_write():
        # ft/: transient write failures retry whole-run under the
        # spill.write budget — atomic_save's tmp+replace makes a
        # re-write idempotent (no torn final file can pre-exist)
        from ..ft.inject import fault_point
        from ..ft.retry import retry_call

        def _write_both():
            fault_point("spill.write", path=base)
            # the writer's stamps land on the run handle the reader
            # verifies against — in-process, before any barrier release
            run.kdigest = _save_col(key, kpath)
            run.vdigest = _save_col(value, vpath)
        retry_call("spill.write", _write_both, detail=base)
        counters.add(wsize=nbytes)

    run = _Run(kpath, vpath, len(fr), counters,
               _col_kind(key), _col_kind(value))
    if writer is None:
        do_write()
    else:
        run.pending = writer.submit(do_write)
    return run


def external_sorted_chunks(frames: Iterator[KVFrame], by: str,
                           settings, counters) -> Iterator[KVFrame]:
    """Generator: sort a stream of frames by key or value in bounded
    memory, yielding ASCENDING sorted chunks in global order (each ≈ half
    the page budget).  Callers must consume incrementally (pushing into a
    spilling dataset) — that is what keeps peak residency ≈ the budget.
    Descending callers flip each chunk and reverse the chunk order."""
    budget = settings.memsize * (1 << 20)

    # pass 1: sort each frame (one vector sort via the shared column
    # argsort — a single order definition with the in-core path), spill
    # as a run.  With the background writer (exec/spill.py) the spill of
    # run k-1 overlaps the sort of run k; its bounded pending queue caps
    # unwritten frames, and every reader below passes the durability
    # barrier before its first block
    from ..exec import spill_bg_enabled
    from ..ops.sort import argsort_column
    writer = None
    if spill_bg_enabled():
        from ..exec.spill import SpillWriter
        writer = SpillWriter()
    runs: List[_Run] = []
    rowbytes = 16
    try:
        for seq, fr in enumerate(frames):
            col = fr.key if by == "key" else fr.value
            order = argsort_column(col)
            runs.append(_write_run(fr.take(order), settings, counters,
                                   seq, writer=writer))
            if len(fr):
                # size blocks for the WIDEST rows seen, or a fat-row
                # run's refills would blow the budget the merge bounds
                rowbytes = max(rowbytes, fr.nbytes() // len(fr))
    finally:
        if writer is not None:
            writer.close()   # errors surface at the runs' barriers

    if not runs:
        return

    # pass 2: k-way merge by safe-boundary chunks
    k = len(runs)
    block_rows = max(1, budget // max(1, 2 * k * rowbytes))
    live = list(runs)
    try:
        while live:
            for r in live:
                r.refill(block_rows, by)
            live = [r for r in live if r.buf is not None]
            if not live:
                break
            # structured (multi-column) surrogates sort/searchsort fine but
            # their scalars lack `<`; compare via tuples for the min only
            bound = min((r.tail() for r in live),
                        key=lambda x: x.tolist() if isinstance(x, np.void)
                        else x)
            pieces = [p for r in live
                      if (p := r.take_upto(bound)) is not None]
            merged = _merge_sorted(pieces, by)
            counters.mem(merged.nbytes())   # working set → msizemax
            counters.mem(-merged.nbytes())
            yield merged
            live = [r for r in live if not r.exhausted()]
    finally:
        for r in runs:
            r.drop()


def _merge_sorted(pieces: List[KVFrame], by: str) -> KVFrame:
    if len(pieces) == 1:
        return pieces[0]
    from ..ops.sort import argsort_column
    from .column import concat
    key = concat([p.key for p in pieces])
    value = concat([p.value for p in pieces])
    fr = KVFrame(key, value)
    order = argsort_column(fr.key if by == "key" else fr.value)
    return fr.take(order)


def group_stream(chunks: Iterator[KVFrame]) -> Iterator[KMVFrame]:
    """Sorted KV chunk stream → KMVFrame stream cut on group boundaries.
    Each chunk's trailing group is held back until the next chunk shows a
    different key, so no group is ever split across frames.

    Memory bound: O(largest single group + one chunk) — a group bigger
    than the budget stays one frame, which is exactly the multi-block
    ("extended") KMV contract the dataset layer and BlockedMultivalue
    implement (reference src/keymultivalue.cpp:974-999; our
    _split_kmv_to_budget keeps an oversized group whole and spills it)."""
    from ..ops.segment import group_dense, group_bytes

    pending: Optional[KVFrame] = None
    for chunk in chunks:
        if pending is not None:
            from .column import concat
            chunk = KVFrame(concat([pending.key, chunk.key]),
                            concat([pending.value, chunk.value]))
            pending = None
        if len(chunk) == 0:
            continue
        sur = sort_surrogate(chunk.key)
        # hold back the run of the final key
        first_of_last = int(np.searchsorted(sur, sur[-1], side="left"))
        if first_of_last > 0:
            pending = chunk.slice(first_of_last, len(chunk))
            head = chunk.slice(0, first_of_last)
            yield _group_one(head)
        else:
            pending = chunk
    if pending is not None and len(pending):
        yield _group_one(pending)


def _group_one(fr: KVFrame) -> KMVFrame:
    from ..ops.segment import group_frame
    return group_frame(fr)
