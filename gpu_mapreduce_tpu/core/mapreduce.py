"""The MapReduce class — the ~30-method op algebra of the reference
(``src/mapreduce.h:59-131``), re-designed TPU-first.

Semantics follow ``doc/Interface_c++.txt`` and the call stacks in SURVEY.md
§3.  Key differences from the reference, by design (SURVEY.md §7):

* Data is columnar (frames of dense arrays / byte strings), not byte-packed
  pages.  Every op has a vectorised *batch* path (callbacks receive whole
  columns, run jitted on device) next to the per-pair *host* path (callbacks
  receive python scalars — the reference's serial-callback model, kept for
  parity and arbitrary-object support like python/mrmpi.py's pickled KVs).
* Parallelism is a pluggable backend: the default :class:`SerialBackend`
  is the analogue of the reference's mpistubs/ serial MPI (1-proc semantics,
  ``mpistubs/mpi.cpp:244-395``); the mesh backend (``parallel/``) runs the
  same ops sharded over a ``jax.sharding.Mesh`` with ICI collectives.
* ``aggregate()`` early-outs with one proc exactly like the reference
  (``src/mapreduce.cpp:403-406``).

Every mutating op returns the *global* pair count, like the reference's
MPI_Allreduce'd returns (``src/mapreduce.cpp:557-558``).
"""

from __future__ import annotations

import copy as _copymod
import functools
import sys
import time
from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from ..ops.segment import group_frame
from ..ops.sort import argsort_column
from ..utils.io import file_chunks, findfiles
from .column import BytesColumn, Column, DenseColumn, as_column, concat
from .dataset import KeyMultiValue, KeyValue
from .frame import BlockedMultivalue, KMVFrame, KVFrame
from .runtime import Counters, Error, MRError, Settings, Timer, global_counters


class SerialBackend:
    """1-proc backend: all distributed ops are local no-ops or renames.

    This is the moral equivalent of linking against ``mpistubs/`` — the
    reference's complete single-process MPI fake (``mpistubs/mpi.cpp``):
    the same program text runs serial or parallel unchanged."""

    nprocs = 1
    me = 0

    def aggregate(self, mr: "MapReduce", hash_fn) -> None:
        return  # nprocs==1 early-out, src/mapreduce.cpp:403-406

    def gather(self, mr: "MapReduce", nprocs: int) -> None:
        return

    def broadcast(self, mr: "MapReduce", root: int) -> None:
        return

    def allreduce_sum(self, x):
        return x


class _TaskSink:
    """Per-task KV stand-in for mapstyle-2 worker threads: records the
    callback's add traffic, replayed into the real KeyValue in task order
    once all workers finish (KeyValue's append buffers are not
    thread-safe, and serial replay keeps output order deterministic)."""

    __slots__ = ("_calls",)

    def __init__(self):
        self._calls: list = []

    def add(self, key, value):
        self._calls.append(("add", key, value))

    def add_batch(self, keys, values):
        self._calls.append(("add_batch", keys, values))

    def add_frame(self, frame):
        self._calls.append(("add_frame", frame))

    def add_kv(self, other):
        self._calls.append(("add_kv", other))

    def replay(self, kv: KeyValue):
        for name, *args in self._calls:
            getattr(kv, name)(*args)
        self._calls.clear()


def _fusible(fn):
    """Defer this op into the plan recorder (plan/) when one is active —
    either an explicit ``with mr.pipeline():`` block or the ``fuse=1``
    setting (MRTPU_FUSE).  The deferred call returns a lazy
    :class:`~..plan.recorder.PendingCount`; barriers (maps, gather,
    scans, print, stats, save/load, copy) flush the plan, and the fuser
    replays any non-fusible stage through the undeferred method —
    ``_plan_replaying`` guards that re-entry."""
    op = fn.__name__

    @functools.wraps(fn)
    def wrapper(self, *args, **kw):
        if not self._plan_replaying:
            if not _defer_ok(op, args, kw):
                # user-callback ops (host reduces, ptr-carrying calls,
                # comparator sorts) can have arbitrary Python side
                # effects the caller observes right after the call —
                # the sssp shape reduce(f, ptr=open_mr), closure
                # counters — and they never fuse anyway: they are a
                # barrier, not a recorded stage
                self._flush_plan()
                return fn(self, *args, **kw)
            rec = self._plan
            if rec is None and self.settings.fuse:
                from ..plan.recorder import PlanRecorder
                rec = self._plan = PlanRecorder(self, auto=True)
            if rec is not None:
                return rec.record(op, args, kw)
        return fn(self, *args, **kw)
    return wrapper


def _defer_ok(op: str, args: tuple, kw: dict) -> bool:
    """Only ops that could possibly fuse are worth deferring: aggregate,
    convert, int-flag sorts and registered-kernel reduces.  Anything
    carrying a user callback runs as a barrier instead."""
    if op in ("sort_keys", "sort_values"):
        arg = args[0] if args else kw.get("flag_or_cmp", 1)
        return not callable(arg)
    if op != "reduce":
        return True          # aggregate / convert
    if kw.get("ptr") is not None or (len(args) > 1 and args[1] is not None):
        return False
    fn = args[0] if args else kw.get("func")
    from ..plan.fuser import _kernel_op
    return fn is not None and _kernel_op(fn) is not None


def _traced(fn):
    """Wrap an MR op in a tracer span (gpu_mapreduce_tpu/obs): wall
    time, counter deltas (shuffle/pad/spill bytes, HBM hi-water) and the
    returned global pair count land as span attributes; nesting follows
    the call structure (collate parents aggregate+convert, compress
    parents convert+reduce, the shuffle/ingest child spans hang under
    their op).  Disabled tracing costs one attribute check."""
    op = fn.__name__

    @functools.wraps(fn)
    def wrapper(self, *args, **kw):
        tr = self.tracer
        if not tr.enabled:
            return fn(self, *args, **kw)
        with tr.span(op, cat="mr_op",
                     shards=self.backend.nprocs) as sp:
            out = fn(self, *args, **kw)
            if isinstance(out, int):
                sp.set(npairs=out)
            if op.startswith("map_file"):
                sp.set(ingest=self.last_ingest.get("mode"))
            return out
    return wrapper


class MapReduce:
    """One MapReduce object owns at most one KV and/or one KMV
    (reference src/mapreduce.h:43-44)."""

    def __init__(self, comm=None, trace=None, metrics_port=None,
                 **settings):
        self.error = Error()
        self.settings = Settings(**settings)
        self.settings.validate(self.error)
        self.counters = global_counters()
        # fault-tolerance knobs (ft/): apply MRTPU_FAULTS / MRTPU_RETRY
        # when they changed — two getenv+compare when they did not
        from ..ft import configure_from_env as _ft_env
        _ft_env()
        # tracing is process-global (obs/): `trace=path` turns on the
        # JSONL sink (the MRTPU_TRACE env var does the same without a
        # code change); `trace=True` enables the in-memory ring only
        from ..obs import get_tracer
        self.tracer = get_tracer()
        if trace:
            self.tracer.enable(jsonl=trace if isinstance(trace, str)
                               else None)
        # live metrics are process-global too: `metrics_port=N` arms the
        # registry + span bridge and serves /metrics on localhost:N (the
        # MRTPU_METRICS_PORT env var does the same; obs/httpd.py).  A
        # bind failure (port already taken by a sibling process) warns
        # instead of killing the constructor — metrics must never fail
        # the app they observe
        if metrics_port is not None:
            try:
                from ..obs.httpd import ensure_server
                ensure_server(int(metrics_port))
            except Exception as e:
                self.error.warning(
                    f"metrics server on port {metrics_port!r} failed "
                    f"({e!r}); continuing without live export")
        if comm is None or comm == 1 or (isinstance(comm, int)):
            self.backend = SerialBackend()
        else:
            # a jax.sharding.Mesh → distributed backend (parallel/)
            from ..parallel.backend import MeshBackend
            self.backend = MeshBackend(comm)
        self._kv_data: Optional[KeyValue] = None
        self._kmv_data: Optional[KeyMultiValue] = None
        self._open = False
        self._last_stats: dict = {}
        self._plan = None              # active plan recorder (plan/)
        self._plan_replaying = False   # fuser is replaying a stage
        self.last_exchange = None      # per-call ExchangeCallStats
        # which path the last file map took ({"mode": "mesh"|"host", …},
        # parallel/ingest.py); None-mode until a file map runs
        self.last_ingest: dict = {"mode": None}
        self._ingest_pool_obj = None   # shared ingest executor (lazy)

    # ------------------------------------------------------------------
    # settings passthrough (reference exposes them as public members)
    # ------------------------------------------------------------------
    def __getattr__(self, name):
        s = self.__dict__.get("settings")
        if s is not None and hasattr(s, name):
            return getattr(s, name)
        raise AttributeError(name)

    def set(self, **kw):
        candidate = _copymod.deepcopy(self.settings)
        for k, v in kw.items():
            if not hasattr(candidate, k):
                self.error.all(f"unknown setting {k!r}")
            setattr(candidate, k, v)
        candidate.validate(self.error)  # raises before touching live settings
        self.settings = candidate
        # turning fusion off is a barrier: an active fuse=1 auto
        # recorder must not keep deferring past the user's fuse=0
        if not candidate.fuse and self._plan is not None and self._plan.auto:
            self._flush_plan()
        return self

    # ------------------------------------------------------------------
    # datasets: reading kv/kmv is a plan barrier (plan/) — any pending
    # deferred chain materializes first, so direct readers (apps, oink
    # commands, checkpoint, user code) never see stale/None state under
    # fuse=1.  During plan execution the recorder's stage list is empty
    # (recorder.flush swaps it out first), so these reads cost nothing.
    # ------------------------------------------------------------------
    @property
    def kv(self) -> Optional[KeyValue]:
        rec = self.__dict__.get("_plan")
        if rec is not None and rec.stages:
            self._flush_plan()
        return self._kv_data

    @kv.setter
    def kv(self, value: Optional[KeyValue]) -> None:
        # writes are barriers too: pending deferred ops were issued
        # against the OLD dataset — eager semantics would have run them
        # before the caller's assignment, so run them now
        rec = self.__dict__.get("_plan")
        if rec is not None and rec.stages:
            self._flush_plan()
        self._kv_data = value

    @property
    def kmv(self) -> Optional[KeyMultiValue]:
        rec = self.__dict__.get("_plan")
        if rec is not None and rec.stages:
            self._flush_plan()
        return self._kmv_data

    @kmv.setter
    def kmv(self, value: Optional[KeyMultiValue]) -> None:
        rec = self.__dict__.get("_plan")
        if rec is not None and rec.stages:
            self._flush_plan()
        self._kmv_data = value

    # ------------------------------------------------------------------
    # lazy pipeline recording (plan/)
    # ------------------------------------------------------------------
    def pipeline(self):
        """Record the ops issued inside the block and run them fused::

            with mr.pipeline():
                mr.aggregate(); mr.convert(); mr.reduce(count, batch=True)

        Exit (or any barrier op) fuses maximal device-tier runs into
        single compiled programs via the plan cache; non-fusible stages
        fall back to the eager path.  The same recording starts
        implicitly per-op under ``fuse=1`` / ``MRTPU_FUSE=1``."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            from ..plan.recorder import PlanRecorder
            prev = self._plan
            rec = self._plan = PlanRecorder(self)
            if prev is not None:
                # adopt a pre-existing recorder's pending stages (e.g.
                # fuse=1 deferred an aggregate before this block) so
                # they execute in issue order — and may fuse with ours
                rec.stages, prev.stages = prev.stages, []
                if prev.auto:
                    prev = None
            try:
                yield rec
            except BaseException:
                # abort, don't run heavy deferred compute mid-unwind or
                # let a replay error mask the user's exception: the
                # un-flushed tail is discarded (prefixes a mid-block
                # barrier already flushed stay applied)
                rec.stages.clear()
                raise
            finally:
                if self._plan is rec:
                    self._plan = prev
                rec.flush()
        return _ctx()

    def _flush_plan(self) -> None:
        """Execute any pending recorded plan (the barrier hook).  Auto
        recorders (fuse=1) uninstall; an explicit pipeline() recorder
        stays installed and keeps recording after the barrier."""
        rec = self._plan
        if rec is None:
            return
        # the plan barrier is a cancellation barrier too: a cancelled
        # request's pending chain is never dispatched (the request
        # owner then calls discard_plan so the RELEASE path's dataset
        # reads — also flush barriers — cannot dispatch it either)
        from ..obs.context import barrier_check
        barrier_check()
        if rec.auto:
            self._plan = None
        rec.flush()

    def discard_plan(self) -> None:
        """Drop any pending recorded stages WITHOUT executing them —
        the cancellation path (serve/session.py): a cancelled request's
        deferred chain must not dispatch from the cleanup that releases
        its frames (``kv``/``kmv`` reads are flush barriers).  The
        stages' PendingCounts stay unresolved and raise if ever read,
        like any discarded pending value."""
        rec = self._plan
        if rec is None:
            return
        self._plan = None
        rec.stages.clear()

    # ------------------------------------------------------------------
    # internal helpers
    # ------------------------------------------------------------------
    def _ingest_pool(self):
        """ONE ThreadPoolExecutor per MapReduce for mapstyle-2 ingest
        (run_sinks / _run_tasks) instead of a fresh executor per call —
        thread spin-up was per-shard overhead on the pipelined mesh
        ingest.  Sized once at min(cpu, 16).  A weakref finalizer shuts
        the pool down when the MR is collected, so a long-lived process
        churning MapReduce objects never accumulates idle worker
        threads (the executor must not anchor a reference cycle back to
        self)."""
        pool = self._ingest_pool_obj
        if pool is None:
            import os as _os
            import weakref
            from concurrent.futures import ThreadPoolExecutor
            pool = ThreadPoolExecutor(
                max_workers=max(1, min((_os.cpu_count() or 4), 16)),
                thread_name_prefix="mrtpu-ingest")
            self._ingest_pool_obj = pool
            weakref.finalize(self, pool.shutdown, False)
        return pool

    def _new_kv(self, name="kv") -> KeyValue:
        return KeyValue(self.settings, self.error, self.counters, name)

    def _new_kmv(self) -> KeyMultiValue:
        return KeyMultiValue(self.settings, self.error, self.counters)

    def _require_kv(self, op: str) -> KeyValue:
        self._flush_plan()   # barrier: readers need the real dataset
        if self.kv is None or not self.kv.complete_done:
            self.error.all(f"Cannot {op} without completed KeyValue")
        return self.kv

    def _require_kmv(self, op: str) -> KeyMultiValue:
        self._flush_plan()
        if self.kmv is None:
            self.error.all(f"Cannot {op} without KeyMultiValue")
        return self.kmv

    def _start_map(self, addflag: int) -> KeyValue:
        self._flush_plan()   # a new map consumes/replaces the dataset
        if self.kmv is not None:
            self.kmv.free()
            self.kmv = None
        if addflag and self.kv is not None:
            self.kv.append()
        else:
            if self.kv is not None:
                self.kv.free()
            self.kv = self._new_kv()
        return self.kv

    def _finish_kv(self, op: str) -> int:
        if self._open:
            return self.kv.nkv
        n = self.kv.complete()
        n = int(self.backend.allreduce_sum(n))
        self._op_stats(op, nkv=n)
        return n

    def _op_stats(self, op: str, **kw):
        self._last_stats = {"op": op, **kw}
        # ft/: one durable journal record per completed barrier op (and
        # the programmatic auto-checkpoint trigger); a dict-check no-op
        # when MRTPU_JOURNAL is unarmed
        from ..ft.journal import note_op
        note_op(self, op, kw.get("nkv", kw.get("nkmv")))
        if self.settings.verbosity:
            self.kv_stats(self.settings.verbosity, _op=op)
            if self.settings.verbosity >= 2 and self._op_snap is not None:
                c = self.counters
                w0, r0, s0 = self._op_snap
                dw, dr, ds = c.wsize - w0, c.rsize - r0, c.cssize - s0
                if dw or dr or ds:
                    print(f"  {op} I/O: {dw / (1 << 20):.3g} Mb spilled, "
                          f"{dr / (1 << 20):.3g} Mb re-read, "
                          f"{ds / (1 << 20):.3g} Mb shuffled")
        self._op_snap = None

    _op_snap = None

    def _begin_op(self) -> Timer:
        """Per-op start: timer + counter snapshot for verbosity=2 deltas
        (the reference's file_stats/stats per-op reporting,
        src/mapreduce.cpp:3112-3226).  The obs/ span layer snapshots the
        same counters independently (Span.__enter__) — kept separate on
        purpose: the print path must work with tracing disabled, and the
        disabled tracer must cost nothing, so neither can own the other's
        snapshot.

        Also the per-op cancellation barrier: a request cancelled (or
        past its deadline) stops HERE, before the op does any work —
        the dataset is whatever the previous op left, consistent and
        checkpointable (obs/context.barrier_check)."""
        from ..obs.context import barrier_check
        barrier_check()
        c = self.counters
        self._op_snap = (c.wsize, c.rsize, c.cssize)
        return Timer()

    def _shard_counts(self, which: str = "kv"):
        """Per-shard row counts: mesh datasets report real shard counts;
        host datasets report one value per frame (the serial 'procs')."""
        ds = self.kv if which == "kv" else self.kmv
        if ds is None:
            return []
        out = []
        for f in ds._frames:
            counts = getattr(f, "gcounts" if which == "kmv" else "counts",
                             None)
            if counts is not None:
                out.extend(int(x) for x in counts)
            else:
                out.append(f.n if hasattr(f, "n") else len(f))
        return out

    def _tier_note(self, op: str, fr) -> None:
        """verbosity≥2: say which tier an op ran on — a silent fall to the
        host per-pair path is a 1000× slowdown the user should see.  The
        same fact lands on the current span (obs/) for machine readers."""
        from .frame import KMVFrame, KVFrame as _KVF
        host = isinstance(fr, (KMVFrame, _KVF))
        self.tracer.annotate(tier="host" if host else "device",
                             rows=len(fr))
        if self.settings.verbosity >= 2:
            n = len(fr)
            print(f"  {op}: {'host per-row' if host else 'device batch'} "
                  f"tier ({n} rows)")

    # ------------------------------------------------------------------
    # map family (reference src/mapreduce.cpp:1044-1642)
    # ------------------------------------------------------------------
    def _run_tasks(self, kv, tasks, call: Callable) -> int:
        """Dispatch ``call(itask, payload, sink)`` over an iterable of
        task payloads, honouring mapstyle (reference map_tasks
        scheduling, src/mapreduce.cpp:1136-1213).  Returns the task count.

        * 0 chunk / 1 stride — under one controller both reduce to "run
          every task here", in task order;
        * 2 master-slave — the reference hands tasks to ranks on demand
          from a master work queue.  The controller analog is a dynamic
          thread pool: workers PULL the next task when free (good for
          I/O-bound file ingestion, where CPython releases the GIL).
          Each task writes a private buffer; buffers replay into the
          real KV in task order — so the result is bit-identical to
          styles 0/1 (*stronger* than the reference, whose master-slave
          pair order is schedule-dependent) and the KV's normal spill
          budget applies as tasks complete.  A bounded in-flight window
          backpressures both the payload producer (chunk readers) and
          buffered output — peak extra memory is O(window) tasks, never
          O(ntasks)."""
        from ..ft.retry import ingest_task
        onfault = self.settings.onfault
        if self.settings.mapstyle != 2:
            n = 0
            for itask, payload in enumerate(tasks):
                # ft/: per-task fault points + retry/quarantine policy;
                # attempts buffer into a private sink only when the
                # policy is armed (zero-delta fast path otherwise), and
                # a raw OSError wraps as MRError naming file/task
                ingest_task(call, itask, payload, kv, onfault=onfault,
                            private_sink=False)
                n += 1
            return n
        from collections import deque

        from ..obs.context import bind as _ctx_bind
        ingest_task = _ctx_bind(ingest_task)   # pool tasks charge the
        #                                        submitting request
        pool = self._ingest_pool()     # shared per-MR executor
        nworkers = pool._max_workers
        window = 4 * nworkers
        inflight: deque = deque()      # (future, sink) in task order
        n = 0

        def drain_one():
            fut, sink = inflight.popleft()
            fut.result()               # propagate callback exceptions
            sink.replay(kv)

        try:
            for itask, payload in enumerate(tasks):
                if len(inflight) >= window:
                    drain_one()
                sink = _TaskSink()
                inflight.append(
                    (pool.submit(ingest_task, call, itask, payload, sink,
                                 onfault=onfault), sink))
                n += 1
            while inflight:
                drain_one()
        except BaseException:
            for fut, _ in inflight:
                fut.cancel()
            raise
        return n

    @_traced
    def map(self, nmap: int, func: Callable, ptr=None, addflag: int = 0) -> int:
        """Task map: func(itask, kv, ptr) called for nmap tasks
        (reference map(nmap,func,ptr,addflag) → map_tasks,
        src/mapreduce.cpp:1044-1225)."""
        t = self._begin_op()
        kv = self._start_map(addflag)
        self._run_tasks(kv, range(nmap),
                        lambda itask, _task, sink: func(itask, sink, ptr))
        n = self._finish_kv("map")
        self._time("map", t)
        return n


    def _find_inputs(self, files, recurse, readflag) -> List[str]:
        """findfiles under the ft/ discovery policy: a failing path
        surfaces as MRError naming it (never a raw OSError), or —
        under onfault="skip" — quarantines and drops, exactly like the
        same failure noticed one stage later at task-read time."""
        from ..ft.retry import input_unreadable, quarantine_or_raise
        if self.settings.onfault != "skip":
            try:
                return findfiles(files, bool(recurse), bool(readflag))
            except OSError as e:
                raise input_unreadable(e) from e
        names: List[str] = []
        for p in files:
            try:
                names.extend(findfiles([p], bool(recurse),
                                       bool(readflag)))
            except OSError as e:
                quarantine_or_raise(e, p, "skip")
        return names

    @_traced
    def map_files(self, files: Union[str, Sequence[str]], func: Callable,
                  ptr=None, self_flag: int = 0, recurse: int = 0,
                  readflag: int = 0, addflag: int = 0) -> int:
        """File map: func(itask, filename, kv, ptr) per file (reference
        map(nstr,strings,self,recurse,readflag,func,ptr,addflag),
        src/mapreduce.cpp:1060-1092).

        On a mesh backend the ingest is PER-SHARD (parallel/ingest.py):
        each shard's contiguous byte-balanced slice of the file list
        lands on its own device at map time, with byte/object keys
        interned into dest-sharded decode tables — the reference's
        'every rank reads its own files' map stage
        (src/mapreduce.cpp:1102-1225).  ``last_ingest`` records which
        path ran."""
        t = self._begin_op()
        if isinstance(files, str):
            files = [files]
        names = self._find_inputs(files, recurse, readflag)
        kv = self._start_map(addflag)
        call = lambda itask, fname, sink: func(itask, fname, sink, ptr)
        if self._mesh_ingest_ok(addflag):
            from ..parallel.ingest import mesh_map_files
            self.last_ingest = mesh_map_files(self, kv, names, call)
        else:
            self._run_tasks(kv, names, call)
            self.last_ingest = {"mode": "host"}
        n = self._finish_kv("map_files")
        self._time("map_files", t)
        return n

    def _mesh_ingest_ok(self, addflag: int) -> bool:
        """Per-shard file ingest preconditions: a multi-shard mesh, a
        fresh KV (addflag appends into an existing — possibly host —
        dataset), and in-core (the out-of-core page/spill budget is the
        host frames' machinery)."""
        from ..parallel.backend import MeshBackend
        return (isinstance(self.backend, MeshBackend)
                and self.backend.nprocs > 1
                and not addflag
                and self.settings.outofcore != 1)

    @_traced
    def map_file_char(self, nmap: int, files, recurse: int, readflag: int,
                      sepchar: Union[str, bytes], delta: int, func: Callable,
                      ptr=None, addflag: int = 0) -> int:
        """Chunk map with single-char separator (reference
        src/mapreduce.cpp:1232-1301,1312-1469): split files into ~nmap chunks
        ending on sepchar; func(itask, chunk_bytes, kv, ptr)."""
        return self._map_chunks(nmap, files, recurse, readflag,
                                _to_bytes(sepchar), delta, func, ptr, addflag)

    @_traced
    def map_file_str(self, nmap: int, files, recurse: int, readflag: int,
                     sepstr: Union[str, bytes], delta: int, func: Callable,
                     ptr=None, addflag: int = 0) -> int:
        """Chunk map with string separator (reference map_chunks sepstr
        variant)."""
        return self._map_chunks(nmap, files, recurse, readflag,
                                _to_bytes(sepstr), delta, func, ptr, addflag)

    def _map_chunks(self, nmap, files, recurse, readflag, sep, delta,
                    func, ptr, addflag) -> int:
        t = self._begin_op()
        if isinstance(files, str):
            files = [files]
        names = self._find_inputs(files, recurse, readflag)
        if not names:
            self.error.all("No files found for chunked map")
        per_file = max(1, nmap // max(1, len(names)))
        kv = self._start_map(addflag)
        call = lambda itask, chunk, sink: func(itask, chunk, sink, ptr)
        if self._mesh_ingest_ok(addflag):
            from ..parallel.ingest import mesh_map_chunks
            self.last_ingest = mesh_map_chunks(self, kv, names, per_file,
                                               sep, delta, call)
        else:
            from ..exec import prefetch_iter
            from ..ft.retry import (ingest_active, ingest_read,
                                    input_unreadable)
            onfault = self.settings.onfault

            def chunk_stream():
                # each file reads under the ft/ ingest.read policy:
                # retry budget, MRError naming the file, quarantine-
                # skip under onfault=skip (None = file skipped).  With
                # the policy disarmed chunks stay LAZY per chunk (the
                # host path's memory property) — a retry needs the
                # whole file's chunks re-readable, so only the armed
                # path materializes per file
                for fname in names:
                    if not ingest_active(onfault):
                        it = file_chunks(fname, per_file, sep, delta)
                        while True:
                            try:
                                chunk = next(it)
                            except StopIteration:
                                break
                            except OSError as e:
                                raise input_unreadable(e, fname) from e
                            yield chunk
                        continue
                    chunks = ingest_read(
                        lambda f=fname: list(file_chunks(f, per_file,
                                                         sep, delta)),
                        file=fname, onfault=onfault)
                    if chunks is not None:
                        yield from chunks
            # the serial chunk reader feeds the window lazily — under
            # mapstyle 2 backpressure holds O(window) chunks, not all.
            # exec/ prefetch overlaps the file read of chunk N+1 with
            # chunk N's callback (MRTPU_PREFETCH extra chunks resident)
            self._run_tasks(kv, prefetch_iter(chunk_stream(),
                                              path="ingest.serial"), call)
            self.last_ingest = {"mode": "host"}
        n = self._finish_kv("map_chunks")
        self._time("map_chunks", t)
        return n

    @_traced
    def map_mr(self, mr: "MapReduce", func: Callable, ptr=None,
               addflag: int = 0, batch: bool = False) -> int:
        """Map over an existing MR's KV pairs (reference map(mr,func,...),
        src/mapreduce.cpp:1560-1642; self-map via snapshot 1584-1601).

        host path: func(itask, key, value, kv, ptr) per pair;
        batch path: func(frame, kv, ptr) per KVFrame (vectorised)."""
        t = self._begin_op()
        src = mr._require_kv("map over")
        src_frames = list(src.frames())  # snapshot supports self-map
        kv = self._start_map(addflag)
        itask = 0
        for fr in src_frames:
            if batch:
                if not isinstance(fr, KVFrame):
                    # the callback may add_frame(fr) into the new KV —
                    # mark sharded frames so donation (exec/) never
                    # deletes arrays the snapshot still references
                    fr._shared = True
                func(fr, kv, ptr)
                itask += 1
            else:
                for k, v in fr.pairs():
                    func(itask, k, v, kv, ptr)
                    itask += 1
        n = self._finish_kv("map_mr")
        self._time("map_mr", t)
        return n

    # ------------------------------------------------------------------
    # shuffle / distribution ops
    # ------------------------------------------------------------------
    @_fusible
    @_traced
    def aggregate(self, hash_fn: Optional[Callable] = None) -> int:
        """THE shuffle: each key to one proc — user hash or
        hashlittle(key)%nprocs (reference src/mapreduce.cpp:385-563;
        call stack SURVEY.md §3.2).  Serial backend: no-op."""
        t = self._begin_op()
        kv = self._require_kv("aggregate")
        self.backend.aggregate(self, hash_fn)
        self._op_stats("aggregate", nkv=kv.nkv)
        self._time("aggregate", t, comm=True)
        return int(self.backend.allreduce_sum(kv.nkv))

    @_traced
    def broadcast(self, root: int = 0) -> int:
        """Replicate root's KV on all procs (reference
        src/mapreduce.cpp:569-623)."""
        kv = self._require_kv("broadcast")
        self.backend.broadcast(self, root)
        return int(self.backend.allreduce_sum(kv.nkv))

    @_traced
    def gather(self, nprocs: int) -> int:
        """Funnel KV onto the first nprocs procs (reference
        src/mapreduce.cpp:893-1036)."""
        kv = self._require_kv("gather")
        if nprocs <= 0:
            self.error.all("Cannot gather to fewer than 1 processor")
        self.backend.gather(self, nprocs)
        return int(self.backend.allreduce_sum(kv.nkv))

    @_traced
    def scrunch(self, nprocs: int, key) -> int:
        """gather + collapse (reference src/mapreduce.cpp:2075-2095)."""
        self.gather(nprocs)
        return self.collapse(key)

    # ------------------------------------------------------------------
    # grouping ops
    # ------------------------------------------------------------------
    def _use_external(self, kv: KeyValue) -> bool:
        """Out-of-core multi-frame host dataset ⇒ stream through the
        external sort/merge instead of consolidating in core."""
        return (self.settings.outofcore == 1 and kv.nframes > 1
                and kv.is_host_dataset())

    def _hbm_budget_bytes(self) -> Optional[int]:
        """Per-shard HBM budget for mesh datasets: maxpage frames ×
        memsize MB — the device-tier reading of the reference's page
        budget (every op runs in 1–7 fixed pages no matter the data,
        doc/Interface_c++.txt:39-59).  None = unlimited (maxpage 0 or
        in-core mode)."""
        s = self.settings
        if s.outofcore != 1 or s.maxpage == 0:
            return None
        return s.memsize * (1 << 20) * s.maxpage

    def _mesh_over_budget(self, kv: KeyValue) -> bool:
        """Whether the mesh-resident per-shard bytes of kv exceed the
        HBM budget (VERDICT r2 #3)."""
        budget = self._hbm_budget_bytes()
        if budget is None or kv.is_host_dataset():
            return False
        from ..parallel.sharded import ShardedKV
        per_shard = sum(f.nbytes() // max(f.nprocs, 1)
                        for f in kv._frames if isinstance(f, ShardedKV))
        return per_shard > budget

    def _demote_mesh_kv(self) -> None:
        """Stream every mesh frame's shard blocks to host frames under
        the page budget (spilling beyond maxpage like any host dataset),
        so convert/sort can run the bounded external path.  One shard
        block is resident at a time; the device dataset frees at the
        end."""
        from .dataset import _split_to_budget
        from ..parallel.sharded import ShardedKV
        kv = self.kv
        newkv = self._new_kv()
        # kv.frames(), not kv._frames: spilled host frames load lazily
        # (a _Spilled record has no to_host) and sharded frames stream
        # per shard block
        for fr in kv.frames():
            if isinstance(fr, ShardedKV):
                for p in range(fr.nprocs):
                    if int(fr.counts[p]):
                        for piece in _split_to_budget(
                                fr.shard_to_host(p), self.settings):
                            newkv._push_frame(piece)
            else:
                for piece in _split_to_budget(
                        fr if isinstance(fr, KVFrame) else fr.to_host(),
                        self.settings):
                    newkv._push_frame(piece)
        kv.free()
        newkv.nkv = sum(newkv._frame_n(f) for f in newkv._frames)
        newkv.complete_done = True
        self.kv = newkv

    @_fusible
    @_traced
    def convert(self) -> int:
        """Local KV→KMV grouping (reference src/mapreduce.cpp:861-886 →
        KeyMultiValue::convert; here sort+segment, SURVEY.md §3.3).  An
        out-of-core multi-frame dataset streams: external sort runs →
        k-way merge → group-boundary frame cuts, in ~one page budget of
        memory (the Spool cascade's job, src/mapreduce.cpp:2359-2633)."""
        t = self._begin_op()
        kv = self._require_kv("convert")
        self.kmv = self._new_kmv()
        if self._mesh_over_budget(kv):
            # a mesh dataset past the per-shard HBM budget demotes to
            # host page frames and groups through the external path
            self._demote_mesh_kv()
            kv = self.kv
        if self._use_external(kv):
            from .external import external_sorted_chunks, group_stream
            chunks = external_sorted_chunks(kv.frames(), "key",
                                            self.settings, self.counters)
            for kmv_frame in group_stream(chunks):
                self.kmv.push(kmv_frame)
        else:
            frame = kv.one_frame()
            if isinstance(frame, KVFrame):
                kmv_frame = group_frame(frame)
            else:  # ShardedKV → per-shard sort+segment under shard_map
                from ..parallel.group import convert_sharded
                kmv_frame = convert_sharded(frame, self.counters)
            self.kmv.push(kmv_frame)
        kv.free()
        self.kv = None
        n = self.kmv.complete()
        self._op_stats("convert", nkmv=n)
        self._time("convert", t)
        return int(self.backend.allreduce_sum(n))

    @_traced
    def collate(self, hash_fn: Optional[Callable] = None) -> int:
        """aggregate + convert (reference src/mapreduce.cpp:710-738)."""
        self.aggregate(hash_fn)
        return self.convert()

    @_traced
    def clone(self) -> int:
        """KV→KMV, each pair its own 1-value group (reference
        src/mapreduce.cpp:631-652).  Sharded input clones per shard on
        device (row i ⇒ group i of size 1)."""
        kv = self._require_kv("clone")
        fr = kv.one_frame()
        if not isinstance(fr, KVFrame):
            from ..parallel.devkernels import clone_sharded
            kmv_frame = clone_sharded(fr)
        else:
            n = len(fr)
            kmv_frame = KMVFrame(fr.key, np.ones(n, np.int64),
                                 np.arange(n + 1, dtype=np.int64), fr.value)
        kv.free()
        self.kv = None
        self.kmv = self._new_kmv()
        self.kmv.push(kmv_frame)
        return int(self.backend.allreduce_sum(self.kmv.complete()))

    @_traced
    def collapse(self, key) -> int:
        """KV→single KMV group per proc: multivalue = [k1,v1,k2,v2,...]
        (reference src/mapreduce.cpp:681-702).  Keys and values must share a
        representable common type (all bytes, or all numeric of one shape) —
        the reference interleaves raw bytes; we interleave typed rows and
        refuse to silently coerce across types."""
        kv = self._require_kv("collapse")
        parts: List[Column] = []
        for fr in kv.frames():      # spilled frames stream one at a time
            fr = fr.to_host()
            if len(fr):
                parts.append(_interleave_frame(fr, self.error))
        values = concat(parts) if parts \
            else DenseColumn(np.zeros(0, np.int64))
        n = len(values)
        kmv_frame = KMVFrame(_rows_to_column([key]), np.asarray([n]),
                             np.asarray([0, n]), values)
        kv.free()
        self.kv = None
        self.kmv = self._new_kmv()
        self.kmv.push(kmv_frame)
        return int(self.backend.allreduce_sum(self.kmv.complete()))

    # ------------------------------------------------------------------
    # reduce family
    # ------------------------------------------------------------------
    @_fusible
    @_traced
    def reduce(self, func: Callable, ptr=None, batch: bool = False,
               block_rows: Optional[int] = None) -> int:
        """Callback per KMV group → new KV (reference
        src/mapreduce.cpp:1769-1867; SURVEY.md §3.4).

        host path: func(key, values_list, kv, ptr) per group;
        batch path: func(kmv_frame, kv, ptr) per KMVFrame — the vectorised
        tier that keeps reduction on device (segment ops).

        ``block_rows``: groups larger than this receive a
        :class:`~.frame.BlockedMultivalue` instead of a list — the
        reference's multi-page "extended" KMV (nvalues==0 signal +
        multivalue_blocks(), src/mapreduce.cpp:1874-1925).  Callbacks use
        ``iter_blocks(mv)`` to handle both uniformly; setting it tiny is
        the ONEMAX stress hook (src/keymultivalue.cpp:43-45)."""
        t = self._begin_op()
        kmv = self._require_kmv("reduce")
        kv = self._new_kv()
        for fr in kmv.frames():
            if batch:
                self._tier_note("reduce(batch)", fr)
                func(fr, kv, ptr)
            elif block_rows is not None:
                self._reduce_blocked(fr, func, kv, ptr, block_rows)
            else:
                self.tracer.annotate(tier="host", groups=len(fr))
                if self.settings.verbosity >= 2:
                    print(f"  reduce: host per-group tier ({len(fr)} groups)")
                for k, vals in fr.groups():
                    func(k, vals, kv, ptr)
        kmv.free()
        self.kmv = None
        self.kv = kv
        return self._finish_kv("reduce")

    @staticmethod
    def _reduce_blocked(fr, func, kv, ptr, block_rows: int):
        if not isinstance(fr, KMVFrame):
            fr = fr.to_host()
        keys = fr.key.tolist()
        for i, k in enumerate(keys):
            if int(fr.nvalues[i]) > block_rows:
                func(k, BlockedMultivalue(fr, i, block_rows), kv, ptr)
            else:
                func(k, fr.group_values(i).tolist(), kv, ptr)

    @_traced
    def compress(self, func: Callable, ptr=None, batch: bool = False,
                 block_rows: Optional[int] = None) -> int:
        """Local convert + reduce, KV→KV — the combiner (reference
        src/mapreduce.cpp:749-851).  ``block_rows`` as in :meth:`reduce`."""
        self.convert()
        return self.reduce(func, ptr, batch=batch, block_rows=block_rows)

    # ------------------------------------------------------------------
    # scan / print (read-only)
    # ------------------------------------------------------------------
    @_traced
    def scan_kv(self, func: Callable, ptr=None, batch: bool = False) -> int:
        """Read-only iteration over KV pairs (reference
        src/mapreduce.cpp:1933-1997)."""
        kv = self._require_kv("scan")
        for fr in kv.frames():
            if batch:
                func(fr, ptr)
            else:
                for k, v in fr.pairs():
                    func(k, v, ptr)
        return int(self.backend.allreduce_sum(kv.nkv))

    @_traced
    def scan_kmv(self, func: Callable, ptr=None, batch: bool = False,
                 block_rows: Optional[int] = None) -> int:
        """Read-only iteration over KMV groups (reference
        src/mapreduce.cpp:2000-2065).  ``block_rows`` as in :meth:`reduce`
        (the reference's scan shares the multi-block machinery)."""
        kmv = self._require_kmv("scan")
        for fr in kmv.frames():
            if batch:
                func(fr, ptr)
            elif block_rows is not None:
                self._reduce_blocked(
                    fr, lambda k, mv, _kv, p: func(k, mv, p), None, ptr,
                    block_rows)
            else:
                for k, vals in fr.groups():
                    func(k, vals, ptr)
        return int(self.backend.allreduce_sum(kmv.nkmv))

    def print(self, nstride: int = 1, kflag: int = -1, vflag: int = -1,
              file=None, fflag: int = 0) -> int:
        """Formatted dump of KV pairs or KMV groups (reference print variants
        src/mapreduce.cpp:1671-1761; type decoders keyvalue.cpp:773-835).
        kflag/vflag are accepted for API parity; columns self-describe, so
        they only force integer/float/string formatting when >=0."""
        self._flush_plan()
        out = sys.stdout if file is None else (open(file, "a") if fflag else open(file, "w"))
        try:
            if self.kv is not None:
                count = 0
                for fr in self.kv.frames():
                    for k, v in fr.pairs():
                        if count % nstride == 0:
                            out.write(f"{_fmt(k, kflag)} {_fmt(v, vflag)}\n")
                        count += 1
                return self.kv.nkv
            if self.kmv is not None:
                for fr in self.kmv.frames():
                    for k, vals in fr.groups():
                        out.write(f"{_fmt(k, kflag)} " +
                                  " ".join(_fmt(v, vflag) for v in vals) + "\n")
                return self.kmv.nkmv
            self.error.all("Cannot print without KeyValue or KeyMultiValue")
        finally:
            if file is not None:
                out.close()

    # ------------------------------------------------------------------
    # sorting (reference src/mapreduce.cpp:2102-2352)
    # ------------------------------------------------------------------
    @_fusible
    @_traced
    def sort_keys(self, flag_or_cmp: Union[int, Callable] = 1) -> int:
        """Per-proc sort of KV by key.  int flag: |flag| selects the
        reference's pre-built comparator family (moot for typed columns),
        sign selects direction (reference flags ±1..6,
        src/mapreduce.cpp:2102-2126,2692-2802).  Callable: compare(a,b)→-1/0/1
        (appcompare)."""
        return self._sort_kv(by="key", flag_or_cmp=flag_or_cmp)

    @_fusible
    @_traced
    def sort_values(self, flag_or_cmp: Union[int, Callable] = 1) -> int:
        """Per-proc sort of KV by value (reference src/mapreduce.cpp:2152)."""
        return self._sort_kv(by="value", flag_or_cmp=flag_or_cmp)

    def _sort_kv(self, by: str, flag_or_cmp) -> int:
        t = self._begin_op()
        kv = self._require_kv(f"sort_{by}s")
        if self._mesh_over_budget(kv):
            self._demote_mesh_kv()   # see convert(): HBM budget
            kv = self.kv
        if not callable(flag_or_cmp) and self._use_external(kv):
            return self._sort_kv_external(kv, by, flag_or_cmp < 0, t)
        fr = kv.one_frame()
        if not isinstance(fr, KVFrame):
            interned = getattr(fr, f"{by}_decode", None) is not None
            budget = self._hbm_budget_bytes()
            if interned and budget is not None and fr.nbytes() > budget:
                # the interned device sort is GLOBAL (GSPMD gathers the
                # whole dataset transiently) — past the budget, demote
                # shard-by-shard into page frames (spilling past
                # maxpage) so the bounded external merge applies; a
                # single to_host() frame never qualified for
                # _use_external and just relocated the blow-up from HBM
                # to controller RAM (ADVICE r3)
                self._demote_mesh_kv()
                kv = self.kv
                if not callable(flag_or_cmp) and self._use_external(kv):
                    return self._sort_kv_external(kv, by,
                                                  flag_or_cmp < 0, t)
                fr = kv.one_frame()
            elif not callable(flag_or_cmp):
                # per-shard device sort; an interned byte/object column
                # sorts by an id→rank surrogate built once from the
                # decode table (u64 ids are hashes, so sorting raw ids
                # would not be lexicographic — reference flag 5/6 string
                # semantics, src/mapreduce.cpp:2763-2802) — the dataset
                # itself stays on device (VERDICT r2 #7)
                from ..parallel.group import (sort_interned_sharded,
                                              sort_sharded)
                out = (sort_interned_sharded if interned
                       else sort_sharded)(fr, by,
                                          descending=flag_or_cmp < 0)
                kv.free()
                kv.add_frame(out)
                n = kv.complete()
                self._op_stats(f"sort_{by}s", nkv=n)
                self._time("sort", t)
                return int(self.backend.allreduce_sum(n))
            # comparator callbacks serialize to host
            fr = fr.to_host()
        col = fr.key if by == "key" else fr.value
        if callable(flag_or_cmp):
            order = argsort_column(col, cmp=flag_or_cmp)
        else:
            order = argsort_column(col, descending=flag_or_cmp < 0)
        fr2 = fr.take(order)
        kv.free()
        kv.add_batch(fr2.key, fr2.value)
        n = kv.complete()
        self._op_stats(f"sort_{by}s", nkv=n)
        self._time("sort", t)
        return int(self.backend.allreduce_sum(n))

    def _sort_kv_external(self, kv: KeyValue, by: str, descending: bool,
                          t: Timer) -> int:
        """Out-of-core sort: external runs + k-way merge into a fresh
        spilling dataset; descending flips each ascending chunk and
        reverses the frame order (global order preserved, memory
        bounded)."""
        from .external import external_sorted_chunks
        newkv = self._new_kv()
        for ch in external_sorted_chunks(kv.frames(), by, self.settings,
                                         self.counters):
            if descending:
                ch = ch.take(np.arange(len(ch) - 1, -1, -1))
            newkv._push_frame(ch)
        if descending:
            newkv._frames.reverse()
        newkv.nkv = sum(newkv._frame_n(f) for f in newkv._frames)
        newkv.complete_done = True
        kv.free()
        self.kv = newkv
        self._op_stats(f"sort_{by}s", nkv=newkv.nkv)
        self._time("sort", t)
        return int(self.backend.allreduce_sum(newkv.nkv))

    @_traced
    def sort_multivalues(self, flag_or_cmp: Union[int, Callable] = 1) -> int:
        """Sort values *within* each multivalue (reference
        src/mapreduce.cpp:2210-2352)."""
        t = self._begin_op()
        kmv = self._require_kmv("sort_multivalues")
        new = self._new_kmv()
        for fr in kmv.frames():
            if not isinstance(fr, KMVFrame):  # ShardedKMV
                if callable(flag_or_cmp) or fr.value_decode is not None:
                    # comparator callbacks serialize; interned byte
                    # values decode first — their ids are hashes, not
                    # lexicographic order
                    fr = fr.to_host()
                else:
                    from ..parallel.group import sort_multivalues_sharded
                    new.push(sort_multivalues_sharded(
                        fr, descending=flag_or_cmp < 0))
                    continue
            values = _sort_groups(fr, flag_or_cmp)
            new.push(KMVFrame(fr.key, fr.nvalues, fr.offsets, values))
        kmv.free()
        self.kmv = new
        self._time("sort", t)
        return int(self.backend.allreduce_sum(new.complete()))

    # ------------------------------------------------------------------
    # whole-object ops
    # ------------------------------------------------------------------
    @_traced
    def add(self, mr: "MapReduce") -> int:
        """Append mr's KV pairs to my KV (reference
        src/mapreduce.cpp:348-374)."""
        self._flush_plan()
        src = mr._require_kv("add from")
        if self.kv is None:
            self.kv = self._new_kv()
        else:
            self.kv.append()
        self.kv.add_kv(src)
        return self._finish_kv("add")

    def copy(self) -> "MapReduce":
        """Deep copy: new MR with copied settings and data (reference
        src/mapreduce.cpp:269-342)."""
        self._flush_plan()
        mr = MapReduce()
        mr.backend = self.backend
        mr.settings = _copymod.deepcopy(self.settings)
        if self.kv is not None:
            mr.kv = mr._new_kv()
            mr.kv.add_kv(self.kv)
            mr.kv.complete()
        if self.kmv is not None:
            mr.kmv = mr._new_kmv()
            for fr in self.kmv.frames():
                mr.kmv.push(fr)
            mr.kmv.complete()
        return mr

    def stream(self, sources, dir: str, parser: str = "words",
               reduce: str = "count", **kw):
        """Open a standing query whose resident dataset is THIS object
        (stream/engine.py, doc/streaming.md): tail ``sources``
        (append-only files/dirs), cut micro-batches, run the
        ``parser``/``reduce`` chain on each delta and merge it here —
        after every committed batch ``self`` holds the up-to-date
        aggregate and ``self.kv`` reads it like any batch result.
        ``dir`` is the stream's durable home (journal + checkpoints);
        constructing over a directory with committed batches RESUMES
        from the last committed cursor.  Returns the
        :class:`~..stream.Stream` handle (poll_once/drain/status/
        snapshot/close)."""
        from ..stream import Stream
        comm = getattr(self.backend, "mesh", None)
        return Stream(dir, sources, parser=parser, reduce=reduce,
                      comm=comm, resident=self, **kw)

    def open(self, addflag: int = 0):
        """Begin cross-MR adds: my KV accepts kv.add() from other MRs'
        callbacks until close() (reference src/mapreduce.cpp:1648-1664)."""
        self._start_map(addflag)
        self._open = True
        return self.kv

    def close(self) -> int:
        """End cross-MR adds (reference src/mapreduce.cpp:658-672)."""
        if not self._open:
            self.error.all("Cannot close without open")
        self._open = False
        return self._finish_kv("close")

    # ------------------------------------------------------------------
    # stats (reference src/mapreduce.cpp:2937-3066)
    # ------------------------------------------------------------------
    def kv_stats(self, level: int = 0, _op: str = "") -> tuple:
        """Global pair/byte counts; level ≥ 2 adds the per-shard histogram
        (reference kv_stats verbosity=2, src/mapreduce.cpp:2937-2968 via
        write_histo — how imbalance/corruption is detected)."""
        self._flush_plan()
        kv = self.kv
        if kv is None:
            return (0, 0)
        n = int(self.backend.allreduce_sum(kv.nkv))
        nb = int(self.backend.allreduce_sum(kv.nbytes()))
        if level:
            print(f"{n} pairs, {nb / (1 << 20):.3g} Mb of KV data "
                  f"{('after ' + _op) if _op else ''}".rstrip())
            if level >= 2:
                from .runtime import write_histo
                write_histo("KV pairs", self._shard_counts("kv"))
        return (n, nb)

    def kmv_stats(self, level: int = 0) -> tuple:
        self._flush_plan()
        kmv = self.kmv
        if kmv is None:
            return (0, 0, 0)
        g = int(self.backend.allreduce_sum(kmv.nkmv))
        n = int(self.backend.allreduce_sum(kmv.nvalues))
        nb = int(self.backend.allreduce_sum(kmv.nbytes()))
        if level:
            print(f"{g} pairs, {n} values, {nb / (1 << 20):.3g} Mb of KMV data")
            if level >= 2:
                from .runtime import write_histo
                write_histo("KMV groups", self._shard_counts("kmv"))
        return (g, n, nb)

    # ------------------------------------------------------------------
    # checkpoint / restore (capability improvement over the reference,
    # which persists only via print-to-file text — SURVEY.md §5)
    # ------------------------------------------------------------------
    @_traced
    def save(self, path: str) -> int:
        """Checkpoint the current KV or KMV to a directory; returns the
        number of frames written (core/checkpoint.py).  The save runs
        under the ft/ ``checkpoint.save`` retry policy — the directory
        swap is atomic, so a retried save can never mix generations."""
        self._flush_plan()
        from .checkpoint import save as _save
        from ..ft.retry import retry_call
        return retry_call("checkpoint.save", lambda: _save(self, path),
                          detail=path)

    @_traced
    def load(self, path: str) -> int:
        """Replace the dataset with a checkpoint; returns the global
        pair/group count."""
        self._flush_plan()
        from .checkpoint import load as _load
        return _load(self, path)

    # ------------------------------------------------------------------
    # elastic topology (ROADMAP item 4: reshard live, resume anywhere)
    # ------------------------------------------------------------------
    @_traced
    def reshard(self, comm) -> int:
        """Redistribute the resident dataset onto a new topology and
        swap the backend — the live elasticity op (parallel/reshard.py,
        doc/reliability.md#elastic-recovery).

        ``comm``: a ``jax.sharding.Mesh`` of any width (sharded frames
        move N→M as a collective range exchange, global row/group order
        preserved exactly), or ``None``/an int for the serial backend
        (sharded frames compact to host).  Host-resident frames are
        untouched either way — they shard lazily at the next
        ``aggregate`` under the new backend, like fresh data.  Returns
        the global pair/group count, like every mutating op."""
        self._flush_plan()
        from .runtime import Timer as _T
        t = _T()
        if comm is None or isinstance(comm, int):
            new_backend = SerialBackend()
            mesh = None
        else:
            from ..parallel.backend import MeshBackend
            new_backend = MeshBackend(comm)
            mesh = comm
        from ..parallel.reshard import reshard_kmv, reshard_kv
        from ..parallel.sharded import ShardedKMV, ShardedKV
        from ..parallel.shuffle import free_if_donated
        nfrom = self.backend.nprocs

        def move(ds, fr):
            if not isinstance(fr, (ShardedKV, ShardedKMV)):
                return fr
            if mesh is None:
                return fr.to_host()
            if fr.mesh is mesh:
                return fr
            try:
                if isinstance(fr, ShardedKV):
                    return reshard_kv(fr, mesh,
                                      transport=self.settings.all2all,
                                      counters=self.counters)
                return reshard_kmv(fr, mesh,
                                   transport=self.settings.all2all,
                                   counters=self.counters)
            except BaseException:
                # donation may have consumed the frame mid-exchange:
                # leave a clean empty dataset, not deleted buffers
                free_if_donated(ds, fr)
                raise
        n = 0
        for ds in (self._kv_data, self._kmv_data):
            if ds is None:
                continue
            out = []
            for fr in ds._frames:
                new = move(ds, fr)
                if new is not fr:
                    self.counters.mem(new.nbytes() - fr.nbytes())
                out.append(new)
            ds._frames = out
        self.backend = new_backend
        if self._kv_data is not None:
            self._kv_data.nkv = sum(self._kv_data._frame_n(f)
                                    for f in self._kv_data._frames)
            n = self._kv_data.nkv
        if self._kmv_data is not None:
            n = self._kmv_data.complete()
        n = int(self.backend.allreduce_sum(n))
        self.counters.add(commtime=t.elapsed())
        self.last_reshard = {"from": nfrom, "to": self.backend.nprocs,
                             "wall_s": round(t.elapsed(), 6), "n": n}
        self._op_stats("reshard", nkv=n)
        return n

    def stats(self) -> dict:
        """The structured cumulative snapshot that ``cummulative_stats``
        prints: every Counters field by name (msizemax, rsize, wsize,
        cssize, crsize, cspad, commtime, msize, ndispatch), plus — when
        tracing is enabled (obs/) — an ``"ops"`` per-op aggregate over
        the span ring (count / total_s / byte sums per op name), plus a
        ``"plan"`` section with the compile-cache telemetry (plan cache
        + bounded shuffle jit caches: hits/misses/evictions) and the
        cumulative fusion-effectiveness counters (``"fusion"``:
        per-group fused/megafused/pallas program counts and dispatch
        savings vs the eager baseline — doc/plan.md), plus an
        ``"exec"`` section with the async-overlap telemetry (per-path
        overlap ratios + active knobs — doc/perf.md), plus —
        when the metrics registry is armed (obs/metrics.py) — a
        ``"metrics"`` section with the full labeled registry snapshot
        (op latency histograms, exchange byte counters, gauges)."""
        self._flush_plan()   # barrier: counters must include the chain
        out = self.counters.snapshot()
        if self.tracer.enabled:
            out["ops"] = self.tracer.stats()
        from ..plan.cache import cache_stats
        out["plan"] = cache_stats()
        # overlap telemetry (exec/): per-path busy/wait seconds and the
        # overlap ratio the mrtpu_overlap_ratio gauge exposes
        from ..exec import exec_stats
        out["exec"] = exec_stats()
        # fault-tolerance telemetry (ft/): retry outcomes per site,
        # faults injected, quarantine accounting, journal progress
        from ..ft import ft_stats
        out["ft"] = ft_stats()
        from ..obs import metrics as _metrics
        if _metrics.enabled():
            out["metrics"] = _metrics.snapshot()
        return out

    def cummulative_stats(self, level: int = 1, reset: int = 0):
        # a formatting consumer of the same snapshot stats() returns —
        # the two can never disagree
        s = self.stats()
        if level:
            print(f"Cummulative hi-water mem = {s['msizemax'] / (1 << 20):.3g} Mb")
            print(f"Cummulative spill I/O = {s['rsize'] / (1 << 20):.3g} Mb read, "
                  f"{s['wsize'] / (1 << 20):.3g} Mb written")
            print(f"Cummulative comm = {s['cssize'] / (1 << 20):.3g} Mb sent, "
                  f"{s['crsize'] / (1 << 20):.3g} Mb received, "
                  f"{s['cspad'] / (1 << 20):.3g} Mb padding, "
                  f"{s['commtime']:.3g} secs")
        if reset:
            self.counters.__init__()
        return self.counters

    def _time(self, op: str, t: Timer, comm: bool = False):
        dt = t.elapsed()
        if comm:
            self.counters.add(commtime=dt)
        if self.settings.timer:
            print(f"{op} time (secs) = {dt:.6g}")
            if self.settings.timer >= 2:
                # the controller orchestrates, so per-shard TIME is not
                # observable the way the reference's per-proc barriers are
                # (src/mapreduce.cpp:3112-3128); the per-shard ROW histogram
                # is the imbalance signal that histogram exposed
                from .runtime import write_histo
                which = "kv" if self.kv is not None else "kmv"
                write_histo(f"{op} rows", self._shard_counts(which))


# ---------------------------------------------------------------------------

def _to_bytes(s) -> bytes:
    return s.encode() if isinstance(s, str) else bytes(s)


def _rows_to_column(rows: list) -> Column:
    first = rows[0] if rows else 0
    if isinstance(first, (bytes, str)):
        return BytesColumn([r.encode() if isinstance(r, str) else r
                            for r in rows])
    from .dataset import rows_to_array
    return DenseColumn(rows_to_array(rows))


def _sort_groups(fr: KMVFrame, flag_or_cmp) -> Column:
    """Sort the values inside every group of a host KMVFrame.  Dense
    scalar values sort in ONE stable lexsort over (group, value) — no
    per-group Python; comparator callbacks and non-scalar values keep
    the per-group path."""
    if not callable(flag_or_cmp) and isinstance(fr.values, DenseColumn):
        vals = np.asarray(fr.values.data)
        if vals.ndim == 1:
            seg = np.repeat(np.arange(len(fr), dtype=np.int64),
                            np.asarray(fr.nvalues, dtype=np.int64))
            order = np.lexsort((vals, seg))     # ascending within groups
            if flag_or_cmp < 0:
                # descending: reverse each group's slice of the
                # ascending order (offsets arithmetic, still no loop)
                off = np.asarray(fr.offsets)
                pos = np.arange(len(vals), dtype=np.int64)
                order = order[off[seg] + off[seg + 1] - 1 - pos]
            return DenseColumn(vals[order])
    pieces = []
    for i in range(len(fr)):
        col = fr.group_values(i)
        if callable(flag_or_cmp):
            order = argsort_column(col, cmp=flag_or_cmp)
        else:
            order = argsort_column(col, descending=flag_or_cmp < 0)
        pieces.append(col.take(order))
    return concat(pieces) if pieces else fr.values


def _interleave_frame(fr: KVFrame, error: Error) -> Column:
    """Vectorised collapse() interleave of one frame: [k1,v1,k2,v2,...].
    Dense same-shape columns use a strided write (no per-row Python);
    bytes interleave as lists; anything ragged/object falls back to the
    per-row path."""
    k, v = fr.key, fr.value
    n = len(fr)
    if isinstance(k, BytesColumn) and isinstance(v, BytesColumn):
        out: list = [None] * (2 * n)
        out[0::2] = list(k.data)
        out[1::2] = list(v.data)
        return BytesColumn(out)
    if isinstance(k, DenseColumn) and isinstance(v, DenseColumn):
        ka, va = np.asarray(k.data), np.asarray(v.data)
        # fast path only for IDENTICAL dtypes: numpy "promotes"
        # uint64+int64 to float64, which would silently round u64 hash
        # ids above 2^53 — mixed dtypes take the exact per-row path
        if ka.shape[1:] == va.shape[1:] and ka.dtype == va.dtype:
            arr = np.empty((2 * n,) + ka.shape[1:], ka.dtype)
            arr[0::2] = ka
            arr[1::2] = va
            return DenseColumn(arr)
    rows: list = [None] * (2 * n)
    kl, vl = k.tolist(), v.tolist()
    rows[0::2] = kl
    rows[1::2] = vl
    return _interleave_rows(rows, error)


def _interleave_rows(rows: list, error: Error) -> Column:
    """Build the collapse() multivalue column, refusing mixed types."""
    if not rows:
        return DenseColumn(np.zeros(0, np.int64))
    if all(isinstance(r, (bytes, str)) for r in rows):
        return BytesColumn([r.encode() if isinstance(r, str) else r
                            for r in rows])
    if any(isinstance(r, (bytes, str)) for r in rows):
        error.all("collapse requires keys and values of a common type "
                  "(all bytes or all numeric)")
    from .dataset import rows_to_array
    arr = rows_to_array(rows)
    if arr.dtype == object:
        error.all("collapse requires keys and values of a common shape")
    return DenseColumn(arr)


def _fmt(x, flag: int) -> str:
    if isinstance(x, bytes):
        try:
            return x.decode()
        except UnicodeDecodeError:
            return repr(x)
    if isinstance(x, tuple):
        return " ".join(_fmt(e, flag) for e in x)
    if isinstance(x, float) or flag in (3, 4):
        return f"{x:g}"
    return str(x)
