"""Elastic recovery (ISSUE 8): live ``mr.reshard``, topology-portable
checkpoint resume, and end-to-end artifact integrity.

Contracts under test:

* ``mr.reshard(new_mesh)`` moves a live sharded dataset N→M as a
  collective range exchange with EXACT global row order preserved
  (N→M→N round-trips byte-identical, also under shuffle chaos);
* a checkpoint taken on one mesh width restores onto any other width
  (``ft.resume(dir, mesh=...)``), with the post-resume tail
  byte-identical to an uninterrupted run on the target mesh;
* every durable artifact (checkpoint frame, spill run, journal record)
  is digest-stamped on write and verified on read: a bit flip is
  detected (``mrtpu_integrity_failures_total{artifact}``), never
  silently consumed, and recovery routes through the existing ft/
  machinery — spill retries, checkpoint generation fallback, journal
  record quarantine."""

import glob
import json
import os

import numpy as np
import pytest

from gpu_mapreduce_tpu import ft
from gpu_mapreduce_tpu.core.mapreduce import MapReduce
from gpu_mapreduce_tpu.core.runtime import MRError
from gpu_mapreduce_tpu.parallel.mesh import make_mesh
import gpu_mapreduce_tpu.ft.retry as ftr


@pytest.fixture(autouse=True)
def ft_state(monkeypatch):
    slept = []
    monkeypatch.setattr(ftr, "_sleep", slept.append)
    ft.reset()
    yield slept
    ft.reset()


def _integrity_count(artifact: str) -> int:
    from gpu_mapreduce_tpu.obs.metrics import get_registry
    return get_registry().counter(
        "mrtpu_integrity_failures_total", "", ("artifact",)
    ).value(artifact=artifact)


def kv_rows(mr):
    """Host rows in EXACT global (shard-major) order."""
    return [(k, v) for fr in mr.kv.frames() for k, v in fr.pairs()]


def kmv_groups(mr):
    groups = {}
    mr.scan_kmv(lambda k, vs, p: groups.__setitem__(k, list(vs)))
    return groups


def _agg_mr(width: int) -> MapReduce:
    mr = MapReduce(make_mesh(width))
    keys = (np.arange(1200, dtype=np.uint64) * 7919) % 131
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys * 3))
    mr.aggregate()
    return mr


# ---------------------------------------------------------------------------
# mr.reshard
# ---------------------------------------------------------------------------

def test_reshard_roundtrip_preserves_exact_global_order():
    """N→M→N must be the identity on the global row order, not just the
    multiset — the range dest is monotone, so the exchange's packed
    output IS the contiguous split."""
    mr = _agg_mr(4)
    before = kv_rows(mr)
    assert mr.reshard(make_mesh(2)) == len(before)
    assert mr.backend.nprocs == 2
    assert kv_rows(mr) == before
    mr.reshard(make_mesh(8))
    assert mr.backend.nprocs == 8
    assert kv_rows(mr) == before
    mr.reshard(make_mesh(4))
    assert kv_rows(mr) == before
    assert mr.last_reshard["from"] == 8 and mr.last_reshard["to"] == 4


def test_reshard_chaos_golden_on_shuffle_exchange():
    """Chaos golden: injected shuffle.exchange faults absorbed by the
    retry budget leave the resharded rows byte-identical (the
    acceptance criterion's N→M→N under MRTPU_FAULTS)."""
    clean = _agg_mr(4)
    want = kv_rows(clean)
    ft.schedule(site="shuffle.exchange", rate=0.4, seed=11, max_faults=3)
    ft.set_budget("shuffle.exchange", 8)
    mr = _agg_mr(4)
    mr.reshard(make_mesh(2))
    mr.reshard(make_mesh(8))
    mr.reshard(make_mesh(4))
    assert kv_rows(mr) == want
    assert sum(ft.fault_counts().values()) >= 1, \
        "chaos schedule injected nothing — the golden proved nothing"


def test_reshard_byte_keyed_decode_tables_survive():
    """Interned byte-string keys decode correctly after the width
    changes (ShardTables route by id hash, not row placement)."""
    mr = MapReduce(make_mesh(4))
    words = [b"w%03d" % (i % 37) for i in range(500)]
    mr.map(1, lambda i, kv, p: [kv.add(w, 1) for w in words])
    mr.aggregate()
    before = sorted(kv_rows(mr))
    mr.reshard(make_mesh(2))
    assert sorted(kv_rows(mr)) == before
    mr.reshard(make_mesh(8))
    assert sorted(kv_rows(mr)) == before


def test_reshard_kmv_groups_atomic():
    """Grouped data reshards at group granularity: every group's value
    run stays whole, on every width."""
    mr = MapReduce(make_mesh(4))
    keys = np.arange(900, dtype=np.uint64) % 23
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys * 7))
    mr.collate()
    want = kmv_groups(mr)
    mr.reshard(make_mesh(8))
    assert kmv_groups(mr) == want
    mr.reshard(make_mesh(2))
    assert kmv_groups(mr) == want
    mr.reshard(None)          # serial pull-down compacts to host
    assert mr.backend.nprocs == 1
    assert kmv_groups(mr) == want


def test_reshard_empty_and_host_resident():
    mr = MapReduce(make_mesh(4))
    mr.map(1, lambda i, kv, p: None)
    mr.aggregate()
    assert mr.reshard(make_mesh(2)) == 0
    # host-resident (serial) data: reshard just swaps the backend;
    # the rows shard at the next aggregate like fresh data
    mr2 = MapReduce()
    mr2.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(64, dtype=np.uint64), np.ones(64, np.int64)))
    n = mr2.reshard(make_mesh(4))
    assert n == 64 and mr2.backend.nprocs == 4
    mr2.aggregate()
    assert sorted(kv_rows(mr2)) == [(i, 1) for i in range(64)]


# ---------------------------------------------------------------------------
# checkpoint manifests + integrity
# ---------------------------------------------------------------------------

def test_manifest_v2_shard_ranges_and_digests(tmp_path):
    mr = _agg_mr(4)
    ck = str(tmp_path / "ck")
    mr.save(ck)
    man = json.load(open(os.path.join(ck, "manifest.json")))
    assert man["version"] == 2
    assert man["mesh"]["nprocs"] == 4
    fm = man["frames"][0]
    assert fm["rows"] == [0, len(kv_rows(mr))]
    assert fm["digest"].startswith("crc32:")
    assert len(fm["shards"]) == 4
    assert sum(fm["shards"]) == fm["n"]
    assert len(fm["shard_digests"]) == 4
    # round-trips into a fresh MR, on a different width and on none
    mr2 = MapReduce(make_mesh(2))
    mr2.load(ck)
    mr3 = MapReduce()
    mr3.load(ck)
    assert sorted(kv_rows(mr3)) == sorted(kv_rows(mr))


def test_manifest_v1_still_loads(tmp_path):
    """Back-compat: a pre-integrity (v1) manifest restores with no
    digest checks — absence of a stamp is not corruption."""
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(10, dtype=np.uint64), np.arange(10, dtype=np.uint64)))
    ck = str(tmp_path / "v1")
    mr.save(ck)
    mpath = os.path.join(ck, "manifest.json")
    man = json.load(open(mpath))
    json.dump({"version": 1, "kind": man["kind"],
               "nframes": man["nframes"], "counts": man["counts"]},
              open(mpath, "w"))
    assert MapReduce().load(ck) == 10


def test_bitflipped_checkpoint_detected_never_consumed(tmp_path):
    mr = _agg_mr(4)
    ck = str(tmp_path / "ck")
    mr.save(ck)
    from gpu_mapreduce_tpu.core import checkpoint
    assert checkpoint.validate(ck)
    fpath = glob.glob(os.path.join(ck, "frame-*.npz"))[0]
    blob = bytearray(open(fpath, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(fpath, "wb").write(bytes(blob))
    before = _integrity_count("checkpoint")
    assert not checkpoint.validate(ck)
    with pytest.raises(OSError, match="checksum mismatch"):
        MapReduce().load(ck)
    assert _integrity_count("checkpoint") > before


def test_verify_knob_off_skips_digest_checks(tmp_path, monkeypatch):
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: kv.add(1, 2))
    ck = str(tmp_path / "ck")
    mr.save(ck)
    calls = []
    from gpu_mapreduce_tpu.utils import integrity
    real = integrity.file_digest
    monkeypatch.setattr(integrity, "file_digest",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    monkeypatch.setenv("MRTPU_VERIFY", "0")
    MapReduce().load(ck)
    assert not calls, "MRTPU_VERIFY=0 must skip read-side digests"
    monkeypatch.setenv("MRTPU_VERIFY", "1")
    MapReduce().load(ck)
    assert calls


# ---------------------------------------------------------------------------
# spill-run integrity
# ---------------------------------------------------------------------------

def _one_run(tmp_path):
    from gpu_mapreduce_tpu.core.external import _write_run
    from gpu_mapreduce_tpu.core.frame import KVFrame
    from gpu_mapreduce_tpu.core.column import DenseColumn
    from gpu_mapreduce_tpu.core.runtime import Counters, Settings
    s = Settings(fpath=str(tmp_path / "sp"))
    fr = KVFrame(DenseColumn(np.arange(256, dtype=np.uint64)),
                 DenseColumn(np.arange(256, dtype=np.int64)))
    return _write_run(fr, s, Counters(), 0)


def test_corrupted_spill_run_detected(tmp_path):
    run = _one_run(tmp_path)
    assert run.kdigest and run.kdigest.startswith("crc32:")
    blob = bytearray(open(run.kpath, "rb").read())
    blob[200] ^= 1
    open(run.kpath, "wb").write(bytes(blob))
    before = _integrity_count("spill")
    with pytest.raises(OSError, match="checksum mismatch"):
        run.refill(64, "key")
    assert _integrity_count("spill") > before
    assert run.buf is None, "corrupt rows must never reach the merge"


def test_transient_spill_corruption_recovers_via_retry(tmp_path,
                                                       monkeypatch):
    """The acceptance wording: a bad spill run 'recovers via retry' —
    a transient flip (repaired before the re-read, staged here in the
    backoff hook) is absorbed by the spill.read budget."""
    run = _one_run(tmp_path)
    good = open(run.kpath, "rb").read()
    blob = bytearray(good)
    blob[77] ^= 4
    open(run.kpath, "wb").write(bytes(blob))
    ft.set_budget("spill.read", 2)
    monkeypatch.setattr(ftr, "_sleep",
                        lambda s: open(run.kpath, "wb").write(good))
    run.refill(64, "key")
    assert run.buf is not None and len(run.buf) == 64
    assert ftr.retries_snapshot().get(("spill.read", "recovered")) == 1


def test_external_sort_verifies_runs_end_to_end(tmp_path):
    """The integrated path: an outofcore sort under MRTPU_VERIFY=1
    writes stamped runs and verifies each before merging — output
    unchanged vs the unverified path."""
    keys = np.random.default_rng(7).integers(
        0, 1 << 40, 200_000).astype(np.uint64)

    def build():
        mr = MapReduce(outofcore=1, memsize=1, maxpage=1,
                       fpath=str(tmp_path / "sp"))
        mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
        mr.sort_keys(1)
        return [int(k) for fr in mr.kv.frames() for k, _ in fr.pairs()]

    assert build() == sorted(int(k) for k in keys)


# ---------------------------------------------------------------------------
# journal record integrity
# ---------------------------------------------------------------------------

def test_journal_bitflip_quarantined_not_replayed(tmp_path):
    from gpu_mapreduce_tpu.ft.journal import Journal, read_journal
    jdir = str(tmp_path / "j")
    j = Journal(jdir, script_mode=True)
    j.begin(["mr a"], "t")
    j.cmd_done("one")
    j.cmd_done("two")
    j.close()
    path = os.path.join(jdir, "journal.jsonl")
    lines = open(path).read().splitlines()
    assert len(read_journal(jdir)) == 3
    bad = lines[1].replace('"cmd": "one"', '"cmd": "???"')
    assert bad != lines[1]
    open(path, "w").write("\n".join([lines[0], bad, lines[2]]) + "\n")
    before = _integrity_count("journal")
    recs = read_journal(jdir)
    assert [r["kind"] for r in recs] == ["begin", "cmd"]
    assert recs[-1]["cmd"] == "two"     # records PAST the flip survive
    assert _integrity_count("journal") > before


# ---------------------------------------------------------------------------
# topology-portable resume
# ---------------------------------------------------------------------------

def _corpus(tmp_path):
    d1 = tmp_path / "w1.txt"
    d1.write_bytes(b"apple banana apple cherry banana apple " * 30)
    d2 = tmp_path / "w2.txt"
    d2.write_bytes(b"dog cat dog bird cat dog emu " * 25)
    return str(d1), str(d2)


def _script(d1, d2, o1, o2):
    return (f"mr a\n"
            f"wordfreq 3 -i {d1} -o {o1} NULL\n"
            f"wordfreq 3 -i {d2} -o {o2} NULL\n")


def _files(prefix):
    """{suffix: content} of a per-shard output family."""
    return {os.path.basename(p)[len(os.path.basename(prefix)):]:
            open(p).read() for p in sorted(glob.glob(prefix + "*"))}


def _content(prefix):
    """Distribution-agnostic content: all lines, sorted."""
    return sorted(ln for p in glob.glob(prefix + "*")
                  for ln in open(p).read().splitlines())


def _killed_journaled_run(tmp_path, monkeypatch, width, script, jname):
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    jdir = str(tmp_path / jname)
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    with pytest.raises(InjectedFatal):
        OinkScript(comm=make_mesh(width), screen=False).run_string(script)
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    return jdir


@pytest.mark.parametrize("to_width", [1, 2, 8])
def test_resume_onto_other_mesh_width_golden(tmp_path, monkeypatch,
                                             to_width):
    """A 4-shard checkpoint resumes on 1-, 2- and 8-shard meshes: the
    post-resume tail's output files are BYTE-IDENTICAL to an
    uninterrupted run on the target mesh, and the pre-crash outputs'
    content matches it too (their per-shard split keeps the writer's
    width — the files were already durable)."""
    from gpu_mapreduce_tpu.oink import OinkScript
    d1, d2 = _corpus(tmp_path)
    c1, c2 = str(tmp_path / "c1"), str(tmp_path / "c2")
    OinkScript(comm=make_mesh(to_width), screen=False).run_string(
        _script(d1, d2, c1, c2))

    k1, k2 = str(tmp_path / "k1"), str(tmp_path / "k2")
    jdir = _killed_journaled_run(tmp_path, monkeypatch, 4,
                                 _script(d1, d2, k1, k2), "j")
    s = ft.resume(jdir, mesh=make_mesh(to_width))
    assert s._ft_resharded == (to_width != 4)
    assert _files(k2) == _files(c2), "resumed tail not byte-identical"
    assert _content(k1) == _content(c1)
    rec = [r for r in ft.read_journal(jdir)
           if r["kind"] == "resume"][-1]
    assert rec["ckpt_nprocs"] == 4 and rec["nprocs"] == to_width


def test_resume_falls_back_past_damaged_generation(tmp_path,
                                                   monkeypatch):
    """The newest checkpoint generation missing a frame file (or bit-
    flipped) falls back to the previous kept generation BEFORE replay
    commits to a skip count — output still identical."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    d1, d2 = _corpus(tmp_path)
    out = str(tmp_path / "out")
    script = (f"wordfreq 3 -i {d1} -o NULL freq\n"
              f"freq stats 0\n"
              f"wordfreq 3 -i {d2} -o {out} NULL\n")
    jdir = str(tmp_path / "jf")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    with pytest.raises(InjectedFatal):
        OinkScript(comm=make_mesh(4), screen=False).run_string(script)
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    gens = sorted(glob.glob(os.path.join(jdir, "ckpt-*")))
    assert len(gens) >= 2, "keep-2 GC should have left two generations"
    victim = glob.glob(os.path.join(gens[-1], "*", "frame-*.npz"))
    assert victim, "newest generation holds no frames to damage"
    os.remove(victim[0])

    s = ft.resume(jdir, mesh=make_mesh(2))
    rec = [r for r in ft.read_journal(jdir)
           if r["kind"] == "resume"][-1]
    assert rec["generations_skipped"] >= 1
    assert "freq" in s.obj.named

    c = str(tmp_path / "cln")
    OinkScript(comm=make_mesh(2), screen=False).run_string(
        f"wordfreq 3 -i {d1} -o NULL freq\n"
        f"freq stats 0\n"
        f"wordfreq 3 -i {d2} -o {c} NULL\n")
    assert _files(out) == _files(c)


def test_latest_checkpoint_skips_damaged_generation(tmp_path,
                                                    monkeypatch):
    from gpu_mapreduce_tpu.oink import OinkScript
    d1, d2 = _corpus(tmp_path)
    jdir = str(tmp_path / "jl")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    OinkScript(screen=False).run_string(
        f"wordfreq 3 -i {d1} -o NULL freq\n"
        f"freq stats 0\n")
    monkeypatch.delenv("MRTPU_JOURNAL")
    ft.reset()
    gens = sorted(glob.glob(os.path.join(jdir, "ckpt-*")))
    assert len(gens) >= 2
    assert ft.latest_checkpoint(jdir) is not None
    assert os.path.basename(gens[-1]) in ft.latest_checkpoint(jdir)
    for f in glob.glob(os.path.join(gens[-1], "*", "frame-*.npz")):
        os.remove(f)
    assert os.path.basename(gens[-2]) in ft.latest_checkpoint(jdir)


def test_latest_checkpoint_validates_auto_slot(tmp_path, monkeypatch):
    """The programmatic ``auto`` slot gets the same pre-restore probe
    as script generations: a damaged auto checkpoint is never handed
    to the caller (code-review finding)."""
    monkeypatch.setenv("MRTPU_JOURNAL", str(tmp_path / "ja"))
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "2")
    mr = MapReduce()
    keys = np.arange(100, dtype=np.uint64) % 7
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.collate()
    mr.reduce(lambda k, vs, kv, p: kv.add(k, len(vs)))
    mr.sort_keys(1)
    jdir = str(tmp_path / "ja")
    ck = ft.latest_checkpoint(jdir)
    assert ck is not None and ck.endswith("auto")
    for f in glob.glob(os.path.join(ck, "frame-*.npz")):
        os.remove(f)
    assert ft.latest_checkpoint(jdir) is None


def test_shard_digest_mismatch_localizes_writer_shard(tmp_path):
    """When the frame FILE digest is consistent but a shard's row data
    contradicts its per-shard stamp (targeted rewrite / tampered
    manifest), load still refuses — and names the writer shard."""
    mr = _agg_mr(4)
    ck = str(tmp_path / "ck")
    mr.save(ck)
    mpath = os.path.join(ck, "manifest.json")
    man = json.load(open(mpath))
    fm = man["frames"][0]
    fpath = os.path.join(ck, fm["file"])
    with np.load(fpath) as z:
        arrs = {k: z[k].copy() for k in z.files}
    # flip one VALUE inside writer shard 2's row range, re-save the
    # frame cleanly, and "fix up" the file-level stamp — only the
    # per-shard digests can catch this now
    row = fm["shards"][0] + fm["shards"][1] + 1
    arrs["k_arr"] = arrs["k_arr"].copy()
    arrs["k_arr"][row] ^= np.uint64(1)
    np.savez(fpath, **arrs)
    from gpu_mapreduce_tpu.utils.integrity import file_digest
    fm["digest"] = file_digest(fpath)
    json.dump(man, open(mpath, "w"))
    with pytest.raises(OSError, match="writer shard 2"):
        MapReduce().load(ck)
