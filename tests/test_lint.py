"""mrlint: driver, the five checkers, pragmas, and the self-check.

Each checker gets at least one TRUE-POSITIVE fixture (a seeded
violation of its review class must be found) and one CLEAN fixture (the
correct idiom must not be flagged) — the checkers guard CI, so both
directions are load-bearing: a silent false negative re-opens the
review class, a false positive teaches people to pragma reflexively.

The self-check at the bottom runs the full analyzer over the shipped
package and asserts zero unsuppressed findings (the ISSUE 11 acceptance
criterion) AND a coverage floor — an entry-detection regression that
silently resolved nothing would also report zero findings, so "clean"
alone proves too little.
"""

import json
import os
import subprocess
import sys
import textwrap

from gpu_mapreduce_tpu import lint
from gpu_mapreduce_tpu.lint.callgraph import CallGraph
from gpu_mapreduce_tpu.lint import purity as _purity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_fixture(root, files, rules, docs=None, extra=()):
    """Write a throwaway package under root/pkg (+ optional doc/ files),
    analyze it, return (all findings, unsuppressed findings)."""
    for rel, src in files.items():
        path = os.path.join(root, "pkg", rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(src))
    for rel, src in (docs or {}).items():
        path = os.path.join(root, "doc", rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(src))
    project = lint.Project(root, package="pkg")
    findings = lint.run(project, rules=rules)
    return findings, [f for f in findings if not f.suppressed]


# ---------------------------------------------------------------------------
# trace-purity
# ---------------------------------------------------------------------------

PURITY_BAD = """
    import jax
    import time

    def outer(mesh, spec):
        def body(k, v):
            print("traced")          # host effect in traced code
            t = time.time()          # ambient value baked in
            return k + v + t
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec),
                             out_specs=spec)
"""

PURITY_CLEAN = """
    import jax
    import jax.numpy as jnp

    def outer(mesh, spec):
        def body(k, v):
            s = jnp.cumsum(v)
            return k, s
        return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec),
                             out_specs=(spec, spec))
"""


def test_purity_true_positive(tmp_path):
    _, live = run_fixture(str(tmp_path), {"mod.py": PURITY_BAD},
                          ["trace-purity"])
    rules = {f.rule for f in live}
    assert "purity-host-call" in rules
    msgs = " ".join(f.msg for f in live)
    assert "print()" in msgs and "time.time()" in msgs


def test_purity_clean(tmp_path):
    _, live = run_fixture(str(tmp_path), {"mod.py": PURITY_CLEAN},
                          ["trace-purity"])
    assert live == []


def test_purity_walks_partial_wrapped_pallas_kernel(tmp_path):
    """The ops/pallas call-site idiom — the kernel body handed to
    ``pallas_call`` wrapped as ``functools.partial(kernel, static...)``
    — is seeded as a traced entry: a host effect inside the kernel
    body must be found (the fixture mirrors ops/pallas/group.py's
    paged table kernel shape)."""
    src = """
        import functools
        import os
        from jax.experimental import pallas as pl

        def _table_kernel(T, page, base, k_ref, out_ref):
            limit = int(os.environ.get("MRTPU_DEBUG_T", T))  # host read
            out_ref[:] = k_ref[:] + limit

        def run_pages(keys, T, page):
            return pl.pallas_call(
                functools.partial(_table_kernel, T, page, 0),
                out_shape=None,
            )(keys)
    """
    _, live = run_fixture(str(tmp_path), {"mod.py": src},
                          ["trace-purity"])
    assert any(f.rule == "purity-host-call"
               and "_table_kernel" in f.symbol + f.msg
               for f in live), live


def test_knob_registry_sees_fusion_v2_knobs():
    """The fusion-v2 knobs route through utils/env.py and carry
    doc/settings.md rows — the pair the knob-registry rule reconciles
    (any drift re-opens a knob-undocumented/knob-stale finding in the
    self-check below)."""
    with open(os.path.join(REPO, "doc", "settings.md")) as f:
        doc = f.read()
    assert "MRTPU_MEGAFUSE" in doc and "MRTPU_PALLAS_GROUP" in doc
    from gpu_mapreduce_tpu.ops.pallas.group import pallas_group_enabled
    from gpu_mapreduce_tpu.plan.fuser import megafuse_enabled
    assert isinstance(megafuse_enabled(), bool)
    assert isinstance(pallas_group_enabled(), bool)


def test_purity_clean_partial_pallas_kernel(tmp_path):
    """The same shape with a pure kernel body stays clean."""
    src = """
        import functools
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _table_kernel(T, k_ref, out_ref):
            out_ref[:] = jnp.cumsum(k_ref[:])[:T]

        def run(keys, T):
            return pl.pallas_call(functools.partial(_table_kernel, T),
                                  out_shape=None)(keys)
    """
    _, live = run_fixture(str(tmp_path), {"mod.py": src},
                          ["trace-purity"])
    assert live == []


def test_purity_taint_coercion_and_transitive(tmp_path):
    # float(param) in a helper REACHED from a jit body, param tainted
    # through the call chain; plus a lock acquisition in traced code
    src = """
        import jax
        import threading

        _LOCK = threading.Lock()

        def helper(x):
            return float(x)              # coerces a traced operand

        @jax.jit
        def entry(a, b):
            with _LOCK:                  # trace-time-only lock
                c = helper(a)
            return c + b
    """
    _, live = run_fixture(str(tmp_path), {"mod.py": src},
                          ["trace-purity"])
    rules = {f.rule for f in live}
    assert "purity-coerce" in rules      # float(x) on tainted param
    assert "purity-lock" in rules


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_BAD_MUTATION = """
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self.rejects = 0

        def admit(self):
            with self._lock:
                self.rejects += 1

        def fast_path(self):
            self.rejects += 1            # the PR 6 bug class
"""

LOCK_CLEAN = """
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self.rejects = 0

        def admit(self):
            with self._lock:
                self.rejects += 1

        def other(self):
            with self._lock:
                self.rejects += 2
"""

LOCK_CYCLE = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A:
            with B:
                pass

    def g():
        with B:
            helper()

    def helper():
        with A:
            pass
"""


def test_lock_unguarded_mutation(tmp_path):
    _, live = run_fixture(str(tmp_path), {"mod.py": LOCK_BAD_MUTATION},
                          ["lock-discipline"])
    assert len(live) == 1
    assert live[0].rule == "lock-unguarded-mutation"
    assert "rejects" in live[0].msg
    assert live[0].symbol == "Server.fast_path"


def test_lock_clean(tmp_path):
    _, live = run_fixture(str(tmp_path), {"mod.py": LOCK_CLEAN},
                          ["lock-discipline"])
    assert live == []


def test_lock_order_cycle_through_call(tmp_path):
    # f nests A->B syntactically; g holds B and CALLS helper which
    # takes A — the cycle only exists through the callgraph
    _, live = run_fixture(str(tmp_path), {"mod.py": LOCK_CYCLE},
                          ["lock-discipline"])
    assert any(f.rule == "lock-order-cycle" for f in live)
    msg = next(f.msg for f in live if f.rule == "lock-order-cycle")
    assert "A" in msg and "B" in msg


# ---------------------------------------------------------------------------
# cache-key
# ---------------------------------------------------------------------------

CACHEKEY_BAD = """
    import os
    from .cache import CACHE

    def knob():
        return os.environ.get("MRTPU_MODE", "1")

    def builder(mesh):
        mode = knob()                    # read inside the builder...
        return (mesh, mode)

    def cached(mesh):
        return CACHE.get_or_build(
            (mesh,),                     # ...but absent from the key
            lambda: builder(mesh))
"""

CACHEKEY_CLEAN = """
    import os
    from .cache import CACHE

    def knob():
        return os.environ.get("MRTPU_MODE", "1")

    def builder(mesh):
        mode = knob()
        return (mesh, mode)

    def cached(mesh):
        return CACHE.get_or_build(
            (mesh, knob()),              # knob derivable from the key
            lambda: builder(mesh))
"""

CACHE_STUB = """
    class LRU:
        def get_or_build(self, key, build):
            return build()
    CACHE = LRU()
"""

CACHEKEY_LRU = """
    import functools
    import os

    @functools.lru_cache(maxsize=8)
    def builder(mesh):
        mode = os.environ.get("MRTPU_MODE", "1")   # args ARE the key
        return (mesh, mode)
"""


def test_cachekey_true_positive(tmp_path):
    _, live = run_fixture(
        str(tmp_path), {"mod.py": CACHEKEY_BAD, "cache.py": CACHE_STUB},
        ["cache-key"])
    assert len(live) == 1
    f = live[0]
    assert f.rule == "cache-key-missing-knob"
    assert "MRTPU_MODE" in f.msg


def test_cachekey_clean_when_key_derives_knob(tmp_path):
    _, live = run_fixture(
        str(tmp_path),
        {"mod.py": CACHEKEY_CLEAN, "cache.py": CACHE_STUB},
        ["cache-key"])
    assert live == []


def test_cachekey_lru_cache_builder(tmp_path):
    _, live = run_fixture(str(tmp_path), {"mod.py": CACHEKEY_LRU},
                          ["cache-key"])
    assert len(live) == 1
    assert "lru_cache" in live[0].msg


CACHEKEY_CAS_BAD = """
    import hashlib
    import os

    def memo_key(payload):
        mode = os.environ.get("MRTPU_MODE", "1")   # changes the result...
        if mode == "0":
            payload = payload.upper()
        return hashlib.sha256(payload.encode()).hexdigest()
"""

CACHEKEY_CAS_CLEAN = """
    import hashlib
    import os

    def memo_key(payload):
        mode = os.environ.get("MRTPU_MODE", "1")
        return hashlib.sha256(
            (payload + mode).encode()).hexdigest()  # knob IS keyed
"""


def test_cachekey_cas_builder_env_read_flagged(tmp_path):
    # idiom 3: a content-address key builder (*_key/*_digest around a
    # hashing call) whose reachable env knob never feeds the digest —
    # two stores could silently share one key across knob states
    _, live = run_fixture(str(tmp_path), {"mod.py": CACHEKEY_CAS_BAD},
                          ["cache-key"])
    assert len(live) == 1
    assert live[0].rule == "cache-key-missing-knob"
    assert "MRTPU_MODE" in live[0].msg


def test_cachekey_cas_builder_clean_when_knob_keyed(tmp_path):
    _, live = run_fixture(str(tmp_path), {"mod.py": CACHEKEY_CAS_CLEAN},
                          ["cache-key"])
    assert live == []


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

KNOBS_BAD = """
    import os
    from .utils.env import env_knob

    def a():
        return os.environ.get("MRTPU_RAW_READ", "1")   # bypass

    def b():
        return env_knob("MRTPU_UNDOCUMENTED", int, 0)  # no doc row
"""

ENV_STUB = """
    import os
    def env_knob(name, cast, default):
        return default
"""

SETTINGS_DOC = """
    | `MRTPU_RAW_READ` | 1 | documented but read raw |
    | `MRTPU_GHOST` | - | documented, read nowhere |
"""


def test_knob_registry(tmp_path):
    _, live = run_fixture(
        str(tmp_path),
        {"mod.py": KNOBS_BAD, "utils/env.py": ENV_STUB},
        ["knob-registry"], docs={"settings.md": SETTINGS_DOC})
    by_rule = {}
    for f in live:
        by_rule.setdefault(f.rule, []).append(f)
    assert any("MRTPU_RAW_READ" in f.msg
               for f in by_rule.get("knob-bypass", []))
    assert any("MRTPU_UNDOCUMENTED" in f.msg
               for f in by_rule.get("knob-undocumented", []))
    stale = by_rule.get("knob-stale", [])
    assert any("MRTPU_GHOST" in f.msg for f in stale)
    assert all(f.path == "doc/settings.md" for f in stale)


def test_knob_registry_clean(tmp_path):
    clean = """
        from .utils.env import env_knob
        def a():
            return env_knob("MRTPU_RAW_READ", int, 1)
    """
    doc = "| `MRTPU_RAW_READ` | 1 | all good |\n"
    _, live = run_fixture(
        str(tmp_path), {"mod.py": clean, "utils/env.py": ENV_STUB},
        ["knob-registry"], docs={"settings.md": doc})
    assert live == []


# ---------------------------------------------------------------------------
# metric-catalog (the migrated check_metrics_doc)
# ---------------------------------------------------------------------------

def test_metric_catalog_fixture(tmp_path):
    files = {"mod.py": 'NAME = "mrtpu_seeded_total"\n'}
    doc = "catalog: `mrtpu_ghost_total` only\n"
    _, live = run_fixture(str(tmp_path), files, ["metric-catalog"],
                          docs={"observability.md": doc})
    rules = sorted(f.rule for f in live)
    assert rules == ["metric-stale", "metric-undocumented"]


def test_metric_catalog_repo_agrees():
    project = lint.Project(REPO)
    live = [f for f in lint.run(project, rules=["metric-catalog"])
            if not f.suppressed]
    assert live == [], [str(f) for f in live]


# ---------------------------------------------------------------------------
# pragmas + baseline
# ---------------------------------------------------------------------------

def test_pragma_suppression_line_and_scope(tmp_path):
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked(self):
                with self._lock:
                    self.n += 1

            def inline(self):
                self.n += 1  # mrlint: disable=lock-unguarded-mutation

            def next_line(self):
                # mrlint: disable=lock-unguarded-mutation — justified
                self.n += 1

            # mrlint: disable=lock-unguarded-mutation — whole scope
            def scoped(self):
                self.n += 1
                self.n += 2

            def still_flagged(self):
                self.n += 1
    """
    findings, live = run_fixture(str(tmp_path), {"mod.py": src},
                                 ["lock-discipline"])
    assert len(live) == 1
    assert live[0].symbol == "S.still_flagged"
    # suppressed findings are still counted, not silently dropped
    assert sum(1 for f in findings if f.suppressed) == 4


def test_pragma_wrong_rule_does_not_suppress(tmp_path):
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked(self):
                with self._lock:
                    self.n += 1

            def bare(self):
                self.n += 1  # mrlint: disable=trace-purity
    """
    _, live = run_fixture(str(tmp_path), {"mod.py": src},
                          ["lock-discipline"])
    assert len(live) == 1


def test_module_pragma_after_docstring(tmp_path):
    # the natural header position — right under the module docstring —
    # must cover the whole file
    src = '''
        """Module docstring."""
        # mrlint: disable=knob-bypass
        import os

        def a():
            return os.environ.get("MRTPU_HEADER_TEST", "1")
    '''
    findings, live = run_fixture(str(tmp_path), {"mod.py": src},
                                 ["knob-registry"],
                                 docs={"settings.md":
                                       "| `MRTPU_HEADER_TEST` | 1 | x |"})
    assert [f.rule for f in live] == []
    assert any(f.suppressed and f.rule == "knob-bypass" for f in findings)


def test_changed_scope_keeps_reconciliation_findings(tmp_path):
    # a doc-only edit can orphan a metric/knob registered in an
    # UNCHANGED code file; the quick gate's changed-file report scope
    # must still surface those whole-tree invariants
    files = {"mod.py": 'NAME = "mrtpu_orphan_total"\n'}
    for rel, src in files.items():
        path = os.path.join(str(tmp_path), "pkg", rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(src)
    os.makedirs(os.path.join(str(tmp_path), "doc"), exist_ok=True)
    with open(os.path.join(str(tmp_path), "doc", "observability.md"),
              "w") as f:
        f.write("no catalog entry here\n")
    project = lint.Project(str(tmp_path), package="pkg")
    # report scope excludes mod.py entirely — the finding must survive
    scoped = lint.run(project, rules=["metric-catalog"],
                      only_paths={"doc/observability.md"})
    assert any(f.rule == "metric-undocumented" and not f.suppressed
               for f in scoped)
    assert all(f.symbol == "mrtpu_orphan_total" for f in scoped)


def test_baseline_suppression(tmp_path):
    _, live = run_fixture(str(tmp_path), {"mod.py": LOCK_BAD_MUTATION},
                          ["lock-discipline"])
    baseline = {f.fingerprint for f in live}
    project = lint.Project(str(tmp_path), package="pkg")
    again = lint.run(project, rules=["lock-discipline"],
                     baseline=baseline)
    assert all(f.suppressed for f in again)


# ---------------------------------------------------------------------------
# self-check: the shipped package is clean AND coverage is real
# ---------------------------------------------------------------------------

def test_selfcheck_repo_runs_clean():
    """ISSUE 11 acceptance: zero unsuppressed findings on the tree."""
    project = lint.Project(
        REPO, extra_files=("soak.py", "bench.py", "weakscale.py"))
    findings = lint.run(project)
    live = [f for f in findings if not f.suppressed]
    assert live == [], "\n" + "\n".join(str(f) for f in live)
    # the pragma pile must stay visible and bounded: every suppression
    # is a reviewed, justified exception (doc/lint.md policy)
    assert sum(1 for f in findings if f.suppressed) < 40


def test_selfcheck_coverage_floor():
    """Zero findings must not mean zero analysis: the purity checker
    has to see a substantial traced set or entry detection regressed."""
    project = lint.Project(REPO)
    graph = CallGraph(project)
    entries = _purity._entries(graph)
    traced = graph.reachable(entries)
    assert len(graph.funcs) > 800
    assert len(entries) > 25, "jit/shard_map entry detection regressed"
    assert len(traced) > 80
    mods = {t.module.relpath for t in traced}
    for must in ("gpu_mapreduce_tpu/parallel/shuffle.py",
                 "gpu_mapreduce_tpu/parallel/wire.py",
                 "gpu_mapreduce_tpu/plan/fuser.py"):
        assert must in mods, f"{must} fell out of the traced set"


def test_cli_json_and_exit_code():
    """The CLI contract ci.sh relies on: exit 0 + parseable --json on a
    clean tree, without importing jax (SIGALRM-free, fast)."""
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mrlint.py"),
         "--json", "-"],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    payload = json.loads(res.stdout)
    assert payload["total"] == 0
    assert payload["files_scanned"] > 100
    assert "jax" not in res.stderr.lower()


def test_cli_unknown_rule_exits_2():
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "mrlint.py"),
         "-r", "no-such-rule"],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 2
    assert "unknown rule" in res.stderr


# ---------------------------------------------------------------------------
# net-timeout
# ---------------------------------------------------------------------------

NET_BAD = """
    import socket
    import urllib.request

    def probe(port):
        socket.create_connection(("127.0.0.1", port)).close()

    def fetch(url):
        return urllib.request.urlopen(url).read()

    def legacy(host):
        import http.client
        return http.client.HTTPConnection(host, 80)
"""

NET_CLEAN = """
    import socket
    import urllib.request

    def probe(port):
        socket.create_connection(("127.0.0.1", port),
                                 timeout=0.5).close()

    def fetch(url):
        return urllib.request.urlopen(url, timeout=30.0).read()

    def fetch_positional(url):
        # timeout in its positional slot counts too
        return urllib.request.urlopen(url, None, 30.0).read()

    def legacy(host):
        import http.client
        return http.client.HTTPConnection(host, 80, 10.0)

    def intentional(port):
        socket.create_connection(("127.0.0.1", port)).close()  # mrlint: disable=net-timeout
"""


def test_net_timeout_true_positive(tmp_path):
    _, live = run_fixture(str(tmp_path),
                          {"serve/mod.py": NET_BAD},
                          rules=["net-timeout"])
    assert len(live) == 3
    assert all(f.rule == "net-timeout" for f in live)


def test_net_timeout_clean(tmp_path):
    _, live = run_fixture(str(tmp_path),
                          {"serve/mod.py": NET_CLEAN},
                          rules=["net-timeout"])
    assert live == []


def test_net_timeout_out_of_scope_module_ignored(tmp_path):
    # the rule scopes to serve/ + obs/httpd.py + opted-in extras: a
    # data-plane module with a raw socket is not this rule's business
    _, live = run_fixture(str(tmp_path),
                          {"parallel/mod.py": NET_BAD},
                          rules=["net-timeout"])
    assert live == []


def test_net_timeout_tree_is_clean():
    project = lint.Project(REPO, package="gpu_mapreduce_tpu",
                           extra_files=("scripts/mrctl.py",
                                        "scripts/mrlaunch.py"))
    live = [f for f in lint.run(project, rules=["net-timeout"])
            if not f.suppressed]
    assert live == [], [str(f) for f in live]
