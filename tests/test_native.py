"""Native C++ runtime parity tests — every mrnative entry point against
its Python/numpy reference implementation (the reference's equivalent
host paths: src/hash.cpp, oink/map_read_*.cpp, cpu/InvertedIndex.cpp)."""

import random
import re

import numpy as np
import pytest

from gpu_mapreduce_tpu import native
from gpu_mapreduce_tpu.ops.hash import (hash_bytes64, hash_bytes64_batch,
                                        hashlittle)

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native lib unavailable: {native.build_error()}")


def test_hashlittle_parity_random():
    rnd = random.Random(7)
    for _ in range(300):
        data = bytes(rnd.randrange(256) for _ in range(rnd.randrange(50)))
        iv = rnd.randrange(2 ** 32)
        assert native.hashlittle(data, iv) == hashlittle(data, iv)


def test_hashlittle_batch_and_intern():
    words = [b"alpha", b"", b"x" * 13, b"mixed bytes\x00\xff", b"q"]
    buf = b"".join(words)
    offs = np.cumsum([0] + [len(w) for w in words]).astype(np.int64)
    assert native.hashlittle_batch(buf, offs, 9).tolist() == \
        [hashlittle(w, 9) for w in words]
    assert native.intern64_batch(buf, offs).tolist() == \
        [hash_bytes64(w) for w in words]


def test_hash_bytes64_batch_routes_native():
    words = [bytes([i]) * (i % 7) for i in range(64)]
    got = hash_bytes64_batch(words)
    assert got.tolist() == [hash_bytes64(w) for w in words]


def test_parse_table_rejects_overflow_and_partial_tokens():
    # > 2^64-1 must error (the numpy fallback raises OverflowError)
    with pytest.raises(ValueError):
        native.parse_table(b"99999999999999999999999 1\n",
                           (np.uint64, np.uint64))
    with pytest.raises(ValueError):
        native.parse_table(b"1 1.5abc\n", (np.uint64, np.float64))
    with pytest.raises(ValueError):
        native.parse_table(b"1 0x10\n", (np.uint64, np.float64))


def test_invertedindex_native_engine(tmp_path):
    from gpu_mapreduce_tpu.apps.invertedindex import InvertedIndex
    html = b'<a href="http://a/1">x</a><p><a href="http://b/2">y</a>'
    f = tmp_path / "part-00000"
    f.write_bytes(html)
    app = InvertedIndex(engine="native")
    nhits, nurls = app.run([str(f)], outdir=str(tmp_path / "out"))
    assert (nhits, nurls) == (2, 2)
    lines = sorted((tmp_path / "out").glob("*"))
    text = "".join(p.read_text() for p in lines)
    assert "http://a/1" in text and "http://b/2" in text


def test_parse_table_u64_exact_and_f64():
    tbl = b"1 2 3.5\n18446744073709551615 7 0.25\n 0 0 1e3 "
    u1, u2, f = native.parse_table(tbl, (np.uint64, np.uint64, np.float64))
    assert u1.tolist() == [1, 18446744073709551615, 0]   # 2^64-1 exact
    assert u2.tolist() == [2, 7, 0]
    assert f.tolist() == [3.5, 0.25, 1000.0]
    with pytest.raises(ValueError):
        native.parse_table(b"1 2\n3\n", (np.uint64, np.uint64))
    with pytest.raises(ValueError):
        native.parse_table(b"1 x\n", (np.uint64, np.uint64))


def test_parse_table_capacity_retry():
    n = 5000
    tbl = b"\n".join(b"%d %d" % (i, i * 2) for i in range(n))
    a, b = native.parse_table(tbl, (np.uint64, np.uint64))
    assert a.tolist() == list(range(n))
    assert b.tolist() == [2 * i for i in range(n)]


def test_find_hrefs_matches_regex():
    rnd = random.Random(11)
    parts = []
    urls = []
    for i in range(100):
        u = b"http://site%d/p%d" % (i, rnd.randrange(1000))
        urls.append(u)
        parts.append(b'<p>junk<a href="%s">t</a>' % u)
    html = b"<html>" + b"".join(parts) + b'<a href="noquote'
    s, l = native.find_hrefs(html)
    got = [html[a:a + b] for a, b in zip(s, l)]
    # lookahead regex: every match position, like the device mark kernel
    oracle = [m.group(1) for m in
              re.finditer(rb'(?=<a href="([^"]*)")', html)]
    assert got == oracle == urls


def test_find_hrefs_overlapping_matches():
    # a pattern occurrence *inside* a prior URL span must still match
    # (device mark kernel marks every position)
    html = b'<a href="aaa<a href="bar">x</a>'
    s, l = native.find_hrefs(html)
    got = [html[a:a + b] for a, b in zip(s, l)]
    oracle = [m.group(1) for m in
              re.finditer(rb'(?=<a href="([^"]*)")', html)]
    assert got == oracle == [b'aaa<a href=', b'bar']


def test_parse_table_inf_nan_plus_like_fallback():
    u, f = native.parse_table(b"+5 inf\n007 -nan\n1 -infinity\n",
                              (np.uint64, np.float64))
    assert u.tolist() == [5, 7, 1]
    assert f[0] == np.inf and np.isnan(f[1]) and f[2] == -np.inf
    # zero-padded beyond 20 chars still parses (fallback does too)
    u2, = native.parse_table(b"0000000000000000000000042\n", (np.uint64,))
    assert u2.tolist() == [42]


def test_kernels_parse_cols_native_path(tmp_path):
    from gpu_mapreduce_tpu.oink.kernels import _parse_cols
    p = tmp_path / "e.txt"
    p.write_text("5 6 1.5\n18446744073709551615 2 0.25\n")
    vi, vj, w = _parse_cols(str(p), (np.uint64, np.uint64, np.float64))
    assert vi.tolist() == [5, 18446744073709551615]
    assert vj.tolist() == [6, 2]
    assert w.tolist() == [1.5, 0.25]


def test_intern_ranges_matches_batch():
    """Zero-copy range interning must agree with the packed-buffer intern
    and the seeded alt family must differ from the default family."""
    rnd = random.Random(5)
    data = bytes(rnd.randrange(256) for _ in range(4096))
    buf = np.frombuffer(data, np.uint8)
    starts = np.array([0, 10, 100, 1000, 4000], np.int64)
    lens = np.array([5, 0, 33, 300, 96], np.int64)
    ids = native.intern_ranges(buf, starts, lens)
    pieces = [data[s:s + l] for s, l in zip(starts, lens)]
    offs = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    expect = native.intern64_batch(b"".join(pieces), offs)
    np.testing.assert_array_equal(ids, expect)
    alt = native.intern_ranges(buf, starts, lens, 0x9E3779B9, 0x85EBCA6B)
    assert not np.array_equal(ids, alt)


def test_find_hrefs_edge_positions():
    # pattern flush at start / end-of-buffer, quote at last byte,
    # unterminated tail, '<' density
    html = b'<a href="x"' + b"<<<<" + b'<a href="yy"'
    s, l = native.find_hrefs(html)
    got = [html[a:a + b] for a, b in zip(s, l)]
    assert got == [b"x", b"yy"]
    assert native.find_hrefs(b'<a href="')[0].size == 0   # no quote
    assert native.find_hrefs(b"")[0].size == 0
    assert native.find_hrefs(b"<" * 64)[0].size == 0


def test_intern_ranges2_matches_two_single_family_passes():
    rng = np.random.default_rng(3)
    buf = rng.integers(0, 256, 4096, dtype=np.uint8)
    starts = np.sort(rng.choice(3800, 40, replace=False)).astype(np.int64)
    lens = rng.integers(0, 200, 40, dtype=np.int64)  # incl. len 0 and >12
    ah, al = 0x9E3779B9, 0x85EBCA6B
    ids, alts = native.intern_ranges2(buf, starts, lens, ah, al)
    assert ids.tolist() == native.intern_ranges(buf, starts, lens).tolist()
    assert alts.tolist() == \
        native.intern_ranges(buf, starts, lens, ah, al).tolist()
