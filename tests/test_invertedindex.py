"""InvertedIndex pipeline vs a regex oracle; mark kernel (pallas interpret +
xla twin) equivalence."""

import re

import numpy as np
import pytest

import jax.numpy as jnp

from gpu_mapreduce_tpu.apps.invertedindex import InvertedIndex, PATTERN
from gpu_mapreduce_tpu.ops.pallas.match import (compact_matches, mark_pallas,
                                                mark_xla, url_lengths)

HTML = (b'<html><body><a href="http://a.com/x">x</a>'
        b'<p>no link</p><a href="http://b.org/long/path?q=1">y</a>'
        b'<A HREF="http://case.sensitive/">skip</A>'
        b'<a href="http://a.com/x">dup</a></body></html>')


def oracle_urls(data: bytes):
    return re.findall(rb'<a href="([^"]*)"', data)


def test_mark_xla_vs_pallas_interpret():
    rng = np.random.default_rng(0)
    noise = rng.integers(0, 256, size=100_000, dtype=np.uint8)
    data = noise.tobytes() + HTML * 7 + noise.tobytes()
    buf = jnp.asarray(np.frombuffer(data, np.uint8))
    m1 = np.asarray(mark_xla(buf, PATTERN))
    m2 = np.asarray(mark_pallas(buf, PATTERN, interpret=True))
    np.testing.assert_array_equal(m1.astype(np.int8), m2)
    # ground truth from python
    expect = np.zeros(len(data), np.int8)
    start = 0
    while True:
        i = data.find(PATTERN, start)
        if i < 0:
            break
        expect[i] = 1
        start = i + 1
    np.testing.assert_array_equal(m2, expect)


def test_mark_cross_lane_boundaries():
    # place the pattern at every offset mod 128+rows to cross lane/row edges
    for off in (0, 1, 119, 120, 126, 127, 128, 255, 256, 1000):
        data = b"x" * off + b'<a href="u">' + b"y" * 300
        buf = jnp.asarray(np.frombuffer(data, np.uint8))
        m = np.asarray(mark_pallas(buf, PATTERN, interpret=True))
        assert m.sum() == 1 and m[off] == 1, off


def test_compact_and_lengths():
    data = HTML
    buf = jnp.asarray(np.frombuffer(data, np.uint8))
    mask = mark_xla(buf, PATTERN)
    starts, n = compact_matches(mask.astype(jnp.int8), 16)
    assert int(n) == 3  # lowercase '<a href="' occurrences
    starts = starts + len(PATTERN)
    lengths, windows = url_lengths(buf, starts, ord('"'), 128)
    urls = [bytes(np.asarray(windows[i][: int(lengths[i])]))
            for i in range(int(n))]
    assert urls == oracle_urls(data)


def test_unterminated_href_dropped(tmp_path):
    f = tmp_path / "bad.html"
    f.write_bytes(b'<a href="http://ok/">fine</a><a href="no-close-quote')
    ii = InvertedIndex()
    nhits, nurl = ii.run([str(f)])
    assert nhits == 1 and nurl == 1
    assert list(ii.urls.values()) == [b"http://ok/"]


def test_empty_href_kept(tmp_path):
    # length 0 is a real empty URL, distinct from "no terminator"
    f = tmp_path / "e.html"
    f.write_bytes(b'<a href="">empty</a><a href="http://x/">x</a>')
    ii = InvertedIndex()
    nhits, nurl = ii.run([str(f)])
    assert (nhits, nurl) == (2, 2)
    assert sorted(ii.urls.values()) == [b"", b"http://x/"]


@pytest.fixture
def html_corpus(tmp_path):
    rng = np.random.default_rng(7)
    hosts = [b"http://site%d.org/p%d" % (i % 5, i) for i in range(40)]
    files = []
    for fi in range(6):
        parts = [b"<html>"]
        for _ in range(rng.integers(5, 30)):
            u = hosts[rng.integers(0, len(hosts))]
            parts.append(b'<a href="' + u + b'">link</a>' +
                         bytes(rng.integers(32, 127, size=50, dtype=np.uint8)))
        parts.append(b"</html>")
        p = tmp_path / f"part-{fi:05d}.html"
        p.write_bytes(b"".join(parts))
        files.append(str(p))
    return files


def test_pipeline_matches_regex_oracle(html_corpus, tmp_path):
    import collections

    index = collections.defaultdict(set)
    total = 0
    for f in html_corpus:
        data = open(f, "rb").read()
        for u in oracle_urls(data):
            index[u].add(f)
            total += 1
    ii = InvertedIndex()
    outdir = str(tmp_path / "out")
    nhits, nurl = ii.run(html_corpus, outdir=outdir)
    assert nhits == total
    assert nurl == len(index)
    # output file lines reconstruct the oracle index
    got = {}
    with open(f"{outdir}/part-00000") as fh:
        for line in fh:
            url, names = line.rstrip("\n").split("\t")
            got[url.encode()] = set(names.split(" "))
    assert got == dict(index)


def test_pipeline_on_mesh(html_corpus):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    ii1 = InvertedIndex()
    n1 = ii1.run(html_corpus)
    ii2 = InvertedIndex(comm=make_mesh())
    n2 = ii2.run(html_corpus)
    assert n1 == n2


def test_mesh_chunked_h2d_and_paged_mark(html_corpus, monkeypatch):
    """r4 large-shape hardening: bounded H2D messages (MR_H2D_CHUNK_WORDS)
    and fixed-page mark dispatches (MR_MARK_PAGE_WORDS) must be invisible
    in the results — forced tiny here so even a KB-scale corpus crosses
    both seams.  The knobs key the builder caches (_env_knobs), so no
    cache management is needed around the env toggles."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    ii1 = InvertedIndex()
    n1 = ii1.run(html_corpus)
    monkeypatch.setenv("MR_H2D_CHUNK_WORDS", "32")
    monkeypatch.setenv("MR_MARK_PAGE_WORDS", "256")
    ii2 = InvertedIndex(engine="pallas", comm=make_mesh())
    n2 = ii2.run(html_corpus)
    assert n1 == n2
    assert ii1.urls == ii2.urls


def test_long_url_second_tier(tmp_path):
    """URLs longer than the 64-byte first-tier window take the 256-byte
    re-gather path; ones beyond MAX_URL still drop."""
    long_url = b"http://example.org/" + b"x" * 150          # tier 2
    giant = b"http://example.org/" + b"y" * 400             # > MAX_URL: drop
    short = b"http://e/"
    f = tmp_path / "long.html"
    f.write_bytes(b'<a href="%s">a</a><a href="%s">b</a><a href="%s">c</a>'
                  % (short, long_url, giant))
    ii = InvertedIndex()
    nhits, nurl = ii.run([str(f)])
    assert (nhits, nurl) == (2, 2)
    assert sorted(ii.urls.values()) == sorted([short, long_url])


def test_long_url_dense_corpus_wide_fallback(tmp_path):
    """More long URLs than the long-tail capacity → the wide (full-window)
    fallback must engage and still match the oracle."""
    urls = [b"http://example.org/" + bytes([97 + i % 26]) * 120
            for i in range(40)]
    f = tmp_path / "dense.html"
    f.write_bytes(b"".join(b'<a href="%s">x</a>' % u for u in urls))
    ii = InvertedIndex()
    nhits, nurl = ii.run([str(f)])
    assert nhits == len(urls)
    assert nurl == len(set(urls))
    assert sorted(set(ii.urls.values())) == sorted(set(urls))


@pytest.mark.slow
def test_multi_batch_corpus(html_corpus, monkeypatch):
    """Force the per-corpus byte cap below one file so every file becomes
    its own batch — counts and url dict must match the single-batch run."""
    ii1 = InvertedIndex()
    n1 = ii1.run(html_corpus)
    monkeypatch.setattr(InvertedIndex, "_BATCH_BYTES", 4096)
    ii2 = InvertedIndex()
    n2 = ii2.run(html_corpus)
    assert n1 == n2
    assert ii1.urls == ii2.urls
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    ii3 = InvertedIndex(comm=make_mesh(1))
    n3 = ii3.run(html_corpus)
    assert n3 == n1


def test_single_file_over_cap_raises(tmp_path, monkeypatch):
    p = tmp_path / "big.html"
    p.write_bytes(b"x" * 8192)
    monkeypatch.setattr(InvertedIndex, "_BATCH_BYTES", 4096)
    with pytest.raises(ValueError, match="exceeds the device corpus cap"):
        InvertedIndex().run([str(p)])


def test_pipeline_on_single_device_mesh(html_corpus):
    """The bench's actual tier: P=1 mesh → zero-copy ShardedKV from the
    fused extract, aggregate early-out, device convert, batch count
    reduce (emit_batch) — must agree with the serial path."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ShardedKV

    ii1 = InvertedIndex()
    n1 = ii1.run(html_corpus)
    ii2 = InvertedIndex(comm=make_mesh(1))
    n2 = ii2.run(html_corpus)
    assert n1 == n2
    # the reduced KV must still be device-resident (count per url id)
    fr = ii2.mr.kv.one_frame()
    assert isinstance(fr, ShardedKV)
    import numpy as np
    counts = {int(k): int(v) for k, v in fr.to_host().pairs()}
    ref = {int(k): int(v) for k, v in ii1.mr.kv.one_frame().pairs()}
    assert counts == ref


def test_mesh_ingestion_no_controller_funnel(html_corpus):
    """VERDICT r2 #2: per-device ingestion — every shard extracts its own
    file slice on its own device and the whole map/aggregate/convert/
    reduce pipeline runs with ZERO device→host frame materialisations."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ShardedKV, ToHostStats

    ii1 = InvertedIndex()
    n1 = ii1.run(html_corpus)
    ii2 = InvertedIndex(comm=make_mesh())
    snap = ToHostStats.snapshot()
    n2 = ii2.run(html_corpus)
    assert ToHostStats.delta(snap) == (0, 0)
    assert n2 == n1
    fr = ii2.mr.kv.one_frame()
    assert isinstance(fr, ShardedKV)
    counts = {int(k): int(v) for k, v in fr.to_host().pairs()}
    ref = {int(k): int(v) for k, v in ii1.mr.kv.one_frame().pairs()}
    assert counts == ref
    # the url dict built from per-shard host slices matches the serial one
    assert ii2.urls == ii1.urls


def test_mesh_multi_round_batches(html_corpus, monkeypatch):
    """Per-shard corpora above the int32 cap process in rounds (one
    ShardedKV frame per round) and still match the serial oracle."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    ii1 = InvertedIndex()
    n1 = ii1.run(html_corpus)
    monkeypatch.setattr(InvertedIndex, "_BATCH_BYTES", 4096)
    ii2 = InvertedIndex(comm=make_mesh())
    n2 = ii2.run(html_corpus)
    assert n2 == n1
    assert ii1.urls == ii2.urls


def test_map_stats_multi_batch_and_wide(html_corpus, tmp_path, monkeypatch):
    """bench.py's detail record surfaces the batching + two-tier window
    machinery (VERDICT r2 #9): forced multi-batch shows nbatches > 1;
    a long-URL-dense corpus shows a wide fallback."""
    monkeypatch.setattr(InvertedIndex, "_BATCH_BYTES", 4096)
    ii = InvertedIndex()
    ii.run(html_corpus)
    assert ii.stats["nbatches"] > 1, ii.stats
    monkeypatch.undo()

    urls = [b"http://example.org/" + bytes([97 + i % 26]) * 120
            for i in range(40)]
    f = tmp_path / "dense.html"
    f.write_bytes(b"".join(b'<a href="%s">x</a>' % u for u in urls))
    ii2 = InvertedIndex()
    ii2.run([str(f)])
    assert ii2.stats["wide_fallbacks"] >= 1, ii2.stats
    assert ii2.stats["nlong_max"] > 0


def test_fold_id_check_detects_collisions_within_and_across_batches():
    """u64 intern collision safety on the no-url-dict path: one id
    carrying two alt-family values must raise at compaction — whether
    the pairs sit in one batch or span batches (the r4 append-only
    hot loop + doubling-trigger compaction rework of _fold_id_check;
    run() always compacts at map close)."""
    import numpy as np
    import pytest
    from gpu_mapreduce_tpu.apps.invertedindex import InvertedIndex

    idx = InvertedIndex(engine="native")
    ids = np.array([5, 7, 5], np.uint64)
    alts = np.array([1, 2, 9], np.uint64)
    idx._fold_id_check(ids, alts)   # append only; checked at compaction
    with pytest.raises(ValueError, match="collision"):
        idx._compact_chk_runs()

    idx = InvertedIndex(engine="native")
    idx._fold_id_check(np.array([5, 7], np.uint64),
                       np.array([1, 2], np.uint64))
    idx._fold_id_check(np.array([8, 5], np.uint64),
                       np.array([3, 9], np.uint64))  # 5 -> 9 vs 1: deferred
    with pytest.raises(ValueError, match="collision"):
        idx._compact_chk_runs()

    # benign duplicates (same id, same alt) across batches survive
    idx = InvertedIndex(engine="native")
    idx._fold_id_check(np.array([5, 7], np.uint64),
                       np.array([1, 2], np.uint64))
    idx._fold_id_check(np.array([5, 8], np.uint64),
                       np.array([1, 3], np.uint64))
    idx._compact_chk_runs()
    ri, ra = idx._chk_sorted
    assert ri.tolist() == [5, 7, 8] and ra.tolist() == [1, 2, 3]


@pytest.mark.parametrize("engine", ["xla", "native"])
def test_mesh_outdir_writes_per_shard_parts(html_corpus, tmp_path, engine):
    """VERDICT r3 #7: an 8-device run writes 8 part-<shard> files from
    per-shard data (url bytes decoded from the destination shard's own
    dict on the device tier), and their union matches the serial
    oracle's single output file."""
    import collections
    import os

    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    oracle = collections.defaultdict(set)
    for f in html_corpus:
        for u in oracle_urls(open(f, "rb").read()):
            oracle[u].add(f)

    ii = InvertedIndex(engine=engine, comm=make_mesh(8))
    outdir = str(tmp_path / f"out_{engine}")
    nhits, nurl = ii.run(html_corpus, outdir=outdir)
    parts = sorted(os.listdir(outdir))
    assert parts == [f"part-{p:05d}" for p in range(8)]
    assert nurl == len(oracle)
    got = {}
    for part in parts:
        with open(os.path.join(outdir, part)) as fh:
            for line in fh:
                url, names = line.rstrip("\n").split("\t")
                assert url.encode() not in got   # each key on ONE shard
                got[url.encode()] = set(names.split(" "))
    assert got == dict(oracle)
    if engine == "xla":
        # the device tier never built a controller-global dict
        assert ii.shard_urls is not None
        assert sum(len(d) for d in ii.shard_urls) == len(oracle)
        assert ii._urls == {}


def test_fold_id_check_thread_hammer():
    """4 threads interleave batches (shared hot ids + disjoint tails)
    while doubling-trigger compactions race the appends; the final
    compacted run must be exactly the global unique pair set."""
    import threading

    idx = InvertedIndex(engine="native")
    idx._CHK_MIN_COMPACT = 256          # force many mid-stream compactions
    rng = np.random.default_rng(3)
    hot = np.arange(100, dtype=np.uint64)
    batches = []
    for t in range(4):
        for b in range(30):
            tail = (np.arange(200, dtype=np.uint64)
                    + 1000 * (1 + t * 30 + b))
            ids = np.concatenate([hot, tail])
            rng.shuffle(ids)
            batches.append((t, ids))
    expect = set()
    for _, ids in batches:
        expect.update(ids.tolist())

    def work(t):
        for bt, ids in batches:
            if bt == t:
                idx._fold_id_check(ids, ids + np.uint64(7))  # alt = id+7

    threads = [threading.Thread(target=work, args=(t,)) for t in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    idx._compact_chk_runs()
    ri, ra = idx._chk_sorted
    assert idx._chk_tails == []
    assert set(ri.tolist()) == expect
    assert (ra == ri + np.uint64(7)).all()
    assert (np.diff(ri.astype(np.int64)) > 0).all()   # sorted, deduped

    # and a collision smuggled in by one thread still surfaces
    idx._fold_id_check(np.array([5], np.uint64), np.array([99], np.uint64))
    with pytest.raises(ValueError, match="collision"):
        idx._compact_chk_runs()
