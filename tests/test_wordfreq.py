"""End-to-end wordfreq slice vs a collections.Counter oracle
(the reference's own hello world, examples/wordfreq.cpp)."""

import collections

import pytest

from gpu_mapreduce_tpu.apps.wordfreq import wordfreq, wordfreq_interned

TEXT1 = b"the quick brown fox jumps over the lazy dog\nthe fox ran\n"
TEXT2 = b"pack my box with five dozen liquor jugs\nthe dog slept\n"


@pytest.fixture
def word_files(tmp_path):
    p1 = tmp_path / "a.txt"
    p2 = tmp_path / "b.txt"
    p1.write_bytes(TEXT1)
    p2.write_bytes(TEXT2)
    return [str(p1), str(p2)]


def oracle(files):
    c = collections.Counter()
    for f in files:
        with open(f, "rb") as fh:
            c.update(fh.read().split())
    return c


@pytest.mark.parametrize("impl", [wordfreq, wordfreq_interned])
def test_wordfreq_matches_counter(word_files, impl):
    c = oracle(word_files)
    nwords, nunique, top = impl(word_files, ntop=5)
    assert nwords == sum(c.values())
    assert nunique == len(c)
    assert top[0] == (b"the", 4)
    # counts of the returned top-5 must match the oracle
    for w, n in top:
        assert c[w] == n
    # and must be the true top-5 multiset of counts
    want = sorted(c.values(), reverse=True)[:5]
    assert sorted((n for _, n in top), reverse=True) == want


@pytest.mark.parametrize("ndev", [1, 4, 8])
def test_wordfreq_mesh_auto_intern(word_files, ndev):
    """VERDICT r1 #5: the host (byte-key) wordfreq on a mesh must ACTUALLY
    distribute — keys auto-intern to u64 ids, the exchange runs on device,
    and the id→bytes table resurrects the words for the reduce/top-N."""
    from gpu_mapreduce_tpu.apps.wordfreq import _fileread, _sum
    from gpu_mapreduce_tpu.core.mapreduce import MapReduce
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ShardedKV

    c = oracle(word_files)
    mr = MapReduce(make_mesh(ndev))
    nwords = mr.map_files(word_files, _fileread)
    mr.aggregate()
    fr = mr.kv.one_frame()
    assert isinstance(fr, ShardedKV), "byte keys did not shard"
    assert fr.key_decode, "intern table missing"
    if ndev > 1:
        assert (fr.counts > 0).sum() > 1, \
            f"no actual distribution: {fr.counts}"
    mr.convert()
    nunique = mr.reduce(_sum)
    assert (nwords, nunique) == (sum(c.values()), len(c))
    got = {}
    mr.scan_kv(lambda k, v, p: got.__setitem__(k, int(v)))
    assert got == dict(c)  # byte keys resurrected exactly


def test_wordfreq_full_pipeline_on_mesh(word_files):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    c = oracle(word_files)
    nwords, nunique, top = wordfreq(word_files, ntop=5,
                                    comm=make_mesh(4))
    assert (nwords, nunique) == (sum(c.values()), len(c))
    assert top[0] == (b"the", 4)
    for w, n in top:
        assert c[w] == n


def test_wordfreq_directory_ingest(tmp_path):
    (tmp_path / "sub").mkdir()
    (tmp_path / "x.txt").write_bytes(TEXT1)
    (tmp_path / "sub" / "y.txt").write_bytes(TEXT2)
    # non-recursive directory expansion sees only the top-level file
    nwords, _, _ = wordfreq([str(tmp_path)])
    c = oracle([str(tmp_path / "x.txt")])
    assert nwords == sum(c.values())


def test_recursive_file_ingest(tmp_path):
    from gpu_mapreduce_tpu import MapReduce

    (tmp_path / "sub").mkdir()
    (tmp_path / "x.txt").write_bytes(TEXT1)
    (tmp_path / "sub" / "y.txt").write_bytes(TEXT2)
    seen = []
    mr = MapReduce()
    mr.map_files([str(tmp_path)],
                 lambda t, f, kv, p: (seen.append(f), kv.add(t, 0)),
                 recurse=1)
    both = oracle([str(tmp_path / "x.txt"), str(tmp_path / "sub" / "y.txt")])
    assert len(seen) == 2  # recursion found the nested file
    nwords, nunique, _ = wordfreq_dir_recursive(tmp_path)
    assert nwords == sum(both.values()) and nunique == len(both)


def wordfreq_dir_recursive(tmp_path):
    """wordfreq over a directory tree via the library API (recurse=1)."""
    import collections

    from gpu_mapreduce_tpu import MapReduce
    from gpu_mapreduce_tpu.apps.wordfreq import _fileread, _sum

    mr = MapReduce()
    nwords = mr.map_files([str(tmp_path)], _fileread, recurse=1)
    mr.collate()
    nunique = mr.reduce(_sum)
    return nwords, nunique, None
