"""Out-of-core external sort/convert: bounded peak memory (VERDICT r1 #4).

The reference's defining capability is running every op in a few fixed
pages regardless of data size (doc/Interface_c++.txt:39-59; the Spool
merge cascade).  These tests push a dataset ~10× the page budget through
sort_keys / sort_values / convert+reduce and assert BOTH correctness vs
in-core oracles AND that the `msizemax` hi-water stays ~2× the budget —
the ONEMAX-style property round 1 never asserted."""

import numpy as np
import pytest

from gpu_mapreduce_tpu import MapReduce
from gpu_mapreduce_tpu.core.runtime import global_counters

MEMSIZE_MB = 1
BUDGET = MEMSIZE_MB << 20
NROWS = 10 * BUDGET // 16        # u64 key + u64 value = 16 B/row → ~10 pages


def _fresh_counters():
    c = global_counters()
    c.msize = 0
    c.msizemax = 0
    return c


def _big_mr(tmp_path, rng, nkey=5000):
    mr = MapReduce(outofcore=1, memsize=MEMSIZE_MB, maxpage=1,
                   fpath=str(tmp_path))
    keys = rng.integers(0, nkey, NROWS).astype(np.uint64)
    vals = rng.integers(0, 1 << 30, NROWS).astype(np.uint64)
    # several map adds → several frames, most spilled
    step = NROWS // 8
    mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                             vals[s:s + step])
                                for s in range(0, NROWS, step)])
    return mr, keys, vals


def test_external_sort_keys_bounded(tmp_path, rng):
    mr, keys, vals = _big_mr(tmp_path, rng)
    c = _fresh_counters()
    mr.sort_keys(1)
    assert c.msizemax <= 2.5 * BUDGET, f"peak {c.msizemax} vs {BUDGET}"
    got_k = np.concatenate([np.asarray(f.key.data) for f in mr.kv.frames()])
    got_v = np.concatenate([np.asarray(f.value.data) for f in mr.kv.frames()])
    assert len(got_k) == NROWS
    np.testing.assert_array_equal(got_k, np.sort(keys, kind="stable"))
    # per-key value multisets survive (the external merge, like the
    # reference's qsort, does not promise stability for duplicate keys)
    order = np.lexsort((got_v, got_k))
    oracle = np.lexsort((vals, keys))
    np.testing.assert_array_equal(got_k[order], keys[oracle])
    np.testing.assert_array_equal(got_v[order], vals[oracle])


def test_external_sort_descending_bounded(tmp_path, rng):
    mr, keys, vals = _big_mr(tmp_path, rng)
    c = _fresh_counters()
    mr.sort_values(-1)
    assert c.msizemax <= 2.5 * BUDGET
    got_k = np.concatenate([np.asarray(f.key.data) for f in mr.kv.frames()])
    got_v = np.concatenate([np.asarray(f.value.data) for f in mr.kv.frames()])
    assert len(got_v) == NROWS
    # global descending order by value
    assert (np.diff(got_v.astype(np.int64)) <= 0).all()
    # (key, value) pairing survives the descending reshuffle
    order = np.lexsort((got_k, got_v))
    oracle = np.lexsort((keys, vals))
    np.testing.assert_array_equal(got_v[order], vals[oracle])
    np.testing.assert_array_equal(got_k[order], keys[oracle])


def test_external_convert_giant_single_key(tmp_path, rng):
    """All rows share one key: the whole dataset is one group — it must
    come back as exactly one group (the extended-KMV contract), correct
    even though the peak is O(group) by design."""
    mr = MapReduce(outofcore=1, memsize=MEMSIZE_MB, maxpage=1,
                   fpath=str(tmp_path))
    n = 3 * BUDGET // 16
    vals = np.arange(n, dtype=np.uint64)
    step = n // 4
    mr.map(1, lambda i, kv, p: [kv.add_batch(
        np.full(step, 7, np.uint64), vals[s:s + step])
        for s in range(0, n, step)])
    mr.convert()
    frames = list(mr.kmv.frames())
    keys = [int(k) for f in frames for k in np.asarray(f.key.data)]
    assert keys == [7]
    total = sum(int(f.nvalues.sum()) for f in frames)
    assert total == n


def test_external_convert_reduce_bounded(tmp_path, rng):
    mr, keys, vals = _big_mr(tmp_path, rng)
    c = _fresh_counters()
    mr.convert()
    assert c.msizemax <= 2.5 * BUDGET, f"peak {c.msizemax} vs {BUDGET}"
    assert mr.kmv.nframes > 1          # actually streamed in pieces
    # group counts match a dict oracle; reduce streams frame by frame
    import collections
    oracle = collections.Counter(keys.tolist())
    got = {}
    mr.reduce(lambda k, vlist, kv, p: got.__setitem__(int(k), len(vlist)))
    assert got == dict(oracle)
    assert c.msizemax <= 2.5 * BUDGET


def test_external_convert_groups_never_split(tmp_path, rng):
    """Every key appears in exactly one KMV group across all frames."""
    mr, keys, _ = _big_mr(tmp_path, rng, nkey=300)
    _fresh_counters()
    mr.convert()
    seen = {}
    for fr in mr.kmv.frames():
        for i, k in enumerate(np.asarray(fr.key.data).tolist()):
            assert k not in seen, f"key {k} split across frames"
            seen[k] = int(fr.nvalues[i])
    import collections
    oracle = collections.Counter(keys.tolist())
    assert seen == dict(oracle)


def test_external_sort_multicolumn_keys(tmp_path, rng):
    """Edge-style [n,2] u64 keys sort lexicographically out of core."""
    mr = MapReduce(outofcore=1, memsize=MEMSIZE_MB, maxpage=1,
                   fpath=str(tmp_path))
    n = 3 * BUDGET // 24
    e = rng.integers(0, 1000, (n, 2)).astype(np.uint64)
    v = np.arange(n, dtype=np.uint64)
    step = n // 4
    mr.map(1, lambda i, kv, p: [kv.add_batch(e[s:s + step], v[s:s + step])
                                for s in range(0, n, step)])
    c = _fresh_counters()
    mr.sort_keys(1)
    assert c.msizemax <= 2.5 * BUDGET
    got = np.concatenate([np.asarray(f.key.data) for f in mr.kv.frames()])
    order = np.lexsort((e[:, 1], e[:, 0]))
    np.testing.assert_array_equal(got, e[order])


def _big_mesh_mr(tmp_path, rng, ndev=8):
    import jax

    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ShardedKV

    assert len(jax.devices()) >= ndev
    mr = MapReduce(make_mesh(ndev), outofcore=1, memsize=MEMSIZE_MB,
                   maxpage=1, fpath=str(tmp_path))
    keys = rng.integers(0, 5000, NROWS).astype(np.uint64)
    vals = rng.integers(0, 1 << 30, NROWS).astype(np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
    mr.aggregate()
    fr = mr.kv.one_frame()
    assert isinstance(fr, ShardedKV)
    # genuinely past the per-shard HBM budget (maxpage * memsize)
    assert fr.nbytes() // ndev > BUDGET
    return mr, keys, vals


def test_mesh_convert_over_hbm_budget(tmp_path, rng):
    """VERDICT r2 #3: a mesh dataset ~10× the per-shard HBM budget
    demotes shard blocks to host page frames and converts through the
    bounded external path — correct groups, bounded msizemax."""
    import collections

    mr, keys, vals = _big_mesh_mr(tmp_path, rng)
    c = _fresh_counters()
    mr.convert()
    assert c.msizemax <= 3 * BUDGET, f"peak {c.msizemax} vs {BUDGET}"
    assert mr.kmv.nframes > 1          # streamed in pieces, not in-core
    oracle = collections.Counter(keys.tolist())
    got = {}
    mr.reduce(lambda k, vlist, kv, p: got.__setitem__(int(k), len(vlist)))
    assert got == dict(oracle)
    assert c.msizemax <= 3 * BUDGET


def test_mesh_sort_over_hbm_budget(tmp_path, rng):
    """sort_keys on an over-budget mesh dataset takes the same demote +
    external-merge route and stays bounded."""
    mr, keys, vals = _big_mesh_mr(tmp_path, rng)
    c = _fresh_counters()
    mr.sort_keys(1)
    assert c.msizemax <= 3 * BUDGET, f"peak {c.msizemax} vs {BUDGET}"
    got_k = np.concatenate([np.asarray(f.key.data) for f in mr.kv.frames()])
    np.testing.assert_array_equal(np.sort(got_k, kind="stable"), got_k)
    np.testing.assert_array_equal(np.sort(keys), got_k)


def test_mesh_demote_with_spilled_host_frames(tmp_path, rng):
    """A KV mixing an over-budget ShardedKV with SPILLED host frames
    demotes cleanly (spills load lazily via kv.frames()) and converts
    to the dict oracle."""
    import collections

    mr, keys, vals = _big_mesh_mr(tmp_path, rng)
    extra_k = rng.integers(0, 5000, BUDGET // 8).astype(np.uint64)
    extra_v = np.ones(len(extra_k), np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(extra_k, extra_v), addflag=1)
    mr.convert()
    oracle = collections.Counter(keys.tolist()) \
        + collections.Counter(extra_k.tolist())
    got = {}
    mr.reduce(lambda k, vl, kv, p: got.__setitem__(int(k), len(vl)))
    assert got == dict(oracle)


def test_mesh_interned_sort_over_global_budget(tmp_path, rng):
    """ADVICE r3: an interned mesh KV whose PER-SHARD bytes fit the HBM
    budget but whose GLOBAL bytes exceed it (the interned device sort
    gathers globally) must demote shard-by-shard into page frames and
    sort through the bounded external path — not decode everything into
    one controller-RAM frame."""
    import jax

    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ShardedKV

    ndev = 8
    assert len(jax.devices()) >= ndev
    mr = MapReduce(make_mesh(ndev), outofcore=1, memsize=MEMSIZE_MB,
                   maxpage=1, fpath=str(tmp_path))
    nrows = 3 * BUDGET // 16           # ids are u64 pairs: 16 B/row
    words = [b"w%06d" % (i % 40000) for i in range(nrows)]
    vals = rng.integers(0, 1 << 30, nrows).astype(np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(words, vals))
    mr.aggregate()
    fr = mr.kv.one_frame()
    assert isinstance(fr, ShardedKV) and fr.key_decode is not None
    assert fr.nbytes() > BUDGET            # global gather would blow it
    assert fr.nbytes() // ndev <= BUDGET   # but per-shard fits
    c = _fresh_counters()
    mr.sort_keys(5)
    assert c.msizemax <= 3 * BUDGET, f"peak {c.msizemax} vs {BUDGET}"
    got = []
    mr.scan_kv(lambda k, v, p: got.append(bytes(k)))
    assert got == sorted(words)
