"""plan/ subsystem: golden equivalence (fused output == eager output)
for every fusible chain, plan-cache hit/eviction, fallback-on-host-tier,
bounded shuffle jit caches and per-call exchange stats (ISSUE 2)."""

import numpy as np
import pytest

from gpu_mapreduce_tpu.core.mapreduce import MapReduce
from gpu_mapreduce_tpu.core.runtime import global_counters
from gpu_mapreduce_tpu.ops.reduces import (count, cull, max_values,
                                           sum_values)
from gpu_mapreduce_tpu.parallel.mesh import make_mesh
from gpu_mapreduce_tpu.plan import plan_cache, plan_history

TEXT1 = b"the quick brown fox jumps over the lazy dog\nthe fox ran\n"
TEXT2 = b"pack my box with five dozen liquor jugs\nthe dog slept\n"


def _filler(keys, vals):
    def m(itask, kv, ptr):
        kv.add_batch(keys, vals)
    return m


def scan_pairs(mr):
    got = []
    mr.scan_kv(lambda k, v, p: got.append((k if isinstance(k, bytes)
                                           else int(k), int(v))))
    return sorted(got)


def run_chain(comm, fuse, kernel, keys, vals, **settings):
    mr = MapReduce(comm, fuse=fuse, **settings)
    mr.map(1, _filler(keys, vals))
    mr.aggregate()
    mr.convert()
    n = mr.reduce(kernel, batch=True)
    pairs = scan_pairs(mr)
    return int(n), pairs


def intcount_keys(n=3000, card=97):
    k = ((np.arange(n, dtype=np.uint64) * 7919) % card).astype(np.uint64)
    return k, np.ones(n, np.int64)


# ---------------------------------------------------------------------------
# golden equivalence: fused == eager, serial + fake-cluster mesh
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("ndev", [None, 1, 4, 8])
@pytest.mark.parametrize("kernel", [count, sum_values, max_values, cull])
def test_intcount_chain_equivalence(ndev, kernel):
    """The intcount pipeline (dense u64 keys) through every registered
    kernel reduce: fused output byte-identical to eager."""
    keys, _ = intcount_keys()
    vals = np.arange(len(keys), dtype=np.int64)
    comm = make_mesh(ndev) if ndev else None
    eager = run_chain(comm, 0, kernel, keys, vals)
    fused = run_chain(make_mesh(ndev) if ndev else None, 1, kernel,
                      keys, vals)
    assert eager == fused


@pytest.mark.parametrize("ndev", [None, 4])
def test_wordfreq_host_reduce_equivalence(tmp_path, ndev):
    """wordfreq with byte-string keys and a HOST python reduce: the
    collate fuses (byte keys intern + exchange + group in 2 programs),
    the host-tier reduce falls back — output identical to eager."""
    from gpu_mapreduce_tpu.apps.wordfreq import _fileread, _sum
    p1, p2 = tmp_path / "a.txt", tmp_path / "b.txt"
    p1.write_bytes(TEXT1)
    p2.write_bytes(TEXT2)
    files = [str(p1), str(p2)]

    def wf(fuse):
        mr = MapReduce(make_mesh(ndev) if ndev else None, fuse=fuse)
        nwords = mr.map_files(files, _fileread)
        mr.collate()
        nunique = mr.reduce(_sum)
        return int(nwords), int(nunique), scan_pairs(mr)

    assert wf(0) == wf(1)


def test_wordfreq_app_end_to_end_fused(tmp_path):
    """The full wordfreq app (collate→reduce→gather→sort→scan) under
    MRTPU-style fuse=1 via settings: top-N identical to eager."""
    from gpu_mapreduce_tpu.apps.wordfreq import _fileread, _sum
    from gpu_mapreduce_tpu.apps.common import top_n
    p1 = tmp_path / "a.txt"
    p1.write_bytes(TEXT1 + TEXT2)

    def wf(fuse):
        mr = MapReduce(make_mesh(4), fuse=fuse)
        mr.map_files([str(p1)], _fileread)
        mr.collate()
        mr.reduce(_sum)
        return sorted((k, int(v)) for k, v in top_n(mr, 5))

    assert wf(0) == wf(1)


@pytest.mark.parametrize("kernel", [count, cull])
def test_invertedindex_pairs_equivalence(kernel):
    """The invertedindex shape — (url_id, doc_id) u64 pairs, heavy key
    repetition — counted/dedup'd fused vs eager on the mesh."""
    rng = np.random.default_rng(7)
    urls = rng.integers(0, 200, 5000).astype(np.uint64)
    docs = rng.integers(0, 16, 5000).astype(np.uint64)
    eager = run_chain(make_mesh(8), 0, kernel, urls, docs.astype(np.int64))
    fused = run_chain(make_mesh(8), 1, kernel, urls, docs.astype(np.int64))
    assert eager == fused


@pytest.mark.parametrize("ndev", [None, 4])
def test_spill_breaks_fusion_still_correct(tmp_path, ndev):
    """outofcore=1 is a fusion boundary: the chain replays eagerly
    (spilled frames stream the external path) and output matches."""
    keys, vals = intcount_keys(5000)
    comm = make_mesh(ndev) if ndev else None
    eager = run_chain(comm, 0, count, keys, vals, outofcore=1,
                      memsize=1, maxpage=1, fpath=str(tmp_path))
    fused = run_chain(make_mesh(ndev) if ndev else None, 1, count, keys,
                      vals, outofcore=1, memsize=1, maxpage=1,
                      fpath=str(tmp_path))
    assert eager == fused
    assert all(not g["fused"] for g in plan_history()[-1]["groups"])


def test_host_callback_reduce_is_barrier():
    """A python reduce callback never defers — it flushes the recorded
    [aggregate, convert] prefix (which fuses) and runs eagerly, so its
    side effects stay ordered."""
    keys, vals = intcount_keys(500)
    seen = []

    def pysum(key, values, kv, ptr):
        seen.append(key)
        kv.add(key, sum(values))

    def run(fuse):
        seen.clear()
        mr = MapReduce(make_mesh(4), fuse=fuse)
        mr.map(1, _filler(keys, vals))
        mr.aggregate()
        mr.convert()
        mr.reduce(pysum)
        n = len(seen)           # side effect visible immediately
        return n, scan_pairs(mr)

    assert run(0) == run(1)
    kinds = [g["kind"] for g in plan_history()[-1]["groups"]]
    assert kinds == ["exchange"]   # collate fused; reduce never recorded


def test_ptr_reduce_is_barrier():
    """reduce(f, ptr=other_mr) writes into ANOTHER object (the sssp
    shape): it must execute in issue order, not at some later flush."""
    keys, vals = intcount_keys(300, card=11)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    other = MapReduce(make_mesh(4))
    other.open()

    def emit(key, values, kv, ptr):
        ptr.kv.add(key, len(values))
        kv.add(key, len(values))

    mr.aggregate()
    mr.convert()
    mr.reduce(emit, ptr=other)
    assert other.close() == 11      # side effect landed before close


def test_sort_stage_replays_eagerly():
    keys, vals = intcount_keys(800)

    def run(fuse):
        mr = MapReduce(make_mesh(4), fuse=fuse)
        mr.map(1, _filler(keys, vals))
        mr.aggregate()
        mr.convert()
        mr.reduce(count, batch=True)
        mr.sort_values(-1)
        return scan_pairs(mr)

    assert run(0) == run(1)


def test_p1_mesh_local_fusion():
    """P==1 mesh: aggregate early-outs eagerly (sharding the frame),
    then [convert, reduce] fuses into ONE local program."""
    keys, vals = intcount_keys(1000, card=31)
    eager = run_chain(make_mesh(1), 0, sum_values, keys, vals)
    fused = run_chain(make_mesh(1), 1, sum_values, keys, vals)
    assert eager == fused
    kinds = [g["kind"] for g in plan_history()[-1]["groups"]]
    assert "local" in kinds


# ---------------------------------------------------------------------------
# recorder semantics
# ---------------------------------------------------------------------------

def test_pipeline_context_manager_and_pending_count():
    keys, vals = intcount_keys(600)
    mr = MapReduce(make_mesh(4))
    mr.map(1, _filler(keys, vals))
    with mr.pipeline():
        na = mr.aggregate()
        nc = mr.convert()
        nr = mr.reduce(count, batch=True)
        # still recorded — nothing executed yet
        assert mr._plan is not None and len(mr._plan.stages) == 3
    # exit flushed; PendingCounts resolve to the real counts
    assert na == len(keys)
    assert int(nc) == 97 and nr == 97
    assert f"{nr}" == "97"
    assert nr + 1 == 98 and nr > 0


def test_discarded_pending_count_raises():
    """A PendingCount whose stage was discarded by an aborted pipeline()
    must raise when resolved — a silent 0 would look like a real count
    for an op that never ran."""
    from gpu_mapreduce_tpu import MRError
    keys, vals = intcount_keys(200, card=7)
    mr = MapReduce(make_mesh(4))
    mr.map(1, _filler(keys, vals))
    with pytest.raises(ValueError, match="user bug"):
        with mr.pipeline():
            n = mr.aggregate()
            raise ValueError("user bug")
    with pytest.raises(MRError, match="discarded"):
        int(n)


def test_pipeline_adopts_pending_auto_stages():
    """fuse=1 defers an aggregate; a pipeline() block entered afterwards
    must adopt it so stages execute in issue order (not convert/reduce
    against un-aggregated shards)."""
    keys, vals = intcount_keys(2000, card=97)
    eager = run_chain(make_mesh(4), 0, count, keys, vals)

    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    mr.aggregate()                      # deferred into the auto recorder
    with mr.pipeline():
        mr.convert()
        mr.reduce(count, batch=True)
    n = int(mr.kv_stats(0)[0])
    assert (n, scan_pairs(mr)) == eager


def test_kv_read_is_a_barrier():
    """Direct mr.kv/mr.kmv reads (apps, oink commands poke these) flush
    the pending plan — no stale/None state under fuse=1."""
    keys, vals = intcount_keys(400, card=13)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    mr.aggregate()
    mr.convert()
    assert mr._plan is not None and mr._plan.stages
    assert mr.kmv is not None           # property read flushed the plan
    assert mr._plan is None or not mr._plan.stages


def test_pending_count_coercion_is_a_barrier():
    """Reading a deferred count mid-chain flushes the recorded prefix."""
    keys, vals = intcount_keys(400)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    n = mr.aggregate()
    assert mr._plan is not None
    assert int(n) == len(keys)      # coercion flushed the plan
    assert mr._plan is None         # auto recorder uninstalled


def test_fuse_dispatch_reduction():
    """The acceptance headline: the fused chain launches fewer compiled
    programs than the eager chain."""
    keys, vals = intcount_keys(2048, card=257)

    def dispatches(fuse):
        mr = MapReduce(make_mesh(4), fuse=fuse)
        mr.map(1, _filler(keys, vals))
        c0 = global_counters().snapshot()["ndispatch"]
        mr.aggregate()
        mr.convert()
        int(mr.reduce(count, batch=True))
        return global_counters().snapshot()["ndispatch"] - c0

    eager, fused = dispatches(0), dispatches(1)
    assert fused < eager, (fused, eager)


def test_fused_output_compacts_to_eager_size():
    """Duplicate-heavy keys: the fused chain's resident KV must not stay
    sized at row capacity — it compacts to the eager tier's
    round_cap(max groups) shapes."""
    n, card = 20000, 37
    keys = ((np.arange(n, dtype=np.uint64) * 7919) % card)
    vals = np.ones(n, np.int64)

    def run(fuse):
        mr = MapReduce(make_mesh(4), fuse=fuse)
        mr.map(1, _filler(keys, vals))
        mr.aggregate()
        mr.convert()
        int(mr.reduce(count, batch=True))
        fr = mr.kv.one_frame()
        return fr.key.shape[0], scan_pairs(mr)

    (esize, epairs), (fsize, fpairs) = run(0), run(1)
    assert epairs == fpairs
    assert fsize == esize          # not ~20000 rows for 37 groups


def test_set_fuse_off_flushes_auto_recorder():
    keys, vals = intcount_keys(300, card=9)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    mr.aggregate()
    assert mr._plan is not None and mr._plan.stages
    mr.set(fuse=0)
    assert mr._plan is None        # flushed + uninstalled
    n = mr.convert()               # eager again: a real int
    assert isinstance(n, int)


def test_kv_assignment_flushes_pending_plan():
    """mr.kv = ... replaces the dataset; pending deferred ops were
    issued against the OLD one and must run first (eager order)."""
    keys, vals = intcount_keys(400, card=13)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    na = mr.aggregate()
    mr.kv = mr._new_kv()           # barrier: aggregate ran on old data
    assert int(na) == 400


def test_pipeline_exception_discards_tail():
    """An exception inside pipeline() aborts the un-flushed tail — the
    user's exception surfaces, not a replay error's."""
    keys, vals = intcount_keys(200, card=7)
    mr = MapReduce(make_mesh(4))
    mr.map(1, _filler(keys, vals))
    with pytest.raises(ValueError, match="user bug"):
        with mr.pipeline():
            mr.aggregate()
            raise ValueError("user bug")
    # dataset untouched by the discarded stage; eager ops still work
    mr.aggregate()
    mr.convert()
    assert int(mr.reduce(count, batch=True)) == 7


# ---------------------------------------------------------------------------
# plan cache
# ---------------------------------------------------------------------------

def test_plan_cache_hit_and_span_attr():
    keys, vals = intcount_keys(512, card=41)

    def run():
        mr = MapReduce(make_mesh(4), fuse=1)
        mr.map(1, _filler(keys, vals))
        mr.aggregate()
        mr.convert()
        mr.reduce(count, batch=True)
        return scan_pairs(mr), mr

    from gpu_mapreduce_tpu.obs import get_tracer
    tracer = get_tracer().enable()
    try:
        tracer.clear()
        before = plan_cache().stats()
        first, _ = run()
        second, mr = run()
        assert first == second
        after = mr.stats()["plan"]["plan"]
        assert after["hits"] >= before["hits"] + 1
        evs = [e for e in tracer.events() if e["name"] == "plan.execute"]
        assert evs, "plan.execute spans missing"
        assert any(e["args"].get("cache_hit") for e in evs)
        assert any(not e["args"].get("cache_hit") for e in evs)
    finally:
        tracer.disable()


def test_unhashable_hash_fn_runs_uncached():
    """An unhashable callable stage arg can't key the plan cache — the
    plan must still execute (uncached), not crash at flush."""
    class WeirdHash:
        __hash__ = None                       # unhashable
        host_hash = True                      # host tier → eager replay

        def __call__(self, keys):
            return [int.from_bytes(k, "little") % 4 for k in keys]
    keys, vals = intcount_keys(200, card=9)
    eager = run_chain(make_mesh(4), 0, count, keys, vals)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    mr.aggregate(WeirdHash())
    mr.convert()
    n = int(mr.reduce(count, batch=True))
    assert (n, scan_pairs(mr)) == eager


def test_pending_count_division_and_stats_barrier():
    keys, vals = intcount_keys(500, card=25)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    mr.aggregate()
    mr.convert()
    n = mr.reduce(count, batch=True)
    assert n / 5 == 5.0 and n // 7 == 3 and n % 7 == 4
    assert -n == -25 and abs(n) == 25 and divmod(n, 7) == (3, 4)
    # stats() is a barrier: counters include the pending chain
    mr2 = MapReduce(make_mesh(4), fuse=1)
    mr2.map(1, _filler(keys, vals))
    mr2.aggregate()
    mr2.convert()
    mr2.reduce(count, batch=True)
    assert mr2._plan is not None and mr2._plan.stages
    s = mr2.stats()
    assert mr2._plan is None or not mr2._plan.stages
    assert s["cssize"] > 0          # the exchange actually ran


def test_plan_cache_eviction():
    cache = plan_cache()
    old = cache.maxsize
    cache.resize(1)
    try:
        ev0 = cache.stats()["evictions"]
        for card in (11, 13, 17):    # distinct shapes → distinct keys
            keys, vals = intcount_keys(256, card=card)
            run_chain(make_mesh(4), 1, count, keys, vals)
        st = cache.stats()
        assert st["size"] <= 1
        assert st["evictions"] > ev0
    finally:
        cache.resize(old)


def test_shuffle_jit_caches_bounded():
    """The phase1/phase2 executable caches evict past maxsize instead of
    growing without limit (ISSUE 2 satellite)."""
    from gpu_mapreduce_tpu.parallel import shuffle
    old = shuffle.PHASE2_CACHE.maxsize
    shuffle.PHASE2_CACHE.resize(2)
    try:
        ev0 = shuffle.PHASE2_CACHE.stats()["evictions"]
        for n in (64, 256, 1024, 4096):
            keys = (np.arange(n, dtype=np.uint64) * 31) % 7
            run_chain(make_mesh(4), 0, count, keys,
                      np.ones(n, np.int64))
        st = shuffle.PHASE2_CACHE.stats()
        assert st["size"] <= 2
        assert st["evictions"] > ev0
    finally:
        shuffle.PHASE2_CACHE.resize(old)


# ---------------------------------------------------------------------------
# per-call exchange stats (ISSUE 2 satellite)
# ---------------------------------------------------------------------------

def test_exchange_call_stats_per_object():
    """Two MapReduce objects keep their OWN exchange telemetry — the
    deprecated class attrs record only the last one process-wide."""
    k1, v1 = intcount_keys(512, card=7)
    k2, v2 = intcount_keys(2048, card=300)
    mr1 = MapReduce(make_mesh(4))
    mr1.map(1, _filler(k1, v1))
    mr1.aggregate()
    mr2 = MapReduce(make_mesh(4))
    mr2.map(1, _filler(k2, v2))
    mr2.aggregate()
    s1, s2 = mr1.last_exchange, mr2.last_exchange
    assert s1 is not None and s2 is not None
    assert s1.rows == 512 and s2.rows == 2048     # not clobbered
    # the stats object also rides the sharded frame itself
    fr = mr2.kv.one_frame()
    assert getattr(fr, "exchange_stats", None) is s2
    # deprecated shim still readable (last exchange process-wide)
    from gpu_mapreduce_tpu.parallel.shuffle import ExchangeStats
    assert ExchangeStats.last == (s2.nrounds, s2.bucket)


def test_fused_chain_sets_last_exchange():
    keys, vals = intcount_keys(1024, card=19)
    mr = MapReduce(make_mesh(4), fuse=1)
    mr.map(1, _filler(keys, vals))
    mr.aggregate()
    mr.convert()
    int(mr.reduce(count, batch=True))
    assert mr.last_exchange is not None
    assert mr.last_exchange.rows == 1024


# ---------------------------------------------------------------------------
# dump_plan / plan_dump
# ---------------------------------------------------------------------------

def test_dump_plan_command(tmp_path):
    from gpu_mapreduce_tpu.oink.command import run_command
    keys, vals = intcount_keys(128, card=5)
    run_chain(make_mesh(4), 1, count, keys, vals)   # ensure history
    out = tmp_path / "plan.txt"
    cmd = run_command("dump_plan", [str(out)])
    text = out.read_text()
    assert "plan " in text and "group" in text
    assert "aggregate" in text
    cmd2 = run_command("dump_plan", ["-"], screen=False)
    assert "aggregate" in cmd2.result_msg


def test_oink_script_set_fuse(tmp_path):
    """`set fuse 1` in an OINK script: the wordfreq command runs its
    collate/reduce through the plan path with identical results."""
    import io
    from gpu_mapreduce_tpu.oink import OinkScript
    data = tmp_path / "data.txt"
    data.write_bytes(TEXT1 + TEXT2)

    def run(fuse):
        out = io.StringIO()
        s = OinkScript(screen=out)
        s.run_string(f"set fuse {fuse}\n"
                     f"wordfreq 5 -i {data} -o NULL NULL\n")
        return [ln for ln in out.getvalue().splitlines()
                if ln.strip() and not ln.startswith("WordFreq:")]

    assert run(0) == run(1)
