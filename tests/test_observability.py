"""Observability parity (VERDICT r1 #6): verbosity=2 / timer=2 per-shard
histograms (reference write_histo, src/mapreduce.cpp:3251-3311), per-op
spill/comm deltas, and tier notes — plus the structured obs/ tracing
layer (spans, sinks, Chrome export, mr.stats())."""

import json

import numpy as np
import pytest

from gpu_mapreduce_tpu import MapReduce
from gpu_mapreduce_tpu.core.runtime import histogram


def test_histogram_bins():
    lo, ave, hi, bins = histogram([0, 5, 10, 10], nbins=5)
    assert (lo, hi) == (0, 10)
    assert ave == 6.25
    assert sum(bins) == 4
    assert bins[0] == 1 and bins[-1] == 2
    lo, ave, hi, bins = histogram([7, 7, 7])
    assert (lo, hi) == (7, 7) and bins[0] == 3


def test_verbosity2_histograms_mesh(capsys):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    mr = MapReduce(make_mesh(4), verbosity=2)
    keys = np.arange(4000, dtype=np.uint64) % 97
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.collate()
    outp = capsys.readouterr().out
    assert "KV pairs (per shard):" in outp
    assert "histogram:" in outp
    assert "shuffled" in outp          # comm delta reported for aggregate


def test_timer2_row_histogram(capsys):
    mr = MapReduce(timer=2)
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(100, dtype=np.uint64), np.ones(100, np.uint64)))
    mr.sort_keys(1)
    outp = capsys.readouterr().out
    assert "sort time (secs)" in outp
    assert "rows (per shard):" in outp


def test_tier_note_host_reduce(capsys):
    mr = MapReduce(verbosity=2)
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.array([1, 1, 2], np.uint64), np.ones(3, np.uint64)))
    mr.convert()
    mr.reduce(lambda k, v, kv, p: kv.add(k, len(v)))
    assert "host per-group tier" in capsys.readouterr().out


def test_spill_delta_reported(tmp_path, capsys):
    mr = MapReduce(outofcore=1, memsize=1, maxpage=1, fpath=str(tmp_path),
                   verbosity=2)
    n = 3 << 16
    keys = np.arange(n, dtype=np.uint64)
    step = n // 4
    mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                             keys[s:s + step])
                                for s in range(0, n, step)])
    mr.sort_keys(1)
    outp = capsys.readouterr().out
    assert "Mb spilled" in outp


def test_publish_preserves_corrupt_baseline(tmp_path):
    """r4 review: publish() over a corrupt BASELINE.json must not
    silently destroy the previous records — the unparsable file moves
    aside to .corrupt and the write is atomic (tmp+rename)."""
    import json
    import os

    from gpu_mapreduce_tpu.utils.publish import publish, read_published

    path = str(tmp_path / "BASELINE.json")
    publish("a", {"x": 1}, path=path)
    assert read_published("a", path=path) == {"x": 1}

    with open(path) as f:
        truncated = f.read()[:-5]          # rip off the closing braces
    with open(path, "w") as f:
        f.write(truncated)
    publish("b", {"y": 2}, path=path)
    assert read_published("b", path=path) == {"y": 2}
    corrupt = path + ".corrupt"
    assert os.path.exists(corrupt)         # old records survive for repair
    assert '"a"' in open(corrupt).read()
    assert not os.path.exists(path + ".tmp")
    json.load(open(path))                  # the new file parses


# ---------------------------------------------------------------------------
# obs/ tracing subsystem (PR 1): spans, sinks, export, stats
# ---------------------------------------------------------------------------

@pytest.fixture
def tracer():
    """The process-global tracer, reset before and after the test so
    span rings/sinks never leak across tests."""
    from gpu_mapreduce_tpu.obs import get_tracer
    tr = get_tracer()
    tr.reset()
    yield tr
    tr.reset()


def test_span_nesting_and_counter_deltas():
    from gpu_mapreduce_tpu.core.runtime import Counters
    from gpu_mapreduce_tpu.obs import Tracer

    c = Counters()
    tr = Tracer(counters=c).enable()
    with tr.span("outer", cat="t"):
        with tr.span("inner", cat="t", shards=4):
            c.add(cssize=100, cspad=7, wsize=50)
            c.mem(1 << 20)
    evs = tr.events()
    assert [e["name"] for e in evs] == ["inner", "outer"]  # exit order
    inner, outer = evs
    assert inner["parent"] == outer["id"]          # nesting recorded
    assert outer["parent"] == 0
    assert inner["args"]["shards"] == 4
    # counter deltas land on every span that was open during the bump
    for ev in (inner, outer):
        assert ev["args"]["shuffle_sent_bytes"] == 100
        assert ev["args"]["shuffle_pad_bytes"] == 7
        assert ev["args"]["spill_write_bytes"] == 50
        assert ev["args"]["hbm_hiwater_bytes"] == 1 << 20
    assert inner["dur"] <= outer["dur"]


def test_jsonl_sink_round_trip(tmp_path, tracer):
    from gpu_mapreduce_tpu.obs import read_jsonl

    path = str(tmp_path / "t.jsonl")
    mr = MapReduce(trace=path)
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(100, dtype=np.uint64), np.ones(100, np.uint64)))
    mr.sort_keys(1)
    evs = read_jsonl(path)
    assert [e["name"] for e in evs] == ["map", "sort_keys"]
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in evs)
    assert evs[0]["args"]["npairs"] == 100
    assert evs[0]["cat"] == "mr_op"


def test_chrome_trace_export_valid(tmp_path, tracer):
    from gpu_mapreduce_tpu.obs import write_chrome_trace

    tracer.enable()
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(64, dtype=np.uint64), np.ones(64, np.uint64)))
    mr.compress(lambda k, v, kv, p: kv.add(k, len(v)))
    out = str(tmp_path / "chrome.json")
    n = write_chrome_trace(out, tracer.events())
    doc = json.load(open(out))                 # must parse as plain JSON
    evs = doc["traceEvents"]
    assert len(evs) == n >= 3                  # map, convert, reduce, compress
    # complete ("X") events must carry ts+dur; any B has a matching E
    opens = {}
    for e in evs:
        assert e["ph"] in ("X", "B", "E")
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float))
        elif e["ph"] == "B":
            opens[e["id"]] = opens.get(e["id"], 0) + 1
        else:
            opens[e["id"]] -= 1
    assert all(v == 0 for v in opens.values())
    # compress parents its convert+reduce
    byname = {e["name"]: e for e in evs}
    assert byname["convert"]["parent"] == byname["compress"]["id"]
    assert byname["reduce"]["parent"] == byname["compress"]["id"]


def test_stats_matches_cummulative_print(tmp_path, capsys):
    mr = MapReduce(outofcore=1, memsize=1, maxpage=1, fpath=str(tmp_path))
    n = 3 << 16
    keys = np.arange(n, dtype=np.uint64)
    step = n // 4
    mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                             keys[s:s + step])
                                for s in range(0, n, step)])
    mr.sort_keys(1)
    s = mr.stats()
    # every printed cummulative_stats field is a stats() key
    assert {"msizemax", "rsize", "wsize", "cssize", "crsize", "cspad",
            "commtime"} <= set(s)
    assert s["wsize"] > 0 and s["rsize"] > 0    # the spill ran
    mr.cummulative_stats(1)
    out = capsys.readouterr().out
    # the print is a formatting consumer of the same snapshot: rebuild
    # each line from stats() and require byte equality
    assert (f"Cummulative hi-water mem = "
            f"{s['msizemax'] / (1 << 20):.3g} Mb") in out
    assert (f"Cummulative spill I/O = {s['rsize'] / (1 << 20):.3g} Mb read, "
            f"{s['wsize'] / (1 << 20):.3g} Mb written") in out
    assert (f"Cummulative comm = {s['cssize'] / (1 << 20):.3g} Mb sent, "
            f"{s['crsize'] / (1 << 20):.3g} Mb received, "
            f"{s['cspad'] / (1 << 20):.3g} Mb padding, "
            f"{s['commtime']:.3g} secs") in out


def test_spill_deltas_land_on_spans(tmp_path, tracer):
    tracer.enable()
    mr = MapReduce(outofcore=1, memsize=1, maxpage=1, fpath=str(tmp_path))
    n = 3 << 16
    keys = np.arange(n, dtype=np.uint64)
    step = n // 4
    mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                             keys[s:s + step])
                                for s in range(0, n, step)])
    mr.sort_keys(1)
    evs = tracer.events()
    assert any(e["args"].get("spill_write_bytes", 0) > 0 for e in evs)
    assert any(e["args"].get("spill_read_bytes", 0) > 0 for e in evs)


def test_tracer_disabled_zero_cost(tracer):
    import time

    from gpu_mapreduce_tpu.obs import NULL_SPAN

    # the disabled fast path returns the shared no-op singleton: no
    # allocation, no stack touch, no sink work
    assert tracer.span("x") is NULL_SPAN
    assert tracer.span("y", cat="z") is NULL_SPAN
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(16, dtype=np.uint64), np.ones(16, np.uint64)))
    assert tracer.events() == []               # nothing recorded
    t0 = time.perf_counter()
    for _ in range(100_000):
        tracer.span("x")
    dt = time.perf_counter() - t0
    assert dt < 1.0                            # ~µs/call ceiling, generous


def test_wordfreq_mesh_trace_acceptance(tmp_path, tracer):
    """The PR acceptance path: a traced wordfreq run yields a JSONL
    trace whose Chrome export is valid, with spans for every MR op and
    shuffle sent/pad bytes on the exchange."""
    from gpu_mapreduce_tpu.obs import chrome_trace, read_jsonl
    from gpu_mapreduce_tpu.oink.kernels import count, read_words
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    words = tmp_path / "w.txt"
    words.write_text("a b c a b a d e f g h a b\n" * 50)
    jsonl = str(tmp_path / "wf.jsonl")
    mr = MapReduce(make_mesh(4), trace=jsonl)
    mr.map_files([str(words)], read_words)
    mr.collate()
    mr.reduce(count, batch=True)
    evs = read_jsonl(jsonl)
    names = {e["name"] for e in evs}
    assert {"map_files", "aggregate", "convert", "collate",
            "reduce"} <= names
    assert "shuffle.exchange" in names         # child span of aggregate
    ex = next(e for e in evs if e["name"] == "shuffle.exchange")
    agg = next(e for e in evs if e["name"] == "aggregate")
    assert ex["parent"] == agg["id"]
    assert ex["args"]["sent_bytes"] > 0
    assert ex["args"]["pad_bytes"] >= 0
    assert ex["args"]["bucket"] > 0 and ex["args"]["nrounds"] >= 1
    assert agg["args"]["shuffle_sent_bytes"] == ex["args"]["sent_bytes"]
    doc = chrome_trace(evs)
    json.loads(json.dumps(doc))                # fully serializable
    assert len(doc["traceEvents"]) == len(evs)


def test_dump_trace_script_command(tmp_path, tracer):
    tracer.enable()
    from gpu_mapreduce_tpu.oink.script import OinkScript

    words = tmp_path / "w.txt"
    words.write_text("a b b c c c\n")
    out = tmp_path / "trace.json"
    interp = OinkScript(screen=False)
    try:
        interp.run_string(f"wordfreq 2 -i {words} -o NULL NULL\n"
                          f"dump_trace {out}")
    finally:
        interp.close()
    doc = json.load(open(out))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "oink.wordfreq" in names            # script-command span
    assert {"map_files", "collate", "reduce"} <= names
