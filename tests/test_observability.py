"""Observability parity (VERDICT r1 #6): verbosity=2 / timer=2 per-shard
histograms (reference write_histo, src/mapreduce.cpp:3251-3311), per-op
spill/comm deltas, and tier notes."""

import numpy as np

from gpu_mapreduce_tpu import MapReduce
from gpu_mapreduce_tpu.core.runtime import histogram


def test_histogram_bins():
    lo, ave, hi, bins = histogram([0, 5, 10, 10], nbins=5)
    assert (lo, hi) == (0, 10)
    assert ave == 6.25
    assert sum(bins) == 4
    assert bins[0] == 1 and bins[-1] == 2
    lo, ave, hi, bins = histogram([7, 7, 7])
    assert (lo, hi) == (7, 7) and bins[0] == 3


def test_verbosity2_histograms_mesh(capsys):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    mr = MapReduce(make_mesh(4), verbosity=2)
    keys = np.arange(4000, dtype=np.uint64) % 97
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.collate()
    outp = capsys.readouterr().out
    assert "KV pairs (per shard):" in outp
    assert "histogram:" in outp
    assert "shuffled" in outp          # comm delta reported for aggregate


def test_timer2_row_histogram(capsys):
    mr = MapReduce(timer=2)
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(100, dtype=np.uint64), np.ones(100, np.uint64)))
    mr.sort_keys(1)
    outp = capsys.readouterr().out
    assert "sort time (secs)" in outp
    assert "rows (per shard):" in outp


def test_tier_note_host_reduce(capsys):
    mr = MapReduce(verbosity=2)
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.array([1, 1, 2], np.uint64), np.ones(3, np.uint64)))
    mr.convert()
    mr.reduce(lambda k, v, kv, p: kv.add(k, len(v)))
    assert "host per-group tier" in capsys.readouterr().out


def test_spill_delta_reported(tmp_path, capsys):
    mr = MapReduce(outofcore=1, memsize=1, maxpage=1, fpath=str(tmp_path),
                   verbosity=2)
    n = 3 << 16
    keys = np.arange(n, dtype=np.uint64)
    step = n // 4
    mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                             keys[s:s + step])
                                for s in range(0, n, step)])
    mr.sort_keys(1)
    outp = capsys.readouterr().out
    assert "Mb spilled" in outp


def test_publish_preserves_corrupt_baseline(tmp_path):
    """r4 review: publish() over a corrupt BASELINE.json must not
    silently destroy the previous records — the unparsable file moves
    aside to .corrupt and the write is atomic (tmp+rename)."""
    import json
    import os

    from gpu_mapreduce_tpu.utils.publish import publish, read_published

    path = str(tmp_path / "BASELINE.json")
    publish("a", {"x": 1}, path=path)
    assert read_published("a", path=path) == {"x": 1}

    with open(path) as f:
        truncated = f.read()[:-5]          # rip off the closing braces
    with open(path, "w") as f:
        f.write(truncated)
    publish("b", {"y": 2}, path=path)
    assert read_published("b", path=path) == {"y": 2}
    corrupt = path + ".corrupt"
    assert os.path.exists(corrupt)         # old records survive for repair
    assert '"a"' in open(corrupt).read()
    assert not os.path.exists(path + ".tmp")
    json.load(open(path))                  # the new file parses
