"""Checkpoint/restore round-trips (core/checkpoint.py — a capability
improvement over the reference, which has no restartable persistence:
SURVEY.md §5, page files deleted on destruction)."""

import numpy as np
import pytest

from gpu_mapreduce_tpu import MapReduce
from gpu_mapreduce_tpu.core.runtime import MRError


def kv_pairs(mr):
    pairs = []
    mr.scan_kv(lambda k, v, p: pairs.append((k, v)))
    return pairs


def test_kv_roundtrip(tmp_path):
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(1000, dtype=np.uint64), np.arange(1000) * 2))
    n = mr.save(str(tmp_path / "ckpt"))
    assert n >= 1
    mr2 = MapReduce()
    assert mr2.load(str(tmp_path / "ckpt")) == 1000
    assert kv_pairs(mr2) == kv_pairs(mr)


def test_kmv_roundtrip(tmp_path):
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: [kv.add(i % 7, i) for i in range(100)])
    mr.convert()
    mr.save(str(tmp_path / "c"))
    groups = {}
    mr.scan_kmv(lambda k, vs, p: groups.__setitem__(k, list(vs)))
    mr2 = MapReduce()
    assert mr2.load(str(tmp_path / "c")) == 7
    groups2 = {}
    mr2.scan_kmv(lambda k, vs, p: groups2.__setitem__(k, list(vs)))
    assert groups == groups2


def test_bytes_and_objects_roundtrip(tmp_path):
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: [kv.add(w, 1) for w in
                                (b"alpha", b"beta", b"alpha")])
    mr.save(str(tmp_path / "b"))
    mr2 = MapReduce()
    mr2.load(str(tmp_path / "b"))
    assert sorted(kv_pairs(mr2)) == sorted(kv_pairs(mr))

    mro = MapReduce()
    mro.map(1, lambda i, kv, p: kv.add(("tup", 3), {"d": [1, 2]}))
    mro.save(str(tmp_path / "o"))
    mro2 = MapReduce()
    mro2.load(str(tmp_path / "o"))
    assert kv_pairs(mro2) == [(("tup", 3), {"d": [1, 2]})]


def test_spilled_roundtrip(tmp_path):
    """A spilled multi-frame KV checkpoints frame-by-frame and restores
    with identical content."""
    mr = MapReduce(outofcore=1, memsize=1, maxpage=1,
                   fpath=str(tmp_path / "spill"))
    keys = np.arange(300_000, dtype=np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    nf = mr.save(str(tmp_path / "ck"))
    assert nf > 1                      # genuinely multi-frame
    mr2 = MapReduce()
    assert mr2.load(str(tmp_path / "ck")) == 300_000


def test_mesh_dataset_checkpoints_to_host(tmp_path):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    mr = MapReduce(make_mesh(4))
    keys = np.arange(64, dtype=np.uint64) % 9
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.aggregate()
    mr.save(str(tmp_path / "m"))
    mr2 = MapReduce()                   # restores WITHOUT the mesh
    assert mr2.load(str(tmp_path / "m")) == 64


def test_script_save_load(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from gpu_mapreduce_tpu.oink.script import OinkScript

    s = OinkScript(screen=False, logfile=None)
    s.run_string("mr a\n")
    s.obj.get_mr("a").map(1, lambda i, kv, p: kv.add(1, 2))
    s.run_string(f"a save {tmp_path}/ck\n"
                 f"mr b\n"
                 f"b load {tmp_path}/ck\n")
    assert kv_pairs(s.obj.get_mr("b")) == [(1, 2)]


def test_load_missing_manifest(tmp_path):
    with pytest.raises(MRError, match="manifest"):
        MapReduce().load(str(tmp_path / "nope"))


def test_save_refuses_open_buffers(tmp_path):
    mr = MapReduce()
    kvh = mr.open()
    kvh.add(1, 2)
    with pytest.raises(MRError, match="uncompleted"):
        mr.save(str(tmp_path / "x"))
    mr.close()
    assert mr.save(str(tmp_path / "x")) == 1


def test_load_streams_into_outofcore_budget(tmp_path):
    """Restoring into an outofcore MR spills frame-by-frame — resident
    bytes stay within ~the budget, never the whole checkpoint."""
    src = MapReduce()
    keys = np.arange(400_000, dtype=np.uint64)
    src.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    src.save(str(tmp_path / "big"))

    dst = MapReduce(outofcore=1, memsize=1, maxpage=1,
                    fpath=str(tmp_path / "sp"))
    assert dst.load(str(tmp_path / "big")) == 400_000
    assert dst.kv._resident_bytes() <= 2 * (1 << 20)
    assert sum(1 for _ in dst.kv.frames()) >= 1   # frames stream back


def test_collapse_mixed_dtype_stays_exact():
    """uint64 keys above 2^53 with int64 values must NOT round through
    a float64 promotion (review r2)."""
    mr = MapReduce()
    big = (1 << 60) + 1
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.array([big], np.uint64), np.array([-1], np.int64)))
    mr.collapse(0)
    groups = {}
    mr.scan_kmv(lambda k, vs, p: groups.__setitem__(k, list(vs)))
    assert groups[0][0] == big
    assert groups[0][1] == -1


def test_example_in_checkpoint(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from gpu_mapreduce_tpu.oink.script import OinkScript

    s = OinkScript(screen=False, logfile=None)
    s.run_file("/root/repo/examples/in.checkpoint")
    a = sorted((tmp_path / "deg.original").read_text().split())
    b = sorted((tmp_path / "deg.restored").read_text().split())
    assert a == b and len(a) > 0


def test_save_double_fault_preserves_old_checkpoint(tmp_path, monkeypatch):
    """ADVICE r3: if the tmp→path rename fails AND the old→path restore
    also fails, the previous checkpoint must survive on disk (the
    cleanup used to rmtree the only remaining copy)."""
    import os

    from gpu_mapreduce_tpu.core import checkpoint

    path = str(tmp_path / "ck")
    mr = MapReduce()
    mr.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(8, dtype=np.uint64), np.ones(8, np.uint64)))
    mr.save(path)

    mr2 = MapReduce()
    mr2.map(1, lambda i, kv, p: kv.add_batch(
        np.arange(4, dtype=np.uint64), np.zeros(4, np.uint64)))

    real_rename = os.rename

    def failing_rename(src, dst):
        if dst == path:            # both the swap and the restore
            raise OSError("injected rename failure")
        return real_rename(src, dst)

    monkeypatch.setattr(checkpoint.os, "rename", failing_rename)
    with pytest.raises(MRError, match="survives"):
        mr2.save(path)
    monkeypatch.undo()

    old = [d for d in os.listdir(tmp_path) if d.startswith("ck.old.")]
    assert old, "previous checkpoint dir was deleted in the double fault"
    mr3 = MapReduce()
    mr3.load(str(tmp_path / old[0]))
    got = []
    mr3.scan_kv(lambda k, v, p: got.append(int(k)))
    assert sorted(got) == list(range(8))
