"""Standing-query micro-batch engine tests (stream/ + the serve and
OINK surfaces — doc/streaming.md).

The load-bearing goldens: incremental processing is byte-identical to
one-shot batch over the concatenated input (fuse={0,1}); a kill -9
mid-batch resumes from the last committed cursor with byte-identical
recovered state (same process, a fresh process, AND a fleet survivor
adopting a dead replica's streams); warm same-shaped micro-batches
recompile nothing (plan-cache steady state)."""

import json
import os
import signal
import subprocess
import sys
import time
from collections import Counter

import pytest

from gpu_mapreduce_tpu.core.mapreduce import MapReduce
from gpu_mapreduce_tpu.core.runtime import MRError
from gpu_mapreduce_tpu.exec.prefetch import tail_chunks
from gpu_mapreduce_tpu.oink.command import run_command
from gpu_mapreduce_tpu.serve import ServeClient, Server
from gpu_mapreduce_tpu.stream import BatchCutter, Stream, Tailer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def oracle(text: str) -> str:
    """What one-shot wordfreq over ``text`` prints as the canonical
    snapshot (sorted ``key count`` lines)."""
    c = Counter(text.split())
    return "".join(f"{k} {c[k]}\n" for k in sorted(c))


def wait_until(fn, timeout=30.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# units: tailing + cut policy
# ---------------------------------------------------------------------------

def test_tail_chunks_newline_alignment(tmp_path):
    p = str(tmp_path / "t.txt")
    with open(p, "w") as f:
        f.write("one two\nthree")            # torn trailing line
    chunks, off = tail_chunks(p, 0)
    assert b"".join(chunks) == b"one two\n"  # torn tail stays pending
    assert off == len("one two\n")
    # the newline arrives: the pending tail is consumed
    with open(p, "a") as f:
        f.write(" four\nfive\n")
    chunks, off2 = tail_chunks(p, off)
    assert b"".join(chunks) == b"three four\nfive\n"
    # nothing new: no chunks, cursor stays put
    chunks, off3 = tail_chunks(p, off2)
    assert chunks == [] and off3 == off2
    # final=True consumes an unterminated tail
    with open(p, "a") as f:
        f.write("six")
    chunks, _ = tail_chunks(p, off2, final=True)
    assert b"".join(chunks) == b"six"
    # a file that SHRANK is not append-only: loud error, no silent skew
    with open(p, "w") as f:
        f.write("tiny")
    with pytest.raises(OSError):
        tail_chunks(p, off2)


def test_tailer_directory_picks_up_new_files(tmp_path):
    d = tmp_path / "dir"
    d.mkdir()
    (d / "a.txt").write_text("a b\n")
    t = Tailer([str(d)])
    chunks, _wm = t.poll()
    assert b"".join(chunks) == b"a b\n"
    (d / "b.txt").write_text("c\n")          # born after the tailer
    chunks, _wm = t.poll()
    assert b"".join(chunks) == b"c\n"
    assert t.pending_bytes() == 0


def test_batch_cutter_triggers():
    c = BatchCutter(rows=10, nbytes=100, wait_s=5.0)
    assert not c.should_cut(0, 0, now=0.0)       # empty never cuts
    assert not c.should_cut(50, 5, now=0.0)      # under every trigger
    assert c.should_cut(50, 10, now=0.1)         # rows trigger
    c.cut_done()
    assert c.should_cut(100, 1, now=0.2)         # bytes trigger
    c.cut_done()
    assert not c.should_cut(1, 1, now=10.0)      # fresh pending
    assert c.should_cut(1, 1, now=15.0)          # ...aged past wait_s


# ---------------------------------------------------------------------------
# the incremental golden: byte-identical to one-shot, fuse={0,1}
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fuse", [0, 1])
def test_incremental_wordfreq_golden(tmp_path, fuse):
    parts = ["apple banana apple\ncherry banana\n",
             "banana date apple\n",
             "cherry cherry date elderberry\nfig\n"]
    src = str(tmp_path / "in.txt")
    s = Stream(str(tmp_path / "st"), [src],
               settings={"fuse": fuse})
    seen = ""
    for part in parts:                  # grow + drain, one micro-batch
        with open(src, "a") as f:       # per append round
            f.write(part)
        s.drain()
        seen += part
        assert s.snapshot() == oracle(seen)     # identical at EVERY step
    st = s.status()
    assert st["batches"] == len(parts)
    assert st["rows"] == sum(p.count("\n") for p in parts)
    assert st["bytes"] == len(seen.encode())
    s.close()
    # one-shot over the concatenated input agrees byte-for-byte
    one = Stream(str(tmp_path / "one"), [src], settings={"fuse": fuse})
    one.drain(final=True)
    assert one.snapshot() == oracle(seen)
    one.close()


def test_kv_parser_sum_reduce(tmp_path):
    src = tmp_path / "kv.txt"
    src.write_text("a 3\nb 2\na 5\n")
    s = Stream(str(tmp_path / "st"), [str(src)], parser="kv",
               reduce="sum")
    s.drain()
    assert s.snapshot() == "a 8\nb 2\n"
    src.write_text("a 3\nb 2\na 5\nb 10\n")      # append more
    s.drain()
    assert s.snapshot() == "a 8\nb 12\n"
    s.close()


def test_window_retire_and_merge(tmp_path):
    src = str(tmp_path / "in.txt")
    s = Stream(str(tmp_path / "st"), [src], window=2)
    batches = ["a a b\n", "b c\n", "c d d\n"]
    for part in batches:
        with open(src, "a") as f:
            f.write(part)
        s.drain()
    # only the LAST TWO batches are resident: batch 1 retired
    assert s.snapshot() == oracle(batches[1] + batches[2])
    assert s.status()["buckets"] == 2
    s.close()


def test_mr_stream_external_resident(tmp_path):
    src = tmp_path / "in.txt"
    src.write_text("x y x\n")
    mr = MapReduce()
    s = mr.stream([str(src)], dir=str(tmp_path / "st"))
    s.drain()
    assert s.snapshot() == "x 2\ny 1\n"
    # merges landed in the CALLER's dataset, via public API only
    got = {}
    mr2 = mr.copy()
    mr2.gather(1)
    mr2.sort_keys(1)
    mr2.scan_kv(lambda k, v, p: got.__setitem__(bytes(k), int(v)))
    assert got == {b"x": 2, b"y": 1}
    s.close()


def test_bad_parser_and_reduce_raise(tmp_path):
    with pytest.raises(MRError):
        Stream(str(tmp_path / "a"), [], parser="nope")
    with pytest.raises(MRError):
        Stream(str(tmp_path / "b"), [], reduce="cull")


# ---------------------------------------------------------------------------
# watermarks + lag attribution
# ---------------------------------------------------------------------------

def test_watermark_and_lag_accounting(tmp_path):
    src = str(tmp_path / "in.txt")
    with open(src, "w") as f:
        f.write("a b\n")
    old = time.time() - 50.0
    os.utime(src, (old, old))
    s = Stream(str(tmp_path / "st"), [src])
    s.drain()
    st = s.status()
    assert abs(st["watermark"] - old) < 2.0      # newest COMMITTED mtime
    assert st["lag_s"] == 0.0                    # caught up: no lag
    # new pending data: lag = now - watermark (the uncommitted tail is
    # at least that much newer than what the resident state reflects)
    with open(src, "a") as f:
        f.write("c d\n")
    st = s.status()
    assert st["pending_bytes"] == 4
    assert st["lag_s"] >= 45.0
    # ingest attribution rides the prefetch metrics satellite
    s.drain()
    st = s.status()
    assert st["lag_s"] == 0.0
    assert st["ingest"]["prefetch_wait_s"] >= 0.0
    assert "prefetch_depth" in st["ingest"]
    s.close()


# ---------------------------------------------------------------------------
# exactly-once: suspend/resume, kill -9, fleet takeover
# ---------------------------------------------------------------------------

def test_suspend_resume_roundtrip(tmp_path):
    src = str(tmp_path / "in.txt")
    with open(src, "w") as f:
        f.write("a b a\n")
    s = Stream(str(tmp_path / "st"), [src])
    s.drain()
    s.suspend()                  # no stream_close record: query stays
    assert s.poll_once(force=True) == 0          # detached handle
    with open(src, "a") as f:
        f.write("b c\n")
    s2 = Stream(str(tmp_path / "st"), [src])
    assert s2.seq == 1 and s2.status()["resumed"]
    s2.drain()
    assert s2.snapshot() == oracle("a b a\nb c\n")
    s2.close()


_KILL_CHILD = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
from gpu_mapreduce_tpu.stream import Stream
sdir, src, mode = sys.argv[1], sys.argv[2], sys.argv[3]
s = Stream(sdir, [src])
assert s.poll_once(force=True) > 0      # batch 1 commits durably
with open(src, "a") as f:
    f.write("banana elderberry banana\nfig\n")
orig = s._journal.append
def boom(rec):
    if mode == "before":                # die BEFORE the commit record
        os.kill(os.getpid(), signal.SIGKILL)
    orig(rec)                           # ...or AFTER it is durable
    os.kill(os.getpid(), signal.SIGKILL)
s._journal.append = boom
s.poll_once(force=True)                 # batch 2: dies mid-commit
raise SystemExit("unreachable: SIGKILL must have fired")
"""


@pytest.mark.parametrize("mode", ["before", "after"])
def test_kill9_exactly_once_resume(tmp_path, mode):
    """kill -9 mid-batch, then resume in a FRESH process state: the
    recovered snapshot is byte-identical to an uninterrupted run —
    a batch that died before its commit record replays in full, one
    that died after never reapplies (doc/streaming.md#exactly-once)."""
    src = str(tmp_path / "in.txt")
    part1 = "apple banana apple\ncherry\n"
    part2 = "banana elderberry banana\nfig\n"   # the child appends this
    with open(src, "w") as f:
        f.write(part1)
    sdir = str(tmp_path / "st")
    child = str(tmp_path / "child.py")
    with open(child, "w") as f:
        f.write(_KILL_CHILD.format(repo=REPO))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, child, sdir, src, mode],
                       capture_output=True, text=True, env=env,
                       timeout=240)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    s = Stream(sdir, [src])
    assert s.status()["resumed"]
    assert s.seq == (1 if mode == "before" else 2)
    s.drain(final=True)
    assert s.snapshot() == oracle(part1 + part2)
    assert s.status()["rows"] == 4               # never double-counted
    s.close()


# ---------------------------------------------------------------------------
# plan-cache steady state: warm micro-batches recompile nothing
# ---------------------------------------------------------------------------

def test_warm_stream_reuses_cached_plan(tmp_path):
    from gpu_mapreduce_tpu.plan.cache import cache_stats
    src = str(tmp_path / "in.txt")
    s = Stream(str(tmp_path / "st"), [src], settings={"fuse": 1})
    batch = "alpha beta gamma alpha\ndelta beta\n"

    def feed_one():
        with open(src, "a") as f:
            f.write(batch)                  # identical shape each time
        s.drain()

    feed_one()                              # warm-up: compiles land here
    feed_one()
    warm = cache_stats()["plan"]["misses"]
    for _ in range(3):
        feed_one()
    assert cache_stats()["plan"]["misses"] == warm, \
        "steady-state micro-batches must not recompile"
    assert s.snapshot() == oracle(batch * 5)
    s.close()


# ---------------------------------------------------------------------------
# the serve surface: /v1/streams
# ---------------------------------------------------------------------------

def test_serve_stream_http_roundtrip(tmp_path):
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "state"))
    srv.start()
    try:
        c = ServeClient.local(srv.port)
        r = c.stream_open(tenant="acme")            # feed mode
        stid = r["id"]
        assert r["state"] == "open" and r["feed"]
        c.stream_feed(stid, b"apple banana apple\ncherry\n")
        wait_until(lambda: c.stream_status(stid)["stream"]["batches"]
                   >= 1, msg="first micro-batch")
        st = c.stream_status(stid)
        assert st["tenant"] == "acme"
        assert st["stream"]["rows"] == 2
        assert st["stream"]["watermark"] > 0         # fed by commit
        assert st["stream"]["lag_s"] >= 0.0
        assert "prefetch_depth" in st["stream"]["ingest"]
        assert "prefetch_wait_s" in st["stream"]["ingest"]
        assert len(c.streams()) == 1
        assert srv.stats()["streams"]["open"] == 1
        # feeding tail-mode arguments to a CLOSED stream is a 409
        out = c.stream_close(stid)
        assert out["state"] == "closed"
        assert out["stream"]["rows"] == 2
        from gpu_mapreduce_tpu.serve.client import ServeError
        with pytest.raises(ServeError) as ei:
            c.stream_feed(stid, b"late\n")
        assert ei.value.code == 409
    finally:
        srv.shutdown()


def test_serve_stream_events_and_watch_contract(tmp_path):
    import threading
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "state"))
    srv.start()
    try:
        c = ServeClient.local(srv.port)
        stid = c.stream_open()["id"]
        got = []

        def watch():
            for ev in c.stream_events(stid, timeout=30.0):
                got.append(ev)
                if ev.get("event") == "status" and \
                        ev.get("state") in ("closed", "failed"):
                    return
        t = threading.Thread(target=watch, daemon=True)
        t.start()
        time.sleep(0.3)                 # subscription attaches first
        c.stream_feed(stid, b"x y x\n")
        wait_until(lambda: any(e.get("event") == "batch" for e in got),
                   msg="batch event on the stream")
        c.stream_close(stid)
        t.join(timeout=30)
        assert not t.is_alive()
        kinds = [e.get("event") for e in got]
        assert kinds[0] == "status"         # snapshot first
        batch = next(e for e in got if e.get("event") == "batch")
        assert batch["rows"] == 1 and batch["seq"] == 1
        assert got[-1].get("state") == "closed"   # terminal marker
    finally:
        srv.shutdown()


def test_serve_stream_validation_cap_and_budget_pin(tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv("MRTPU_SERVE_STREAMS", "1")
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "state"))
    srv.start()
    try:
        c = ServeClient.local(srv.port)
        from gpu_mapreduce_tpu.serve.client import ServeError
        with pytest.raises(ServeError) as ei:
            c.stream_open(parser="nope")
        assert ei.value.code == 400
        with pytest.raises(ServeError) as ei:
            c.stream_open(reduce="cull")
        assert ei.value.code == 400
        stid = c.stream_open()["id"]
        # the cap: a second OPEN stream is 429 + Retry-After
        with pytest.raises(ServeError) as ei:
            c.stream_open()
        assert ei.value.code == 429
        assert ei.value.retry_after is not None
        # tenant budget defaults pinned the engine's spill into the
        # stream's own scratch, not the daemon cwd
        eng = srv.streams.get(stid).engine
        assert eng.settings.get("fpath", "").startswith(
            srv.streams.stream_dir(stid))
        c.stream_close(stid)
        # closing freed the cap slot
        stid2 = c.stream_open()["id"]
        assert stid2 != stid
    finally:
        srv.shutdown()


def test_serve_stream_resumes_across_daemon_restart(tmp_path):
    state = str(tmp_path / "state")
    srv = Server(port=0, workers=1, state_dir=state)
    srv.start()
    c = ServeClient.local(srv.port)
    stid = c.stream_open()["id"]
    c.stream_feed(stid, b"x y x\n")
    wait_until(lambda: c.stream_status(stid)["stream"]["batches"] >= 1,
               msg="batch before restart")
    srv.shutdown()          # suspends the stream, no stream_close
    srv2 = Server(port=0, workers=1, state_dir=state)
    srv2.start()
    try:
        c2 = ServeClient.local(srv2.port)
        st = c2.stream_status(stid)
        assert st["state"] == "open"
        assert st["stream"]["batches"] == 1 and st["stream"]["resumed"]
        c2.stream_feed(stid, b"z z\n")
        wait_until(lambda: c2.stream_status(stid)["stream"]["batches"]
                   >= 2, msg="post-restart batch")
        out = c2.stream_close(stid)
        assert out["state"] == "closed"
        assert srv2.streams.get(stid).engine.snapshot() == \
            oracle("x y x\nz z\n")
        # a CLOSED stream stays closed on the next restart
        srv2.shutdown()
        srv3 = Server(port=0, workers=1, state_dir=state)
        srv3.start()
        assert srv3.streams.get(stid) is None
        srv3.shutdown()
    finally:
        srv2.shutdown()     # idempotent


def test_fleet_takeover_adopts_streams(tmp_path):
    """A dead replica's standing queries move to the survivor: stream
    directory copied, stream_open re-journaled under the claimant, the
    engine resumed from the last committed cursor — and the final
    snapshot is byte-identical to an uninterrupted run."""
    root = str(tmp_path / "fleet")

    def replica(rid, **kw):
        return Server(port=0, workers=1, queue_cap=8, fleet_dir=root,
                      replica_id=rid, lease_s=0.6, heartbeat_s=0.1,
                      **kw)

    a = replica("a")
    b = replica("b")
    a.start()
    b.start()
    try:
        ca = ServeClient.local(a.port)
        stid = ca.stream_open(tenant="acme")["id"]
        assert stid.startswith("a.")
        ca.stream_feed(stid, b"apple banana apple\ncherry\n")
        wait_until(lambda: ca.stream_status(stid)["stream"]["batches"]
                   >= 1, msg="batch on the original replica")
        # kill -9 emulation: heartbeat stalls, listener stops, runner
        # threads stop (a dead process has no threads), lease left on
        # disk — serve/fleet failover discipline (tests/test_fleet.py)
        a._fleet_suspended = True
        a.streams.suspend_all()
        if a._listener is not None:
            a._listener.stop()
        wait_until(lambda: b.streams.get(stid) is not None,
                   timeout=60, msg="survivor adopting the stream")
        ss = b.streams.get(stid)
        assert ss.failed_over and ss.tenant == "acme"
        wait_until(lambda: ss.engine is not None
                   and ss.engine.status()["resumed"], msg="resume")
        assert ss.engine.seq == 1        # committed state carried over
        cb = ServeClient.local(b.port)
        cb.stream_feed(stid, b"banana date\n")
        wait_until(lambda: cb.stream_status(stid)["stream"]["batches"]
                   >= 2, msg="post-takeover batch")
        out = cb.stream_close(stid)
        assert out["state"] == "closed"
        assert b.streams.get(stid).engine.snapshot() == \
            oracle("apple banana apple\ncherry\nbanana date\n")
    finally:
        b.shutdown()
        a.shutdown()


# ---------------------------------------------------------------------------
# the OINK surface
# ---------------------------------------------------------------------------

def test_oink_stream_command_family(tmp_path):
    src = str(tmp_path / "in.txt")
    with open(src, "w") as f:
        f.write("a b a\nb c\n")
    sdir = str(tmp_path / "st")
    c = run_command("stream", ["open", sdir, src], screen=False)
    assert "open" in c.result_msg
    c = run_command("stream", ["poll", sdir], screen=False)
    assert c.stream_status["rows"] == 2
    with open(src, "a") as f:
        f.write("c c d\n")
    c = run_command("stream", ["poll", sdir], screen=False)
    assert c.stream_status["rows"] == 3          # resumed + continued
    out = str(tmp_path / "snap.txt")
    run_command("stream", ["snapshot", sdir, out], screen=False)
    with open(out) as f:
        assert f.read() == oracle("a b a\nb c\nc c d\n")
    c = run_command("stream", ["status", sdir], screen=False)
    assert c.stream_status["state"] == "open"
    c = run_command("stream", ["close", sdir], screen=False)
    assert c.stream_status["state"] == "closed"
    with pytest.raises(MRError):
        run_command("stream", ["poll"], screen=False)   # usage
    with pytest.raises(MRError):
        run_command("stream", ["open", sdir], screen=False)
