"""The exact wire codec (parallel/wire.py, MRTPU_WIRE): delta-packed
keys, narrow values, tiered per-bucket caps — compressed exchanges must
be BYTE-IDENTICAL to the raw path on every surface (eager aggregate,
fused plans, gather, reshard range exchanges, chaos retries), send
strictly fewer pad bytes on skew, and report honest telemetry."""

import collections
import os

import numpy as np
import pytest

import jax

from gpu_mapreduce_tpu import MapReduce
from gpu_mapreduce_tpu.core.column import DenseColumn
from gpu_mapreduce_tpu.core.frame import KVFrame
from gpu_mapreduce_tpu.parallel import shuffle, wire
from gpu_mapreduce_tpu.parallel.mesh import make_mesh
from gpu_mapreduce_tpu.parallel.sharded import shard_frame


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8
    return make_mesh(8)


def zipf_keys(n=20000, seed=7, lim=1 << 22):
    """RMAT-hub-style skew in a u32-ish range (narrows u64→u32 on the
    wire and forces the tier ladder)."""
    rng = np.random.default_rng(seed)
    return np.minimum(rng.zipf(1.3, n), lim).astype(np.uint64)


def run_exchange(mesh, keys, vals, wire_flag, dest=("hash", None),
                 transport=1):
    os.environ["MRTPU_WIRE"] = wire_flag
    shuffle._SPEC_CACHE.clear()
    skv = shard_frame(KVFrame(DenseColumn(keys.copy()),
                              DenseColumn(vals.copy())), mesh)
    out = shuffle.exchange(skv, dest, transport=transport)
    return (np.asarray(out.key), np.asarray(out.value),
            out.counts.copy(), out.exchange_stats)


# ---------------------------------------------------------------------------
# planner units (the ci.sh quick subset: codec/tiers)
# ---------------------------------------------------------------------------

def test_codec_tier_ladder_properties():
    """plan_tiers must (a) cover the raw max bucket, (b) never exceed
    the uniform schedule's slots, (c) stay within the round bound."""
    rng = np.random.default_rng(3)
    for _ in range(50):
        P = int(rng.integers(2, 16))
        counts = rng.integers(0, 5000, (P, P))
        if rng.random() < 0.5:      # inject a hub column
            counts[:, 0] = rng.integers(2000, 60000, P)
        B, nrounds, _cap, _bmax, _nc = shuffle._plan_caps(counts)
        tiers = wire.plan_tiers(counts, B, nrounds)
        bmax = int(counts.max())
        assert sum(tiers) >= bmax, (tiers, bmax)
        assert sum(tiers) <= B * nrounds, (tiers, B, nrounds)
        assert len(tiers) <= shuffle._MAX_ROUNDS
        assert all(t >= 8 and t & (t - 1) == 0 for t in tiers)


def test_codec_pack_width_planning():
    """Pack widths from bucket ranges: narrowest exact dtype, never a
    non-narrowing one, raw for over-range or empty columns."""
    counts = np.array([[3, 2], [1, 4]])
    # stats layout [P, P, 4] u64: kmin, kmax, vmin, vmax
    stats = np.zeros((2, 2, 4), np.uint64)
    stats[:, :, 0] = 100
    stats[:, :, 1] = 100 + 200          # key range 200 → uint8
    stats[:, :, 2] = 7
    stats[:, :, 3] = 7 + (1 << 20)      # value range 2^20 → uint32

    class Col:
        def __init__(self, dt):
            self.dtype = np.dtype(dt)
            self.ndim = 1
            self.shape = (8,)
    kp, vp, (kr, vr) = wire.plan_packs(Col(np.uint64), Col(np.uint64),
                                       counts, stats, (True, True))
    assert (kp, vp) == ("uint8", "uint32") and kr == 200
    # a u32 column with a 2^20 range narrows no further than uint32 —
    # which is NOT narrower than the column: ship raw
    stats2 = np.zeros((2, 2, 4), np.uint64)
    stats2[:, :, 1] = 1 << 20           # key range 2^20 on a u32 column
    kp2, _vp2, _ = wire.plan_packs(Col(np.uint32), Col(np.uint64),
                                   counts, stats2, (True, False))
    assert kp2 is None
    # empty matrix → no evidence → raw
    kp3, vp3, _ = wire.plan_packs(Col(np.uint64), Col(np.uint64),
                                  np.zeros((2, 2), int), stats,
                                  (True, True))
    assert kp3 is None and vp3 is None


def test_codec_signed_value_roundtrip(mesh, monkeypatch):
    """Signed value columns delta-pack over their int64 bit-pattern
    stats and decode exactly — including negative bases."""
    rng = np.random.default_rng(11)
    n = 4000
    keys = rng.integers(0, 1 << 16, n).astype(np.uint64)
    vals = (rng.integers(0, 50000, n) - 40000).astype(np.int64)
    k0, v0, c0, _ = run_exchange(mesh, keys, vals, "0")
    k1, v1, c1, st = run_exchange(mesh, keys, vals, "1")
    assert np.array_equal(k0, k1) and np.array_equal(v0, v1)
    assert (c0 == c1).all()
    assert st.wire_bytes > 0 and st.wire_ratio > 1.0


# ---------------------------------------------------------------------------
# goldens: compressed == raw, byte for byte
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("transport", [1, 0])
def test_golden_zipf_exchange_byte_identical(mesh, transport):
    keys = zipf_keys()
    vals = np.arange(len(keys), dtype=np.uint64)
    k0, v0, c0, s0 = run_exchange(mesh, keys, vals, "0",
                                  transport=transport)
    k1, v1, c1, s1 = run_exchange(mesh, keys, vals, "1",
                                  transport=transport)
    assert np.array_equal(k0, k1), "compressed keys differ from raw"
    assert np.array_equal(v0, v1), "compressed values differ from raw"
    assert (c0 == c1).all()
    # the codec engaged and reported an honest ratio
    assert s1.wire_bytes > 0
    assert s1.wire_ratio == pytest.approx(
        (s1.sent_bytes + s1.pad_bytes) / s1.wire_bytes, rel=1e-3)
    assert s0.wire_bytes == 0 and s0.wire_ratio == 0.0


def test_golden_pad_tax_tiered_caps_beat_global_B(mesh):
    """The pad-tax satellite: on the zipf corpus the tier ladder must
    send STRICTLY fewer pad bytes than the raw global-B schedule, and
    the actual wire bytes must undercut the raw volume."""
    keys = zipf_keys()
    vals = np.ones(len(keys), np.uint64)
    _k0, _v0, _c0, s0 = run_exchange(mesh, keys, vals, "0")
    _k1, _v1, _c1, s1 = run_exchange(mesh, keys, vals, "1")
    assert s1.pad_bytes < s0.pad_bytes, (s1.pad_bytes, s0.pad_bytes)
    assert s1.wire_bytes < s0.sent_bytes + s0.pad_bytes
    assert s1.wire_ratio > 1.0


def test_golden_wordfreq_pipeline_eager_vs_fused(mesh, monkeypatch):
    """The full aggregate→convert→reduce pipeline (byte-keyed wordfreq
    shape) agrees across {wire on/off} × {eager/fused} — the fused
    codec program composes group/reduce on DECODED rows."""
    words = [b"w%04d" % i for i in
             np.random.default_rng(5).zipf(1.5, 4000) % 600]
    from gpu_mapreduce_tpu.ops.reduces import count

    def run(wire_flag, fuse):
        monkeypatch.setenv("MRTPU_WIRE", wire_flag)
        shuffle._SPEC_CACHE.clear()
        mr = MapReduce(mesh, fuse=fuse)
        mr.map(1, lambda i, kv, p: [kv.add(w, 1) for w in words])
        mr.aggregate()
        mr.convert()
        mr.reduce(count, batch=True)
        return sorted((bytes(k), int(v)) for fr in mr.kv.frames()
                      for k, v in fr.pairs())

    golden = run("0", 0)
    assert collections.Counter(dict(golden)) == \
        collections.Counter(words)
    assert run("1", 0) == golden
    assert run("1", 1) == golden
    assert run("0", 1) == golden


def test_golden_kmv_group_path(mesh, monkeypatch):
    """collate (the grouped ShardedKMV surface) is identical wire
    on/off — groups, sizes and multivalue runs included."""
    keys = zipf_keys(6000, seed=9, lim=1 << 14)
    vals = np.arange(6000, dtype=np.uint64)

    def grouped(wire_flag):
        monkeypatch.setenv("MRTPU_WIRE", wire_flag)
        shuffle._SPEC_CACHE.clear()
        mr = MapReduce(mesh)
        mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
        mr.collate()
        out = {}
        mr.scan_kmv(lambda k, vs, p: out.__setitem__(
            int(k), sorted(int(v) for v in vs)))
        return out

    assert grouped("1") == grouped("0")


def test_golden_reshard_n_m_n_compressed(mesh, monkeypatch):
    """N→M→N reshard through the compressed range exchange: global row
    order (and bytes) preserved exactly — the PR 7 contract must
    survive the codec."""
    monkeypatch.setenv("MRTPU_WIRE", "1")
    shuffle._SPEC_CACHE.clear()
    keys = zipf_keys(8000, seed=13)
    mr = MapReduce(mesh)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys * 5))
    mr.aggregate()

    def global_rows(fr):
        P, cap = fr.nprocs, fr.cap
        k = np.asarray(fr.key)
        v = np.asarray(fr.value)
        sel = np.concatenate(
            [np.arange(i * cap, i * cap + int(fr.counts[i]))
             for i in range(P)])
        return k[sel], v[sel]

    k0, v0 = global_rows(mr.kv.one_frame())
    mr.reshard(make_mesh(3))
    mr.reshard(make_mesh(8))
    k1, v1 = global_rows(mr.kv.one_frame())
    assert np.array_equal(k0, k1) and np.array_equal(v0, v1)


def test_chaos_golden_exchange_faults_under_wire(mesh, monkeypatch):
    """shuffle.exchange faults injected under MRTPU_WIRE=1: the ft/
    retry re-runs the WHOLE two-phase compressed exchange and the output
    stays byte-identical to the fault-free compressed run."""
    from gpu_mapreduce_tpu import ft
    monkeypatch.setenv("MRTPU_WIRE", "1")
    monkeypatch.setenv("MRTPU_DONATE", "0")   # retries need live inputs
    keys = zipf_keys(5000, seed=21)
    vals = np.arange(5000, dtype=np.uint64)

    def pipeline():
        shuffle._SPEC_CACHE.clear()
        mr = MapReduce(mesh)
        mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
        mr.aggregate()
        fr = mr.kv.one_frame()
        return np.asarray(fr.key).copy(), fr.counts.copy()

    clean_k, clean_c = pipeline()
    ft.reset()
    try:
        ft.schedule(site="shuffle.exchange", rate=1.0, seed=3,
                    max_faults=2)
        ft.set_budget("shuffle.exchange", 4)
        chaos_k, chaos_c = pipeline()
        assert ft.fault_counts().get("shuffle.exchange", 0) >= 1
        assert np.array_equal(chaos_k, clean_k)
        assert (chaos_c == clean_c).all()
    finally:
        ft.reset()


# ---------------------------------------------------------------------------
# telemetry + speculation
# ---------------------------------------------------------------------------

def test_wire_metrics_and_request_profile(mesh, monkeypatch):
    """mrtpu_exchange_bytes_total grows a {kind=wire} series and the
    request profile rolls up wire bytes + compression ratio."""
    from gpu_mapreduce_tpu.obs import metrics as obs_metrics
    from gpu_mapreduce_tpu.obs import request_scope
    from gpu_mapreduce_tpu.obs import get_tracer
    monkeypatch.setenv("MRTPU_WIRE", "1")
    obs_metrics.reset()
    get_tracer().reset()
    try:
        obs_metrics.enable_metrics(flight=False)
        keys = zipf_keys(4000, seed=2)
        shuffle._SPEC_CACHE.clear()
        with request_scope(label="wire-test") as acct:
            # through the MR op so the byte volume ALSO flows down the
            # Counters funnel into the account (profile sent/pad bytes)
            monkeypatch.setenv("MRTPU_WIRE", "1")
            mr = MapReduce(mesh)
            mr.map(1, lambda i, kv, p: kv.add_batch(
                keys, np.ones(len(keys), np.uint64)))
            mr.aggregate()
            codec_ratio = mr.last_exchange.wire_ratio
            # a RAW exchange in the same request must not inflate the
            # reported compression (its logical bytes are excluded)
            monkeypatch.setenv("MRTPU_WIRE", "0")
            mr2 = MapReduce(mesh)
            mr2.map(1, lambda i, kv, p: kv.add_batch(
                keys, np.ones(len(keys), np.uint64)))
            mr2.aggregate()
        snap = obs_metrics.snapshot()
        kinds = {s["labels"]["kind"]: s["value"] for s in
                 snap["mrtpu_exchange_bytes_total"]["samples"]}
        assert kinds.get("wire", 0) > 0
        assert kinds["sent"] > 0 and kinds["pad"] > 0
        prof = acct.profile()["exchange"]
        assert prof["wire_bytes"] > 0
        assert prof["compression_ratio"] == pytest.approx(codec_ratio,
                                                         rel=1e-3)
        assert prof["compression_ratio"] > 1.0
    finally:
        obs_metrics.reset()
        get_tracer().reset()


def test_range_reshard_feeds_exchange_metrics(mesh, monkeypatch):
    """PR 7 regression (satellite): ("range", ...) reshard exchanges
    must feed record_exchange — sent/pad/rows/rounds — exactly like
    dest-fn exchanges, and a counters-less direct exchange() call must
    still carry byte telemetry on its per-call stats."""
    from gpu_mapreduce_tpu.obs import metrics as obs_metrics
    from gpu_mapreduce_tpu.obs import get_tracer
    obs_metrics.reset()
    get_tracer().reset()
    try:
        obs_metrics.enable_metrics(flight=False)
        keys = zipf_keys(4000, seed=17)
        mr = MapReduce(mesh)
        mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
        mr.aggregate()
        before = obs_metrics.snapshot()

        def kinds(snap):
            return {s["labels"]["kind"]: s["value"] for s in
                    snap["mrtpu_exchange_bytes_total"]["samples"]}

        def count_of(snap, name):
            return sum(s["value"] for s in snap[name]["samples"])

        mr.reshard(make_mesh(4))           # the range exchange
        after = obs_metrics.snapshot()
        assert kinds(after)["sent"] > kinds(before)["sent"]
        assert kinds(after)["pad"] >= kinds(before)["pad"]
        assert count_of(after, "mrtpu_exchange_rows_total") > \
            count_of(before, "mrtpu_exchange_rows_total")
        assert count_of(after, "mrtpu_exchanges_total") > \
            count_of(before, "mrtpu_exchanges_total")

        # a direct exchange with NO counters still reports bytes
        monkeypatch.setenv("MRTPU_WIRE", "0")
        shuffle._SPEC_CACHE.clear()
        skv = shard_frame(KVFrame(DenseColumn(keys),
                                  DenseColumn(keys)), mesh)
        out = shuffle.exchange(skv, ("hash", None), counters=None)
        assert out.exchange_stats.sent_bytes > 0
        assert out.exchange_stats.pad_bytes >= 0
    finally:
        obs_metrics.reset()
        get_tracer().reset()


def test_wire_speculative_plan_reuse_and_overflow(mesh, monkeypatch):
    """The speculative-cap cache under the codec: a same-distribution
    repeat reuses the cached wire plan (phase 2 runs ONCE); a repeat
    whose key range outgrows the cached pack width re-runs at fresh
    widths — results exact either way."""
    monkeypatch.setenv("MRTPU_WIRE", "1")
    calls = []
    orig = shuffle._phase2_wire_jit

    def spy(mesh_, transport, tiers, cap_out, kpack, vpack, **kw):
        calls.append((tiers, cap_out, kpack, vpack))
        return orig(mesh_, transport, tiers, cap_out, kpack, vpack,
                    **kw)

    monkeypatch.setattr(shuffle, "_phase2_wire_jit", spy)
    shuffle._SPEC_CACHE.clear()
    rng = np.random.default_rng(23)
    n = 4096
    small = rng.integers(0, 1 << 20, n).astype(np.uint64)
    vals = np.ones(n, np.uint64)

    def xchg(keys):
        skv = shard_frame(KVFrame(DenseColumn(keys), DenseColumn(vals)),
                          mesh)
        out = shuffle.exchange(skv, ("hash", None))
        got = collections.Counter(
            int(k) for k, _ in out.to_host().pairs())
        assert got == collections.Counter(int(k) for k in keys)
        return out.exchange_stats

    xchg(small)
    assert len(calls) == 1 and calls[0][2] == "uint32"
    st = xchg(rng.permutation(small))
    assert len(calls) == 2 and st.speculative, \
        "same-range repeat must keep the speculative wire dispatch"
    wide = small.copy()
    wide[0] = np.uint64((1 << 63) + 5)     # range outgrows uint32
    st2 = xchg(wide)
    assert len(calls) >= 4 and not st2.speculative
    assert calls[-1][2] is None            # fresh plan ships raw keys
