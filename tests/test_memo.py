"""Result memoization tests (serve/memo.py + the daemon wiring): key
exactness contract, durable hits across daemon restarts and replica
death, corruption degrading to recompute (never a wrong result),
byte-exactness vs fresh recompute across fuse/wire/mesh-width, and the
journaled-intent cache GC (doc/serve.md, "Result memoization")."""

import json
import os
import time

import pytest

from gpu_mapreduce_tpu.serve import ServeClient, Server
from gpu_mapreduce_tpu.serve import memo
from gpu_mapreduce_tpu.utils.cas import cas_store, reset_store


def _integrity_count(artifact: str) -> int:
    from gpu_mapreduce_tpu.obs.metrics import get_registry
    return get_registry().counter(
        "mrtpu_integrity_failures_total", "", ("artifact",)
    ).value(artifact=artifact)


def write_corpus(path, words, repeat):
    path.write_text((" ".join(words) + " ") * repeat)
    return str(path)


def wf_script(corpus, top=3, fuse=False):
    lines = [f"variable files index {corpus}"]
    if fuse:
        lines.append("set fuse 1")
    lines.append(f"wordfreq {top} -i v_files")
    return "\n".join(lines) + "\n"


def ii_script(*files):
    return (f"variable files index {' '.join(files)}\n"
            f"invertedindex -i v_files\n")


def write_html(path, urls):
    path.write_text(" ".join(f'<a href="{u}"> text' for u in urls))
    return str(path)


@pytest.fixture
def cas_env(tmp_path, monkeypatch):
    """One isolated CAS root per test; singletons re-rooted, counters
    zeroed, plan LRU cold on entry and on exit."""
    from gpu_mapreduce_tpu.plan.cache import plan_cache
    monkeypatch.setenv("MRTPU_CAS_DIR", str(tmp_path / "cas"))
    monkeypatch.setenv("MRTPU_JIT_PERSIST", "0")
    reset_store()
    memo.reset_counts()
    plan_cache().clear()
    yield str(tmp_path / "cas")
    plan_cache().clear()
    reset_store()


def serve_one(tmp_path, name, script, **kw):
    """Run one submission through a fresh daemon; returns the result."""
    srv = Server(port=0, workers=1, queue_cap=8,
                 state_dir=str(tmp_path / name), **kw)
    srv.start()
    try:
        c = ServeClient.local(srv.port)
        return c.wait(c.submit(script=script)["id"])
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# key/manifest units
# ---------------------------------------------------------------------------

def test_memo_key_tracks_script_and_input_bytes(tmp_path, cas_env):
    corpus = write_corpus(tmp_path / "c.txt", ["a", "b"], 10)
    k1 = memo.memo_key(wf_script(corpus))
    assert k1 is not None and k1 == memo.memo_key(wf_script(corpus))
    assert memo.memo_key(wf_script(corpus, top=5)) != k1
    with open(corpus, "a") as f:
        f.write("extra ")
    assert memo.memo_key(wf_script(corpus)) != k1   # input bytes moved


def test_memo_key_excludes_perf_knobs(tmp_path, cas_env, monkeypatch):
    """The exactness contract: fuse/wire/megafuse/mesh-width change HOW
    a result is computed, never WHAT — none of them may enter the key."""
    corpus = write_corpus(tmp_path / "c.txt", ["a", "b"], 10)
    base = memo.memo_key(wf_script(corpus))
    for knob in ("MRTPU_FUSE", "MRTPU_WIRE", "MRTPU_MEGAFUSE"):
        for v in ("0", "1"):
            monkeypatch.setenv(knob, v)
            assert memo.memo_key(wf_script(corpus)) == base
        monkeypatch.delenv(knob)


def test_non_memoizable_scripts(tmp_path, cas_env):
    corpus = write_corpus(tmp_path / "c.txt", ["a"], 5)
    # nondeterministic output / side-effectful commands
    assert memo.memo_key(f"set timer 1\n{wf_script(corpus)}") is None
    assert memo.memo_key(f"set verbosity 2\n{wf_script(corpus)}") is None
    assert memo.memo_key("save foo /tmp/x\n") is None
    assert memo.memo_key("load foo /tmp/x\n") is None
    # directory input token: contents unenumerable at key time
    assert memo.memo_key(f"variable files index {tmp_path}\n"
                         f"wordfreq 3 -i v_files\n") is None
    # standing queries: a moving target, never a pure function of the
    # submission (doc/streaming.md)
    assert memo.memo_key("stream open /tmp/st in.txt\n") is None
    assert memo.memo_key("mr x\nstream poll /tmp/st\n") is None


def test_lookup_misses_when_input_grew(tmp_path, cas_env):
    """PR 20 regression: an input that GREW between store and lookup
    (append-only files under a standing query do exactly that) must
    fall through to recompute — the stat manifest stored with the
    record is re-checked before any hit is served."""
    corpus = write_corpus(tmp_path / "c.txt", ["a", "b"], 3)
    payload = wf_script(corpus)
    key = memo.memo_key(payload)
    result = {"status": "done", "output": "x\n", "files": {}}
    assert memo.store(key, result, payload=payload)
    assert memo.lookup(key) is not None
    with open(corpus, "a") as f:
        f.write("more words appended\n")
    assert memo.lookup(key) is None         # grown input: recompute
    # staleness is not corruption: the entry survives (its key still
    # matches the ORIGINAL bytes) and no integrity failure is counted
    st = memo.memo_stats()
    assert st["corrupt"] == 0 and st["entries"] == 1


def test_session_store_carries_stat_manifest(tmp_path, cas_env):
    """End-to-end: run_session's store() call passes the payload, so
    the daemon-written record carries the re-stat manifest."""
    corpus = write_corpus(tmp_path / "c.txt", ["x", "y", "z"], 4)
    payload = wf_script(corpus)
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "state"))
    srv.start()
    try:
        c = ServeClient.local(srv.port)
        sid = c.submit(script=payload)["id"]
        r = c.wait(sid, timeout=60)
        assert r["status"] == "done"
        key = memo.memo_key(payload)
        assert memo.lookup(key) is not None
        with open(corpus, "a") as f:
            f.write("grown\n")
        assert memo.lookup(key) is None
    finally:
        srv.shutdown()


def test_store_lookup_roundtrip_and_done_only(cas_env):
    result = {"status": "done", "output": "x\n", "files": {}, "mrs": {},
              "meta": {"wall_s": 0.1}}
    key = "a" * 64
    assert not memo.store(key, {**result, "status": "failed"})
    assert memo.lookup(key) is None
    assert memo.store(key, result, writer="r1")
    assert memo.lookup(key) == result
    st = memo.memo_stats()
    assert st["stores"] == 1 and st["hits"] == 1


def test_corrupt_record_reads_as_miss_and_counts(cas_env):
    result = {"status": "done", "output": "x\n", "files": {}, "mrs": {}}
    key = "b" * 64
    memo.store(key, result)
    path = memo._memo_path(key)
    raw = open(path).read().replace("x\\n", "y\\n", 1)
    with open(path, "w") as f:
        f.write(raw)
    before = _integrity_count("cas")
    assert memo.lookup(key) is None              # never the flipped bytes
    assert _integrity_count("cas") == before + 1
    assert not os.path.exists(path)              # removed: next run stores
    assert memo.memo_stats()["corrupt"] == 1


# ---------------------------------------------------------------------------
# the acceptance golden: daemon restart serves a warm hit with 0 work
# ---------------------------------------------------------------------------

def test_warm_restart_serves_hit_zero_compiles_zero_ops(tmp_path,
                                                        cas_env):
    from gpu_mapreduce_tpu.plan.cache import plan_cache
    corpus = write_corpus(tmp_path / "w.txt", ["to", "be", "or"], 40)
    script = wf_script(corpus, fuse=True)
    cold = serve_one(tmp_path, "a", script)
    assert cold["status"] == "done"
    assert cold["meta"]["memo"] == {"hit": False,
                                    "key": memo.memo_key(script)}
    # daemon restart: a NEW server instance, cold in-memory plan cache
    plan_cache().clear()
    warm = serve_one(tmp_path, "b", script)
    assert warm["status"] == "done"
    m = warm["meta"]["memo"]
    assert m["hit"] and m["key"] == cold["meta"]["memo"]["key"]
    assert m["source_wall_s"] == cold["meta"]["wall_s"]
    # zero recompiles, zero MR ops: nothing executed at all
    assert warm["meta"]["dispatches"] == 0
    assert warm["meta"]["plan_cache"]["plan"] == {"hits": 0, "misses": 0}
    # byte-exact: output, files and named MRs verbatim
    for field in ("output", "files", "mrs"):
        assert warm[field] == cold[field]


def test_plan_persist_restart_rescues_without_memo(tmp_path, cas_env,
                                                   monkeypatch):
    """Rung (a) alone: with memoization off, a restarted daemon still
    recompiles nothing — every plan digest loads from the disk tier."""
    from gpu_mapreduce_tpu.plan.cache import plan_cache
    monkeypatch.setenv("MRTPU_MEMOIZE", "0")
    corpus = write_corpus(tmp_path / "w.txt", ["to", "be", "or"], 40)
    script = wf_script(corpus, fuse=True)
    cold = serve_one(tmp_path, "a", script)
    plan_cache().clear()
    warm = serve_one(tmp_path, "b", script)
    assert warm["status"] == "done"
    assert not warm["meta"]["memo"]["hit"]       # it really re-ran
    assert warm["output"] == cold["output"]
    pc = warm["meta"]["plan_cache"]
    assert pc.get("persistent", {}).get("hits", 0) > 0
    assert pc.get("persistent", {}).get("misses", 0) == 0


def test_memo_opt_out_recomputes(tmp_path, cas_env, monkeypatch):
    corpus = write_corpus(tmp_path / "w.txt", ["x", "y"], 20)
    script = wf_script(corpus)
    serve_one(tmp_path, "a", script)
    monkeypatch.setenv("MRTPU_MEMOIZE", "0")
    again = serve_one(tmp_path, "b", script)
    assert again["status"] == "done"
    assert "memo" not in again["meta"] or not again["meta"]["memo"]["hit"]


# ---------------------------------------------------------------------------
# fleet: A computes, A dies, B serves the verified hit
# ---------------------------------------------------------------------------

def test_fleet_peer_serves_hit_after_replica_death(tmp_path,
                                                   monkeypatch):
    from gpu_mapreduce_tpu.plan.cache import plan_cache
    monkeypatch.delenv("MRTPU_CAS_DIR", raising=False)
    monkeypatch.setenv("MRTPU_JIT_PERSIST", "0")
    root = tmp_path / "fleet"
    monkeypatch.setenv("MRTPU_FLEET_DIR", str(root))
    reset_store()
    memo.reset_counts()
    plan_cache().clear()
    try:
        corpus = write_corpus(tmp_path / "w.txt", ["p", "q", "r"], 30)
        script = wf_script(corpus, fuse=True)
        a = Server(port=0, workers=1, fleet_dir=str(root),
                   replica_id="a", lease_s=0.6, heartbeat_s=0.1)
        a.start()
        try:
            ca = ServeClient.local(a.port)
            cold = ca.wait(ca.submit(script=script)["id"])
            assert cold["status"] == "done"
        finally:
            a.shutdown()                         # replica A is gone
        plan_cache().clear()                     # B starts cold
        b = Server(port=0, workers=1, fleet_dir=str(root),
                   replica_id="b", lease_s=0.6, heartbeat_s=0.1)
        b.start()
        try:
            cb = ServeClient.local(b.port)
            warm = cb.wait(cb.submit(script=script)["id"])
            assert warm["status"] == "done"
            assert warm["meta"]["memo"]["hit"]
            assert warm["meta"]["dispatches"] == 0
            assert warm["output"] == cold["output"]
            assert warm["files"] == cold["files"]
        finally:
            b.shutdown()
    finally:
        plan_cache().clear()
        reset_store()


# ---------------------------------------------------------------------------
# corruption degrades to recompute — never a wrong result
# ---------------------------------------------------------------------------

def test_fleet_bit_flip_falls_back_to_recompute(tmp_path, cas_env):
    corpus = write_corpus(tmp_path / "w.txt", ["m", "n", "o"], 30)
    script = wf_script(corpus)
    cold = serve_one(tmp_path, "a", script)
    key = cold["meta"]["memo"]["key"]
    path = memo._memo_path(key)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF                   # bit-flip the record
    with open(path, "wb") as f:
        f.write(raw)
    before = _integrity_count("cas")
    again = serve_one(tmp_path, "b", script)
    assert again["status"] == "done"
    assert not again["meta"]["memo"]["hit"]      # verified → recomputed
    assert again["output"] == cold["output"]     # and still exact
    assert _integrity_count("cas") == before + 1
    # the recompute re-stored a good record: third time hits again
    third = serve_one(tmp_path, "c", script)
    assert third["meta"]["memo"]["hit"]
    assert third["output"] == cold["output"]


# ---------------------------------------------------------------------------
# byte-exactness across the excluded knobs (wordfreq + invertedindex)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_memo_exactness_across_fuse_wire_mesh(tmp_path, cas_env,
                                              monkeypatch):
    """The contract the key exclusions rest on: every knob combination
    recomputes the SAME bytes, so serving a memoized result under a
    different fuse/wire/mesh-width state is indistinguishable from
    recomputing — and a hit is in fact served across the change."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    # distinct per-word counts: top-N tie order is not part of the
    # determinism contract, so the fixture must not depend on it
    corpus = str(tmp_path / "w.txt")
    with open(corpus, "w") as f:
        f.write(("aa " * 4 + "bb " * 3 + "cc " * 2 + "dd ") * 25)
    html = [write_html(tmp_path / "h0.html",
                       ["http://x.com/a", "http://y.com/b"]),
            write_html(tmp_path / "h1.html", ["http://x.com/a"])]
    for label, script in (("wf", wf_script(corpus)),
                          ("ii", ii_script(*html))):
        memoized = serve_one(tmp_path, f"{label}-base", script)
        assert memoized["status"] == "done"
        assert not memoized["meta"]["memo"]["hit"]
        combos = [("0", "0", 1), ("0", "1", 1), ("1", "0", 1),
                  ("1", "1", 1), ("1", "1", 2)]
        for i, (fuse, wire, width) in enumerate(combos):
            monkeypatch.setenv("MRTPU_FUSE", fuse)
            monkeypatch.setenv("MRTPU_WIRE", wire)
            comm = make_mesh(width) if width > 1 else None
            # fresh recompute (memo off): byte-identical results
            monkeypatch.setenv("MRTPU_MEMOIZE", "0")
            fresh = serve_one(tmp_path, f"{label}-f{i}", script,
                              comm=comm)
            assert fresh["status"] == "done"
            assert fresh["output"] == memoized["output"]
            assert fresh["files"] == memoized["files"]
            # memo on: the knob change does not mask the hit
            monkeypatch.setenv("MRTPU_MEMOIZE", "1")
            hit = serve_one(tmp_path, f"{label}-h{i}", script,
                            comm=comm)
            assert hit["meta"]["memo"]["hit"]
            assert hit["output"] == memoized["output"]
        for knob in ("MRTPU_FUSE", "MRTPU_WIRE", "MRTPU_MEMOIZE"):
            monkeypatch.delenv(knob, raising=False)


# ---------------------------------------------------------------------------
# cache GC: TTL sweep with journaled intents, kill -9 replay
# ---------------------------------------------------------------------------

def test_memo_ttl_sweep_journals_intent(tmp_path, cas_env, monkeypatch):
    from gpu_mapreduce_tpu.ft.journal import read_journal
    monkeypatch.setenv("MRTPU_MEMO_TTL", "1")
    monkeypatch.setenv("MRTPU_CAS_GRACE", "1")
    corpus = write_corpus(tmp_path / "w.txt", ["s", "t"], 20)
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "st"))
    srv.start()
    try:
        c = ServeClient.local(srv.port)
        res = c.wait(c.submit(script=wf_script(corpus))["id"])
        key = res["meta"]["memo"]["key"]
        path = memo._memo_path(key)
        assert os.path.exists(path)
        os.utime(path, (time.time() - 3600, time.time() - 3600))
        assert srv._gc_once() >= 1
        assert not os.path.exists(path)          # swept
        kinds = [r["kind"] for r in read_journal(srv.state_dir)]
        assert "memo_gc" in kinds                # intent preceded delete
        assert srv.stats()["cache"]["gc"]["swept"] >= 1
    finally:
        srv.shutdown()


def test_restart_finishes_interrupted_cache_gc(tmp_path, cas_env):
    """Kill -9 between the intent record and the delete: the restarted
    daemon finishes both sweep halves idempotently (refcounts by
    hardlink count can never go negative, replay or not)."""
    from gpu_mapreduce_tpu.ft.journal import Journal
    state = str(tmp_path / "st")
    memo.store("c" * 64, {"status": "done", "output": "old\n",
                          "files": {}, "mrs": {}})
    dorp = cas_store().put_bytes(b"orphaned chunk")
    keep = cas_store().put_bytes(b"kept chunk")
    dest = tmp_path / "ref.bin"
    assert cas_store().materialize(keep, str(dest))  # externally linked
    j = Journal(state, script_mode=True)
    j.append({"kind": "memo_gc", "keys": ["c" * 64]})
    # the intent names BOTH chunks — but `keep` gained a reference
    # before the crash, so replay must spare it
    j.append({"kind": "cas_gc", "digests": [dorp, keep]})
    j.close()
    srv = Server(port=0, workers=1, state_dir=state)
    srv.start()                                  # _recover replays
    try:
        assert memo.lookup("c" * 64) is None
        assert not cas_store().contains(dorp)
        assert cas_store().contains(keep)
        assert cas_store().refcount(keep) == 1
        # a second restart replays the same intents: still a no-op
        srv2 = Server(port=0, workers=1, state_dir=state)
        srv2.start()
        srv2.shutdown()
        assert cas_store().contains(keep)
    finally:
        srv.shutdown()


def test_memo_hit_journals_cache_hit_record(tmp_path, cas_env):
    from gpu_mapreduce_tpu.ft.journal import read_journal
    corpus = write_corpus(tmp_path / "w.txt", ["u", "v"], 20)
    script = wf_script(corpus)
    serve_one(tmp_path, "a", script)
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "b"))
    srv.start()
    try:
        c = ServeClient.local(srv.port)
        res = c.wait(c.submit(script=script)["id"])
        assert res["meta"]["memo"]["hit"]
        recs = read_journal(srv.state_dir)
        hits = [r for r in recs if r["kind"] == "cache_hit"]
        assert len(hits) == 1
        assert hits[0]["key"] == res["meta"]["memo"]["key"]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# stats surfaces
# ---------------------------------------------------------------------------

def test_daemon_stats_cache_section(tmp_path, cas_env):
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "st"))
    srv.start()
    try:
        doc = srv.stats()["cache"]
        assert doc["cas"]["enabled"] == 1
        assert set(doc["memo"]) >= {"enabled", "entries", "hits",
                                    "misses", "stores", "corrupt"}
        assert set(doc["gc"]) == {"memo_ttl_s", "cas_grace_s", "swept"}
    finally:
        srv.shutdown()


def test_plan_cache_stats_has_persistent_section(cas_env):
    from gpu_mapreduce_tpu.plan.cache import cache_stats
    st = cache_stats()
    assert "persistent" in st
    assert st["persistent"]["enabled"] == 1
