"""Fault-tolerance subsystem (ft/): deterministic injection, retry/
backoff policy, quarantine-skip, MRError wrapping of raw input-file
OSErrors, and journaled kill-and-resume.

The chaos golden contract mirrors exec/: any seeded fault schedule the
retry budget absorbs must leave output BYTE-IDENTICAL to the fault-free
run — on wordfreq (host and mesh), an invertedindex-shaped postings
pipeline, the external sort's spill sites, and checkpoint.save."""

import collections
import io
import json
import os

import numpy as np
import pytest

from gpu_mapreduce_tpu import ft
from gpu_mapreduce_tpu.core.mapreduce import MapReduce
from gpu_mapreduce_tpu.core.runtime import MRError
from gpu_mapreduce_tpu.parallel.mesh import make_mesh
from gpu_mapreduce_tpu.utils.io import read_words
import gpu_mapreduce_tpu.ft.retry as ftr


@pytest.fixture(autouse=True)
def ft_state(monkeypatch):
    """Reset injection schedules, budgets, counters and journals around
    every test, and record backoff sleeps instead of sleeping."""
    slept = []
    monkeypatch.setattr(ftr, "_sleep", slept.append)
    ft.reset()
    yield slept
    ft.reset()


@pytest.fixture
def word_corpus(tmp_path):
    import random
    r = random.Random(41)
    vocab = [f"word{i:03d}".encode() for i in range(120)]
    files, oracle = [], collections.Counter()
    for i in range(6):
        ws = r.choices(vocab, k=300 + 40 * i)
        oracle.update(ws)
        p = tmp_path / f"c{i}.txt"
        p.write_bytes(b" ".join(ws))
        files.append(str(p))
    return files, oracle


# ---------------------------------------------------------------------------
# injection mechanics
# ---------------------------------------------------------------------------

def test_parse_faults_env_format():
    specs = ft.parse_faults(
        "seed=7;site=ingest.read;rate=0.05;kind=oserror"
        "|site=spill.read,spill.write;rate=1.0;n=2;after=3")
    assert len(specs) == 3
    assert specs[0].site == "ingest.read" and specs[0].seed == 7
    assert specs[0].rate == 0.05 and specs[0].kind == "oserror"
    assert {s.site for s in specs[1:]} == {"spill.read", "spill.write"}
    assert specs[1].max_faults == 2 and specs[1].after == 3
    with pytest.raises(ValueError):
        ft.parse_faults("site=nonexistent.site")
    with pytest.raises(ValueError):
        ft.parse_faults("kind=meteor")
    with pytest.raises(ValueError):
        ft.parse_faults("bogus")


def test_fault_point_deterministic_and_counted():
    """Same seed → the same probes fault, independent of wall time."""
    def verdicts():
        ft.reset()
        ft.schedule(site="spill.read", rate=0.3, seed=99)
        out = []
        for _ in range(40):
            try:
                ft.fault_point("spill.read")
                out.append(False)
            except OSError:
                out.append(True)
        return out

    a, b = verdicts(), verdicts()
    assert a == b
    assert any(a) and not all(a)
    assert ft.fault_counts()["spill.read"] == sum(b)


def test_disarmed_is_noop():
    for site in ft.SITES:
        ft.fault_point(site)          # never raises
    assert ft.fault_counts() == {}
    assert ft.retries_snapshot() == {}


def test_injected_exception_kinds():
    from gpu_mapreduce_tpu.ft.inject import (InjectedFatal,
                                             InjectedOSError,
                                             InjectedTimeout)
    for kind, cls in (("oserror", InjectedOSError),
                      ("timeout", InjectedTimeout),
                      ("fatal", InjectedFatal)):
        ft.reset()
        ft.schedule(site="spill.write", rate=1.0, kind=kind)
        with pytest.raises(cls) as ei:
            ft.fault_point("spill.write")
        assert ei.value.ft_site == "spill.write"
    assert ft.classify("spill.write", InjectedOSError()) == "transient"
    assert ft.classify("spill.write", InjectedFatal()) == "fatal"


def test_env_arming_via_mapreduce_constructor(monkeypatch):
    monkeypatch.setenv("MRTPU_FAULTS",
                       "seed=3;site=spill.read;rate=1.0;n=1")
    monkeypatch.setenv("MRTPU_RETRY", "spill.read=4")
    MapReduce()            # construction applies the env knobs
    assert ft.budget("spill.read") == 4
    with pytest.raises(OSError):
        ft.fault_point("spill.read")
    ft.fault_point("spill.read")      # n=1: second probe passes
    monkeypatch.setenv("MRTPU_FAULTS", "")
    monkeypatch.setenv("MRTPU_RETRY", "")
    MapReduce()            # change applies again
    assert ft.budget("spill.read") == 0


def test_malformed_env_warns_and_disarms(monkeypatch, capsys):
    monkeypatch.setenv("MRTPU_FAULTS", "site=nope.nope")
    monkeypatch.setenv("MRTPU_RETRY", "spill.read=lots")
    MapReduce()
    err = capsys.readouterr().err
    assert "MRTPU_FAULTS ignored" in err
    assert "MRTPU_RETRY ignored" in err
    ft.fault_point("spill.read")      # disarmed, not crashed


# ---------------------------------------------------------------------------
# retry engine
# ---------------------------------------------------------------------------

def test_retry_recovers_and_counts(ft_state):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    ft.set_budget("spill.read", 5)
    assert ft.retry_call("spill.read", flaky) == "ok"
    snap = ft.retries_snapshot()
    assert snap[("spill.read", "retry")] == 2
    assert snap[("spill.read", "recovered")] == 1
    assert len(ft_state) == 2          # one backoff sleep per retry


def test_retry_exhausted_raises_mrerror_naming_site():
    ft.set_budget("spill.write", 2)

    def always():
        raise OSError("disk flaking")

    with pytest.raises(MRError) as ei:
        ft.retry_call("spill.write", always, detail="/spool/run7")
    msg = str(ei.value)
    assert "spill.write" in msg and "3 attempts" in msg
    assert "/spool/run7" in msg and "disk flaking" in msg
    assert isinstance(ei.value.__cause__, OSError)
    assert ft.retries_snapshot()[("spill.write", "exhausted")] == 1


def test_fatal_errors_never_retry(ft_state):
    ft.set_budget("ingest.read", 5)

    def poison():
        raise ValueError("bad data, retry cannot help")

    with pytest.raises(ValueError):
        ft.retry_call("ingest.read", poison)
    assert ft_state == []              # no backoff sleeps happened
    assert ft.retries_snapshot()[("ingest.read", "fatal")] == 1
    # a deterministically-missing file is fatal too
    assert ft.classify("ingest.read", FileNotFoundError()) == "fatal"


def test_backoff_is_exponential_capped_and_jittered(ft_state, monkeypatch):
    monkeypatch.setenv("MRTPU_RETRY_BACKOFF", "0.1")
    monkeypatch.setenv("MRTPU_RETRY_BACKOFF_MAX", "0.5")
    ft.set_budget("spill.read", 6)

    def always():
        raise OSError("x")

    with pytest.raises(MRError):
        ft.retry_call("spill.read", always)
    delays = list(ft_state)
    assert len(delays) == 6
    # jitter scales [0.5, 1.0) of base*2^k, capped at 0.5
    for k, d in enumerate(delays):
        nominal = min(0.5, 0.1 * 2 ** k)
        assert 0.5 * nominal <= d < nominal
    assert delays[2] > delays[0]       # growth is visible through jitter
    assert max(delays) < 0.5           # the cap held


def test_budget_zero_is_passthrough():
    """Disarmed sites add no wrapper frames and no error rewriting."""
    with pytest.raises(OSError) as ei:
        ft.retry_call("spill.read", lambda: (_ for _ in ()).throw(
            OSError("raw")))
    assert type(ei.value) is OSError   # not MRError-wrapped
    assert ft.retries_snapshot() == {}


# ---------------------------------------------------------------------------
# chaos goldens: faulted-with-retry output == fault-free output
# ---------------------------------------------------------------------------

def _arm_all_sites(budget=3, max_faults=1):
    for site in ft.SITES:
        ft.schedule(site=site, rate=1.0, seed=11, max_faults=max_faults)
        ft.set_budget(site, budget)


def _wordfreq_pairs(files, comm, ckpt_dir):
    """Mesh/host wordfreq through the raw op algebra + a checkpoint
    round-trip, so ingest.*, shuffle.exchange AND checkpoint.save all
    probe; returns (sorted pairs, reloaded pairs)."""
    from gpu_mapreduce_tpu.ops.reduces import count
    mr = MapReduce(comm)

    def fileread(itask, fname, kv, ptr):
        with open(fname, "rb") as f:
            ws = read_words(f.read())
        kv.add_batch(ws, np.ones(len(ws), np.int64))

    mr.map_files(list(files), fileread)
    mr.collate()
    mr.reduce(count, batch=True)
    pairs = sorted((bytes(k), int(v)) for fr in mr.kv.frames()
                   for k, v in fr.pairs())
    mr.save(ckpt_dir)
    mr2 = MapReduce(comm)
    mr2.load(ckpt_dir)
    pairs2 = sorted((bytes(k), int(v)) for fr in mr2.kv.frames()
                    for k, v in fr.pairs())
    return pairs, pairs2


def test_chaos_golden_mesh_wordfreq_all_sites(word_corpus, tmp_path):
    """THE acceptance golden: a seeded schedule injecting ≥1 fault at
    every reachable registered site leaves mesh wordfreq output (and
    its checkpoint round-trip) byte-identical to the fault-free run,
    with retries visible in mr.stats()["ft"] and, when armed, in
    mrtpu_retries_total."""
    from gpu_mapreduce_tpu.obs import get_tracer, metrics as obs_metrics
    files, oracle = word_corpus
    clean, clean2 = _wordfreq_pairs(files, make_mesh(4),
                                    str(tmp_path / "ck.clean"))
    assert collections.Counter(dict(clean)) == oracle
    assert clean == clean2

    obs_metrics.reset()
    get_tracer().reset()
    try:
        obs_metrics.enable_metrics(flight=False)
        _arm_all_sites(budget=3, max_faults=1)
        chaos, chaos2 = _wordfreq_pairs(files, make_mesh(4),
                                        str(tmp_path / "ck.chaos"))
        assert chaos == clean            # byte-identical under faults
        assert chaos2 == clean
        faults = ft.fault_counts()
        for site in ("ingest.read", "ingest.tokenize",
                     "shuffle.exchange", "checkpoint.save"):
            assert faults.get(site, 0) >= 1, (site, faults)
        st = MapReduce(make_mesh(4)).stats()["ft"]
        assert st["faults_injected"] == faults
        assert st["retries"]["shuffle.exchange"]["recovered"] >= 1
        # the registry counted the same retries (collector pull)
        snap = obs_metrics.snapshot()
        got = {(s["labels"]["site"], s["labels"]["outcome"])
               for s in snap["mrtpu_retries_total"]["samples"]}
        assert ("shuffle.exchange", "recovered") in got
        assert {s["labels"]["site"]
                for s in snap["mrtpu_faults_injected_total"]["samples"]
                } >= {"ingest.read", "shuffle.exchange"}
    finally:
        obs_metrics.reset()
        get_tracer().reset()


def test_chaos_golden_serial_wordfreq(word_corpus, tmp_path):
    # budget must cover the COMBINED per-task faults of ingest.read and
    # ingest.tokenize (both probe inside the same retried task slot)
    files, oracle = word_corpus
    clean, _ = _wordfreq_pairs(files, None, str(tmp_path / "s.clean"))
    _arm_all_sites(budget=5, max_faults=2)
    chaos, chaos2 = _wordfreq_pairs(files, None,
                                    str(tmp_path / "s.chaos"))
    assert chaos == clean == chaos2
    assert ft.fault_counts().get("ingest.read", 0) >= 1


def test_chaos_golden_invertedindex_postings(word_corpus):
    """Composed invertedindex shape: (word, doc) postings counts over a
    mesh, byte-identical under injection at the ingest+shuffle sites."""
    files, _ = word_corpus

    def postings(comm):
        mr = MapReduce(comm)

        def emit(itask, fname, kv, ptr):
            with open(fname, "rb") as f:
                ws = list(dict.fromkeys(read_words(f.read())))
            kv.add_batch(ws, np.full(len(ws), itask, np.int64))

        mr.map_files(list(files), emit)
        mr.collate()

        def fold(key, vals, kv, ptr):
            kv.add(key, len(vals))

        mr.reduce(fold)
        return sorted((bytes(k), int(v)) for fr in mr.kv.frames()
                      for k, v in fr.pairs())

    clean = postings(make_mesh(4))
    _arm_all_sites(budget=3, max_faults=1)
    assert postings(make_mesh(4)) == clean
    assert ft.fault_counts().get("shuffle.exchange", 0) >= 1


N_SPILL_ROWS = 3 * (1 << 20) // 16      # ~3 pages of 16 B rows, memsize=1


def test_chaos_golden_external_sort_spill_sites(tmp_path, rng):
    """spill.write + spill.read fault under retry: the external sort's
    run files are immutable/atomic, so retried writes and block reads
    reproduce the identical sorted stream."""
    def sort_rows(tag, rng_):
        mr = MapReduce(outofcore=1, memsize=1, maxpage=1,
                       fpath=str(tmp_path / tag))
        keys = rng_.integers(0, 1 << 40, N_SPILL_ROWS).astype(np.uint64)
        vals = np.arange(len(keys), dtype=np.uint64)
        step = len(keys) // 5
        mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                                 vals[s:s + step])
                                    for s in range(0, len(keys), step)])
        mr.sort_keys(1)
        return [(int(k), int(v)) for fr in mr.kv.frames()
                for k, v in fr.pairs()]

    clean = sort_rows("clean", rng)
    for site in ("spill.write", "spill.read"):
        ft.schedule(site=site, rate=1.0, seed=5, max_faults=2)
        ft.set_budget(site, 3)
    chaos = sort_rows("chaos", np.random.default_rng(12345))
    assert chaos == clean
    faults = ft.fault_counts()
    assert faults["spill.write"] >= 1 and faults["spill.read"] >= 1
    snap = ft.retries_snapshot()
    assert snap[("spill.write", "recovered")] >= 1
    assert snap[("spill.read", "recovered")] >= 1


def test_chaos_exhausted_budget_fails_with_mrerror(word_corpus):
    """More faults than budget: the run dies with the ft MRError (the
    flight-recorder trigger), not a raw injected exception."""
    files, _ = word_corpus
    ft.schedule(site="ingest.read", rate=1.0, seed=2, max_faults=10)
    ft.set_budget("ingest.read", 1)
    mr = MapReduce(make_mesh(4))
    with pytest.raises(MRError, match="ingest.read retry budget "
                                      "exhausted"):
        mr.map_files(list(files), lambda i, f, kv, p: kv.add(b"x", 1))


# ---------------------------------------------------------------------------
# satellite: raw OSError from a map input wraps as MRError (file/task)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("comm_factory", [lambda: None,
                                          lambda: make_mesh(4)],
                         ids=["serial", "mesh"])
def test_unreadable_input_wraps_mrerror(word_corpus, comm_factory):
    files, _ = word_corpus
    bad = files[2]

    def fileread(itask, fname, kv, ptr):
        if fname == bad:
            raise OSError(5, "Input/output error", fname)
        with open(fname, "rb") as f:
            ws = read_words(f.read())
        kv.add_batch(ws, np.ones(len(ws), np.int64))

    mr = MapReduce(comm_factory())
    with pytest.raises(MRError) as ei:
        mr.map_files(list(files), fileread)
    msg = str(ei.value)
    assert bad in msg and "task" in msg
    assert isinstance(ei.value.__cause__, OSError)


def test_vanished_file_on_mesh_chunk_path_wraps_mrerror(word_corpus,
                                                        monkeypatch):
    """A file that disappears between findfiles and the byte balance
    must surface as MRError naming the file, not a raw getsize
    OSError — on the mesh chunk path."""
    files, _ = word_corpus
    import gpu_mapreduce_tpu.parallel.ingest as ing
    real = os.path.getsize

    def flaky_getsize(p):
        if p == files[1]:
            raise OSError(2, "No such file or directory", p)
        return real(p)

    monkeypatch.setattr(ing.os.path, "getsize", flaky_getsize)
    mr = MapReduce(make_mesh(4))
    with pytest.raises(MRError, match="unreadable"):
        mr.map_file_str(16, list(files), 0, 0, b" ", 32,
                        lambda i, c, kv, p: kv.add(b"x", 1))


# ---------------------------------------------------------------------------
# onfault policy: skip-with-quarantine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("comm_factory", [lambda: None,
                                          lambda: make_mesh(4)],
                         ids=["serial", "mesh"])
def test_quarantine_skip_accounting(word_corpus, comm_factory):
    """onfault=skip: a poisoned input quarantines (with a record naming
    site/task/file) and the run completes on the remaining inputs."""
    files, oracle = word_corpus
    poisoned = files[3]
    with open(poisoned, "rb") as f:
        poisoned_words = collections.Counter(read_words(f.read()))

    def fileread(itask, fname, kv, ptr):
        if fname == poisoned:
            raise ValueError("corrupt encoding")
        with open(fname, "rb") as f:
            ws = read_words(f.read())
        kv.add_batch(ws, np.ones(len(ws), np.int64))

    mr = MapReduce(comm_factory(), onfault="skip")
    n = mr.map_files(list(files), fileread)
    want = oracle - poisoned_words
    assert n == sum(want.values())
    got = collections.Counter()
    for fr in mr.kv.frames():
        for k, v in fr.pairs():
            got[bytes(k)] += 1
    assert got == want
    q = ft.quarantine_snapshot()
    assert q["count"] == 1
    rec = q["records"][0]
    assert rec["file"] == poisoned and "ValueError" in rec["error"]
    assert mr.stats()["ft"]["quarantined"]["count"] == 1


def test_onfault_retry_default_budget_then_skip_vs_fail():
    """onfault=retry grants a default ingest budget even with
    MRTPU_RETRY unset; onfault validation rejects unknown values."""
    ft.schedule(site="ingest.read", rate=1.0, seed=1, max_faults=2)
    mr = MapReduce(onfault="retry")
    n = mr.map(3, lambda i, kv, p: kv.add(i, i))
    assert n == 3                      # two faults absorbed by retries
    assert ft.retries_snapshot()[("ingest.read", "recovered")] >= 1
    with pytest.raises(MRError, match="onfault"):
        MapReduce(onfault="explode")


def test_quarantine_after_exhausted_retries(word_corpus):
    """onfault=skip composes with a budget: the input retries first,
    quarantines only when the budget is spent."""
    files, oracle = word_corpus
    ft.schedule(site="ingest.tokenize", rate=1.0, seed=4)
    ft.set_budget("ingest.tokenize", 1)
    mr = MapReduce(onfault="skip")

    def fileread(itask, fname, kv, ptr):
        with open(fname, "rb") as f:
            ws = read_words(f.read())
        kv.add_batch(ws, np.ones(len(ws), np.int64))

    n = mr.map_files(list(files), fileread)
    # every task's two attempts both faulted → everything quarantined
    assert n == 0
    q = ft.quarantine_snapshot()
    assert q["count"] == len(files)
    assert q["by_site"] == {"ingest.tokenize": len(files)}
    assert ft.retries_snapshot()[("ingest.tokenize", "retry")] == \
        len(files)


def test_injected_fatal_kills_through_onfault_skip(word_corpus):
    """The kill switch must kill: onfault=skip quarantines per-input
    failures, never the InjectedFatal the resume runbook relies on."""
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    files, _ = word_corpus
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, max_faults=1)
    mr = MapReduce(onfault="skip")
    with pytest.raises(InjectedFatal):
        mr.map_files(list(files), lambda i, f, kv, p: kv.add(b"x", 1))
    assert ft.quarantine_snapshot()["count"] == 0


# ---------------------------------------------------------------------------
# journal + kill-and-resume
# ---------------------------------------------------------------------------

def test_failed_optional_checkpoint_never_kills_the_run(tmp_path,
                                                        monkeypatch):
    """A transient OSError during an auto-checkpoint (no retry budget
    armed) skips the round and retries at the next trigger — the
    journaled run it protects keeps going."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft import journal as ftj
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "jk")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    calls = {"n": 0}
    import gpu_mapreduce_tpu.core.checkpoint as ckpt
    real = ckpt.save

    def flaky_save(mr, path):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(28, "No space left on device")
        return real(mr, path)

    monkeypatch.setattr(ckpt, "save", flaky_save)
    o1, o2 = str(tmp_path / "f1"), str(tmp_path / "f2")
    OinkScript(screen=False).run_string(_script(d1, d2, o1, o2))
    assert os.path.exists(o1) and os.path.exists(o2)
    kinds = [r["kind"] for r in ft.read_journal(jdir)]
    # round 1 failed (no record, partial dir dropped); round 2 landed
    assert kinds.count("ckpt") == 1
    assert not [d for d in os.listdir(jdir)
                if d == "ckpt-00001"]      # partial set cleaned up

def test_journal_op_records_and_auto_checkpoint(tmp_path, monkeypatch):
    """MRTPU_JOURNAL alone arms the programmatic journal (via the
    MapReduce constructor, like every other ft env knob)."""
    monkeypatch.setenv("MRTPU_JOURNAL", str(tmp_path / "j"))
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "2")
    mr = MapReduce()
    keys = np.arange(100, dtype=np.uint64) % 7
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.collate()
    mr.reduce(lambda k, vs, kv, p: kv.add(k, len(vs)))
    mr.sort_keys(1)
    recs = ft.read_journal(str(tmp_path / "j"))
    kinds = [r["kind"] for r in recs]
    ops = [r["op"] for r in recs if r["kind"] == "op"]
    assert "map" in ops and "convert" in ops and "sort_keys" in ops
    assert "auto_ckpt" in kinds        # every-2-ops trigger fired
    ck = ft.latest_checkpoint(str(tmp_path / "j"))
    assert ck is not None
    mr2 = MapReduce()
    mr2.load(ck)
    assert mr2.kv is not None or mr2.kmv is not None


def _write_script_inputs(tmp_path):
    d1 = tmp_path / "w1.txt"
    d1.write_bytes(b"apple banana apple cherry banana apple " * 30)
    d2 = tmp_path / "w2.txt"
    d2.write_bytes(b"dog cat dog bird cat dog emu " * 25)
    return str(d1), str(d2)


def _script(d1, d2, o1, o2):
    return (f"mr a\n"
            f"wordfreq 3 -i {d1} -o {o1} NULL\n"
            f"wordfreq 3 -i {d2} -o {o2} NULL\n")


def test_kill_and_resume_reproduces_identical_output(tmp_path,
                                                     monkeypatch):
    """Crash-at-any-point safety: a fatal injected fault kills the
    script after its first command checkpointed; ft.resume replays the
    journal from the last durable checkpoint and the final outputs are
    byte-identical to a fault-free run.  (Resume reads ONLY disk state
    — journal + checkpoints — which is what makes the in-process
    'kill' equivalent to kill -9.)"""
    from gpu_mapreduce_tpu.oink import OinkScript
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "journal")

    # fault-free reference
    c1, c2 = str(tmp_path / "o1.clean"), str(tmp_path / "o2.clean")
    OinkScript(screen=False).run_string(_script(d1, d2, c1, c2))

    # journaled run killed during command 2 (probe 2 of ingest.read)
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    k1, k2 = str(tmp_path / "o1.kill"), str(tmp_path / "o2.kill")
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    with pytest.raises(InjectedFatal):
        OinkScript(screen=False).run_string(_script(d1, d2, k1, k2))
    assert os.path.exists(k1) and not os.path.exists(k2)
    kinds = [r["kind"] for r in ft.read_journal(jdir)]
    assert kinds.count("ckpt") >= 1 and "begin" in kinds

    # resume with faults disarmed: only the un-checkpointed tail reruns
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    s = ft.resume(jdir)
    assert open(k2).read() == open(c2).read()
    assert open(k1).read() == open(c1).read()
    assert "a" in s.obj.named          # the `mr a` builtin re-ran
    # the resumed run journaled into the same dir (resumable again)
    kinds = [r["kind"] for r in ft.read_journal(jdir)]
    assert "resume" in kinds
    assert kinds.count("ckpt") >= 2


def test_resume_without_checkpoint_replays_from_scratch(tmp_path,
                                                        monkeypatch):
    """A crash before the first checkpoint resumes by replaying the
    whole script (nothing durable to restore)."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "journal0")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "5")
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, max_faults=1)
    o1, o2 = str(tmp_path / "p1"), str(tmp_path / "p2")
    with pytest.raises(InjectedFatal):
        OinkScript(screen=False).run_string(_script(d1, d2, o1, o2))
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    ft.resume(jdir)
    c1, c2 = str(tmp_path / "q1"), str(tmp_path / "q2")
    OinkScript(screen=False).run_string(_script(d1, d2, c1, c2))
    assert open(o1).read() == open(c1).read()
    assert open(o2).read() == open(c2).read()


def test_oink_resume_builtin(tmp_path, monkeypatch):
    """The script-level entry point: `resume <dir>` inside a fresh
    interpreter replays the journal (the operator runbook path)."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "jr")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    o1, o2 = str(tmp_path / "r1"), str(tmp_path / "r2")
    with pytest.raises(InjectedFatal):
        OinkScript(screen=False).run_string(_script(d1, d2, o1, o2))
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    s = OinkScript(screen=False)
    s.run_string(f"resume {jdir}\n")
    assert os.path.exists(o2)
    with pytest.raises(MRError):
        s.one("resume")                # arity check


def test_resume_replays_named_mr_from_skipped_command(tmp_path,
                                                      monkeypatch):
    """A named-MR command (`freq print`) whose MR was registered by a
    SKIPPED command's -o must replay: the skip counter counts any
    non-builtin word, and the restore recreates the name."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "jn")
    script = (f"wordfreq 3 -i {d1} -o NULL freq\n"
              f"freq stats 0\n"
              f"shell mkdir {tmp_path}/mkd\n"
              f"wordfreq 3 -i {d2} -o {tmp_path}/n2 NULL\n")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    with pytest.raises(InjectedFatal):
        OinkScript(screen=False).run_string(script)
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    s = ft.resume(jdir)
    assert "freq" in s.obj.named           # restored from the ckpt
    assert os.path.exists(str(tmp_path / "n2"))


def test_spill_only_chaos_keeps_ingest_fast_path():
    """Arming non-ingest sites must not flip the ingest paths into
    their buffered/materializing mode (the lazy-chunk property)."""
    from gpu_mapreduce_tpu.ft.retry import ingest_active
    ft.schedule(site="spill.write", rate=0.01)
    assert not ingest_active("fail")
    ft.schedule(site="ingest.read", rate=0.01)
    assert ingest_active("fail")


def test_unbudgeted_transient_error_not_reported_as_exhausted(
        word_corpus):
    """Injection armed, MRTPU_RETRY unset: a transient map-input error
    propagates as the plain wrapped MRError — never as a 'retry budget
    exhausted' claim about a policy that was never enabled."""
    files, _ = word_corpus
    ft.schedule(site="ingest.tokenize", rate=1.0, max_faults=1)
    mr = MapReduce()
    with pytest.raises(MRError) as ei:
        mr.map_files(list(files), lambda i, f, kv, p: kv.add(b"x", 1))
    assert "exhausted" not in str(ei.value)
    assert all(o != "exhausted" for _, o in ft.retries_snapshot())


def test_resume_missing_journal_raises():
    with pytest.raises(MRError, match="no journal"):
        ft.resume("/nonexistent/journal/dir")


def test_resume_with_journal_env_still_set(tmp_path, monkeypatch):
    """The runbook footgun: resuming WITHOUT unsetting MRTPU_JOURNAL
    (same dir) must not write a bogus begin for the one-line resume
    script — begin is lazy, so the journal's real begin stays the
    latest and the resume replays the original script, resumably."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "je")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    o1, o2 = str(tmp_path / "e1"), str(tmp_path / "e2")
    with pytest.raises(InjectedFatal):
        OinkScript(screen=False).run_string(_script(d1, d2, o1, o2))
    ft.clear_faults()
    # env var STILL SET, same dir — what an operator actually types
    s = OinkScript(screen=False)
    s.run_string(f"resume {jdir}\n")
    assert os.path.exists(o2)
    begins = [r for r in ft.read_journal(jdir) if r["kind"] == "begin"]
    assert len(begins) == 1            # no bogus resume-script begin
    assert begins[0]["lines"][0].strip() != f"resume {jdir}"


def test_injected_checkpoint_fault_never_kills_journaled_run(
        tmp_path, monkeypatch):
    """Any-kind injected fault at checkpoint.save with no budget: the
    OPTIONAL auto-checkpoint round is skipped, the run survives."""
    from gpu_mapreduce_tpu.oink import OinkScript
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "jc")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    ft.schedule(site="checkpoint.save", kind="runtime", rate=1.0,
                max_faults=1)
    o1, o2 = str(tmp_path / "c1"), str(tmp_path / "c2")
    OinkScript(screen=False).run_string(_script(d1, d2, o1, o2))
    assert os.path.exists(o1) and os.path.exists(o2)
    kinds = [r["kind"] for r in ft.read_journal(jdir)]
    assert kinds.count("ckpt") == 1    # round 1 skipped, round 2 landed


def test_quarantine_skip_at_discovery_and_balance_time(word_corpus,
                                                       monkeypatch):
    """A file failing at findfiles/balance_by_bytes gets the same
    onfault=skip disposition as a task-time failure — which stage
    notices must not decide whether the run survives."""
    files, oracle = word_corpus
    import gpu_mapreduce_tpu.parallel.ingest as ing
    real = os.path.getsize
    bad = files[1]
    with open(bad, "rb") as f:
        bad_words = collections.Counter(read_words(f.read()))
    monkeypatch.setattr(
        ing.os.path, "getsize",
        lambda p: (_ for _ in ()).throw(OSError(5, "I/O error", p))
        if p == bad else real(p))

    def fileread(itask, fname, kv, ptr):
        with open(fname, "rb") as f:
            ws = read_words(f.read())
        kv.add_batch(ws, np.ones(len(ws), np.int64))

    mr = MapReduce(make_mesh(4), onfault="skip")
    n = mr.map_files(list(files), fileread)
    assert n == sum((oracle - bad_words).values())
    q = ft.quarantine_snapshot()
    assert q["count"] == 1 and q["records"][0]["file"] == bad
    # discovery of a wholly-missing path quarantines too
    ft.reset()
    mr = MapReduce(onfault="skip")
    n = mr.map_files(list(files) + ["/nonexistent/ghost.txt"], fileread)
    assert n == sum(oracle.values())
    assert ft.quarantine_snapshot()["records"][0]["file"] == \
        "/nonexistent/ghost.txt"


def test_second_script_run_resumes_with_per_script_numbering(
        tmp_path, monkeypatch):
    """One interpreter running two scripts: command numbering restarts
    at each begin, so a crash in script 2 resumes script 2's commands
    (not an over-skipped ghost of script 1's)."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "j2s")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    o = {k: str(tmp_path / k) for k in ("a1", "a2", "b1", "b2")}
    script2 = (f"mr b\n"
               f"wordfreq 3 -i {d1} -o {o['b1']} NULL\n"
               f"wordfreq 3 -i {d2} -o {o['b2']} NULL\n")
    s = OinkScript(screen=False)
    s.run_string(_script(d1, d2, o["a1"], o["a2"]))     # script 1 OK
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    with pytest.raises(InjectedFatal):                  # script 2 dies
        s.run_string(script2)
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    r = ft.resume(jdir)
    assert os.path.exists(o["b2"])
    assert open(o["b2"]).read() == open(o["a2"]).read()
    assert "b" in r.obj.named


def test_new_interpreter_does_not_close_live_script_journal(
        tmp_path, monkeypatch):
    """Constructing a second OinkScript (env armed) must not close the
    journal a live first interpreter still appends to."""
    from gpu_mapreduce_tpu.oink import OinkScript
    d1, d2 = _write_script_inputs(tmp_path)
    monkeypatch.setenv("MRTPU_JOURNAL", str(tmp_path / "jl"))
    s1 = OinkScript(screen=False)
    OinkScript(screen=False)       # would close s1's journal if buggy
    s1.run_string(_script(d1, d2, str(tmp_path / "l1"),
                          str(tmp_path / "l2")))   # appends fine
    assert os.path.exists(str(tmp_path / "l2"))


def test_ckpt_gc_keeps_fresh_low_numbered_dirs(tmp_path, monkeypatch):
    """begin() restarts per-script numbering, so a re-run in the same
    journal dir writes LOW-numbered ckpt dirs; GC must keep them (by
    mtime) over the previous run's stale high-numbered ones — resume
    points at the fresh one."""
    from gpu_mapreduce_tpu.oink import OinkScript
    from gpu_mapreduce_tpu.ft.inject import InjectedFatal
    d1, d2 = _write_script_inputs(tmp_path)
    jdir = str(tmp_path / "jgc")
    monkeypatch.setenv("MRTPU_JOURNAL", jdir)
    monkeypatch.setenv("MRTPU_CKPT_EVERY", "1")
    o = str(tmp_path / "gc")
    # script 1: THREE commands → ckpt-00001..3 (keep=2 leaves 2 and 3)
    s = OinkScript(screen=False)
    s.run_string(f"mr a\n"
                 f"wordfreq 3 -i {d1} -o {o}.a NULL\n"
                 f"wordfreq 3 -i {d2} -o {o}.b NULL\n"
                 f"wordfreq 3 -i {d1} -o {o}.c NULL\n")
    # script 2 (same dir): crash after command 1 — its single fresh
    # ckpt-00001 must survive GC despite sorting below the stale
    # ckpt-00002/3 dirs left by script 1
    ft.schedule(site="ingest.read", kind="fatal", rate=1.0, after=1,
                max_faults=1)
    with pytest.raises(InjectedFatal):
        s.run_string(f"mr b\n"
                     f"wordfreq 3 -i {d1} -o {o}.d NULL\n"
                     f"wordfreq 3 -i {d2} -o {o}.e NULL\n"
                     f"wordfreq 3 -i {d1} -o {o}.f NULL\n")
    ft.reset()
    monkeypatch.delenv("MRTPU_JOURNAL")
    r = ft.resume(jdir)                 # must load the FRESH checkpoint
    assert os.path.exists(f"{o}.f")
    assert "b" in r.obj.named


def test_unknown_retry_site_rejected(monkeypatch, capsys):
    """A typo'd MRTPU_RETRY site must warn loudly, never silently
    disarm the protection the operator thinks is on."""
    with pytest.raises(ValueError, match="unknown retry site"):
        ft.set_budget("ingest.raed", 3)
    with pytest.raises(ValueError):
        ft.parse_retry("ingest.raed=3")
    monkeypatch.setenv("MRTPU_RETRY", "ingest.raed=3")
    MapReduce()
    assert "MRTPU_RETRY ignored" in capsys.readouterr().err


def test_programmatic_budget_survives_env_respec(monkeypatch):
    ft.set_budget("spill.read", 3)
    monkeypatch.setenv("MRTPU_RETRY", "ingest.read=2")
    MapReduce()
    assert ft.budget("spill.read") == 3    # programmatic survives
    assert ft.budget("ingest.read") == 2
    monkeypatch.setenv("MRTPU_RETRY", "")
    MapReduce()
    assert ft.budget("spill.read") == 3    # env respec drops env only
    assert ft.budget("ingest.read") == 0


# ---------------------------------------------------------------------------
# stats surface
# ---------------------------------------------------------------------------

def test_stats_ft_section_shape():
    st = MapReduce().stats()["ft"]
    assert set(st) == {"retries", "faults_injected", "quarantined",
                       "budgets", "journal"}
    assert st["journal"] is None
    ft.set_budget("spill.read", 2)
    st = MapReduce().stats()["ft"]
    assert st["budgets"] == {"spill.read": 2}


# ---------------------------------------------------------------------------
# ENOSPC mid-append (ISSUE 15 satellite): the journal tears, never lies
# ---------------------------------------------------------------------------

class _EnospcFile:
    """File wrapper that writes a PARTIAL line then raises ENOSPC on
    the first record-sized write — the torn-tail shape a full disk
    actually produces (some bytes land, the rest don't, no newline)."""

    def __init__(self, f, after_writes=0):
        self._f = f
        self._skip = after_writes
        self.fired = False

    def write(self, s):
        if not self.fired and self._skip == 0:
            self.fired = True
            self._f.write(s[: max(3, len(s) // 3)])
            import errno
            raise OSError(errno.ENOSPC, "No space left on device")
        self._skip = max(0, self._skip - 1)
        return self._f.write(s)

    def __getattr__(self, name):
        return getattr(self._f, name)


def test_journal_enospc_mid_append_torn_tail_quarantined(tmp_path):
    """ENOSPC raised mid-``Journal.append``: existing records stay
    readable past the torn tail, the torn record is NOT half-replayed
    after restart, and the serve disk monitor latches degraded on the
    raised error."""
    from gpu_mapreduce_tpu.ft import journal as J
    from gpu_mapreduce_tpu.serve.overload import DiskMonitor

    jdir = str(tmp_path / "jd")
    j = J.Journal(jdir, script_mode=True)
    j.begin(["cmd a", "cmd b", "cmd c"], "t")
    j.cmd_done("cmd a")

    j._f = _EnospcFile(j._f)
    with pytest.raises(OSError) as ei:
        j.cmd_done("cmd b")
    assert j._f.fired

    # the serve tier's pressure monitor latches on exactly this error
    dm = DiskMonitor([jdir], floor_mb=0)
    assert dm.note_error(ei.value) is True
    assert dm.degraded and "ENOSPC" in (dm.check() or "")

    j.close()

    # past the torn tail every durable record still reads; the torn
    # cmd record was never durable, so it must be ABSENT (not merged,
    # not half-parsed) — records never lead their facts
    recs = J.read_journal(jdir)
    kinds = [(r.get("kind"), r.get("seq")) for r in recs]
    assert ("begin", None) == (recs[0]["kind"], recs[0].get("seq", None))
    assert ("cmd", 1) in kinds
    assert ("cmd", 2) not in kinds

    # restart: the reopened journal seals the tear and keeps appending;
    # the replay plan counts only the durable command
    j2 = J.Journal(jdir, script_mode=True)
    j2.cmd_seq = 1
    j2.cmd_done("cmd b")          # the retry lands cleanly after seal
    j2.close()
    recs = J.read_journal(jdir)
    kinds = [(r.get("kind"), r.get("seq")) for r in recs]
    assert kinds.count(("cmd", 2)) == 1
    plan = J.plan_resume(jdir)
    assert plan["cmds_done"] == 2 and plan["skip"] == 0
