"""Golden equivalence + safety tests for the async execution layer
(gpu_mapreduce_tpu/exec/): ingest prefetch, background spill with its
durability barrier, and device-buffer donation.

The overlap contract is "faster, byte-identical": every knob
(MRTPU_PREFETCH / MRTPU_SPILL_BG / MRTPU_DONATE) toggled on vs off must
produce bit-identical datasets, a background-writer crash must surface
as the original error (never as a read of a torn run), and the prefetch
pipeline must preserve source order under any scheduling."""

import collections
import os
import threading
import time

import numpy as np
import pytest

from gpu_mapreduce_tpu.core.mapreduce import MapReduce
from gpu_mapreduce_tpu.parallel.mesh import make_mesh
from gpu_mapreduce_tpu.utils.io import read_words
from gpu_mapreduce_tpu import exec as mrexec


@pytest.fixture(autouse=True)
def _fresh_exec_stats():
    mrexec.reset_stats()
    yield
    mrexec.reset_stats()


# ---------------------------------------------------------------------------
# prefetch_iter mechanics
# ---------------------------------------------------------------------------

def test_prefetch_preserves_order_and_bounds_lookahead():
    """Items arrive in source order and the producer never runs more
    than depth+1 items ahead of the consumer (backpressure)."""
    produced = []
    consumed = []
    max_ahead = [0]

    def src():
        for i in range(40):
            produced.append(i)
            max_ahead[0] = max(max_ahead[0],
                               len(produced) - len(consumed))
            yield i

    for item in mrexec.prefetch_iter(src(), depth=2, path="t.order"):
        time.sleep(0.002)          # slow consumer: producer races ahead
        consumed.append(item)
    assert consumed == list(range(40))
    # depth slots in the queue + 1 in the producer's hand + 1 the
    # consumer holds
    assert max_ahead[0] <= 2 + 2, max_ahead[0]


def test_prefetch_threaded_production():
    """The producer really runs on its own thread (overlap exists)."""
    tids = set()

    def src():
        for i in range(5):
            tids.add(threading.get_ident())
            yield i

    out = list(mrexec.prefetch_iter(src(), depth=1, path="t.thread"))
    assert out == list(range(5))
    assert tids == {t for t in tids if t != threading.get_ident()}
    st = mrexec.exec_stats()["overlap"]["t.thread"]
    assert st["items"] == 5


def test_prefetch_zero_depth_is_passthrough():
    tids = set()

    def src():
        for i in range(5):
            tids.add(threading.get_ident())
            yield i

    out = list(mrexec.prefetch_iter(src(), depth=0, path="t.zero"))
    assert out == list(range(5))
    assert tids == {threading.get_ident()}          # no thread
    assert "t.zero" not in mrexec.exec_stats()["overlap"]


def test_prefetch_propagates_producer_error():
    def src():
        yield 1
        yield 2
        raise RuntimeError("reader died")

    got = []
    with pytest.raises(RuntimeError, match="reader died"):
        for x in mrexec.prefetch_iter(src(), depth=2, path="t.err"):
            got.append(x)
    assert got == [1, 2]


def test_prefetch_early_consumer_exit_stops_producer():
    state = {"produced": 0}

    def src():
        for i in range(10_000):
            state["produced"] += 1
            yield i

    it = mrexec.prefetch_iter(src(), depth=1, path="t.break")
    for x in it:
        if x == 3:
            break
    it.close()
    assert state["produced"] < 100    # stopped promptly, not drained


# ---------------------------------------------------------------------------
# golden equivalence: prefetch on/off
# ---------------------------------------------------------------------------

@pytest.fixture
def word_corpus(tmp_path):
    import random
    r = random.Random(31)
    vocab = [f"tok{i:04d}".encode() for i in range(300)]
    files, oracle = [], collections.Counter()
    for i in range(9):
        ws = r.choices(vocab, k=700 + 90 * i)
        oracle.update(ws)
        p = tmp_path / f"c{i}.txt"
        p.write_bytes(b" ".join(ws))
        files.append(str(p))
    return files, oracle


def _ingest_chunks(files, comm, monkeypatch, prefetch: int):
    monkeypatch.setenv("MRTPU_PREFETCH", str(prefetch))
    mr = MapReduce(comm)

    def tokenize(itask, chunk, kv, ptr):
        ws = read_words(chunk)
        kv.add_batch(ws, np.ones(len(ws), np.int64))

    n = mr.map_file_str(32, list(files), 0, 0, b" ", 32, tokenize)
    return n, mr.last_ingest, sorted(mr.kv.one_frame().to_host().pairs())


def test_golden_mesh_chunk_ingest_prefetch_on_off(word_corpus,
                                                  monkeypatch):
    """map_file_str over an 8-shard mesh: MRTPU_PREFETCH=0 vs 3 must be
    byte-identical — same pair multiset, same per-shard row counts, same
    task numbering (pair order)."""
    files, oracle = word_corpus
    n0, ing0, pairs0 = _ingest_chunks(files, make_mesh(8), monkeypatch, 0)
    n3, ing3, pairs3 = _ingest_chunks(files, make_mesh(8), monkeypatch, 3)
    assert n0 == n3 == sum(oracle.values())
    assert ing0["mode"] == ing3["mode"] == "mesh"
    assert ing0["rows_per_shard"] == ing3["rows_per_shard"]
    assert ing0["chunks_per_shard"] == ing3["chunks_per_shard"]
    assert pairs0 == pairs3
    assert collections.Counter(k for k, _ in pairs3) == oracle
    st = mrexec.exec_stats()["overlap"]
    assert st["ingest.chunks"]["items"] >= 8     # the pipeline ran


def test_golden_mesh_file_ingest_prefetch_on_off(word_corpus,
                                                 monkeypatch):
    """map_files (per-file sinks) golden under prefetch, mesh path."""
    from gpu_mapreduce_tpu.oink.kernels import read_words as rw_file
    files, oracle = word_corpus

    def run(prefetch):
        monkeypatch.setenv("MRTPU_PREFETCH", str(prefetch))
        mr = MapReduce(make_mesh(8))
        n = mr.map_files(list(files), rw_file)
        return n, mr.last_ingest, sorted(mr.kv.one_frame()
                                         .to_host().pairs())

    n0, ing0, p0 = run(0)
    n2, ing2, p2 = run(2)
    assert n0 == n2 == sum(oracle.values())
    assert ing0["mode"] == ing2["mode"] == "mesh"
    assert ing0["rows_per_shard"] == ing2["rows_per_shard"]
    assert p0 == p2


def test_golden_serial_chunk_ingest_prefetch_on_off(word_corpus,
                                                    monkeypatch):
    """The serial _map_chunks path (host backend): pair ORDER matters
    (task order is the output order) and must survive prefetch."""
    files, oracle = word_corpus

    def run(prefetch):
        monkeypatch.setenv("MRTPU_PREFETCH", str(prefetch))
        mr = MapReduce()
        out = []

        def tokenize(itask, chunk, kv, ptr):
            for w in read_words(chunk):
                kv.add(w, 1)
                out.append((itask, w))

        n = mr.map_file_str(16, list(files), 0, 0, b" ", 32, tokenize)
        return n, out, [p for fr in mr.kv.frames() for p in fr.pairs()]

    n0, order0, pairs0 = run(0)
    n2, order2, pairs2 = run(2)
    assert n0 == n2
    assert order0 == order2          # identical task payloads + order
    assert pairs0 == pairs2
    assert collections.Counter(k for k, _ in pairs0) == oracle


def test_prefetch_unshardable_fallback_golden(tmp_path, monkeypatch):
    """A mid-stream Unshardable (an add_frame payload, which per-shard
    ingest cannot assemble) must replay every sink into the host KV in
    task order — identical with the pipeline on and off."""
    from gpu_mapreduce_tpu.core.dataset import as_column
    from gpu_mapreduce_tpu.core.frame import KVFrame
    files = []
    for i in range(8):
        p = tmp_path / f"m{i}.txt"
        p.write_bytes(b"alpha beta gamma " * (i + 1))
        files.append(str(p))

    def run(prefetch):
        monkeypatch.setenv("MRTPU_PREFETCH", str(prefetch))
        mr = MapReduce(make_mesh(8))

        def mixed(itask, chunk, kv, ptr):
            ws = read_words(chunk)
            if itask % 3 == 2:   # every third chunk hands a pre-built
                kv.add_frame(KVFrame(   # frame → Unshardable mid-stream
                    as_column(ws), as_column(np.ones(len(ws), np.int64))))
            else:
                kv.add_batch(ws, np.ones(len(ws), np.int64))

        n = mr.map_file_str(16, files, 0, 0, b" ", 16, mixed)
        return n, mr.last_ingest["mode"], \
            [p for fr in mr.kv.frames() for p in fr.pairs()]

    n0, mode0, pairs0 = run(0)
    n2, mode2, pairs2 = run(2)
    assert mode0 == mode2 == "host"
    assert n0 == n2
    assert pairs0 == pairs2          # replay order = task order, both


# ---------------------------------------------------------------------------
# background spill: golden + durability barrier + crash safety
# ---------------------------------------------------------------------------

N_SPILL_ROWS = 5 * (1 << 20) // 16   # ~5 pages of 16 B rows, memsize=1


def _external_sort(tmp_path, monkeypatch, rng, bg: int):
    monkeypatch.setenv("MRTPU_SPILL_BG", str(bg))
    mr = MapReduce(outofcore=1, memsize=1, maxpage=1,
                   fpath=str(tmp_path / f"spill{bg}"))
    keys = rng.integers(0, 1 << 40, N_SPILL_ROWS).astype(np.uint64)
    vals = np.arange(len(keys), dtype=np.uint64)
    step = len(keys) // 6
    mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                             vals[s:s + step])
                                for s in range(0, len(keys), step)])
    mr.sort_keys(1)
    out = [(int(k), int(v)) for fr in mr.kv.frames()
           for k, v in fr.pairs()]
    return out


def test_golden_background_spill_on_off(tmp_path, monkeypatch, rng):
    """External sort through the spill cascade: background writer on vs
    off must produce the identical sorted stream."""
    eager = _external_sort(tmp_path, monkeypatch, rng, bg=0)
    rng2 = np.random.default_rng(12345)     # same stream as `rng`
    overlapped = _external_sort(tmp_path, monkeypatch, rng2, bg=1)
    assert eager == overlapped
    assert eager == sorted(eager)
    st = mrexec.exec_stats()["overlap"]
    assert st["spill"]["items"] >= 2        # the writer thread ran


def test_spill_durability_barrier_with_slow_writer(tmp_path, monkeypatch,
                                                   rng):
    """A deliberately slow background writer must never let the merge
    read a run early: the reader blocks at the barrier and the output is
    still exactly sorted."""
    from gpu_mapreduce_tpu.exec import spill as spill_mod
    orig = spill_mod.atomic_save

    def slow_save(path, arr, allow_pickle=False):
        time.sleep(0.05)
        orig(path, arr, allow_pickle)

    monkeypatch.setattr(spill_mod, "atomic_save", slow_save)
    out = _external_sort(tmp_path, monkeypatch, rng, bg=1)
    assert out == sorted(out)
    st = mrexec.exec_stats()["overlap"]["spill"]
    assert st["wait_s"] > 0                 # the barrier actually held


def test_crash_during_background_spill_never_reads_torn_run(
        tmp_path, monkeypatch, rng):
    """A writer crash mid-file must surface as the ORIGINAL error at the
    durability barrier — never as a numpy parse of a torn .npy — and
    must leave no torn file under a final run name."""
    from gpu_mapreduce_tpu.core import external as ext
    calls = {"n": 0}
    orig = ext._save_col

    def dying_save(col, path):
        calls["n"] += 1
        if calls["n"] == 4:   # crash mid-write of the 2nd run's file
            with open(path + ".tmp", "wb") as f:
                f.write(b"\x93NUMPY-half-a-header")   # torn tmp bytes
            raise OSError("disk gone")
        orig(col, path)

    monkeypatch.setattr(ext, "_save_col", dying_save)
    monkeypatch.setenv("MRTPU_SPILL_BG", "1")
    spill_dir = tmp_path / "crash"
    mr = MapReduce(outofcore=1, memsize=1, maxpage=1,
                   fpath=str(spill_dir))
    keys = rng.integers(0, 1 << 40, N_SPILL_ROWS).astype(np.uint64)
    step = len(keys) // 6
    mr.map(1, lambda i, kv, p: [kv.add_batch(keys[s:s + step],
                                             keys[s:s + step])
                                for s in range(0, len(keys), step)])
    with pytest.raises(Exception, match="disk gone"):
        mr.sort_keys(1)
    # nothing torn survives under a FINAL run name: every remaining
    # sortrun .npy parses, the torn bytes only ever lived in a .tmp
    for name in os.listdir(spill_dir):
        if "sortrun" in name and name.endswith(".npy"):
            np.load(os.path.join(spill_dir, name), allow_pickle=True)


def test_atomic_save_leaves_no_final_on_crash(tmp_path):
    """atomic_save's contract directly: an interrupted write leaves only
    the tmp sibling, never a readable-but-wrong final path."""
    from gpu_mapreduce_tpu.exec.spill import atomic_save
    path = str(tmp_path / "run.k.npy")
    arr = np.arange(1000)
    atomic_save(path, arr)
    np.testing.assert_array_equal(np.load(path), arr)
    # an object array with allow_pickle=False dies INSIDE np.save, i.e.
    # mid-write: the final path must never appear
    path2 = str(tmp_path / "run.v.npy")
    with pytest.raises(ValueError):
        atomic_save(path2, np.array([b"a", 1], object),
                    allow_pickle=False)
    assert not os.path.exists(path2)
    assert os.path.exists(path2 + ".tmp")    # only the torn tmp remains


# ---------------------------------------------------------------------------
# donation: golden + buffers actually donated
# ---------------------------------------------------------------------------

def _pipeline(comm, monkeypatch, donate: int, fuse: int = 0):
    from gpu_mapreduce_tpu.ops.reduces import count
    monkeypatch.setenv("MRTPU_DONATE", str(donate))
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 500, 20_000).astype(np.uint64)
    vals = np.ones(len(keys), np.int64)
    mr = MapReduce(comm, fuse=fuse)
    mr.kv = mr._new_kv()
    mr.kv.add_batch(keys, vals)
    mr.kv.complete()
    mr.aggregate()
    mr.convert()
    n = int(mr.reduce(count, batch=True))
    fr = mr.kv.one_frame().to_host()
    return n, sorted(zip(np.asarray(fr.key.data).tolist(),
                         np.asarray(fr.value.data).tolist()))


def test_golden_donation_on_off_eager(monkeypatch):
    n0, p0 = _pipeline(make_mesh(8), monkeypatch, donate=0)
    n1, p1 = _pipeline(make_mesh(8), monkeypatch, donate=1)
    assert n0 == n1 == 500
    assert p0 == p1


def test_golden_donation_on_off_fused(monkeypatch):
    """The fused plan tier with donation on must match eager-no-donation
    bit for bit (composes the plan/ golden contract with exec/)."""
    n0, p0 = _pipeline(make_mesh(8), monkeypatch, donate=0, fuse=0)
    n1, p1 = _pipeline(make_mesh(8), monkeypatch, donate=1, fuse=1)
    assert n0 == n1
    assert p0 == p1


def test_exchange_donates_dead_input_buffers(monkeypatch):
    """With MRTPU_DONATE=1 the exchange's input dataset buffers are
    actually DELETED (aliased away) — the residency win exists; with =0
    they survive (the golden escape hatch)."""
    from gpu_mapreduce_tpu.core.frame import KVFrame
    from gpu_mapreduce_tpu.core.column import DenseColumn
    from gpu_mapreduce_tpu.parallel import shuffle
    from gpu_mapreduce_tpu.parallel.sharded import shard_frame

    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1 << 30, 4096).astype(np.uint64)
    vals = np.arange(len(keys), dtype=np.uint64)
    oracle = sorted(zip(keys.tolist(), vals.tolist()))

    monkeypatch.setenv("MRTPU_DONATE", "0")
    skv = shard_frame(KVFrame(DenseColumn(keys), DenseColumn(vals)),
                      make_mesh(8))
    out = shuffle.exchange(skv, ("hash", None))
    assert not skv.key.is_deleted()
    got = sorted((int(k), int(v)) for k, v in out.to_host().pairs())
    assert got == oracle

    monkeypatch.setenv("MRTPU_DONATE", "1")
    skv = shard_frame(KVFrame(DenseColumn(keys), DenseColumn(vals)),
                      make_mesh(8))
    out = shuffle.exchange(skv, ("hash", None))
    assert skv.key.is_deleted() and skv.value.is_deleted()
    got = sorted((int(k), int(v)) for k, v in out.to_host().pairs())
    assert got == oracle


def test_speculative_phase2_never_donates(monkeypatch):
    """Two same-shape exchanges: the second takes the speculative path,
    whose phase-2 MUST keep its inputs alive (a failed speculation
    re-runs phase 2 on them).  The skew flip then exercises exactly that
    re-run — with donation on throughout, output stays correct."""
    from gpu_mapreduce_tpu.core.frame import KVFrame
    from gpu_mapreduce_tpu.core.column import DenseColumn
    from gpu_mapreduce_tpu.parallel import shuffle
    from gpu_mapreduce_tpu.parallel.sharded import shard_frame

    monkeypatch.setenv("MRTPU_DONATE", "1")
    shuffle._SPEC_CACHE.clear()
    mesh = make_mesh(8)
    rng = np.random.default_rng(11)
    n = 4096
    uni = rng.integers(0, 1 << 40, n).astype(np.uint64)
    vals = np.arange(n, dtype=np.uint64)

    def xchg(keys):
        skv = shard_frame(KVFrame(DenseColumn(keys), DenseColumn(vals)),
                          mesh)
        out = shuffle.exchange(skv, ("hash", None))
        got = sorted((int(k), int(v)) for k, v in out.to_host().pairs())
        assert got == sorted(zip(keys.tolist(), vals.tolist()))
        return out

    xchg(uni)                                   # cold
    out = xchg(rng.permutation(uni))            # speculative hit
    assert out.exchange_stats.speculative
    hub = uni.copy()
    hub[: n * 3 // 4] = hub[0]                  # overflow: spec re-runs
    out = xchg(hub)
    assert not out.exchange_stats.speculative


def test_donation_never_warns_unusable(monkeypatch):
    """The library only donates provably-aliasable buffers, so jax's
    'Some donated buffers were not usable' warning must never fire —
    including the count-reduce case whose value output is 1-D int64
    while the input values are narrow uint8 (the non-aliasable side is
    simply not donated)."""
    import warnings as _warnings
    from gpu_mapreduce_tpu.ops.reduces import count
    monkeypatch.setenv("MRTPU_DONATE", "1")
    n = 12347                      # odd size: fresh shapes, fresh jits
    keys = (np.arange(n, dtype=np.uint64) * 7) % 300
    with _warnings.catch_warnings(record=True) as w:
        _warnings.simplefilter("always")
        for fuse in (0, 1):
            for vdtype in (np.uint8, np.int64):
                mr = MapReduce(make_mesh(8), fuse=fuse)
                mr.kv = mr._new_kv()
                mr.kv.add_batch(keys, np.ones(n, vdtype))
                mr.kv.complete()
                mr.aggregate()
                mr.convert()
                assert int(mr.reduce(count, batch=True)) == 300
    bad = [x for x in w if "donated buffers" in str(x.message)]
    assert not bad, [str(x.message) for x in bad]


def test_copy_then_aggregate_never_corrupts_sibling(monkeypatch):
    """add_kv/copy() share ShardedKV frame OBJECTS: with donation on
    (the default), an aggregate on either MR must not delete device
    arrays the other still reads (the _shared guard)."""
    from gpu_mapreduce_tpu.ops.reduces import count
    monkeypatch.setenv("MRTPU_DONATE", "1")
    mesh = make_mesh(8)
    keys = (np.arange(1 << 12, dtype=np.uint64) * 31) % 200
    mr = MapReduce(mesh)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys,
                                            np.ones(len(keys), np.int64)))
    mr.aggregate()                      # dataset now ONE sharded frame
    mr2 = mr.copy()                     # shares that frame object
    mr2.aggregate()                     # must NOT consume mr's arrays
    mr.convert()                        # reads the shared frame
    n = int(mr.reduce(count, batch=True))
    mr2.convert()
    n2 = int(mr2.reduce(count, batch=True))
    assert n == n2 == 200


def test_failed_exchange_after_donation_leaves_clean_state(monkeypatch):
    """A phase-2 failure after the donated phase-1 dispatch must leave
    the dataset EMPTY (clean MRError on next op), never frames holding
    deleted buffers (cryptic RuntimeError deep in XLA)."""
    from gpu_mapreduce_tpu.core.runtime import MRError
    from gpu_mapreduce_tpu.parallel import shuffle
    monkeypatch.setenv("MRTPU_DONATE", "1")

    def boom(*a, **kw):
        raise RuntimeError("phase2 exploded")

    mr = MapReduce(make_mesh(8))
    keys = np.arange(1 << 12, dtype=np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.aggregate()                      # install the sharded frame
    # both phase-2 variants: the wire codec (MRTPU_WIRE, default on)
    # dispatches _phase2_wire_jit instead of _phase2_jit
    monkeypatch.setattr(shuffle, "_phase2_jit", boom)
    monkeypatch.setattr(shuffle, "_phase2_wire_jit", boom)
    shuffle._SPEC_CACHE.clear()
    with pytest.raises(RuntimeError, match="phase2 exploded"):
        mr.aggregate()                  # phase 1 donated, phase 2 died
    with pytest.raises(MRError):
        mr.convert()                    # clean error, not deleted-array


def test_failed_fused_group_after_donation_leaves_clean_state(
        monkeypatch):
    """The fused plan tier honours the same contract as the eager
    exchange: a fused-program failure after the donated phase-1 frees
    the dataset to a clean MRError state."""
    from gpu_mapreduce_tpu.core.runtime import MRError
    from gpu_mapreduce_tpu.plan import fuser
    from gpu_mapreduce_tpu.ops.reduces import count
    monkeypatch.setenv("MRTPU_DONATE", "1")
    mr = MapReduce(make_mesh(8))
    keys = np.arange(1 << 12, dtype=np.uint64) % 100
    mr.kv = mr._new_kv()
    mr.kv.add_batch(keys, np.ones(len(keys), np.int64))
    mr.kv.complete()
    mr.aggregate()                      # install a ShardedKV frame

    def boom(*a, **kw):
        raise RuntimeError("fused exploded")

    monkeypatch.setattr(fuser, "_fused_exchange_jit", boom)
    mr.set(fuse=1)
    with pytest.raises(RuntimeError, match="fused exploded"):
        mr.aggregate()
        mr.convert()
        int(mr.reduce(count, batch=True))   # barrier runs the plan
    kv = mr._kv_data
    assert kv is not None and kv._frames == [] and not kv.complete_done
    mr.set(fuse=0)
    with pytest.raises(MRError):
        mr.convert()                    # clean error, not deleted-array


@pytest.mark.skipif(
    (os.cpu_count() or 1) < 2,
    reason="MapReduce._ingest_pool sizes its executor min(cpu_count, 16); "
           "on a 1-CPU container that is ONE worker, so cross-file reads "
           "cannot overlap by construction — the parallelism contract "
           "this test asserts only exists on multi-core hosts")
def test_mapstyle2_map_files_reads_in_parallel(word_corpus, monkeypatch):
    """mapstyle-2 mesh map_files must keep cross-file read parallelism:
    with ~1 file per shard, callbacks still run on several pool threads
    concurrently (the pre-exec behavior, kept under the pipeline)."""
    import threading as _threading
    files, oracle = word_corpus
    monkeypatch.setenv("MRTPU_PREFETCH", "1")
    mr = MapReduce(make_mesh(8), mapstyle=2)
    active = {"now": 0, "max": 0}
    lock = _threading.Lock()

    def cb(itask, fname, kv, ptr):
        with lock:
            active["now"] += 1
            active["max"] = max(active["max"], active["now"])
        time.sleep(0.03)                # hold the slot so overlap shows
        with open(fname, "rb") as f:
            ws = read_words(f.read())
        kv.add_batch(ws, np.ones(len(ws), np.int64))
        with lock:
            active["now"] -= 1

    n = mr.map_files(list(files), cb)
    assert n == sum(oracle.values())
    assert active["max"] > 1, "file reads serialized"


# ---------------------------------------------------------------------------
# surfacing: stats() / metrics / pool reuse
# ---------------------------------------------------------------------------

def test_stats_exec_section_and_gauge(word_corpus, monkeypatch):
    from gpu_mapreduce_tpu.obs import metrics as obs_metrics
    from gpu_mapreduce_tpu.obs.metrics import enable_metrics
    from gpu_mapreduce_tpu.obs.tracer import get_tracer
    files, _ = word_corpus
    enable_metrics(flight=False)
    try:
        monkeypatch.setenv("MRTPU_PREFETCH", "2")
        mr = MapReduce(make_mesh(8))

        def tokenize(itask, chunk, kv, ptr):
            ws = read_words(chunk)
            kv.add_batch(ws, np.ones(len(ws), np.int64))

        mr.map_file_str(16, list(files), 0, 0, b" ", 32, tokenize)
        st = mr.stats()["exec"]
        assert st["knobs"]["prefetch"] == 2
        ov = st["overlap"]["ingest.chunks"]
        assert ov["items"] > 0 and 0.0 <= ov["overlap_ratio"] <= 1.0
        snap = obs_metrics.snapshot()
        g = snap["mrtpu_overlap_ratio"]
        paths = {s["labels"]["path"] for s in g["samples"]}
        assert "ingest.chunks" in paths
    finally:
        obs_metrics.reset()
        get_tracer().reset()


def test_ingest_pool_reused_across_calls(word_corpus, monkeypatch):
    """mapstyle-2 ingest reuses ONE executor per MapReduce (the
    run_sinks satellite) instead of building one per call."""
    files, oracle = word_corpus
    monkeypatch.setenv("MRTPU_PREFETCH", "1")
    mr = MapReduce(make_mesh(8), mapstyle=2)
    from gpu_mapreduce_tpu.oink.kernels import read_words as rw_file
    n1 = mr.map_files(list(files), rw_file)
    pool1 = mr._ingest_pool_obj
    assert pool1 is not None
    n2 = mr.map_files(list(files), rw_file)
    assert mr._ingest_pool_obj is pool1
    assert n1 == n2 == sum(oracle.values())
