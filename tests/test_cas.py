"""Content-addressed store tests (utils/cas.py + plan/cache.py's
persistent tier): chunk put/get/verify, hardlink-refcount dedup,
grace-window GC with idempotent journaled finish, stable plan digests,
and the on-disk plan cache that survives restarts (doc/perf.md, "The
caching tier")."""

import json
import os

import pytest

from gpu_mapreduce_tpu.utils.cas import (CASStore, cas_enabled, cas_root,
                                         cas_store, reset_store,
                                         sha256_bytes, sha256_file)


def _integrity_count(artifact: str) -> int:
    from gpu_mapreduce_tpu.obs.metrics import get_registry
    return get_registry().counter(
        "mrtpu_integrity_failures_total", "", ("artifact",)
    ).value(artifact=artifact)


@pytest.fixture
def store(tmp_path):
    return CASStore(str(tmp_path / "cas"))


# ---------------------------------------------------------------------------
# chunk store units
# ---------------------------------------------------------------------------

def test_put_get_roundtrip(store):
    data = b"the quick brown fox" * 100
    digest = store.put_bytes(data)
    assert digest == sha256_bytes(data)
    assert store.contains(digest)
    assert store.get_bytes(digest) == data
    # second put of the same bytes is a dedup hit, not a rewrite
    before = os.path.getmtime(store._opath(digest))
    assert store.put_bytes(data) == digest
    assert os.path.getmtime(store._opath(digest)) == before
    assert store.dedup_hits >= 1


def test_missing_chunk_reads_none(store):
    assert store.get_bytes("0" * 64) is None
    assert not store.contains("0" * 64)
    assert store.refcount("0" * 64) == 0


def test_corrupt_chunk_quarantined_and_counted(store):
    digest = store.put_bytes(b"payload bytes")
    path = store._opath(digest)
    raw = bytearray(open(path, "rb").read())
    raw[0] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)
    before = _integrity_count("cas")
    # verified read: mismatch → None, never the flipped bytes
    assert store.get_bytes(digest) is None
    assert _integrity_count("cas") == before + 1
    assert not store.contains(digest)          # quarantined away
    assert store.quarantined == 1


def test_adopt_and_dedup_share_inodes(store, tmp_path):
    a = tmp_path / "a.bin"
    b = tmp_path / "b.bin"
    a.write_bytes(b"same chunk content")
    b.write_bytes(b"same chunk content")
    da = store.dedup_file(str(a))
    db = store.dedup_file(str(b))
    assert da == db == sha256_file(str(a))
    # both files now hardlink the one stored object
    assert os.stat(a).st_ino == os.stat(b).st_ino \
        == os.stat(store._opath(da)).st_ino
    assert store.refcount(da) == 2
    assert a.read_bytes() == b"same chunk content"


def test_materialize_links_and_releases(store, tmp_path):
    digest = store.put_bytes(b"spill page")
    dest = tmp_path / "restored.bin"
    assert store.materialize(digest, str(dest))
    assert dest.read_bytes() == b"spill page"
    assert store.refcount(digest) == 1
    # releasing a reference is just unlinking the caller's own link:
    # the count can never go negative, it is the link count itself
    os.remove(dest)
    assert store.refcount(digest) == 0
    assert not store.materialize("f" * 64, str(tmp_path / "nope"))


# ---------------------------------------------------------------------------
# GC: grace window, re-reference safety, idempotent finish
# ---------------------------------------------------------------------------

def test_gc_grace_window_and_referenced_chunks_survive(store, tmp_path):
    ref = tmp_path / "kept.bin"
    ref.write_bytes(b"referenced")
    dref = store.dedup_file(str(ref))           # nlink 2: referenced
    dorp = store.put_bytes(b"orphan")           # nlink 1: orphaned
    now = os.path.getmtime(store._opath(dorp)) + 10.0
    # inside the grace window nothing is a candidate
    assert store.gc_candidates(grace_s=3600.0, now=now) == []
    cands = store.gc_candidates(grace_s=1.0, now=now)
    assert cands == [dorp]                      # referenced chunk exempt
    assert store.gc_finish(cands) == 1
    assert not store.contains(dorp)
    assert store.contains(dref)


def test_gc_finish_idempotent_and_rereference_safe(store, tmp_path):
    dorp = store.put_bytes(b"short lived")
    now = os.path.getmtime(store._opath(dorp)) + 10.0
    cands = store.gc_candidates(grace_s=1.0, now=now)
    assert cands == [dorp]
    # a reference taken AFTER the intent was journaled: finish re-stats
    # and skips — the chunk survives
    out = tmp_path / "taken.bin"
    assert store.materialize(dorp, str(out))
    assert store.gc_finish(cands) == 0
    assert store.contains(dorp)
    os.remove(out)
    assert store.gc_finish(cands) == 1          # now truly unreferenced
    # replaying the same intent (kill -9 recovery) is a no-op
    assert store.gc_finish(cands) == 0
    assert store.refcount(dorp) == 0


def test_stats_shape(store):
    store.put_bytes(b"x")
    store.put_bytes(b"y" * 1000)
    st = store.stats()
    assert st["enabled"] == 1 and st["chunks"] == 2
    assert st["bytes"] >= 1001
    for k in ("dedup_hits", "stores", "reads", "quarantined",
              "gc_removed", "gc_bytes"):
        assert k in st


# ---------------------------------------------------------------------------
# singleton wiring (env-driven, like every other tier)
# ---------------------------------------------------------------------------

def test_cas_root_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv("MRTPU_CAS_DIR", raising=False)
    monkeypatch.delenv("MRTPU_FLEET_DIR", raising=False)
    assert cas_root() is None and not cas_enabled()
    monkeypatch.setenv("MRTPU_FLEET_DIR", str(tmp_path / "fleet"))
    assert cas_root() == str(tmp_path / "fleet" / "cas")
    monkeypatch.setenv("MRTPU_CAS_DIR", str(tmp_path / "cas"))
    assert cas_root() == str(tmp_path / "cas")   # explicit dir wins
    monkeypatch.setenv("MRTPU_CAS", "0")
    assert not cas_enabled()                     # one-knob kill switch


def test_cas_store_singleton_reroots(tmp_path, monkeypatch):
    reset_store()
    try:
        monkeypatch.setenv("MRTPU_CAS_DIR", str(tmp_path / "one"))
        s1 = cas_store()
        assert s1 is not None and s1 is cas_store()
        monkeypatch.setenv("MRTPU_CAS_DIR", str(tmp_path / "two"))
        s2 = cas_store()
        assert s2 is not s1 and s2.root == str(tmp_path / "two")
        monkeypatch.setenv("MRTPU_CAS", "0")
        assert cas_store() is None
    finally:
        reset_store()


# ---------------------------------------------------------------------------
# stable plan digests + payload serialization
# ---------------------------------------------------------------------------

def test_stable_plan_digest_stability():
    from gpu_mapreduce_tpu.plan.cache import stable_plan_digest
    key = ("fp123", ("sig", 4), ("serial",), "xla", False, True)
    d1 = stable_plan_digest(key)
    d2 = stable_plan_digest(("fp123", ("sig", 4), ("serial",), "xla",
                             False, True))
    assert d1 == d2 and len(d1) == 64
    assert stable_plan_digest(key) != stable_plan_digest(
        ("fp124",) + key[1:])
    # function components render by qualified name (stable across
    # processes), unstatable components make the plan process-local
    fkey = (("fn", sha256_bytes),)
    assert stable_plan_digest(fkey) == stable_plan_digest(fkey)
    assert stable_plan_digest((object(),)) is None


def test_plan_payload_jsonable_roundtrip():
    import numpy as np
    from gpu_mapreduce_tpu.plan.cache import from_jsonable, to_jsonable
    val = ("wire", (1, 2, (3, "u32")), np.int32(7), 2.5, None)
    back = from_jsonable(json.loads(json.dumps(to_jsonable(val))))
    assert back == ("wire", (1, 2, (3, "u32")), 7, 2.5, None)
    assert isinstance(back, tuple) and isinstance(back[1], tuple)
    with pytest.raises(TypeError):
        to_jsonable(object())


def test_persistent_plan_cache_roundtrip(tmp_path, monkeypatch):
    from gpu_mapreduce_tpu.plan.cache import PersistentPlanCache
    pp = PersistentPlanCache(str(tmp_path))
    payload = {"caps": {"0": ["wire", [1, 2]]}, "mega": {}}
    assert pp.store("d" * 64, payload)
    assert not pp.store("d" * 64, payload)       # unchanged → no write
    assert pp.load("d" * 64) == payload
    assert pp.load("e" * 64) is None
    st = pp.stats()
    assert st["entries"] == 1 and st["hits"] == 1 and st["misses"] == 1


def test_persistent_plan_cache_corruption_degrades(tmp_path):
    from gpu_mapreduce_tpu.plan.cache import PersistentPlanCache
    pp = PersistentPlanCache(str(tmp_path))
    pp.store("a" * 64, {"caps": {}, "mega": {}})
    path = pp._path("a" * 64)
    raw = open(path).read().replace('"caps"', '"craps"', 1)
    with open(path, "w") as f:
        f.write(raw)
    before = _integrity_count("cas")
    assert pp.load("a" * 64) is None             # miss, never bad state
    assert _integrity_count("cas") == before + 1
    assert not os.path.exists(path)              # removed


def test_persistent_plan_cache_cap_evicts_oldest(tmp_path, monkeypatch):
    from gpu_mapreduce_tpu.plan.cache import PersistentPlanCache
    monkeypatch.setenv("MRTPU_PLAN_PERSIST_CAP", "2")
    pp = PersistentPlanCache(str(tmp_path))
    for i, d in enumerate(("a" * 64, "b" * 64, "c" * 64)):
        pp.store(d, {"caps": {}, "mega": {}, "n": i})
        os.utime(pp._path(d), (1000.0 + i, 1000.0 + i))
    pp.store("d" * 64, {"caps": {}, "mega": {}, "n": 3})
    st = pp.stats()
    assert st["entries"] == 2 and st["evictions"] >= 2
    assert pp.load("a" * 64) is None             # oldest went first


# ---------------------------------------------------------------------------
# the restart path: a cleared in-memory cache refills from disk
# ---------------------------------------------------------------------------

def test_plan_persist_restart_refills_from_disk(tmp_path, monkeypatch):
    """The rung-(a) smoke: run a fused script, clear the in-memory plan
    LRU (a restart's cold cache), run again — the persistent tier
    serves every plan digest instead of recompiling cold."""
    from gpu_mapreduce_tpu.oink.script import OinkScript
    from gpu_mapreduce_tpu.plan.cache import persistent_cache, plan_cache
    monkeypatch.setenv("MRTPU_CAS_DIR", str(tmp_path / "cas"))
    monkeypatch.setenv("MRTPU_JIT_PERSIST", "0")
    reset_store()
    plan_cache().clear()
    try:
        corpus = tmp_path / "c.txt"
        corpus.write_text("alpha beta gamma alpha beta alpha\n" * 50)
        script = (f"variable files index {corpus}\nset fuse 1\n"
                  f"wordfreq 3 -i v_files\n")
        OinkScript(screen=False).run_string(script)
        pp = persistent_cache()
        assert pp is not None
        first = pp.stats()
        assert first["entries"] > 0              # this run persisted
        plan_cache().clear()                     # "restart"
        OinkScript(screen=False).run_string(script)
        second = pp.stats()
        assert second["hits"] > first["hits"]    # disk tier rescued
        assert second["entries"] == first["entries"]  # no rewrite churn
    finally:
        plan_cache().clear()
        reset_store()
