"""Word-packed mark kernel + unaligned-window + masked-hash primitives
(the round-2 fused map stage) vs byte-level oracles."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gpu_mapreduce_tpu.apps.invertedindex import PATTERN
from gpu_mapreduce_tpu.ops.hash import (bytes_to_words32, hash_bytes64,
                                        hash_bytes64_masked, hashlittle,
                                        hashlittle_masked)
from gpu_mapreduce_tpu.ops.pallas.match import (bytes_view_u32,
                                                compact_word_matches,
                                                first_byte_pos, mark_xla,
                                                mark_words_pallas,
                                                mark_words_xla,
                                                mask_words_to_length,
                                                unaligned_words)


def _planted_buffer(rng, n, offsets):
    buf = rng.integers(0, 256, n, dtype=np.uint8)
    for off in offsets:
        buf[off:off + len(PATTERN)] = np.frombuffer(PATTERN, np.uint8)
    return buf


def _byte_oracle(buf):
    """Ground-truth match starts from python bytes.find."""
    data = buf.tobytes()
    out, start = [], 0
    while True:
        i = data.find(PATTERN, start)
        if i < 0:
            return np.array(out, np.int64)
        out.append(i)
        start = i + 1


@pytest.mark.parametrize("offsets", [
    (0,), (1,), (2,), (3,),                     # every word alignment
    (508, 1020, 131067),                        # crossing lane/row/block edges
    (5, 1000, 131072 * 4 - 20),
])
def test_mark_words_pallas_vs_oracle(rng, offsets):
    n = 131072 * 4 + 64
    buf = _planted_buffer(rng, n, offsets)
    words = jnp.asarray(bytes_view_u32(buf))
    wm_k = np.asarray(mark_words_pallas(words, PATTERN, interpret=True))
    wm_x = np.asarray(mark_words_xla(words, PATTERN))
    np.testing.assert_array_equal(wm_k, wm_x)
    starts, cnt = compact_word_matches(jnp.asarray(wm_k), n, 64)
    st = np.asarray(starts)
    st = np.sort(st[st < n])
    oracle = _byte_oracle(buf)
    np.testing.assert_array_equal(st, oracle)
    assert int(cnt) == len(oracle)


def test_mark_words_pallas_paged_matches_single(rng):
    """The r4 paged mark (fixed 16 MB dispatches on chip) must be
    bit-identical to the single-dispatch kernel and the XLA twin —
    including matches whose pattern bytes STRADDLE a page seam."""
    page = 2048  # words; tiny so the test crosses several seams
    n = 4 * (3 * page + 100)  # 3 full pages + a ragged tail
    seam = 4 * page
    # plants spaced >= len(PATTERN) so none clobbers another; seam-2 and
    # 2*seam-5 straddle the first and second page seams respectively
    offsets = (0, seam - 16, seam - 2, seam + 8, 2 * seam - 5, n - 64)
    buf = _planted_buffer(rng, n, offsets)
    words = jnp.asarray(bytes_view_u32(buf))
    paged = np.asarray(mark_words_pallas(words, PATTERN, interpret=True,
                                         page_words=page))
    single = np.asarray(mark_words_pallas(words, PATTERN, interpret=True,
                                          page_words=len(words)))
    oracle = np.asarray(mark_words_xla(words, PATTERN))
    np.testing.assert_array_equal(paged, single)
    np.testing.assert_array_equal(paged, oracle)
    starts, cnt = compact_word_matches(jnp.asarray(paged), n, 64)
    st = np.asarray(starts)
    np.testing.assert_array_equal(np.sort(st[st < n]), _byte_oracle(buf))


@pytest.mark.parametrize("alt", ["searchsorted", "blocked"])
def test_compact_variants_match_scatter(rng, alt, monkeypatch):
    """The searchsorted gather-side dual and the blocked two-level-scan
    variant must be bit-identical to the scatter compaction — including
    cap overflow, empty masks, and (for blocked) hits straddling its
    row seams and landing in the final ragged row."""
    from gpu_mapreduce_tpu.ops.pallas.match import _BLOCK_C
    n = 131072 * 4 + 64
    seam = _BLOCK_C * 4   # one blocked row, in bytes
    buf = _planted_buffer(rng, n,
                          (3, seam - 2, 7 * seam + 11, 131067, n - 40))
    words = jnp.asarray(bytes_view_u32(buf))
    wm = mark_words_xla(words, PATTERN)
    for cap in (64, 2):   # plenty of room / overflowing the cap
        s1, c1 = compact_word_matches(wm, n, cap, mode="scatter")
        s2, c2 = compact_word_matches(wm, n, cap, mode=alt)
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        assert int(c1) == int(c2)
    # the MR_COMPACT env fallback (mode=None) must route identically
    monkeypatch.setenv("MR_COMPACT", alt)
    s3, c3 = compact_word_matches(wm, n, 64)
    np.testing.assert_array_equal(
        np.asarray(s3), np.asarray(compact_word_matches(wm, n, 64,
                                                        mode=alt)[0]))
    monkeypatch.delenv("MR_COMPACT")
    empty = jnp.zeros(1024, jnp.int8)
    s, c = compact_word_matches(empty, 4096, 8, mode=alt)
    assert int(c) == 0 and (np.asarray(s) == 4096).all()
    with pytest.raises(ValueError, match="expected"):
        compact_word_matches(empty, 4096, 8, mode="searchsort")


def test_word_mask_agrees_with_byte_mask(rng):
    buf = _planted_buffer(rng, 4096, (7, 130, 1001))
    words = jnp.asarray(bytes_view_u32(buf))
    wm = np.asarray(mark_words_xla(words, PATTERN))
    bm = np.asarray(mark_xla(jnp.asarray(buf), PATTERN))
    for i, v in enumerate(wm.tolist()):
        if v:
            assert bm[4 * i + v - 1] == 1
    assert (wm > 0).sum() == bm.sum()


def test_min_period_guard():
    with pytest.raises(ValueError):
        mark_words_xla(jnp.zeros(8, jnp.uint32), b"aaa")


def test_unaligned_words_every_alignment(rng):
    data = rng.integers(0, 256, 256, dtype=np.uint8)
    words = jnp.asarray(bytes_view_u32(data))
    for s in (0, 1, 2, 3, 17, 100):
        win = np.asarray(unaligned_words(words, jnp.asarray([s], np.int32), 8))
        want = np.zeros(32, np.uint8)
        take = data[s:s + 32]
        want[:len(take)] = take
        np.testing.assert_array_equal(
            win[0], want.view("<u4"), err_msg=f"start={s}")


def test_unaligned_words_out_of_range_zero():
    words = jnp.asarray(np.full(4, 0xFFFFFFFF, np.uint32))
    win = np.asarray(unaligned_words(words, jnp.asarray([14, 99], np.int32), 4))
    assert win[0, 0] == 0xFFFF          # last 2 real bytes, then zeros
    assert (win[0, 1:] == 0).all()
    assert (win[1] == 0).all()          # fully out of range


def test_first_byte_pos_and_mask(rng):
    rows = np.array([
        b'abc"xxxxxxxx',     # quote at 3
        b'"aaaaaaaaaaa',      # quote at 0
        b'nothing-here',      # none
    ])
    arr = np.frombuffer(b"".join(rows), np.uint8).reshape(3, 12)
    pad = np.zeros((3, 4), np.uint8)
    wu = jnp.asarray(np.concatenate([arr, pad], 1).view("<u4"))
    pos = np.asarray(first_byte_pos(wu, ord('"')))
    np.testing.assert_array_equal(pos, [3, 0, -1])
    masked = np.asarray(mask_words_to_length(
        wu, jnp.asarray([3, 0, 5], np.int32)))
    b = masked.view(np.uint32)
    # row 0: bytes 0..2 kept, rest zero
    np.testing.assert_array_equal(
        masked[0].view("<u4"), np.frombuffer(b"abc" + b"\0" * 13, "<u4"))
    assert (masked[1] == 0).all()


def test_masked_hash_matches_scalar(rng):
    maxl = 48
    lens = rng.integers(0, maxl + 1, 64).astype(np.int32)
    rows = np.zeros((64, maxl), np.uint8)
    strs = []
    for i, l in enumerate(lens):
        s = rng.integers(1, 256, l, dtype=np.uint8).tobytes()
        strs.append(s)
        rows[i, :l] = np.frombuffer(s, np.uint8)
    words = bytes_to_words32(rows, maxl)
    want32 = np.array([hashlittle(s) for s in strs], np.uint32)
    want64 = np.array([hash_bytes64(s) for s in strs], np.uint64)
    np.testing.assert_array_equal(hashlittle_masked(words, lens), want32)
    np.testing.assert_array_equal(hash_bytes64_masked(words, lens), want64)
    # jit path (fori_loop branch kicks in over 8 blocks → use wide rows too)
    got = np.asarray(jax.jit(hash_bytes64_masked)(
        jnp.asarray(words), jnp.asarray(lens)))
    np.testing.assert_array_equal(got, want64)


def test_masked_hash_wide_fori_branch(rng):
    maxl = 256  # 64 words → fori_loop path under jit
    lens = rng.integers(0, maxl + 1, 16).astype(np.int32)
    rows = np.zeros((16, maxl), np.uint8)
    strs = []
    for i, l in enumerate(lens):
        s = rng.integers(1, 256, l, dtype=np.uint8).tobytes()
        strs.append(s)
        rows[i, :l] = np.frombuffer(s, np.uint8)
    words = bytes_to_words32(rows, maxl)
    want = np.array([hash_bytes64(s) for s in strs], np.uint64)
    got = np.asarray(jax.jit(hash_bytes64_masked)(
        jnp.asarray(words), jnp.asarray(lens)))
    np.testing.assert_array_equal(got, want)


def test_device_ids_match_native_intern(tmp_path, rng):
    """The fused device path and the native C++ host path must produce the
    SAME u64 url ids (ops/hash.py contract) — checked end-to-end."""
    from gpu_mapreduce_tpu import native
    from gpu_mapreduce_tpu.apps.invertedindex import InvertedIndex
    files = []
    for fi in range(2):
        parts = []
        for u in range(5):
            parts.append(b'<a href="http://h%d/u%d">x</a>pad' % (fi, u))
        p = tmp_path / f"f{fi}.html"
        p.write_bytes(b"".join(parts))
        files.append(str(p))
    ii_dev = InvertedIndex(engine="pallas")
    ii_dev.run(files)
    if not native.available():
        pytest.skip("no native toolchain")
    ii_nat = InvertedIndex(engine="native")
    ii_nat.run(files)
    assert ii_dev.urls == ii_nat.urls
    assert set(ii_dev.urls.keys()) == set(ii_nat.urls.keys())
