"""OINK script interpreter tests — grammar (reference oink/input.cpp),
variables (oink/variable.cpp), named-MR dispatch (oink/mrmpi.cpp), and
the examples/in.* integration scripts with golden invariants."""

import io
import math

import numpy as np
import pytest

from gpu_mapreduce_tpu.core.runtime import MRError
from gpu_mapreduce_tpu.oink import OinkScript, Variables


def run(text, **kw):
    out = io.StringIO()
    s = OinkScript(screen=out, **kw)
    s.run_string(text)
    return out.getvalue(), s


# ---------------------------------------------------------------------------
# variables + formula evaluator
# ---------------------------------------------------------------------------

def test_variable_styles():
    v = Variables()
    v.set(["a", "index", "x", "y", "z"])
    v.set(["n", "loop", "3"])
    v.set(["m", "loop", "5", "8"])
    v.set(["p", "loop", "12", "pad"])
    v.set(["s", "string", "hello world"])
    assert v.retrieve("a") == "x"
    assert v.retrieve("n") == "1"
    assert v.retrieve("m") == "5"
    assert v.retrieve("p") == "01"          # padded to len("12")
    assert v.retrieve("s") == "hello world"
    # first definition wins for index/loop (variable.cpp:113)
    v.set(["a", "index", "other"])
    assert v.retrieve("a") == "x"
    # next advances and removes on exhaustion
    assert v.next(["n"]) is False
    assert v.retrieve("n") == "2"
    assert v.next(["n"]) is False
    assert v.next(["n"]) is True
    assert v.retrieve("n") is None


def test_variable_equal_formulas():
    v = Variables()
    cases = {
        "1+2*3": 7, "(1+2)*3": 9, "2^3^2": 512,      # ^ right-assoc
        "-2^2": 4,          # UNARY binds tighter than ^ (variable.cpp:68)
        "10/4": 2.5, "sqrt(16)+ln(exp(2))": 6,
        "PI": math.pi, "floor(2.7)+ceil(2.1)+round(2.5)": 8,
        "1 < 2 && 2 <= 2": 1, "1 > 2 || 0": 0, "!0": 1,
        "3 == 3": 1, "3 != 3": 0, "atan2(0,1)": 0,
    }
    for f, want in cases.items():
        assert v.evaluate(f) == pytest.approx(want), f
    v.set(["x", "equal", "6*7"])
    assert v.evaluate("v_x + 1") == 43
    with pytest.raises(MRError):
        v.evaluate("nosuchkeyword")
    with pytest.raises(MRError):
        v.evaluate("1 +")


def test_variable_equal_reset_and_style_guard():
    v = Variables()
    v.set(["e", "equal", "1"])
    v.set(["e", "equal", "2"])               # EQUAL may be reset
    assert v.retrieve("e") == "2"
    # index over an existing name is a silent no-op (variable.cpp:114)
    v.set(["e", "index", "q"])
    assert v.retrieve("e") == "2"
    with pytest.raises(MRError):
        v.set(["e", "string", "q"])          # string/equal cross-reset
    v.set(["e", "delete"])
    v.set(["e", "index", "q"])
    assert v.retrieve("e") == "q"


# ---------------------------------------------------------------------------
# interpreter grammar
# ---------------------------------------------------------------------------

def test_substitution_comments_quotes():
    out, _ = run('variable x index abc\n'
                 'print "x=$x brace=${x}"  # trailing comment\n'
                 "print 'hash # inside quotes survives'\n")
    assert "x=abc brace=abc" in out
    assert "hash # inside quotes survives" in out


def test_continuation_lines():
    out, _ = run('variable x index abc\nprint &\n"joined $x"\n')
    assert "joined abc" in out


def test_if_elif_else():
    out, _ = run('if "1 > 2" then "print A" elif "2 > 1" "print B" '
                 'else "print C"\n')
    assert "B" in out and "A" not in out and "C" not in out
    out, _ = run('if "0" then "print A" else "print C1" "print C2"\n')
    assert "C1" in out and "C2" in out


def test_label_next_jump_loop():
    out, _ = run("variable i loop 4\n"
                 "label top\n"
                 'print "i=$i"\n'
                 "next i\n"
                 "jump SELF top\n"
                 'print "done"\n')
    for k in (1, 2, 3, 4):
        assert f"i={k}" in out
    assert "done" in out
    assert out.count("i=4") == 1


def test_unknown_command_and_bad_substitution():
    with pytest.raises(MRError, match="Unknown command"):
        run("frobnicate 1 2\n")
    with pytest.raises(MRError, match="illegal variable"):
        run('print "$q"\n')


def test_shell_and_log(tmp_path):
    d = tmp_path / "sub"
    out, s = run(f"shell mkdir {d}\n"
                 f"log {tmp_path}/my.log\n"
                 'print "to the log"\n')
    s.close()
    assert d.is_dir()
    assert "to the log" in (tmp_path / "my.log").read_text()


# ---------------------------------------------------------------------------
# mr objects + named-MR method dispatch (oink/mrmpi.cpp)
# ---------------------------------------------------------------------------

@pytest.fixture
def edge_file(tmp_path, rng):
    e = rng.integers(0, 20, size=(60, 2)).astype(np.uint64)
    e = e[e[:, 0] != e[:, 1]]
    p = tmp_path / "edges.txt"
    p.write_text("\n".join(f"{a} {b}" for a, b in e) + "\n")
    return str(p), e


def test_mr_create_and_methods(edge_file):
    path, e = edge_file
    out, s = run(f"mr work\n"
                 f"work map/file {path} read_edge\n"
                 f"work map/mr work edge_to_vertices\n"
                 f"work collate NULL\n"
                 f"work reduce count\n")
    mr = s.obj.get_mr("work")
    got = {}
    mr.scan_kv(lambda k, v, p: got.__setitem__(int(k), int(v)))
    import collections
    oracle = collections.Counter(
        np.concatenate([e[:, 0], e[:, 1]]).tolist())
    assert got == dict(oracle)


def test_mr_copy_add_delete(edge_file):
    path, _ = edge_file
    _, s = run(f"mr a\n"
               f"a map/file {path} read_edge\n"
               f"a copy b\n"
               f"b add a\n")
    na = s.obj.get_mr("a").kv.nkv
    assert s.obj.get_mr("b").kv.nkv == 2 * na
    s.one("a delete")
    with pytest.raises(MRError):
        s.obj.get_mr("a")


def test_mr_command_errors(edge_file):
    path, _ = edge_file
    _, s = run("mr a\n")
    with pytest.raises(MRError, match="already in use"):
        s.one("mr a")
    with pytest.raises(MRError, match="alphanumeric"):
        s.one("mr bad-name")
    with pytest.raises(MRError, match="Unknown MR object method"):
        s.one("a frobnicate")
    s.one(f"a map/file {path} read_edge")
    with pytest.raises(MRError, match="unknown reduce kernel"):
        s.one("a compress nosuchkernel")


# ---------------------------------------------------------------------------
# registered-command dispatch with -i/-o (input.cpp:429-468)
# ---------------------------------------------------------------------------

def test_command_with_io_switches(edge_file, tmp_path):
    path, e = edge_file
    outfile = tmp_path / "upper.txt"
    out, s = run(f"edge_upper -i {path} -o {outfile} mru\n"
                 f"degree 0 -i mru\n")
    got = np.loadtxt(outfile, dtype=np.uint64).reshape(-1, 2)
    assert np.all(got[:, 0] < got[:, 1])
    assert "mru" in s.obj.named


def test_v_files_variable_input(tmp_path, rng):
    words = ["alpha", "beta", "beta", "gamma"] * 10
    f1, f2 = tmp_path / "w1.txt", tmp_path / "w2.txt"
    f1.write_text(" ".join(words))
    f2.write_text(" ".join(words))
    out, s = run(f"variable files index {f1} {f2}\n"
                 f"wordfreq 2 -i v_files\n")
    assert "2 files, 80 words, 3 unique" in out
    assert "40 beta" in out


def test_set_scratch_maps_to_fpath(tmp_path):
    _, s = run(f"set scratch {tmp_path} verbosity 0\n"
               f"mr w\n")
    assert s.obj.get_mr("w").settings.fpath == str(tmp_path)


# ---------------------------------------------------------------------------
# examples/in.* integration (the reference's own acceptance style:
# printed invariants, SURVEY.md §4.1)
#
# Golden values: the RMAT generator seeds jax.random.PRNGKey, whose
# bit-stream is stable per jax build but NOT across jax upgrades — the
# container's jax regenerated a different (equally valid) graph from
# seed 12345, shifting the derived counts.  Regenerated 2026-08-04
# under the pinned container jax; determinism re-verified by two
# independent runs producing identical output before re-pinning.
# ---------------------------------------------------------------------------

def test_example_in_cc_golden(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    out = io.StringIO()
    s = OinkScript(screen=out)
    s.run_file("/root/repo/examples/in.cc")
    text = out.getvalue()
    assert "RMAT: 65536 rows, 131072 non-zeroes" in text
    # fused engine: 9 pointer-jumping rounds (the composed MR engine's
    # count was 8 zone-propagation rounds; component count is identical)
    assert "CC_find: 54 components in 9 iterations" in text
    assert "CCStats: 54 components, 64308 vertices" in text
    assert (tmp_path / "tmp.cc").exists()


def test_example_in_luby_golden(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    out = io.StringIO()
    s = OinkScript(screen=out)
    s.run_file("/root/repo/examples/in.luby")
    text = out.getvalue()
    assert "RMAT: 4096 rows, 16384 non-zeroes" in text
    # fused engine: 5 rounds (composed counted 4 edge-winner rounds)
    assert "Luby_find: 1129 MIS vertices in 5 iterations" in text


def test_example_in_tri_golden(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    out = io.StringIO()
    s = OinkScript(screen=out)
    s.run_file("/root/repo/examples/in.tri")
    text = out.getvalue()
    assert "RMAT: 65536 rows, 524288 non-zeroes" in text
    assert "Tri_find: 692 triangles" in text
    rows = (tmp_path / "tmp.tri").read_text().splitlines()
    assert len(rows) == 692


def test_example_in_pagerank_golden(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    out = io.StringIO()
    s = OinkScript(screen=out)
    s.run_file("/root/repo/examples/in.pagerank")
    text = out.getvalue()
    assert "RMAT: 16384 rows, 131072 non-zeroes" in text
    assert "PageRank: 11239 vertices, 131072 edges, 7 iterations" in text
    import numpy as np
    ranks = np.loadtxt(tmp_path / "tmp.pr", dtype=np.float64)
    assert len(ranks) == 11239
    assert abs(ranks[:, 1].sum() - 1.0) < 1e-3      # a distribution


def test_example_in_rmat_golden(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    out = io.StringIO()
    s = OinkScript(screen=out)
    s.run_file("/root/repo/examples/in.rmat")
    text = out.getvalue()
    assert "RMAT: 65536 rows, 524288 non-zeroes" in text
    assert "DegreeStats: 65536 vertices, 524288 edges" in text


def test_example_in_wordfreq_via_var(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    corpus = tmp_path / "data.txt"
    corpus.write_text("to be or not to be that is the question "
                      "to be is to do")
    out = io.StringIO()
    s = OinkScript(screen=out)
    s.variables.set(["files", "index", str(corpus)])
    s.run_file("/root/repo/examples/in.wordfreq")
    text = out.getvalue()
    assert "1 files, 15 words, 9 unique" in text
    assert "4 to" in text and "3 be" in text


def test_example_in_sssp_named_mr_weighting(tmp_path, monkeypatch):
    # in.sssp drives `mre map/mr mre add_weight` through named-MR dispatch
    monkeypatch.chdir(tmp_path)
    out = io.StringIO()
    s = OinkScript(screen=out)
    s.run_file("/root/repo/examples/in.sssp")
    text = out.getvalue()
    assert text.count("SSSP: source") == 10
    assert (tmp_path / "tmp.sssp.0").exists()


def test_main_cli(tmp_path, monkeypatch, capsys):
    from gpu_mapreduce_tpu.oink.script import main
    monkeypatch.chdir(tmp_path)
    words = tmp_path / "w.txt"
    words.write_text("a b a c a b " * 5)
    script = tmp_path / "in.test"
    script.write_text("wordfreq 2 -i v_files\n"
                      'print "done on $p procs"\n')
    rc = main(["-in", str(script), "-log", str(tmp_path / "log.oink"),
               "-var", "files", str(words), "-var", "p", "1",
               "-echo", "log"])
    assert rc == 0
    log = (tmp_path / "log.oink").read_text()
    assert "done on 1 procs" in log
    assert "wordfreq 2 -i v_files" in log    # echo log mode
