"""Fake-cluster tests for the mesh backend: 8 virtual CPU devices stand in
for 8 TPU chips (SURVEY.md §4 — the mpistubs trick, inverted)."""

import collections

import numpy as np
import pytest

import jax

from gpu_mapreduce_tpu import MapReduce
from gpu_mapreduce_tpu.parallel.mesh import make_mesh
from gpu_mapreduce_tpu.parallel.sharded import ShardedKV
from gpu_mapreduce_tpu.parallel.group import reduce_sharded
from gpu_mapreduce_tpu.ops.hash import hash_u64


@pytest.fixture(scope="module")
def mesh():
    # conftest fakes 8 CPU devices; a larger fake cluster (pod-scale
    # sanity runs override the flag) still exercises the same paths
    assert len(jax.devices()) >= 8, "conftest should fake >=8 CPU devices"
    return make_mesh(8)


def emit(itask, kv, ptr):
    rng = np.random.default_rng(itask)
    keys = rng.integers(0, 97, size=500).astype(np.uint64)
    kv.add_batch(keys, keys * 10 + itask)


def oracle_pairs():
    out = []
    for itask in range(6):
        rng = np.random.default_rng(itask)
        keys = rng.integers(0, 97, size=500).astype(np.uint64)
        out.extend(zip(keys.tolist(), (keys * 10 + itask).tolist()))
    return out


def multiset(pairs):
    return collections.Counter((int(k), int(v)) for k, v in pairs)


@pytest.mark.parametrize("all2all", [1, 0])
def test_aggregate_preserves_pairs_and_partitions(mesh, all2all):
    mr = MapReduce(mesh, all2all=all2all)
    n = mr.map(6, emit)
    assert n == 3000
    assert mr.aggregate() == 3000
    frame = mr.kv.one_frame()
    assert isinstance(frame, ShardedKV)
    # multiset of pairs is preserved
    assert multiset(frame.to_host().pairs()) == multiset(oracle_pairs())
    # every key lives on exactly one shard, and it's the lookup3 shard
    P, cap = frame.nprocs, frame.cap
    k = np.asarray(frame.key).reshape(P, cap)
    for i in range(P):
        ki = k[i, :frame.counts[i]]
        expect = hash_u64(ki) % P
        assert (expect == i).all()


def test_collate_reduce_matches_oracle(mesh):
    mr = MapReduce(mesh)
    mr.map(6, emit)
    ngroups = mr.collate()
    oracle = collections.Counter(int(k) for k, _ in oracle_pairs())
    assert ngroups == len(oracle)

    def count(frame, kv, ptr):
        kv.add_frame(reduce_sharded(frame, "count"))

    mr.reduce(count, batch=True)
    got = {}
    mr.scan_kv(lambda k, v, p: got.update({int(k): int(v)}))
    assert got == dict(oracle)


def test_reduce_sharded_sum_max_min(mesh):
    mr = MapReduce(mesh)
    mr.map(6, emit)
    mr.collate()
    groups = collections.defaultdict(list)
    for k, v in oracle_pairs():
        groups[int(k)].append(int(v))
    frame = mr.kmv.one_frame()
    for op, fn in (("sum", sum), ("max", max), ("min", min)):
        skv = reduce_sharded(frame, op)
        got = dict(skv.to_host().pairs())
        assert got == {k: fn(v) for k, v in groups.items()}, op


def test_host_reduce_on_sharded_kmv(mesh):
    """The per-group host callback tier must also work on sharded data."""
    mr = MapReduce(mesh)
    mr.map(2, emit)
    mr.collate()

    def longest(key, values, kv, ptr):
        kv.add(key, max(values))

    mr.reduce(longest)
    groups = collections.defaultdict(list)
    for itask in range(2):
        rng = np.random.default_rng(itask)
        keys = rng.integers(0, 97, size=500).astype(np.uint64)
        for k, v in zip(keys, keys * 10 + itask):
            groups[int(k)].append(int(v))
    got = dict((int(k), int(v)) for k, v in kv_pairs(mr))
    assert got == {k: max(v) for k, v in groups.items()}


def kv_pairs(mr):
    pairs = []
    mr.scan_kv(lambda k, v, p: pairs.append((k, v)))
    return pairs


def test_sort_sharded(mesh):
    mr = MapReduce(mesh)
    mr.map(6, emit)
    mr.aggregate()
    mr.sort_keys(1)
    frame = mr.kv.one_frame()
    P, cap = frame.nprocs, frame.cap
    k = np.asarray(frame.key).reshape(P, cap)
    for i in range(P):
        ki = k[i, :frame.counts[i]]
        assert (np.diff(ki.astype(np.int64)) >= 0).all()
    mr.sort_keys(-1)
    frame = mr.kv.one_frame()
    k = np.asarray(frame.key).reshape(P, cap)
    for i in range(P):
        ki = k[i, :frame.counts[i]]
        assert (np.diff(ki.astype(np.int64)) <= 0).all()


def test_sort_multivalues_sharded(mesh):
    mr = MapReduce(mesh)
    mr.map(6, emit)
    mr.collate()
    mr.sort_multivalues(1)
    for k, vals in mr.kmv.one_frame().groups():
        assert list(vals) == sorted(vals)
    mr2 = MapReduce(mesh)
    mr2.map(6, emit)
    mr2.collate()
    mr2.sort_multivalues(-1)
    for k, vals in mr2.kmv.one_frame().groups():
        assert list(vals) == sorted(vals, reverse=True)


def test_gather_and_broadcast(mesh):
    mr = MapReduce(mesh)
    mr.map(6, emit)
    mr.aggregate()
    before = multiset(mr.kv.one_frame().to_host().pairs())
    mr.gather(2)
    frame = mr.kv.one_frame()
    assert frame.counts[2:].sum() == 0 and frame.counts[:2].sum() == 3000
    assert multiset(frame.to_host().pairs()) == before

    mr.gather(1)
    frame = mr.kv.one_frame()
    assert frame.counts[0] == 3000
    n = mr.broadcast(0)
    frame = mr.kv.one_frame()
    assert (frame.counts == 3000).all()
    assert n == 3000 * 8  # every proc holds a replica (reference semantics)


def test_scrunch(mesh):
    mr = MapReduce(mesh)
    mr.map(2, emit)
    mr.scrunch(1, np.uint64(7))
    g, n, _ = mr.kmv_stats()
    assert g == 1 and n == 2 * 500 * 2  # one group, (k,v) interleaved


def test_wordfreq_interned_on_mesh(tmp_path, mesh):
    from gpu_mapreduce_tpu.apps.wordfreq import wordfreq_interned

    text = (b"alpha beta gamma alpha delta beta alpha "
            b"epsilon zeta eta theta " * 50)
    f = tmp_path / "w.txt"
    f.write_bytes(text)
    nw_s, nu_s, top_s = wordfreq_interned([str(f)], ntop=3)
    nw_m, nu_m, top_m = wordfreq_interned([str(f)], ntop=3, comm=mesh)
    assert (nw_s, nu_s) == (nw_m, nu_m)
    # compare counts only: rank 3 is a six-way tie at 50, so word identity
    # at the tail is an incidental tie-break of each execution path
    assert [c for _, c in top_s] == [c for _, c in top_m] == [150, 100, 50]


@pytest.mark.parametrize("all2all", [1, 0])
def test_skewed_exchange_multi_round(mesh, all2all, monkeypatch):
    """Skewed buckets force nrounds > 1 in the flow-controlled exchange;
    round-window rows must not wrap into earlier rounds (round-1 advisor
    finding: negative scatter indices wrapped before mode='drop')."""
    from gpu_mapreduce_tpu.core.frame import KVFrame
    from gpu_mapreduce_tpu.core.column import DenseColumn
    from gpu_mapreduce_tpu.parallel import shuffle
    from gpu_mapreduce_tpu.parallel.sharded import shard_frame

    # per shard: ~1 row to each dest 1..7, a pile of rows to dest 0 —
    # mean nonzero bucket << max bucket ⇒ multi-round
    rng = np.random.default_rng(99)
    hub = np.zeros(2000, np.uint64)            # dest 0 via key % 8
    tail = rng.integers(1, 8, size=56).astype(np.uint64)
    keys = np.concatenate([hub, tail])
    rng.shuffle(keys)
    vals = np.arange(len(keys), dtype=np.uint64)

    monkeypatch.setenv("MRTPU_WIRE", "0")  # the RAW schedule under test
    #                                        (wire twin: test_wire.py)
    seen = {}
    orig = shuffle._phase2_jit

    def spy(mesh_, transport, B, nrounds, cap_out, **kw):
        seen["nrounds"] = nrounds
        return orig(mesh_, transport, B, nrounds, cap_out, **kw)

    monkeypatch.setattr(shuffle, "_phase2_jit", spy)
    shuffle._SPEC_CACHE.clear()   # order-independent: no speculation hit
    skv = shard_frame(KVFrame(DenseColumn(keys), DenseColumn(vals)), mesh)
    dest = ("hash", lambda k: k.astype(np.uint32))
    out = shuffle.exchange(skv, dest, transport=all2all)
    assert seen["nrounds"] > 1, "test no longer exercises the multi-round path"
    # the public telemetry (r4: the driver dryrun asserts on this too)
    assert shuffle.ExchangeStats.last_nrounds == seen["nrounds"]
    assert shuffle.ExchangeStats.last_bucket >= 1
    assert multiset(out.to_host().pairs()) == multiset(zip(keys, vals))
    P, cap = out.nprocs, out.cap
    k = np.asarray(out.key).reshape(P, cap)
    for i in range(P):
        assert (k[i, :out.counts[i]] % P == i).all()


def test_build_send_round_window_no_wrap():
    """_build_send round r must contain EXACTLY bucket slots [rB, rB+B) —
    the round-1 advisor bug wrapped the previous round's rows (negative
    scatter indices) into this round's buffer, which XLA may keep or drop
    depending on unspecified duplicate-update order."""
    import jax.numpy as jnp
    from gpu_mapreduce_tpu.parallel.shuffle import _build_send

    nprocs, B = 4, 4
    # bucket 0: 10 rows, bucket 1: 1 row, bucket 2: 0 rows, bucket 3: 2 rows
    counts = jnp.array([10, 1, 0, 2], jnp.int32)
    rows = jnp.arange(1, 17, dtype=jnp.uint64)  # 13 real + 3 padding, no zeros
    for r in range(3):
        send = np.asarray(_build_send(nprocs, B, rows, counts, r))
        expect = np.zeros((nprocs, B), np.uint64)
        offs = [0, 10, 11, 11]
        for d in range(nprocs):
            for s in range(B):
                q0 = r * B + s
                if q0 < counts[d]:
                    expect[d, s] = rows[offs[d] + q0]
        np.testing.assert_array_equal(send, expect, err_msg=f"round {r}")


def test_sort_keys_lexicographic_after_intern():
    """sort_keys on a mesh KV whose byte keys were auto-interned must
    order by the BYTES, not the u64 intern ids (reference string sort,
    src/mapreduce.cpp:2763-2802)."""
    from gpu_mapreduce_tpu import MapReduce
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    words = [b"pear", b"apple", b"fig", b"zoo", b"beta", b"kiwi",
             b"mango", b"date"]
    mr = MapReduce(make_mesh(4))
    mr.map(1, lambda i, kv, p: [kv.add(w, 1) for w in words])
    mr.aggregate()
    mr.sort_keys(5)
    got = []
    mr.scan_kv(lambda k, v, p: got.append(k))
    assert got == sorted(words)
    mr.sort_keys(-5)
    got = []
    mr.scan_kv(lambda k, v, p: got.append(k))
    assert got == sorted(words, reverse=True)


def test_bytes_values_shard_and_roundtrip(mesh):
    """VERDICT r2 #4: byte-string VALUES intern and shard like keys —
    a (u64 key, bytes value) KV aggregates across the mesh, groups, and
    reduces to the serial oracle with the original value bytes intact."""
    import jax.numpy  # noqa: F401

    def emit_bv(itask, kv, ptr):
        rng = np.random.default_rng(40 + itask)
        for _ in range(200):
            k = int(rng.integers(0, 37))
            kv.add(np.uint64(k), b"doc-%03d" % rng.integers(0, 50))

    oracle = collections.defaultdict(list)
    mr0 = MapReduce()
    mr0.map(4, emit_bv)
    mr0.scan_kv(lambda k, v, p: oracle[int(k)].append(bytes(v)))

    mr = MapReduce(mesh)
    mr.map(4, emit_bv)
    mr.aggregate()
    fr = mr.kv.one_frame()
    assert isinstance(fr, ShardedKV) and fr.value_decode is not None
    # round-trip: pairs decode to the original bytes
    got = collections.defaultdict(list)
    mr.scan_kv(lambda k, v, p: got[int(k)].append(bytes(v)))
    assert {k: sorted(v) for k, v in got.items()} == \
        {k: sorted(v) for k, v in oracle.items()}
    # convert + host reduce sees decoded byte values per group
    mr.convert()
    sizes = {}
    mr.reduce(lambda k, vals, kv, p: (
        sizes.__setitem__(int(k), sorted(bytes(v) for v in vals)),
        kv.add(k, len(vals))))
    assert sizes == {k: sorted(v) for k, v in oracle.items()}


def test_bytes_keys_and_values_wordpair(mesh):
    """Both columns byte strings: (word, doc) pairs shuffle on ids for
    both sides and print/scan reconstruct bytes on both sides."""
    pairs = [(b"alpha", b"d1"), (b"beta", b"d2"), (b"alpha", b"d2"),
             (b"gamma", b"d3"), (b"beta", b"d1"), (b"alpha", b"d1")]
    mr = MapReduce(mesh)
    mr.map(1, lambda i, kv, p: [kv.add(k, v) for k, v in pairs])
    mr.aggregate()
    fr = mr.kv.one_frame()
    assert fr.key_decode is not None and fr.value_decode is not None
    got = []
    mr.scan_kv(lambda k, v, p: got.append((bytes(k), bytes(v))))
    assert sorted(got) == sorted(pairs)
    mr.convert()
    grouped = {}
    mr.scan_kmv(lambda k, vals, p: grouped.__setitem__(
        bytes(k), sorted(bytes(v) for v in vals)))
    oracle = collections.defaultdict(list)
    for k, v in pairs:
        oracle[k].append(v)
    assert grouped == {k: sorted(v) for k, v in oracle.items()}


def test_sort_interned_stays_on_device():
    """VERDICT r2 #7: sort_keys/sort_values on interned mesh columns run
    on device (rank surrogate) — no frame materialisation — and match
    the lexicographic oracle."""
    from gpu_mapreduce_tpu.parallel.sharded import ToHostStats

    words = [b"pear", b"apple", b"fig", b"zoo", b"beta", b"kiwi",
             b"mango", b"date", b"apple", b"fig"]
    mr = MapReduce(make_mesh(4))
    mr.map(1, lambda i, kv, p: [kv.add(w, np.uint64(j))
                                for j, w in enumerate(words)])
    mr.aggregate()
    snap = ToHostStats.snapshot()
    mr.sort_keys(5)
    assert ToHostStats.delta(snap) == (0, 0)
    got = []
    mr.scan_kv(lambda k, v, p: got.append(bytes(k)))
    assert got == sorted(words)
    snap = ToHostStats.snapshot()
    mr.sort_keys(-5)
    assert ToHostStats.delta(snap) == (0, 0)
    got = []
    mr.scan_kv(lambda k, v, p: got.append(bytes(k)))
    assert got == sorted(words, reverse=True)

    # interned VALUES sort by bytes too, on device
    mr2 = MapReduce(make_mesh(4))
    mr2.map(1, lambda i, kv, p: [kv.add(np.uint64(j), w)
                                 for j, w in enumerate(words)])
    mr2.aggregate()
    snap = ToHostStats.snapshot()
    mr2.sort_values(5)
    assert ToHostStats.delta(snap) == (0, 0)
    got = []
    mr2.scan_kv(lambda k, v, p: got.append(bytes(v)))
    assert got == sorted(words)


def test_one_sync_per_sharded_op(mesh):
    """VERDICT r2 #8: each sharded MR op costs exactly ONE controller
    round-trip — parity with the reference's one MPI_Allreduce per op
    (src/mapreduce.cpp:557-558).  A composed collate (aggregate+convert)
    therefore costs two, and a full composed-cc-style stage sequence
    stays at one sync per stage."""
    from gpu_mapreduce_tpu.parallel.sharded import SyncStats

    mr = MapReduce(mesh)
    mr.map(6, emit)

    snap = SyncStats.snapshot()
    mr.aggregate()
    assert SyncStats.delta(snap) == 1, "aggregate != 1 sync"

    snap = SyncStats.snapshot()
    mr.convert()
    assert SyncStats.delta(snap) == 1, "convert != 1 sync"

    from gpu_mapreduce_tpu.oink.kernels import count
    snap = SyncStats.snapshot()
    mr.reduce(count, batch=True)
    assert SyncStats.delta(snap) == 0, "batch reduce pulls mid-op"

    # correctness unchanged
    import collections
    oracle = collections.Counter(k for k, v in oracle_pairs())
    got = {}
    mr.scan_kv(lambda k, v, p: got.__setitem__(int(k), int(v)))
    assert got == dict(oracle)


def test_gather_reference_mod_layout(mesh):
    """gather(n): producing shard i's rows land on shard i % n — the
    reference's exact sender→receiver mapping ("lo procs recv from hi
    procs with same ID % numprocs", src/mapreduce.cpp:919-928)."""
    mr = MapReduce(mesh)
    keys = np.arange(64, dtype=np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.aggregate()
    before = mr.kv.one_frame()
    k_before = np.asarray(before.key)
    owner = {}
    for p in range(before.nprocs):
        blk = k_before[p * before.cap:p * before.cap + int(before.counts[p])]
        for k in blk.tolist():
            owner[k] = p
    mr.gather(3)            # n ∤ P: the layouts genuinely differ here
    after = mr.kv.one_frame()
    assert int(after.counts[:3].sum()) == 64
    k_after = np.asarray(after.key)
    for dest in range(3):
        blk = k_after[dest * after.cap:
                      dest * after.cap + int(after.counts[dest])]
        for k in blk.tolist():
            assert owner[k] % 3 == dest, (k, owner[k], dest)


def test_exchange_speculative_caps(mesh, monkeypatch):
    """r4 (VERDICT r3 weak #5): a repeat exchange with the same
    shapes speculates phase 2 with the cached caps so the count-matrix
    pull overlaps device work.  Three contracts: a same-distribution
    repeat runs phase 2 ONCE with the cached caps; a hub-skewed repeat
    whose buckets overflow the cached caps re-runs correctly sized
    (results always exact); sync count stays one per op."""
    from gpu_mapreduce_tpu.core.column import DenseColumn
    from gpu_mapreduce_tpu.core.frame import KVFrame
    from gpu_mapreduce_tpu.parallel import shuffle
    from gpu_mapreduce_tpu.parallel.sharded import SyncStats, shard_frame

    monkeypatch.setenv("MRTPU_WIRE", "0")  # the RAW caps under test
    #                                        (wire twin: test_wire.py)
    calls = []
    orig = shuffle._phase2_jit

    def spy(mesh_, transport, B, nrounds, cap_out, **kw):
        calls.append((B, nrounds, cap_out))
        return orig(mesh_, transport, B, nrounds, cap_out, **kw)

    monkeypatch.setattr(shuffle, "_phase2_jit", spy)
    shuffle._SPEC_CACHE.clear()
    rng = np.random.default_rng(5)
    n = 4096
    uni = rng.integers(0, 1 << 40, n).astype(np.uint64)
    vals = np.arange(n, dtype=np.uint64)

    def xchg(keys):
        skv = shard_frame(KVFrame(DenseColumn(keys), DenseColumn(vals)),
                          mesh)
        before = SyncStats.pulls
        out = shuffle.exchange(skv, ("hash", None))
        assert SyncStats.pulls - before == 1     # still one sync per op
        assert multiset(out.to_host().pairs()) == multiset(zip(keys, vals))

    xchg(uni)                       # cold: one fresh phase 2
    assert len(calls) == 1
    xchg(rng.permutation(uni))      # same distribution: speculation holds
    assert len(calls) == 2, "speculative hit must not re-run phase 2"
    assert calls[1] == calls[0]

    hub = uni.copy()
    hub[: n * 3 // 4] = hub[0]      # 75% on one key: cached caps overflow
    xchg(hub)
    assert len(calls) == 4, "overflowing speculation must re-run phase 2"
    assert calls[3][0] * calls[3][1] > calls[0][0] * calls[0][1]

    xchg(uni)                       # skewed caps fit uniform (Bmax small)
    spec_after = shuffle._SPEC_CACHE[next(iter(shuffle._SPEC_CACHE))]
    assert spec_after[0] == "raw"   # entries are tagged plans now
    assert len(calls) in (5, 6)     # hit (maybe oversized) or re-run
    if len(calls) == 5:             # held: cache must right-size if gross
        assert spec_after[3] <= 4 * calls[0][2]


def test_add_cross_domain_keys_group(mesh):
    """ADVICE r5 regression: a bytes-keyed dataset added to an
    object-keyed one must carry ONE id per logical key — the bytes-kind
    side re-interns through the pickle domain at concat
    (devkernels._align_domains), so equal keys group after collate."""
    mr1 = MapReduce(mesh)
    mr1.map(1, lambda i, kv, p: [kv.add(b"x", 1), kv.add(b"y", 2)])
    mr1.aggregate()
    assert mr1.kv.one_frame().key_decode.kind == "bytes"

    mr2 = MapReduce(mesh)
    # a tuple key forces the object tier, so b"x" here hashes over its
    # PICKLE — a different u64 than mr1's raw-bytes hash
    mr2.map(1, lambda i, kv, p: [kv.add(b"x", 3), kv.add((1, "t"), 4)])
    mr2.aggregate()
    assert mr2.kv.one_frame().key_decode.kind == "object"

    mr1.add(mr2)
    mr1.collate()
    groups = {}

    def take(k, vals, kv, ptr):
        key = tuple(k) if isinstance(k, (list, tuple)) else k
        groups[key] = sorted(int(v) for v in vals)
        kv.add(0, len(vals))

    mr1.reduce(take)
    assert groups[b"x"] == [1, 3]          # ONE group across both domains
    assert groups[b"y"] == [2]
    assert groups[(1, "t")] == [4]
