"""Test configuration: fake an 8-device cluster on CPU.

The reference tests "multi-node" code serially by linking mpistubs/ (a fake
1-proc MPI).  Our equivalent trick runs JAX on CPU with 8 virtual devices
(SURVEY.md §4), so mesh/sharding/collective code paths execute for real
without TPU hardware.  Must run before jax initialises its backends.
"""

import os

# FORCE (not setdefault): the outer environment may pin JAX_PLATFORMS to the
# TPU plugin ("axon"); subprocesses spawned by tests (the C-binding
# binaries embed Python) inherit os.environ and must get CPU like the test
# process itself does via jax.config below.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_enable_x64", True)
# the axon TPU plugin's register() forces jax_platforms="axon,cpu" via
# jax.config, which beats the env var — force it back to cpu for tests
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
