"""serve/ daemon tests — admission control, session isolation, tenant
budgets, warm plan-cache sharing, journaled crash recovery (kill -9
mid-queue replay + in-flight resume), and the obs/httpd request plane
satellites (doc/serve.md)."""

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from gpu_mapreduce_tpu.core.runtime import MRError
from gpu_mapreduce_tpu.serve import (AdmissionQueue, ServeClient,
                                     ServeError, Server, TenantBudgets,
                                     normalize_payload)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_corpus(path, words, repeat):
    path.write_text((" ".join(words) + " ") * repeat)
    return str(path)


def wf_script(corpus, top=3, out=None, fuse=False):
    lines = [f"variable files index {corpus}"]
    if fuse:
        lines.append("set fuse 1")
    lines.append(f"wordfreq {top} -i v_files" +
                 (f" -o {out} wf" if out else ""))
    return "\n".join(lines) + "\n"


@pytest.fixture
def server(tmp_path):
    """One in-process daemon on an ephemeral port; always shut down."""
    srv = Server(port=0, workers=2, queue_cap=8,
                 state_dir=str(tmp_path / "state"))
    srv.start()
    try:
        yield srv
    finally:
        srv.shutdown()


def client(srv) -> ServeClient:
    return ServeClient.local(srv.port)


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------

def test_normalize_payload():
    assert normalize_payload({"script": "mr x\n"}) == "mr x\n"
    assert normalize_payload({"ops": ["mr x", "x delete"]}) == \
        "mr x\nx delete\n"
    for bad in ({}, {"script": ""}, {"ops": []}, {"ops": [1]},
                {"script": "a", "ops": ["b"]}):
        with pytest.raises(MRError):
            normalize_payload(bad)


def test_admission_queue_bounds_and_force():
    q = AdmissionQueue(2)
    assert q.offer("a") and q.offer("b")
    assert not q.offer("c")          # full → reject
    assert q.stats()["rejects"] == 1
    assert q.offer("c", force=True)  # recovery replay path
    assert [q.take(0), q.take(0), q.take(0)] == ["a", "b", "c"]
    assert q.take(0.01) is None
    q.offer("d")
    q.close()
    assert q.take(0) == "d"          # close still drains accepted work
    assert q.take(0) is None
    assert not q.offer("e")          # closed → no new admissions


def test_oink_clear_preserves_namespace_defaults():
    # serve/ sessions carry tenant budget wiring in ObjectManager
    # defaults; a script-level `clear` must not shed it
    from gpu_mapreduce_tpu.oink import OinkScript
    s = OinkScript(screen=False)
    s.obj.set_default("memsize", 7)
    s.one("clear")
    assert s.obj.defaults["memsize"] == 7


# ---------------------------------------------------------------------------
# obs/httpd request-plane satellites
# ---------------------------------------------------------------------------

def test_ensure_server_returns_bound_port():
    from gpu_mapreduce_tpu.obs import httpd
    port = httpd.ensure_server(0)
    assert isinstance(port, int) and port > 0
    # idempotent: a second call reports the SAME bound port
    assert httpd.ensure_server(0) == port
    r = urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                               timeout=5)
    assert r.status == 200


def test_metrics_server_stop_drains_inflight():
    from gpu_mapreduce_tpu.obs.httpd import (MetricsServer,
                                             register_routes,
                                             unregister_routes)
    release = threading.Event()
    entered = threading.Event()

    def slow(method, path, body, headers):
        entered.set()
        release.wait(5)
        return 200, {"ok": True}, "application/json", None

    register_routes("/t-drain/", slow)
    srv = MetricsServer(port=0)
    port = srv.start()
    got = {}

    def fetch():
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/t-drain/x", timeout=10)
        got["status"] = r.status
        got["body"] = r.read()

    t = threading.Thread(target=fetch)
    t.start()
    assert entered.wait(5)
    stopper = threading.Thread(target=srv.stop)
    stopper.start()
    time.sleep(0.1)           # stop() is now waiting on the handler
    release.set()
    stopper.join(10)
    t.join(10)
    unregister_routes("/t-drain/")
    # the in-flight response completed despite the concurrent stop()
    assert got.get("status") == 200 and b"ok" in got.get("body", b"")
    assert not srv.running


# ---------------------------------------------------------------------------
# API round-trip
# ---------------------------------------------------------------------------

def test_submit_roundtrip_script_and_ops(server, tmp_path):
    c = client(server)
    corpus = write_corpus(tmp_path / "w.txt", ["to", "be", "or"], 40)
    r = c.submit(script=wf_script(corpus))
    assert r["state"] == "queued" and r["id"]
    res = c.wait(r["id"])
    assert res["status"] == "done"
    assert "1 files, 120 words, 3 unique" in res["output"]
    # the same workload as a JSON ops batch
    r2 = c.submit(ops=[f"variable files index {corpus}",
                       "wordfreq 3 -i v_files"], tenant="opsy")
    res2 = c.wait(r2["id"])
    assert res2["status"] == "done"
    assert res2["output"] == res["output"]
    # status/list/stats surfaces
    st = c.status(r["id"])
    assert st["state"] == "done" and st["tenant"] == "default"
    assert {j["id"] for j in c.jobs()} >= {r["id"], r2["id"]}
    stats = c.stats()
    assert stats["sessions"]["by_state"]["done"] >= 2
    assert stats["queue"]["cap"] == 8


def test_failed_session_reports_error(server):
    c = client(server)
    r = c.submit(script="frobnicate 1 2\n")
    res = c.wait(r["id"])
    assert res["status"] == "failed"
    assert "Unknown command" in res["error"]
    # a failed session never kills the worker: the next one runs
    r2 = c.submit(ops=["mr x", "x delete"])
    assert c.wait(r2["id"])["status"] == "done"


def test_unknown_session_404(server):
    c = client(server)
    with pytest.raises(ServeError) as ei:
        c.result("s999999")
    assert ei.value.code == 404
    with pytest.raises(ServeError) as ei:
        c.status("s999999")
    assert ei.value.code == 404


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_backpressure_429(tmp_path):
    srv = Server(port=0, workers=0, queue_cap=2,
                 state_dir=str(tmp_path / "state"), paused=True)
    srv.start()
    try:
        c = client(srv)
        ids = [c.submit(ops=["mr x"])["id"] for _ in range(2)]
        assert len(ids) == 2
        with pytest.raises(ServeError) as ei:
            c.submit(ops=["mr x"])
        assert ei.value.code == 429
        assert ei.value.retry_after >= 1
        assert srv.queue.stats()["rejects"] >= 1
        st = c.stats()
        assert st["queue"]["depth"] == 2
    finally:
        srv.shutdown()


def test_drain_rejects_new_work(server, tmp_path):
    c = client(server)
    assert c.drain()["draining"]
    with pytest.raises(ServeError) as ei:
        c.submit(ops=["mr x"])
    assert ei.value.code == 503
    assert ei.value.retry_after is not None


# ---------------------------------------------------------------------------
# session isolation + tenant budgets
# ---------------------------------------------------------------------------

def test_concurrent_sessions_namespace_isolation(server, tmp_path):
    """Two tenants running the SAME script shape (`mr x`, same MR and
    variable names) concurrently: a shared namespace would fail the
    second `mr x` with "already in use" — isolation means both succeed
    with their own data."""
    c = client(server)
    ca = write_corpus(tmp_path / "a.txt", ["alpha", "beta"], 30)
    cb = write_corpus(tmp_path / "b.txt", ["gamma", "delta", "eps"], 20)

    def script(corpus):
        return (f"mr x\n"
                f"variable files index {corpus}\n"
                f"wordfreq 5 -i v_files -o NULL x2\n")

    ra = c.submit(script=script(ca), tenant="a")
    rb = c.submit(script=script(cb), tenant="b")
    res_a = c.wait(ra["id"])
    res_b = c.wait(rb["id"])
    assert res_a["status"] == "done" and res_b["status"] == "done"
    assert "60 words, 2 unique" in res_a["output"]
    assert "60 words, 3 unique" in res_b["output"]
    # per-tenant session metrics carry the right labels
    from gpu_mapreduce_tpu.obs.metrics import get_registry
    snap = get_registry().collect()
    tenants = {s["labels"]["tenant"]
               for s in snap["mrtpu_serve_sessions_total"]["samples"]}
    assert {"a", "b"} <= tenants


def test_tenant_budget_isolation_and_labels(tmp_path):
    """Tenant A outgrows its page budget and SPILLS (through the
    core/ page machinery, into its own session scratch); tenant B's
    resident pages are untouched — B spills nothing, and each tenant's
    pages gauge reads its own account."""
    budgets = TenantBudgets(pages=1, memsize=1)    # 1 MB allowance
    srv = Server(port=0, workers=2, queue_cap=8,
                 state_dir=str(tmp_path / "state"), budgets=budgets)
    srv.start()
    try:
        c = client(srv)
        big = write_corpus(tmp_path / "big.txt",
                           [f"w{i:04d}" for i in range(200)], 2000)
        small = write_corpus(tmp_path / "small.txt", ["tiny", "data"], 10)
        assert os.path.getsize(big) > 2 * (1 << 20)
        ra = c.submit(script=wf_script(big, top=2), tenant="a")
        rb = c.submit(script=wf_script(small, top=2), tenant="b")
        res_a = c.wait(ra["id"], timeout=240)
        res_b = c.wait(rb["id"])
        assert res_a["status"] == "done" and res_b["status"] == "done"
        pages_a = res_a["meta"]["pages"]
        pages_b = res_b["meta"]["pages"]
        assert pages_a["tenant"] == "a" and pages_b["tenant"] == "b"
        # A paid spill I/O for its overage; B never did
        assert pages_a["spilled_bytes"] > 0
        assert pages_b["spilled_bytes"] == 0
        # per-tenant gauge labels, independent accounts
        from gpu_mapreduce_tpu.obs.metrics import get_registry
        snap = get_registry().collect()
        by_tenant = {s["labels"]["tenant"]: s["value"]
                     for s in snap["mrtpu_tenant_pages"]["samples"]}
        assert {"a", "b"} <= set(by_tenant)
        # the server-side stats surface sees both accounts too
        st = c.stats()["tenants"]
        assert st["a"]["spilled_bytes"] > 0
        assert st["b"]["spilled_bytes"] == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# warm cross-session plan cache
# ---------------------------------------------------------------------------

def test_repeated_request_hits_shared_plan_cache(server, tmp_path):
    """The acceptance assertion: an identical second request compiles
    NOTHING — the fleet-wide plan cache (PR 2's LRU) serves it, and the
    dispatch count matches the first run."""
    c = client(server)
    corpus = write_corpus(tmp_path / "w.txt",
                          ["to", "be", "or", "not"], 50)
    script = wf_script(corpus, fuse=True)
    cold = c.wait(c.submit(script=script)["id"])
    warm = c.wait(c.submit(script=script)["id"])
    assert cold["status"] == "done" and warm["status"] == "done"
    assert warm["output"] == cold["output"]
    pc_cold = cold["meta"]["plan_cache"]["plan"]
    pc_warm = warm["meta"]["plan_cache"]["plan"]
    assert pc_cold["misses"] > 0            # cold run built the plans
    assert pc_warm["misses"] == 0           # warm run recompiled nothing
    assert pc_warm["hits"] >= pc_cold["misses"]
    assert warm["meta"]["dispatches"] == cold["meta"]["dispatches"]


# ---------------------------------------------------------------------------
# journaled crash recovery
# ---------------------------------------------------------------------------

def _spawn_daemon(state, extra):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    p = subprocess.Popen(
        [sys.executable, "-m", "gpu_mapreduce_tpu.serve",
         "--port", "0", "--state", state] + extra,
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL)
    line = json.loads(p.stdout.readline())
    return p, int(line["serving"])


def test_kill9_mid_queue_replay_byte_identical(tmp_path):
    """The acceptance golden: kill -9 a daemon with a populated queue;
    the restarted daemon replays the journal and produces results
    byte-identical to an uninterrupted daemon's."""
    corpora = [write_corpus(tmp_path / f"c{i}.txt",
                            [f"w{j}" for j in range(i + 2)], 30 + i)
               for i in range(3)]
    scripts = [wf_script(c, top=5, out=f"tmp.wf{i}")
               for i, c in enumerate(corpora)]

    # golden: an uninterrupted in-process daemon
    gold_srv = Server(port=0, workers=1,
                      state_dir=str(tmp_path / "golden"))
    gold_srv.start()
    try:
        gc = client(gold_srv)
        golden = [gc.wait(gc.submit(script=s)["id"]) for s in scripts]
    finally:
        gold_srv.shutdown()
    assert all(g["status"] == "done" for g in golden)

    # phase 1: paused daemon journals the queue, then SIGKILL
    state = str(tmp_path / "state")
    p, port = _spawn_daemon(state, ["--paused"])
    try:
        c = ServeClient.local(port)
        sids = [c.submit(script=s)["id"] for s in scripts]
        assert c.stats()["queue"]["depth"] == 3
    finally:
        os.kill(p.pid, signal.SIGKILL)
        p.wait()

    # phase 2: restart live; the queue replays in admission order
    p2, port2 = _spawn_daemon(state, ["--workers", "2"])
    try:
        c2 = ServeClient.local(port2)
        replayed = [c2.wait(sid, timeout=120) for sid in sids]
        for got, want in zip(replayed, golden):
            assert got["status"] == "done"
            assert got["output"] == want["output"]
            assert {k: v["sha256"] for k, v in got["files"].items()} == \
                {k: v["sha256"] for k, v in want["files"].items()}
        c2.shutdown()
        p2.wait(timeout=30)
    finally:
        if p2.poll() is None:
            p2.kill()
            p2.wait()


def test_inflight_session_resumes_from_checkpoint(tmp_path):
    """A session that died MID-RUN (journal holds begin+cmd+ckpt in its
    session dir) resumes from the checkpoint on the replayed attempt:
    the already-checkpointed command is skipped, output files come out
    byte-identical, and the result is flagged ``resumed``."""
    from gpu_mapreduce_tpu.ft.journal import Journal
    from gpu_mapreduce_tpu.oink.script import OinkScript

    corpus = write_corpus(tmp_path / "w.txt", ["p", "q", "p", "r"], 25)
    script_text = (f"variable files index {corpus}\n"
                   f"wordfreq 3 -i v_files -o tmp.wf wf\n"
                   f"print \"after-ckpt marker\"\n")

    # golden full run
    gold = Server(port=0, workers=1, state_dir=str(tmp_path / "golden"))
    gold.start()
    try:
        gc = client(gold)
        golden = gc.wait(gc.submit(script=script_text)["id"])
    finally:
        gold.shutdown()

    # manufacture the crashed in-flight session: journal + checkpoint
    # exactly as run_session would have left them mid-run
    state = str(tmp_path / "state")
    sdir = os.path.join(state, "sessions", "s000001")
    outdir = os.path.join(sdir, "out")
    os.makedirs(outdir, exist_ok=True)
    crash = OinkScript(screen=io.StringIO())
    crash._ft_journal = Journal(sdir, script_mode=True, every=1)
    crash._path_prepend = outdir
    lines = script_text.splitlines()
    crash._ft_pending_begin = (lines, "<serve>")
    for ln in lines[:2]:          # dies before the print command
        crash.one(ln)
    crash._ft_journal.close()

    boot = Server(port=0, workers=0, state_dir=state, paused=True)
    boot.start()
    try:
        assert client(boot).submit(script=script_text)["id"] == "s000001"
    finally:
        boot.shutdown()

    srv = Server(port=0, workers=1, state_dir=state)
    srv.start()
    try:
        res = client(srv).wait("s000001")
    finally:
        srv.shutdown()
    assert res["status"] == "done"
    assert res["meta"]["resumed"] is True
    # the checkpointed wordfreq was NOT re-executed: only the
    # post-checkpoint command's output replays...
    assert res["output"] == 'after-ckpt marker \n'
    # ...but the session's FILES are byte-identical to the golden run
    assert {k: v["sha256"] for k, v in res["files"].items()} == \
        {k: v["sha256"] for k, v in golden["files"].items()}


def test_clear_inside_script_reports_live_namespace(server, tmp_path):
    """`clear` swaps the interpreter's ObjectManager; the session must
    report (and account-scope-release) the LIVE namespace, not the one
    captured before the run (regression: post-clear MRs were invisible
    and their frames never deflated the tenant gauge)."""
    c = client(server)
    corpus = write_corpus(tmp_path / "w.txt", ["post", "clear"], 10)
    res = c.wait(c.submit(script=(
        f"mr pre\n"
        f"clear\n"
        f"variable files index {corpus}\n"
        f"wordfreq 2 -i v_files -o NULL after\n"))["id"])
    assert res["status"] == "done", res["error"]
    assert "after" in res["mrs"] and "pre" not in res["mrs"]


def test_budget_settings_are_pinned_against_tenant_set(tmp_path):
    """An armed tenant budget must survive the script's own `set`: a
    tenant raising maxpage past its allowance fails loudly instead of
    running unbounded (regression: `set` silently overrode the
    daemon-seeded budget defaults)."""
    budgets = TenantBudgets(pages=1, memsize=1)
    srv = Server(port=0, workers=1, queue_cap=4,
                 state_dir=str(tmp_path / "state"), budgets=budgets)
    srv.start()
    try:
        c = client(srv)
        res = c.wait(c.submit(script="set maxpage 100000\nmr x\n",
                              tenant="evil")["id"])
        assert res["status"] == "failed"
        assert "pinned" in res["error"]
        # pins survive a script-level clear too
        res2 = c.wait(c.submit(script="clear\nset memsize 4096\n",
                               tenant="evil")["id"])
        assert res2["status"] == "failed" and "pinned" in res2["error"]
    finally:
        srv.shutdown()


def test_journal_survives_torn_tail_across_restarts(tmp_path):
    """A kill -9 mid-append leaves a torn final journal line; the
    reopened journal must seal it (no merge with the next record) and
    the reader must skip it (no silent drop of later records)."""
    from gpu_mapreduce_tpu.ft.journal import Journal, read_journal
    d = str(tmp_path / "j")
    j = Journal(d, script_mode=True)
    j.append({"kind": "serve_submit", "sid": "s1"})
    j.close()
    with open(j.path, "a") as f:
        f.write('{"kind": "serve_sub')      # torn mid-append, no \n
    j2 = Journal(d, script_mode=True)       # reopen = restart
    j2.append({"kind": "serve_submit", "sid": "s2"})
    j2.close()
    kinds = [(r.get("kind"), r.get("sid")) for r in read_journal(d)]
    assert ("serve_submit", "s1") in kinds
    assert ("serve_submit", "s2") in kinds  # not merged into the tear


def test_set_prepend_stays_inside_session_dir(server, tmp_path):
    """The reference `set prepend` idiom keeps working in a session but
    re-roots UNDER the session's out dir; an absolute prepend (which
    would silently move -o files out of the sandbox and off the result)
    fails the session loudly."""
    c = client(server)
    corpus = write_corpus(tmp_path / "w.txt", ["pre", "pend"], 10)
    res = c.wait(c.submit(script=(
        f"set prepend sub\n"
        f"variable files index {corpus}\n"
        f"wordfreq 2 -i v_files -o nested.wf wf\n"))["id"])
    assert res["status"] == "done", res["error"]
    assert "sub/nested.wf" in res["files"]         # re-rooted, captured
    res2 = c.wait(c.submit(script="set prepend /tmp\nmr x\n")["id"])
    assert res2["status"] == "failed" and "pinned" in res2["error"]


def test_env_journal_does_not_break_sessions(tmp_path, monkeypatch):
    """MRTPU_JOURNAL in the daemon's environment arms a process-global
    script journal on every OinkScript — sessions must deactivate it
    (not just close it) or their first barrier op writes to a closed
    file and every job fails (regression: confirmed live repro)."""
    from gpu_mapreduce_tpu.ft import journal as ftj
    monkeypatch.setenv("MRTPU_JOURNAL", str(tmp_path / "globaljournal"))
    srv = Server(port=0, workers=1, state_dir=str(tmp_path / "state"))
    srv.start()
    try:
        c = client(srv)
        corpus = write_corpus(tmp_path / "w.txt", ["env", "j"], 20)
        res = c.wait(c.submit(script=wf_script(corpus, top=2))["id"])
        assert res["status"] == "done", res["error"]
        # and the session journaled into its OWN directory regardless
        assert os.path.exists(os.path.join(
            srv.session_dir(res["id"]), "journal.jsonl"))
    finally:
        srv.shutdown()
        ftj.reset()


# ---------------------------------------------------------------------------
# mrctl
# ---------------------------------------------------------------------------

def test_mrctl_cli(server, tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import mrctl
    finally:
        sys.path.pop(0)
    corpus = write_corpus(tmp_path / "w.txt", ["cli", "test"], 15)
    script = tmp_path / "job.oink"
    script.write_text(wf_script(corpus, top=2))
    rc = mrctl.main(["--port", str(server.port), "submit", str(script),
                     "--tenant", "ops", "--wait"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out)
    assert rec["status"] == "done" and "30 words, 2 unique" in \
        rec["output"]
    assert mrctl.main(["--port", str(server.port), "stats"]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["sessions"]["by_state"]["done"] >= 1
    # state-dir discovery path (ephemeral daemon, serve.json)
    rc = mrctl.main(["--state", server.state_dir, "status"])
    assert rc == 0


# ---------------------------------------------------------------------------
# elastic-recovery satellites: quotas, priority, TTL GC, degraded mode
# ---------------------------------------------------------------------------

def test_admission_queue_priority_order():
    q = AdmissionQueue(8)
    q.offer("low1", priority=0)
    q.offer("hi", priority=5)
    q.offer("low2", priority=0)
    q.offer("mid", priority=2)
    assert [q.take(0) for _ in range(4)] == ["hi", "mid", "low1", "low2"]


def test_tenant_rate_limiter_isolated_buckets():
    from gpu_mapreduce_tpu.serve.admission import TenantRateLimiter
    rl = TenantRateLimiter(rate=1.0, burst=2)
    now = 1000.0
    assert rl.check("a", now)[0] and rl.check("a", now)[0]
    ok, ra = rl.check("a", now)          # bucket drained
    assert not ok and 0 < ra <= 1.0
    assert rl.check("b", now)[0], "tenant b must not share a's bucket"
    ok, _ = rl.check("a", now + 1.0)     # one token refilled
    assert ok
    assert TenantRateLimiter(rate=0.0).check("x")[0]   # 0 = off


def test_rate_limited_submit_429_per_tenant(tmp_path):
    """A tenant past its rate gets 429 + its OWN Retry-After; other
    tenants are untouched; decisions land in the per-tenant metric."""
    from gpu_mapreduce_tpu.serve.admission import TenantRateLimiter
    srv = Server(port=0, workers=0, paused=True,
                 state_dir=str(tmp_path / "state"))
    srv.ratelimit = TenantRateLimiter(rate=0.001, burst=1)
    srv.start()
    try:
        c = client(srv)
        assert c.submit(script="mr x\n", tenant="noisy")["id"]
        with pytest.raises(ServeError) as ei:
            c.submit(script="mr x\n", tenant="noisy")
        assert ei.value.code == 429
        assert ei.value.retry_after >= 1
        # a different tenant is admitted right through
        assert c.submit(script="mr x\n", tenant="quiet")["id"]
        from gpu_mapreduce_tpu.obs.metrics import get_registry
        m = get_registry().counter("mrtpu_serve_admission_total", "",
                                   ("outcome", "tenant"))
        assert m.value(outcome="throttled", tenant="noisy") >= 1
        assert m.value(outcome="accepted", tenant="quiet") >= 1
    finally:
        srv.shutdown()


def test_submit_priority_recorded_and_replayed(tmp_path):
    """Priority rides the journal: a paused daemon's replayed queue
    drains high-priority sessions first on restart."""
    state = str(tmp_path / "state")
    srv = Server(port=0, workers=0, paused=True, state_dir=state)
    srv.start()
    try:
        c = client(srv)
        lo = c.submit(script="mr x\n", priority=0)["id"]
        hi = c.submit(script="mr x\n", priority=7)["id"]
        assert c.status(hi)["priority"] == 7
    finally:
        srv.shutdown()
    srv2 = Server(port=0, workers=0, paused=True, state_dir=state)
    srv2.start()
    try:
        first = srv2.queue.take(0)
        assert first.sid == hi and first.priority == 7
        assert srv2.queue.take(0).sid == lo
    finally:
        srv2.shutdown()


def test_session_ttl_gc_journaled(tmp_path):
    """Done sessions past MRTPU_SERVE_TTL are swept — journaled intent
    first, dirs+result removed, dropped from the listing — and a
    restart neither lists nor replays them (the GC'd sid is terminal)."""
    state = str(tmp_path / "state")
    srv = Server(port=0, workers=1, state_dir=state)
    srv.ttl_s = 0.05
    srv.start()
    try:
        c = client(srv)
        sid = c.submit(script="mr x\n")["id"]
        assert c.wait(sid)["status"] == "done"
        sdir = srv.session_dir(sid)
        assert os.path.isdir(sdir)
        time.sleep(0.08)
        assert srv._gc_once() == 1
        assert not os.path.exists(sdir)
        assert not os.path.exists(srv.result_path(sid))
        with pytest.raises(ServeError) as ei:
            c.status(sid)
        assert ei.value.code == 404
        from gpu_mapreduce_tpu.ft.journal import read_journal
        kinds = [r["kind"] for r in read_journal(state)]
        assert "serve_gc" in kinds
    finally:
        srv.shutdown()
    # a live (queued/running) session is never GC'd and a restart
    # neither lists nor replays the swept one
    srv2 = Server(port=0, workers=0, paused=True, state_dir=state)
    srv2.start()
    try:
        assert sid not in srv2.sessions
        assert srv2.queue.depth() == 0
    finally:
        srv2.shutdown()


def test_gc_kill_mid_delete_finishes_on_restart(tmp_path):
    """kill -9 between the serve_gc intent record and the delete: the
    restart finishes the sweep instead of resurrecting the session."""
    state = str(tmp_path / "state")
    srv = Server(port=0, workers=1, state_dir=state)
    srv.start()
    try:
        c = client(srv)
        sid = c.submit(script="mr x\n")["id"]
        assert c.wait(sid)["status"] == "done"
        # intent journaled, then "killed" before _gc_files ran
        srv._journal.append({"kind": "serve_gc", "sid": sid,
                             "tenant": "default"})
    finally:
        srv.shutdown()
    assert os.path.isdir(os.path.join(state, "sessions", sid))
    srv2 = Server(port=0, workers=0, paused=True, state_dir=state)
    srv2.start()
    try:
        assert sid not in srv2.sessions
        assert not os.path.exists(os.path.join(state, "sessions", sid))
    finally:
        srv2.shutdown()


def test_degraded_restart_resumes_on_available_mesh(tmp_path):
    """Tentpole (4): a session checkpointed on a 4-shard mesh resumes
    on a daemon restarted with only 2 shards — the recovered tail's
    files are byte-identical to an uninterrupted 2-shard daemon's run,
    and the result carries ``meta.resharded``."""
    from gpu_mapreduce_tpu.ft.journal import Journal
    from gpu_mapreduce_tpu.oink.script import OinkScript
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    corpus = write_corpus(tmp_path / "w.txt", ["p", "q", "p", "r"], 25)
    script_text = (f"variable files index {corpus}\n"
                   f"wordfreq 3 -i v_files -o NULL wf\n"
                   f"wordfreq 2 -i v_files -o tmp.out NULL\n")

    gold = Server(port=0, workers=1, comm=make_mesh(2),
                  state_dir=str(tmp_path / "golden"))
    gold.start()
    try:
        gc = client(gold)
        golden = gc.wait(gc.submit(script=script_text)["id"])
    finally:
        gold.shutdown()
    assert golden["status"] == "done"

    # manufacture the crashed 4-shard in-flight session (checkpoint
    # after the first wordfreq, death before the output-writing one)
    state = str(tmp_path / "state")
    sdir = os.path.join(state, "sessions", "s000001")
    outdir = os.path.join(sdir, "out")
    os.makedirs(outdir, exist_ok=True)
    crash = OinkScript(comm=make_mesh(4), screen=io.StringIO())
    crash._ft_journal = Journal(sdir, script_mode=True, every=1)
    crash._path_prepend = outdir
    lines = script_text.splitlines()
    crash._ft_pending_begin = (lines, "<serve>")
    for ln in lines[:2]:
        crash.one(ln)
    crash._ft_journal.close()

    boot = Server(port=0, workers=0, state_dir=state, paused=True)
    boot.start()
    try:
        assert client(boot).submit(script=script_text)["id"] == "s000001"
    finally:
        boot.shutdown()

    srv = Server(port=0, workers=1, comm=make_mesh(2), state_dir=state)
    srv.start()
    try:
        assert srv.stats()["mesh"]["nprocs"] == 2
        res = client(srv).wait("s000001")
    finally:
        srv.shutdown()
    assert res["status"] == "done"
    assert res["meta"]["resumed"] is True
    assert res["meta"]["resharded"] is True
    assert {k: v["sha256"] for k, v in res["files"].items()} == \
        {k: v["sha256"] for k, v in golden["files"].items()}


# ---------------------------------------------------------------------------
# request-scoped tracing + exact per-request attribution (ISSUE 9)
# ---------------------------------------------------------------------------

def test_concurrent_sessions_meta_deltas_exact(tmp_path):
    """THE regression for the retired exact-only-when-idle caveat: two
    sessions run CONCURRENTLY (workers=2) — a spill-heavy one and a
    light one — and each result's meta/profile shows exactly its own
    traffic.  Before the RequestAccount scope, the light session's
    deltas bracketed process-global counters and inhaled its
    neighbor's spill bytes."""
    budgets = TenantBudgets(pages=1, memsize=1)    # force A to spill
    srv = Server(port=0, workers=2, queue_cap=8,
                 state_dir=str(tmp_path / "state"), budgets=budgets)
    srv.start()
    try:
        c = client(srv)
        big = write_corpus(tmp_path / "big.txt",
                           [f"w{i:04d}" for i in range(200)], 2000)
        small = write_corpus(tmp_path / "small.txt", ["tiny", "data"],
                             10)
        ra = c.submit(script=wf_script(big, top=2), tenant="heavy")
        rb = c.submit(script=wf_script(small, top=2), tenant="light")
        res_a = c.wait(ra["id"], timeout=240)
        res_b = c.wait(rb["id"], timeout=240)
        assert res_a["status"] == "done" and res_b["status"] == "done"
        prof_a = res_a["meta"]["profile"]
        prof_b = res_b["meta"]["profile"]
        # distinct request identities, stamped everywhere
        assert res_a["meta"]["trace_id"] != res_b["meta"]["trace_id"]
        assert prof_a["trace_id"] == res_a["meta"]["trace_id"]
        # A really spilled; B's account saw NONE of it, even though
        # both ran on one process's shared global counters
        assert prof_a["spill"]["write_bytes"] > 0
        assert prof_b["spill"]["write_bytes"] == 0
        assert prof_b["spill"]["read_bytes"] == 0
        # stage tables are per-request too
        assert "oink.wordfreq" in prof_a["stages"]
        assert "oink.wordfreq" in prof_b["stages"]
    finally:
        srv.shutdown()


def test_session_trace_id_links_every_artifact(server, tmp_path):
    """One request, one id: the 202, result meta, /profile, the
    session journal records, and the session's spans on any trace sink
    (the serve-worker half of the propagation goldens)."""
    import gpu_mapreduce_tpu.obs as obs
    from gpu_mapreduce_tpu.ft.journal import read_journal
    trace_path = str(tmp_path / "serve_trace.jsonl")
    obs.get_tracer().enable(jsonl=trace_path)
    c = client(server)
    corpus = write_corpus(tmp_path / "w.txt", ["to", "be", "or"], 40)
    r = c.submit(script=wf_script(corpus), tenant="acme")
    tid = r["trace_id"]
    assert tid
    res = c.wait(r["id"])
    assert res["status"] == "done"
    assert res["meta"]["trace_id"] == tid
    assert res["meta"]["profile"]["trace_id"] == tid
    assert c.status(r["id"])["trace_id"] == tid
    # /profile serves the same id (durable once finished)
    prof = c.profile(r["id"])
    assert prof["trace_id"] == tid and prof["live"] is False
    assert prof["profile"]["stages"].get("oink.wordfreq")
    # session journal records are stamped
    recs = read_journal(os.path.join(server.state_dir, "sessions",
                                     r["id"]))
    assert recs and all(rec.get("trace") == tid for rec in recs)
    # the worker's spans carry it on the shared JSONL sink
    mine = [e for e in obs.read_jsonl(trace_path)
            if e.get("trace") == tid]
    assert any(e["name"] == "oink.wordfreq" for e in mine)
    # the serve journal's submit record carries it (replay keeps ids)
    srecs = read_journal(server.state_dir)
    sub = [x for x in srecs if x.get("kind") == "serve_submit"
           and x.get("sid") == r["id"]]
    assert sub and sub[0]["trace"] == tid


def test_events_stream_live_no_polling(server, tmp_path):
    """/v1/jobs/<id>/events: ONE streamed request observes the running
    transition, at least one top-level span, the final profile, and
    the terminal status — no client polling."""
    c = client(server)
    blocker = write_corpus(tmp_path / "blk.txt",
                           [f"w{i:03d}" for i in range(100)], 1500)
    corpus = write_corpus(tmp_path / "w.txt", ["to", "be", "or"], 40)
    # saturate both workers so the watched session stays queued until
    # the stream is attached
    rb1 = c.submit(script=wf_script(blocker, top=2))
    rb2 = c.submit(script=wf_script(blocker, top=2))
    r = c.submit(script=wf_script(corpus))
    seen = list(c.events(r["id"], timeout=120))
    kinds = [e["event"] for e in seen]
    states = [e.get("state") for e in seen if e["event"] == "status"]
    assert states[0] in ("queued", "running", "done")
    assert states[-1] == "done"                    # stream ends terminal
    if states[0] == "queued":                      # attached in time:
        assert "running" in states                # saw the transition
    assert any(e["event"] == "profile" for e in seen)
    prof = [e for e in seen if e["event"] == "profile"][-1]["profile"]
    assert prof["trace_id"] == r["trace_id"]
    c.wait(rb1["id"], timeout=240)
    c.wait(rb2["id"], timeout=240)
    # a finished session's stream replays profile THEN the terminal
    # status (the live ordering: a client stopping at the terminal
    # marker has already seen the profile) and ends
    replay = list(c.events(r["id"], timeout=60))
    assert [e["event"] for e in replay] == ["profile", "status"]
    assert replay[-1]["state"] == "done"
    # unknown session: a clean 404, not a stream
    with pytest.raises(ServeError) as ei:
        list(c.events("nope"))
    assert ei.value.code == 404


def test_slo_endpoint_and_burn(server, monkeypatch):
    import gpu_mapreduce_tpu.obs.slo as obs_slo
    monkeypatch.setenv("MRTPU_SLO",
                       "tenant=*;p99_ms=60000;err_pct=1;windows=60,600")
    obs_slo.reset()                      # re-read the env spec
    try:
        c = client(server)
        # three failing sessions for a fresh tenant → err burn >> 1
        for _ in range(3):
            r = c.submit(script="frobnicate\n", tenant="slo-t")
            assert c.wait(r["id"])["status"] == "failed"
        out = c.slo()
        assert out["objectives"], out
        assert out["burn"]["slo-t"]["60s"] > 1.0
        assert "slo-t" in out["firing"]
        # the burn gauge landed in the registry
        from gpu_mapreduce_tpu.obs.metrics import get_registry
        samples = get_registry().collect()[
            "mrtpu_slo_burn_ratio"]["samples"]
        assert any(s["labels"]["tenant"] == "slo-t" for s in samples)
    finally:
        obs_slo.reset()


def test_mrctl_profile_watch_slo(server, tmp_path, capsys):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mrctl", os.path.join(REPO, "scripts", "mrctl.py"))
    mrctl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mrctl)
    c = client(server)
    corpus = write_corpus(tmp_path / "w.txt", ["to", "be", "or"], 40)
    r = c.submit(script=wf_script(corpus))
    c.wait(r["id"])
    port = ["--port", str(server.port)]
    assert mrctl.main(port + ["profile", r["id"]]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["trace_id"] == r["trace_id"]
    assert out["profile"]["dispatches"] >= 0
    # watch on a finished session: prints the profile and the terminal
    # status (in that order — the stop-at-terminal client still gets
    # the profile), exit 0
    assert mrctl.main(port + ["watch", r["id"]]) == 0
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    assert [ln["event"] for ln in lines] == ["profile", "status"]
    assert lines[-1]["state"] == "done"
    # slo subcommand round-trips
    assert mrctl.main(port + ["slo"]) == 0
    json.loads(capsys.readouterr().out)
    # failed session → watch exits 5
    rf = c.submit(script="frobnicate\n")
    c.wait(rf["id"])
    assert mrctl.main(port + ["watch", rf["id"]]) == 5
    capsys.readouterr()
