"""Request-scoped trace context, exact per-request cost attribution,
and the tenant SLO engine (obs/context.py, obs/slo.py) — plus the
operator surfaces that ride them: /v1/jobs/<id>/{profile,events},
/v1/slo, mrctl profile/watch, trace_view --trace, and the
metric-catalog lint."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from gpu_mapreduce_tpu.core.mapreduce import MapReduce
from gpu_mapreduce_tpu.core.runtime import global_counters
from gpu_mapreduce_tpu.obs import context as obs_context
from gpu_mapreduce_tpu.obs import slo as obs_slo
from gpu_mapreduce_tpu.obs import get_tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")


def load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def ctx_state():
    """Reset the process-global tracer/registry/flight/context/SLO
    state around every test — attribution must never leak across."""
    from gpu_mapreduce_tpu.obs import flight, metrics

    def _reset():
        get_tracer().reset()
        metrics.reset()
        flight.reset()
        obs_context.reset()
        obs_slo.reset()

    _reset()
    yield
    _reset()


# ---------------------------------------------------------------------------
# RequestAccount + scopes
# ---------------------------------------------------------------------------

def test_request_scope_charges_exactly_this_scope():
    with obs_context.request_scope(tenant="t", label="a") as acct:
        global_counters().add(cssize=100, cspad=10, wsize=7,
                              ndispatch=3)
        global_counters().mem(4096)
        global_counters().mem(-4096)
    prof = acct.profile()
    assert prof["exchange"]["sent_bytes"] == 100
    assert prof["exchange"]["pad_bytes"] == 10
    assert prof["spill"]["write_bytes"] == 7
    assert prof["dispatches"] == 3
    assert prof["hbm"]["hi_water_bytes"] == 4096
    assert prof["tenant"] == "t" and prof["trace_id"]
    # after the scope closes, charges no longer land on it
    global_counters().add(cssize=999)
    assert acct.profile()["exchange"]["sent_bytes"] == 100


def test_two_threads_never_bleed_synthetic():
    """The mechanism itself: two concurrent scopes hammering the SAME
    process-global counters each see exactly their own deltas."""
    accounts = {}
    barrier = threading.Barrier(2)

    def work(name, n, nbytes):
        with obs_context.request_scope(label=name) as acct:
            accounts[name] = acct
            barrier.wait()
            for _ in range(n):
                global_counters().add(cssize=nbytes, ndispatch=1)
    ta = threading.Thread(target=work, args=("a", 200, 13))
    tb = threading.Thread(target=work, args=("b", 300, 7))
    ta.start(); tb.start(); ta.join(); tb.join()
    pa, pb = accounts["a"].profile(), accounts["b"].profile()
    assert pa["exchange"]["sent_bytes"] == 200 * 13
    assert pb["exchange"]["sent_bytes"] == 300 * 7
    assert pa["dispatches"] == 200 and pb["dispatches"] == 300


def test_two_threads_never_bleed_real_workload(tmp_path):
    """Real MR work: a spill-heavy external sort in scope A, a pure
    in-memory pipeline in scope B, concurrently.  B's account shows
    ZERO spill traffic even while A spills next door — the
    exact-under-concurrency contract."""
    accounts = {}
    barrier = threading.Barrier(2)
    keys = (np.arange(300_000, dtype=np.uint64) * 7919) % (1 << 40)

    def spiller():
        with obs_context.request_scope(label="spiller") as acct:
            accounts["a"] = acct
            barrier.wait()
            mr = MapReduce(outofcore=1, memsize=1, maxpage=1,
                           fpath=str(tmp_path / "spill"))
            mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
            mr.sort_keys(1)

    def light():
        with obs_context.request_scope(label="light") as acct:
            accounts["b"] = acct
            barrier.wait()
            for _ in range(3):
                mr = MapReduce()
                small = np.arange(5000, dtype=np.uint64)
                mr.map(1, lambda i, kv, p: kv.add_batch(small, small))
                mr.aggregate()
    os.makedirs(tmp_path / "spill", exist_ok=True)
    ta = threading.Thread(target=spiller)
    tb = threading.Thread(target=light)
    ta.start(); tb.start(); ta.join(120); tb.join(120)
    pa, pb = accounts["a"].profile(), accounts["b"].profile()
    assert pa["spill"]["write_bytes"] > 0          # A really spilled
    assert pb["spill"]["write_bytes"] == 0         # ...and B saw none
    assert pb["spill"]["read_bytes"] == 0
    assert pa["trace_id"] != pb["trace_id"]


# ---------------------------------------------------------------------------
# span trace ids + cross-thread propagation (goldens on the JSONL sink)
# ---------------------------------------------------------------------------

def read_jsonl(path):
    from gpu_mapreduce_tpu.obs import read_jsonl as _rj
    return _rj(str(path))


def test_spans_carry_scope_trace_id(tmp_path):
    trace = tmp_path / "t.jsonl"
    get_tracer().enable(jsonl=str(trace))
    with obs_context.request_scope(label="golden") as acct:
        mr = MapReduce()
        k = np.arange(100, dtype=np.uint64)
        mr.map(2, lambda i, kv, p: kv.add_batch(k, k))
        mr.aggregate()
    events = read_jsonl(trace)
    assert events, "no spans written"
    assert {e.get("trace") for e in events} == {acct.trace_id}


def test_prefetch_producer_carries_submitting_trace(tmp_path):
    from gpu_mapreduce_tpu.exec.prefetch import prefetch_iter
    trace = tmp_path / "t.jsonl"
    get_tracer().enable(jsonl=str(trace))
    with obs_context.request_scope(label="consumer") as acct:
        out = list(prefetch_iter(iter(range(32)), depth=2))
    assert out == list(range(32))
    evs = [e for e in read_jsonl(trace) if e["name"] == "exec.prefetch"]
    assert evs, "producer span missing"
    assert evs[0].get("trace") == acct.trace_id
    # and it really ran on another thread
    assert evs[0]["tid"] != threading.get_ident() & 0x7FFFFFFF


def test_spill_writer_carries_submitting_trace(tmp_path):
    from gpu_mapreduce_tpu.exec.spill import SpillWriter, atomic_save
    trace = tmp_path / "t.jsonl"
    get_tracer().enable(jsonl=str(trace))
    w = SpillWriter(path="spill")
    arr = np.arange(64, dtype=np.uint64)
    with obs_context.request_scope(label="sorter") as acct:
        pend = w.submit(lambda: atomic_save(
            str(tmp_path / "run0.npy"), arr))
        pend.wait()
    w.close()
    evs = [e for e in read_jsonl(trace)
           if e["name"] == "exec.spill_write"]
    assert evs and evs[0].get("trace") == acct.trace_id
    assert evs[0]["tid"] != threading.get_ident() & 0x7FFFFFFF
    # the wsize counter bump from the writer thread charged the scope
    assert acct.profile()["spill"]["write_bytes"] == 0  # atomic_save
    #   alone doesn't bump wsize — external.py does; the span is the
    #   propagation proof here


def test_ingest_pool_tasks_charge_submitting_request():
    """mapstyle-2 pool tasks run under the submitting request's
    context: counter traffic from worker threads lands on the scope."""
    with obs_context.request_scope(label="pooled") as acct:
        mr = MapReduce(mapstyle=2)
        def cb(itask, kv, ptr):
            global_counters().add(cssize=11)
            kv.add(str(itask), "x")
        mr.map(8, cb)
    assert acct.profile()["exchange"]["sent_bytes"] == 8 * 11


def test_oink_script_gets_own_trace_and_journal_stamps(tmp_path,
                                                       monkeypatch):
    from gpu_mapreduce_tpu.ft.journal import read_journal
    from gpu_mapreduce_tpu.oink.script import OinkScript
    jdir = tmp_path / "journal"
    monkeypatch.setenv("MRTPU_JOURNAL", str(jdir))
    tracer = get_tracer().enable()
    s = OinkScript(screen=False)
    s.run_string("mr x\nx delete\n")
    ids = {e.get("trace") for e in tracer.events()}
    assert len(ids) == 1 and None not in ids
    (tid,) = ids
    recs = read_journal(str(jdir))
    assert recs, "journal empty"
    assert all(r.get("trace") == tid for r in recs), recs
    # a SECOND top-level script is a different request
    tracer.clear()
    s2 = OinkScript(screen=False)
    s2.run_string("mr y\ny delete\n")
    ids2 = {e.get("trace") for e in tracer.events()}
    assert len(ids2) == 1 and ids2 != ids


def test_process_default_context_and_profile_knob(monkeypatch):
    tracer = get_tracer().enable()
    mr = MapReduce()
    k = np.arange(10, dtype=np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(k, k))
    evs = tracer.events()
    assert evs and all(e.get("trace") for e in evs)
    # the id is the process context's, and stable across ops
    proc = obs_context.active_account()
    assert {e["trace"] for e in evs} == {proc.trace_id}
    # MRTPU_PROFILE=0: no implicit context, spans carry no trace
    monkeypatch.setenv("MRTPU_PROFILE", "0")
    obs_context.reset()
    tracer.clear()
    mr.map(1, lambda i, kv, p: kv.add_batch(k, k))
    assert all(e.get("trace") is None for e in tracer.events())
    assert obs_context.active_account() is None


def test_flight_dump_carries_trace_id(tmp_path):
    from gpu_mapreduce_tpu.obs import flight
    get_tracer().enable()
    rec = flight.enable(dir=str(tmp_path))
    with obs_context.request_scope(label="doomed") as acct:
        mr = MapReduce()
        k = np.arange(10, dtype=np.uint64)
        mr.map(1, lambda i, kv, p: kv.add_batch(k, k))
        path = rec.dump("test")
    doc = json.load(open(path))
    assert doc["trace_id"] == acct.trace_id
    assert any(s.get("trace") == acct.trace_id for s in doc["spans"])


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

def test_parse_slo():
    objs = obs_slo.parse_slo(
        "tenant=acme;p99_ms=2000;err_pct=0.5;windows=60,600"
        "|tenant=*;err_pct=5")
    assert objs[0].tenant == "acme" and objs[0].p99_ms == 2000
    assert objs[0].windows == (60.0, 600.0)
    assert objs[1].tenant == "*" and objs[1].p99_ms is None
    eng = obs_slo.SLOEngine(objs)
    assert eng.objective_for("acme").p99_ms == 2000
    assert eng.objective_for("other").err_pct == 5
    for bad in ("tenant=*", "tenant=*;p99_ms=0", "tenant=*;typo=1",
                "tenant=*;err_pct=200", "p99_ms"):
        with pytest.raises(ValueError):
            obs_slo.parse_slo(bad)


def _feed_sessions(reg, tenant, done=0, failed=0, wall_s=0.01):
    c = reg.counter("mrtpu_serve_sessions_total", "", ("tenant",
                                                       "status"))
    h = reg.histogram("mrtpu_serve_session_seconds", "", ("tenant",
                                                          "status"))
    for status, n in (("done", done), ("failed", failed)):
        if n:
            c.inc(n, tenant=tenant, status=status)
            for _ in range(n):
                h.observe(wall_s, tenant=tenant, status=status)


def test_burn_rate_and_alert_arms_flight():
    from gpu_mapreduce_tpu.obs import flight
    from gpu_mapreduce_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    eng = obs_slo.SLOEngine(obs_slo.parse_slo(
        "tenant=*;p99_ms=5000;err_pct=1;windows=60,600"))
    t0 = 1_000_000.0
    # 10 sessions, 5 failed → err fraction 0.5 over a 1% budget = 50×
    _feed_sessions(reg, "acme", done=5, failed=5)
    burn = eng.tick(now=t0, reg=reg)
    assert burn["acme"]["60s"] == pytest.approx(50.0)
    assert burn["acme"]["600s"] == pytest.approx(50.0)
    snap = eng.snapshot()
    assert "acme" in snap["firing"]
    assert snap["alerts"] and snap["alerts"][0]["tenant"] == "acme"
    assert flight.get() is not None          # the alert ARMED it
    # gauges exported into the same registry
    g = reg.collect()["mrtpu_slo_burn_ratio"]["samples"]
    by = {(s["labels"]["tenant"], s["labels"]["window"]): s["value"]
          for s in g}
    assert by[("acme", "60s")] == pytest.approx(50.0)
    # no NEW traffic in the next minute → the 60s window cools to 0
    eng.tick(now=t0 + 61, reg=reg)
    eng.tick(now=t0 + 122, reg=reg)
    burn = eng.tick(now=t0 + 183, reg=reg)
    assert burn["acme"]["60s"] == 0.0
    assert "acme" not in eng.snapshot()["firing"]


def test_latency_burn_uses_bucket_resolution():
    from gpu_mapreduce_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    eng = obs_slo.SLOEngine(obs_slo.parse_slo(
        "tenant=*;p99_ms=5000;windows=60"))
    # 100 done sessions, 4 of them slower than 5 s → 4% slow over the
    # 1% tail budget = 4× burn
    _feed_sessions(reg, "t", done=96, wall_s=0.01)
    _feed_sessions(reg, "t", done=4, wall_s=9.0)
    burn = eng.tick(now=1_000_000.0, reg=reg)
    assert burn["t"]["60s"] == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# offline: trace_view --trace / --traces + the metric-catalog lint
# ---------------------------------------------------------------------------

def _synthetic_trace(path):
    evs = [
        {"name": "oink.wordfreq", "cat": "oink", "ph": "X", "ts": 0.0,
         "dur": 1_000_000.0, "id": 1, "parent": 0, "trace": "T1",
         "args": {"dispatches": 5, "shuffle_sent_bytes": 1 << 20}},
        {"name": "map_files", "cat": "mr_op", "ph": "X", "ts": 0.0,
         "dur": 300_000.0, "id": 2, "parent": 1, "trace": "T1",
         "args": {}},
        {"name": "collate", "cat": "mr_op", "ph": "X", "ts": 300_000.0,
         "dur": 600_000.0, "id": 3, "parent": 1, "trace": "T1",
         "args": {}},
        {"name": "shuffle.exchange", "cat": "shuffle", "ph": "X",
         "ts": 350_000.0, "dur": 500_000.0, "id": 4, "parent": 3,
         "trace": "T1", "args": {}},
        {"name": "oink.other", "cat": "oink", "ph": "X", "ts": 0.0,
         "dur": 50_000.0, "id": 5, "parent": 0, "trace": "T2",
         "args": {}},
    ]
    with open(path, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")


def test_trace_view_trace_filter_and_critical_path(tmp_path, capsys):
    tv = load_script("trace_view")
    path = str(tmp_path / "t.jsonl")
    _synthetic_trace(path)
    assert tv.main([path, "--traces"]) == 0
    out = capsys.readouterr().out
    assert "T1" in out and "T2" in out
    assert tv.main([path, "--trace", "T1", "--json"]) == 0
    prof = json.loads(capsys.readouterr().out)
    assert prof["spans"] == 4
    assert prof["dispatches"] == 5
    assert prof["shuffle_sent_bytes"] == 1 << 20
    path_names = [h["name"] for h in prof["critical_path"]]
    assert path_names == ["oink.wordfreq", "collate",
                          "shuffle.exchange"]
    # self time: collate 0.6s with a 0.5s child → 0.1s self
    assert prof["critical_path"][1]["self_s"] == pytest.approx(0.1)
    # human-readable report renders without error
    assert tv.main([path, "--trace", "T1"]) == 0
    assert "critical path" in capsys.readouterr().out


def test_metric_catalog_lint_passes():
    lint = load_script("check_metrics_doc")
    assert lint.main() == 0


def test_trace_index_wall():
    tv = load_script("trace_view")
    idx = tv.trace_index([
        {"trace": "A", "ts": 0.0, "dur": 1e6, "parent": 0, "id": 1},
        {"trace": "A", "ts": 5e5, "dur": 1e6, "parent": 1, "id": 2}])
    assert idx["A"]["spans"] == 2 and idx["A"]["top_spans"] == 1
    assert idx["A"]["wall_s"] == pytest.approx(1.5)
