"""Multi-process data plane (parallel/dist.py + scripts/mrlaunch.py).

Fast tier: heartbeat/fence/watchdog mechanics with fake peers (no
subprocesses, no jax.distributed), the launcher's dead-rank evidence
rules, the durable-write helpers, and the process-level fault kinds.

Slow tier (``-m slow``, run by ``scripts/ci.sh dist``): real
multi-process goldens — N CPU processes over ``jax.distributed`` + gloo
running the collective wordfreq pipeline, including THE chaos golden: a
4-process run with rank 2 SIGKILLed mid-job must detect the loss in
bounded time, shrink to width 2, resume from the last durable
checkpoint, and produce output byte-identical to an uninterrupted
2-process run.
"""

import collections
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from gpu_mapreduce_tpu.parallel import dist as D

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MRLAUNCH = os.path.join(REPO, "scripts", "mrlaunch.py")


def _load_mrlaunch():
    spec = importlib.util.spec_from_file_location("_mrlaunch_t", MRLAUNCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# shrink policy
# ---------------------------------------------------------------------------

def test_shrink_width_largest_pow2():
    assert D.shrink_width(4) == 4
    assert D.shrink_width(3) == 2
    assert D.shrink_width(2) == 2
    assert D.shrink_width(1) == 1
    assert D.shrink_width(0) == 0
    assert D.shrink_width(7) == 4


# ---------------------------------------------------------------------------
# heartbeats + fences
# ---------------------------------------------------------------------------

def test_heartbeat_roundtrip_and_expiry(tmp_path):
    run = str(tmp_path)
    os.makedirs(D.hb_dir(run, 0), exist_ok=True)
    D.write_beat(run, 3, lease_s=30.0, gen=0, seq=7)
    beat = D.read_beat(run, 3)
    assert beat["rank"] == 3 and beat["seq"] == 7
    assert not D.beat_expired(beat, skew_s=0.5)
    # expiry is expires + skew, judged against the caller's clock
    assert D.beat_expired(beat, skew_s=0.5,
                          now=time.time() + 31.0)
    # missing or unreadable protects nobody
    assert D.beat_expired(None, skew_s=0.5)
    assert D.beat_expired({"junk": 1}, skew_s=0.5)


def test_fence_is_exclusive_and_gen_scoped(tmp_path):
    run = str(tmp_path)
    assert D.fence_rank(run, 2, by="launcher", gen=0) is True
    assert D.fence_rank(run, 2, by="other", gen=0) is False  # lost race
    assert D.is_fenced(run, 2, gen=0)
    # a fence for gen 0's rank 2 must NOT fence gen 1's rank 2
    assert not D.is_fenced(run, 2, gen=1)


def test_heartbeat_thread_latches_fence(tmp_path):
    run = str(tmp_path)
    hb = D.Heartbeat(run, 1, heartbeat_s=0.02, lease_s=1.0)
    hb.start()
    try:
        assert D.read_beat(run, 1) is not None
        assert not hb.fenced
        D.fence_rank(run, 1, by="test", gen=0)
        deadline = time.time() + 2.0
        while not hb.fenced and time.time() < deadline:
            time.sleep(0.01)
        assert hb.fenced
    finally:
        hb.stop()
    assert D.read_beat(run, 1) is None      # clean leave drops the lease


# ---------------------------------------------------------------------------
# the collective watchdog
# ---------------------------------------------------------------------------

def _runtime(tmp_path, world=2, rank=0, **kw):
    kw.setdefault("heartbeat_s", 0.02)
    kw.setdefault("lease_s", 0.2)
    kw.setdefault("skew_s", 0.05)
    kw.setdefault("sync_timeout_s", 30.0)
    return D.DistRuntime(rank, world, str(tmp_path), **kw)


def test_guard_passthrough_result(tmp_path):
    rt = _runtime(tmp_path)
    D.write_beat(str(tmp_path), 1, lease_s=30.0)
    assert rt.guard("exchange", lambda a, b: a + b, 2, 3) == 5


def test_guard_trips_on_expired_peer_lease(tmp_path):
    rt = _runtime(tmp_path)
    # peer 1's lease is already stale: a hung collective must become a
    # bounded PeerLostError, not an infinite stall
    D.write_beat(str(tmp_path), 1, lease_s=0.01)
    time.sleep(0.1)
    t0 = time.monotonic()
    with pytest.raises(D.PeerLostError) as ei:
        rt.guard("exchange", time.sleep, 60)
    assert time.monotonic() - t0 < 5.0
    assert ei.value.dead == [1]
    assert ei.value.site == "exchange"


def test_guard_trips_on_sync_deadline_with_live_peer(tmp_path):
    # the hung-but-heartbeating case: the peer's lease stays fresh, so
    # only the sync deadline can catch it
    rt = _runtime(tmp_path, sync_timeout_s=0.3)
    D.write_beat(str(tmp_path), 1, lease_s=60.0)
    t0 = time.monotonic()
    with pytest.raises(D.PeerLostError) as ei:
        rt.guard("count_sync", time.sleep, 60)
    assert 0.2 < time.monotonic() - t0 < 5.0
    assert "deadline" in str(ei.value)


def test_guard_raises_fenced_for_zombie(tmp_path):
    rt = _runtime(tmp_path)
    D.write_beat(str(tmp_path), 1, lease_s=60.0)
    D.fence_rank(str(tmp_path), 0, by="launcher", gen=0)
    with pytest.raises(D.RankFencedError):
        rt.guard("ckpt_barrier", lambda: 1)


def test_guard_converts_transport_error_when_peer_died(tmp_path):
    rt = _runtime(tmp_path)
    D.write_beat(str(tmp_path), 1, lease_s=0.15)

    def fail():
        raise RuntimeError("connection reset by peer")
    # the transport sees the death before the lease expires; the guard
    # confirms against the lease within one expiry window and converts
    with pytest.raises(D.PeerLostError):
        rt.guard("exchange", fail)


def test_guard_reraises_original_error_with_healthy_peers(tmp_path):
    rt = _runtime(tmp_path)
    D.write_beat(str(tmp_path), 1, lease_s=60.0)

    def fail():
        raise ValueError("a real bug, not a dead peer")
    with pytest.raises(ValueError):
        rt.guard("exchange", fail)


def test_guard_call_without_runtime_is_direct(tmp_path):
    assert D.active() is None
    assert D.guard_call("exchange", lambda: 42) == 42


def test_exit_report_roundtrip(tmp_path):
    run = str(tmp_path)
    os.makedirs(D.hb_dir(run, 1), exist_ok=True)
    D.write_exit_report(run, 0, 1, "peer_lost", dead=[2], site="exchange")
    from gpu_mapreduce_tpu.utils.fsio import read_json
    rec = read_json(D.exit_path(run, 0, 1))
    assert rec["code"] == "peer_lost" and rec["dead"] == [2]


# ---------------------------------------------------------------------------
# process-level fault kinds (ft/inject)
# ---------------------------------------------------------------------------

def test_peer_kill_spec_parses_with_rank_selector():
    from gpu_mapreduce_tpu.ft import inject
    specs = inject.parse_faults(
        "site=dist.exchange;kind=peer_kill;rank=2;after=1;n=1")
    (s,) = specs
    assert s.kind == "peer_kill" and s.rank == 2 and s.after == 1


def test_peer_kinds_rejected_outside_dist_sites():
    from gpu_mapreduce_tpu.ft import inject
    with pytest.raises(ValueError):
        inject.FaultSpec(site="spill.write", kind="peer_kill")
    with pytest.raises(ValueError):
        inject.FaultSpec(site="*", kind="peer_hang")


def test_rank_selector_filters_other_ranks(monkeypatch):
    from gpu_mapreduce_tpu.ft import inject
    monkeypatch.setattr(inject, "_RANK", 1)
    spec = inject.FaultSpec(site="dist.exchange", kind="peer_hang",
                            rank=2)
    assert not spec.matches("dist.exchange")
    monkeypatch.setattr(inject, "_RANK", 2)
    assert spec.matches("dist.exchange")


def test_peer_hang_sleeps_bounded(monkeypatch):
    from gpu_mapreduce_tpu.ft import inject
    monkeypatch.setenv("MRTPU_DIST_HANG_S", "0.05")
    inject.clear_faults()
    inject.schedule(site="dist.count_sync", kind="peer_hang",
                    max_faults=1)
    try:
        t0 = time.monotonic()
        inject.fault_point("dist.count_sync")   # sleeps, then returns
        assert time.monotonic() - t0 >= 0.04
    finally:
        inject.clear_faults()


# ---------------------------------------------------------------------------
# durable writes (utils/fsio — the satellite durability fix)
# ---------------------------------------------------------------------------

def test_atomic_write_json_fsyncs_parent_dir(tmp_path, monkeypatch):
    from gpu_mapreduce_tpu.utils import fsio
    synced = []
    real_fsync = os.fsync

    def spy(fd):
        try:
            import stat
            if stat.S_ISDIR(os.fstat(fd).st_mode):
                synced.append(fd)
        except OSError:
            pass
        return real_fsync(fd)
    monkeypatch.setattr(os, "fsync", spy)
    path = str(tmp_path / "x.json")
    fsio.atomic_write_json(path, {"a": 1})
    assert synced, "parent directory was not fsynced after the rename"
    assert fsio.read_json(path) == {"a": 1}


def test_spill_atomic_save_fsyncs_parent_dir(tmp_path, monkeypatch):
    from gpu_mapreduce_tpu.exec import spill
    from gpu_mapreduce_tpu.utils import fsio
    dirs = []
    real = fsio.fsync_dir
    monkeypatch.setattr(fsio, "fsync_dir",
                        lambda p: (dirs.append(p), real(p)))
    path = str(tmp_path / "run.npy")
    spill.atomic_save(path, np.arange(10))
    assert dirs and os.path.realpath(dirs[0]) == \
        os.path.realpath(str(tmp_path))
    assert np.array_equal(np.load(path), np.arange(10))


def test_journal_creation_fsyncs_dir(tmp_path, monkeypatch):
    from gpu_mapreduce_tpu.ft.journal import Journal
    from gpu_mapreduce_tpu.utils import fsio
    dirs = []
    real = fsio.fsync_dir
    monkeypatch.setattr(fsio, "fsync_dir",
                        lambda p: (dirs.append(p), real(p)))
    j = Journal(str(tmp_path / "jd"))
    j.close()
    assert any(d.endswith("jd") for d in dirs)


# ---------------------------------------------------------------------------
# multi-controller helpers on the single-process fake mesh
# ---------------------------------------------------------------------------

def test_host_pull_matches_asarray_single_process():
    import jax.numpy as jnp
    arr = jnp.arange(12)
    assert np.array_equal(D.host_pull(arr), np.arange(12))


def test_shard_local_rows_single_controller():
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(2)
    counts = np.array([3, 2], np.int64)
    blocks = [np.array([1, 2, 3], np.uint64),
              np.array([4, 5], np.uint64)]
    garr, cap = D.shard_local_rows(mesh, blocks, counts)
    assert cap == 8 and garr.shape == (16,)
    host = np.asarray(garr)
    assert list(host[:3]) == [1, 2, 3]
    assert list(host[cap:cap + 2]) == [4, 5]


# ---------------------------------------------------------------------------
# launcher units
# ---------------------------------------------------------------------------

def test_classify_dead_trusts_exit_reports_over_sigabrt():
    m = _load_mrlaunch()
    # rank 2 SIGKILLed; rank 0 reported dead=[2]; ranks 1,3 torn down
    # by the coordination-service cascade (SIGABRT) — survivors
    codes = {0: 75, 1: -6, 2: -9, 3: -6}
    reports = {0: {"code": "peer_lost", "dead": [2]}}
    assert m._classify_dead(codes, [], reports) == {2}


def test_classify_dead_sigkill_is_always_dead():
    m = _load_mrlaunch()
    codes = {0: 75, 1: -9, 2: 75, 3: -6}
    reports = {0: {"code": "peer_lost", "dead": []},
               2: {"code": "peer_lost", "dead": []}}
    # -9 is hard evidence; once hard evidence exists, rank 3's SIGABRT
    # is read as the coordination-service cascade, not a death
    assert m._classify_dead(codes, [], reports) == {1}


def test_classify_dead_abrt_only_when_no_other_evidence():
    m = _load_mrlaunch()
    codes = {0: -6, 1: -6}
    assert m._classify_dead(codes, [], {}) == {0, 1}


def test_classify_dead_hung_ranks_count():
    m = _load_mrlaunch()
    codes = {0: 75, 1: -9}   # 1 was SIGKILLed by the launcher (hung)
    reports = {0: {"code": "peer_lost", "dead": []}}
    assert m._classify_dead(codes, [1], reports) == {1}


def test_latest_manifest_skips_damaged_generation(tmp_path):
    m = _load_mrlaunch()
    run = str(tmp_path)
    for step, tag in ((1, b"one"), (2, b"two")):
        sdir = m._step_dir(run, step)
        os.makedirs(sdir, exist_ok=True)
        path = os.path.join(sdir, "rank0.npz")
        with open(path, "wb") as f:
            f.write(tag)
        from gpu_mapreduce_tpu.utils.fsio import atomic_write_json
        atomic_write_json(m._manifest_path(sdir), {
            "step": step, "width": 1, "chunks_done": step,
            "shards": {"0": {"file": "rank0.npz", "nrows": 0,
                             "sha256": m._sha256(path)}}})
    # damage the newest generation's shard: fallback must pick step 1
    with open(os.path.join(m._step_dir(run, 2), "rank0.npz"), "wb") as f:
        f.write(b"corrupt")
    man, sdir = m.latest_manifest(run)
    assert man["step"] == 1 and sdir.endswith("step-00001")


def test_merge_table_and_stable_ids_deterministic():
    m = _load_mrlaunch()
    ids1 = m._stable_ids([b"alpha", b"beta", b"alpha"])
    ids2 = m._stable_ids([b"alpha", b"beta", b"alpha"])
    assert np.array_equal(ids1, ids2) and ids1[0] == ids1[2]
    tk, tc = m._merge_table(np.array([1, 5], np.uint64),
                            np.array([2, 3], np.int64),
                            np.array([5, 9], np.uint64),
                            np.array([1, 7], np.int64))
    assert list(tk) == [1, 5, 9] and list(tc) == [2, 4, 7]


# ---------------------------------------------------------------------------
# slow tier: real multi-process goldens
# ---------------------------------------------------------------------------

def _write_corpus(path, nwords=3000, vocab=150, seed=11):
    import random
    rng = random.Random(seed)
    words = [f"w{i:03d}".encode() for i in range(vocab)]
    with open(path, "wb") as f:
        for _ in range(nwords):
            f.write(rng.choice(words))
            f.write(b" " if rng.random() < 0.85 else b"\n")
    return path


def _expected_output(corpus_paths):
    """The reference answer, computed serially: counts by word, rows
    sorted (-count, word) — exactly the worker's output contract."""
    from gpu_mapreduce_tpu.utils.io import read_words
    counts = collections.Counter()
    for p in corpus_paths:
        with open(p, "rb") as f:
            counts.update(read_words(f.read()))
    rows = sorted(counts.items(), key=lambda wc: (-wc[1], wc[0]))
    return b"".join(w + b" %d\n" % c for w, c in rows)


def _mrlaunch(nproc, rundir, corpus, out, chunks=4, env=None,
              timeout=300, expect_rc=0):
    e = dict(os.environ)
    e.pop("MRTPU_FAULTS", None)
    e.update(env or {})
    r = subprocess.run(
        [sys.executable, MRLAUNCH, "--np", str(nproc),
         "--rundir", rundir, "wordfreq", "--files", corpus,
         "--out", out, "--chunks", str(chunks)],
        env=e, cwd=REPO, capture_output=True, timeout=timeout)
    assert r.returncode == expect_rc, \
        f"mrlaunch rc={r.returncode}\n{r.stdout.decode()[-2000:]}" \
        f"\n{r.stderr.decode()[-2000:]}"
    return r


@pytest.mark.slow
def test_dist_two_process_wordfreq_matches_serial(tmp_path):
    corpus = _write_corpus(str(tmp_path / "c.txt"))
    out = str(tmp_path / "out.txt")
    _mrlaunch(2, str(tmp_path / "run"), corpus, out)
    with open(out, "rb") as f:
        assert f.read() == _expected_output([corpus])


@pytest.mark.slow
def test_dist_chaos_golden_peer_kill_shrinks_and_matches(tmp_path):
    """THE acceptance golden: 4-process run, rank 2 SIGKILLed at its
    second exchange; survivors detect in bounded time, the launcher
    shrinks to width 2 and resumes from the last durable checkpoint;
    the output is byte-identical to an uninterrupted 2-process run."""
    corpus = _write_corpus(str(tmp_path / "c.txt"))
    ref = str(tmp_path / "ref.txt")
    _mrlaunch(2, str(tmp_path / "ref-run"), corpus, ref, chunks=6)

    out = str(tmp_path / "out.txt")
    t0 = time.monotonic()
    r = _mrlaunch(4, str(tmp_path / "run"), corpus, out, chunks=6, env={
        "MRTPU_FAULTS":
            "site=dist.exchange;kind=peer_kill;rank=2;after=1;n=1",
        "MRTPU_DIST_SYNC_TIMEOUT": "20",
    })
    wall = time.monotonic() - t0
    with open(out, "rb") as f:
        got = f.read()
    with open(ref, "rb") as f:
        want = f.read()
    assert got == want, "shrunk-and-resumed output differs from the " \
                        "uninterrupted narrow run"
    summary = json.loads(
        r.stdout.decode().split("mrlaunch: ", 1)[1].splitlines()[0])
    assert summary["final_width"] == 2
    assert summary["generations"] == 2
    assert summary["history"][0]["dead"] == [2]
    assert summary["recover_seconds"] is not None
    assert summary["recover_seconds"] < 60.0
    assert wall < 240.0


@pytest.mark.slow
def test_dist_chaos_golden_peer_hang_trips_watchdog(tmp_path):
    """A hung (still-heartbeating) rank must trip the survivors' sync
    deadline instead of stalling the suite; the run then completes at
    the shrunk width with correct output."""
    corpus = _write_corpus(str(tmp_path / "c.txt"))
    out = str(tmp_path / "out.txt")
    t0 = time.monotonic()
    r = _mrlaunch(2, str(tmp_path / "run"), corpus, out, chunks=6, env={
        "MRTPU_FAULTS":
            "site=dist.count_sync;kind=peer_hang;rank=1;after=2;n=1",
        "MRTPU_DIST_SYNC_TIMEOUT": "6",
    }, timeout=300)
    wall = time.monotonic() - t0
    with open(out, "rb") as f:
        assert f.read() == _expected_output([corpus])
    summary = json.loads(
        r.stdout.decode().split("mrlaunch: ", 1)[1].splitlines()[0])
    assert summary["generations"] == 2
    assert summary["history"][0]["dead"] == [1]
    assert wall < 240.0, "the hang was not bounded by the watchdog"
