"""Graph-algorithm command suite (cc_find, tri_find, luby_find, sssp,
pagerank) vs exact numpy/python oracles — the reference prints invariants
("CC_find: N components", oink/cc_find.cpp:104-106); we assert them."""

import collections

import numpy as np
import pytest

from gpu_mapreduce_tpu.oink import ObjectManager, run_command


def union_find_labels(edges, vertices):
    """Oracle: component label = min vertex id in the component."""
    parent = {int(v): int(v) for v in vertices}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in edges:
        ra, rb = find(int(a)), find(int(b))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    return {v: find(v) for v in parent}


@pytest.fixture
def graph_file(tmp_path, rng):
    """Sparse undirected graph with several components."""
    edges = []
    for base in (0, 100, 200, 300):          # 4 islands of 25 vertices
        e = rng.integers(base, base + 25, size=(40, 2))
        edges.append(e)
    e = np.unique(np.concatenate(edges).astype(np.uint64), axis=0)
    e = e[e[:, 0] != e[:, 1]]
    path = tmp_path / "graph.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e) + "\n")
    return str(path), e


def test_cc_find_matches_union_find(graph_file, tmp_path):
    path, e = graph_file
    out = tmp_path / "cc.out"
    cmd = run_command("cc_find", ["0"], inputs=[path], outputs=[str(out)],
                      screen=False)
    verts = np.unique(e)
    oracle = union_find_labels(e, verts)
    got = {int(a): int(b) for a, b in
           np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))


def test_cc_find_fused_equals_composed(graph_file, tmp_path, monkeypatch):
    """Both engines must produce identical (vertex, zone) outputs and
    component counts — same min-vertex-id fixpoint."""
    from gpu_mapreduce_tpu.oink.commands import cc as ccmod

    path, e = graph_file
    outs = {}
    for engine in ("fused", "composed"):
        monkeypatch.setattr(ccmod.CCFind, "engine", engine)
        out = tmp_path / f"cc.{engine}"
        cmd = run_command("cc_find", ["0"], inputs=[path],
                          outputs=[str(out)], screen=False)
        outs[engine] = (cmd.ncc,
                        np.loadtxt(out, dtype=np.uint64).reshape(-1, 2))
    assert outs["fused"][0] == outs["composed"][0]
    f = {tuple(r) for r in outs["fused"][1]}
    c = {tuple(r) for r in outs["composed"][1]}
    assert f == c


def test_cc_find_fused_on_mesh(graph_file, tmp_path):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    path, e = graph_file
    out = tmp_path / "cc.out"
    obj = ObjectManager(comm=make_mesh(8))
    cmd = run_command("cc_find", ["0"], obj=obj, inputs=[path],
                      outputs=[str(out)], screen=False)
    oracle = union_find_labels(e, np.unique(e))
    # cc labels are assembled on the host (fused engine pulls the [n]
    # label vector), so the output is a single file — per-shard .<p>
    # files apply to MESH-resident outputs (see test_oink_commands
    # test_degree_on_mesh_backend)
    got = {int(a): int(b) for a, b in
           np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))


def test_cc_find_single_component(tmp_path):
    # a path graph 0-1-2-...-19: one component, worst case for propagation
    e = np.stack([np.arange(19), np.arange(1, 20)], 1).astype(np.uint64)
    path = tmp_path / "path.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e))
    out = tmp_path / "cc.out"
    cmd = run_command("cc_find", ["0"], inputs=[str(path)],
                      outputs=[str(out)], screen=False)
    got = np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)
    assert cmd.ncc == 1
    assert set(got[:, 1].tolist()) == {0}
    assert sorted(got[:, 0].tolist()) == list(range(20))


def test_cc_stats_histogram(graph_file, tmp_path):
    path, e = graph_file
    ccout = tmp_path / "cc.out"
    run_command("cc_find", ["0"], inputs=[path], outputs=[str(ccout)],
                screen=False)
    cmd = run_command("cc_stats", [], inputs=[str(ccout)], screen=False)
    oracle = union_find_labels(e, np.unique(e))
    sizes = collections.Counter(oracle.values())          # label → size
    hist = collections.Counter(sizes.values())            # size → ncomp
    assert dict(cmd.stats) == dict(hist)
    assert cmd.ncc == len(sizes)
    assert cmd.nvert == len(oracle)


def test_cc_find_on_mesh_backend(graph_file, tmp_path):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    path, e = graph_file
    out = tmp_path / "cc_mesh.out"
    obj = ObjectManager(comm=make_mesh(4))
    cmd = run_command("cc_find", ["0"], obj=obj, inputs=[path],
                      outputs=[str(out)], screen=False)
    oracle = union_find_labels(e, np.unique(e))
    # cc labels are assembled on the host (fused engine pulls the [n]
    # label vector), so the output is a single file — per-shard .<p>
    # files apply to MESH-resident outputs (see test_oink_commands
    # test_degree_on_mesh_backend)
    got = {int(a): int(b) for a, b in
           np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))


@pytest.mark.slow
def test_cc_find_mesh_stays_on_device(tmp_path, monkeypatch):
    """VERDICT r1 #3 'done' criterion: the COMPOSED cc_find engine's
    iteration loop on the mesh backend must never materialise a frame on
    the host — all kernels run their device (shard_map) tier.  RMAT
    graph, union-find oracle.  (The default fused engine satisfies this
    trivially — the whole loop is one dispatch — so this test pins the
    composed MR pipeline.)"""
    from gpu_mapreduce_tpu.models.rmat import generate_unique
    from gpu_mapreduce_tpu.oink.commands import cc as ccmod
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ToHostStats

    monkeypatch.setattr(ccmod.CCFind, "engine", "composed")
    e, _ = generate_unique(seed=42, nlevels=10, nnonzero=4,
                           abcd=(0.57, 0.19, 0.19, 0.05), frac=0.1)
    e = e[e[:, 0] != e[:, 1]].astype(np.uint64)
    path = tmp_path / "rmat.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e) + "\n")

    obj = ObjectManager(comm=make_mesh(4))
    # the final output/scan stage legitimately goes to host, so instrument
    # the loop by patching zone_winner to record the counter each round
    snaps = []
    orig_winner = ccmod.zone_winner

    def spy_winner(fr, kv, ptr):
        snaps.append(ToHostStats.snapshot())
        return orig_winner(fr, kv, ptr)

    ccmod.zone_winner = spy_winner
    try:
        out = tmp_path / "cc.out"
        cmd = run_command("cc_find", ["0"], obj=obj, inputs=[str(path)],
                          outputs=[str(out)], screen=False)
    finally:
        ccmod.zone_winner = orig_winner

    assert len(snaps) >= 2, "expected multiple propagation rounds"
    # no to_host between the first and last iteration snapshot
    assert snaps[-1] == snaps[0], f"host materialisation in loop: {snaps}"

    oracle = union_find_labels(e, np.unique(e))
    # the COMPOSED engine's label KV stays mesh-resident to the end, so
    # the r4 per-shard output applies: union of cc.out.<p> files
    rows = np.concatenate(
        [np.loadtxt(f, dtype=np.uint64).reshape(-1, 2)
         for f in sorted(tmp_path.glob("cc.out.*")) if f.stat().st_size])
    got = {int(a): int(b) for a, b in rows}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))


def _spy_snapshots(module, kernel_name):
    """Patch a kernel to record a ToHostStats snapshot at each call."""
    from gpu_mapreduce_tpu.parallel.sharded import ToHostStats
    snaps = []
    orig = getattr(module, kernel_name)

    def spy(*args, **kw):
        snaps.append(ToHostStats.snapshot())
        return orig(*args, **kw)

    setattr(module, kernel_name, spy)
    return snaps, lambda: setattr(module, kernel_name, orig)


@pytest.mark.slow
def test_luby_mesh_stays_on_device(graph_file, tmp_path, monkeypatch):
    """Pins the COMPOSED engine's device tier (the default fused engine
    is one dispatch for the whole loop — trivially on-device)."""
    from gpu_mapreduce_tpu.oink.commands import luby as lmod
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    monkeypatch.setattr(lmod.LubyFind, "engine", "composed")
    path, e = graph_file
    snaps, restore = _spy_snapshots(lmod, "edge_winner")
    try:
        obj = ObjectManager(comm=make_mesh(4))
        out = tmp_path / "mis.out"
        run_command("luby_find", ["7"], obj=obj, inputs=[path],
                    outputs=[str(out)], screen=False)
    finally:
        restore()
    assert len(snaps) >= 2
    assert snaps[-1] == snaps[0], f"host materialisation in loop: {snaps}"


@pytest.mark.slow
def test_sssp_mesh_stays_on_device(tmp_path, rng, monkeypatch):
    """Pins the COMPOSED engine's device tier (the default fused engine
    is one dispatch for the whole loop — trivially on-device)."""
    from gpu_mapreduce_tpu.oink.commands import sssp as smod
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    monkeypatch.setattr(smod.SSSPCommand, "engine", "composed")
    e = rng.integers(0, 40, size=(150, 2)).astype(np.uint64)
    e = e[e[:, 0] != e[:, 1]]
    w = rng.uniform(0.1, 2.0, len(e))
    path = tmp_path / "wg.txt"
    path.write_text("\n".join(f"{a} {b} {c:.6f}" for (a, b), c in zip(e, w)))
    snaps, restore = _spy_snapshots(smod, "pick_shortest")
    try:
        obj = ObjectManager(comm=make_mesh(4))
        out = tmp_path / "sssp.out"
        run_command("sssp", ["1", "3"], obj=obj, inputs=[str(path)],
                    outputs=[str(out)], screen=False)
    finally:
        restore()
    # skip the first snapshot (source-selection scan runs before the loop)
    assert len(snaps) >= 3
    assert snaps[-1] == snaps[1], f"host materialisation in loop: {snaps}"


def test_tri_mesh_stays_on_device(tri_file, tmp_path, monkeypatch):
    """Pins the COMPOSED engine's device tier."""
    from gpu_mapreduce_tpu.oink.commands import tri as tmod
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    monkeypatch.setattr(tmod.TriFind, "engine", "composed")
    path, e = tri_file
    s1, restore1 = _spy_snapshots(tmod, "first_degree")
    s2, restore2 = _spy_snapshots(tmod, "emit_triangles")
    try:
        obj = ObjectManager(comm=make_mesh(4))
        out = tmp_path / "tri.out"
        run_command("tri_find", [], obj=obj, inputs=[path],
                    outputs=[str(out)], screen=False)
    finally:
        restore1()
        restore2()
    assert s1 and s2
    assert s2[0] == s1[0], ("host materialisation between degree and "
                            f"triangle stages: {s1} vs {s2}")


# ---------------------------------------------------------------------------
# tri_find / neigh_tri
# ---------------------------------------------------------------------------

def brute_triangles(edges):
    """Oracle: set of frozenset vertex triples forming triangles."""
    es = {(int(a), int(b)) for a, b in edges}
    adj = collections.defaultdict(set)
    for a, b in es:
        adj[a].add(b)
        adj[b].add(a)
    tris = set()
    for a, b in es:
        for c in adj[a] & adj[b]:
            tris.add(frozenset((a, b, c)))
    return tris


@pytest.fixture
def tri_file(tmp_path, rng):
    """Canonical (upper, deduped) edge file — what tri_find expects
    (examples/in.tri runs edge_upper first)."""
    e = rng.integers(0, 18, size=(120, 2)).astype(np.uint64)
    e = e[e[:, 0] != e[:, 1]]
    e = np.unique(np.stack([np.minimum(e[:, 0], e[:, 1]),
                            np.maximum(e[:, 0], e[:, 1])], 1), axis=0)
    path = tmp_path / "upper.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e) + "\n")
    return str(path), e


def test_tri_find_matches_brute_force(tri_file, tmp_path):
    path, e = tri_file
    out = tmp_path / "tri.out"
    cmd = run_command("tri_find", [], inputs=[path], outputs=[str(out)],
                      screen=False)
    oracle = brute_triangles(e)
    got_rows = np.loadtxt(out, dtype=np.uint64).reshape(-1, 3)
    got = {frozenset(map(int, row)) for row in got_rows}
    assert got == oracle
    assert cmd.ntri == len(oracle) == len(got_rows)  # each exactly once


def test_tri_find_fused_equals_composed(tri_file, tmp_path, monkeypatch):
    from gpu_mapreduce_tpu.oink.commands import tri as tmod

    path, e = tri_file
    tris = {}
    for engine in ("fused", "composed"):
        monkeypatch.setattr(tmod.TriFind, "engine", engine)
        out = tmp_path / f"tri.{engine}"
        cmd = run_command("tri_find", [], inputs=[path],
                          outputs=[str(out)], screen=False)
        rows = np.loadtxt(out, dtype=np.uint64).reshape(-1, 3)
        tris[engine] = {frozenset(map(int, r)) for r in rows}
        assert cmd.ntri == len(rows)
    assert tris["fused"] == tris["composed"]


def test_tri_find_triangle_free(tmp_path):
    # bipartite graph has no triangles
    e = np.array([(a, b) for a in range(5) for b in range(10, 15)],
                 dtype=np.uint64)
    path = tmp_path / "bip.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e))
    cmd = run_command("tri_find", [], inputs=[str(path)], screen=False)
    assert cmd.ntri == 0


def test_tri_find_on_mesh_backend(tri_file, tmp_path):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    path, e = tri_file
    out = tmp_path / "tri_mesh.out"
    obj = ObjectManager(comm=make_mesh(4))
    cmd = run_command("tri_find", [], obj=obj, inputs=[path],
                      outputs=[str(out)], screen=False)
    oracle = brute_triangles(e)
    got = {frozenset(map(int, row))
           for row in np.loadtxt(out, dtype=np.uint64).reshape(-1, 3)}
    assert got == oracle and cmd.ntri == len(oracle)


# ---------------------------------------------------------------------------
# luby_find
# ---------------------------------------------------------------------------

def greedy_mis(edges, seed):
    """Oracle: Luby with fixed per-vertex randoms equals sequential greedy
    MIS over vertices ordered by (rand, id)."""
    from gpu_mapreduce_tpu.oink.commands.luby import vertex_rand
    adj = collections.defaultdict(set)
    for a, b in edges.tolist():
        if a != b:
            adj[a].add(b)
            adj[b].add(a)
    verts = np.array(sorted(adj), dtype=np.uint64)
    order = sorted(verts.tolist(),
                   key=lambda v: (float(vertex_rand(np.array([v],
                                   dtype=np.uint64), seed)[0]), v))
    mis = set()
    for v in order:
        if not (adj[v] & mis):
            mis.add(v)
    return mis, adj


@pytest.mark.parametrize("seed", [42, 7])
def test_luby_find_is_maximal_independent(graph_file, tmp_path, seed):
    path, e = graph_file
    out = tmp_path / "mis.out"
    cmd = run_command("luby_find", [str(seed)], inputs=[path],
                      outputs=[str(out)], screen=False)
    oracle, adj = greedy_mis(e, seed)
    got = set(np.loadtxt(out, dtype=np.uint64).reshape(-1).tolist())
    # independence + maximality against the input graph
    for v in got:
        assert not (adj[v] & got)
    for v in adj:
        assert v in got or (adj[v] & got)
    # determinism: parallel rounds == sequential greedy by (rand, id)
    assert got == oracle
    assert cmd.nset == len(got)


def test_luby_fused_serial_equals_mesh(graph_file, tmp_path):
    """The fused engine must pick the identical MIS on the serial and
    mesh backends (same priorities, deterministic lexicographic rule)."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh

    path, e = graph_file
    o1, o2 = tmp_path / "a.out", tmp_path / "b.out"
    run_command("luby_find", ["7"], inputs=[path], outputs=[str(o1)],
                screen=False)
    obj = ObjectManager(comm=make_mesh(8))
    run_command("luby_find", ["7"], obj=obj, inputs=[path],
                outputs=[str(o2)], screen=False)
    assert sorted(o1.read_text().split()) == sorted(o2.read_text().split())


def test_luby_find_complete_graph(tmp_path):
    # K6: MIS is exactly one vertex, one round
    e = np.array([(a, b) for a in range(6) for b in range(a + 1, 6)],
                 dtype=np.uint64)
    path = tmp_path / "k6.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e))
    cmd = run_command("luby_find", ["1"], inputs=[str(path)], screen=False)
    assert cmd.nset == 1


def test_luby_find_self_loop_terminates(tmp_path):
    # a self-loop must not livelock the round loop
    path = tmp_path / "loop.txt"
    path.write_text("1 2\n5 5\n2 3\n")
    cmd = run_command("luby_find", ["3"], inputs=[str(path)], screen=False)
    assert cmd.nset >= 1


def test_luby_find_on_mesh_backend(graph_file, tmp_path):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    path, e = graph_file
    out = tmp_path / "mis_mesh.out"
    obj = ObjectManager(comm=make_mesh(4))
    run_command("luby_find", ["42"], obj=obj, inputs=[path],
                outputs=[str(out)], screen=False)
    oracle, _ = greedy_mis(e, 42)
    got = set(np.loadtxt(out, dtype=np.uint64).reshape(-1).tolist())
    assert got == oracle


# ---------------------------------------------------------------------------
# sssp
# ---------------------------------------------------------------------------

def dijkstra(edges_w, source):
    """Oracle: directed single-source shortest paths, {v: (dist, pred)}."""
    import heapq
    adj = collections.defaultdict(list)
    verts = set()
    for a, b, w in edges_w:
        adj[int(a)].append((int(b), float(w)))
        verts.update((int(a), int(b)))
    dist = {v: float("inf") for v in verts}
    pred = {v: 0 for v in verts}
    dist[source] = 0.0
    pq = [(0.0, source)]
    while pq:
        d, u = heapq.heappop(pq)
        if d > dist[u]:
            continue
        for v, w in adj[u]:
            if d + w < dist[v]:
                dist[v] = d + w
                pred[v] = u
                heapq.heappush(pq, (dist[v], v))
    return {v: (dist[v], pred[v]) for v in verts}


@pytest.fixture
def weighted_graph_file(tmp_path, rng):
    e = rng.integers(0, 40, size=(150, 2)).astype(np.uint64)
    e = e[e[:, 0] != e[:, 1]]
    _, idx = np.unique(e, axis=0, return_index=True)
    e = e[np.sort(idx)]
    w = rng.uniform(0.5, 5.0, size=len(e)).round(3)
    path = tmp_path / "wgraph.txt"
    path.write_text("\n".join(f"{a} {b} {c}" for (a, b), c
                              in zip(e.tolist(), w.tolist())) + "\n")
    return str(path), [(a, b, c) for (a, b), c in zip(e.tolist(), w.tolist())]


def test_sssp_matches_dijkstra(weighted_graph_file, tmp_path):
    path, ew = weighted_graph_file
    out = tmp_path / "sssp.out"
    cmd = run_command("sssp", ["1", "17"], inputs=[path],
                      outputs=[str(out)], screen=False)
    (source, got), = cmd.results.items()
    oracle = dijkstra(ew, source)
    assert set(got) == set(oracle)
    for v in oracle:
        assert got[v][0] == pytest.approx(oracle[v][0])
        if np.isfinite(oracle[v][0]) and v != source:
            # pred must realise the shortest distance (ties may differ)
            pd = got[v][1]
            w = min(c for a, b, c in ew if a == pd and b == v)
            assert got[v][0] == pytest.approx(got[pd][0] + w)
    # file round-trip
    rows = [l.split() for l in out.read_text().splitlines()]
    assert len(rows) == len(oracle)


def test_sssp_fused_equals_composed(weighted_graph_file, monkeypatch):
    """Both engines must agree on distances for every source (preds may
    differ on ties; each is separately validated vs Dijkstra)."""
    from gpu_mapreduce_tpu.oink.commands import sssp as smod

    path, ew = weighted_graph_file
    res = {}
    for engine in ("fused", "composed"):
        monkeypatch.setattr(smod.SSSPCommand, "engine", engine)
        cmd = run_command("sssp", ["2", "17"], inputs=[path], screen=False)
        res[engine] = cmd.results
    assert set(res["fused"]) == set(res["composed"])
    for source in res["fused"]:
        f, c = res["fused"][source], res["composed"][source]
        assert set(f) == set(c)
        for v in f:
            assert f[v][0] == pytest.approx(c[v][0])


def test_sssp_multi_source_line_graph(tmp_path):
    # 0 →1→ 1 →1→ 2 →1→ 3: distances are exact path sums
    e = [(i, i + 1, 1.0) for i in range(6)]
    path = tmp_path / "line.txt"
    path.write_text("\n".join(f"{a} {b} {c}" for a, b, c in e))
    cmd = run_command("sssp", ["3", "5"], inputs=[path], screen=False)
    assert len(cmd.results) == 3
    for source, got in cmd.results.items():
        oracle = dijkstra(e, source)
        for v in oracle:
            assert got[v][0] == pytest.approx(oracle[v][0])


def test_sssp_on_mesh_backend(weighted_graph_file, tmp_path):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    path, ew = weighted_graph_file
    obj = ObjectManager(comm=make_mesh(4))
    cmd = run_command("sssp", ["1", "17"], obj=obj, inputs=[path],
                      screen=False)
    (source, got), = cmd.results.items()
    oracle = dijkstra(ew, source)
    for v in oracle:
        assert got[v][0] == pytest.approx(oracle[v][0])


# ---------------------------------------------------------------------------
# pagerank command (reference ships a stub; we assert vs dense numpy oracle)
# ---------------------------------------------------------------------------

def numpy_pagerank(src, dst, n, alpha, iters=200):
    r = np.full(n, 1.0 / n)
    deg = np.bincount(src, minlength=n).astype(float)
    inv = np.where(deg > 0, 1.0 / np.maximum(deg, 1), 0.0)
    for _ in range(iters):
        contrib = r * inv
        inflow = np.bincount(dst, weights=contrib[src], minlength=n)
        dangling = r[deg == 0].sum() / n
        r = (1 - alpha) / n + alpha * (inflow + dangling)
    return r


def test_pagerank_command_matches_oracle(weighted_graph_file, tmp_path):
    path, ew = weighted_graph_file
    out = tmp_path / "pr.out"
    cmd = run_command("pagerank", ["1e-9", "200", "0.85"], inputs=[path],
                      outputs=[str(out)], screen=False)
    e = np.array([(a, b) for a, b, _ in ew], dtype=np.uint64)
    verts, inv = np.unique(e.reshape(-1), return_inverse=True)
    oracle = numpy_pagerank(inv.reshape(-1, 2)[:, 0],
                            inv.reshape(-1, 2)[:, 1], len(verts), 0.85)
    assert cmd.nvert == len(verts)
    got = np.array([cmd.ranks[int(v)] for v in verts])
    np.testing.assert_allclose(got, oracle, rtol=2e-4)
    assert abs(got.sum() - 1.0) < 1e-3
    rows = np.loadtxt(out).reshape(-1, 2)
    assert len(rows) == len(verts)


def test_pagerank_command_on_mesh(weighted_graph_file):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    path, ew = weighted_graph_file
    obj = ObjectManager(comm=make_mesh(4))
    cmd = run_command("pagerank", ["1e-9", "200", "0.85"], obj=obj,
                      inputs=[path], screen=False)
    e = np.array([(a, b) for a, b, _ in ew], dtype=np.uint64)
    verts, inv = np.unique(e.reshape(-1), return_inverse=True)
    oracle = numpy_pagerank(inv.reshape(-1, 2)[:, 0],
                            inv.reshape(-1, 2)[:, 1], len(verts), 0.85)
    got = np.array([cmd.ranks[int(v)] for v in verts])
    np.testing.assert_allclose(got, oracle, rtol=2e-4)


def test_neigh_tri_per_vertex_files(tri_file, tmp_path):
    path, e = tri_file
    # adjacency file from the neighbor command, triangles from tri_find
    adjf, trif = tmp_path / "adj.out", tmp_path / "tri.out"
    run_command("neighbor", [], inputs=[path], outputs=[str(adjf)],
                screen=False)
    run_command("tri_find", [], inputs=[path], outputs=[str(trif)],
                screen=False)
    outdir = tmp_path / "nt"
    cmd = run_command("neigh_tri", [str(outdir)],
                      inputs=[str(adjf), str(trif)], screen=False)
    adj = collections.defaultdict(set)
    for a, b in e.tolist():
        adj[a].add(b)
        adj[b].add(a)
    tris = brute_triangles(e)
    verts = sorted(adj)
    assert cmd.nvert == len(verts)
    for v in verts:
        lines = (outdir / str(v)).read_text().splitlines()
        # neighbor lines "v x" must cover adj[v]; triangle lines "a b" are
        # the opposite edge of each triangle containing v
        pairs = [tuple(map(int, l.split())) for l in lines]
        nb_lines = [p for p in pairs if p[0] == v and p[1] in adj[v]]
        tri_lines = [p for p in pairs if frozenset((v,) + p) in tris]
        assert len(nb_lines) + len(tri_lines) == len(pairs)
        assert {p[1] for p in nb_lines} == adj[v]
        want_tris = {t for t in tris if v in t}
        assert {frozenset((v,) + p) for p in tri_lines} == want_tris


def test_sssp_zero_sources_named_output(weighted_graph_file):
    """sssp 0 <seed> with a named-MR output must not crash (review r2:
    loop-local vars in the named-MR block)."""
    path, _ = weighted_graph_file
    obj = ObjectManager()
    cmd = run_command("sssp", ["0", "5"], obj=obj, inputs=[path],
                      outputs=[(None, "named")], screen=False)
    assert cmd.results == {}
    assert "named" in obj.named


def test_cc_fused_mesh_device_staging(graph_file, tmp_path):
    """VERDICT r2 #2: the fused cc engine consumes the mesh-resident edge
    KV directly — device-side vertex ranking, zero device→host frame
    materialisations through staging + iteration."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ToHostStats

    path, e = graph_file
    out = tmp_path / "cc.out"
    obj = ObjectManager(comm=make_mesh(8))
    snap = ToHostStats.snapshot()
    cmd = run_command("cc_find", ["0"], obj=obj, inputs=[path],
                      outputs=[str(out)], screen=False)
    assert ToHostStats.delta(snap) == (0, 0)
    oracle = union_find_labels(e, np.unique(e))
    got = {int(a): int(b) for a, b in
           np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))


def test_luby_self_loop_only_mesh(tmp_path):
    """Staged luby with a self-loop-only graph emits the empty result
    directly from the device staging (n==0), no host edge pull."""
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    from gpu_mapreduce_tpu.parallel.sharded import ToHostStats

    path = tmp_path / "loops.txt"
    path.write_text("3 3\n7 7\n9 9\n")
    obj = ObjectManager(comm=make_mesh(4))
    snap = ToHostStats.snapshot()
    cmd = run_command("luby_find", ["5"], obj=obj, inputs=[str(path)],
                      screen=False)
    assert (cmd.nset, cmd.niterate) == (0, 0)
    assert ToHostStats.delta(snap) == (0, 0)
