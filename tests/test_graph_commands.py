"""Graph-algorithm command suite (cc_find, tri_find, luby_find, sssp,
pagerank) vs exact numpy/python oracles — the reference prints invariants
("CC_find: N components", oink/cc_find.cpp:104-106); we assert them."""

import collections

import numpy as np
import pytest

from gpu_mapreduce_tpu.oink import ObjectManager, run_command


def union_find_labels(edges, vertices):
    """Oracle: component label = min vertex id in the component."""
    parent = {int(v): int(v) for v in vertices}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in edges:
        ra, rb = find(int(a)), find(int(b))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    return {v: find(v) for v in parent}


@pytest.fixture
def graph_file(tmp_path, rng):
    """Sparse undirected graph with several components."""
    edges = []
    for base in (0, 100, 200, 300):          # 4 islands of 25 vertices
        e = rng.integers(base, base + 25, size=(40, 2))
        edges.append(e)
    e = np.unique(np.concatenate(edges).astype(np.uint64), axis=0)
    e = e[e[:, 0] != e[:, 1]]
    path = tmp_path / "graph.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e) + "\n")
    return str(path), e


def test_cc_find_matches_union_find(graph_file, tmp_path):
    path, e = graph_file
    out = tmp_path / "cc.out"
    cmd = run_command("cc_find", ["0"], inputs=[path], outputs=[str(out)],
                      screen=False)
    verts = np.unique(e)
    oracle = union_find_labels(e, verts)
    got = {int(a): int(b) for a, b in
           np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))


def test_cc_find_single_component(tmp_path):
    # a path graph 0-1-2-...-19: one component, worst case for propagation
    e = np.stack([np.arange(19), np.arange(1, 20)], 1).astype(np.uint64)
    path = tmp_path / "path.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e))
    out = tmp_path / "cc.out"
    cmd = run_command("cc_find", ["0"], inputs=[str(path)],
                      outputs=[str(out)], screen=False)
    got = np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)
    assert cmd.ncc == 1
    assert set(got[:, 1].tolist()) == {0}
    assert sorted(got[:, 0].tolist()) == list(range(20))


def test_cc_stats_histogram(graph_file, tmp_path):
    path, e = graph_file
    ccout = tmp_path / "cc.out"
    run_command("cc_find", ["0"], inputs=[path], outputs=[str(ccout)],
                screen=False)
    cmd = run_command("cc_stats", [], inputs=[str(ccout)], screen=False)
    oracle = union_find_labels(e, np.unique(e))
    sizes = collections.Counter(oracle.values())          # label → size
    hist = collections.Counter(sizes.values())            # size → ncomp
    assert dict(cmd.stats) == dict(hist)
    assert cmd.ncc == len(sizes)
    assert cmd.nvert == len(oracle)


def test_cc_find_on_mesh_backend(graph_file, tmp_path):
    from gpu_mapreduce_tpu.parallel.mesh import make_mesh
    path, e = graph_file
    out = tmp_path / "cc_mesh.out"
    obj = ObjectManager(comm=make_mesh(4))
    cmd = run_command("cc_find", ["0"], obj=obj, inputs=[path],
                      outputs=[str(out)], screen=False)
    oracle = union_find_labels(e, np.unique(e))
    got = {int(a): int(b) for a, b in
           np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))
