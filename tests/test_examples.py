"""The example drivers run end-to-end as subprocesses — the reference
ships its examples as its acceptance surface (examples/README), so ours
must keep working, not just the library underneath them."""

import collections
import os
import random
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=420, env_extra=None):
    env = dict(os.environ, PYTHONPATH=REPO, **(env_extra or {}))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env)


@pytest.fixture(scope="module")
def word_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("words")
    random.seed(3)
    vocab = ["alpha", "beta", "gamma", "delta", "epsilon"]
    oracle = collections.Counter()
    files = []
    for i in range(3):
        ws = random.choices(vocab, [9, 7, 5, 3, 1], k=1200)
        oracle.update(ws)
        p = d / f"w{i}.txt"
        p.write_text(" ".join(ws))
        files.append(str(p))
    return files, oracle


def test_wordfreq_driver(word_files):
    import re
    files, oracle = word_files
    r = _run("wordfreq.py", *files)
    assert r.returncode == 0, r.stderr[-2000:]
    # \b anchors: a digit-prefixed wrong value must not suffix-match
    assert re.search(rf"\b{sum(oracle.values())} total words, "
                     rf"{len(oracle)} unique words", r.stdout)


def test_wordfreq2_driver_two_passes(word_files):
    files, oracle = word_files
    r = _run("wordfreq2.py", *files)
    assert r.returncode == 0, r.stderr[-2000:]
    out = r.stdout
    assert "top 10 (local sort):" in out
    assert "top 10 (global, after gather):" in out
    import re
    top_word, top_count = oracle.most_common(1)[0]
    # both passes lead with the global max (one controller: local=global);
    # line-anchored so a digit-prefixed wrong count can't match
    assert len(re.findall(rf"^  {top_count} {top_word}$", out,
                          re.M)) == 2
    assert re.search(rf"\b{sum(oracle.values())} total words", out)


def test_invertedindex_driver_mesh(tmp_path):
    files = []
    for i in range(4):
        p = tmp_path / f"d{i}.html"
        p.write_bytes((b'<a href="http://e.org/p%d">x</a> pad ' % (i % 3))
                      * 5)
        files.append(str(p))
    out = tmp_path / "out"
    env_extra = {"JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    r = _run("invertedindex.py", str(out), *files,
             "--engine", "xla", "--mesh", "8", env_extra=env_extra)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "20 (url, doc) pairs, 3 unique urls" in r.stdout
    parts = sorted(os.listdir(out))
    assert parts == [f"part-{i:05d}" for i in range(8)]
    lines = [ln for p in parts
             for ln in (out / p).read_text().splitlines()]
    assert len(lines) == 3
    # --mesh beyond the device count must refuse, not truncate — pin
    # the actual refusal message, not just any failing run
    r2 = _run("invertedindex.py", str(out), *files, "--mesh", "99",
              timeout=240, env_extra=env_extra)
    assert r2.returncode != 0
    assert "devices available" in (r2.stderr + r2.stdout)


def test_rmat_driver(tmp_path):
    r = _run("rmat.py", "8", "4", "0.25", "0.25", "0.25", "0.25",
             "0.0", "7", str(tmp_path / "mat"))
    assert r.returncode == 0, r.stderr[-2000:]
    edges = (tmp_path / "mat").read_text().splitlines()
    assert len(edges) == 256 * 4 and len(set(edges)) == len(edges)


def test_intcount_driver(tmp_path):
    import re
    rng = np.random.default_rng(6)
    vals = rng.integers(0, 50, 4096).astype("<u4")
    p = tmp_path / "ints.bin"
    p.write_bytes(vals.tobytes())
    r = _run("intcount.py", str(p))
    assert r.returncode == 0, r.stderr[-2000:]
    assert re.search(rf"\b{len(np.unique(vals))} unique", r.stdout)
    assert re.search(rf"\b{len(vals)} ", r.stdout), r.stdout  # total too
