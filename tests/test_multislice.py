"""Multi-slice (DCN) mesh mapping (VERDICT r1 #9): the proc axis factors
into (slice, chip); the shuffle routes hierarchically — ICI all-to-all
within a slice grouping rows by destination chip, then ONE cross-slice
all-to-all between same-chip-index peers.  Results must be identical to
the flat mesh (the hierarchy is a routing detail, not a semantic)."""

import numpy as np
import pytest

from gpu_mapreduce_tpu import MapReduce
from gpu_mapreduce_tpu.parallel.mesh import (make_mesh, make_mesh2,
                                             mesh_axis_size)
from gpu_mapreduce_tpu.parallel.sharded import ShardedKV, shard_frame
from gpu_mapreduce_tpu.parallel.shuffle import exchange
from gpu_mapreduce_tpu.core.frame import KVFrame
from gpu_mapreduce_tpu.core.column import DenseColumn


@pytest.mark.parametrize("shape", [(2, 4), (4, 2), (2, 2)])
def test_hier_exchange_matches_flat(shape, rng):
    S, C = shape
    P = S * C
    n = 500
    keys = rng.integers(0, 1 << 40, n).astype(np.uint64)
    vals = rng.integers(0, 1 << 30, n).astype(np.uint64)
    fr = KVFrame(DenseColumn(keys), DenseColumn(vals))

    flat = exchange(shard_frame(fr, make_mesh(P)), ("hash", None))
    hier = exchange(shard_frame(fr, make_mesh2(S, C)), ("hash", None))
    assert mesh_axis_size(hier.mesh) == P
    np.testing.assert_array_equal(flat.counts, hier.counts)
    f1, f2 = flat.to_host(), hier.to_host()
    o1 = np.lexsort((np.asarray(f1.value.data), np.asarray(f1.key.data)))
    o2 = np.lexsort((np.asarray(f2.value.data), np.asarray(f2.key.data)))
    np.testing.assert_array_equal(np.asarray(f1.key.data)[o1],
                                  np.asarray(f2.key.data)[o2])
    np.testing.assert_array_equal(np.asarray(f1.value.data)[o1],
                                  np.asarray(f2.value.data)[o2])
    # per-shard contents must match exactly (same key→proc map)
    for i in range(P):
        a = np.sort(np.asarray(flat.key)[i * flat.cap:
                                         i * flat.cap + flat.counts[i]])
        b = np.sort(np.asarray(hier.key)[i * hier.cap:
                                         i * hier.cap + hier.counts[i]])
        np.testing.assert_array_equal(a, b)


def test_full_pipeline_on_multislice_mesh(rng):
    keys = (rng.integers(0, 50, 3000)).astype(np.uint64)
    import collections
    want = collections.Counter(keys.tolist())

    mr = MapReduce(make_mesh2(2, 4))
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, np.ones(len(keys),
                                                          np.uint64)))
    mr.collate()
    from gpu_mapreduce_tpu.ops.reduces import count
    n = mr.reduce(count, batch=True)
    assert n == len(want)
    got = {int(k): int(v) for k, v in mr.kv.one_frame().to_host().pairs()}
    assert got == dict(want)


def test_cc_find_on_multislice_mesh(tmp_path, rng):
    from gpu_mapreduce_tpu.oink import ObjectManager, run_command
    from tests.test_graph_commands import union_find_labels
    e = rng.integers(0, 80, (200, 2)).astype(np.uint64)
    e = np.unique(e[e[:, 0] != e[:, 1]], axis=0)
    path = tmp_path / "g.txt"
    path.write_text("\n".join(f"{a} {b}" for a, b in e) + "\n")
    out = tmp_path / "cc.out"
    obj = ObjectManager(comm=make_mesh2(2, 4))
    cmd = run_command("cc_find", ["0"], obj=obj, inputs=[str(path)],
                      outputs=[str(out)], screen=False)
    oracle = union_find_labels(e, np.unique(e))
    got = {int(a): int(b) for a, b in
           np.loadtxt(out, dtype=np.uint64).reshape(-1, 2)}
    assert got == oracle
    assert cmd.ncc == len(set(oracle.values()))


def test_gather_and_broadcast_on_multislice(rng):
    mr = MapReduce(make_mesh2(2, 4))
    keys = np.arange(64, dtype=np.uint64)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, keys))
    mr.aggregate()
    mr.gather(2)
    fr = mr.kv.one_frame()
    assert isinstance(fr, ShardedKV)
    assert (fr.counts[2:] == 0).all() and fr.counts[:2].sum() == 64
    mr.broadcast(0)
    fr = mr.kv.one_frame()
    assert all(int(c) == int(fr.counts[0]) for c in fr.counts)


def test_spmd_ingestion_on_multislice_mesh(tmp_path):
    """Mesh-SPMD InvertedIndex ingestion over a (slice, chip) mesh: the
    per-device corpus placement and shard_map extract run on 2-axis
    meshes identically to flat ones."""
    from gpu_mapreduce_tpu.apps.invertedindex import InvertedIndex

    paths = []
    for i in range(8):
        p = tmp_path / f"f{i}.html"
        p.write_bytes(b'<a href="http://s%d.org/p">x</a>fill' % (i % 3) * 5)
        paths.append(str(p))
    ii1 = InvertedIndex()
    n1 = ii1.run(paths)
    ii2 = InvertedIndex(comm=make_mesh2(2, 4))
    n2 = ii2.run(paths)
    assert n1 == n2
    assert ii1.urls == ii2.urls


def test_per_shard_output_on_multislice_mesh(tmp_path):
    """r4: per-shard part files + destination-sharded url dicts work on
    a (slice, chip) mesh too — 8 part files, union == serial oracle."""
    import collections
    import os

    from gpu_mapreduce_tpu.apps.invertedindex import InvertedIndex

    paths = []
    oracle = collections.defaultdict(set)
    for i in range(6):
        p = tmp_path / f"g{i}.html"
        body = []
        for j in range(30):
            u = "http://m%d.org/q%d" % (j % 5, j)
            body.append('<a href="%s">x</a> words ' % u)
            oracle[u.encode()].add(str(p))
        p.write_bytes("".join(body).encode())
        paths.append(str(p))
    ii = InvertedIndex(engine="xla", comm=make_mesh2(2, 4))
    outdir = str(tmp_path / "out")
    nh, nu = ii.run(paths, outdir=outdir)
    parts = sorted(os.listdir(outdir))
    assert parts == [f"part-{p:05d}" for p in range(8)]
    got = {}
    for part in parts:
        for line in open(os.path.join(outdir, part)):
            url, names = line.rstrip("\n").split("\t")
            assert url.encode() not in got
            got[url.encode()] = set(names.split(" "))
    assert got == dict(oracle)


def test_init_multihost_single_process():
    """init_multihost (the MPI_Init analog) joins the multi-controller
    runtime; exercised at num_processes=1 in a subprocess (the runtime
    binds ports and can only initialise once per process)."""
    import os
    import subprocess
    import sys
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=4'\n"
        "from gpu_mapreduce_tpu.utils.platform import pin_platform\n"
        "pin_platform('cpu')\n"
        "from gpu_mapreduce_tpu.parallel.mesh import (init_multihost,"
        " make_mesh, mesh_axis_size)\n"
        "import socket\n"
        "s = socket.socket(); s.bind(('127.0.0.1', 0))\n"
        "port = s.getsockname()[1]; s.close()\n"
        "pid = init_multihost(f'127.0.0.1:{port}', 1, 0)\n"
        "assert pid == 0, pid\n"
        "import jax\n"
        "assert jax.process_count() == 1\n"
        "assert mesh_axis_size(make_mesh()) == 4\n"
        "print('OK')\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK" in r.stdout
