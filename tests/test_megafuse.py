"""Fusion v2 (megafused single-dispatch plan groups, plan/fuser.py) +
the Pallas segment-group/segment-reduce table kernels
(ops/pallas/group.py): interpret-mode kernel goldens, fused-vs-eager
byte identity (wire on/off, pallas on/off, chaos), the "1 dispatch per
plan group" steady-state assertion, speculation-miss fallbacks, the
kernel-launch dispatch accounting, and the fusion telemetry surfaces
(mr.stats()["plan"]["fusion"], the per-request profile)."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpu_mapreduce_tpu.core.mapreduce import MapReduce
from gpu_mapreduce_tpu.core.runtime import global_counters
from gpu_mapreduce_tpu.ops.pallas import group as pgroup
from gpu_mapreduce_tpu.ops.reduces import (count, cull, max_values,
                                           sum_values)
from gpu_mapreduce_tpu.parallel.mesh import make_mesh


def ndispatch():
    return global_counters().snapshot()["ndispatch"]


def scan_pairs(mr):
    got = []
    mr.scan_kv(lambda k, v, p: got.append((k if isinstance(k, bytes)
                                           else int(k), int(v))))
    return sorted(got)


def run_chain(comm, fuse, kernel, keys, vals):
    mr = MapReduce(comm, fuse=fuse)
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
    mr.aggregate()
    mr.convert()
    n = mr.reduce(kernel, batch=True)
    return int(n), scan_pairs(mr)


def intcount_keys(n=8000, card=97):
    k = ((np.arange(n, dtype=np.uint64) * 7919) % card).astype(np.uint64)
    return k, np.arange(n, dtype=np.int64)


def warm_pipeline(mr, keys, vals, kernel=count):
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
    mr.aggregate()
    mr.convert()
    return int(mr.reduce(kernel, batch=True))


# ---------------------------------------------------------------------------
# interpret-mode kernel unit goldens (CPU)
# ---------------------------------------------------------------------------

def _table_reference(keys, vals, nvalid):
    """numpy oracle: per-key count and exact mod-2^64 sum."""
    cnts, sums = {}, {}
    for k, v in zip(keys[:nvalid].tolist(), vals[:nvalid].tolist()):
        cnts[k] = cnts.get(k, 0) + 1
        sums[k] = (sums.get(k, 0) + int(v)) % (1 << 64)
    return cnts, sums


@pytest.mark.parametrize("reduce_op", ["count", "sum"])
def test_kernel_table_golden(rng, reduce_op):
    """The paged table kernel + slot epilogue against a numpy oracle:
    ascending unique keys, exact counts/sums, zero fill — the layout
    the sort path emits."""
    cap, nvalid, gcap = 1024, 900, 256
    keys = (rng.integers(0, 150, cap).astype(np.uint64)
            * np.uint64(0x9E3779B97F4A7C15))
    vals = rng.integers(-(1 << 40), 1 << 40, cap).astype(np.int64)
    T = pgroup.table_slots(gcap)
    cfg = ("tbl", T, 256, True)
    ukey, uval, g, overflow = jax.jit(
        lambda k, v, n: pgroup.segment_group_reduce(
            k, v, n, gcap, reduce_op, cfg))(
        jnp.asarray(keys), jnp.asarray(vals), jnp.int32(nvalid))
    cnts, sums = _table_reference(keys, vals, nvalid)
    uk = np.sort(np.asarray(list(cnts), np.uint64))
    got_k = np.asarray(ukey)
    got_v = np.asarray(uval)
    assert int(overflow) == 0
    assert int(g) == len(uk)
    assert np.array_equal(got_k[:len(uk)], uk)
    assert (got_k[len(uk):] == 0).all() and (got_v[len(uk):] == 0).all()
    for i, k in enumerate(uk.tolist()):
        if reduce_op == "count":
            assert int(got_v[i]) == cnts[k]
        else:
            assert int(np.uint64(got_v[i].astype(np.uint64))) == sums[k]


def test_kernel_paged_matches_single_page(rng):
    """Page seams are invisible: tiny pages == one page, bit for bit."""
    cap, gcap = 777, 128
    keys = rng.integers(0, 60, cap).astype(np.uint64)
    vals = rng.integers(0, 1 << 30, cap).astype(np.int64)
    T = pgroup.table_slots(gcap)
    outs = []
    for page in (64, 1024):
        cfg = ("tbl", T, page, True)
        outs.append(pgroup.segment_group_reduce(
            jnp.asarray(keys), jnp.asarray(vals), jnp.int32(cap), gcap,
            "sum", cfg))
    for a, b in zip(outs[0], outs[1]):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_kernel_overflow_detected(rng):
    """More distinct keys than table slots: the overflow counter is
    nonzero (the megafuse validation evidence) — never silent drops."""
    cap = 512
    keys = np.arange(cap, dtype=np.uint64) * np.uint64(7919)
    vals = np.ones(cap, np.int64)
    cfg = ("tbl", 64, 512, True)   # 64 slots, 512 distinct keys
    _uk, _uv, _g, overflow = pgroup.segment_group_reduce(
        jnp.asarray(keys), jnp.asarray(vals), jnp.int32(cap), 64,
        "count", cfg)
    assert int(overflow) > 0


def test_kernel_signed_and_narrow_dtypes(rng):
    """int32 keys / int32 values: signed reconstruction is exact and
    sums wrap mod 2^32 exactly like the eager segment_sum."""
    cap, gcap = 600, 64
    keys = rng.integers(-30, 30, cap).astype(np.int32)
    vals = rng.integers(-(1 << 30), 1 << 30, cap).astype(np.int32)
    T = pgroup.table_slots(gcap)
    cfg = ("tbl", T, 1024, True)
    ukey, uval, g, overflow = pgroup.segment_group_reduce(
        jnp.asarray(keys), jnp.asarray(vals), jnp.int32(cap), gcap,
        "sum", cfg)
    assert int(overflow) == 0
    uk = np.sort(np.unique(keys))
    assert np.array_equal(np.asarray(ukey)[:len(uk)], uk)
    for i, k in enumerate(uk.tolist()):
        ref = np.int32(vals[keys == k].sum(dtype=np.int32))
        assert np.asarray(uval)[i] == ref
    assert int(g) == len(uk)


def test_kernel_eager_launch_counts_dispatch(rng):
    """Satellite: Counters.ndispatch counts pallas_call launches too —
    one per EAGER page call; launches traced inside a jit ride the
    enclosing program's count (no double billing), so "1 dispatch per
    pipeline" cannot be faked by moving work into uncounted kernels."""
    cap = 512
    keys = jnp.asarray(rng.integers(0, 40, cap).astype(np.uint64))
    vals = jnp.asarray(np.ones(cap, np.int64))
    d0 = ndispatch()
    pgroup.segment_table(keys, vals, jnp.int32(cap), 128, 256, False,
                         True)   # 2 pages, eager
    assert ndispatch() - d0 == 2
    d0 = ndispatch()
    jax.jit(lambda k, v: pgroup.segment_table(
        k, v, jnp.int32(cap), 128, 256, False, True))(keys, vals)
    assert ndispatch() - d0 == 0   # rides the (uncounted-here) jit


def test_kernel_mark_launch_counts_dispatch():
    """The pre-existing mark kernels report their eager launches too."""
    from gpu_mapreduce_tpu.ops.pallas.match import mark_words_pallas
    words = jnp.zeros(1 << 10, jnp.uint32)
    d0 = ndispatch()
    mark_words_pallas(words, b'<a href="', interpret=True)
    assert ndispatch() - d0 == 1


# ---------------------------------------------------------------------------
# golden equivalence: eager == fused cold (v1) == fused warm (megafused)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kernel", [count, sum_values, max_values, cull])
def test_megafuse_golden_all_kernels(kernel):
    keys, vals = intcount_keys()
    eager = run_chain(make_mesh(8), 0, kernel, keys, vals)
    fused_cold = run_chain(make_mesh(8), 1, kernel, keys, vals)
    fused_warm = run_chain(make_mesh(8), 1, kernel, keys, vals)
    assert eager == fused_cold == fused_warm


@pytest.mark.parametrize("wire", ["0", "1"])
def test_megafuse_golden_wire_modes(monkeypatch, wire):
    monkeypatch.setenv("MRTPU_WIRE", wire)
    keys, vals = intcount_keys()
    eager = run_chain(make_mesh(8), 0, count, keys, vals)
    run_chain(make_mesh(8), 1, count, keys, vals)
    fused_warm = run_chain(make_mesh(8), 1, count, keys, vals)
    assert eager == fused_warm


@pytest.mark.slow
def test_megafuse_golden_pallas_forced_matches_sort(monkeypatch):
    """MRTPU_PALLAS_GROUP=1 (the table kernels, interpret mode on this
    CPU) produces results identical to the sort path, warm and cold."""
    keys, vals = intcount_keys()
    sort_path = run_chain(make_mesh(8), 1, count, keys, vals)
    monkeypatch.setenv("MRTPU_PALLAS_GROUP", "1")
    on_cold = run_chain(make_mesh(8), 1, count, keys, vals)
    on_warm = run_chain(make_mesh(8), 1, count, keys, vals)
    assert sort_path == on_cold == on_warm


def test_megafuse_golden_kmv_chain():
    """[aggregate, convert] (collate for a host reduce) megafuses on
    the sort path (KMV is kernel-unsupported) — output identical."""
    from gpu_mapreduce_tpu.apps.wordfreq import _sum
    keys, _ = intcount_keys()
    vals = np.ones(len(keys), np.int64)

    def wf(fuse):
        mr = MapReduce(make_mesh(8), fuse=fuse)
        mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
        mr.collate()
        nu = mr.reduce(_sum)
        return int(nu), scan_pairs(mr)

    eager = wf(0)
    assert eager == wf(1) == wf(1)


def test_megafuse_golden_under_chaos():
    """shuffle-site chaos injection on the megafused group: the ft/
    retry re-runs the whole group and output stays byte-identical
    (the fault point sits before the single dispatch)."""
    from gpu_mapreduce_tpu import ft
    keys, vals = intcount_keys()
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, keys, vals)
    clean = warm_pipeline(mr, keys, vals), scan_pairs(mr)
    ft.reset()
    try:
        ft.schedule(site="shuffle.exchange", rate=1.0, seed=3,
                    max_faults=2)
        ft.set_budget("shuffle.exchange", 4)
        chaos = warm_pipeline(mr, keys, vals), scan_pairs(mr)
        assert ft.fault_counts().get("shuffle.exchange", 0) >= 1
        assert chaos == clean
    finally:
        ft.reset()


# ---------------------------------------------------------------------------
# the dispatch-count acceptance: 1 per plan group, steady state
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("wire", ["0", "1"])
def test_single_dispatch_per_pipeline(monkeypatch, wire):
    """[aggregate, convert, reduce(kernel)] under MRTPU_MEGAFUSE=1 on
    the 8-device fake mesh: ONE Counters.ndispatch per plan group once
    warm — with and without the wire codec."""
    monkeypatch.setenv("MRTPU_WIRE", wire)
    keys, vals = intcount_keys()
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, keys, vals)
    n1 = warm_pipeline(mr, keys, vals)
    d0 = ndispatch()
    n2 = warm_pipeline(mr, keys, vals)
    assert ndispatch() - d0 == 1
    assert n1 == n2


@pytest.mark.slow
def test_single_dispatch_with_pallas_kernels(monkeypatch):
    """Still exactly 1 dispatch with the table kernels forced on: the
    paged pallas_calls ride the single megafused jit program (the
    launch counter's tracer check), never a second host dispatch."""
    monkeypatch.setenv("MRTPU_PALLAS_GROUP", "1")
    keys, vals = intcount_keys()
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, keys, vals)
    warm_pipeline(mr, keys, vals)
    d0 = ndispatch()
    warm_pipeline(mr, keys, vals)
    assert ndispatch() - d0 == 1


def test_megafuse_off_takes_v1_dispatches(monkeypatch):
    monkeypatch.setenv("MRTPU_MEGAFUSE", "0")
    keys, vals = intcount_keys()
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, keys, vals)
    warm_pipeline(mr, keys, vals)
    d0 = ndispatch()
    warm_pipeline(mr, keys, vals)
    assert ndispatch() - d0 >= 2


def test_local_group_single_dispatch():
    """[convert, reduce] on an already-sharded KV: warm = 1 dispatch
    (the compact dispatch folds into the cached-capacity program)."""
    keys, vals = intcount_keys()

    def cycle(mr):
        mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
        mr.aggregate()
        _ = mr.kv            # barrier: aggregate replays eagerly
        mr.convert()
        return int(mr.reduce(count, batch=True))

    mr = MapReduce(make_mesh(8), fuse=1)
    cycle(mr)
    n1 = cycle(mr)
    d0 = ndispatch()
    mr.map(1, lambda i, kv, p: kv.add_batch(keys, vals))
    mr.aggregate()
    _ = mr.kv
    dpre = ndispatch()
    mr.convert()
    n2 = int(mr.reduce(count, batch=True))
    assert ndispatch() - dpre == 1
    assert n1 == n2
    assert dpre > d0   # the eager aggregate really dispatched before


# ---------------------------------------------------------------------------
# speculation misses fall back, correctly
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_speculation_miss_pack_overflow_falls_back():
    """Warm on a narrow key range, then feed a wider one: the cached
    wire pack can't round-trip it, the megafused result is discarded
    and the v1 path re-runs — output equals eager."""
    narrow, vals = intcount_keys(card=97)
    wide = ((np.arange(8000, dtype=np.uint64) * 0x9E3779B97F4A7C15)
            % np.uint64(1 << 60)).astype(np.uint64)
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, narrow, vals)
    warm_pipeline(mr, narrow, vals)          # megafuse armed for narrow
    got = warm_pipeline(mr, wide, vals), scan_pairs(mr)
    mre = MapReduce(make_mesh(8), fuse=0)
    ref = warm_pipeline(mre, wide, vals), scan_pairs(mre)
    assert got == ref


@pytest.mark.slow
def test_speculation_miss_group_growth_falls_back():
    """Warm on few distinct keys, then many: the cached group capacity
    (and kernel table) overflow, detected host-side — the sort-path v1
    replay keeps the output exact."""
    few, vals = intcount_keys(card=17)
    many, _ = intcount_keys(card=3000)
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, few, vals)
    warm_pipeline(mr, few, vals)
    got = warm_pipeline(mr, many, vals), scan_pairs(mr)
    mre = MapReduce(make_mesh(8), fuse=0)
    ref = warm_pipeline(mre, many, vals), scan_pairs(mre)
    assert got == ref


def test_fallback_warns_once(monkeypatch):
    """Unsupported chains warn exactly once per reason (then silent)."""
    monkeypatch.setenv("MRTPU_PALLAS_GROUP", "1")
    keys, vals = intcount_keys()
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, keys, vals, kernel=max_values)   # arm megafuse
    pgroup._WARNED.clear()   # AFTER arming: a shared plan-cache entry
    #                          may have megafused (and warned) already
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        warm_pipeline(mr, keys, vals, kernel=max_values)
        warm_pipeline(mr, keys, vals, kernel=max_values)
    ours = [w for w in rec if "MRTPU_PALLAS_GROUP" in str(w.message)]
    assert len(ours) == 1


# ---------------------------------------------------------------------------
# telemetry surfaces
# ---------------------------------------------------------------------------

def test_fusion_stats_in_mr_stats():
    from gpu_mapreduce_tpu.plan.cache import reset_fusion_stats
    keys, vals = intcount_keys()
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, keys, vals)
    reset_fusion_stats()
    warm_pipeline(mr, keys, vals)
    fu = mr.stats()["plan"]["fusion"]
    assert fu["groups"] >= 1 and fu["fused_groups"] >= 1
    assert fu["mega_groups"] >= 1
    assert fu["dispatches_saved"] >= 4       # 5 eager − 1 megafused
    assert fu["dispatches"] <= fu["eager_dispatch_estimate"]


def test_profile_fusion_section():
    """The per-request profile (what GET /v1/jobs/<id>/profile serves)
    carries the request's own fusion effectiveness."""
    from gpu_mapreduce_tpu.obs.context import request_scope
    keys, vals = intcount_keys()
    mr = MapReduce(make_mesh(8), fuse=1)
    warm_pipeline(mr, keys, vals)            # warm outside the scope
    with request_scope(label="megafuse-test") as acct:
        warm_pipeline(mr, keys, vals)
    prof = acct.profile()
    assert prof["fusion"]["fused_groups"] >= 1
    assert prof["fusion"]["mega_groups"] >= 1
    assert prof["fusion"]["dispatches_saved"] >= 4
