"""PageRank vs a dense numpy power-iteration oracle, single-chip and
sharded (8 virtual CPU devices).  The reference ships only the pagerank
skeleton (oink/pagerank.cpp:53-55); these goldens pin our designed-from-
pattern implementation."""

import numpy as np
import pytest

from gpu_mapreduce_tpu.models.pagerank import (
    pagerank, pagerank_sharded, pad_edges_for_mesh)
from gpu_mapreduce_tpu.parallel.mesh import make_mesh


def dense_oracle(src, dst, n, damping=0.85, iters=200):
    A = np.zeros((n, n))
    for a, b in zip(src, dst):
        A[a, b] += 1.0
    deg = A.sum(1)
    P = np.divide(A, deg[:, None], where=deg[:, None] > 0)
    x = np.full(n, 1.0 / n)
    for _ in range(iters):
        dangling = x[deg == 0].sum()
        x = (1 - damping) / n + damping * (P.T @ x + dangling / n)
    return x


@pytest.fixture
def graph(rng):
    n = 50
    src = rng.integers(0, n, 400).astype(np.int32)
    dst = rng.integers(0, n, 400).astype(np.int32)
    return src, dst, n


def test_pagerank_matches_dense_oracle(graph):
    src, dst, n = graph
    ranks, iters = pagerank(src, dst, n, tol=1e-7, maxiter=200)
    ranks = np.asarray(ranks)
    want = dense_oracle(src, dst, n)
    np.testing.assert_allclose(ranks, want, atol=1e-5)
    np.testing.assert_allclose(ranks.sum(), 1.0, rtol=1e-4)
    assert 1 <= int(iters) <= 200


def test_pagerank_with_dangling_vertices():
    # vertex 3 is dangling (never a source); chain 0->1->2->3
    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 3], np.int32)
    ranks, _ = pagerank(src, dst, 4, tol=1e-7, maxiter=300)
    want = dense_oracle(src, dst, 4, iters=300)
    np.testing.assert_allclose(np.asarray(ranks), want, atol=1e-5)


def test_pagerank_sharded_matches_single_chip(graph):
    src, dst, n = graph
    mesh = make_mesh(8)
    got, _ = pagerank_sharded(mesh, src, dst, n, tol=1e-7, maxiter=200)
    want = dense_oracle(src, dst, n)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pad_edges_for_mesh():
    src = np.arange(5, dtype=np.int32)
    dst = np.arange(5, dtype=np.int32)
    s, d, v = pad_edges_for_mesh(src, dst, 4)
    assert len(s) == len(d) == len(v) == 8
    assert v.sum() == 5 and v[:5].all() and not v[5:].any()
